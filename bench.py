"""Headline benchmark: BLS signature-share verifies/sec on one chip.

BASELINE.json:2 metric ("sig-share verifies/sec/chip").  The reference
(zhaohanjin/hbbft + threshold_crypto, pure Rust) verifies each share with
one pairing equality on a CPU core — ~10^3 verifies/sec/core (BASELINE.md
§6, PAPERS.md EdDSA/BLS-in-consensus measurements).  This bench runs the
TPU path: N same-message shares RLC-collapsed into batched 128-bit scalar
multiplications plus two pairings, all on device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is measured against this machine's own single-thread
pure-Python-free CPU estimate; the reference publishes no numbers
(BASELINE.json:13 "published": {}), so the CPU pairing-rate proxy
(1000 verifies/sec, the literature figure for one core) is the anchor.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from hbbft_tpu.utils.jaxcache import enable_cache

enable_cache()

import random

from hbbft_tpu.crypto.backend import VerifyRequest
from hbbft_tpu.crypto.bls.suite import BLSSuite
from hbbft_tpu.crypto.keys import SecretKeySet
from hbbft_tpu.crypto.tpu.backend import TpuBackend

# Literature CPU rate for one-pairing-per-share verification on one core
# (~0.5-1.5 ms/pairing; PAPERS.md arxiv 2302.00418). No published
# reference numbers exist to compare against (BASELINE.json:13).
CPU_BASELINE_VERIFIES_PER_SEC = 1000.0


def main() -> None:
    # 2048 shares amortize the flush's fixed pairing cost well while
    # keeping first-compile time (one shape bucket) tolerable.
    n_shares = int(os.environ.get("BENCH_SHARES", "2048"))
    suite = BLSSuite()
    rng = random.Random(7)
    sks = SecretKeySet.random(2, rng, suite)
    pks = sks.public_keys()
    msg = b"hbbft-tpu benchmark epoch document"
    reqs = []
    for i in range(n_shares):
        share = sks.secret_key_share(i % 8).sign(msg)
        reqs.append(VerifyRequest.sig_share(pks.public_key_share(i % 8), msg, share))

    backend = TpuBackend(suite)
    # Warmup on the SAME shape bucket: compiles the flush kernel once
    # (cached on disk afterwards), so the timed run measures execution.
    warm = backend.verify_batch(reqs)
    assert all(warm), "warmup verification failed"

    t0 = time.perf_counter()
    results = backend.verify_batch(reqs)
    dt = time.perf_counter() - t0
    assert all(results), "benchmark verification failed"

    rate = n_shares / dt
    print(
        json.dumps(
            {
                "metric": "bls_sig_share_verifies_per_sec_per_chip",
                "value": round(rate, 2),
                "unit": "verifies/sec",
                "vs_baseline": round(rate / CPU_BASELINE_VERIFIES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
