"""Headline benchmark: BLS signature-share verifies/sec on one chip.

BASELINE.json:2 metric ("sig-share verifies/sec/chip").  The reference
(zhaohanjin/hbbft + threshold_crypto, pure Rust) verifies each share with
one pairing equality on a CPU core — ~10^3 verifies/sec/core (BASELINE.md
§6, PAPERS.md EdDSA/BLS-in-consensus measurements).  This bench runs the
TPU path: N same-message shares RLC-collapsed into batched 128-bit scalar
multiplications plus two pairings, all on device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
``vs_baseline`` is measured against the literature CPU pairing rate
(~1000 verifies/sec/core); the reference publishes no numbers
(BASELINE.json:13 "published": {}).

Relay hardening (the round-1 failure: BENCH_r01.json was a traceback —
the axon TPU relay was down and ``import jax`` hung/raised): the TPU
backend is probed in a SUBPROCESS with a bounded timeout and retries.
If the chip is unreachable, the same kernel runs on the CPU platform at
a reduced batch and the JSON line carries ``"device": "cpu-fallback"``
plus an ``"error"`` note — always parseable, never a stack trace.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from hbbft_tpu.utils.jaxcache import enable_cache

PROBE_ATTEMPTS = 2
PROBE_TIMEOUT_S = 45
PROBE_WAIT_S = 10


def emit(payload: dict, code: int = 0) -> None:
    print(json.dumps(payload))
    sys.exit(code)


def probe_tpu() -> tuple[bool, str]:
    """Can a fresh interpreter initialize the TPU backend?  Run out of
    process: a dead relay makes ``jax.devices()`` HANG, which no
    in-process guard can bound."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return False, "JAX_PLATFORMS=cpu requested"
    last = ""
    for attempt in range(PROBE_ATTEMPTS):
        try:
            r = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "import jax; d = jax.devices(); "
                    "print(d[0].platform, len(d))",
                ],
                capture_output=True,
                text=True,
                timeout=PROBE_TIMEOUT_S,
            )
            if r.returncode == 0 and r.stdout.strip():
                return True, r.stdout.strip()
            last = (r.stderr or "backend init failed").strip()[-300:]
        except subprocess.TimeoutExpired:
            last = f"backend init timed out after {PROBE_TIMEOUT_S}s (relay down?)"
        if attempt + 1 < PROBE_ATTEMPTS:
            time.sleep(PROBE_WAIT_S)
    return False, last


def main() -> None:
    start = time.monotonic()
    # Soft deadline for the whole bench: stop escalating batch sizes
    # when it would risk a driver timeout (each size needs its own
    # kernel-bucket compile).  The largest size that completed is
    # reported, so a timeboxed run still yields a number.
    deadline_s = float(os.environ.get("BENCH_DEADLINE_S", "480"))
    tpu_ok, note = probe_tpu()
    if not tpu_ok:
        # CPU fallback: same kernel, small batches.  Sweep several sizes
        # so even a fallback round carries scaling signal (round-2
        # VERDICT weak #4); the deadline check between sizes keeps a
        # slow box from blowing the driver timeout.
        os.environ["JAX_PLATFORMS"] = "cpu"
        if os.environ.get("BENCH_SHARES_FALLBACK"):
            sizes = [int(os.environ["BENCH_SHARES_FALLBACK"])]
        else:
            sizes = [16, 64, 256]
    else:
        # Headline size only: one measured size costs ~7 min wall on this
        # box (import + persistent-cache deserialization + relay latency;
        # device execute is ~26 s of it), bench prints its single JSON
        # line only at the END, and the driver's timeout is unknown — a
        # multi-size sweep risks reporting NOTHING.  The full batch-size
        # curve (512/2048/10240, old + endo kernels) is recorded in
        # BATTERY_r03.jsonl / BASELINE.md; per-size reruns are
        # BENCH_SHARES=n.
        sizes = [10240]
        if os.environ.get("BENCH_SHARES"):
            sizes = [int(os.environ["BENCH_SHARES"])]

    import jax

    if not tpu_ok:
        jax.config.update("jax_platforms", "cpu")
    enable_cache()

    import random

    from hbbft_tpu.crypto.backend import VerifyRequest
    from hbbft_tpu.crypto.bls.suite import BLSSuite
    from hbbft_tpu.crypto.keys import SecretKeySet
    from hbbft_tpu.crypto.tpu.backend import TpuBackend

    # Literature CPU rate for one-pairing-per-share verification on one
    # core (~0.5-1.5 ms/pairing; PAPERS.md arxiv 2302.00418).
    cpu_baseline = 1000.0

    suite = BLSSuite()
    rng = random.Random(7)
    sks = SecretKeySet.random(2, rng, suite)
    pks = sks.public_keys()
    msg = b"hbbft-tpu benchmark epoch document"
    backend = TpuBackend(suite)

    # Sign once per key index: pure-Python BLS signing costs ~12 ms each,
    # and per-request re-signing added ~2.5 min of setup across the sweep
    # (the verify cost is per REQUEST — reusing the 8 signatures changes
    # nothing about what the kernel measures).
    shares8 = [sks.secret_key_share(k).sign(msg) for k in range(8)]

    def measure(n_shares: int) -> float:
        reqs = [
            VerifyRequest.sig_share(
                pks.public_key_share(i % 8), msg, shares8[i % 8]
            )
            for i in range(n_shares)
        ]
        # Warmup on the SAME shape bucket: compiles the flush kernel
        # once (cached on disk afterwards), so the timed run measures
        # execution.
        warm = backend.verify_batch(reqs)
        assert all(warm), "warmup verification failed"
        t0 = time.perf_counter()
        results = backend.verify_batch(reqs)
        dt = time.perf_counter() - t0
        assert all(results), "benchmark verification failed"
        return n_shares / dt

    best_rate, best_n, all_rates = 0.0, 0, {}
    for i, n_shares in enumerate(sizes):
        rate = measure(n_shares)
        all_rates[str(n_shares)] = round(rate, 2)
        if rate > best_rate:
            best_rate, best_n = rate, n_shares
        elapsed = time.monotonic() - start
        if elapsed > deadline_s:
            break
        # A larger batch costs roughly proportionally more; skip the
        # next escalation if it clearly cannot fit the deadline.
        if i + 1 < len(sizes) and rate > 0:
            projected = sizes[i + 1] / rate * 2  # warm + timed run
            if elapsed + projected > deadline_s:
                all_rates[f"skipped_{sizes[i + 1]}"] = "deadline"
                break

    rate = best_rate
    payload = {
        "metric": "bls_sig_share_verifies_per_sec_per_chip",
        "value": round(rate, 2),
        "unit": "verifies/sec",
        "vs_baseline": round(rate / cpu_baseline, 3),
        "shares": best_n,
        "rates_by_batch": all_rates,
        "device": "tpu" if tpu_ok else "cpu-fallback",
    }
    sweep = _latest_battery_sweep()
    if sweep:
        # Scaling visibility without re-measuring (round-3 VERDICT weak
        # #1): the bench itself times only the headline size (wall-time
        # budget), so surface the most recent battery flush sweep so the
        # driver artifact alone shows whether batching still improves.
        payload["battery_flush_sweep"] = sweep
    if tpu_ok:
        # Driver-visible Pallas-Keccak validation + throughput (the data
        # plane's Merkle hashing rides this kernel on TPU; VERDICT round
        # 1 weak #5 asked for a check the bench run executes).
        try:
            payload.update(_keccak_pallas_stats())
        except Exception as e:
            payload["keccak_pallas_error"] = f"{type(e).__name__}: {e}"[:200]
    else:
        payload["error"] = f"tpu unreachable: {note}"
    emit(payload)


def _battery_sweep_from_lines(lines, source: str) -> dict:
    """Parse per-batch flush rates out of battery JSONL lines.

    The battery writes each step as ``{"step": "bench_flush_<n>", ...,
    "results": [{"shares": n, "value": rate, ...}]}`` (the subprocess's
    JSON lines land nested under ``results``); round-3 rows were flat.
    Both shapes are read here — the round-4 verdict found the flat-only
    parser silently returned {} against every real r04 row.  Rates are
    compared with ``is not None`` (a legitimate 0.0 must surface as a
    regression, not vanish), and per-size rates live under their own
    ``rates`` key so the source string never mixes with numeric keys.
    """
    rates: dict = {}
    for line in lines:
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if "flush" not in str(row.get("step", "")):
            continue
        candidates = [row] + [
            r for r in row.get("results", []) if isinstance(r, dict)
        ]
        for r in candidates:
            shares = r.get("shares")
            if shares is None:
                shares = r.get("batch")
            rate = r.get("verifies_per_sec")
            if rate is None:
                rate = r.get("rate")
            if rate is None:
                rate = r.get("value")
            if shares is None or rate is None:
                continue
            # Later rows win: battery steps re-measure sizes as the
            # kernel improves within a round.
            rates[str(shares)] = round(float(rate), 1)
    if not rates:
        return {}
    return {"source": source, "rates": rates}


def _latest_battery_sweep() -> dict:
    """Pull per-batch flush rates from the newest BATTERY_r*.jsonl."""
    import glob

    root = os.path.dirname(os.path.abspath(__file__))
    files = glob.glob(os.path.join(root, "BATTERY_r*.jsonl"))
    if not files:
        return {}
    # Newest by mtime: BATTERY_TAG is free-form, so filename order can
    # shadow genuinely newer rounds (r4 vs r10, ad-hoc tags).
    newest = max(files, key=os.path.getmtime)
    try:
        with open(newest) as fh:
            lines = fh.readlines()
    except OSError:
        return {}
    return _battery_sweep_from_lines(lines, os.path.basename(newest))


def _keccak_pallas_stats() -> dict:
    """Validate the Pallas Keccak kernel against hashlib and measure its
    batched throughput on the chip."""
    import hashlib

    import numpy as np

    from hbbft_tpu.ops.jaxops import keccak_pallas as kp

    rng = np.random.default_rng(3)
    n = int(os.environ.get("BENCH_KECCAK_BATCH", "16384"))
    msgs = rng.integers(0, 256, size=(n, 65), dtype=np.uint8)
    digests = kp.sha3_256_batch(msgs)  # compiles + runs on TPU
    for i in (0, 1, n // 2, n - 1):
        assert (
            digests[i].tobytes() == hashlib.sha3_256(msgs[i].tobytes()).digest()
        ), "pallas keccak mismatch vs hashlib"
    t0 = time.perf_counter()
    kp.sha3_256_batch(msgs)
    dt = time.perf_counter() - t0
    out = {
        "keccak_pallas_hashes_per_sec": round(n / dt, 1),
        "keccak_pallas_checked": True,
    }
    # Multi-block sponge (config 2's big-shard shape; round-3 item #5):
    # 272-byte messages absorb 3 blocks.
    nm = max(256, n // 8)
    msgs_mb = rng.integers(0, 256, size=(nm, 272), dtype=np.uint8)
    digests_mb = kp.sha3_256_batch(msgs_mb)
    for i in (0, nm - 1):
        assert (
            digests_mb[i].tobytes()
            == hashlib.sha3_256(msgs_mb[i].tobytes()).digest()
        ), "pallas multi-block keccak mismatch vs hashlib"
    t0 = time.perf_counter()
    kp.sha3_256_batch(msgs_mb)
    dt = time.perf_counter() - t0
    out["keccak_pallas_multiblock_hashes_per_sec"] = round(nm / dt, 1)
    out["keccak_pallas_multiblock_checked"] = True
    return out


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except Exception as e:  # never a bare traceback on stdout
        emit(
            {
                "metric": "bls_sig_share_verifies_per_sec_per_chip",
                "value": 0,
                "unit": "verifies/sec",
                "vs_baseline": 0,
                "error": f"{type(e).__name__}: {e}"[:500],
            },
            code=1,
        )
