"""Run a real N=4 hbbft cluster over localhost TCP sockets.

Unlike examples/simulation.py (virtual-time, one process, one thread),
every node here is a thread pair (protocol + socket event loop) and
every protocol message crosses a real kernel socket as a length-prefixed
serde frame.  The demo commits a few epochs, severs one node mid-run,
shows the cluster committing without it, reconnects it, and prints the
per-peer transport stats + a Prometheus metrics sample.

    env JAX_PLATFORMS=cpu python examples/cluster.py

``--traffic`` runs the round-10 traffic plane instead: a seeded
open-loop client fleet offers paced load through per-node mempools
for a few seconds (optionally under a WAN link shape with
``--profile wan``), then prints submit→commit latency percentiles —
the served-system view of the same cluster.

    env JAX_PLATFORMS=cpu python examples/cluster.py --traffic
    env JAX_PLATFORMS=cpu python examples/cluster.py --traffic --profile wan
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.transport import FaultInjector, LocalCluster  # noqa: E402
from hbbft_tpu.transport.faults import wan_profile  # noqa: E402


def main() -> None:
    n = 4
    print(f"starting {n}-node TCP cluster on localhost ...")
    with LocalCluster(n, seed=1) as cluster:
        for i, addr in sorted(cluster.addr_map.items()):
            print(f"  node {i} listening on {addr[0]}:{addr[1]}")

        cluster.drive_to(range(n), 3, tag="warm")
        print("\nall 4 nodes committed 3 epochs; batches agree:",
              all(
                  cluster.batches(i)[0].contributions
                  == cluster.batches(0)[0].contributions
                  for i in range(n)
              ))

        print("\nsevering node 3's network (process stays alive) ...")
        cluster.disconnect(3)
        target = len(cluster.batches(0)) + 2
        cluster.drive_to([0, 1, 2], target, tag="outage")
        print("  majority committed to", target, "epochs; node 3 at",
              len(cluster.batches(3)))

        print("reconnecting node 3 ...")
        cluster.reconnect(3)
        if not cluster.wait(lambda c: len(c.batches(3)) >= target, 60):
            raise RuntimeError(
                f"node 3 never caught up ({len(cluster.batches(3))}/{target})"
            )
        print("  node 3 caught up to", len(cluster.batches(3)), "epochs")

        print("\nper-peer transport stats (node 0):")
        for peer, st in sorted(cluster.nodes[0].transport.stats().items()):
            print(
                f"  ->{peer}: frames_out={st['frames_out']}"
                f" bytes_out={st['bytes_out']} frames_in={st['frames_in']}"
                f" reconnects={st['reconnects']}"
            )

        m = cluster.merged_metrics()
        print("\nPrometheus sample (first 8 lines):")
        for line in m.prometheus_text().splitlines()[:8]:
            print(" ", line)


def main_traffic(profile: str, duration_s: float) -> None:
    from hbbft_tpu.traffic import ClientFleet, TrafficDriver

    n = 4
    lf = wan_profile(profile)
    injector = FaultInjector(seed=9, default=lf) if lf is not None else None
    fleet = ClientFleet(num_clients=8, rate_tps_each=5.0, seed=42)
    print(
        f"starting {n}-node TCP cluster ({profile} links), offering "
        f"{fleet.offered_tps:g} txns/s from {len(fleet.clients)} open-loop "
        f"clients for {duration_s:g}s ..."
    )
    with LocalCluster(n, seed=1, injector=injector) as cluster:
        driver = TrafficDriver(cluster, fleet)
        res = driver.run_open_loop(duration_s, drain_timeout_s=60.0)
        hist = driver.recorder.hist
        print(f"\n  arrived   {res['arrived']}")
        print(f"  admitted  {res['admitted']}")
        print(f"  committed {res['committed']}  "
              f"(outstanding {res['outstanding']})")
        epochs = min(cluster.batch_count(i) for i in cluster.nodes)
        print(f"  epochs    {epochs}  ({epochs / res['wall_s']:.2f}/s)")
        print("\nsubmit→commit latency:")
        for q in (0.5, 0.9, 0.99):
            print(f"  p{q * 100:g}  {hist.quantile(q) * 1e3:8.1f} ms")
        print(f"  max  {hist.max * 1e3:8.1f} ms")
        if injector is not None:
            print(f"\n{injector.stats.shaped} frames paid the WAN shape "
                  f"({injector.stats.dropped} dropped)")
        print("\nPrometheus latency summary:")
        for line in cluster.merged_metrics().prometheus_text().splitlines():
            if "traffic" in line or "faults" in line:
                print(" ", line)


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--traffic", action="store_true",
                    help="run the open-loop traffic-plane demo")
    ap.add_argument("--profile", default="clean",
                    choices=("clean", "wan", "wan-lossy"),
                    help="link shape for --traffic (default clean)")
    ap.add_argument("--duration", type=float, default=3.0,
                    help="offered-load window in seconds (default 3)")
    args = ap.parse_args()
    if args.traffic:
        main_traffic(args.profile, args.duration)
    else:
        main()
