"""Run a real N=4 hbbft cluster over localhost TCP sockets.

Unlike examples/simulation.py (virtual-time, one process, one thread),
every node here is a thread pair (protocol + socket event loop) and
every protocol message crosses a real kernel socket as a length-prefixed
serde frame.  The demo commits a few epochs, severs one node mid-run,
shows the cluster committing without it, reconnects it, and prints the
per-peer transport stats + a Prometheus metrics sample.

    env JAX_PLATFORMS=cpu python examples/cluster.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.transport import LocalCluster  # noqa: E402


def main() -> None:
    n = 4
    print(f"starting {n}-node TCP cluster on localhost ...")
    with LocalCluster(n, seed=1) as cluster:
        for i, addr in sorted(cluster.addr_map.items()):
            print(f"  node {i} listening on {addr[0]}:{addr[1]}")

        cluster.drive_to(range(n), 3, tag="warm")
        print("\nall 4 nodes committed 3 epochs; batches agree:",
              all(
                  cluster.batches(i)[0].contributions
                  == cluster.batches(0)[0].contributions
                  for i in range(n)
              ))

        print("\nsevering node 3's network (process stays alive) ...")
        cluster.disconnect(3)
        target = len(cluster.batches(0)) + 2
        cluster.drive_to([0, 1, 2], target, tag="outage")
        print("  majority committed to", target, "epochs; node 3 at",
              len(cluster.batches(3)))

        print("reconnecting node 3 ...")
        cluster.reconnect(3)
        if not cluster.wait(lambda c: len(c.batches(3)) >= target, 60):
            raise RuntimeError(
                f"node 3 never caught up ({len(cluster.batches(3))}/{target})"
            )
        print("  node 3 caught up to", len(cluster.batches(3)), "epochs")

        print("\nper-peer transport stats (node 0):")
        for peer, st in sorted(cluster.nodes[0].transport.stats().items()):
            print(
                f"  ->{peer}: frames_out={st['frames_out']}"
                f" bytes_out={st['bytes_out']} frames_in={st['frames_in']}"
                f" reconnects={st['reconnects']}"
            )

        m = cluster.merged_metrics()
        print("\nPrometheus sample (first 8 lines):")
        for line in m.prometheus_text().splitlines()[:8]:
            print(" ", line)


if __name__ == "__main__":
    main()
