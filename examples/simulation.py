"""Network simulation benchmark for QueueingHoneyBadger.

Reference behavior: upstream ``examples/simulation.rs`` (SURVEY.md §2 #17)
— an N-node virtual network running ``QueueingHoneyBadger`` (wrapped in
``SenderQueue``) over a hardware-quality model (link latency, bandwidth,
CPU-speed factor, per-message CPU-time accounting), printing a per-epoch
throughput/latency table.  Same capability, re-built on this framework's
sans-I/O state machines and deferred-verification pools.

The simulation is event-driven over *virtual time*:

* each node has a virtual clock; handling a message advances it by the
  measured wall CPU time divided by the CPU-speed factor;
* a message sent at time t arrives at ``t + latency + size/bandwidth``;
* an epoch is "done" at the virtual time the LAST correct node outputs
  its batch for that epoch.

Usage::

    python examples/simulation.py --nodes 16 --txns 256 --batch-size 256
    python examples/simulation.py --nodes 10 --suite bls --backend tpu

With ``--suite bls`` the real BLS12-381 threshold crypto runs (and
``--backend tpu`` batches its pairing checks on the accelerator via
``--flush-every``); the default insecure scalar suite benchmarks the
protocol plane alone, like the reference's simulation does with its
always-on native crypto but without a 20-minute runtime.
"""

from __future__ import annotations

import argparse
import heapq
import os
import random
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.crypto.backend import BatchedBackend, EagerBackend
from hbbft_tpu.crypto.keys import SecretKey, SecretKeySet
from hbbft_tpu.crypto.pool import VerifyPool
from hbbft_tpu.crypto.suite import ScalarSuite
from hbbft_tpu.protocols.dynamic_honey_badger import DhbBatch
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.queueing_honey_badger import Input, QueueingHoneyBadger
from hbbft_tpu.protocols.sender_queue import SenderQueue
from hbbft_tpu.protocols.traits import Step
from hbbft_tpu.utils import sizeof


@dataclass
class HwQuality:
    """Hardware-quality model (upstream ``HwQuality``): per-link latency
    in seconds, bandwidth in bytes/second, and a CPU-speed factor
    (1.0 = this host's speed; 0.5 = half as fast)."""

    latency_s: float = 0.1
    bandwidth_bps: float = 2_000_000.0
    cpu_factor: float = 1.0

    def net_delay(self, nbytes: int) -> float:
        return self.latency_s + nbytes / self.bandwidth_bps

    def cpu_time(self, wall_s: float) -> float:
        return wall_s / self.cpu_factor


@dataclass(order=True)
class _Event:
    at: float
    seq: int
    dest: Any = field(compare=False)
    sender: Any = field(compare=False)
    payload: Any = field(compare=False)


@dataclass
class SimNode:
    id: Any
    protocol: SenderQueue
    pool: VerifyPool
    rng: random.Random
    clock: float = 0.0
    cpu_used: float = 0.0
    sent_msgs: int = 0
    sent_bytes: int = 0
    outputs: List[DhbBatch] = field(default_factory=list)
    epoch_done_at: Dict[Tuple[int, int], float] = field(default_factory=dict)
    committed: List[Any] = field(default_factory=list)


class TimedNetwork:
    """Event-driven virtual-time network (upstream ``TestNetwork``)."""

    def __init__(self, nodes: Dict[Any, SimNode], backend, hw: HwQuality,
                 flush_every: int = 1) -> None:
        from hbbft_tpu.utils.metrics import Metrics

        self.nodes = nodes
        self.backend = backend
        self.hw = hw
        self.flush_every = max(1, flush_every)
        self.events: List[_Event] = []
        self._seq = 0
        self.delivered = 0
        self._since_flush: Dict[Any, int] = {nid: 0 for nid in nodes}
        self.metrics = Metrics()

    def _push(self, at: float, dest: Any, sender: Any, payload: Any) -> None:
        self._seq += 1
        heapq.heappush(self.events, _Event(at, self._seq, dest, sender, payload))

    def _emit(self, node: SimNode, step: Step) -> None:
        for out in step.output:
            if isinstance(out, DhbBatch):
                node.outputs.append(out)
                node.epoch_done_at.setdefault((out.era, out.epoch), node.clock)
                for _, contrib in out.contributions:
                    if isinstance(contrib, (list, tuple)):
                        node.committed.extend(contrib)
        all_ids = sorted(self.nodes)
        for tm in step.messages:
            size = sizeof.estimate(tm.message)
            for dest in tm.target.recipients(all_ids, node.id):
                node.sent_msgs += 1
                node.sent_bytes += size
                self._push(node.clock + self.hw.net_delay(size), dest,
                           node.id, tm.message)

    def _timed(self, node: SimNode, fn, *args) -> Step:
        t0 = time.perf_counter()
        step = fn(*args)
        wall = time.perf_counter() - t0
        node.clock += self.hw.cpu_time(wall)
        node.cpu_used += wall
        return step

    def _maybe_flush(self, node: SimNode) -> None:
        self._since_flush[node.id] += 1
        if self._since_flush[node.id] < self.flush_every:
            return
        self._since_flush[node.id] = 0
        while node.pool:
            self.metrics.count("verify_requests", len(node.pool))
            with self.metrics.timer("verify_flush"):
                step = self._timed(node, node.pool.flush, self.backend)
            self._emit(node, step)

    def input(self, nid: Any, value: Any) -> None:
        node = self.nodes[nid]
        step = self._timed(node, node.protocol.handle_input, value, node.rng)
        self._emit(node, step)
        self._maybe_flush(node)

    def run(self, done) -> None:
        while not done(self):
            if self.events:
                ev = heapq.heappop(self.events)
                node = self.nodes.get(ev.dest)
                if node is None:
                    continue
                node.clock = max(node.clock, ev.at)
                step = self._timed(node, node.protocol.handle_message,
                                   ev.sender, ev.payload, node.rng)
                self.delivered += 1
                self._emit(node, step)
                self._maybe_flush(node)
                continue
            # No events in flight: drain deferred verifies to unblock.
            progressed = False
            for node in self.nodes.values():
                while node.pool:
                    progressed = True
                    self._emit(node, self._timed(node, node.pool.flush,
                                                 self.backend))
            if not progressed and not self.events:
                raise RuntimeError("network idle but goal not met")


def build_network(args) -> TimedNetwork:
    rng = random.Random(args.seed)
    if args.suite == "bls":
        from hbbft_tpu.crypto.bls.suite import BLSSuite
        suite = BLSSuite()
    else:
        suite = ScalarSuite()
    if args.backend == "tpu":
        from hbbft_tpu.crypto.tpu.backend import TpuBackend
        backend = TpuBackend(suite)
    elif args.backend == "eager":
        backend = EagerBackend(suite)
    else:
        backend = BatchedBackend(suite)

    n = args.nodes
    f = (n - 1) // 3
    ids = list(range(n))
    sks = SecretKeySet.random(f, rng, suite)
    pks = sks.public_keys()
    node_sks = {i: SecretKey.random(rng, suite) for i in ids}
    node_pks = {i: node_sks[i].public_key() for i in ids}

    hw = HwQuality(latency_s=args.lag_ms / 1000.0,
                   bandwidth_bps=args.bw_kbps * 125.0,
                   cpu_factor=args.cpu_factor)

    nodes: Dict[Any, SimNode] = {}
    for i in ids:
        ni = NetworkInfo(
            our_id=i,
            val_ids=ids,
            public_key_set=pks,
            secret_key_share=sks.secret_key_share(i),
            public_keys=dict(node_pks),
            secret_key=node_sks[i],
        )
        pool = VerifyPool()
        proto = SenderQueue.wrap(
            lambda s, ni=ni: QueueingHoneyBadger(
                ni, s, batch_size=args.batch_size, session_id=b"simulation"),
            pool, peers=ids)
        nodes[i] = SimNode(id=i, protocol=proto, pool=pool,
                           rng=random.Random((args.seed << 16) ^ (i + 1)))
    return TimedNetwork(nodes, backend, hw, flush_every=args.flush_every)


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    p.add_argument("--nodes", type=int, default=10, help="network size N")
    p.add_argument("--txns", type=int, default=128, help="total transactions")
    p.add_argument("--txn-size", type=int, default=16, help="bytes per txn")
    p.add_argument("--batch-size", type=int, default=128,
                   help="target txns per epoch across the network")
    p.add_argument("--lag-ms", type=float, default=100.0, help="link latency")
    p.add_argument("--bw-kbps", type=float, default=2000.0, help="bandwidth")
    p.add_argument("--cpu-factor", type=float, default=1.0,
                   help="CPU speed multiplier (0.5 = half speed)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--suite", choices=["scalar", "bls"], default="scalar")
    p.add_argument("--backend", choices=["eager", "batched", "tpu"],
                   default="batched")
    p.add_argument("--flush-every", type=int, default=1,
                   help="deliveries per deferred-verify flush (TPU batching)")
    args = p.parse_args()

    net = build_network(args)
    rng = random.Random(args.seed + 7)
    txns = [rng.randbytes(args.txn_size) for _ in range(args.txns)]
    for i, txn in enumerate(txns):
        net.input(i % args.nodes, Input.user(txn))

    want = set(txns)
    t_wall = time.perf_counter()
    net.run(lambda n: all(want <= set(node.committed)
                          for node in n.nodes.values()))
    wall = time.perf_counter() - t_wall

    nodes = list(net.nodes.values())
    epochs = sorted(set().union(*[set(n.epoch_done_at) for n in nodes]))
    print(f"\n{'epoch':>5} {'done@(sim s)':>12} {'txns':>6} {'cum txns':>9} "
          f"{'tx/s (sim)':>11}")
    cum = 0
    for e in epochs:
        done_at = max(n.epoch_done_at.get(e, 0.0) for n in nodes)
        batch_txns = 0
        for n in nodes:
            for b in n.outputs:
                if (b.era, b.epoch) == e:
                    batch_txns = sum(len(c) for _, c in b.contributions
                                     if isinstance(c, (list, tuple)))
                    break
            if batch_txns:
                break
        cum += batch_txns
        rate = cum / done_at if done_at > 0 else 0.0
        tag = f"{e[0]}.{e[1]}"
        print(f"{tag:>5} {done_at:>12.3f} {batch_txns:>6} {cum:>9} {rate:>11.1f}")

    sim_end = max(max(n.epoch_done_at.values(), default=0.0) for n in nodes)
    msgs = sum(n.sent_msgs for n in nodes)
    mbytes = sum(n.sent_bytes for n in nodes) / 1e6
    cpu = sum(n.cpu_used for n in nodes)
    print(f"\nN={args.nodes} f={(args.nodes - 1) // 3} suite={args.suite} "
          f"backend={args.backend} flush_every={args.flush_every}")
    print(f"committed {args.txns} txns in {sim_end:.3f} sim-s "
          f"({args.txns / sim_end if sim_end else 0:.1f} tx/s); "
          f"{msgs} msgs, {mbytes:.2f} MB on the wire; "
          f"crypto+protocol CPU {cpu:.2f}s; wall {wall:.2f}s")
    print("\n" + net.metrics.report())


if __name__ == "__main__":
    main()
