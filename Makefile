# Repo-level developer targets.  The native libraries have their own
# Makefile (native/); tests run through pytest (see CLAUDE.md for the
# tier structure and timing expectations).

PYTHON ?= python

# Invariant linter (tools/lint, always available) + ruff (stock
# pyflakes/pycodestyle/isort layer, configured in pyproject.toml) when
# the machine has it.
lint:
	$(PYTHON) -m tools.lint
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check .; \
	else \
		echo "ruff not installed; skipped (invariant lint ran)"; \
	fi

# Pre-commit aggregate: the invariant + cross-language contract linter
# (HBT/HBC/HBX rules, incl. the knob-doc staleness gate), ruff when
# installed, then the two no-jax smoke tiers.  Safe during crypto-cache
# cold states — nothing here compiles XLA graphs.
check: lint cluster-smoke obs-smoke
	@echo "make check: all gates green"

asan ubsan tsan:
	$(MAKE) -C native $@

test-protocol:
	$(PYTHON) -m pytest tests/ -q \
		--ignore=tests/test_tpu_crypto.py --ignore=tests/test_jax_ops.py

# N=4 TCP cluster smoke: 3 epochs over localhost sockets, kill/restart
# and partition drills included (the ISSUE-4 acceptance surface), plus
# the native-node tier (ISSUE-5: engine-per-node oracle equivalence,
# drills re-run native, wire-codec fuzz parity — needs g++, skips
# cleanly without one) and the process-per-node tier (ISSUE-13:
# native_proc identity vs both thread arms, SIGKILL/restart drill,
# per-worker scrape + parent-side trace merge).
cluster-smoke:
	env JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_transport.py \
		tests/test_transport_native.py tests/test_transport_proc.py \
		-q -m 'not slow'

# Traffic-plane tier (ISSUE 6): open-loop clients, mempool pacing/dedup,
# WAN link shapes, submit→commit latency accounting, kill/restart
# resubmit drill.  No jax/XLA involvement — safe to run during
# crypto-cache cold states, like cluster-smoke.
traffic-smoke:
	env JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_traffic.py \
		tests/test_metrics.py -q -m 'not slow'

# Byzantine chaos tier (ISSUE 7): live-socket adversary arms (crash/
# equivocate/corrupt-share/replay/flood) on both node impls, composed
# chaos schedules (Byzantine + WAN + kill/restart + partition/heal),
# safety/liveness oracles, misbehavior accounting + escalating
# reconnect bans.  No jax/XLA involvement — safe during crypto-cache
# cold states; native halves skip cleanly without g++.
chaos-smoke:
	env JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_chaos.py -q -m 'not slow'

# Flight-recorder tier (ISSUE 9): trace rings on both node arms, Chrome
# trace export + phase spans, Prometheus exposition grammar, live
# /metrics /trace.json /healthz scrape against a driven cluster, plus
# the round-16 critical-path analyzer + /diag stall diagnostician
# (golden sim-net fixtures, live stall drill, CLI round trip).  No
# jax/XLA involvement — safe during crypto-cache cold states; the
# native-arm halves skip cleanly without g++.
obs-smoke:
	env JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_obs.py \
		tests/test_analyze.py tests/test_metrics.py -q -m 'not slow'

# Live stall-diagnostician demo: drive an N-node cluster (default 4)
# with scrape endpoints up, print its per-epoch critical paths, then
# partition an honest node and print the /diag verdict.
N ?= 4
diag:
	env JAX_PLATFORMS=cpu PYTHONPATH= $(PYTHON) tools/analyze.py --demo $(N)

# Crypto-plane tier (ISSUE 12): the shared batched share-verification
# service — service-arm vs inline-arm output identity on both node
# impls, corrupt-share attribution parity, service-death fallback
# drill, cadence/threads validation pins.  Runs on the Batched CPU
# backend: no jax/XLA involvement — safe during crypto-cache cold
# states; native halves skip cleanly without g++.
cryptoplane-smoke:
	env JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_cryptoplane.py \
		tests/test_cryptoplane_proc.py -q -m 'not slow'

# Engine-plane tier (ISSUE 14 + 17): the vectorized field plane (kernel
# fuzz + cross-arm identity) and the epoch arena + batched sha3 plane
# (hashlib-oracle fuzz both arms, ARENA x SIMD identity matrix over an
# era change, telemetry sanity).  No jax/XLA involvement — safe during
# crypto-cache cold states; skips cleanly without g++.
engine-smoke:
	env JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_field_simd.py \
		tests/test_sha3_arena.py -q -m 'not slow'

.PHONY: lint check asan ubsan tsan test-protocol cluster-smoke traffic-smoke \
	chaos-smoke obs-smoke cryptoplane-smoke engine-smoke diag
