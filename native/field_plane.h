// Vectorized field-arithmetic plane for the scalar-suite engine
// (ISSUE 14): batched Montgomery arithmetic mod r (the BLS12-381 scalar
// field order) with an AVX-512 IFMA 8-lane arm and a portable 4x64
// scalar arm behind ONE runtime dispatch point.
//
// Layering:
//   * hbf:: scalar core — 4x64-word helpers (add/sub/cmp, 2^256-radix
//     Montgomery REDC, mont_mul/to_mont/from_mont/mont_inv).  These are
//     DETERMINISTIC (never dispatched); engine code uses them to keep
//     loops in the Montgomery domain and convert once at boundaries —
//     the structural fix for the old store-canonical/double-REDC cost.
//   * hbf:: batch kernels — mul_batch / dot_batch / lagrange_dens /
//     rlc_accum.  Each dispatches to the IFMA arm (native/field_ifma.cpp,
//     52-bit-limb 8-lane structure-of-arrays over _mm512_madd52{lo,hi})
//     when compiled in AND the CPU advertises AVX512IFMA AND
//     HBBFT_TPU_SIMD != 0; the scalar arm otherwise.
//
// THE DISPATCH-IDENTITY CONTRACT (docs/INVARIANTS.md): every batch
// kernel's boundary semantics are R-FREE — canonical values (or exact
// integers for rlc_accum) in and out, never Montgomery residues.  The
// two arms use different Montgomery radices internally (2^256 scalar,
// 2^260 IFMA), so a residue crossing the dispatch boundary would be
// arm-dependent; full products/sums mod r are arm-independent EXACT
// values.  Protocol outputs are therefore byte-identical across
// HBBFT_TPU_SIMD=0/1 by construction, and the equivalence suites pin it.
//
// Operand domains: unless stated otherwise, inputs are < 2^256 with at
// least one operand of every multiplied pair CANONICAL (< r) — the same
// precondition the engine's classic mulmod always had (wire-sourced
// shares may be >= r; the values they meet are canonical).  Outputs are
// canonical.

#ifndef HBBFT_FIELD_PLANE_H
#define HBBFT_FIELD_PLANE_H

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>

// IFMA arm entry points (native/field_ifma.cpp — always linked; compiled
// as stubs when the toolchain lacks -mavx512ifma, in which case
// hbf_ifma_compiled() is 0 and the dispatch never reaches them).
extern "C" {
int32_t hbf_ifma_compiled();
int32_t hbf_ifma_cpu_ok();
void hbf_ifma_mul_batch(const uint64_t* a, const uint64_t* b, uint64_t* out,
                        size_t n);
void hbf_ifma_dot_acc(const uint64_t* a, const uint64_t* b, size_t n,
                      uint64_t acc8[8], size_t* done);
void hbf_ifma_lagrange_dens(const int64_t* xs, size_t k, uint64_t* dens);
void hbf_ifma_rlc_accum(const uint64_t* x, const uint64_t* coeffs, size_t n,
                        uint64_t acc8[8]);
}

namespace hbf {

// --------------------------------------------------------------------------
// Constants (r = BLS12-381 scalar field order)
// --------------------------------------------------------------------------

// r = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
inline const uint64_t R4[4] = {0xFFFFFFFF00000001ULL, 0x53BDA402FFFE5BFEULL,
                               0x3339D80809A1D805ULL, 0x73EDA753299D7D48ULL};
// -(r^-1) mod 2^64
inline const uint64_t NP64 = 0xFFFFFFFEFFFFFFFFULL;
// 2^512 mod r (to_mont multiplier for the 2^256 radix)
inline const uint64_t R2_256[4] = {0xC999E990F3F29C6DULL, 0x2B6CEDCB87925C23ULL,
                                   0x05D314967254398FULL, 0x0748D9D99F59FF11ULL};
// 2^256 mod r (Montgomery one for the 2^256 radix)
inline const uint64_t ONE_M256[4] = {0x00000001FFFFFFFEULL,
                                     0x5884B7FA00034802ULL,
                                     0x998C4FEFECBC4FF5ULL,
                                     0x1824B159ACC5056FULL};
// 2^260 mod r (the IFMA radix; used to lift IFMA-reduced partial sums
// back to plain values on the scalar side of the boundary)
inline const uint64_t TWO260[4] = {0x00000022FFFFFFDDULL, 0x8D12939700396C23ULL,
                                   0xFF1776E6AEDF7745ULL, 0x26821FA14F77DF20ULL};

// --------------------------------------------------------------------------
// 4x64 scalar core (little-endian words)
// --------------------------------------------------------------------------

inline int cmp4(const uint64_t a[4], const uint64_t b[4]) {
  for (int i = 3; i >= 0; --i) {
    if (a[i] < b[i]) return -1;
    if (a[i] > b[i]) return 1;
  }
  return 0;
}

inline bool is_zero4(const uint64_t a[4]) {
  return (a[0] | a[1] | a[2] | a[3]) == 0;
}

// a + b with carry out (no reduction); out may alias a or b.
inline uint64_t add4_raw(const uint64_t a[4], const uint64_t b[4],
                         uint64_t out[4]) {
  unsigned __int128 c = 0;
  for (int i = 0; i < 4; ++i) {
    c += (unsigned __int128)a[i] + b[i];
    out[i] = (uint64_t)c;
    c >>= 64;
  }
  return (uint64_t)c;
}

// a - b with borrow out; out may alias.
inline uint64_t sub4_raw(const uint64_t a[4], const uint64_t b[4],
                         uint64_t out[4]) {
  unsigned __int128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 d = (unsigned __int128)a[i] - b[i] - (uint64_t)borrow;
    out[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  return (uint64_t)borrow;
}

inline void addmod4(const uint64_t a[4], const uint64_t b[4], uint64_t out[4]) {
  uint64_t s[4], t[4];
  uint64_t carry = add4_raw(a, b, s);
  uint64_t borrow = sub4_raw(s, R4, t);
  if (carry || !borrow)
    std::memcpy(out, t, sizeof(t));
  else
    std::memcpy(out, s, sizeof(s));
}

inline void submod4(const uint64_t a[4], const uint64_t b[4], uint64_t out[4]) {
  uint64_t d[4];
  if (sub4_raw(a, b, d)) add4_raw(d, R4, d);
  std::memcpy(out, d, sizeof(d));
}

inline void mul4_raw(const uint64_t a[4], const uint64_t b[4],
                     uint64_t out[8]) {
  std::memset(out, 0, 8 * sizeof(uint64_t));
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 c = 0;
    for (int j = 0; j < 4; ++j) {
      c += (unsigned __int128)a[i] * b[j] + out[i + j];
      out[i + j] = (uint64_t)c;
      c >>= 64;
    }
    out[i + 4] = (uint64_t)c;
  }
}

// REDC: given T (8 words, T < r * 2^256), returns T * 2^-256 mod r,
// canonical.
inline void redc256(const uint64_t t_in[8], uint64_t out[4]) {
  uint64_t t[9];
  std::memcpy(t, t_in, 8 * sizeof(uint64_t));
  t[8] = 0;
  for (int i = 0; i < 4; ++i) {
    uint64_t m = t[i] * NP64;
    unsigned __int128 c = 0;
    for (int j = 0; j < 4; ++j) {
      c += (unsigned __int128)m * R4[j] + t[i + j];
      t[i + j] = (uint64_t)c;
      c >>= 64;
    }
    for (int j = i + 4; j < 9 && c; ++j) {
      c += t[j];
      t[j] = (uint64_t)c;
      c >>= 64;
    }
  }
  uint64_t res[4] = {t[4], t[5], t[6], t[7]};
  if (t[8] || cmp4(res, R4) >= 0) sub4_raw(res, R4, res);
  std::memcpy(out, res, sizeof(res));
}

// Montgomery product a * b * 2^-256 mod r (canonical out).  Valid when
// a * b < r * 2^256 — i.e. at least one side canonical, the other
// < 2^256.  One REDC pass: the building block that keeps loops in the
// Montgomery domain (the classic mulmod pays two).
inline void mont_mul4(const uint64_t a[4], const uint64_t b[4],
                      uint64_t out[4]) {
  uint64_t t[8];
  mul4_raw(a, b, t);
  redc256(t, out);
}

// a -> a * 2^256 mod r (enter the 2^256 Montgomery domain)
inline void to_mont4(const uint64_t a[4], uint64_t out[4]) {
  mont_mul4(a, R2_256, out);
}

// a -> a * 2^-256 mod r (leave the domain; also the exact map from a
// mont residue back to its plain value)
inline void from_mont4(const uint64_t a[4], uint64_t out[4]) {
  uint64_t t[8] = {a[0], a[1], a[2], a[3], 0, 0, 0, 0};
  redc256(t, out);
}

// Classic full product a * b mod r (two REDC passes) — for one-shot
// call sites; batch loops should stay in the Montgomery domain instead.
inline void mulmod4(const uint64_t a[4], const uint64_t b[4], uint64_t out[4]) {
  uint64_t m[4];
  mont_mul4(a, b, m);
  mont_mul4(m, R2_256, out);
}

// a^(r-2) in the Montgomery domain: in/out are mont residues (the
// domain is a ring isomorphic via x -> x*2^256, so the Fermat ladder
// carries over verbatim with mont_mul as the product).
inline void mont_inv4(const uint64_t a_m[4], uint64_t out_m[4]) {
  uint64_t e[4];
  std::memcpy(e, R4, sizeof(e));
  e[0] -= 2;  // r - 2 (no borrow: r[0] ends ...0001)
  uint64_t result[4], base[4];
  std::memcpy(result, ONE_M256, sizeof(result));
  std::memcpy(base, a_m, sizeof(base));
  for (int i = 0; i < 255; ++i) {
    if ((e[i / 64] >> (i % 64)) & 1) mont_mul4(result, base, result);
    mont_mul4(base, base, base);
  }
  std::memcpy(out_m, result, sizeof(result));
}

// base^e mod r for a small exponent (square-and-multiply over classic
// mulmod; e <= 2^20 in practice — the per-kernel-call R-power fixups).
inline void pow_small4(const uint64_t base[4], uint64_t e, uint64_t out[4]) {
  uint64_t acc[4] = {1, 0, 0, 0};
  uint64_t b[4];
  std::memcpy(b, base, sizeof(b));
  while (e) {
    if (e & 1) mulmod4(acc, b, acc);
    e >>= 1;
    if (e) mulmod4(b, b, b);
  }
  std::memcpy(out, acc, sizeof(acc));
}

// --------------------------------------------------------------------------
// Dispatch
// --------------------------------------------------------------------------

// -1 = auto (HBBFT_TPU_SIMD env, default on), 0 = force scalar,
// 1 = force IFMA (clamped to availability).
inline std::atomic<int32_t>& simd_force_cell() {
  static std::atomic<int32_t> cell{-1};
  return cell;
}

inline int32_t simd_available() {
  static const int32_t avail =
      (hbf_ifma_compiled() && hbf_ifma_cpu_ok()) ? 1 : 0;
  return avail;
}

// Resolved dispatch mode for this call: 1 = IFMA, 0 = scalar.
inline int32_t simd_mode() {
  int32_t f = simd_force_cell().load(std::memory_order_relaxed);
  if (f == 0) return 0;
  if (f == 1) return simd_available();
  static const int32_t env_on = [] {
    const char* s = std::getenv("HBBFT_TPU_SIMD");
    return (s && s[0] == '0' && !s[1]) ? 0 : 1;
  }();
  return env_on ? simd_available() : 0;
}

inline int32_t simd_force(int32_t mode) {
  simd_force_cell().store(mode < 0 ? -1 : (mode ? 1 : 0),
                          std::memory_order_relaxed);
  return simd_mode();
}

// --------------------------------------------------------------------------
// Batch kernels (R-free boundaries; see the dispatch-identity contract)
// --------------------------------------------------------------------------

// out[i] = a[i] * b[i] mod r (elementwise; arrays of n 4-word values).
// Precondition per pair: at least one side canonical.
inline void mul_batch(const uint64_t* a, const uint64_t* b, uint64_t* out,
                      size_t n) {
  size_t i = 0;
  if (simd_mode() && n >= 8) {
    size_t main = n & ~(size_t)7;
    hbf_ifma_mul_batch(a, b, out, main);
    i = main;
  }
  for (; i < n; ++i) mulmod4(a + 4 * i, b + 4 * i, out + 4 * i);
}

// out = sum_i a[i] * b[i] mod r.  The scalar arm accumulates one-REDC
// Montgomery products (sum of a*b*2^-256 terms, linear in the shared
// R-factor) and converts ONCE; the IFMA arm accumulates a*b*2^-260
// terms and lifts by 2^260 once.  Both yield the exact canonical sum.
inline void dot_batch(const uint64_t* a, const uint64_t* b, size_t n,
                      uint64_t out[4]) {
  uint64_t s[4] = {0, 0, 0, 0};
  size_t i = 0;
  if (simd_mode() && n >= 8) {
    uint64_t acc8[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    size_t done = 0;
    hbf_ifma_dot_acc(a, b, n, acc8, &done);
    // acc8 = exact integer sum of the 8-lane Montgomery products
    // (== (sum_{i<done} a_i*b_i) * 2^-260 mod r, unreduced): reduce,
    // then lift the 2^-260.
    uint64_t red[4];
    uint64_t m[4];
    redc256(acc8, m);  // * 2^-256
    uint64_t t[8];
    mul4_raw(m, R2_256, t);
    redc256(t, red);  // exact value of acc8 mod r
    mulmod4(red, TWO260, s);
    i = done;
  }
  if (i < n) {
    // Scalar (sub)sum in the 2^-256-deficit domain, lifted once.
    uint64_t t[4] = {0, 0, 0, 0};
    for (; i < n; ++i) {
      uint64_t p[4];
      mont_mul4(a + 4 * i, b + 4 * i, p);  // a*b*2^-256
      addmod4(t, p, t);
    }
    to_mont4(t, t);  // * 2^256: the exact canonical partial sum
    addmod4(s, t, s);
  }
  std::memcpy(out, s, 4 * sizeof(uint64_t));
}

// dens[i] = prod_{j != i} (x_j - x_i) mod r for i in [0, k); xs are
// positive evaluation points < 2^31 (Lagrange denominators — the
// O(k^2) half of every coefficient computation).  A zero output marks
// a duplicate point (callers treat it as their existing fall-back /
// invalid-input condition).
inline void lagrange_dens(const int64_t* xs, size_t k, uint64_t* dens) {
  if (simd_mode() && k >= 8) {
    hbf_ifma_lagrange_dens(xs, k, dens);
    return;
  }
  // Scalar arm: Montgomery-domain chains with a single R-power fixup
  // (k-1 one-REDC muls per point instead of k-1 classic two-REDC
  // mulmods).  acc starts at ONE_M256 (= R); after m = k-1 products of
  // canonical factors it holds prod * R^(2-k); multiplying by
  // R^(k-1) through one more mont_mul restores the canonical product.
  uint64_t fix[4];
  pow_small4(ONE_M256, k >= 1 ? k - 1 : 0, fix);
  for (size_t i = 0; i < k; ++i) {
    uint64_t acc[4];
    std::memcpy(acc, ONE_M256, sizeof(acc));
    uint64_t xi[4] = {(uint64_t)xs[i], 0, 0, 0};
    for (size_t j = 0; j < k; ++j) {
      if (j == i) continue;
      uint64_t xj[4] = {(uint64_t)xs[j], 0, 0, 0};
      uint64_t f[4];
      submod4(xj, xi, f);
      mont_mul4(acc, f, acc);
    }
    mont_mul4(acc, fix, dens + 4 * i);
  }
}

// acc8 += sum_i coeffs[i] * x[i] as an EXACT 512-bit integer (the RLC
// accumulate: coeffs are 64-bit, x are 4-word values; n * 2^320 fits 8
// words for any feasible n).  Identical to the per-item schoolbook
// accumulate in either arm — the sum is an integer, not a residue.
inline void rlc_accum(const uint64_t* x, const uint64_t* coeffs, size_t n,
                      uint64_t acc8[8]) {
  size_t i = 0;
  if (simd_mode() && n >= 8) {
    size_t main = n & ~(size_t)7;
    hbf_ifma_rlc_accum(x, coeffs, main, acc8);
    i = main;
  }
  for (; i < n; ++i) {
    const uint64_t* a = x + 4 * i;
    uint64_t r = coeffs[i];
    unsigned __int128 c = 0;
    for (int w = 0; w < 4; ++w) {
      c += (unsigned __int128)a[w] * r + acc8[w];
      acc8[w] = (uint64_t)c;
      c >>= 64;
    }
    for (int w = 4; w < 8 && c; ++w) {
      c += acc8[w];
      acc8[w] = (uint64_t)c;
      c >>= 64;
    }
  }
}

}  // namespace hbf

#endif  // HBBFT_FIELD_PLANE_H
