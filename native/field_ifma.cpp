// AVX-512 IFMA arm of the field-arithmetic plane (native/field_plane.h).
//
// This translation unit is the ONLY code compiled with -mavx512ifma
// (feature-gated in native/Makefile and hbbft_tpu/ops/native.py: the
// flag is dropped if the toolchain rejects it, and the #else branch
// below compiles stubs).  The runtime-dispatch guarantee that a
// non-IFMA host never executes vector code rests on two rules:
//
//  1. hbf::simd_mode() (field_plane.h) only routes here when
//     hbf_ifma_compiled() AND hbf_ifma_cpu_ok() both hold — every other
//     function in this file runs exclusively behind that gate, so the
//     compiler is free to use EVB/EVEX encodings anywhere in them.
//  2. This file includes NO shared inline code (not even field_plane.h):
//     a COMDAT-inline function compiled here under -mavx512ifma could
//     win linker resolution over the copy engine.cpp instantiated and
//     smuggle AVX-512 into unconditionally-executed paths.  The few
//     4x64 scalar helpers the fixup constants need are duplicated as
//     static locals instead.
//
// Kernel math: 8-lane structure-of-arrays over 52-bit limbs (5 limbs =
// 260 bits), Montgomery radix 2^260, CIOS reduction with
// _mm512_madd52{lo,hi}_epu64, lazy reduction (values < 2r between
// multiplies, strict-52 limbs re-normalized after every multiply so the
// madd52 low-52 masking stays exact).  Boundary semantics are R-free
// (field_plane.h dispatch-identity contract): canonical values or exact
// integers in and out, so results are bit-identical to the scalar arm.

#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" int32_t hbf_ifma_cpu_ok() {
#if defined(__x86_64__) || defined(__i386__)
  return (__builtin_cpu_supports("avx512ifma") &&
          __builtin_cpu_supports("avx512f"))
             ? 1
             : 0;
#else
  return 0;
#endif
}

#if defined(__AVX512IFMA__) && defined(__AVX512F__)

#include <immintrin.h>

extern "C" int32_t hbf_ifma_compiled() { return 1; }

namespace {

const uint64_t M52 = (1ULL << 52) - 1;
// -(r^-1) mod 2^52
const uint64_t NP52 = 0xFFFFEFFFFFFFFULL;
// r in 52-bit limbs (little-endian)
const uint64_t R52[5] = {0xFFFFF00000001ULL, 0x02FFFE5BFEFFFULL,
                         0x9A1D80553BDA4ULL, 0x7D483339D8080ULL,
                         0x073EDA753299DULL};
// 2^260 mod r (Montgomery one for this radix), 52-bit limbs
const uint64_t ONEM260_52[5] = {0x00022FFFFFFDDULL, 0x9700396C23000ULL,
                                0xEDF77458D1293ULL, 0xDF20FF1776E6AULL,
                                0x026821FA14F77ULL};
// 2^520 mod r (to-Montgomery multiplier for this radix), 52-bit limbs
const uint64_t R2_260_52[5] = {0x99103F29C6CF0ULL, 0x57927663D999EULL,
                               0xA1C0ED631138BULL, 0x3C829F7715F1BULL,
                               0x009FF646CC027ULL};
// r and 2^260 mod r in 64-bit words (for the scalar fixup-power helper)
const uint64_t R64[4] = {0xFFFFFFFF00000001ULL, 0x53BDA402FFFE5BFEULL,
                         0x3339D80809A1D805ULL, 0x73EDA753299D7D48ULL};
const uint64_t NP64 = 0xFFFFFFFEFFFFFFFFULL;
const uint64_t TWO260_64[4] = {0x00000022FFFFFFDDULL, 0x8D12939700396C23ULL,
                               0xFF1776E6AEDF7745ULL, 0x26821FA14F77DF20ULL};

// ---- minimal local 4x64 scalar helpers (fixup powers + canonical
// subtract; duplicated from field_plane.h on purpose — see the header
// comment on COMDAT contamination) --------------------------------------

int s_cmp4(const uint64_t a[4], const uint64_t b[4]) {
  for (int i = 3; i >= 0; --i) {
    if (a[i] < b[i]) return -1;
    if (a[i] > b[i]) return 1;
  }
  return 0;
}

void s_sub4(const uint64_t a[4], const uint64_t b[4], uint64_t out[4]) {
  unsigned __int128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 d = (unsigned __int128)a[i] - b[i] - (uint64_t)borrow;
    out[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

void s_mulmod4(const uint64_t a[4], const uint64_t b[4], uint64_t out[4]) {
  uint64_t t[9];
  auto redc = [&](uint64_t res[4]) {
    for (int i = 0; i < 4; ++i) {
      uint64_t m = t[i] * NP64;
      unsigned __int128 c = 0;
      for (int j = 0; j < 4; ++j) {
        c += (unsigned __int128)m * R64[j] + t[i + j];
        t[i + j] = (uint64_t)c;
        c >>= 64;
      }
      for (int j = i + 4; j < 9 && c; ++j) {
        c += t[j];
        t[j] = (uint64_t)c;
        c >>= 64;
      }
    }
    uint64_t r4[4] = {t[4], t[5], t[6], t[7]};
    if (t[8] || s_cmp4(r4, R64) >= 0) s_sub4(r4, R64, r4);
    std::memcpy(res, r4, sizeof(r4));
  };
  auto mul = [&](const uint64_t x[4], const uint64_t y[4]) {
    std::memset(t, 0, sizeof(t));
    for (int i = 0; i < 4; ++i) {
      unsigned __int128 c = 0;
      for (int j = 0; j < 4; ++j) {
        c += (unsigned __int128)x[i] * y[j] + t[i + j];
        t[i + j] = (uint64_t)c;
        c >>= 64;
      }
      t[i + 4] = (uint64_t)c;
    }
  };
  // classic two-pass mulmod (a*b*2^-256, then *2^512*2^-256)
  const uint64_t R2_256[4] = {0xC999E990F3F29C6DULL, 0x2B6CEDCB87925C23ULL,
                              0x05D314967254398FULL, 0x0748D9D99F59FF11ULL};
  uint64_t m4[4];
  mul(a, b);
  redc(m4);
  mul(m4, R2_256);
  redc(out);
}

// (2^260)^e mod r for small e (the per-call R-power fixups)
void s_pow260(uint64_t e, uint64_t out[4]) {
  uint64_t acc[4] = {1, 0, 0, 0};
  uint64_t b[4];
  std::memcpy(b, TWO260_64, sizeof(b));
  while (e) {
    if (e & 1) s_mulmod4(acc, b, acc);
    e >>= 1;
    if (e) s_mulmod4(b, b, b);
  }
  std::memcpy(out, acc, sizeof(acc));
}

void limbs52_of(const uint64_t w[4], uint64_t l[5]) {
  l[0] = w[0] & M52;
  l[1] = ((w[0] >> 52) | (w[1] << 12)) & M52;
  l[2] = ((w[1] >> 40) | (w[2] << 24)) & M52;
  l[3] = ((w[2] >> 28) | (w[3] << 36)) & M52;
  l[4] = w[3] >> 16;
}

// ---- 8-lane SoA core ---------------------------------------------------

struct Fe8 {
  __m512i l[5];
};

inline __m512i vm52() { return _mm512_set1_epi64((long long)M52); }

inline Fe8 bcast(const uint64_t limbs[5]) {
  Fe8 o;
  for (int i = 0; i < 5; ++i) o.l[i] = _mm512_set1_epi64((long long)limbs[i]);
  return o;
}

inline __m512i stride4_idx() { return _mm512_setr_epi64(0, 4, 8, 12, 16, 20, 24, 28); }

// 8 consecutive 4-word elements (AoS) -> 52-bit SoA
inline Fe8 load8(const uint64_t* aos) {
  __m512i idx = stride4_idx();
  __m512i w0 = _mm512_i64gather_epi64(idx, aos + 0, 8);
  __m512i w1 = _mm512_i64gather_epi64(idx, aos + 1, 8);
  __m512i w2 = _mm512_i64gather_epi64(idx, aos + 2, 8);
  __m512i w3 = _mm512_i64gather_epi64(idx, aos + 3, 8);
  __m512i m = vm52();
  Fe8 o;
  o.l[0] = _mm512_and_epi64(w0, m);
  o.l[1] = _mm512_and_epi64(
      _mm512_or_epi64(_mm512_srli_epi64(w0, 52), _mm512_slli_epi64(w1, 12)), m);
  o.l[2] = _mm512_and_epi64(
      _mm512_or_epi64(_mm512_srli_epi64(w1, 40), _mm512_slli_epi64(w2, 24)), m);
  o.l[3] = _mm512_and_epi64(
      _mm512_or_epi64(_mm512_srli_epi64(w2, 28), _mm512_slli_epi64(w3, 36)), m);
  o.l[4] = _mm512_srli_epi64(w3, 16);
  return o;
}

// strict-52 SoA (value < 2^256) -> 8 AoS elements
inline void store8(const Fe8& a, uint64_t* aos) {
  __m512i w0 = _mm512_or_epi64(a.l[0], _mm512_slli_epi64(a.l[1], 52));
  __m512i w1 = _mm512_or_epi64(_mm512_srli_epi64(a.l[1], 12),
                               _mm512_slli_epi64(a.l[2], 40));
  __m512i w2 = _mm512_or_epi64(_mm512_srli_epi64(a.l[2], 24),
                               _mm512_slli_epi64(a.l[3], 28));
  __m512i w3 = _mm512_or_epi64(_mm512_srli_epi64(a.l[3], 36),
                               _mm512_slli_epi64(a.l[4], 16));
  __m512i idx = stride4_idx();
  _mm512_i64scatter_epi64(aos + 0, idx, w0, 8);
  _mm512_i64scatter_epi64(aos + 1, idx, w1, 8);
  _mm512_i64scatter_epi64(aos + 2, idx, w2, 8);
  _mm512_i64scatter_epi64(aos + 3, idx, w3, 8);
}

// CIOS Montgomery product a*b*2^-260 per lane (AMM: output value < 2r
// when a*b < r*2^260, which all callers satisfy), normalized back to
// strict 52-bit limbs so it can feed the next multiply.
inline Fe8 mont_mul8(const Fe8& a, const Fe8& b) {
  const __m512i z = _mm512_setzero_si512();
  const __m512i np = _mm512_set1_epi64((long long)NP52);
  const __m512i r0 = _mm512_set1_epi64((long long)R52[0]);
  const __m512i r1 = _mm512_set1_epi64((long long)R52[1]);
  const __m512i r2 = _mm512_set1_epi64((long long)R52[2]);
  const __m512i r3 = _mm512_set1_epi64((long long)R52[3]);
  const __m512i r4 = _mm512_set1_epi64((long long)R52[4]);
  __m512i t0 = z, t1 = z, t2 = z, t3 = z, t4 = z, t5 = z;
  for (int i = 0; i < 5; ++i) {
    __m512i ai = a.l[i];
    t0 = _mm512_madd52lo_epu64(t0, ai, b.l[0]);
    t1 = _mm512_madd52lo_epu64(t1, ai, b.l[1]);
    t2 = _mm512_madd52lo_epu64(t2, ai, b.l[2]);
    t3 = _mm512_madd52lo_epu64(t3, ai, b.l[3]);
    t4 = _mm512_madd52lo_epu64(t4, ai, b.l[4]);
    __m512i m = _mm512_madd52lo_epu64(z, t0, np);
    t0 = _mm512_madd52lo_epu64(t0, m, r0);
    t1 = _mm512_madd52lo_epu64(t1, m, r1);
    t2 = _mm512_madd52lo_epu64(t2, m, r2);
    t3 = _mm512_madd52lo_epu64(t3, m, r3);
    t4 = _mm512_madd52lo_epu64(t4, m, r4);
    t1 = _mm512_add_epi64(t1, _mm512_srli_epi64(t0, 52));
    // shift one limb down, folding in the high halves of this round's
    // products (they belong one position up)
    t0 = _mm512_madd52hi_epu64(_mm512_madd52hi_epu64(t1, ai, b.l[0]), m, r0);
    t1 = _mm512_madd52hi_epu64(_mm512_madd52hi_epu64(t2, ai, b.l[1]), m, r1);
    t2 = _mm512_madd52hi_epu64(_mm512_madd52hi_epu64(t3, ai, b.l[2]), m, r2);
    t3 = _mm512_madd52hi_epu64(_mm512_madd52hi_epu64(t4, ai, b.l[3]), m, r3);
    t4 = _mm512_madd52hi_epu64(_mm512_madd52hi_epu64(t5, ai, b.l[4]), m, r4);
    t5 = z;
  }
  // normalize (value < 2r < 2^257, so the top limb needs no mask)
  const __m512i m52 = vm52();
  Fe8 o;
  __m512i c;
  o.l[0] = _mm512_and_epi64(t0, m52);
  c = _mm512_srli_epi64(t0, 52);
  t1 = _mm512_add_epi64(t1, c);
  o.l[1] = _mm512_and_epi64(t1, m52);
  c = _mm512_srli_epi64(t1, 52);
  t2 = _mm512_add_epi64(t2, c);
  o.l[2] = _mm512_and_epi64(t2, m52);
  c = _mm512_srli_epi64(t2, 52);
  t3 = _mm512_add_epi64(t3, c);
  o.l[3] = _mm512_and_epi64(t3, m52);
  c = _mm512_srli_epi64(t3, 52);
  o.l[4] = _mm512_add_epi64(t4, c);
  return o;
}

// conditional subtract r per lane (strict-52 input, value < 2r):
// canonical output
inline void canon8(Fe8& a) {
  const __m512i m52 = vm52();
  __m512i d[5];
  __mmask8 borrow = 0;
  for (int i = 0; i < 5; ++i) {
    __m512i ri = _mm512_set1_epi64((long long)R52[i]);
    __m512i bi = _mm512_maskz_set1_epi64(borrow, 1);
    __m512i sub = _mm512_sub_epi64(_mm512_sub_epi64(a.l[i], ri), bi);
    // borrow iff the signed result went negative (operands < 2^53)
    borrow = _mm512_cmplt_epi64_mask(sub, _mm512_setzero_si512());
    d[i] = _mm512_and_epi64(sub, m52);
  }
  // borrow out => value < r => keep a; else take d
  for (int i = 0; i < 5; ++i)
    a.l[i] = _mm512_mask_mov_epi64(d[i], borrow, a.l[i]);
}

// Fold a 7-slot redundant SoA accumulator (per-lane 52-bit-radix
// values) into an exact 8x64-word integer added into acc8.
void fold_acc(__m512i t[7], uint64_t acc8[8]) {
  // normalize per lane first so the cross-lane sums fit u64
  const __m512i m52 = vm52();
  __m512i c = _mm512_setzero_si512();
  for (int i = 0; i < 7; ++i) {
    __m512i u = _mm512_add_epi64(t[i], c);
    c = _mm512_srli_epi64(u, 52);
    t[i] = (i < 6) ? _mm512_and_epi64(u, m52) : u;
  }
  uint64_t s[7];
  for (int i = 0; i < 7; ++i) s[i] = (uint64_t)_mm512_reduce_add_epi64(t[i]);
  // 52-bit-radix digits (each < 2^56) -> 8x64 words, added into acc8
  unsigned __int128 carry = 0;
  uint64_t add8[8] = {0, 0, 0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 7; ++i) {
    size_t bit = 52u * (size_t)i;
    size_t w = bit / 64, sh = bit % 64;
    unsigned __int128 v = (unsigned __int128)s[i] << sh;
    unsigned __int128 lo = (unsigned __int128)add8[w] + (uint64_t)v;
    add8[w] = (uint64_t)lo;
    unsigned __int128 hi =
        (unsigned __int128)add8[w + 1] + (uint64_t)(v >> 64) + (uint64_t)(lo >> 64);
    add8[w + 1] = (uint64_t)hi;
    if (hi >> 64) {
      for (size_t j = w + 2; j < 8; ++j) {
        if (++add8[j]) break;
      }
    }
  }
  carry = 0;
  for (int i = 0; i < 8; ++i) {
    carry += (unsigned __int128)acc8[i] + add8[i];
    acc8[i] = (uint64_t)carry;
    carry >>= 64;
  }
}

// ---- 8-lane state-parallel Keccak-f[1600] (batched sha3 plane,
// native/sha3_plane.h) -----------------------------------------------------
//
// Eight independent FIPS-202 SHA3-256 states side by side: Keccak state
// word w of message j lives in qword lane j of st[w].  Rotations use
// vprolvq (broadcast counts — the intrinsic with an immediate count
// cannot take a table value from a loop), chi is one vpternlogq per
// word (imm 0xD2 = a ^ (~b & c)).  Round constants / rotation offsets
// are duplicated from sha3_gf.h on purpose — this unit includes no
// shared inline code (COMDAT rule, header comment).

const uint64_t KC_RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808AULL,
    0x8000000080008000ULL, 0x000000000000808BULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008AULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000AULL,
    0x000000008000808BULL, 0x800000000000008BULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800AULL, 0x800000008000000AULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};
const int KC_RHO[25] = {0,  1,  62, 28, 27, 36, 44, 6,  55, 20, 3,  10,
                        43, 25, 39, 41, 45, 15, 21, 8,  18, 2,  61, 56,
                        14};

void keccak_f_x8(__m512i st[25]) {
  for (int round = 0; round < 24; ++round) {
    __m512i c[5], d[5];
    for (int x = 0; x < 5; ++x)
      c[x] = _mm512_xor_epi64(
          _mm512_xor_epi64(_mm512_xor_epi64(st[x], st[x + 5]),
                           _mm512_xor_epi64(st[x + 10], st[x + 15])),
          st[x + 20]);
    for (int x = 0; x < 5; ++x) {
      d[x] = _mm512_xor_epi64(c[(x + 4) % 5],
                              _mm512_rol_epi64(c[(x + 1) % 5], 1));
      for (int y = 0; y < 5; ++y)
        st[x + 5 * y] = _mm512_xor_epi64(st[x + 5 * y], d[x]);
    }
    __m512i b[25];
    for (int x = 0; x < 5; ++x)
      for (int y = 0; y < 5; ++y)
        b[y + 5 * ((2 * x + 3 * y) % 5)] = _mm512_rolv_epi64(
            st[x + 5 * y], _mm512_set1_epi64(KC_RHO[x + 5 * y]));
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x)
        st[x + 5 * y] = _mm512_ternarylogic_epi64(
            b[x + 5 * y], b[(x + 1) % 5 + 5 * y], b[(x + 2) % 5 + 5 * y],
            0xD2);
    st[0] = _mm512_xor_epi64(st[0],
                             _mm512_set1_epi64((long long)KC_RC[round]));
  }
}

}  // namespace

extern "C" {

// out[i] = a[i]*b[i] mod r, n a multiple of 8.
void hbf_ifma_mul_batch(const uint64_t* a, const uint64_t* b, uint64_t* out,
                        size_t n) {
  Fe8 r2 = bcast(R2_260_52);
  for (size_t i = 0; i < n; i += 8) {
    Fe8 A = load8(a + 4 * i);
    Fe8 B = load8(b + 4 * i);
    Fe8 P = mont_mul8(A, B);        // a*b*2^-260
    Fe8 Q = mont_mul8(P, r2);       // a*b
    canon8(Q);
    store8(Q, out + 4 * i);
  }
}

// acc8 += exact integer sum of per-lane a[i]*b[i]*2^-260 residues over
// the largest multiple-of-8 prefix; *done reports how many elements
// were consumed (the caller lifts the 2^-260 once and handles the tail).
void hbf_ifma_dot_acc(const uint64_t* a, const uint64_t* b, size_t n,
                      uint64_t acc8[8], size_t* done) {
  __m512i t[7];
  for (int i = 0; i < 7; ++i) t[i] = _mm512_setzero_si512();
  size_t main = n & ~(size_t)7;
  size_t since_fold = 0;
  for (size_t i = 0; i < main; i += 8) {
    Fe8 A = load8(a + 4 * i);
    Fe8 B = load8(b + 4 * i);
    Fe8 P = mont_mul8(A, B);  // strict-52 limbs, value < 2r
    for (int l = 0; l < 5; ++l) t[l] = _mm512_add_epi64(t[l], P.l[l]);
    // limbs grow ~2^52 per chunk: fold well before u64 overflow
    if (++since_fold == 1024) {
      fold_acc(t, acc8);
      for (int l = 0; l < 7; ++l) t[l] = _mm512_setzero_si512();
      since_fold = 0;
    }
  }
  if (since_fold) fold_acc(t, acc8);
  *done = main;
}

// dens[i] = prod_{j != i} (x_j - x_i) mod r (canonical), xs positive.
void hbf_ifma_lagrange_dens(const int64_t* xs, size_t k, uint64_t* dens) {
  uint64_t fix64[4];
  s_pow260(k - 1, fix64);  // (2^260)^(k-1) mod r
  uint64_t fix52[5];
  limbs52_of(fix64, fix52);
  Fe8 FIX = bcast(fix52);
  Fe8 ONE = bcast(ONEM260_52);
  const __m512i z = _mm512_setzero_si512();
  const __m512i r0 = _mm512_set1_epi64((long long)R52[0]);
  for (size_t base = 0; base < k; base += 8) {
    alignas(64) int64_t xi[8];
    for (int l = 0; l < 8; ++l)
      xi[l] = (base + (size_t)l < k) ? xs[base + l] : 0;
    __m512i XI = _mm512_load_si512((const void*)xi);
    Fe8 acc = ONE;
    for (size_t j = 0; j < k; ++j) {
      __m512i d = _mm512_sub_epi64(_mm512_set1_epi64(xs[j]), XI);
      __mmask8 wrap = _mm512_cmple_epi64_mask(d, z);  // x_j <= x_i: + r
      Fe8 f;
      f.l[0] = _mm512_mask_add_epi64(d, wrap, d, r0);
      for (int l = 1; l < 5; ++l)
        f.l[l] = _mm512_maskz_set1_epi64(wrap, (long long)R52[l]);
      if (j >= base && j < base + 8) {
        // the i == j lane multiplies by the Montgomery one instead
        // (keeps every lane's R-deficit uniform for the single fixup)
        __mmask8 self = (__mmask8)(1u << (j - base));
        for (int l = 0; l < 5; ++l)
          f.l[l] = _mm512_mask_mov_epi64(f.l[l], self, ONE.l[l]);
      }
      acc = mont_mul8(acc, f);
    }
    acc = mont_mul8(acc, FIX);
    canon8(acc);
    size_t lanes = k - base < 8 ? k - base : 8;
    if (lanes == 8) {
      store8(acc, dens + 4 * base);
    } else {
      alignas(64) uint64_t tmp[32];
      store8(acc, tmp);
      std::memcpy(dens + 4 * base, tmp, lanes * 4 * sizeof(uint64_t));
    }
  }
}

// acc8 += sum_i coeffs[i]*x[i] (exact integer), n a multiple of 8.
void hbf_ifma_rlc_accum(const uint64_t* x, const uint64_t* coeffs, size_t n,
                        uint64_t acc8[8]) {
  __m512i t[7];
  for (int i = 0; i < 7; ++i) t[i] = _mm512_setzero_si512();
  const __m512i m52 = vm52();
  size_t since_fold = 0;
  for (size_t i = 0; i < n; i += 8) {
    Fe8 A = load8(x + 4 * i);
    __m512i C = _mm512_loadu_si512((const void*)(coeffs + i));
    __m512i clo = _mm512_and_epi64(C, m52);
    __m512i chi = _mm512_srli_epi64(C, 52);
    for (int l = 0; l < 5; ++l) {
      t[l] = _mm512_madd52lo_epu64(t[l], clo, A.l[l]);
      t[l + 1] = _mm512_madd52hi_epu64(t[l + 1], clo, A.l[l]);
      t[l + 1] = _mm512_madd52lo_epu64(t[l + 1], chi, A.l[l]);
      t[l + 2] = _mm512_madd52hi_epu64(t[l + 2], chi, A.l[l]);
    }
    if (++since_fold == 512) {
      fold_acc(t, acc8);
      for (int l = 0; l < 7; ++l) t[l] = _mm512_setzero_si512();
      since_fold = 0;
    }
  }
  if (since_fold) fold_acc(t, acc8);
}

// SHA3-256 of 8 equal-length messages (contiguous, stride msg_len);
// digests contiguous (32 bytes each) at out.  Full rate blocks are
// absorbed by qword gathers straight from the messages; the final
// padded block is staged scalar-side (FIPS-202: 0x06 after the tail,
// 0x80 into the last rate byte) so short tails never read past a
// message.  Digest-identical to hbn::sha3_256 per message — the sha3
// plane's dispatch-identity contract rests on exactly that.
void hbf_ifma_sha3_256_x8(const uint8_t* in, size_t msg_len, uint8_t* out) {
  const size_t RATE = 136;  // SHA3-256
  __m512i st[25];
  for (int i = 0; i < 25; ++i) st[i] = _mm512_setzero_si512();
  const __m512i midx = _mm512_setr_epi64(
      0, (long long)msg_len, (long long)(2 * msg_len), (long long)(3 * msg_len),
      (long long)(4 * msg_len), (long long)(5 * msg_len),
      (long long)(6 * msg_len), (long long)(7 * msg_len));
  size_t nfull = msg_len / RATE;
  for (size_t b = 0; b < nfull; ++b) {
    const uint8_t* base = in + b * RATE;
    for (int i = 0; i < 17; ++i) {
      __m512i w = _mm512_i64gather_epi64(midx, (const void*)(base + 8 * i), 1);
      st[i] = _mm512_xor_epi64(st[i], w);
    }
    keccak_f_x8(st);
  }
  size_t rem = msg_len - nfull * RATE;
  alignas(64) uint8_t stage[8 * 136];
  std::memset(stage, 0, sizeof(stage));
  for (int j = 0; j < 8; ++j) {
    std::memcpy(stage + j * RATE, in + j * msg_len + nfull * RATE, rem);
    stage[j * RATE + rem] = 0x06;
    stage[j * RATE + RATE - 1] ^= 0x80;
  }
  const __m512i sidx = _mm512_setr_epi64(0, 136, 272, 408, 544, 680, 816, 952);
  for (int i = 0; i < 17; ++i) {
    __m512i w = _mm512_i64gather_epi64(sidx, (const void*)(stage + 8 * i), 1);
    st[i] = _mm512_xor_epi64(st[i], w);
  }
  keccak_f_x8(st);
  const __m512i oidx = _mm512_setr_epi64(0, 32, 64, 96, 128, 160, 192, 224);
  for (int w = 0; w < 4; ++w)
    _mm512_i64scatter_epi64((void*)(out + 8 * w), oidx, st[w], 1);
}

}  // extern "C"

#else  // !__AVX512IFMA__: stub arm (never dispatched to)

extern "C" {

int32_t hbf_ifma_compiled() { return 0; }

void hbf_ifma_mul_batch(const uint64_t*, const uint64_t*, uint64_t*, size_t) {}
void hbf_ifma_dot_acc(const uint64_t*, const uint64_t*, size_t,
                      uint64_t[8], size_t* done) {
  *done = 0;
}
void hbf_ifma_lagrange_dens(const int64_t*, size_t, uint64_t*) {}
void hbf_ifma_rlc_accum(const uint64_t*, const uint64_t*, size_t, uint64_t[8]) {
}
void hbf_ifma_sha3_256_x8(const uint8_t*, size_t, uint8_t*) {}

}  // extern "C"

#endif
