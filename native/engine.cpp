// Native protocol-plane engine: the HoneyBadger message loop in C++.
//
// Reference behavior: the reference runs its entire consensus stack as
// native (Rust) code; this engine is the equivalent for the
// message-intensive layers — Broadcast, SBV/BinaryAgreement (with the
// ThresholdSign common coin), ThresholdDecrypt, Subset and the
// HoneyBadger epoch loop — for a whole simulated network of nodes with
// a FIFO delivery queue (the VirtualNet crank loop, upstream
// ``tests/net/mod.rs``).  Python keeps the layers that are per-BATCH
// rather than per-message: DynamicHoneyBadger votes / DKG / era logic,
// QueueingHoneyBadger sampling, contribution serde and threshold
// encryption (via callbacks at batch boundaries).
//
// FIDELITY CONTRACT: every handler is a faithful port of the Python
// implementation in hbbft_tpu/protocols/* (same thresholds, same fault
// kinds, same message emission order, same buffering rules, same
// deferred-verify pool semantics with an eager flush), over the
// scalar-insecure suite (hbbft_tpu/crypto/suite.py) — so a run of this
// engine commits byte-identical batches to the pure-Python VirtualNet
// at the same seed.  tests/test_native_engine.py pins this equivalence.
//
// Crypto here is the SCALAR test suite only (additive Z_r, trivial
// discrete logs — protocol-plane benchmarking); real BLS runs use the
// Python/TPU path.  C ABI only (ctypes); no exceptions cross the
// boundary.

#include <algorithm>
#include <cassert>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <atomic>
#include <mutex>
#include <string>
#include <thread>
#include <type_traits>
#include <vector>

#include "field_plane.h"
#include "sha3_gf.h"
#include "sha3_plane.h"
#include <chrono>

namespace {

// Portable cycle/tick source for the delivery profiling counters
// (rdtsc on x86; steady_clock elsewhere so non-x86 builds still work).
inline uint64_t prof_tick() {
#if defined(__x86_64__) || defined(__i386__)
  return __builtin_ia32_rdtsc();
#else
  return (uint64_t)std::chrono::steady_clock::now().time_since_epoch().count();
#endif
}

}  // namespace

namespace {

// ===========================================================================
// 256-bit arithmetic mod r (BLS12-381 scalar field order)
//
// The primitive implementations live in native/field_plane.h (round 15):
// the shared scalar Montgomery core plus the dispatched batch kernels
// (AVX-512 IFMA arm in native/field_ifma.cpp, HBBFT_TPU_SIMD switch).
// The U256 wrappers below keep the engine's historical names.
// ===========================================================================

struct U256 {
  uint64_t w[4];  // little-endian words
  bool operator==(const U256& o) const {
    return std::memcmp(w, o.w, sizeof(w)) == 0;
  }
};

const U256 U256_ZERO = {{0, 0, 0, 0}};

// r = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
const U256 R_MOD = {{0xFFFFFFFF00000001ULL, 0x53BDA402FFFE5BFEULL,
                     0x3339D80809A1D805ULL, 0x73EDA753299D7D48ULL}};
// r - 1
const U256 R_MINUS_1 = {{0xFFFFFFFF00000000ULL, 0x53BDA402FFFE5BFEULL,
                         0x3339D80809A1D805ULL, 0x73EDA753299D7D48ULL}};

inline int u256_cmp(const U256& a, const U256& b) {
  return hbf::cmp4(a.w, b.w);
}

inline bool u256_is_zero(const U256& a) { return hbf::is_zero4(a.w); }

// a + b with carry out (no reduction)
inline uint64_t u256_add_raw(const U256& a, const U256& b, U256& out) {
  return hbf::add4_raw(a.w, b.w, out.w);
}

// a - b with borrow out
inline uint64_t u256_sub_raw(const U256& a, const U256& b, U256& out) {
  return hbf::sub4_raw(a.w, b.w, out.w);
}

inline U256 addmod(const U256& a, const U256& b) {
  U256 o;
  hbf::addmod4(a.w, b.w, o.w);
  return o;
}

inline U256 submod(const U256& a, const U256& b) {
  U256 o;
  hbf::submod4(a.w, b.w, o.w);
  return o;
}

// Montgomery machinery (field_plane.h): engine state stays CANONICAL at
// rest, but every batch loop runs in the Montgomery domain end-to-end
// and converts once at its boundaries (round 15) — the classic two-REDC
// mulmod below is for one-shot call sites only.

// REDC: given T (8 words, value < r * 2^256), returns T * 2^-256 mod r.
inline U256 redc(const uint64_t t_in[8]) {
  U256 o;
  hbf::redc256(t_in, o.w);
  return o;
}

inline void u256_mul_raw(const U256& a, const U256& b, uint64_t out[8]) {
  hbf::mul4_raw(a.w, b.w, out);
}

inline U256 mulmod(const U256& a, const U256& b) {
  U256 o;
  hbf::mulmod4(a.w, b.w, o.w);
  return o;
}

// One-REDC Montgomery product a*b*2^-256 (canonical; one side < r).
inline U256 mont_mul(const U256& a, const U256& b) {
  U256 o;
  hbf::mont_mul4(a.w, b.w, o.w);
  return o;
}

inline U256 to_mont(const U256& a) {
  U256 o;
  hbf::to_mont4(a.w, o.w);
  return o;
}

inline U256 from_mont(const U256& a) {
  U256 o;
  hbf::from_mont4(a.w, o.w);
  return o;
}

// 2^256 mod r — the field plane's copy is the source of truth (the
// deliberate-duplication COMDAT rule covers field_ifma.cpp only).
const U256 ONE_MONT = {{hbf::ONE_M256[0], hbf::ONE_M256[1],
                        hbf::ONE_M256[2], hbf::ONE_M256[3]}};

inline U256 invmod(const U256& a) {
  // Fermat a^(r-2), run inside the Montgomery domain (one REDC per
  // ladder step instead of the classic ladder's two).
  U256 am = to_mont(a), im;
  hbf::mont_inv4(am.w, im.w);
  return from_mont(im);
}

inline void u256_to_be32(const U256& a, uint8_t out[32]) {
  for (int i = 0; i < 4; ++i) {
    uint64_t w = a.w[3 - i];
    for (int j = 0; j < 8; ++j) out[i * 8 + j] = (uint8_t)(w >> (56 - 8 * j));
  }
}

inline U256 u256_from_be(const uint8_t* in, size_t len) {
  U256 out = U256_ZERO;
  // take the last min(len,32) bytes, big-endian
  size_t take = len > 32 ? 32 : len;
  const uint8_t* p = in + (len - take);
  for (size_t i = 0; i < take; ++i) {
    size_t bit_pos = (take - 1 - i) * 8;
    out.w[bit_pos / 64] |= (uint64_t)p[i] << (bit_pos % 64);
  }
  return out;
}

// ===========================================================================
// canonical_bytes hashing + scalar-suite primitives
// (mirrors hbbft_tpu/utils/__init__.py and crypto/suite.py exactly)
// ===========================================================================

using Bytes = std::string;  // byte strings
// Big payloads (RBC values, HB plaintexts, serde ciphertexts) are
// shared, never copied: at an era change a single DKG-epoch payload
// is several hundred KB and flows through decode-cache -> Bcast ->
// Subset -> ThresholdDecrypt -> batch; per-stage copies at N=64 were
// gigabytes of memcpy (round-3 era profile).
using BytesP = std::shared_ptr<const Bytes>;

inline void canon_part(hbn::Sha3& h, const uint8_t* data, size_t len) {
  uint8_t len8[8];
  for (int i = 0; i < 8; ++i) len8[i] = (uint8_t)(len >> (56 - 8 * i));
  h.update(len8, 8);
  h.update(data, len);
}

inline void canon_part(hbn::Sha3& h, const Bytes& b) {
  canon_part(h, (const uint8_t*)b.data(), b.size());
}

inline Bytes canon_int_bytes(uint64_t v) {
  // Python canonical_bytes int: minimal big-endian, >= 1 byte.
  Bytes out;
  int nbytes = 1;
  for (uint64_t t = v; t > 0xFF; t >>= 8) ++nbytes;
  out.resize(nbytes);
  for (int i = 0; i < nbytes; ++i)
    out[i] = (char)(uint8_t)(v >> (8 * (nbytes - 1 - i)));
  return out;
}

// Append a length-prefixed part to a byte string (canonical_bytes builder).
inline void canon_append(Bytes& out, const Bytes& part) {
  uint8_t len8[8];
  uint64_t len = part.size();
  for (int i = 0; i < 8; ++i) len8[i] = (uint8_t)(len >> (56 - 8 * i));
  out.append((const char*)len8, 8);
  out.append(part);
}

inline Bytes canon2(const Bytes& a, const Bytes& b) {
  Bytes out;
  canon_append(out, a);
  canon_append(out, b);
  return out;
}

inline Bytes canon3(const Bytes& a, const Bytes& b, const Bytes& c) {
  Bytes out;
  canon_append(out, a);
  canon_append(out, b);
  canon_append(out, c);
  return out;
}

// ScalarSuite.hash_to_g2: sha3(canonical(b"h2g2", data)) % (r-1) + 1.
// One (often very long — the DKG ciphertext digest) message: the sha3
// plane's single-message path, counted but never lane-parallel.
inline U256 hash_to_g2(const Bytes& data) {
  Bytes buf = canon2("h2g2", data);
  uint8_t digest[32];
  hbs::sha3_256_one((const uint8_t*)buf.data(), buf.size(), digest);
  U256 v = u256_from_be(digest, 32);
  // v mod (r-1): v < 2^256 < 3(r-1), so at most two subtractions.
  while (u256_cmp(v, R_MINUS_1) >= 0) {
    U256 t;
    u256_sub_raw(v, R_MINUS_1, t);
    v = t;
  }
  return addmod(v, {{1, 0, 0, 0}});  // +1, still < r
}

// Signature.parity(): sha3(sig 32B BE)[0] & 1
inline bool sig_parity(const U256& sig) {
  uint8_t be[32], digest[32];
  u256_to_be32(sig, be);
  hbn::sha3_256(be, 32, digest);
  return digest[0] & 1;
}

// kdf_stream(seed, n): sha3(seed || ctr 8B BE) blocks.  The blocks are
// independent equal-length messages, so the whole stream is one sha3
// plane batch: the counter messages are staged contiguously and the
// digests land directly in the output layout (32 bytes per block).
// Stream bytes are identical to the old per-block loop — same messages,
// same digests, same order.
inline Bytes kdf_stream(const Bytes& seed, size_t n) {
  size_t nblocks = (n + 31) / 32;
  if (!nblocks) return Bytes();
  size_t msg_len = seed.size() + 8;
  std::vector<uint8_t> stage(nblocks * msg_len);
  for (size_t ctr = 0; ctr < nblocks; ++ctr) {
    uint8_t* m = stage.data() + ctr * msg_len;
    std::memcpy(m, seed.data(), seed.size());
    for (int i = 0; i < 8; ++i)
      m[seed.size() + i] = (uint8_t)((uint64_t)ctr >> (56 - 8 * i));
  }
  Bytes out;
  out.resize(nblocks * 32);
  hbs::sha3_256_batch(stage.data(), msg_len, nblocks, (uint8_t*)&out[0]);
  out.resize(n);
  return out;
}

// Lagrange coefficients at 0 for x_i = i+1 over the given indices
// (mirrors hbbft_tpu/crypto/poly.py lagrange_coefficients).  Cached by
// index set: every node combining the same (FIFO-typical) first-t+1
// index set otherwise pays the modular inverse + O(k^2) mulmods again —
// the single hottest share of the N=64 era-change combines.
inline std::shared_ptr<const std::vector<U256>> lagrange_cached(
    const std::vector<int>& idxs);

inline std::vector<U256> lagrange(const std::vector<int>& idxs) {
  // Round 15: the whole computation runs in the Montgomery domain
  // (field_plane.h) — one REDC per product instead of the classic
  // two — and the O(k^2) denominator half goes through the dispatched
  // batch kernel (8-lane IFMA when available).  Outputs are the exact
  // canonical coefficients the classic form produced (the domain map
  // x -> x*2^256 is a ring isomorphism; every value converts back at
  // the boundary), so lagrange_cached entries stay arm-independent.
  size_t k = idxs.size();
  std::vector<U256> coeffs(k);
  std::vector<int64_t> xs64(k);
  for (size_t i = 0; i < k; ++i) xs64[i] = idxs[i] + 1;
  std::vector<U256> dens(k);
  hbf::lagrange_dens(xs64.data(), k, dens.empty() ? nullptr : dens[0].w);
  // nums via prefix/suffix products: num_i = Π_{j!=i} x_j in O(k)
  // (the old per-i inner loop was half the O(k^2) mulmods of a miss —
  // at t+1 = 100 a cache miss was ~2.7M cycles, round-7 combine
  // profile).
  std::vector<U256> xs_m(k), nums_m(k);
  for (size_t i = 0; i < k; ++i) {
    U256 x = {{(uint64_t)xs64[i], 0, 0, 0}};
    xs_m[i] = to_mont(x);
  }
  {
    std::vector<U256> pre(k + 1), suf(k + 1);
    pre[0] = ONE_MONT;
    suf[k] = ONE_MONT;
    for (size_t i = 0; i < k; ++i) pre[i + 1] = mont_mul(pre[i], xs_m[i]);
    for (size_t i = k; i-- > 0;) suf[i] = mont_mul(suf[i + 1], xs_m[i]);
    for (size_t i = 0; i < k; ++i) nums_m[i] = mont_mul(pre[i], suf[i + 1]);
  }
  // batch inversion (one Fermat ladder for every denominator)
  std::vector<U256> dens_m(k), prefix(k + 1);
  for (size_t i = 0; i < k; ++i) dens_m[i] = to_mont(dens[i]);
  prefix[0] = ONE_MONT;
  for (size_t i = 0; i < k; ++i) prefix[i + 1] = mont_mul(prefix[i], dens_m[i]);
  U256 inv_acc;
  hbf::mont_inv4(prefix[k].w, inv_acc.w);
  for (size_t i = k; i-- > 0;) {
    U256 d_inv = mont_mul(inv_acc, prefix[i]);
    inv_acc = mont_mul(inv_acc, dens_m[i]);
    coeffs[i] = from_mont(mont_mul(nums_m[i], d_inv));
  }
  return coeffs;
}

inline std::shared_ptr<const std::vector<U256>> lagrange_cached(
    const std::vector<int>& idxs) {
  // Returns a shared_ptr under a mutex: multicore workers share this
  // cache, and a raw reference could be invalidated by a concurrent
  // eviction.  The round-6 by-value form closed that hole with a full
  // t+1-scalar copy per COMBINE (~3 KB alloc+copy on the per-epoch
  // coin path — measurable in the round-7 combine profile); the
  // shared_ptr keeps eviction-safety without the copy.
  static std::mutex mu;
  static std::map<std::vector<int>,
                  std::shared_ptr<const std::vector<U256>>> cache;
  static std::deque<std::vector<int>> order;
  std::lock_guard<std::mutex> lk(mu);
  auto it = cache.find(idxs);
  if (it == cache.end()) {
    if (cache.size() > 4096) {
      cache.erase(order.front());
      order.pop_front();
    }
    it = cache.emplace(
               idxs,
               std::make_shared<const std::vector<U256>>(lagrange(idxs)))
             .first;
    order.push_back(idxs);
  }
  return it->second;
}

// ===========================================================================
// Minimal serde decode for a scalar-suite Ciphertext
// (mirrors hbbft_tpu/utils/serde.py + wire.py for the "ct" struct ONLY)
// ===========================================================================

struct ScalarCiphertext {
  U256 u, w;
  Bytes v;
};

struct SerdeReader {
  const uint8_t* data;
  size_t len, pos = 0;
  bool fail = false;
  uint8_t u8() {
    if (pos + 1 > len) {
      fail = true;
      return 0;
    }
    return data[pos++];
  }
  uint32_t u32() {
    if (pos + 4 > len) {
      fail = true;
      return 0;
    }
    uint32_t v = ((uint32_t)data[pos] << 24) | ((uint32_t)data[pos + 1] << 16) |
                 ((uint32_t)data[pos + 2] << 8) | data[pos + 3];
    pos += 4;
    return v;
  }
  const uint8_t* take(size_t n) {
    if (pos + n > len) {
      fail = true;
      return nullptr;
    }
    const uint8_t* p = data + pos;
    pos += n;
    return p;
  }
};

const char kScalarSuiteName[] = "scalar-insecure";

inline bool read_group_scalar(SerdeReader& r, U256& out) {
  if (r.u8() != 0x11) return false;  // GROUP tag
  uint8_t nlen = r.u8();
  const uint8_t* name = r.take(nlen);
  if (r.fail || nlen != sizeof(kScalarSuiteName) - 1 ||
      std::memcmp(name, kScalarSuiteName, nlen) != 0)
    return false;
  uint8_t group = r.u8();
  if (group != 1 && group != 2) return false;
  uint32_t plen = r.u32();
  const uint8_t* payload = r.take(plen);
  if (r.fail || plen != 32) return false;
  out = u256_from_be(payload, 32);
  return u256_cmp(out, R_MOD) < 0;
}

// Full-strictness parse of serde.dumps(Ciphertext(u, v, w, ScalarSuite())).
inline bool decode_scalar_ciphertext(const uint8_t* data, size_t len,
                                     ScalarCiphertext& out) {
  SerdeReader r{data, len};
  if (r.u8() != 0x10) return false;  // STRUCT
  uint8_t nlen = r.u8();
  const uint8_t* name = r.take(nlen);
  if (r.fail || nlen != 2 || std::memcmp(name, "ct", 2) != 0) return false;
  if (r.u8() != 0x06) return false;  // fields tuple
  if (r.u32() != 4) return false;
  // field 0: suite name string
  if (r.u8() != 0x05) return false;
  uint32_t slen = r.u32();
  const uint8_t* sname = r.take(slen);
  if (r.fail || slen != sizeof(kScalarSuiteName) - 1 ||
      std::memcmp(sname, kScalarSuiteName, slen) != 0)
    return false;
  if (!read_group_scalar(r, out.u)) return false;  // field 1: u
  if (r.u8() != 0x04) return false;                // field 2: v bytes
  uint32_t vlen = r.u32();
  const uint8_t* v = r.take(vlen);
  if (r.fail) return false;
  out.v.assign((const char*)v, vlen);
  if (!read_group_scalar(r, out.w)) return false;  // field 3: w
  return !r.fail && r.pos == r.len;
}

// Ciphertext hash input: canonical(b"ct", u.to_bytes(), v)   [keys.py]
inline U256 ct_hash_scalar(const ScalarCiphertext& ct) {
  uint8_t u_be[32];
  u256_to_be32(ct.u, u_be);
  Bytes buf;
  canon_append(buf, "ct");
  canon_append(buf, Bytes((const char*)u_be, 32));
  canon_append(buf, ct.v);
  return hash_to_g2(buf);
}

// ===========================================================================
// Messages, routing, faults
// ===========================================================================

// Fixed-width POD node bitset.  The word count is a COMPILE-TIME
// parameter: the Python loader builds one shared library per width
// (libhbbft_engine_w{4,8,16,...}.so, -DHBE_WORDS=N) and picks the
// smallest that fits the network, so the common <= 256-node range keeps
// the 4-word set's exact cost (a heap-spill variant measured ~30%
// slower on the N=32 era change — NodeSet is copied in every hot
// threshold path) while larger networks get wider sets instead of a
// hard cap (round-3 VERDICT item #4).
#ifndef HBE_WORDS
#define HBE_WORDS 4
#endif

const int MAX_NODES = 64 * HBE_WORDS;

struct NodeSet {
  uint64_t w[HBE_WORDS] = {};
  void add(int i) { w[i >> 6] |= 1ULL << (i & 63); }
  void clear(int i) { w[i >> 6] &= ~(1ULL << (i & 63)); }
  bool has(int i) const { return (w[i >> 6] >> (i & 63)) & 1; }
  int count() const {
    int c = 0;
    for (int i = 0; i < HBE_WORDS; ++i) c += __builtin_popcountll(w[i]);
    return c;
  }
  NodeSet operator|(const NodeSet& o) const {
    NodeSet r;
    for (int i = 0; i < HBE_WORDS; ++i) r.w[i] = w[i] | o.w[i];
    return r;
  }
};

using Root = std::array<uint8_t, 32>;

struct ProofData {
  Bytes value;
  int index;
  std::vector<Root> path;
  Root root;
  // Validation memo: proofs are SHARED (one object rides the queue to
  // every destination and is re-forwarded by echos), and validity is a
  // pure function of (object, n_leaves) — so the whole network pays
  // the Merkle hashing once instead of N times.
  mutable int8_t valid_memo = -1;  // -1 unknown, else verdict
  mutable int valid_n = 0;         // n_leaves the memo was computed for
};

enum MsgType : uint8_t {
  BC_VALUE,
  BC_ECHO,
  BC_READY,
  BC_ECHO_HASH,
  BC_CAN_DECODE,
  BA_BVAL,
  BA_AUX,
  BA_CONF,
  BA_COIN,
  BA_TERM,
  HB_DECRYPT,
};

// Flattened envelope: the engine knows the whole stack, so one struct
// replaces DhbMessage(HbMessage(SubsetMessage(AbaMessage(...)))).
struct EMsg {
  int32_t era = 0;
  int32_t epoch = 0;
  int32_t proposer = 0;  // subset proposer / decrypt proposer
  int32_t round = 0;     // BA round
  MsgType type = BA_BVAL;
  uint8_t bval = 0;  // bool for BVAL/AUX/TERM; BoolSet mask for CONF
  U256 share = U256_ZERO;  // BA_COIN sig share / HB_DECRYPT share (scalar mode)
  std::shared_ptr<const Bytes> share_b;  // same, external-crypto mode (opaque)
  std::shared_ptr<const ProofData> proof;  // BC_VALUE / BC_ECHO
  Root root{};                             // BC_READY / ECHO_HASH / CAN_DECODE
};

// One queue entry per (sender, dest); broadcasts share ONE EMsg across
// all destinations (N-1 copies of a ~112-byte struct with two
// refcounted pointers otherwise dominate queue memory and copy time at
// large N — the N=300 startup flood alone queues ~10M items).
struct QItem {
  int32_t sender, dest;
  std::shared_ptr<const EMsg> msg;
};

// Fault kinds — identical strings to the Python modules.
const char* F_SBV_DUP_BVAL = "sbv:duplicate-bval";
const char* F_SBV_DUP_AUX = "sbv:duplicate-aux";
const char* F_BA_DUP_CONF = "binary_agreement:duplicate-conf";
const char* F_BA_DUP_TERM = "binary_agreement:duplicate-term";
const char* F_TS_INVALID = "threshold_sign:invalid-share";
const char* F_TS_NONVAL = "threshold_sign:non-validator";
const char* F_TS_DUP = "threshold_sign:duplicate-share";
const char* F_TD_INVALID = "threshold_decrypt:invalid-share";
const char* F_TD_NONVAL = "threshold_decrypt:non-validator";
const char* F_TD_DUP = "threshold_decrypt:duplicate-share";
const char* F_BC_INVALID_PROOF = "broadcast:invalid-proof";
const char* F_BC_WRONG_INDEX = "broadcast:wrong-shard-index";
const char* F_BC_NOT_PROPOSER = "broadcast:value-from-non-proposer";
const char* F_BC_MULTI_VALUE = "broadcast:multiple-values";
const char* F_BC_DUP = "broadcast:duplicate-message";
const char* F_BC_BAD_ENC = "broadcast:root-mismatch-after-decode";
const char* F_HB_FUTURE = "honey_badger:message-beyond-max-future-epochs";
const char* F_HB_FLOOD = "honey_badger:future-epoch-flood";
const char* F_HB_BAD_CT = "honey_badger:invalid-ciphertext";
const char* F_HB_BAD_CONTRIB = "honey_badger:undecodable-contribution";
const char* F_DHB_FUTURE_ERA = "dynamic_honey_badger:message-beyond-next-era";
const char* F_SS_UNKNOWN = "subset:unknown-proposer";

struct Fault {
  int32_t subject;
  const char* kind;
};

// ===========================================================================
// Forward decls + engine-level context
// ===========================================================================

struct Node;
struct Engine;

// sorted-by-str(id) order for batch contribution tuples
// (honey_badger._try_batch sorts by str(proposer)).
inline std::vector<int> str_sorted(std::vector<int> ids) {
  std::sort(ids.begin(), ids.end(), [](int a, int b) {
    return std::to_string(a) < std::to_string(b);
  });
  return ids;
}

// ===========================================================================
// Epoch-state arena (ISSUE 17)
//
// Per-epoch protocol state used to live in std::maps (echo/ready/share
// maps, future-message counters): at N=300 the slot-13 epoch-advance
// stamp measured ~20 Gcyc/epoch of rb-tree teardown + reallocation, and
// the delivery envelope at big N is dominated by dependent cache misses
// chasing freshly allocated rb-tree nodes.  Every one of those maps is
// keyed by an engine node id in [0, e.n) — so they become flat,
// index-keyed arrays (FlatMap) carved from a per-NODE bump arena that
// is recycled WHOLESALE at epoch advance: reset_for_epoch becomes a
// watermark reset instead of an exhaustive per-container destructor
// walk, and a whole epoch's lookups walk a handful of contiguous,
// epoch-hot blocks.
//
// Identity argument (docs/INVARIANTS.md "epoch-state arena"): a
// std::map<int, T> with keys restricted to [0, n) iterates in ascending
// key order; a FlatMap iterates present indices 0..n-1 ascending — the
// same sequence — and find/insert semantics are one-to-one, so every
// converted container preserves the Python dict/Counter iteration
// behavior the maps encoded.  HBBFT_TPU_ARENA=0 (read at hbe_create)
// keeps the same flat containers but FREES the blocks at every reset
// instead of recycling them — a one-build A/B arm for the recycling
// itself, byte-identical by construction.
//
// Lifetime rule: arena memory lives exactly one epoch.  Anything that
// can outlive the epoch (Ts/Td continuations in Pending, batch
// payloads, ProofData pinned by shared_ptr) stays on the normal heap;
// FlatMap may only hold trivially-destructible values.  Under ASan the
// recycled blocks are poisoned between epochs, so any cross-epoch read
// through a stale pointer is a hard fault, not silent state bleed.
// ===========================================================================

#if defined(__SANITIZE_ADDRESS__)
#define HBE_ARENA_ASAN 1
#elif defined(__has_feature)
#if __has_feature(address_sanitizer)
#define HBE_ARENA_ASAN 1
#endif
#endif
#ifdef HBE_ARENA_ASAN
#include <sanitizer/asan_interface.h>
#define HBE_ARENA_POISON(p, s) ASAN_POISON_MEMORY_REGION((p), (s))
#define HBE_ARENA_UNPOISON(p, s) ASAN_UNPOISON_MEMORY_REGION((p), (s))
#else
#define HBE_ARENA_POISON(p, s) ((void)0)
#define HBE_ARENA_UNPOISON(p, s) ((void)0)
#endif

struct EpochArena {
  struct Block {
    uint8_t* p;
    size_t cap;
  };
  static const size_t BLOCK = 64 * 1024;
  std::vector<Block> blocks;
  size_t cur = 0;        // active block index
  size_t off = 0;        // bump offset within the active block
  size_t used = 0;       // bytes handed out since the last reset
  size_t hwm = 0;        // max `used` over all epochs (hbe_arena_stats)
  uint64_t resets = 0;

  EpochArena() = default;
  EpochArena(const EpochArena&) = delete;
  EpochArena& operator=(const EpochArena&) = delete;
  EpochArena(EpochArena&& o) noexcept { *this = std::move(o); }
  EpochArena& operator=(EpochArena&& o) noexcept {
    release();
    blocks = std::move(o.blocks);
    cur = o.cur;
    off = o.off;
    used = o.used;
    hwm = o.hwm;
    resets = o.resets;
    o.blocks.clear();
    o.cur = o.off = o.used = 0;
    return *this;
  }
  ~EpochArena() { release(); }

  uint8_t* alloc(size_t sz) {
    sz = (sz + 15) & ~(size_t)15;
    while (true) {
      if (cur < blocks.size()) {
        Block& b = blocks[cur];
        if (off + sz <= b.cap) {
          uint8_t* p = b.p + off;
          off += sz;
          used += sz;
          HBE_ARENA_UNPOISON(p, sz);
          return p;
        }
        // Advance past this block (its tail stays unused this epoch;
        // `used` counts handed-out bytes, so the watermark is honest).
        ++cur;
        off = 0;
        continue;
      }
      size_t cap = sz > BLOCK ? sz : BLOCK;
      blocks.push_back({(uint8_t*)::malloc(cap), cap});
    }
  }

  // Epoch boundary: one watermark reset.  recycle=1 keeps the blocks
  // (poisoned under ASan); recycle=0 is the HBBFT_TPU_ARENA=0 A/B arm
  // (same containers, malloc-fresh blocks every epoch).
  void reset(bool recycle) {
    ++resets;
    if (used > hwm) hwm = used;
    if (recycle) {
      for (Block& b : blocks) HBE_ARENA_POISON(b.p, b.cap);
    } else {
      release();
    }
    cur = 0;
    off = 0;
    used = 0;
  }

  void release() {
    for (Block& b : blocks) {
      HBE_ARENA_UNPOISON(b.p, b.cap);
      ::free(b.p);
    }
    blocks.clear();
  }
};

// Flat replacement for the per-epoch std::map<int, T> (keys are engine
// node ids in [0, n)): a value array + presence bitmap carved lazily
// from the epoch arena on first insert.  Ascending-index iteration ==
// the map's ascending-key iteration (see the arena identity argument).
// Values must be trivially destructible — the arena reset never runs
// destructors (shared_ptr ownership lives elsewhere, e.g. the per-node
// epoch_pins vector for ProofData).
template <typename T>
struct FlatMap {
  static_assert(std::is_trivially_destructible<T>::value,
                "arena-backed: reset runs no destructors");
  T* v = nullptr;
  uint64_t* present = nullptr;
  int32_t cap = 0;
  int32_t count = 0;

  bool has(int k) const {
    return v && ((present[(unsigned)k >> 6] >> ((unsigned)k & 63)) & 1);
  }
  T* find(int k) { return has(k) ? v + k : nullptr; }
  const T* find(int k) const { return has(k) ? v + k : nullptr; }
  bool empty() const { return count == 0; }
  // operator[]-style access: carve on first touch, value-initialize on
  // a fresh key (matching std::map's operator[]), return the slot.
  T& ref(EpochArena& a, int n, int k) {
    if (!v) {
      size_t words = ((size_t)n + 63) / 64;
      v = (T*)a.alloc(sizeof(T) * (size_t)n);
      present = (uint64_t*)a.alloc(8 * words);
      std::memset(present, 0, 8 * words);
      cap = n;
    }
    uint64_t& w = present[(unsigned)k >> 6];
    uint64_t bit = 1ULL << ((unsigned)k & 63);
    if (!(w & bit)) {
      w |= bit;
      v[k] = T();
      ++count;
    }
    return v[k];
  }
  // Mid-epoch clear (keeps the carve; e.g. ba_next_round's
  // future_count): presence bits only — values are re-initialized on
  // the next ref() of each key.
  void clear() {
    if (v) std::memset(present, 0, 8 * (((size_t)cap + 63) / 64));
    count = 0;
  }
  // Epoch reset: forget the carve — the arena watermark reclaims the
  // memory wholesale (this is the whole point: no per-field teardown).
  void drop() {
    v = nullptr;
    present = nullptr;
    cap = 0;
    count = 0;
  }
};

// ===========================================================================
// SBV broadcast (sbv_broadcast.py)
// ===========================================================================

struct Sbv {
  int n = 0, f = 0;
  NodeSet bval_received[2], aux_received[2];
  NodeSet termed_bval[2], termed_aux[2];
  bool bval_sent[2] = {false, false};
  bool aux_sent = false;
  uint8_t bin_values = 0;  // BoolSet mask: 1 = False present, 2 = True
  int last_output = -1;    // -1 = none yet, else BoolSet mask

  Sbv() = default;
  Sbv(int n_, int f_) : n(n_), f(f_) {}
};

// ===========================================================================
// ThresholdSign (threshold_sign.py) — scalar suite
// ===========================================================================

struct Ts {
  U256 doc_h;  // hash_to_g2(doc) (scalar mode)
  // Open RLC group cursor (scalar deferred mode): pool index of this
  // instance's leader Pending, valid iff grp_round == Node::pool_round
  // (each flush swap-round opens fresh groups).  Ts/Td are PER-NODE
  // objects, so these fields are worker-local under engine_run_mt.
  uint64_t grp_round = 0;
  int32_t grp_idx = -1;
  Bytes doc;   // the signed document (external-crypto mode: hashed Python-side)
  NodeSet seen;
  std::vector<std::pair<int, U256>> verified;  // insertion order (scalar)
  std::vector<std::pair<int, Bytes>> verified_b;  // same, external mode
  NodeSet verified_set;
  bool had_input = false;
  bool terminated = false;
  U256 signature = U256_ZERO;
};

// ===========================================================================
// ThresholdDecrypt (threshold_decrypt.py) — scalar suite
// ===========================================================================

struct Td {
  // Open RLC group cursor — see Ts::grp_round.
  uint64_t grp_round = 0;
  int32_t grp_idx = -1;
  bool has_ct = false;
  ScalarCiphertext ct;
  U256 ct_h = U256_ZERO;  // hash_to_g2 of ct hash input
  BytesP ct_payload;      // serde(Ciphertext) bytes (external-crypto mode)
  bool ct_valid = false;
  bool ciphertext_invalid = false;
  std::vector<std::pair<int, U256>> buffered;  // arrival order (scalar)
  std::vector<std::pair<int, U256>> verified;
  std::vector<std::pair<int, Bytes>> buffered_b;  // same, external mode
  std::vector<std::pair<int, Bytes>> verified_b;
  NodeSet verified_set;
  NodeSet seen;
  bool terminated = false;
  BytesP plaintext;
};

// ===========================================================================
// Broadcast (broadcast.py)
// ===========================================================================

struct Bcast {
  int proposer = -1;     // lint: not-reset (per-proposer config, assigned in hb_reset_state)
  int data_shards = 0;   // lint: not-reset (per-proposer config, assigned in hb_reset_state)
  // echos / echo_hashes / readys / can_decode: arena-backed flat maps
  // keyed by sender id (ISSUE 17; ascending-index iteration preserves
  // the old std::map ascending-key order everywhere these are walked).
  // echos holds raw ProofData pointers — ownership is pinned for the
  // epoch by Node::epoch_pins (arena values must stay trivially
  // destructible).
  FlatMap<const ProofData*> echos;
  FlatMap<Root> echo_hashes;
  FlatMap<Root> readys;
  std::vector<Root> ready_root_order;  // first-seen order of distinct roots
  FlatMap<Root> can_decode;
  // Incremental per-root tallies (distinct roots stay O(1) in honest
  // runs).  The maps above were walked on EVERY echo/ready delivery to
  // recount — an O(N) rb-tree + ProofData pointer chase per message,
  // O(N^3) network-wide, and the profiled bound past N=256.  Counts
  // are derived data only; map/iteration semantics are unchanged.
  std::vector<std::pair<Root, int>> echo_full_by_root;  // full-proof echos
  std::vector<std::pair<Root, int>> echo_any_by_root;   // echos + echo_hashes
  std::vector<std::pair<Root, int>> ready_by_root;

  static int bump(std::vector<std::pair<Root, int>>& v, const Root& r) {
    for (auto& kv : v)
      if (kv.first == r) return ++kv.second;
    v.push_back({r, 1});
    return 1;
  }
  static int tally(const std::vector<std::pair<Root, int>>& v, const Root& r) {
    for (auto& kv : v)
      if (kv.first == r) return kv.second;
    return 0;
  }
  bool can_decode_sent = false;
  bool echo_sent = false;
  bool ready_sent = false;
  bool had_input = false;
  bool terminated = false;
  BytesP value;
};

// ===========================================================================
// BinaryAgreement (binary_agreement.py)
// ===========================================================================

const int MAX_FUTURE_ROUNDS = 100;

struct Ba {
  Bytes session_id;  // lint: not-reset (per-epoch config, assigned in hb_reset_state)
  int round = 0;
  // Round-5 arena note: Sbv lives INLINE (value member) and Proposal
  // holds Bcast/Ba inline below, so one epoch's per-proposer protocol
  // state is a single contiguous proposals array instead of ~4 heap
  // objects per proposer — the COIN/DECRYPT delivery envelope was
  // measured mostly cache misses chasing that pointer web (BASELINE.md
  // round 4).  Ts/Td stay shared_ptr: they escape into Pending, whose
  // continuations can outlive the epoch (commit_events may destroy the
  // EpochState mid-drain).
  Sbv sbv;
  bool conf_sent = false;
  std::vector<std::pair<int, uint8_t>> confs;  // (sender, BoolSet) insertion order
  NodeSet confs_set;
  NodeSet term_confs;
  std::shared_ptr<Ts> coin;
  bool coin_requested = false;
  int coin_value = -1;   // -1 unknown
  int conf_vals = -1;    // -1 unknown, else BoolSet mask
  int estimate = -1;     // -1 unset
  NodeSet terms[2];
  NodeSet term_senders;
  std::vector<std::pair<int, EMsg>> future;
  FlatMap<int32_t> future_count;  // per-sender future-buffer occupancy
  int decision = -1;
  bool terminated = false;
};

// ===========================================================================
// Subset (subset.py) + HB epoch state (honey_badger.py)
// ===========================================================================

struct Proposal {
  Bcast bc;
  Ba ba;
  BytesP value;
  int decision = -1;  // -1 undecided
  bool emitted = false;

  // Reset-in-place for epoch-state reuse (round 5): the whole
  // per-epoch protocol state is recycled instead of reallocated, so
  // the proposals array (and its inner container capacities where the
  // container keeps them) stays resident — the delivery envelope at
  // big N is dominated by dependent cache misses chasing freshly
  // allocated state (BASELINE.md round-4/5 profiles).  EVERY field of
  // Bcast/Ba/Sbv/Proposal must be restored here; a missed field is
  // cross-epoch contamination (the native equivalence suites pin this
  // byte-for-byte against the Python net).  Arena-backed FlatMap
  // fields are restored with .drop() — their storage is reclaimed by
  // the single arena watermark reset in hb_reset_state (ISSUE 17).
  void reset() {
    bc.echos.drop();
    bc.echo_hashes.drop();
    bc.readys.drop();
    bc.ready_root_order.clear();
    bc.can_decode.drop();
    bc.echo_full_by_root.clear();
    bc.echo_any_by_root.clear();
    bc.ready_by_root.clear();
    bc.can_decode_sent = bc.echo_sent = bc.ready_sent = false;
    bc.had_input = bc.terminated = false;
    bc.value = nullptr;
    ba.round = 0;
    ba.sbv = Sbv();
    ba.conf_sent = false;
    ba.confs.clear();
    ba.confs_set = NodeSet();
    ba.term_confs = NodeSet();
    ba.coin = nullptr;
    ba.coin_requested = false;
    ba.coin_value = -1;
    ba.conf_vals = -1;
    ba.estimate = -1;
    ba.terms[0] = NodeSet();
    ba.terms[1] = NodeSet();
    ba.term_senders = NodeSet();
    ba.future.clear();
    ba.future_count.drop();
    ba.decision = -1;
    ba.terminated = false;
    value = nullptr;
    decision = -1;
    emitted = false;
  }
};

// A Subset output awaiting the honey-badger boundary (Python: outputs
// accumulate in the Step until _on_subset_step processes them).
struct SubsetOutItem {
  bool done;
  int proposer;
  BytesP value;
};

struct EpochState {
  int epoch = 0;          // lint: not-reset (advanced by hb_reset_state's caller)
  bool encrypted = false; // lint: not-reset (recomputed per epoch in hb_reset_state)
  Bytes subset_session;   // lint: not-reset (recomputed per epoch in hb_reset_state)
  // lint: not-reset (each element reset via Proposal::reset in hb_reset_state)
  std::vector<Proposal> proposals;  // indexed by proposer id
  bool subset_done = false;
  bool done_emitted = false;
  bool subset_terminated = false;
  // Flat by proposer id, presence = non-null (ISSUE 17: flat iteration
  // 0..n-1 yields the same key set the maps did).  NOT arena-backed:
  // Td escapes into Pending continuations that can outlive the epoch,
  // and shared_ptr/BytesP need destructors the arena never runs —
  // these vectors are sized once in hb_reset_state and nulled per
  // epoch (a pointer sweep, not an rb-tree teardown).
  std::vector<std::shared_ptr<Td>> decrypts;
  std::vector<int> accepted_order;  // proposer ids in acceptance order
  std::vector<BytesP> plaintexts;  // proposer -> decoded-ok plaintext marker
  NodeSet decrypted;
  NodeSet faulty_proposers;
  bool proposed = false;
  bool batch_emitted = false;
  std::vector<SubsetOutItem> pending_outputs;
  std::vector<std::pair<int, BytesP>> pending_payloads;  // all_at_end buffer

  // Epoch-advance reset (see Proposal::reset): same fresh-state
  // semantics as reallocating, but the object and its proposals array
  // stay in place.
  void reset_for_epoch() {
    subset_done = done_emitted = subset_terminated = false;
    for (auto& d : decrypts) d = nullptr;
    accepted_order.clear();
    for (auto& p : plaintexts) p = nullptr;
    decrypted = NodeSet();
    faulty_proposers = NodeSet();
    proposed = batch_emitted = false;
    pending_outputs.clear();
    pending_payloads.clear();
  }
};

struct BatchData {
  int era, epoch;
  std::vector<std::pair<int, BytesP>> contributions;  // str-sorted
};

const int FUTURE_BUFFER_FACTOR = 64;

struct Hb {
  Bytes session_id;  // canonical(dhb_session, era) — provided by Python
  int epoch = 0;
  int max_future_epochs = 3;
  // EncryptionSchedule: kind 0 always, 1 never, 2 every_nth, 3 tick_tock
  int sched_kind = 0;
  int sched_n = 1;
  // SubsetHandlingStrategy: 0 incremental, 1 all_at_end
  int subset_handling = 0;
  // INLINE and recycled (round 5): Node.hb and Hb.state used to be two
  // heap hops in front of every delivery's state access — two dependent
  // cache misses per message at big N, the measured bulk of the
  // COIN-continuation envelope.
  EpochState state;
  // Future-epoch buffer as a ring of max_future_epochs+1 vectors
  // indexed epoch % size (ISSUE 17; sized in hb_reset_state).  Safe
  // because the insertion window is (epoch, epoch+max_future_epochs]
  // — fewer epochs than slots, all distinct mod size — and hb_advance
  // drains each slot exactly when the cursor reaches its epoch, so a
  // slot never mixes two epochs' messages.
  std::vector<std::vector<std::pair<int, EMsg>>> future;
  // Per-sender future-buffer occupancy, flat by sender id (absent ==
  // 0 under the old map semantics).  Survives epochs within an era
  // (decremented on replay); fresh per era via `nd.hb = Hb()`.
  std::vector<int32_t> future_per_sender;

  bool encrypt_on(int e) const {
    switch (sched_kind) {
      case 0: return true;
      case 1: return false;
      case 2: return e % sched_n == 0;
      default: return (e / sched_n) % 2 == 0;
    }
  }
};

// ===========================================================================
// Node + Engine
// ===========================================================================

// One deferred verification (crypto.backend.VerifyRequest kinds).
// External-crypto mode: the verdict comes from the Python verify-batch
// callback at flush; scalar mode precomputes it at submission.
enum VKind : uint8_t { VK_SIG = 0, VK_DEC = 1, VK_CT = 2 };

struct VReq {
  uint8_t kind = VK_SIG;
  int32_t era = 0;
  int32_t sender = -1;             // share sender (engine id); -1 for VK_CT
  const Bytes* doc = nullptr;      // VK_SIG: signed document (owned by Ts,
                                   // kept alive by the continuation's ref)
  const Bytes* ct = nullptr;       // VK_DEC/VK_CT: serde ciphertext payload
                                   // (owned by Td, kept alive likewise)
  std::shared_ptr<const Bytes> share;  // VK_SIG/VK_DEC: wire share bytes
};

// One share of a submit-time RLC group (round 7, scalar deferred
// mode): the leader Pending of a Ts/Td instance holds ALL of that
// instance's shares for the current flush round as a CONTIGUOUS array
// — the flush verifies and folds them with streaming reads instead of
// sweeping one 200+-byte Pending per share through a cold pool (the
// N=300 first-cut regression: the per-share round-trip's DRAM misses
// cost more than the mulmods the RLC removed).
struct RlcShare {
  U256 share;
  U256 pk;  // submit-time snapshot (Pending::pk note applies)
  int32_t sender;
  uint8_t ok;  // verdict, written by the flush's group check
};

// Flat continuation (round 4): COIN/DECRYPT deliveries dominated the
// full-epoch cycle profile (~2.4k cycles each vs ~400 for BVAL/AUX),
// largely the std::function continuation each pool entry heap-allocated
// with ~9 captures.  A tagged struct + switch dispatch (pending_run)
// keeps the same three continuation targets without the allocation.
enum ContKind : uint8_t { CONT_TS = 0, CONT_TD_CT = 1, CONT_TD_SHARE = 2 };

struct Pending {
  bool need_verdict = false;  // true: external mode, verdict from flush cb
  bool pre_ok = false;        // scalar mode: verdict computed at submit
  bool rlc_defer = false;     // scalar RLC mode: verdict computed by the
                              // flush's group pass (scalar_rlc_verdicts)
  uint8_t cont = CONT_TS;
  int32_t era = 0, epoch = 0, proposer = 0, rnd = 0, sender = -1;
  VReq req;
  std::shared_ptr<Ts> ts;    // CONT_TS (keeps req.doc alive)
  std::shared_ptr<Td> td;    // CONT_TD_* (keeps req.ct alive)
  U256 share = U256_ZERO;    // scalar-mode share
  U256 pk = U256_ZERO;       // scalar RLC mode: sender's pk share,
                             // SNAPSHOTTED at submit — an era restart
                             // (batch cb) can replace node.pk_shares
                             // before a deferred verdict runs, and the
                             // verdict must use the submitting era's key
                             // exactly like the old submit-time check
  std::shared_ptr<const Bytes> share_b;  // ext-mode share
  std::vector<RlcShare> grp;  // scalar deferred mode: the instance's
                              // shares this flush round (leader only)
};

const int FUTURE_ERA_BUFFER = 4096;

struct Node {
  int id;
  bool silent = false;   // crash-faulty / adversary-owned: consumes, never acts
  bool tampered = false; // Byzantine: runs the real algorithm, but every
                         // outgoing message is offered to the tamper
                         // callback (net/adversary.py TamperingAdversary)
  bool has_share = false;
  U256 sk_share = U256_ZERO;              // threshold share (scalar)
  std::vector<U256> pk_shares;            // commitment eval, BY ENGINE ID
  // Era validator set (NetworkInfo): sorted ids, id -> index (or -1).
  std::vector<int> val_ids;
  std::vector<int> val_index;
  int era_n = 0, era_f = 0;
  int era = 0;
  Hb hb;                // inline (see Hb.state note); valid iff hb_init
  bool hb_init = false;
  // Per-epoch bump arena backing the FlatMap state above (ISSUE 17):
  // ONE watermark reset per epoch advance (hb_reset_state) replaces
  // the per-container teardown walk.  epoch_pins owns the ProofData
  // objects whose raw pointers live in Bcast::echos for the epoch.
  EpochArena arena;
  std::vector<std::shared_ptr<const ProofData>> epoch_pins;
  std::vector<Pending> pool;
  bool pool_dirty = false;  // queued in Engine::dirty_nodes (deferred mode)
  uint64_t pool_round = 1;  // bumped per flush swap-round (Ts::grp_round)
  std::vector<Pending> flush_scratch;  // engine_flush_pool drain buffer
  bool flushing = false;               // reentrancy guard for the scratch
  int suppress_emit = 0;  // scoped stale-callback guard (per node: the
                          // windows open and close within one delivery,
                          // so this is worker-local in multicore mode)
  std::vector<Fault> faults;
  std::vector<std::pair<int, EMsg>> next_era_buffer;
  std::vector<BatchData> pending_batches;
  uint64_t handled = 0;
};

typedef void (*BatchEventCb)(int32_t node, int32_t era, int32_t epoch);
typedef int32_t (*ContribCb)(int32_t node, int32_t era, int32_t epoch,
                             int32_t proposer, const uint8_t* data,
                             uint64_t len);
// External-crypto callbacks (all Python-side; see native_engine.py):
//  - VerifyBatchCb: verdicts for the flushing node's pending requests,
//    exposed during the call via hbe_vreq_* accessors; Python writes one
//    byte per request into `verdicts`.
//  - SignCb: kind 0 = threshold signature share over ctx (the doc);
//    kind 1 = decryption share for ctx (serde ciphertext payload).
//    Result returned through hbe_ret_bytes(ret, ...).
//  - CombineCb: kind 0 = combine signature shares -> signature bytes;
//    kind 1 = combine decryption shares -> plaintext bytes.  The t+1
//    (index, share) pairs are exposed via hbe_comb_* accessors.
//  - CtParseCb: serde.try_loads verdict for a subset-accepted payload
//    (1 = decodes to a well-formed Ciphertext) — mirrors
//    honey_badger._start_decrypt's decode gate.
typedef void (*VerifyBatchCb)(int32_t node, int32_t count, uint8_t* verdicts);
typedef void (*SignCb)(int32_t node, int32_t era, int32_t kind,
                       const uint8_t* ctx, uint64_t ctx_len, void* ret);
typedef void (*CombineCb)(int32_t node, int32_t era, int32_t kind,
                          const uint8_t* ctx, uint64_t ctx_len, int32_t count,
                          void* ret);
typedef int32_t (*CtParseCb)(int32_t node, const uint8_t* payload,
                             uint64_t len);
// Adversarial scheduling (upstream tests/net/adversary.rs pre_crank):
// called before each delivery attempt with the queue length; Python
// mirrors the seeded Adversary against the engine queue via
// hbe_queue_swap — randomness stays in Python, so the swap stream
// matches the VirtualNet's at the same seed by construction.
typedef void (*PreCrankCb)(uint64_t queue_len);
// Tampering adversary (upstream tests/net/adversary.rs `tamper`; Python
// mirror net/adversary.py TamperingAdversary): called once per outgoing
// TargetedMessage of a tamper-marked node (a broadcast counts once, like
// one Step message).  During the call the engine exposes a PRIVATE clone
// of the message through the hbe_tamper_* accessors/mutators; whatever
// the callback leaves in the clone is what the network sees.  Randomness
// stays in Python, so the decision stream matches the VirtualNet's
// TamperingAdversary at the same seed by construction.
typedef void (*TamperCb)(int32_t sender, int32_t type, int32_t era,
                         int32_t epoch, int32_t proposer, int32_t round);

// ---------------------------------------------------------------------------
// Cluster (one-engine-per-node) mode — ISSUE 5
//
// The engine normally simulates ALL N nodes behind one internal queue.
// With hbe_set_local(), it instead drives ONE local node over a real
// transport: emissions to any other id are serde-encoded into wire
// frames (the exact bytes Python's serde.dumps(SqMessage.algo(...))
// would produce — wire_encode_algo) and epoch-gated per peer, a native
// mirror of protocols/sender_queue.py for a STATIC validator set
// (join-plan hand-off and deferred removal stay Python-side; the
// cluster harnesses never change membership).  Ingress frames arrive
// through hbe_node_ingest_frames as one byte batch per read burst.
// ---------------------------------------------------------------------------

// One held (ahead-of-window) egress message: SenderQueue._outbox entry.
struct ClusterHeld {
  int64_t era, epoch;
  BytesP payload;
};

enum ClStat {
  CL_HANDLED = 0,        // frames decoded to a consumable SqMessage
  CL_BAD_PAYLOAD = 1,    // cluster.bad_payload mirror (decode rejects)
  CL_IGNORED = 2,        // codec-valid but non-engine (join_plan, bare hbmsg)
  CL_DROPPED_STALE = 3,  // egress dropped: behind the peer's window
  CL_HELD = 4,           // egress held: ahead of the peer's window
  CL_RELEASED = 5,       // held messages released by a peer announce
  CL_SENT = 6,           // algo frames handed to the egress buffer
  CL_ANNOUNCES = 7,      // epoch_started broadcasts emitted
};

// -- flight recorder (ISSUE 9) ----------------------------------------------
//
// A bounded ring of milestone events (epoch open/commit, RBC value/
// ready/deliver, BA round/coin/decide, decrypt start/done) stamped
// with CLOCK_REALTIME nanoseconds so per-node rings from different
// engines/processes merge on one wall clock (hbbft_tpu/obs/).  The
// ring is preallocated at hbe_trace_enable — emitting is a branch, a
// clock read and seven stores, no allocation — and overflow drops the
// OLDEST record with a count (the drain cadence of the cluster
// runtime makes that rare; a flood is bounded either way).  Names
// mirror the Python tracer taxonomy (native_engine.TRACE_KIND_NAMES).
enum TraceKind : int32_t {
  TR_EPOCH_OPEN = 1,     // a=era, b=epoch
  TR_EPOCH_COMMIT = 2,   // a=era, b=epoch, c=contribution count
  TR_RBC_VALUE = 3,      // a=era, b=epoch, c=proposer (valid Value accepted)
  TR_RBC_READY = 4,      // a=era, b=epoch, c=proposer (our Ready broadcast)
  TR_RBC_DELIVER = 5,    // a=era, b=epoch, c=proposer (subset got the value)
  TR_BA_ROUND = 6,       // a=era, b=epoch, c=proposer, d=new round
  TR_BA_COIN = 7,        // a=era, b=epoch, c=proposer, d=(round<<1)|parity
  TR_BA_DECIDE = 8,      // a=era, b=epoch, c=proposer, d=(round<<1)|value
  TR_DECRYPT_START = 9,  // a=era, b=epoch, c=proposer
  TR_DECRYPT_DONE = 10,  // a=era, b=epoch, c=proposer
  TR_BA_INPUT = 11,      // a=era, b=epoch, c=proposer, d=(round<<1)|est
};

struct TraceRec {
  int64_t ts_ns;  // CLOCK_REALTIME at emit
  int32_t node;   // observing engine node id
  int32_t kind;   // TraceKind
  int32_t a, b, c, d;
};

struct TraceState {
  std::vector<TraceRec> ring;  // preallocated at enable; cap 0 = off
  uint32_t cap = 0;
  uint64_t head = 0, tail = 0;  // unwrapped write/read cursors
  uint64_t dropped = 0;
};

struct ClusterState {
  int32_t local = -1;  // engine id of the local node; -1 = not cluster mode
  int32_t window = 3;  // SenderQueue max_future_epochs send gate
  int64_t ann_era = -1, ann_epoch = -1;  // last announced (era, epoch)
  std::vector<std::array<int64_t, 2>> peer_epoch;  // last announce per peer
  std::vector<std::deque<ClusterHeld>> outbox;     // ahead-of-window holds
  std::vector<std::pair<int32_t, BytesP>> egress;  // drained by the runtime
  uint64_t egress_bytes = 0;  // payload bytes pending in `egress`
  // Broadcast encode memo: EngineOps::broadcast emits ONE shared EMsg to
  // every destination back-to-back; holding the shared_ptr pins the
  // object so the pointer-identity key can never alias a recycled
  // address (cleared when the egress batch drains).
  std::shared_ptr<const EMsg> enc_src;
  BytesP enc_payload;
  uint64_t stats[8] = {};  // ClStat counters (hbe_node_stat)
};

struct Engine {
  int n = 0, f = 0;
  std::vector<Node> nodes;
  std::deque<QItem> queue;
  uint64_t delivered = 0;
  BatchEventCb batch_cb = nullptr;
  ContribCb contrib_cb = nullptr;
  // current batch exposed to Python during batch_cb
  std::vector<std::pair<int, BytesP>> cur_batch;  // str-sorted (proposer, payload)
  std::atomic<int> depth{0};  // >0 while inside a processing unit (nested entry points)
  // -- external-crypto mode ------------------------------------------------
  bool ext = false;
  int flush_every = 1;  // 0 = flush only when the delivery queue runs dry
  uint64_t since_flush = 0;
  std::atomic<uint64_t> pool_items{0};  // total pending across all nodes
  bool in_flush = false;
  VerifyBatchCb verify_cb = nullptr;
  SignCb sign_cb = nullptr;
  CombineCb combine_cb = nullptr;
  CtParseCb ct_parse_cb = nullptr;
  PreCrankCb pre_crank_cb = nullptr;
  TamperCb tamper_cb = nullptr;
  EMsg* cur_tamper = nullptr;  // the clone exposed during tamper_cb
  // requests exposed to Python during verify_cb (pointers into the batch)
  std::vector<const VReq*> cur_vreqs;
  // (index, share bytes) pairs exposed during combine_cb
  std::vector<std::pair<int32_t, const Bytes*>> cur_comb;
  // Verified-decode cache: once ANY node RS-decoded a root and the
  // re-encoded codeword matched it, the value is pinned for the whole
  // network — any >= k validated shards of that root reconstruct the
  // same bytes (shards that validate against the root ARE the committed
  // codeword, collisions aside).  Bounded FIFO.
  std::map<Root, BytesP> decoded_roots;
  std::deque<Root> decoded_order;
  // -- multicore (generation-parallel) mode: see engine_run_mt ----------
  bool mt_active = false;
  std::mutex cache_mu;             // decoded_roots / mask_by_acc
  std::recursive_mutex cb_mu;      // cur_batch + batch_cb (a batch
                                   // callback may propose, re-entering
                                   // commit_events on the same thread)
  // Per-message-type delivery profiling (rdtsc cycles + counts).
  uint64_t prof_cycles[16] = {};
  uint64_t prof_count[16] = {};
  // batch_cb nesting depth (a batch callback may propose, re-entering
  // commit_events): the slot-12 stamp counts only outermost callbacks,
  // whose wall already includes any nested ones.  Written only on the
  // sequential driver path (same single-writer rule as the counters).
  int batch_cb_depth = 0;
  // KDF-mask cache keyed by the combined share (s*U, 32B BE): any t+1
  // valid decryption shares of a ciphertext interpolate the SAME point,
  // so the expensive kdf_stream over multi-KB ciphertexts (DKG-epoch
  // payloads) runs once per ciphertext instead of once per node.
  std::map<Root, Bytes> mask_by_acc;
  std::deque<Root> mask_order;
  // Ciphertext-hash cache keyed by the SHARED decoded payload object
  // (round 6): hash_to_g2 over the ct hash input re-reads the whole
  // ciphertext body — ~12M cycles for a DKG-epoch payload — and every
  // node was recomputing it for the same committed value (the measured
  // bulk of the non-Python continuation tail at era changes).  All
  // nodes hold the SAME BytesP via decoded_roots, so key by pointer
  // identity and PIN the payload (shared_ptr) so an address can never
  // be reused while its entry lives.  This mirrors the Python net's
  // Ciphertext.hash_input/_verify_ok memos on shared decoded objects —
  // an optimization the engine was missing, never a semantics change
  // (the hash is a pure function of the pinned bytes).
  std::map<const Bytes*, std::pair<BytesP, U256>> ct_hash_by_payload;
  std::deque<const Bytes*> ct_hash_order;
  // HBBFT_TPU_CT_HASH_CACHE=0 disables the cache (read at hbe_create):
  // the HEAD-equivalent leg of back-to-back A/B measurements, and an
  // escape hatch for the payload pinning if memory ever matters more
  // than the recompute.
  bool ct_hash_cache = true;
  // HBBFT_TPU_ARENA=0 (read at hbe_create): free the epoch-arena
  // blocks at every reset instead of recycling them — the one-build
  // A/B arm for the recycling itself (same flat containers, identical
  // outputs by construction; docs/INVARIANTS.md "epoch-state arena").
  bool arena_recycle = true;
  // -- scalar RLC deferred verification (round 7) --------------------------
  // COIN/DECRYPT share checks in scalar mode are deferred to the pool
  // flush and verified per (Ts/Td instance) GROUP with one random-linear-
  // combination check instead of one full-width mulmod per share
  // (scalar_rlc_verdicts).  flush_every is shared with ext mode: scalar
  // mode uses it when rlc is on (1 = eager per-unit flush, exactly the
  // pre-round-7 flush points; 0 = flush on queue-dry — maximal grouping,
  // identical protocol outputs by the deferred-verification invariant).
  // HBBFT_TPU_COIN_RLC=0 (read at hbe_create; hbe_set_rlc overrides)
  // restores the pre-round-7 path: submit-time verdicts, per-unit flush.
  bool rlc = true;
  // Dirty-node list for the deferred scalar flush (VirtualNet's
  // _dirty_pools): a pool can only fill while its own node's handler
  // or flush runs, so engine_flush_scalar visits exactly these instead
  // of scanning all N nodes per flush (the scan bounded how small
  // flush_every could usefully go).  Maintained ONLY under the
  // deferred cadence, which is sequential — never touched by workers.
  std::vector<int32_t> dirty_nodes;
  // Replay re-attribution (round 7): future-round / future-epoch
  // REPLAYS run inside whatever delivery or continuation advanced the
  // round/epoch — without re-attribution their cycles inflate that
  // message type's slot (a COIN continuation would be billed for whole
  // replayed BVAL/AUX/CONF loads, in BOTH RLC arms).  The replay loops
  // stamp each replayed message's own-time into its own typed slot and
  // add it here; enclosing typed stamps subtract the delta.  Counts
  // are NOT re-ticked (the original delivery ticked them when it
  // buffered).  Single-writer: only touched under !mt_active guards.
  uint64_t replay_borrow = 0;
  // True while engine_flush_scalar drains deferred pools: those
  // continuations run OUTSIDE engine_run's typed delivery stamp, so
  // engine_flush_pool folds their cycles back into the delivering
  // message type's slot (BA_COIN / HB_DECRYPT) to keep cyc/delivery
  // comparable across the HBBFT_TPU_COIN_RLC A/B.
  bool in_deferred_flush = false;
  // -- cluster (one-engine-per-node) mode (ISSUE 5) ------------------------
  // Sequential-only, like the deferred cadences: hbe_run_mt falls back.
  ClusterState cluster;
  // -- flight recorder (ISSUE 9) -------------------------------------------
  // Sequential-only, like the counters above: emits are unguarded
  // single-writer stores, so hbe_trace_enable is rejected for runs
  // that will use engine_run_mt (the emit sites check !mt_active).
  TraceState trace;
};

inline void trace_emit(Engine& e, int32_t node, int32_t kind, int32_t a,
                       int32_t b, int32_t c, int32_t d) {
  if (!e.trace.cap || e.mt_active) return;
  TraceState& t = e.trace;
  if (t.head - t.tail == t.cap) {
    t.tail++;
    t.dropped++;
  }
  int64_t ns = std::chrono::duration_cast<std::chrono::nanoseconds>(
                   std::chrono::system_clock::now().time_since_epoch())
                   .count();
  t.ring[t.head % t.cap] = TraceRec{ns, node, kind, a, b, c, d};
  t.head++;
}

const size_t MASK_CACHE_MAX = 4096;

const size_t DECODED_ROOTS_MAX = 8192;

// ct-hash entries pin their payloads; DKG-epoch payloads are hundreds
// of KB, so the cap is sized for N concurrent decrypts plus headroom
// rather than the roomy counts of the byte-small caches above.
const size_t CT_HASH_CACHE_MAX = 1024;

// Scalar deferred-flush cadence active?  (Round 7: the RLC path shares
// ext mode's flush_every machinery; 1 keeps the pre-round-7 per-unit
// eager flush points exactly.)
inline bool scalar_deferred(const Engine& e) {
  return !e.ext && e.rlc && e.flush_every != 1;
}

inline void pool_push(Engine& e, Node& node, Pending&& p) {
  node.pool.push_back(std::move(p));
  e.pool_items++;
  if (!node.pool_dirty && scalar_deferred(e)) {
    node.pool_dirty = true;
    e.dirty_nodes.push_back(node.id);
  }
}

// ---------------------------------------------------------------------------
// Scalar RLC group verification (round 7)
//
// Per-share check being amortized:   share_i == pk_i * H        (COIN)
//                                    share_i * ct_h == pk_i * ct.w  (DECRYPT)
// Group check over k pending shares of one Ts/Td instance, with small
// nonzero 64-bit coefficients r_i from a deterministic splitmix chain
// seeded per (instance hash, sub-range):
//       Σ r_i*share_i == (Σ r_i*pk_i) * H          (resp. the two-sided
//       Σ r_i*share_i * ct_h == (Σ r_i*pk_i) * ct.w decrypt form)
// The Σ accumulators are UNREDUCED 512-bit integers (each term is a
// 64x256 product; k < 2^191 cannot overflow 8 words), reduced once per
// group through the existing Montgomery machinery — so the per-share
// cost is one 4-limb widening mul + add per side (~7 cyc measured)
// against a full Montgomery mulmod (~134 cyc) on the per-share path.
//
// Exactness: a group containing exactly one bad share can never pass
// (r_i != 0 and the defect term r_i*δ_i is nonzero mod r); multiple
// bad shares cancel only with probability ~2^-64 per check, and the
// coefficients are re-drawn per bisection sub-range, so the recursion
// terminates at per-item direct checks and attributes every bad share
// to its sender exactly like the per-share path (the ScalarSuite is
// the protocol-plane TEST suite — trivially forgeable by design — so
// adversarial coefficient-grinding is out of scope; real crypto runs
// the ext-mode backends).  docs/INVARIANTS.md "RLC byte-identity".
// ---------------------------------------------------------------------------

inline uint64_t rlc_mix(uint64_t z) {
  z += 0x9e3779b97f4a7c15ULL;
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

// ---- Scalar RLC share verification: one core, two layouts ----------------
//
// The RLC math (coefficient chain, unreduced accumulators, bisection,
// break-even thresholds) exists ONCE, templated over a layout view:
//  * GrpView — the deferred cadence's contiguous RlcShare arrays on a
//    leader Pending (submit-time groups);
//  * CsrView — flush_every=1 bursts' per-share Pendings via CSR
//    indices (scalar_rlc_verdicts).
// A single implementation keeps the two cadences' verdict behavior
// mechanically identical (the RLC byte-identity invariant's mirror
// obligation, docs/INVARIANTS.md).

// Per-instance check constants: TS verifies share == pk*h1 (h1 =
// doc_h); TD verifies share*h1 == pk*h2 (h1 = ct_h, h2 = ct.w).
// h1m/h2m are the Montgomery lifts (h*2^256), computed once per
// instance lookup so every check below is one-REDC (round 15); the
// products they produce are the EXACT canonical values the classic
// mulmod forms produced, so verdicts and fault logs are unchanged.
struct RlcInstance {
  bool is_ts;
  const U256* h1;
  const U256* h2;
  U256 h1m, h2m;
};

inline RlcInstance rlc_instance(const Pending& p) {
  RlcInstance in;
  if (p.cont == CONT_TS) {
    in.is_ts = true;
    in.h1 = &p.ts->doc_h;
    in.h2 = nullptr;
    in.h1m = to_mont(*in.h1);
  } else {
    in.is_ts = false;
    in.h1 = &p.td->ct_h;
    in.h2 = &p.td->ct.w;
    in.h1m = to_mont(*in.h1);
    in.h2m = to_mont(*in.h2);
  }
  return in;
}

inline uint64_t rlc_seed(const RlcInstance& in) {
  const U256& h = *in.h1;
  return rlc_mix(h.w[0] ^ rlc_mix(h.w[1] ^ rlc_mix(h.w[2] ^ h.w[3])));
}

// Exact per-share check — the same formulas the pre-round-7 submit
// path computed, over the pk snapshot taken at submit.  The TS check
// is REPRESENTATIONAL (`share == mulmod(pk, doc_h)`; mulmod output is
// canonical), so a non-canonical wire encoding (value >= r, congruent
// to the valid share) must fail here too — congruence alone would
// accept it and diverge from the per-share path's fault log.  The TD
// check is congruence on BOTH sides in the per-share path (the share
// flows through mulmod), so non-canonical decrypt shares pass in both
// paths alike; no extra gate there.
inline bool rlc_eq(const RlcInstance& in, const U256& sh, const U256& pk) {
  // mont_mul(x, hm) = x*h*2^256*2^-256 = x*h — the exact canonical
  // product the classic mulmod produced, in one REDC.
  if (in.is_ts) {
    if (u256_cmp(sh, R_MOD) >= 0) return false;
    return sh == mont_mul(pk, in.h1m);
  }
  return mont_mul(sh, in.h1m) == mont_mul(pk, in.h2m);
}

inline bool rlc_eq_acc(const RlcInstance& in, const uint64_t sh[8],
                       const uint64_t pk[8]) {
  // The 512-bit accumulators reduce through ONE redc each (S*2^-256);
  // comparing both sides in that uniformly 2^-256-scaled domain is
  // exact (x -> x*2^-256 is a bijection mod r):
  //   TS:  S == P*h1      <=>  S*2^-256 == mont_mul(P*2^-256, h1m)
  //   TD:  S*h1 == P*h2   <=>  mont_mul(S*2^-256, h1m) ==
  //                            mont_mul(P*2^-256, h2m)
  // so verdicts are identical to the classic two-REDC-per-side form.
  U256 s = redc(sh), p = redc(pk);
  if (in.is_ts) return s == mont_mul(p, in.h1m);
  return mont_mul(s, in.h1m) == mont_mul(p, in.h2m);
}

struct GrpView {
  std::vector<RlcShare>& g;
  const U256& share(size_t k) const { return g[k].share; }
  const U256& pk(size_t k) const { return g[k].pk; }
  int32_t sender(size_t k) const { return g[k].sender; }
  void set_ok(size_t k, bool v) { g[k].ok = v ? 1 : 0; }
};

struct CsrView {
  std::vector<Pending>& items;
  const uint32_t* idxs;
  const U256& share(size_t k) const { return items[idxs[k]].share; }
  const U256& pk(size_t k) const { return items[idxs[k]].pk; }
  int32_t sender(size_t k) const { return items[idxs[k]].sender; }
  void set_ok(size_t k, bool v) { items[idxs[k]].pre_ok = v; }
};

// One RLC check over v[lo..hi).  Two passes (round 15): the sequential
// coefficient chain (+ the TS canonicity gate) first, then the
// accumulate as one batched kernel call over gathered contiguous
// arrays.  The coefficient stream, early-fail behavior, and the exact
// integer sums are identical to the fused per-item loop it replaces —
// an integer sum is order- and arm-independent.
template <class V>
inline bool rlc_check_range_v(const RlcInstance& in, const V& v, size_t lo,
                              size_t hi, uint64_t seed) {
  size_t n = hi - lo;
  // Workers run this under engine_run_mt: scratch is thread-local,
  // capacity retained across checks (group sizes are small and bursty).
  thread_local std::vector<uint64_t> coeffs;
  thread_local std::vector<U256> shs, pks;
  coeffs.resize(n);
  shs.resize(n);
  pks.resize(n);
  uint64_t state = rlc_mix(seed ^ (uint64_t)lo * 0xc2b2ae3d27d4eb4fULL ^
                           (uint64_t)hi * 0x165667b19e3779f9ULL);
  for (size_t k = lo; k < hi; ++k) {
    // Non-canonical TS share in the range: the RLC sum only sees the
    // residue, but the per-share check is representational (rlc_eq
    // notes) — force the range to FAIL so bisection attributes it
    // exactly.
    if (in.is_ts && u256_cmp(v.share(k), R_MOD) >= 0) return false;
    state = rlc_mix(state ^ v.share(k).w[0] ^
                    ((uint64_t)(uint32_t)v.sender(k) << 32));
    coeffs[k - lo] = state | 1;  // nonzero: a lone bad share can't cancel
    shs[k - lo] = v.share(k);
    pks[k - lo] = v.pk(k);
  }
  uint64_t acc_sh[8] = {0, 0, 0, 0, 0, 0, 0, 0};
  uint64_t acc_pk[8] = {0, 0, 0, 0, 0, 0, 0, 0};
  hbf::rlc_accum(shs[0].w, coeffs.data(), n, acc_sh);
  hbf::rlc_accum(pks[0].w, coeffs.data(), n, acc_pk);
  return rlc_eq_acc(in, acc_sh, acc_pk);
}

// Assign verdicts for v[lo..hi): group check, bisect on failure,
// per-share direct checks at the leaves (exact attribution).
template <class V>
void rlc_assign_range_v(const RlcInstance& in, V& v, size_t lo, size_t hi,
                        uint64_t seed) {
  if (hi - lo == 1) {
    v.set_ok(lo, rlc_eq(in, v.share(lo), v.pk(lo)));
    return;
  }
  if (rlc_check_range_v(in, v, lo, hi, seed)) {
    for (size_t k = lo; k < hi; ++k) v.set_ok(k, true);
    return;
  }
  size_t mid = lo + (hi - lo) / 2;
  rlc_assign_range_v(in, v, lo, mid, seed);
  rlc_assign_range_v(in, v, mid, hi, seed);
}

template <class V>
inline void rlc_verify_range_v(const RlcInstance& in, V& v, size_t lo,
                               size_t hi) {
  if (hi - lo < 3) {
    // RLC breaks even around three shares (two accumulate muls + the
    // group finalize vs one direct mulmod per share); below that the
    // direct checks win.
    for (size_t k = lo; k < hi; ++k)
      v.set_ok(k, rlc_eq(in, v.share(k), v.pk(k)));
  } else {
    rlc_assign_range_v(in, v, lo, hi, rlc_seed(in));
  }
}

inline bool rlc_check_one(const Pending& p) {
  return rlc_eq(rlc_instance(p), p.share, p.pk);
}

// Lazy CHUNKED verification, driven by the folded continuations as
// they consume shares: the per-share path never verifies shares whose
// continuations would run after termination, so verifying a whole
// accumulated group up front did strictly MORE crypto than the
// per-share path (at N=300 a group holds ~2.5x the f+1 shares the
// instance needs).  Chunks are RLC-checked with (lo,hi)-seeded
// coefficients like bisection sub-ranges; verdict semantics are
// unchanged (post-termination shares get no verdict and no fault in
// BOTH paths).  Returns the new verified limit.
const size_t RLC_CHUNK = 32;

inline size_t lead_verify_chunk(Pending& lead, size_t lo) {
  size_t hi = lo + RLC_CHUNK;
  if (hi > lead.grp.size()) hi = lead.grp.size();
  RlcInstance in = rlc_instance(lead);
  GrpView v{lead.grp};
  rlc_verify_range_v(in, v, lo, hi);
  return hi;
}

// ===========================================================================
// Engine mechanics: emission, faults, pool flush, merkle/RS helpers
// ===========================================================================

// Multicore emission redirection: when set, the current worker's
// delivery is accumulating its emissions for ordered splicing.
thread_local std::vector<QItem>* tl_emit_sink = nullptr;

// Cluster-mode hooks (defined with the wire codec, after engine_run):
// route an emission to the epoch-gated egress, and broadcast an
// epoch_started announce when the local node's (era, epoch) advanced.
void cluster_emit(Engine& e, int dest, const std::shared_ptr<const EMsg>& msg);
void cluster_announce(Engine& e);

struct EngineOps {
  Engine& e;
  Node& node;

  // One shared message object per emission, tampered first when the
  // sender is adversary-owned.  The tamper callback mutates a clone, so
  // the sender's OWN state keeps the honest values (exactly the Python
  // TamperingAdversary, which rewrites step messages after the faulty
  // node processed them honestly).
  std::shared_ptr<const EMsg> outgoing(const EMsg& m) {
    if (node.tampered && e.tamper_cb) {
      EMsg clone = m;
      e.cur_tamper = &clone;
      e.tamper_cb(node.id, (int32_t)m.type, m.era, m.epoch, m.proposer,
                  m.round);
      e.cur_tamper = nullptr;
      return std::make_shared<const EMsg>(std::move(clone));
    }
    return std::make_shared<const EMsg>(m);
  }

  // -- emission (drops when a stale-callback guard set suppress_emit) ---
  //
  // Multicore mode: workers never touch the shared queue — emissions
  // land in the worker's per-delivery slot (tl_emit_sink) and the
  // scheduler splices them back IN SOURCE-DELIVERY ORDER, reproducing
  // the sequential FIFO append order exactly (engine_run_mt notes).
  void emit(int dest, std::shared_ptr<const EMsg> msg) {
    if (e.cluster.local >= 0) {
      // Cluster mode: only the local node is ever driven, and send/
      // broadcast already exclude self, so every emission targets a
      // remote peer — encode + epoch-gate it toward the wire.
      cluster_emit(e, dest, msg);
      return;
    }
    if (tl_emit_sink) tl_emit_sink->push_back({node.id, dest, std::move(msg)});
    else e.queue.push_back({node.id, dest, std::move(msg)});
  }
  void send(int dest, const EMsg& m) {
    if (node.suppress_emit) return;
    if (dest == node.id) return;
    emit(dest, outgoing(m));
  }
  void broadcast(const EMsg& m) {
    if (node.suppress_emit) return;
    auto shared = outgoing(m);
    for (int d = 0; d < e.n; ++d)
      if (d != node.id) emit(d, shared);
  }
  void broadcast_except(const EMsg& m, const NodeSet& except) {
    if (node.suppress_emit) return;
    auto shared = outgoing(m);
    for (int d = 0; d < e.n; ++d)
      if (d != node.id && !except.has(d)) emit(d, shared);
  }
  void send_nodes(const EMsg& m, const NodeSet& dests) {
    if (node.suppress_emit) return;
    auto shared = outgoing(m);
    for (int d = 0; d < e.n; ++d)
      if (d != node.id && dests.has(d)) emit(d, shared);
  }
  void fault(int subject, const char* kind) {
    node.faults.push_back({subject, kind});
  }
};

inline Root merkle_leaf_hash(const Bytes& v) {
  Bytes buf;
  buf.push_back('\x00');
  buf.append(v);
  Root out;
  hbn::sha3_256((const uint8_t*)buf.data(), buf.size(), out.data());
  return out;
}

inline Root merkle_branch_hash(const Root& l, const Root& r) {
  uint8_t buf[65];
  buf[0] = 0x01;
  std::memcpy(buf + 1, l.data(), 32);
  std::memcpy(buf + 33, r.data(), 32);
  Root out;
  hbn::sha3_256(buf, 65, out.data());
  return out;
}

// Batched Merkle level hashing (sha3 plane).  Leaves: count equal-length
// shards (pointers; the 0x00 domain prefix is staged here), digests into
// out[0..count).  Levels: m parent hashes from 2m children — the 65-byte
// 0x01||l||r messages are staged contiguously and dispatched as one
// batch.  Digests equal the per-call merkle_leaf_hash/merkle_branch_hash
// values exactly (same FIPS-202 arm contract), so tree roots and proofs
// are byte-identical to the unbatched forms.
inline void merkle_leaves_hash(const uint8_t* const* shards, size_t shard_len,
                               size_t count, Root* out) {
  if (!count) return;
  size_t msg_len = 1 + shard_len;
  std::vector<uint8_t> stage(count * msg_len);
  for (size_t i = 0; i < count; ++i) {
    uint8_t* m = stage.data() + i * msg_len;
    m[0] = 0x00;
    std::memcpy(m + 1, shards[i], shard_len);
  }
  hbs::sha3_256_batch(stage.data(), msg_len, count, out[0].data());
}

inline void merkle_reduce_level(const Root* children, size_t m, Root* out) {
  if (!m) return;
  std::vector<uint8_t> stage(m * 65);
  for (size_t i = 0; i < m; ++i) {
    uint8_t* msg = stage.data() + i * 65;
    msg[0] = 0x01;
    std::memcpy(msg + 1, children[2 * i].data(), 32);
    std::memcpy(msg + 33, children[2 * i + 1].data(), 32);
  }
  hbs::sha3_256_batch(stage.data(), 65, m, out[0].data());
}

inline int merkle_depth(int n_leaves) {
  int d = 0, size = 1;
  while (size < n_leaves) {
    size <<= 1;
    ++d;
  }
  return d;
}

inline bool proof_validate(const ProofData& p, int n_leaves) {
  if (p.valid_memo >= 0 && p.valid_n == n_leaves) return p.valid_memo != 0;
  bool ok = false;
  if (p.index >= 0 && p.index < n_leaves &&
      (int)p.path.size() == merkle_depth(n_leaves)) {
    Root h = merkle_leaf_hash(p.value);
    int idx = p.index;
    for (const Root& sib : p.path) {
      h = (idx & 1) ? merkle_branch_hash(sib, h) : merkle_branch_hash(h, sib);
      idx >>= 1;
    }
    ok = h == p.root;
  }
  p.valid_memo = ok ? 1 : 0;
  p.valid_n = n_leaves;
  return ok;
}

// broadcast.py _pack: length-prefix + pad into k equal shards.  The
// GF(2^16) codec (validator sets > 255) needs even shard lengths
// (align = 2); GF(256) uses align = 1.
inline std::vector<Bytes> rbc_pack(const Bytes& value, int k, int align) {
  Bytes payload;
  uint8_t len8[8];
  uint64_t len = value.size();
  for (int i = 0; i < 8; ++i) len8[i] = (uint8_t)(len >> (56 - 8 * i));
  payload.append((const char*)len8, 8);
  payload.append(value);
  size_t shard_len = (payload.size() + k - 1) / k;
  if (shard_len < 1) shard_len = 1;
  shard_len = (shard_len + align - 1) / align * align;
  payload.resize((size_t)k * shard_len, '\x00');
  std::vector<Bytes> shards(k);
  for (int i = 0; i < k; ++i)
    shards[i] = payload.substr((size_t)i * shard_len, shard_len);
  return shards;
}

inline bool rbc_unpack(const std::vector<Bytes>& data_shards, Bytes& out) {
  Bytes payload;
  for (const Bytes& s : data_shards) payload.append(s);
  if (payload.size() < 8) return false;
  uint64_t n = 0;
  for (int i = 0; i < 8; ++i) n = (n << 8) | (uint8_t)payload[i];
  if (8 + n > payload.size()) return false;
  out = payload.substr(8, n);
  return true;
}

// Cached systematic RS matrices (same semantics as gf256.encoding_matrix).
// Capped FIFO + mutex + shared_ptr returns (ISSUE 17 satellite: these two
// were the engine's last genuinely unbounded pure-function caches — the
// per-engine decoded_roots / mask_by_acc / ct_hash_by_payload caches have
// carried FIFO caps since rounds 6/7).  A (k, n) key changes only with
// the validator-set size, so 64 entries is roomy even across many eras;
// the shared_ptr keeps an evicted matrix alive for callers mid-matmul,
// and the mutex makes first-build races (mt workers decode concurrently)
// well-defined instead of accidentally-ordered.
const size_t RS_MATRIX_CACHE_MAX = 64;

template <typename Sym, bool (*BUILD)(int, int, std::vector<Sym>&)>
inline std::shared_ptr<const std::vector<Sym>> rs_matrix_cached(int k, int n) {
  static std::mutex mu;
  static std::map<std::pair<int, int>,
                  std::shared_ptr<const std::vector<Sym>>> cache;
  static std::deque<std::pair<int, int>> order;
  auto key = std::make_pair(k, n);
  std::lock_guard<std::mutex> lk(mu);
  auto it = cache.find(key);
  if (it == cache.end()) {
    std::vector<Sym> m;
    if (!BUILD(k, n, m)) return nullptr;
    if (cache.size() >= RS_MATRIX_CACHE_MAX) {
      cache.erase(order.front());
      order.pop_front();
    }
    it = cache
             .emplace(key, std::make_shared<const std::vector<Sym>>(
                               std::move(m)))
             .first;
    order.push_back(key);
  }
  return it->second;
}

inline bool rs_build8(int k, int n, std::vector<uint8_t>& m) {
  return hbn::encoding_matrix_t<std::vector<uint8_t>>(k, n, m);
}
inline bool rs_build16(int k, int n, std::vector<uint16_t>& m) {
  return hbn::encoding_matrix16_t<std::vector<uint16_t>>(k, n, m);
}

inline std::shared_ptr<const std::vector<uint8_t>> rs_matrix(int k, int n) {
  return rs_matrix_cached<uint8_t, rs_build8>(k, n);
}

inline std::shared_ptr<const std::vector<uint16_t>> rs16_matrix(int k, int n) {
  return rs_matrix_cached<uint16_t, rs_build16>(k, n);
}

inline int rs_align(int n) { return n > 255 ? 2 : 1; }

// Parity rows for k contiguous data rows of `size` bytes; dispatches on
// the validator count (GF(256) <= 255, GF(2^16) beyond).
inline bool rs_encode_rows(int k, int n, const uint8_t* data, size_t size,
                           std::vector<uint8_t>& parity) {
  if (n <= 255) {
    auto mat = rs_matrix(k, n);
    if (!mat) return false;
    parity.assign((size_t)(n - k) * size, 0);
    hbn::gf_matmul(mat->data() + (size_t)k * k, data, parity.data(), n - k, k,
                   size);
    return true;
  }
  if (size % 2) return false;
  auto mat = rs16_matrix(k, n);
  if (!mat) return false;
  size_t nsym = size / 2;
  std::vector<uint16_t> dsym((size_t)k * nsym);
  std::vector<uint16_t> psym((size_t)(n - k) * nsym);
  hbn::bytes_to_sym16(data, (size_t)k * nsym, dsym.data());
  hbn::gf16_matmul(mat->data() + (size_t)k * k, dsym.data(), psym.data(),
                   n - k, k, nsym);
  parity.resize((size_t)(n - k) * size);
  hbn::sym16_to_bytes(psym.data(), (size_t)(n - k) * nsym, parity.data());
  return true;
}

// Reconstruct the k data rows from k codeword rows with the given
// indices; false = out-of-range index / singular subset / bad size.
inline bool rs_reconstruct_rows(int k, int n,
                                const std::vector<uint64_t>& idxs,
                                const uint8_t* have, size_t size,
                                std::vector<uint8_t>& data_out) {
  for (uint64_t idx : idxs)
    if (idx >= (uint64_t)n) return false;
  if (n <= 255) {
    auto mat = rs_matrix(k, n);
    if (!mat) return false;
    std::vector<uint8_t> sub((size_t)k * k), dec((size_t)k * k);
    for (int r = 0; r < k; ++r)
      std::memcpy(sub.data() + (size_t)r * k, mat->data() + idxs[r] * k, k);
    if (!hbn::gf_mat_inv_t<std::vector<uint8_t>>(sub.data(), dec.data(), k))
      return false;
    data_out.assign((size_t)k * size, 0);
    hbn::gf_matmul(dec.data(), have, data_out.data(), k, k, size);
    return true;
  }
  if (size % 2) return false;
  auto mat = rs16_matrix(k, n);
  if (!mat) return false;
  std::vector<uint16_t> sub((size_t)k * k), dec((size_t)k * k);
  for (int r = 0; r < k; ++r)
    std::memcpy(sub.data() + (size_t)r * k, mat->data() + idxs[r] * k, 2 * k);
  if (!hbn::gf16_mat_inv_t<std::vector<uint16_t>>(sub.data(), dec.data(), k))
    return false;
  size_t nsym = size / 2;
  std::vector<uint16_t> hsym((size_t)k * nsym), dsym((size_t)k * nsym);
  hbn::bytes_to_sym16(have, (size_t)k * nsym, hsym.data());
  hbn::gf16_matmul(dec.data(), hsym.data(), dsym.data(), k, k, nsym);
  data_out.resize((size_t)k * size);
  hbn::sym16_to_bytes(dsym.data(), (size_t)k * nsym, data_out.data());
  return true;
}

// ===========================================================================
// The protocol logic.  Layered exactly as the Python stack: each child
// call takes an output accumulator processed at the parent boundary
// (the Python Step.output / map_messages discipline).
// ===========================================================================

struct Ctx;  // per-node processing context

struct Ctx {
  Engine& e;
  Node& node;
  EngineOps ops;
  std::vector<std::pair<int, int>> batch_events;  // (era, epoch) pending

  Ctx(Engine& e_, Node& n_) : e(e_), node(n_), ops{e_, n_} {}

  // Engine node count (message routing: Target.all expands over every
  // node, observers included — VirtualNet.node_order).
  int n_route() const { return e.n; }
  // Era validator-set sizes (NetworkInfo thresholds).
  int n() const { return node.era_n; }
  int f() const { return node.era_f; }
  int num_correct() const { return node.era_n - node.era_f; }
  bool is_val(int id) const { return node.val_index[id] >= 0; }

  // ---- ThresholdSign (coin) ----------------------------------------------
  //
  // `parity_out` carries the coin value(s) of any signature combined in
  // this call (Signature.parity()) — scalar mode computes the combine
  // natively, external mode through the Python combine callback.

  void ts_input(EpochState& st, int proposer, Ba& ba, Ts& ts,
                std::vector<uint8_t>& parity_out) {
    if (ts.had_input) return;
    ts.had_input = true;
    if (!node.has_share) return;
    EMsg m;
    m.era = node.era;
    m.epoch = st.epoch;
    m.proposer = proposer;
    m.round = ba.round;
    m.type = BA_COIN;
    if (e.ext) {
      auto share_b = std::make_shared<Bytes>();
      e.sign_cb(node.id, node.era, 0, (const uint8_t*)ts.doc.data(),
                ts.doc.size(), share_b.get());
      m.share_b = share_b;
      ops.broadcast(m);
      if (!ts.terminated) {
        ts.seen.add(node.id);
        ts.verified_b.push_back({node.id, *share_b});
        ts.verified_set.add(node.id);
        ts_try_output(ts, parity_out);
      }
      return;
    }
    U256 share = mulmod(node.sk_share, ts.doc_h);
    m.share = share;
    ops.broadcast(m);
    if (!ts.terminated) {
      ts.seen.add(node.id);
      ts.verified.push_back({node.id, share});
      ts.verified_set.add(node.id);
      ts_try_output(ts, parity_out);
    }
  }

  void ts_handle_share(EpochState& st, int proposer, Ba& ba,
                       std::shared_ptr<Ts> ts, int sender, const EMsg& m,
                       std::vector<uint8_t>& parity_out) {
    (void)parity_out;
    if (ts->terminated) return;
    if (!is_val(sender)) {
      ops.fault(sender, F_TS_NONVAL);
      return;
    }
    if (ts->seen.has(sender)) {
      ops.fault(sender, F_TS_DUP);
      return;
    }
    ts->seen.add(sender);
    Pending p;
    p.cont = CONT_TS;
    p.era = node.era;
    p.epoch = st.epoch;
    p.proposer = proposer;
    p.rnd = ba.round;
    p.sender = sender;
    p.ts = ts;
    if (e.ext) {
      p.share_b = m.share_b ? m.share_b : std::make_shared<const Bytes>();
      p.need_verdict = true;
      p.req.kind = VK_SIG;
      p.req.era = p.era;
      p.req.sender = sender;
      p.req.doc = &ts->doc;  // Ts kept alive by p.ts
      p.req.share = p.share_b;
    } else {
      p.share = m.share;
      if (e.rlc && scalar_deferred(e)) {
        // Round-7 deferred RLC path: shares of one Ts accumulate as a
        // CONTIGUOUS group on the instance's leader Pending (formed
        // HERE, while the state is cache-hot); the flush verifies the
        // whole group with one RLC check — Σ rᵢ·shareᵢ ==
        // (Σ rᵢ·pkᵢ)·doc_h — bisecting failures so verdicts match the
        // per-share path exactly (scalar_rlc docs / INVARIANTS.md).
        if (ts->grp_round == node.pool_round && ts->grp_idx >= 0) {
          node.pool[ts->grp_idx].grp.push_back(
              {m.share, node.pk_shares[sender], sender, 0});
          return;
        }
        p.rlc_defer = true;
        p.grp.push_back({m.share, node.pk_shares[sender], sender, 0});
        ts->grp_round = node.pool_round;
        ts->grp_idx = (int32_t)node.pool.size();  // this push's index
      } else if (e.rlc) {
        // flush_every=1: per-share Pendings at the pre-round-7 flush
        // points; the flush's verdict pass checks them (grouped only
        // within one unit's burst), keeping runs byte-identical to the
        // Python net.
        p.rlc_defer = true;
        p.pk = node.pk_shares[sender];
      } else {
        // Pre-round-7 path (HBBFT_TPU_COIN_RLC=0): compute the verdict
        // now (order-independent scalar check), run the protocol
        // callback at flush (pool order).
        p.pre_ok = p.share == mulmod(node.pk_shares[sender], ts->doc_h);
      }
    }
    pool_push(e, node, std::move(p));
  }

  // pool callback: TS._on_verified lifted through the coin-round /
  // epoch / era guards (binary_agreement._coin_scope_wrap +
  // honey_badger._guard_epoch).
  // mirror: ts-acceptance-item (twin: threshold_sign.handle_message /
  //     _on_verified — acceptance-rule changes land on BOTH sides)
  void ts_verified_cb(int era, int epoch, int proposer, int rnd,
                      std::shared_ptr<Ts> ts, int sender, const U256& share,
                      std::shared_ptr<const Bytes> share_b, bool ok) {
    bool live_epoch = node.era == era && node.hb_init && node.hb.epoch == epoch;
    if (!live_epoch) node.suppress_emit++;
    std::vector<uint8_t> parity_out;
    // inner: TS._on_verified
    if (!ts->terminated) {
      if (!ok) {
        ops.fault(sender, F_TS_INVALID);
      } else {
        if (e.ext)
          ts->verified_b.push_back({sender, *share_b});
        else
          ts->verified.push_back({sender, share});
        ts->verified_set.add(sender);
        ts_try_output(*ts, parity_out);
      }
    }
    // lift: coin scope (round / BA termination / same instance), then the
    // subset-output and epoch-advance boundaries (_on_ba_step ->
    // _guard_epoch(_on_subset_step) -> _advance in the Python chain).
    if (live_epoch) {
      EpochState& st = node.hb.state;
      if (!parity_out.empty()) {
        Ba& ba = st.proposals[proposer].ba;
        if (ba.round == rnd && !ba.terminated && ba.coin == ts) {
          for (uint8_t par : parity_out) ba_on_coin(st, proposer, ba, par);
        }
      }
      hb_drain_subset_outputs(st);
      hb_advance();
    }
    if (!live_epoch) node.suppress_emit--;
  }

  // Folded continuation for a deferred RLC GROUP of same-Ts shares
  // (scalar deferred mode only): the inner TS._on_verified body runs
  // per item in pool order, but the coin-scope/epoch lift — and the
  // caller's commit_events — run once per group instead of once per
  // share.  This is observably identical to running ts_verified_cb per
  // item: pre-termination items' lifts are no-ops (no parity yet, no
  // pending subset outputs/batches), and post-termination items are
  // complete no-ops (the Python path records no fault after
  // termination either), so only the single terminating item's lift
  // has effects — and it runs here with the same state it would have
  // seen per-item.  Fault order within the group is submission order,
  // as in the per-share path.
  // mirror: ts-acceptance-group (twin: threshold_sign._on_verified —
  //     acceptance-rule changes land on BOTH continuations)
  void ts_group_verified_cb(int era, int epoch, int proposer, int rnd,
                            const std::shared_ptr<Ts>& ts, Pending& lead) {
    size_t count = lead.grp.size(), vlim = 0;
    bool live_epoch = node.era == era && node.hb_init && node.hb.epoch == epoch;
    if (!live_epoch) node.suppress_emit++;
    std::vector<uint8_t> parity_out;
    for (size_t k = 0; k < count; ++k) {
      if (ts->terminated) break;  // later items are no-ops (see above)
      if (k >= vlim) vlim = lead_verify_chunk(lead, k);
      const RlcShare& sh = lead.grp[k];
      if (!sh.ok) {
        ops.fault(sh.sender, F_TS_INVALID);
        continue;
      }
      ts->verified.push_back({sh.sender, sh.share});
      ts->verified_set.add(sh.sender);
      ts_try_output(*ts, parity_out);
    }
    if (live_epoch) {
      EpochState& st = node.hb.state;
      if (!parity_out.empty()) {
        Ba& ba = st.proposals[proposer].ba;
        if (ba.round == rnd && !ba.terminated && ba.coin == ts) {
          for (uint8_t par : parity_out) ba_on_coin(st, proposer, ba, par);
        }
      }
      hb_drain_subset_outputs(st);
      hb_advance();
    }
    if (!live_epoch) node.suppress_emit--;
  }

  void ts_try_output(Ts& ts, std::vector<uint8_t>& parity_out) {
    int threshold = f();
    size_t have = e.ext ? ts.verified_b.size() : ts.verified.size();
    if (ts.terminated || (int)have < threshold + 1) return;
    if (e.ext) {
      // by_index -> sorted, first threshold+1, combine via Python.
      std::vector<std::pair<int, const Bytes*>> by_index;
      for (auto& kv : ts.verified_b)
        by_index.push_back({node.val_index[kv.first], &kv.second});
      std::sort(by_index.begin(), by_index.end(),
                [](auto& a, auto& b) { return a.first < b.first; });
      by_index.resize(threshold + 1);
      e.cur_comb.clear();
      for (auto& kv : by_index) e.cur_comb.push_back({kv.first, kv.second});
      Bytes sig;
      e.combine_cb(node.id, node.era, 0, (const uint8_t*)ts.doc.data(),
                   ts.doc.size(), (int32_t)e.cur_comb.size(), &sig);
      e.cur_comb.clear();
      ts.terminated = true;
      uint8_t digest[32];
      hbn::sha3_256((const uint8_t*)sig.data(), sig.size(), digest);
      parity_out.push_back(digest[0] & 1);
      return;
    }
    // by_index (netinfo.index) -> sorted, first threshold+1, combine.
    std::vector<std::pair<int, U256>> by_index;
    by_index.reserve(ts.verified.size());
    for (auto& kv : ts.verified)
      by_index.push_back({node.val_index[kv.first], kv.second});
    std::sort(by_index.begin(), by_index.end(),
              [](auto& a, auto& b) { return a.first < b.first; });
    by_index.resize(threshold + 1);
    std::vector<int> idxs;
    idxs.reserve(by_index.size());
    for (auto& kv : by_index) idxs.push_back(kv.first);
    // Hold the shared_ptr for the whole sum: lifetime extension does
    // NOT apply through the dereference of a temporary, and a
    // concurrent cache eviction dropping the last refcount mid-sum
    // would be a use-after-free under engine_run_mt.
    uint64_t t0 = prof_tick();
    std::shared_ptr<const std::vector<U256>> lam_p = lagrange_cached(idxs);
    const std::vector<U256>& lam = *lam_p;
    // Gather the shares contiguous and run the whole Lagrange sum as
    // one batched dot product (field plane; round 15).  thread_local
    // scratch (the rlc_check_range_v pattern, per-worker under
    // engine_run_mt): the gather sits inside the slot-14 timed window,
    // so a per-combine allocation would fold allocator jitter into the
    // A/B readout.
    static thread_local std::vector<U256> shs;
    shs.resize(by_index.size());
    for (size_t i = 0; i < by_index.size(); ++i) shs[i] = by_index[i].second;
    U256 acc;
    hbf::dot_batch(lam[0].w, shs[0].w, shs.size(), acc.w);
    if (!e.mt_active) {
      // Slot 14 (registry: SIMD combine-kernel wall): the COIN/DECRYPT
      // combine component — Lagrange coefficients + combine-sum — for
      // the HBBFT_TPU_SIMD A/B readout.
      e.prof_cycles[14] += prof_tick() - t0;
      e.prof_count[14]++;
    }
    ts.signature = acc;
    ts.terminated = true;
    parity_out.push_back(sig_parity(acc) ? 1 : 0);
  }

  // ---- SBV ----------------------------------------------------------------

  void sbv_emit(EpochState& st, int proposer, int rnd, MsgType t, bool b) {
    EMsg m;
    m.era = node.era;
    m.epoch = st.epoch;
    m.proposer = proposer;
    m.round = rnd;
    m.type = t;
    m.bval = b ? 1 : 0;
    ops.broadcast(m);
  }

  void sbv_input(EpochState& st, int proposer, int rnd, Sbv& s, bool b,
                 std::vector<uint8_t>& outs) {
    sbv_send_bval(st, proposer, rnd, s, b, outs);
  }

  void sbv_send_bval(EpochState& st, int proposer, int rnd, Sbv& s, bool b,
                     std::vector<uint8_t>& outs) {
    if (s.bval_sent[b]) return;
    s.bval_sent[b] = true;
    sbv_emit(st, proposer, rnd, BA_BVAL, b);
    sbv_handle_bval(st, proposer, rnd, s, node.id, b, outs);
  }

  void sbv_send_aux(EpochState& st, int proposer, int rnd, Sbv& s, bool b,
                    std::vector<uint8_t>& outs) {
    s.aux_sent = true;
    sbv_emit(st, proposer, rnd, BA_AUX, b);
    sbv_handle_aux(st, proposer, rnd, s, node.id, b, outs);
  }

  void sbv_handle_bval(EpochState& st, int proposer, int rnd, Sbv& s,
                       int sender, bool b, std::vector<uint8_t>& outs) {
    if (s.bval_received[b].has(sender)) {
      if (s.termed_bval[b].has(sender)) {
        s.termed_bval[b].clear(sender);
        return;
      }
      ops.fault(sender, F_SBV_DUP_BVAL);
      return;
    }
    s.bval_received[b].add(sender);
    int count = s.bval_received[b].count();
    if (count >= f() + 1 && !s.bval_sent[b])
      sbv_send_bval(st, proposer, rnd, s, b, outs);
    uint8_t bit = b ? 2 : 1;
    if (count >= 2 * f() + 1 && !(s.bin_values & bit)) {
      bool first = s.bin_values == 0;
      s.bin_values |= bit;
      if (first && !s.aux_sent) sbv_send_aux(st, proposer, rnd, s, b, outs);
      sbv_try_output(s, outs);
    }
  }

  void sbv_handle_aux(EpochState& st, int proposer, int rnd, Sbv& s,
                      int sender, bool b, std::vector<uint8_t>& outs) {
    (void)st;
    (void)proposer;
    (void)rnd;
    if (s.aux_received[b].has(sender)) {
      if (s.termed_aux[b].has(sender)) {
        s.termed_aux[b].clear(sender);
        return;
      }
      ops.fault(sender, F_SBV_DUP_AUX);
      return;
    }
    s.aux_received[b].add(sender);
    sbv_try_output(s, outs);
  }

  void sbv_add_term_evidence(EpochState& st, int proposer, int rnd, Sbv& s,
                             int sender, bool b, std::vector<uint8_t>& outs) {
    if (!s.bval_received[b].has(sender)) {
      s.termed_bval[b].add(sender);
      sbv_handle_bval(st, proposer, rnd, s, sender, b, outs);
    }
    if (!s.aux_received[b].has(sender)) {
      s.termed_aux[b].add(sender);
      sbv_handle_aux(st, proposer, rnd, s, sender, b, outs);
    }
  }

  void sbv_try_output(Sbv& s, std::vector<uint8_t>& outs) {
    if (!s.bin_values) return;
    uint8_t vals = 0;
    int count = 0;
    for (int b = 0; b < 2; ++b) {  // BoolSet iterates False then True
      if (!(s.bin_values & (b ? 2 : 1))) continue;
      int senders = s.aux_received[b].count();
      if (senders) {
        vals |= b ? 2 : 1;
        count += senders;
      }
    }
    int all_senders = (s.aux_received[0] | s.aux_received[1]).count();
    if (count > all_senders) count = all_senders;
    if (count >= num_correct() && vals && (int)vals != s.last_output) {
      s.last_output = vals;
      outs.push_back(vals);
    }
  }

  // ---- BinaryAgreement ----------------------------------------------------

  void ba_make_coin(Ba& ba) { ba_make_coin_static(ba); }

  // process SBV outputs at the BA boundary (binary_agreement._wrap)
  void ba_consume_sbv(EpochState& st, int proposer, Ba& ba,
                      std::vector<uint8_t>& outs) {
    for (size_t i = 0; i < outs.size(); ++i) ba_on_sbv_vals(st, proposer, ba);
    outs.clear();
  }

  void ba_on_sbv_vals(EpochState& st, int proposer, Ba& ba) {
    if (!ba.conf_sent) {
      ba.conf_sent = true;
      EMsg m;
      m.era = node.era;
      m.epoch = st.epoch;
      m.proposer = proposer;
      m.round = ba.round;
      m.type = BA_CONF;
      m.bval = ba.sbv.bin_values;
      ops.broadcast(m);
      ba_handle_conf(st, proposer, ba, node.id, ba.sbv.bin_values);
    } else {
      ba_try_start_coin(st, proposer, ba);
    }
  }

  void ba_handle_conf(EpochState& st, int proposer, Ba& ba, int sender,
                      uint8_t vals) {
    if (ba.confs_set.has(sender)) {
      if (!ba.term_confs.has(sender)) ops.fault(sender, F_BA_DUP_CONF);
      return;
    }
    ba.confs_set.add(sender);
    ba.confs.push_back({sender, vals});
    ba_try_start_coin(st, proposer, ba);
  }

  void ba_try_start_coin(EpochState& st, int proposer, Ba& ba) {
    if (ba.coin_requested || !ba.conf_sent) return;
    uint8_t bin = ba.sbv.bin_values;
    int accepted_count = 0;
    uint8_t acc_union = 0;
    for (auto& kv : ba.confs) {
      if ((kv.second & ~bin) == 0) {  // is_subset(bin_values)
        ++accepted_count;
        acc_union |= kv.second;
      }
    }
    if (accepted_count < num_correct()) return;
    ba.coin_requested = true;
    ba.conf_vals = acc_union;
    std::vector<uint8_t> parity_out;
    ts_input(st, proposer, ba, *ba.coin, parity_out);
    for (uint8_t par : parity_out) ba_on_coin(st, proposer, ba, par);
    ba_maybe_advance(st, proposer, ba);
  }

  void ba_on_coin(EpochState& st, int proposer, Ba& ba, uint8_t parity) {
    ba.coin_value = parity ? 1 : 0;
    trace_emit(e, node.id, TR_BA_COIN, node.era, st.epoch, proposer,
               (ba.round << 1) | (parity ? 1 : 0));
    ba_maybe_advance(st, proposer, ba);
  }

  void ba_maybe_advance(EpochState& st, int proposer, Ba& ba) {
    if (ba.terminated || ba.coin_value < 0 || ba.conf_vals < 0) return;
    bool s = ba.coin_value == 1;
    // BoolSet.definite()
    int definite = -1;
    if (ba.conf_vals == 2) definite = 1;
    if (ba.conf_vals == 1) definite = 0;
    if (definite >= 0) {
      if ((definite == 1) == s) {
        ba_decide(st, proposer, ba, definite == 1);
        return;
      }
      ba.estimate = definite;
    } else {
      ba.estimate = s ? 1 : 0;
    }
    ba_next_round(st, proposer, ba);
  }

  void ba_next_round(EpochState& st, int proposer, Ba& ba) {
    ba.round += 1;
    trace_emit(e, node.id, TR_BA_ROUND, node.era, st.epoch, proposer,
               ba.round);
    ba.sbv = Sbv(n(), f());
    ba.conf_sent = false;
    ba.confs.clear();
    ba.confs_set = NodeSet();
    ba.coin_requested = false;
    ba.coin_value = -1;
    ba.conf_vals = -1;
    ba_make_coin(ba);
    std::vector<uint8_t> outs;
    // Terms seed the new round's evidence (Python iterates False, True).
    for (int b = 0; b < 2; ++b) {
      // Python iterates a set of senders — ints ascend (see CPython
      // small-int set iteration note in the engine tests).
      for (int sender = 0; sender < n(); ++sender) {
        if (!ba.terms[b].has(sender)) continue;
        sbv_add_term_evidence(st, proposer, ba.round, ba.sbv, sender, b, outs);
        ba_consume_sbv(st, proposer, ba, outs);
        // Python: confs.setdefault(sender, single(b)); term_confs.add
        // (unconditional) — no conf-threshold re-check here.
        if (!ba.confs_set.has(sender)) {
          ba.confs_set.add(sender);
          ba.confs.push_back({sender, (uint8_t)(b ? 2 : 1)});
        }
        ba.term_confs.add(sender);
      }
    }
    sbv_input(st, proposer, ba.round, ba.sbv, ba.estimate == 1, outs);
    ba_consume_sbv(st, proposer, ba, outs);
    // Replay buffered future-round messages, re-attributing each
    // replayed message's cycles to its own type (Engine::replay_borrow).
    std::vector<std::pair<int, EMsg>> future;
    future.swap(ba.future);
    ba.future_count.clear();
    if (!e.mt_active) {
      // One tick per message (chained: each message's end is the next
      // one's start) — a 2-rdtsc-per-replay version measurably taxed
      // replay-heavy deferred cadences.
      uint64_t t_prev = prof_tick();
      for (auto& sm : future) {
        if (e.in_deferred_flush && sm.second.type == BA_COIN) {
          // A replayed coin share's own work (a group append) already
          // lands in a COIN/DECRYPT continuation stamp: re-attribution
          // would move cycles within the same slot class while paying
          // a tick per message — skip it (the stamps exist for
          // CROSS-type honesty: BVAL/AUX/CONF loads inside coin
          // continuations).
          ba_handle_message(st, proposer, ba, sm.first, sm.second);
          t_prev = prof_tick();
          continue;
        }
        uint64_t b0 = e.replay_borrow;
        ba_handle_message(st, proposer, ba, sm.first, sm.second);
        uint64_t inner = e.replay_borrow - b0;
        uint64_t t_now = prof_tick();
        uint64_t own = t_now - t_prev - inner;
        t_prev = t_now;
        e.prof_cycles[sm.second.type & 15] += own;
        e.replay_borrow = b0 + inner + own;
      }
    } else {
      for (auto& sm : future)
        ba_handle_message(st, proposer, ba, sm.first, sm.second);
    }
  }

  void ba_handle_term(EpochState& st, int proposer, Ba& ba, int sender,
                      bool b) {
    if (ba.term_senders.has(sender)) {
      if (!ba.terms[b].has(sender)) ops.fault(sender, F_BA_DUP_TERM);
      return;
    }
    ba.term_senders.add(sender);
    ba.terms[b].add(sender);
    if (!ba.terminated) {
      if (ba.terms[b].count() >= f() + 1) {
        ba_decide(st, proposer, ba, b);
        return;
      }
      std::vector<uint8_t> outs;
      sbv_add_term_evidence(st, proposer, ba.round, ba.sbv, sender, b, outs);
      ba_consume_sbv(st, proposer, ba, outs);
      if (!ba.confs_set.has(sender)) {
        ba.term_confs.add(sender);
        ba_handle_conf(st, proposer, ba, sender, b ? 2 : 1);
      }
    }
  }

  void ba_decide(EpochState& st, int proposer, Ba& ba, bool b) {
    if (ba.terminated) return;
    ba.decision = b ? 1 : 0;
    ba.terminated = true;
    trace_emit(e, node.id, TR_BA_DECIDE, node.era, st.epoch, proposer,
               (ba.round << 1) | (b ? 1 : 0));
    EMsg m;
    m.era = node.era;
    m.epoch = st.epoch;
    m.proposer = proposer;
    m.round = ba.round;
    m.type = BA_TERM;
    m.bval = b ? 1 : 0;
    ops.broadcast(m);
    subset_on_ba_decision(st, proposer, b);
  }

  void ba_input(EpochState& st, int proposer, Ba& ba, bool input) {
    if (ba.estimate >= 0 || ba.terminated) return;
    ba.estimate = input ? 1 : 0;
    // Round-16 stall diagnosis: a BA instance stuck at round 0 emits no
    // TR_BA_ROUND (that fires on advance) — this is the "BA started"
    // marker.  Mirrors the Python arm's "ba.input" milestone.
    trace_emit(e, node.id, TR_BA_INPUT, node.era, st.epoch, proposer,
               (ba.round << 1) | (input ? 1 : 0));
    std::vector<uint8_t> outs;
    sbv_input(st, proposer, ba.round, ba.sbv, input, outs);
    ba_consume_sbv(st, proposer, ba, outs);
  }

  void ba_handle_message(EpochState& st, int proposer, Ba& ba, int sender,
                         const EMsg& m) {
    if (m.type == BA_TERM) {
      ba_handle_term(st, proposer, ba, sender, m.bval != 0);
      return;
    }
    if (ba.terminated) return;
    if (m.round < ba.round) return;  // stale: drop
    if (m.round > ba.round) {
      if (m.round - ba.round <= MAX_FUTURE_ROUNDS) {
        // The per-sender cap (4 * MAX_FUTURE_ROUNDS) cannot bind while
        // the WHOLE buffer holds fewer entries than the cap, so the
        // honest path skips the per-sender map entirely (a map op per
        // buffered share taxed the deferred RLC cadence, where rounds
        // advance at flush and most coin traffic buffers).  Crossing
        // the threshold rebuilds exact counts from the buffer — every
        // entry was admitted unconditionally below it — so the drop
        // decisions are identical to counting from the start.
        size_t cap = (size_t)(4 * MAX_FUTURE_ROUNDS);
        if (ba.future.size() < cap) {
          ba.future.push_back({sender, m});
        } else {
          if (ba.future_count.empty())
            for (auto& sm : ba.future)
              ba.future_count.ref(node.arena, e.n, sm.first)++;
          int32_t& cnt = ba.future_count.ref(node.arena, e.n, sender);
          if (cnt < (int32_t)cap) {
            ++cnt;
            ba.future.push_back({sender, m});
          }
        }
      }
      return;
    }
    std::vector<uint8_t> outs;
    switch (m.type) {
      case BA_BVAL:
        sbv_handle_bval(st, proposer, m.round, ba.sbv, sender, m.bval != 0,
                        outs);
        ba_consume_sbv(st, proposer, ba, outs);
        break;
      case BA_AUX:
        sbv_handle_aux(st, proposer, m.round, ba.sbv, sender, m.bval != 0,
                       outs);
        ba_consume_sbv(st, proposer, ba, outs);
        break;
      case BA_CONF:
        ba_handle_conf(st, proposer, ba, sender, m.bval);
        break;
      case BA_COIN: {
        std::vector<uint8_t> parity_out;
        ts_handle_share(st, proposer, ba, ba.coin, sender, m, parity_out);
        for (uint8_t par : parity_out) ba_on_coin(st, proposer, ba, par);
        break;
      }
      default:
        break;
    }
  }

  // ---- Subset -------------------------------------------------------------
  //
  // Subset outputs (contribution / done) are APPENDED to the epoch
  // state's pending list and drained only at the honey-badger boundary
  // (hb_drain_subset_outputs) — mirroring Python, where
  // Subset._progress appends to the step and HoneyBadger's
  // _on_subset_step (under _guard_epoch) processes the accumulated
  // outputs after the complete subset-level call.  Draining inline
  // would reorder verify-pool submissions (decrypt vs coin shares).

  void subset_input(EpochState& st, const BytesP& payload) {
    if (st.subset_terminated) return;
    bc_input(st, node.id, st.proposals[node.id].bc, payload);
  }

  void subset_handle_message(EpochState& st, int sender, const EMsg& m) {
    if (st.subset_terminated) return;
    if (m.proposer < 0 || m.proposer >= e.n || !is_val(m.proposer)) {
      ops.fault(sender, F_SS_UNKNOWN);
      return;
    }
    Proposal& prop = st.proposals[m.proposer];
    switch (m.type) {
      case BC_VALUE:
      case BC_ECHO:
      case BC_READY:
      case BC_ECHO_HASH:
      case BC_CAN_DECODE:
        bc_handle_message(st, m.proposer, prop.bc, sender, m);
        break;
      default:
        ba_handle_message(st, m.proposer, prop.ba, sender, m);
        break;
    }
  }

  // Broadcast delivered a value for this proposer (subset._on_bc_step).
  void subset_on_bc_value(EpochState& st, int proposer, const BytesP& value) {
    Proposal& prop = st.proposals[proposer];
    if (!prop.value) {
      prop.value = value;
      trace_emit(e, node.id, TR_RBC_DELIVER, node.era, st.epoch, proposer, 0);
      ba_input(st, proposer, prop.ba, true);
    }
    subset_progress(st, proposer);
  }

  // BA decided for this proposer (subset._on_ba_step reaction).  Runs
  // inline at the decide point: the deciding BA is terminated, so no
  // further emissions/pool submissions occur between the Python-deferred
  // point and here (see ba_decide).
  void subset_on_ba_decision(EpochState& st, int proposer, bool decision) {
    Proposal& prop = st.proposals[proposer];
    if (prop.decision < 0) {
      prop.decision = decision ? 1 : 0;
      subset_after_decision(st);
    }
    subset_progress(st, proposer);
  }

  void subset_after_decision(EpochState& st) {
    int accepted = 0;
    for (int pid : node.val_ids)
      if (st.proposals[pid].decision == 1) ++accepted;
    if (accepted < num_correct()) return;
    for (int pid : node.val_ids) {  // insertion order == sorted all_ids
      Proposal& p = st.proposals[pid];
      if (p.decision < 0 && !p.ba.terminated) ba_input(st, pid, p.ba, false);
    }
  }

  void subset_progress(EpochState& st, int proposer) {
    if (st.subset_terminated) return;
    Proposal& prop = st.proposals[proposer];
    if (prop.decision == 1 && prop.value && !prop.emitted) {
      prop.emitted = true;
      st.pending_outputs.push_back({false, proposer, prop.value});
    }
    bool all_decided = true, all_done = true;
    for (int pid : node.val_ids) {
      Proposal& p = st.proposals[pid];
      if (p.decision < 0) all_decided = false;
      if (!(p.emitted || p.decision == 0)) all_done = false;
    }
    if (all_decided && all_done && !st.done_emitted) {
      st.done_emitted = true;
      st.subset_terminated = true;
      st.pending_outputs.push_back({true, 0, nullptr});
    }
  }

  // ---- Broadcast ----------------------------------------------------------

  void bc_send_root(EpochState& st, int proposer, MsgType t, const Root& root,
                    int dest /* -1 broadcast */) {
    EMsg m;
    m.era = node.era;
    m.epoch = st.epoch;
    m.proposer = proposer;
    m.type = t;
    m.root = root;
    if (dest < 0)
      ops.broadcast(m);
    else
      ops.send(dest, m);
  }

  void bc_input(EpochState& st, int proposer, Bcast& bc, const BytesP& value) {
    if (node.id != bc.proposer || bc.had_input) return;
    bc.had_input = true;
    int k = bc.data_shards;
    std::vector<Bytes> shards = rbc_pack(*value, k, rs_align(n()));
    // RS parity over the VALIDATOR count (shards are per validator index)
    size_t size = shards[0].size();
    std::vector<uint8_t> data(k * size);
    for (int i = 0; i < k; ++i)
      std::memcpy(data.data() + i * size, shards[i].data(), size);
    std::vector<uint8_t> parity;
    bool enc_ok = rs_encode_rows(k, n(), data.data(), size, parity);
    assert(enc_ok);
    (void)enc_ok;
    for (int i = k; i < n(); ++i)
      shards.push_back(
          Bytes((const char*)parity.data() + (size_t)(i - k) * size, size));
    // Merkle tree over n() (validator-count) leaves + per-validator
    // proofs — leaf and branch levels go through the batched sha3 plane
    // (padding leaves all hash the same empty shard: one digest, copied).
    int depth = merkle_depth(n());
    int tree_size = 1 << depth;
    std::vector<std::vector<Root>> levels(1);
    levels[0].resize(tree_size);
    {
      std::vector<const uint8_t*> ptrs(n());
      for (int i = 0; i < n(); ++i) ptrs[i] = (const uint8_t*)shards[i].data();
      merkle_leaves_hash(ptrs.data(), size, n(), levels[0].data());
    }
    if (n() < tree_size) {
      Root pad = merkle_leaf_hash(Bytes());
      for (int i = n(); i < tree_size; ++i) levels[0][i] = pad;
    }
    while ((int)levels.back().size() > 1) {
      const std::vector<Root>& prev = levels.back();
      std::vector<Root> next(prev.size() / 2);
      merkle_reduce_level(prev.data(), next.size(), next.data());
      levels.push_back(std::move(next));
    }
    Root root = levels.back()[0];
    // netinfo.all_ids order: sorted validator ids; shard index = val index.
    for (int vi = 0; vi < n(); ++vi) {
      int nid = node.val_ids[vi];
      auto proof = std::make_shared<ProofData>();
      proof->value = shards[vi];
      proof->index = vi;
      int idx = vi;
      for (size_t lv = 0; lv + 1 < levels.size(); ++lv) {
        proof->path.push_back(levels[lv][idx ^ 1]);
        idx >>= 1;
      }
      proof->root = root;
      if (nid == node.id) {
        bc_handle_value(st, proposer, bc, node.id, proof);
      } else {
        EMsg m;
        m.era = node.era;
        m.epoch = st.epoch;
        m.proposer = proposer;
        m.type = BC_VALUE;
        m.proof = proof;
        ops.send(nid, m);
      }
    }
  }

  void bc_handle_message(EpochState& st, int proposer, Bcast& bc, int sender,
                         const EMsg& m) {
    if (bc.terminated) return;
    if (!is_val(sender)) {
      ops.fault(sender, F_BC_NOT_PROPOSER);
      return;
    }
    switch (m.type) {
      case BC_VALUE:
        if (sender != bc.proposer) {
          ops.fault(sender, F_BC_NOT_PROPOSER);
          return;
        }
        bc_handle_value(st, proposer, bc, sender, m.proof);
        return;
      case BC_ECHO:
        bc_handle_echo(st, proposer, bc, sender, m.proof);
        return;
      case BC_READY:
        bc_handle_ready(st, proposer, bc, sender, m.root);
        return;
      case BC_ECHO_HASH:
        bc_handle_echo_hash(st, proposer, bc, sender, m.root);
        return;
      case BC_CAN_DECODE:
        bc_handle_can_decode(st, proposer, bc, sender, m.root);
        return;
      default:
        return;
    }
  }

  void bc_handle_value(EpochState& st, int proposer, Bcast& bc, int sender,
                       std::shared_ptr<const ProofData> proof) {
    if (bc.echo_sent) {
      const ProofData* const* it = bc.echos.find(node.id);
      if (it && proof->root != (*it)->root)
        ops.fault(sender, F_BC_MULTI_VALUE);
      return;
    }
    if (proof->index != node.val_index[node.id] ||
        !proof_validate(*proof, n())) {
      ops.fault(sender, F_BC_INVALID_PROOF);
      return;
    }
    bc.echo_sent = true;
    trace_emit(e, node.id, TR_RBC_VALUE, node.era, st.epoch, proposer, 0);
    // Full Echo to everyone except CanDecode-declared peers; hash-only
    // Echo to those (broadcast.py _handle_value).
    NodeSet hash_only;
    bool any_hash_only = false;
    for (int i = 0; i < bc.can_decode.cap; ++i)
      if (bc.can_decode.has(i) && bc.can_decode.v[i] == proof->root) {
        hash_only.add(i);
        any_hash_only = true;
      }
    EMsg em;
    em.era = node.era;
    em.epoch = st.epoch;
    em.proposer = proposer;
    em.type = BC_ECHO;
    em.proof = proof;
    ops.broadcast_except(em, hash_only);
    if (any_hash_only) {
      EMsg hm;
      hm.era = node.era;
      hm.epoch = st.epoch;
      hm.proposer = proposer;
      hm.type = BC_ECHO_HASH;
      hm.root = proof->root;
      ops.send_nodes(hm, hash_only);
    }
    bc_handle_echo(st, proposer, bc, node.id, proof);
  }

  // Distinct senders per root via the incremental tally (a sender may
  // appear in BOTH echos and echo_hashes for the same root — the
  // EchoHash-then-full-Echo order — and is tallied once; see the
  // hit-guarded bump in bc_handle_echo).
  int bc_echo_count(const Bcast& bc, const Root& root) {
    return Bcast::tally(bc.echo_any_by_root, root);
  }

  void bc_handle_echo(EpochState& st, int proposer, Bcast& bc, int sender,
                      std::shared_ptr<const ProofData> proof) {
    const ProofData* const* it = bc.echos.find(sender);
    if (it) {
      const ProofData& prev = **it;
      if (!(prev.value == proof->value && prev.index == proof->index &&
            prev.path == proof->path && prev.root == proof->root))
        ops.fault(sender, F_BC_DUP);
      return;
    }
    if (proof->index != node.val_index[sender]) {
      ops.fault(sender, F_BC_WRONG_INDEX);
      return;
    }
    if (!proof_validate(*proof, n())) {
      ops.fault(sender, F_BC_INVALID_PROOF);
      return;
    }
    const Root* hit = bc.echo_hashes.find(sender);
    if (hit && *hit != proof->root) {
      ops.fault(sender, F_BC_DUP);
      return;
    }
    bc.echos.ref(node.arena, e.n, sender) = proof.get();
    node.epoch_pins.push_back(proof);  // epoch-long ownership (arena note)
    Bcast::bump(bc.echo_full_by_root, proof->root);
    // A same-root EchoHash from this sender was already tallied in
    // echo_any_by_root (the union count de-duplicates senders).
    if (!hit)
      Bcast::bump(bc.echo_any_by_root, proof->root);
    bc_maybe_can_decode(st, proposer, bc, proof->root);
    if (bc_echo_count(bc, proof->root) >= n() - f() && !bc.ready_sent)
      bc_send_ready(st, proposer, bc, proof->root);
    bc_try_decode(st, proposer, bc);
  }

  void bc_handle_echo_hash(EpochState& st, int proposer, Bcast& bc, int sender,
                           const Root& root) {
    const Root* eh = bc.echo_hashes.find(sender);
    const ProofData* const* ec = bc.echos.find(sender);
    if (eh || ec) {
      Root prev = eh ? *eh : (*ec)->root;
      if (prev != root) ops.fault(sender, F_BC_DUP);
      return;
    }
    bc.echo_hashes.ref(node.arena, e.n, sender) = root;
    Bcast::bump(bc.echo_any_by_root, root);
    if (bc_echo_count(bc, root) >= n() - f() && !bc.ready_sent)
      bc_send_ready(st, proposer, bc, root);
    bc_try_decode(st, proposer, bc);
  }

  void bc_handle_can_decode(EpochState& st, int proposer, Bcast& bc,
                            int sender, const Root& root) {
    (void)st;
    (void)proposer;
    const Root* it = bc.can_decode.find(sender);
    if (it) {
      if (*it != root) ops.fault(sender, F_BC_DUP);
      return;
    }
    bc.can_decode.ref(node.arena, e.n, sender) = root;
  }

  void bc_maybe_can_decode(EpochState& st, int proposer, Bcast& bc,
                           const Root& root) {
    if (bc.can_decode_sent || bc.terminated) return;
    if (!node.has_share) return;  // observers stay silent (is_validator)
    // Full-proof echos carry distinct shard indices (wrong-index echos
    // are faulted before insertion), so the per-root echo tally IS the
    // distinct-shard count.
    if (Bcast::tally(bc.echo_full_by_root, root) >= bc.data_shards) {
      bc.can_decode_sent = true;
      bc_send_root(st, proposer, BC_CAN_DECODE, root, -1);
    }
  }

  void bc_handle_ready(EpochState& st, int proposer, Bcast& bc, int sender,
                       const Root& root) {
    const Root* it = bc.readys.find(sender);
    if (it) {
      if (*it != root) ops.fault(sender, F_BC_DUP);
      return;
    }
    bc.readys.ref(node.arena, e.n, sender) = root;
    int count = Bcast::bump(bc.ready_by_root, root);
    if (count == 1) bc.ready_root_order.push_back(root);
    if (count >= f() + 1 && !bc.ready_sent)
      bc_send_ready(st, proposer, bc, root);
    bc_try_decode(st, proposer, bc);
  }

  void bc_send_ready(EpochState& st, int proposer, Bcast& bc,
                     const Root& root) {
    bc.ready_sent = true;
    trace_emit(e, node.id, TR_RBC_READY, node.era, st.epoch, proposer, 0);
    bc_send_root(st, proposer, BC_READY, root, -1);
    bc_handle_ready(st, proposer, bc, node.id, root);
  }

  void bc_try_decode(EpochState& st, int proposer, Bcast& bc) {
    if (bc.terminated) return;
    // Counter(readys.values()) iterates distinct roots in first-seen order.
    for (const Root& root : bc.ready_root_order) {
      if (Bcast::tally(bc.ready_by_root, root) < 2 * f() + 1) continue;
      // Cheap tally gate before walking echos: distinct shard indices
      // per root == full-echo count (see bc_maybe_can_decode).
      if (Bcast::tally(bc.echo_full_by_root, root) < bc.data_shards)
        continue;
      // Reference the shard bytes in place — materializing copies on
      // every decode attempt dominated big-payload (DKG) epochs.
      std::map<int, const Bytes*> shards;  // index -> value (last write wins)
      // Ascending sender-id walk == the old map's ascending-key walk,
      // so "last write wins" resolves identically per shard index.
      for (int s = 0; s < bc.echos.cap; ++s) {
        if (!bc.echos.has(s)) continue;
        const ProofData* pd = bc.echos.v[s];
        if (pd->root == root) shards[pd->index] = &pd->value;
      }
      if ((int)shards.size() < bc.data_shards) continue;
      // Network-wide decode cache (see Engine::decoded_roots).
      {
        BytesP cached;
        {
          std::lock_guard<std::mutex> lk(e.cache_mu);
          auto hit = e.decoded_roots.find(root);
          if (hit != e.decoded_roots.end()) cached = hit->second;
        }
        if (cached) {
          bc.value = cached;
          bc.terminated = true;
          subset_on_bc_value(st, proposer, bc.value);
          return;
        }
      }
      size_t len0 = SIZE_MAX;
      bool equal_len = true;
      for (auto& kv : shards) {
        if (len0 == SIZE_MAX) len0 = kv.second->size();
        else if (kv.second->size() != len0) equal_len = false;
      }
      if (!equal_len) {
        bc.terminated = true;
        ops.fault(bc.proposer, F_BC_BAD_ENC);
        return;
      }
      // reconstruct data shards then re-encode the FULL codeword
      int k = bc.data_shards;
      std::vector<uint64_t> idxs;
      std::vector<uint8_t> have;
      have.reserve((size_t)k * len0);
      for (auto& kv : shards) {
        if ((int)idxs.size() == k) break;
        idxs.push_back(kv.first);
        have.insert(have.end(), kv.second->begin(), kv.second->end());
      }
      std::vector<uint8_t> data;
      if (!rs_reconstruct_rows(k, n(), idxs, have.data(), len0, data)) {
        bc.terminated = true;
        ops.fault(bc.proposer, F_BC_BAD_ENC);
        return;
      }
      // re-encode full codeword + re-hash the tree
      std::vector<uint8_t> parity;
      if (!rs_encode_rows(k, n(), data.data(), len0, parity)) {
        bc.terminated = true;
        ops.fault(bc.proposer, F_BC_BAD_ENC);
        return;
      }
      int depth = merkle_depth(n());
      int tree_size = 1 << depth;
      // batched sha3 plane: leaf level straight off the decoded rows (no
      // per-shard Bytes copies), branch levels as contiguous batches.
      std::vector<Root> level(tree_size);
      {
        std::vector<const uint8_t*> ptrs(n());
        for (int i = 0; i < n(); ++i)
          ptrs[i] = i < k ? data.data() + (size_t)i * len0
                          : parity.data() + (size_t)(i - k) * len0;
        merkle_leaves_hash(ptrs.data(), len0, n(), level.data());
      }
      if (n() < tree_size) {
        Root pad = merkle_leaf_hash(Bytes());
        for (int i = n(); i < tree_size; ++i) level[i] = pad;
      }
      while (level.size() > 1) {
        std::vector<Root> next(level.size() / 2);
        merkle_reduce_level(level.data(), next.size(), next.data());
        level = std::move(next);
      }
      if (level[0] != root) {
        bc.terminated = true;
        ops.fault(bc.proposer, F_BC_BAD_ENC);
        return;
      }
      std::vector<Bytes> data_shards;
      for (int i = 0; i < k; ++i)
        data_shards.push_back(Bytes((const char*)data.data() + (size_t)i * len0, len0));
      Bytes value;
      if (!rbc_unpack(data_shards, value)) {
        bc.terminated = true;
        ops.fault(bc.proposer, F_BC_BAD_ENC);
        return;
      }
      BytesP vp = std::make_shared<const Bytes>(std::move(value));
      {
        std::lock_guard<std::mutex> lk(e.cache_mu);
        e.decoded_roots.emplace(root, vp);
        e.decoded_order.push_back(root);
        if (e.decoded_order.size() > DECODED_ROOTS_MAX) {
          e.decoded_roots.erase(e.decoded_order.front());
          e.decoded_order.pop_front();
        }
      }
      bc.value = vp;
      bc.terminated = true;
      subset_on_bc_value(st, proposer, vp);
      return;
    }
  }

  // ---- ThresholdDecrypt ---------------------------------------------------

  std::shared_ptr<Td> hb_get_decrypt(EpochState& st, int proposer) {
    std::shared_ptr<Td>& slot = st.decrypts[proposer];
    if (!slot) slot = std::make_shared<Td>();
    return slot;
  }

  // hash_to_g2 of the ct hash input, once per distinct committed
  // payload network-wide (Engine::ct_hash_by_payload notes).  The
  // heavy sha3 runs OUTSIDE the lock; a concurrent double-compute is
  // harmless (pure function, first emplace wins).
  U256 ct_hash_cached(const BytesP& payload, const ScalarCiphertext& ct) {
    if (!e.ct_hash_cache) return ct_hash_scalar(ct);
    {
      std::lock_guard<std::mutex> lk(e.cache_mu);
      auto it = e.ct_hash_by_payload.find(payload.get());
      if (it != e.ct_hash_by_payload.end()) return it->second.second;
    }
    U256 h = ct_hash_scalar(ct);
    std::lock_guard<std::mutex> lk(e.cache_mu);
    auto ins = e.ct_hash_by_payload.emplace(
        payload.get(), std::make_pair(payload, h));
    if (ins.second) {
      e.ct_hash_order.push_back(payload.get());
      if (e.ct_hash_order.size() > CT_HASH_CACHE_MAX) {
        e.ct_hash_by_payload.erase(e.ct_hash_order.front());
        e.ct_hash_order.pop_front();
      }
    }
    return h;
  }

  void td_handle_input(EpochState& st, int proposer, std::shared_ptr<Td> td,
                       const ScalarCiphertext& ct, const BytesP& payload) {
    if (td->has_ct || td->terminated) return;
    td->has_ct = true;
    td->ct = ct;
    td->ct_h = ct_hash_cached(payload, ct);
    Pending p;
    p.cont = CONT_TD_CT;
    p.era = node.era;
    p.epoch = st.epoch;
    p.proposer = proposer;
    p.td = td;
    p.pre_ok = td->ct.w == mulmod(td->ct.u, td->ct_h);  // validity pairing
    pool_push(e, node, std::move(p));
  }

  // External mode: the payload already passed the Python-side serde
  // decode gate (ct_parse_cb); validity is a deferred VK_CT request.
  void td_handle_input_ext(EpochState& st, int proposer,
                           std::shared_ptr<Td> td, const BytesP& payload) {
    if (td->has_ct || td->terminated) return;
    td->has_ct = true;
    td->ct_payload = payload;
    Pending p;
    p.cont = CONT_TD_CT;
    p.era = node.era;
    p.epoch = st.epoch;
    p.proposer = proposer;
    p.td = td;
    p.need_verdict = true;
    p.req.kind = VK_CT;
    p.req.era = p.era;
    p.req.ct = td->ct_payload.get();  // Td kept alive by p.td
    pool_push(e, node, std::move(p));
  }

  void td_ct_checked_cb(int era, int epoch, int proposer,
                        std::shared_ptr<Td> td, bool ok) {
    bool live = node.era == era && node.hb_init && node.hb.epoch == epoch;
    if (!live) node.suppress_emit++;
    std::vector<BytesP> plain_out;
    // inner: ThresholdDecrypt._on_ciphertext_checked
    if (!td->terminated) {
      if (!ok) {
        td->ciphertext_invalid = true;
        td->terminated = true;
      } else {
        td->ct_valid = true;
        if (node.has_share) {
          EMsg m;
          m.era = era;
          m.epoch = epoch;
          m.proposer = proposer;
          m.type = HB_DECRYPT;
          td->seen.add(node.id);
          if (e.ext) {
            auto share_b = std::make_shared<Bytes>();
            e.sign_cb(node.id, era, 1,
                      (const uint8_t*)td->ct_payload->data(),
                      td->ct_payload->size(), share_b.get());
            m.share_b = share_b;
            td->verified_b.push_back({node.id, *share_b});
          } else {
            U256 share = mulmod(td->ct.u, node.sk_share);
            m.share = share;
            td->verified.push_back({node.id, share});
          }
          td->verified_set.add(node.id);
          ops.broadcast(m);
        }
        if (e.ext) {
          std::vector<std::pair<int, Bytes>> buffered;
          buffered.swap(td->buffered_b);
          for (auto& kv : buffered)
            td_submit_share_ext(era, epoch, proposer, td, kv.first,
                                std::make_shared<const Bytes>(std::move(kv.second)));
        } else {
          std::vector<std::pair<int, U256>> buffered;
          buffered.swap(td->buffered);
          for (auto& kv : buffered)
            td_submit_share(era, epoch, proposer, td, kv.first, kv.second);
        }
        td_try_output(*td, plain_out);
      }
    }
    if (live) {
      hb_on_decrypt_boundary(proposer, td, plain_out);
      hb_advance();
    }
    if (!live) node.suppress_emit--;
  }

  void td_submit_share(int era, int epoch, int proposer, std::shared_ptr<Td> td,
                       int sender, const U256& share) {
    Pending p;
    p.cont = CONT_TD_SHARE;
    p.era = era;
    p.epoch = epoch;
    p.proposer = proposer;
    p.sender = sender;
    p.td = td;
    p.share = share;
    if (e.rlc && scalar_deferred(e)) {
      // Round-7 deferred RLC path (see ts_handle_share): submit-time
      // group on the Td's leader Pending; flush check is the two-sided
      // Σ rᵢ·shareᵢ·ct_h == (Σ rᵢ·pkᵢ)·ct_w.
      if (td->grp_round == node.pool_round && td->grp_idx >= 0) {
        node.pool[td->grp_idx].grp.push_back(
            {share, node.pk_shares[sender], sender, 0});
        return;
      }
      p.rlc_defer = true;
      p.grp.push_back({share, node.pk_shares[sender], sender, 0});
      td->grp_round = node.pool_round;
      td->grp_idx = (int32_t)node.pool.size();
    } else if (e.rlc) {
      p.rlc_defer = true;
      p.pk = node.pk_shares[sender];
    } else {
      p.pre_ok =
          mulmod(share, td->ct_h) == mulmod(node.pk_shares[sender], td->ct.w);
    }
    pool_push(e, node, std::move(p));
  }

  void td_submit_share_ext(int era, int epoch, int proposer,
                           std::shared_ptr<Td> td, int sender,
                           std::shared_ptr<const Bytes> share_b) {
    Pending p;
    p.cont = CONT_TD_SHARE;
    p.era = era;
    p.epoch = epoch;
    p.proposer = proposer;
    p.sender = sender;
    p.td = td;
    p.share_b = share_b;
    p.need_verdict = true;
    p.req.kind = VK_DEC;
    p.req.era = era;
    p.req.sender = sender;
    p.req.ct = td->ct_payload.get();
    p.req.share = p.share_b;
    pool_push(e, node, std::move(p));
  }

  // mirror: td-acceptance-item (twin: threshold_decrypt.handle_message /
  //     _on_verified — acceptance-rule changes land on BOTH sides)
  void td_verified_cb(int era, int epoch, int proposer, std::shared_ptr<Td> td,
                      int sender, const U256& share,
                      std::shared_ptr<const Bytes> share_b, bool ok) {
    bool live = node.era == era && node.hb_init && node.hb.epoch == epoch;
    if (!live) node.suppress_emit++;
    std::vector<BytesP> plain_out;
    if (!td->terminated) {  // Python: terminated check BEFORE the ok check
      if (!ok) {
        ops.fault(sender, F_TD_INVALID);
      } else {
        if (e.ext)
          td->verified_b.push_back({sender, *share_b});
        else
          td->verified.push_back({sender, share});
        td->verified_set.add(sender);
        td_try_output(*td, plain_out);
      }
    }
    if (live) {
      hb_on_decrypt_boundary(proposer, td, plain_out);
      hb_advance();
    }
    if (!live) node.suppress_emit--;
  }

  // Folded continuation for a deferred RLC GROUP of same-Td decryption
  // shares — the ThresholdDecrypt twin of ts_group_verified_cb (same
  // no-op argument: pre-termination lifts see an empty plain_out and a
  // valid ciphertext, post-termination items are skipped entirely).
  // mirror: td-acceptance-group (twin: threshold_decrypt._on_verified —
  //     acceptance-rule changes land on BOTH continuations)
  void td_group_verified_cb(int era, int epoch, int proposer,
                            const std::shared_ptr<Td>& td, Pending& lead) {
    size_t count = lead.grp.size(), vlim = 0;
    bool live = node.era == era && node.hb_init && node.hb.epoch == epoch;
    if (!live) node.suppress_emit++;
    std::vector<BytesP> plain_out;
    for (size_t k = 0; k < count; ++k) {
      if (td->terminated) break;
      if (k >= vlim) vlim = lead_verify_chunk(lead, k);
      const RlcShare& sh = lead.grp[k];
      if (!sh.ok) {
        ops.fault(sh.sender, F_TD_INVALID);
        continue;
      }
      td->verified.push_back({sh.sender, sh.share});
      td->verified_set.add(sh.sender);
      td_try_output(*td, plain_out);
    }
    if (live) {
      hb_on_decrypt_boundary(proposer, td, plain_out);
      hb_advance();
    }
    if (!live) node.suppress_emit--;
  }

  void td_handle_message(EpochState& st, int proposer, std::shared_ptr<Td> td,
                         int sender, const EMsg& m) {
    if (td->terminated) return;
    if (!is_val(sender)) {
      ops.fault(sender, F_TD_NONVAL);
      return;
    }
    if (td->seen.has(sender)) {
      ops.fault(sender, F_TD_DUP);
      return;
    }
    td->seen.add(sender);
    if (e.ext) {
      std::shared_ptr<const Bytes> share_b =
          m.share_b ? m.share_b : std::make_shared<const Bytes>();
      if (td->ct_valid) {
        td_submit_share_ext(node.era, st.epoch, proposer, td, sender, share_b);
      } else {
        td->buffered_b.push_back({sender, *share_b});
      }
      return;
    }
    if (td->ct_valid) {
      td_submit_share(node.era, st.epoch, proposer, td, sender, m.share);
    } else {
      td->buffered.push_back({sender, m.share});
    }
  }

  void td_try_output(Td& td, std::vector<BytesP>& plain_out) {
    int threshold = f();
    size_t have = e.ext ? td.verified_b.size() : td.verified.size();
    if (td.terminated || (int)have < threshold + 1) return;
    if (e.ext) {
      std::vector<std::pair<int, const Bytes*>> by_index;
      for (auto& kv : td.verified_b)
        by_index.push_back({node.val_index[kv.first], &kv.second});
      std::sort(by_index.begin(), by_index.end(),
                [](auto& a, auto& b) { return a.first < b.first; });
      by_index.resize(threshold + 1);
      e.cur_comb.clear();
      for (auto& kv : by_index) e.cur_comb.push_back({kv.first, kv.second});
      Bytes plain;
      e.combine_cb(node.id, node.era, 1,
                   (const uint8_t*)td.ct_payload->data(),
                   td.ct_payload->size(), (int32_t)e.cur_comb.size(), &plain);
      e.cur_comb.clear();
      BytesP pp = std::make_shared<const Bytes>(std::move(plain));
      td.plaintext = pp;
      td.terminated = true;
      plain_out.push_back(std::move(pp));
      return;
    }
    std::vector<std::pair<int, U256>> by_index;
    by_index.reserve(td.verified.size());
    for (auto& kv : td.verified)
      by_index.push_back({node.val_index[kv.first], kv.second});
    std::sort(by_index.begin(), by_index.end(),
              [](auto& a, auto& b) { return a.first < b.first; });
    by_index.resize(threshold + 1);
    std::vector<int> idxs;
    idxs.reserve(by_index.size());
    for (auto& kv : by_index) idxs.push_back(kv.first);
    // shared_ptr held across the sum — see ts_try_output's combine.
    uint64_t tk0 = prof_tick();
    std::shared_ptr<const std::vector<U256>> lam_p = lagrange_cached(idxs);
    const std::vector<U256>& lam = *lam_p;
    // thread_local scratch inside the timed window — see ts_try_output.
    static thread_local std::vector<U256> shs;
    shs.resize(by_index.size());
    for (size_t i = 0; i < by_index.size(); ++i) shs[i] = by_index[i].second;
    U256 acc;
    hbf::dot_batch(lam[0].w, shs[0].w, shs.size(), acc.w);
    if (!e.mt_active) {
      // Slot 14 (registry: SIMD combine-kernel wall) — see ts_try_output.
      e.prof_cycles[14] += prof_tick() - tk0;
      e.prof_count[14]++;
    }
    uint8_t acc_be[32];
    u256_to_be32(acc, acc_be);
    Root key;
    std::memcpy(key.data(), acc_be, 32);
    size_t need = td.ct.v.size();
    Bytes mt_mask_copy;  // multicore: hold a copy (eviction can race)
    const Bytes* mask_p = nullptr;
    {
      std::lock_guard<std::mutex> lk(e.cache_mu);
      auto it = e.mask_by_acc.find(key);
      if (it == e.mask_by_acc.end() || it->second.size() < need) {
        Bytes seed = canon2("kem", Bytes((const char*)acc_be, 32));
        Bytes mask = kdf_stream(seed, need);
        if (it == e.mask_by_acc.end()) {
          it = e.mask_by_acc.emplace(key, std::move(mask)).first;
          e.mask_order.push_back(key);
          if (e.mask_order.size() > MASK_CACHE_MAX) {
            e.mask_by_acc.erase(e.mask_order.front());
            e.mask_order.pop_front();
          }
        } else {
          it->second = std::move(mask);
        }
      }
      if (e.mt_active) {
        mt_mask_copy = it->second;
        mask_p = &mt_mask_copy;
      } else {
        mask_p = &it->second;  // single-thread: no eviction can intervene
      }
    }
    const Bytes& mask = *mask_p;
    Bytes plain = td.ct.v;
    // word-wise XOR via raw pointers (the indexed std::string loop
    // cannot vectorize and dominated big-ciphertext combines)
    char* p = &plain[0];
    const char* m = mask.data();
    size_t sz = plain.size(), i = 0;
    for (; i + 8 <= sz; i += 8) {
      uint64_t a, b;
      std::memcpy(&a, p + i, 8);
      std::memcpy(&b, m + i, 8);
      a ^= b;
      std::memcpy(p + i, &a, 8);
    }
    for (; i < sz; ++i) p[i] ^= m[i];
    BytesP pp = std::make_shared<const Bytes>(std::move(plain));
    td.plaintext = pp;
    td.terminated = true;
    plain_out.push_back(std::move(pp));
  }

  // ---- HoneyBadger epoch state / advance ----------------------------------

  // honey_badger._EpochState._on_decrypt_step: ciphertext_invalid check
  // then plaintext outputs -> _accept_plaintext.  Runs only when the
  // (era, epoch) is live (the _guard_epoch wrap).
  void hb_on_decrypt_boundary(int proposer, std::shared_ptr<Td> td,
                              std::vector<BytesP>& plain_out) {
    EpochState& st = node.hb.state;
    if (!plain_out.empty())
      trace_emit(e, node.id, TR_DECRYPT_DONE, node.era, st.epoch, proposer, 0);
    if (td->ciphertext_invalid && !st.faulty_proposers.has(proposer)) {
      st.faulty_proposers.add(proposer);
      ops.fault(proposer, F_HB_BAD_CT);
      hb_try_batch(st);
    }
    for (BytesP& p : plain_out) hb_accept_plaintext(st, proposer, p);
    plain_out.clear();
  }

  void hb_accept_plaintext(EpochState& st, int proposer, const BytesP& data) {
    if (st.decrypted.has(proposer) || st.faulty_proposers.has(proposer)) return;
    int ok = 1;
    if (e.contrib_cb) {
      // (Slot 15 retired its round-6 contrib_cb stamp for the arena
      // stats — see hb_reset_state and the slot registry.)
      ok = e.contrib_cb(node.id, node.era, st.epoch, proposer,
                        (const uint8_t*)data->data(), data->size());
    }
    if (!ok) {
      st.faulty_proposers.add(proposer);
      ops.fault(proposer, F_HB_BAD_CONTRIB);
    } else {
      st.decrypted.add(proposer);
      st.plaintexts[proposer] = data;
    }
    hb_try_batch(st);
  }

  void hb_try_batch(EpochState& st) {
    if (st.batch_emitted || !st.subset_done) return;
    for (int p : st.accepted_order)
      if (!st.decrypted.has(p) && !st.faulty_proposers.has(p)) return;
    st.batch_emitted = true;
    BatchData bd;
    bd.era = node.era;
    bd.epoch = st.epoch;
    std::vector<int> ids;
    for (int p = 0; p < (int)st.plaintexts.size(); ++p)
      if (st.plaintexts[p]) ids.push_back(p);
    ids = str_sorted(ids);
    for (int p : ids) bd.contributions.push_back({p, st.plaintexts[p]});
    trace_emit(e, node.id, TR_EPOCH_COMMIT, node.era, st.epoch,
               (int32_t)bd.contributions.size(), 0);
    node.pending_batches.push_back(std::move(bd));
  }

  void hb_drain_subset_outputs(EpochState& st) {
    // Process in order; handlers may not append new subset outputs, but
    // index-walk anyway for safety.
    for (size_t i = 0; i < st.pending_outputs.size(); ++i) {
      SubsetOutItem out = st.pending_outputs[i];
      if (out.done) {
        st.subset_done = true;
        // all_at_end: start every deferred decrypt now, in acceptance
        // order (honey_badger._on_subset_output "done" branch).
        std::vector<std::pair<int, BytesP>> pend;
        pend.swap(st.pending_payloads);
        for (auto& pv : pend) hb_start_decrypt(st, pv.first, pv.second);
        hb_try_batch(st);
      } else {
        st.accepted_order.push_back(out.proposer);
        if (node.hb.subset_handling == 1) {
          st.pending_payloads.push_back({out.proposer, out.value});
        } else {
          hb_start_decrypt(st, out.proposer, out.value);
        }
      }
    }
    st.pending_outputs.clear();
  }

  void hb_start_decrypt(EpochState& st, int proposer, const BytesP& payload) {
    if (!st.encrypted) {
      hb_accept_plaintext(st, proposer, payload);
      return;
    }
    trace_emit(e, node.id, TR_DECRYPT_START, node.era, st.epoch, proposer, 0);
    if (e.ext) {
      // serde decode verdict comes from Python (identical to
      // honey_badger._start_decrypt's try_loads gate).
      int ok = e.ct_parse_cb
                   ? e.ct_parse_cb(node.id, (const uint8_t*)payload->data(),
                                   payload->size())
                   : 0;
      if (!ok) {
        st.faulty_proposers.add(proposer);
        ops.fault(proposer, F_HB_BAD_CT);
        hb_try_batch(st);
        return;
      }
      auto td = hb_get_decrypt(st, proposer);
      td_handle_input_ext(st, proposer, td, payload);
      return;
    }
    ScalarCiphertext ct;
    if (!decode_scalar_ciphertext((const uint8_t*)payload->data(),
                                  payload->size(), ct)) {
      st.faulty_proposers.add(proposer);
      ops.fault(proposer, F_HB_BAD_CT);
      hb_try_batch(st);
      return;
    }
    auto td = hb_get_decrypt(st, proposer);
    td_handle_input(st, proposer, td, ct, payload);
    // _on_decrypt_step boundary after handle_input (no outputs possible,
    // ciphertext_invalid not yet known — verification is deferred).
  }

  // Reset-in-place successor of the round-2..4 hb_make_state (which
  // heap-allocated a fresh EpochState per epoch): the same object and
  // its proposals array are recycled — fresh-state semantics come from
  // the exhaustive per-field resets (EpochState::reset_for_epoch +
  // Proposal::reset), pinned by the native equivalence suites.
  void hb_reset_state(EpochState& st, int epoch) {
    trace_emit(e, node.id, TR_EPOCH_OPEN, node.era, epoch, 0, 0);
    st.reset_for_epoch();
    st.epoch = epoch;
    st.encrypted = node.hb.encrypt_on(epoch);
    Bytes ss;
    canon_append(ss, node.hb.session_id);
    canon_append(ss, canon_int_bytes((uint64_t)epoch));
    st.subset_session = ss;
    st.proposals.resize(e.n);
    st.decrypts.resize(e.n);
    st.plaintexts.resize(e.n);
    node.hb.future.resize((size_t)node.hb.max_future_epochs + 1);
    node.hb.future_per_sender.resize(e.n, 0);
    for (Proposal& p : st.proposals) p.reset();
    // THE arena reset (ISSUE 17): every FlatMap above was dropped by
    // Proposal::reset, so the epoch's flat state is reclaimed by one
    // watermark move (blocks poisoned between epochs under ASan).
    // epoch_pins releases the ProofData ownership the echos maps
    // borrowed.  Slot 15 (registry): arena stats — cycles = max
    // per-node high-water mark (bytes), count = resets.
    node.arena.reset(e.arena_recycle);
    node.epoch_pins.clear();
    if (!e.mt_active) {
      if ((uint64_t)node.arena.hwm > e.prof_cycles[15])
        e.prof_cycles[15] = node.arena.hwm;
      e.prof_count[15]++;
    }
    for (int pid : node.val_ids) {
      Proposal& p = st.proposals[pid];
      p.bc.proposer = pid;
      p.bc.data_shards = n() - 2 * f();
      Bytes bs;
      canon_append(bs, "subset-ba");
      canon_append(bs, ss);
      canon_append(bs, std::to_string(pid));
      p.ba.session_id = bs;
      p.ba.sbv = Sbv(n(), f());
      Ctx::ba_make_coin_static(p.ba);
    }
  }

  static void ba_make_coin_static(Ba& ba) {
    auto ts = std::make_shared<Ts>();
    Bytes doc;
    canon_append(doc, "aba-coin");
    canon_append(doc, ba.session_id);
    canon_append(doc, canon_int_bytes((uint64_t)ba.round));
    ts->doc_h = hash_to_g2(doc);
    ts->doc = std::move(doc);  // external mode signs/verifies the raw doc
    ba.coin = ts;
  }

  void hb_advance() {
    Hb& hb = node.hb;
    while (hb.state.batch_emitted) {
      hb.epoch += 1;
      if (!e.mt_active) {
        // Slot 13 (registry, round 7): epoch-advance wall — recycling
        // the whole per-epoch state (N Proposal resets: map teardowns,
        // container clears) plus N fresh coin setups (hash_to_g2 per
        // proposer).  This belongs to no message type, yet it used to
        // be billed to whichever COIN/DECRYPT delivery happened to
        // complete the epoch — at N=300 it was ~2/3 of those slots'
        // cycles (the bulk of the old >1M "continuation tail" this
        // slot measured before round 7).  Borrowed out of the
        // enclosing typed stamp like replays (Engine::replay_borrow).
        uint64_t t0 = prof_tick();
        hb_reset_state(hb.state, hb.epoch);
        uint64_t dt = prof_tick() - t0;
        e.prof_cycles[13] += dt;
        e.prof_count[13]++;
        e.replay_borrow += dt;
      } else {
        hb_reset_state(hb.state, hb.epoch);
      }
      std::vector<std::pair<int, EMsg>> replay;
      replay.swap(hb.future[(size_t)hb.epoch % hb.future.size()]);
      for (auto& sm : replay) {
        // absent == 0 under the old map semantics, so >1-decrement /
        // ==1-erase collapses to a floor-at-zero decrement.
        int32_t& fc = hb.future_per_sender[sm.first];
        if (fc > 0) fc -= 1;
        // typed re-attribution — see ba_next_round's replay loop
        if (!e.mt_active) {
          uint64_t t0 = prof_tick();
          uint64_t b0 = e.replay_borrow;
          hb_state_dispatch(sm.first, sm.second);
          uint64_t inner = e.replay_borrow - b0;
          uint64_t own = prof_tick() - t0 - inner;
          e.prof_cycles[sm.second.type & 15] += own;
          e.replay_borrow = b0 + inner + own;
        } else {
          hb_state_dispatch(sm.first, sm.second);
        }
      }
    }
  }

  void hb_state_dispatch(int sender, const EMsg& m) {
    EpochState& st = node.hb.state;
    if (m.type == HB_DECRYPT) {
      if (!st.encrypted) {
        ops.fault(sender, F_HB_BAD_CT);
        return;
      }
      // Python: is_node_validator(msg.proposer) else fault the sender.
      if (m.proposer < 0 || m.proposer >= e.n || !is_val(m.proposer)) {
        ops.fault(sender, F_HB_BAD_CT);
        return;
      }
      auto td = hb_get_decrypt(st, m.proposer);
      td_handle_message(st, m.proposer, td, sender, m);
      // _on_decrypt_step boundary: invalid-ct check after every td call.
      std::vector<BytesP> none;
      hb_on_decrypt_boundary(m.proposer, td, none);
      return;
    }
    subset_handle_message(st, sender, m);
    hb_drain_subset_outputs(st);
  }

  void hb_handle_message(int sender, const EMsg& m) {
    Hb& hb = node.hb;
    if (m.epoch < hb.epoch) return;
    if (m.epoch > hb.epoch + hb.max_future_epochs) {
      ops.fault(sender, F_HB_FUTURE);
      return;
    }
    if (m.epoch > hb.epoch) {
      int cap = FUTURE_BUFFER_FACTOR * (hb.max_future_epochs + 1) *
                (n() > 1 ? n() : 1);
      int buffered = hb.future_per_sender[sender];
      if (buffered >= cap) {
        ops.fault(sender, F_HB_FLOOD);
        return;
      }
      hb.future_per_sender[sender] = buffered + 1;
      hb.future[(size_t)m.epoch % hb.future.size()].push_back({sender, m});
      return;
    }
    hb_state_dispatch(sender, m);
    hb_advance();
  }

  void hb_propose(const Bytes& payload) {
    EpochState& st = node.hb.state;
    if (st.proposed) return;
    st.proposed = true;
    subset_input(st, std::make_shared<const Bytes>(payload));
    hb_drain_subset_outputs(st);
    hb_advance();
  }

  // ---- DHB-level era gating (deliver path) --------------------------------

  void deliver(int sender, const EMsg& m) {
    if (m.era < node.era) return;
    if (m.era > node.era + 1) {
      ops.fault(sender, F_DHB_FUTURE_ERA);
      return;
    }
    if (m.era == node.era + 1) {
      if ((int)node.next_era_buffer.size() < FUTURE_ERA_BUFFER)
        node.next_era_buffer.push_back({sender, m});
      return;
    }
    hb_handle_message(sender, m);
  }

  // ---- batch-event delivery (fires Python callbacks) ----------------------

  void commit_events() {
    while (!node.pending_batches.empty()) {
      BatchData bd = std::move(node.pending_batches.front());
      node.pending_batches.erase(node.pending_batches.begin());
      // cur_batch is engine-global (the hbe_batch_* accessors read it
      // during the callback); cb_mu serializes concurrent workers'
      // batch events.  Recursive: the callback may propose, which
      // re-enters here on the same thread.
      std::lock_guard<std::recursive_mutex> lk(e.cb_mu);
      e.cur_batch = bd.contributions;
      if (e.batch_cb) {
        // Slot 12: cycles spent inside the Python batch callback — the
        // per-batch DKG/decrypt tail the round-5 envelope profile
        // pinned (92% of continuation cycles; CLAUDE.md).  Outermost
        // invocations only (batch_cb_depth), so nested proposals'
        // batches are not double-counted.
        uint64_t t0 = prof_tick();
        e.batch_cb_depth++;
        e.batch_cb(node.id, bd.era, bd.epoch);
        e.batch_cb_depth--;
        if (!e.mt_active) {
          if (e.batch_cb_depth == 0) {
            uint64_t dt = prof_tick() - t0;
            e.prof_cycles[12] += dt;
            e.prof_count[12]++;
            // Batch-boundary work is not share work: borrow it out of
            // the enclosing typed stamp (Engine::replay_borrow), like
            // the epoch-advance wall.
            e.replay_borrow += dt;
          }
        }
      }
    }
  }
};

// ===========================================================================
// Top-level engine driving
// ===========================================================================

// Verify one CSR-indexed group (flush_every=1 bursts) through the
// shared RLC core.
inline void rlc_verify_group(std::vector<Pending>& items, const uint32_t* gi,
                             size_t gs) {
  RlcInstance in = rlc_instance(items[gi[0]]);
  CsrView v{items, gi};
  rlc_verify_range_v(in, v, 0, gs);
}

// Flat (CSR) group layout, reused across a flush's swap rounds: group
// g's item indices are idx[start[g] .. start[g+1]) in pool order (the
// per-group std::vector form paid one small heap alloc per group —
// measurable against the mulmods being amortized).
struct RlcGroups {
  std::vector<int32_t> group_of;  // item -> group id, -1 = not deferred
  std::vector<uint32_t> idx;      // item indices, grouped, pool order
  std::vector<uint32_t> start;    // ngroups+1 offsets into idx
  std::vector<std::pair<uintptr_t, int32_t>> table;  // ptr -> gid scratch
  size_t ngroups = 0;
  void reset() {
    group_of.clear();
    idx.clear();
    start.clear();
    ngroups = 0;
  }
  const uint32_t* items_of(size_t g) const { return idx.data() + start[g]; }
  size_t size_of(size_t g) const { return start[g + 1] - start[g]; }
  uint32_t leader_of(size_t g) const { return idx[start[g]]; }
};

// Group the drained items' deferred entries per Ts/Td instance (pool
// order preserved within each group) and compute every verdict.  All
// scratch lives in the caller's RlcGroups (stack-rooted per flush), so
// this is safe from engine_run_mt workers without locks — the shared
// inputs (pk_shares, doc_h/ct_h) are node-local or instance-pinned.
// Used at flush_every=1 only: the deferred cadence forms groups at
// SUBMIT time instead (Pending::grp) and never reaches this pass.
void scalar_rlc_verdicts(Engine& e, std::vector<Pending>& items,
                         RlcGroups& gr) {
  uint32_t deferred = 0, first = 0;
  for (uint32_t i = 0; i < items.size(); ++i) {
    if (items[i].rlc_defer) {
      if (!deferred) first = i;
      ++deferred;
    }
  }
  if (!deferred) return;
  // Deferred flushes run outside engine_run's typed delivery stamp, so
  // the group-check cycles are folded into the COIN/DECRYPT typed
  // slots per group (same honesty rule as the continuation stamps in
  // engine_flush_pool — without it the RLC arm's cyc/delivery would
  // simply EXCLUDE its verification cost).  At flush_every=1 the pass
  // runs inside the delivering unit's typed stamp already.
  uint64_t coin_cyc = 0, dec_cyc = 0;
  uint64_t t0 = prof_tick();
  size_t ngroups = 0;
  if (deferred == 1) {
    // Fast path — the dominant case at flush_every=1 (one share per
    // delivered message): no grouping scratch, just the direct check.
    items[first].pre_ok = rlc_check_one(items[first]);
    ngroups = 1;
    uint64_t dt = prof_tick() - t0;
    if (items[first].cont == CONT_TS)
      coin_cyc = dt;
    else
      dec_cyc = dt;
  } else {
    gr.group_of.assign(items.size(), -1);
    // Open-addressing map from instance pointer to group id (pools at
    // queue-dry flushes hold thousands of items across hundreds of
    // instances; a tree map's alloc-per-node is measurable there).
    size_t cap = 1;
    while (cap < (size_t)deferred * 2) cap <<= 1;
    gr.table.assign(cap, {0, -1});
    gr.start.assign(1, 0);  // reused as per-group counts below
    for (uint32_t i = 0; i < items.size(); ++i) {
      Pending& p = items[i];
      if (!p.rlc_defer) continue;
      uintptr_t key = p.cont == CONT_TS ? (uintptr_t)p.ts.get()
                                        : (uintptr_t)p.td.get();
      size_t slot = (size_t)rlc_mix(key) & (cap - 1);
      while (gr.table[slot].first != 0 && gr.table[slot].first != key)
        slot = (slot + 1) & (cap - 1);
      if (gr.table[slot].first == 0) {
        gr.table[slot] = {key, (int32_t)gr.start.size() - 1};
        gr.start.push_back(0);
      }
      gr.group_of[i] = gr.table[slot].second;
      gr.start[(size_t)gr.table[slot].second + 1]++;
    }
    ngroups = gr.ngroups = gr.start.size() - 1;
    for (size_t g = 1; g <= ngroups; ++g) gr.start[g] += gr.start[g - 1];
    gr.idx.resize(deferred);
    {
      // fill cursor per group, then restore start[] by shifting back
      std::vector<uint32_t>& cur = gr.start;
      for (uint32_t i = 0; i < items.size(); ++i) {
        int32_t g = gr.group_of[i];
        if (g >= 0) gr.idx[cur[(size_t)g]++] = i;
      }
      for (size_t g = ngroups; g > 0; --g) cur[g] = cur[g - 1];
      cur[0] = 0;
    }
    for (size_t g = 0; g < ngroups; ++g) {
      size_t gs = gr.size_of(g);
      uint64_t g0 = prof_tick();
      const uint32_t* gi = gr.items_of(g);
      rlc_verify_group(items, gi, gs);
      if (items[gi[0]].cont == CONT_TS)
        coin_cyc += prof_tick() - g0;
      else
        dec_cyc += prof_tick() - g0;
    }
  }
  if (!e.mt_active) {
    // Slot 11 (registry: scalar RLC group stats): cycles = verdict-pass
    // wall, count = groups checked (singletons included).
    e.prof_cycles[11] += prof_tick() - t0;
    e.prof_count[11] += ngroups;
    if (e.in_deferred_flush) {
      e.prof_cycles[BA_COIN] += coin_cyc;
      e.prof_cycles[HB_DECRYPT] += dec_cyc;
    }
  }
}

// Flat-continuation dispatch (see Pending): the three verified-callback
// targets, constructed without a per-entry std::function allocation.
void pending_run(Engine& e, Node& node, Pending& p, bool ok) {
  Ctx c(e, node);
  switch (p.cont) {
    case CONT_TS:
      c.ts_verified_cb(p.era, p.epoch, p.proposer, p.rnd, p.ts, p.sender,
                       p.share, p.share_b, ok);
      break;
    case CONT_TD_CT:
      c.td_ct_checked_cb(p.era, p.epoch, p.proposer, p.td, ok);
      break;
    case CONT_TD_SHARE:
      c.td_verified_cb(p.era, p.epoch, p.proposer, p.td, p.sender, p.share,
                       p.share_b, ok);
      break;
  }
  c.commit_events();
}

// Folded dispatch for one submit-time RLC group (scalar deferred
// mode): one Ctx, one lift, one commit_events for the whole group.
void pending_run_grp(Engine& e, Node& node, Pending& lead) {
  Ctx c(e, node);
  if (lead.cont == CONT_TS)
    c.ts_group_verified_cb(lead.era, lead.epoch, lead.proposer, lead.rnd,
                           lead.ts, lead);
  else
    c.td_group_verified_cb(lead.era, lead.epoch, lead.proposer, lead.td,
                           lead);
  c.commit_events();
}

void engine_flush_pool(Engine& e, Node& node) {
  // Scalar mode.  Same swap-rounds semantics as always (a nested flush
  // — batch callback proposing into a nested engine_unit — sees only
  // its own fresh entries), but the drain buffer is a PER-NODE scratch
  // whose capacity survives across flushes: the round-2..4 form
  // constructed and destructed a std::vector per flush, one alloc+free
  // per share-carrying delivery — pure COIN-envelope overhead.  The
  // nested case (node.flushing already set) takes a local vector so the
  // outer frame's scratch is never clobbered.
  bool outer = !node.flushing;
  std::vector<Pending> local;
  std::vector<Pending>& items = outer ? node.flush_scratch : local;
  if (outer) node.flushing = true;
  // Group continuations are folded ONLY under the deferred cadence:
  // at flush_every=1 the per-item dispatch keeps the continuation
  // stream byte-identical to the Python VirtualNet's (the fidelity
  // contract); deferred flushes are pinned at the output level instead
  // (tests/test_native_rlc.py), where the fold is observationally
  // equivalent (ts_group_verified_cb notes).
  bool fold = scalar_deferred(e);
  RlcGroups gr;
  while (!node.pool.empty()) {
    items.swap(node.pool);
    // New swap-round: open groups on the old pool are now sealed (the
    // submit sites key off pool_round — Ts::grp_round notes).
    node.pool_round++;
    e.pool_items -= items.size();
    gr.reset();
    if (e.rlc && !e.ext && !fold) scalar_rlc_verdicts(e, items, gr);
    for (uint32_t i = 0; i < items.size(); ++i) {
      Pending& p = items[i];
      uint64_t t0 = prof_tick();
      uint64_t b0 = e.replay_borrow;  // lint: st-only (read; guarded writes)
      if (fold && p.rlc_defer) {
        // Submit-time group: verdicts are streamed off the contiguous
        // grp array in chunks AS the folded continuation consumes
        // shares (lead_verify_chunk) — shares past termination are
        // never verified, exactly like the per-share path.
        pending_run_grp(e, node, p);
        if (!e.mt_active) {
          // Slot 11 (registry): groups dispatched; chunk-check cycles
          // are inside the typed continuation stamps below.
          e.prof_count[11]++;
          e.prof_cycles[11] += prof_tick() - t0;
        }
      } else {
        pending_run(e, node, p, p.pre_ok);
      }
      // (The round-4 slot-14 pool-flush total was retired in round 15 —
      // the slot now stamps the combine kernel at ts/td_try_output; the
      // typed fold below still carries the continuation wall.)
      if (!e.mt_active) {  // profiling counters are single-writer only
        uint64_t dt = prof_tick() - t0;
        if (e.in_deferred_flush) {
          // Deferred flushes run outside engine_run's typed delivery
          // stamp: fold the verification + continuation cycles back
          // into the delivering message type so COIN/DECRYPT
          // cyc/delivery stays comparable across the HBBFT_TPU_COIN_RLC
          // A/B (counts are already ticked at delivery; cycles only
          // here).  Own-time only — replays inside the continuation
          // stamped their own types (Engine::replay_borrow).
          uint64_t own = dt - (e.replay_borrow - b0);
          if (p.cont == CONT_TS)
            e.prof_cycles[BA_COIN] += own;
          else if (p.cont == CONT_TD_SHARE)
            e.prof_cycles[HB_DECRYPT] += own;
        }
      }
    }
    items.clear();
  }
  if (outer) node.flushing = false;
}

// Deferred-cadence scalar flush: drain every node's pool in sorted-id
// order, in rounds (continuations may refill any pool) — the scalar
// twin of engine_flush_ext / VirtualNet._flush_all_pools.
void engine_flush_scalar(Engine& e) {
  if (e.in_flush) return;  // re-entrancy (a propose inside a batch cb)
  e.in_flush = true;
  e.in_deferred_flush = true;
  e.since_flush = 0;
  std::vector<int32_t> batch;
  while (!e.dirty_nodes.empty()) {
    batch.swap(e.dirty_nodes);
    std::sort(batch.begin(), batch.end());
    for (int32_t nid : batch) {
      Node& node = e.nodes[nid];
      node.pool_dirty = false;  // re-pushes during the flush re-queue it
      if (!node.pool.empty()) engine_flush_pool(e, node);
    }
    batch.clear();
  }
  e.in_deferred_flush = false;
  e.in_flush = false;
}

// External-crypto flush: mirrors VirtualNet._flush_all_pools — visit
// nodes with pending requests in sorted-id order; per node, drain the
// pool in rounds (one verify-batch callback per round, continuations in
// submission order; continuations may refill the pool).
void engine_flush_ext_node(Engine& e, Node& node);

void engine_flush_ext(Engine& e) {
  if (e.in_flush) return;  // re-entrancy (a propose inside a batch cb)
  e.in_flush = true;
  e.since_flush = 0;
  bool any = true;
  while (any) {
    any = false;
    for (int nid = 0; nid < e.n; ++nid) {
      Node& node = e.nodes[nid];
      if (!node.pool.empty()) {
        any = true;
        engine_flush_ext_node(e, node);
      }
    }
  }
  e.in_flush = false;
}

// Python's VirtualNet increments its flush counter once per delivered
// message / top-level input; flushing resets it.  Round 7: the scalar
// RLC deferred cadence ticks the same counter (engine_flush_scalar in
// place of the ext verify-batch flush).
inline void engine_count_unit(Engine& e) {
  if (e.in_flush) return;
  if (!e.ext && !scalar_deferred(e)) return;
  e.since_flush++;
  if (e.flush_every > 0 && e.since_flush >= (uint64_t)e.flush_every) {
    // Python's _flush_all_pools resets the counter even when no pool is
    // dirty; skip the N-node scan in that (overwhelmingly common) case.
    if (e.pool_items > 0) {
      if (e.ext)
        engine_flush_ext(e);
      else
        engine_flush_scalar(e);
    } else {
      e.since_flush = 0;
    }
  }
}

// Ext-mode eager flush of ONE node's pool: drain in rounds (one
// verify-batch callback per round, continuations in submission order;
// continuations may refill the pool).  Used by engine_flush_ext for
// every node and directly for tampered nodes (VirtualNet's
// TamperingAdversary drains the faulty node's own pool inside _drive,
// independent of the global flush cadence).
void engine_flush_ext_node(Engine& e, Node& node) {
  while (!node.pool.empty()) {
    std::vector<Pending> items;
    items.swap(node.pool);
    e.pool_items -= items.size();
    std::vector<uint8_t> verdicts;
    int need = 0;
    for (Pending& p : items)
      if (p.need_verdict) ++need;
    if (need) {
      e.cur_vreqs.clear();
      for (Pending& p : items)
        if (p.need_verdict) e.cur_vreqs.push_back(&p.req);
      verdicts.assign(need, 0);
      e.verify_cb(node.id, need, verdicts.data());
      e.cur_vreqs.clear();
    }
    int vi = 0;
    for (Pending& p : items)
      pending_run(e, node, p, p.need_verdict ? verdicts[vi++] != 0 : p.pre_ok);
  }
}

void engine_unit(Engine& e, Node& node, const std::function<void(Ctx&)>& fn) {
  // One top-level processing unit: handler, then batch events, then the
  // eager pool flush (each flush callback fires its own events).  Under
  // the scalar deferred cadence (round 7) pools accumulate across units
  // and drain via engine_flush_scalar — except tampered nodes, whose
  // own pool always drains eagerly (VirtualNet's TamperingAdversary
  // flushes the faulty node inside _drive, independent of cadence).
  e.depth++;
  Ctx ctx(e, node);
  fn(ctx);
  ctx.commit_events();
  if (!e.ext) {
    if (!scalar_deferred(e) || node.tampered) engine_flush_pool(e, node);
  } else if (node.tampered) {
    engine_flush_ext_node(e, node);
  }
  e.depth--;
  // Cluster mode: announce after each OUTERMOST unit, mirroring
  // SenderQueue._post's current-epoch check at the end of every handled
  // step (nested units — era restarts, proposals from batch callbacks —
  // land inside the outer unit, exactly like Python's nested steps).
  if (e.depth == 0) cluster_announce(e);
}

// ---------------------------------------------------------------------------
// Multicore generation-parallel scheduler (round 5; SURVEY §5.8's sharded
// delivery queue).
//
// WHY this is byte-identical to the sequential FIFO loop:
//   * Sequential FIFO processing is breadth-first by GENERATIONS: every
//     message in the current queue is processed before any message it
//     emitted (emissions append at the tail).
//   * Within a generation, deliveries to DIFFERENT nodes touch disjoint
//     mutable state: all protocol state is per-Node; the only shared
//     structures are pure-function caches (decoded_roots, the KDF mask
//     cache, Lagrange coefficients — mutex-guarded; cache-content
//     differences can only change WORK, never verdicts) and the
//     Python-callback staging area (cb_mu-serialized; the Python side
//     keys everything by node with per-node rngs, so cross-node
//     callback order is output-invariant).
//   * Deliveries to the SAME node run in their original queue order on
//     one worker, preserving each node's exact sequential transition
//     sequence (scalar-mode pool flushes are per-unit and node-local;
//     the round-7 RLC verdict pass runs inside that per-unit flush with
//     STACK-LOCAL group accumulators/scratch over node-local inputs, so
//     workers never share RLC state — the deferred cadence itself
//     (flush_every != 1) is sequential-only and hbe_run_mt falls back).
//   * Each delivery's emissions are captured in its own slot and
//     spliced back in SOURCE-DELIVERY ORDER — exactly the order the
//     sequential loop would have appended them.
// Hence the global delivery sequence seen by every node — and therefore
// every output, fault, and batch — is identical to engine_run's, which
// the multicore equivalence tests pin.  Scalar mode only: external-
// crypto flush cadence and adversary replay are inherently sequential
// (the Python layer rejects those combinations).
uint64_t engine_run_mt(Engine& e, uint64_t max_deliveries, int n_threads) {
  uint64_t processed = 0;
  e.mt_active = true;
  std::vector<QItem> gen;
  std::vector<std::vector<uint32_t>> by_dest(e.n);
  while (processed < max_deliveries && !e.queue.empty()) {
    uint64_t take = e.queue.size();
    if (take > max_deliveries - processed) take = max_deliveries - processed;
    gen.clear();
    gen.reserve(take);
    for (uint64_t i = 0; i < take; ++i) {
      gen.push_back(std::move(e.queue.front()));
      e.queue.pop_front();
    }
    std::vector<int> dests;  // distinct destinations, first-seen order
    for (uint64_t i = 0; i < take; ++i) {
      int d = gen[i].dest;
      if (by_dest[d].empty()) dests.push_back(d);
      by_dest[d].push_back((uint32_t)i);
    }
    std::vector<std::vector<QItem>> emitted(take);
    std::atomic<size_t> next{0};
    auto worker = [&]() {
      for (;;) {
        size_t di = next.fetch_add(1);
        if (di >= dests.size()) return;
        Node& node = e.nodes[dests[di]];
        for (uint32_t idx : by_dest[dests[di]]) {
          if (node.silent) continue;
          node.handled++;
          tl_emit_sink = &emitted[idx];
          engine_unit(e, node, [&](Ctx& ctx) {
            ctx.deliver(gen[idx].sender, *gen[idx].msg);
          });
          tl_emit_sink = nullptr;
        }
      }
    };
    if (n_threads <= 1 || dests.size() <= 1) {
      worker();
    } else {
      // Spawn-per-generation keeps the scheduler trivially correct; a
      // persistent pool with a start barrier would shave ~tens of us
      // per generation on a real multicore host (noted as the obvious
      // next step in BASELINE.md's round-5 multicore design note).
      std::vector<std::thread> pool;
      int spawn = n_threads;
      if ((size_t)spawn > dests.size()) spawn = (int)dests.size();
      for (int t = 1; t < spawn; ++t) pool.emplace_back(worker);
      worker();
      for (auto& th : pool) th.join();
    }
    // Sequential epilogue: delivered accounting + ordered splice.
    for (uint64_t i = 0; i < take; ++i) {
      if (!e.nodes[gen[i].dest].silent) e.delivered++;
      for (QItem& q : emitted[i]) e.queue.push_back(std::move(q));
    }
    for (int d : dests) by_dest[d].clear();
    processed += take;
  }
  e.mt_active = false;
  return processed;
}

uint64_t engine_run(Engine& e, uint64_t max_deliveries) {
  uint64_t processed = 0;
  while (processed < max_deliveries) {
    if (e.queue.empty()) {
      // Idle: drain deferred verifications so progress can resume
      // (VirtualNet.crank's empty-queue flush).
      if ((e.ext || scalar_deferred(e)) && e.pool_items > 0 && !e.in_flush) {
        if (e.ext)
          engine_flush_ext(e);
        else
          engine_flush_scalar(e);
        if (!e.queue.empty()) continue;
      }
      break;
    }
    if (e.pre_crank_cb) e.pre_crank_cb(e.queue.size());
    QItem item = std::move(e.queue.front());
    e.queue.pop_front();
    ++processed;
    Node& node = e.nodes[item.dest];
    if (node.silent) continue;
    // Adversary-owned (tampered) destinations mirror the VirtualNet's
    // faulty path: the node runs the real algorithm, but the delivery
    // neither counts toward `delivered` nor ticks the flush cadence
    // (VirtualNet.crank returns before delivered+=1 / _maybe_flush).
    if (!node.tampered) e.delivered++;
    node.handled++;
    uint64_t t0 = prof_tick();
    uint64_t b0 = e.replay_borrow;  // lint: st-only (sequential driver)
    engine_unit(e, node,
                [&](Ctx& ctx) { ctx.deliver(item.sender, *item.msg); });
    int ty = item.msg->type & 15;
    // Own-time only: replayed future messages inside this unit already
    // stamped their own typed slots (Engine::replay_borrow).
    // lint: st-only (engine_run is the sequential driver, never a worker)
    e.prof_cycles[ty] += prof_tick() - t0 - (e.replay_borrow - b0);
    e.prof_count[ty] += 1;
    if (!node.tampered) engine_count_unit(e);
  }
  cluster_announce(e);  // no-op outside cluster mode
  return processed;
}

// ===========================================================================
// Wire codec: EMsg <-> the serde wire grammar (ISSUE 5)
// mirror: wire-grammar (twin: hbbft_tpu/wire.py registration table —
//     HBX001 diffs the tag sets; add/remove tags on BOTH sides)
//
// ENCODE produces the exact bytes Python's serde.dumps would emit for
// SqMessage.algo(DhbMessage(era, HbMessage(...))) over the wire.py
// registered codecs (canonical ints, ScalarG group id 1, struct layout)
// — pinned by hbe_wire_roundtrip in the tests.  DECODE mirrors the
// accept/reject behavior of serde.loads under the ScalarSuite pin plus
// the wire.py unpackers for the whole SqMessage-reachable tree: a
// payload is accepted iff Python's `serde.try_loads(data, ScalarSuite)`
// yields an SqMessage (kind 3 covers codec-valid-but-non-engine values:
// join plans, bare HbMessage algos — Python consumes and ignores/faults
// those without committing anything).  Byte-level structure/limits come
// from hbe_serde_scan with the same caller limits serde.py passes.
// ===========================================================================

struct WireDecoded {
  int kind = 0;  // 1 epoch_started, 2 algo engine message, 3 other-accepted
  int64_t era = 0, epoch = 0;  // epoch_started announce (saturated)
  EMsg msg;                    // kind 2
};

inline void wenc_u32(Bytes& o, uint32_t v) {
  o.push_back((char)(v >> 24));
  o.push_back((char)(v >> 16));
  o.push_back((char)(v >> 8));
  o.push_back((char)v);
}

// Canonical non-negative int: 0x03, sign 0, minimal big-endian magnitude.
inline void wenc_nonneg(Bytes& o, uint64_t v) {
  uint8_t mag[8];
  int l = 0;
  while (v) {
    mag[l++] = (uint8_t)(v & 0xff);
    v >>= 8;
  }
  o.push_back('\x03');
  o.push_back('\x00');
  wenc_u32(o, (uint32_t)l);
  for (int i = l - 1; i >= 0; --i) o.push_back((char)mag[i]);
}

inline void wenc_str(Bytes& o, const char* s) {
  size_t l = std::strlen(s);
  o.push_back('\x05');
  wenc_u32(o, (uint32_t)l);
  o.append(s, l);
}

inline void wenc_bytes(Bytes& o, const uint8_t* p, size_t l) {
  o.push_back('\x04');
  wenc_u32(o, (uint32_t)l);
  o.append((const char*)p, l);
}

inline void wenc_tuple(Bytes& o, uint32_t count) {
  o.push_back('\x06');
  wenc_u32(o, count);
}

inline void wenc_struct(Bytes& o, const char* name) {
  size_t l = std::strlen(name);
  o.push_back('\x10');
  o.push_back((char)l);
  o.append(name, l);
}

inline void wenc_bool(Bytes& o, bool b) { o.push_back(b ? '\x02' : '\x01'); }

inline void wenc_group(Bytes& o, const U256& v) {
  // ScalarG.serde_group == 1 for BOTH G1- and G2-positioned elements
  // (one group id in the scalar suite) — encode must match dumps.
  o.push_back('\x11');
  size_t l = sizeof(kScalarSuiteName) - 1;
  o.push_back((char)l);
  o.append(kScalarSuiteName, l);
  o.push_back('\x01');
  wenc_u32(o, 32);
  uint8_t be[32];
  u256_to_be32(v, be);
  o.append((const char*)be, 32);
}

// sigshare/decshare: fields ("scalar-insecure", <group element>).
inline void wenc_share_struct(Bytes& o, const char* name, const U256& v) {
  wenc_struct(o, name);
  wenc_tuple(o, 2);
  wenc_str(o, kScalarSuiteName);
  wenc_group(o, v);
}

// External-crypto mode carries shares as opaque bytes (EMsg::share_b);
// the cluster wire grammar stays the scalar suite's 32-byte element, so
// an ext-scalar share (ScalarG.to_bytes == 32B BE) re-encodes exactly.
// Oversized/odd lengths (a tamper hook rewrote the bytes) truncate via
// u256_from_be, matching what any 32-byte wire slot could carry anyway.
inline void wenc_share_emsg(Bytes& o, const char* name, const EMsg& m) {
  if (m.share_b)
    wenc_share_struct(
        o, name,
        u256_from_be((const uint8_t*)m.share_b->data(), m.share_b->size()));
  else
    wenc_share_struct(o, name, m.share);
}

Bytes wire_encode_algo(const EMsg& m) {
  Bytes o;
  wenc_struct(o, "sqmsg");
  wenc_tuple(o, 2);
  wenc_str(o, "algo");
  wenc_struct(o, "dhbmsg");
  wenc_tuple(o, 2);
  wenc_nonneg(o, (uint64_t)m.era);
  wenc_struct(o, "hbmsg");
  wenc_tuple(o, 4);
  wenc_nonneg(o, (uint64_t)m.epoch);
  if (m.type == HB_DECRYPT) {
    wenc_str(o, "decrypt");
    wenc_nonneg(o, (uint64_t)m.proposer);
    wenc_struct(o, "decmsg");
    wenc_tuple(o, 1);
    wenc_share_emsg(o, "decshare", m);
    return o;
  }
  wenc_str(o, "subset");
  o.push_back('\x00');  // HbMessage.proposer is None for subset envelopes
  wenc_struct(o, "subsetmsg");
  wenc_tuple(o, 3);
  wenc_nonneg(o, (uint64_t)m.proposer);
  switch (m.type) {
    case BC_VALUE:
    case BC_ECHO: {
      wenc_str(o, "bc");
      wenc_struct(o, m.type == BC_VALUE ? "bc_value" : "bc_echo");
      wenc_tuple(o, 1);
      const ProofData& p = *m.proof;
      wenc_struct(o, "proof");
      wenc_tuple(o, 4);
      wenc_bytes(o, (const uint8_t*)p.value.data(), p.value.size());
      wenc_nonneg(o, (uint64_t)p.index);
      wenc_tuple(o, (uint32_t)p.path.size());
      for (const Root& h : p.path) wenc_bytes(o, h.data(), 32);
      wenc_bytes(o, p.root.data(), 32);
      break;
    }
    case BC_READY:
    case BC_ECHO_HASH:
    case BC_CAN_DECODE: {
      wenc_str(o, "bc");
      wenc_struct(o, m.type == BC_READY        ? "bc_ready"
                     : m.type == BC_ECHO_HASH  ? "bc_echohash"
                                               : "bc_candecode");
      wenc_tuple(o, 1);
      wenc_bytes(o, m.root.data(), 32);
      break;
    }
    default: {  // BA_*
      wenc_str(o, "ba");
      wenc_struct(o, "ba");
      wenc_tuple(o, 2);
      wenc_nonneg(o, (uint64_t)m.round);
      switch (m.type) {
        case BA_BVAL:
        case BA_AUX:
        case BA_TERM:
          wenc_struct(o, m.type == BA_BVAL  ? "ba_bval"
                         : m.type == BA_AUX ? "ba_aux"
                                            : "ba_term");
          wenc_tuple(o, 1);
          wenc_bool(o, m.bval != 0);
          break;
        case BA_CONF:
          wenc_struct(o, "ba_conf");
          wenc_tuple(o, 1);
          wenc_struct(o, "bools");
          wenc_tuple(o, 1);
          wenc_nonneg(o, m.bval);
          break;
        default:  // BA_COIN
          wenc_struct(o, "ba_coin");
          wenc_tuple(o, 1);
          wenc_struct(o, "signmsg");
          wenc_tuple(o, 1);
          wenc_share_emsg(o, "sigshare", m);
          break;
      }
      break;
    }
  }
  return o;
}

Bytes wire_encode_epoch_started(int64_t era, int64_t epoch) {
  Bytes o;
  wenc_struct(o, "sqmsg");
  wenc_tuple(o, 2);
  wenc_str(o, "epoch_started");
  wenc_tuple(o, 2);
  wenc_nonneg(o, (uint64_t)era);
  wenc_nonneg(o, (uint64_t)epoch);
  return o;
}

// CPython-strict UTF-8 validity (rejects continuations at start,
// overlongs, surrogates, > U+10FFFF) — needed where Python's decoder
// utf-8-decodes a FREE string (node ids); fixed-name comparisons reject
// mismatches byte-wise either way.
inline bool wire_utf8_ok(const uint8_t* s, size_t n) {
  size_t i = 0;
  while (i < n) {
    uint8_t c = s[i];
    if (c < 0x80) {
      i += 1;
    } else if (c < 0xC2) {
      return false;
    } else if (c < 0xE0) {
      if (i + 1 >= n || (s[i + 1] & 0xC0) != 0x80) return false;
      i += 2;
    } else if (c < 0xF0) {
      if (i + 2 >= n) return false;
      uint8_t c1 = s[i + 1], c2 = s[i + 2];
      if ((c1 & 0xC0) != 0x80 || (c2 & 0xC0) != 0x80) return false;
      if (c == 0xE0 && c1 < 0xA0) return false;  // overlong
      if (c == 0xED && c1 >= 0xA0) return false;  // surrogate
      i += 3;
    } else if (c < 0xF5) {
      if (i + 3 >= n) return false;
      uint8_t c1 = s[i + 1], c2 = s[i + 2], c3 = s[i + 3];
      if ((c1 & 0xC0) != 0x80 || (c2 & 0xC0) != 0x80 || (c3 & 0xC0) != 0x80)
        return false;
      if (c == 0xF0 && c1 < 0x90) return false;  // overlong
      if (c == 0xF4 && c1 >= 0x90) return false;  // > U+10FFFF
      i += 4;
    } else {
      return false;
    }
  }
  return true;
}

inline int32_t wire_sat32(int64_t v) {
  return v > INT32_MAX ? INT32_MAX : (int32_t)v;
}

// Typed walk over the hbe_serde_scan token stream, mirroring the
// wire.py validators for the SqMessage-reachable closure.  Rejections
// may fire for a different REASON than Python's bottom-up build (e.g.
// wrong-type field before a nested malformed struct), but the verdict
// is identical — the fuzz-parity tests sweep corruptions to pin it.
struct WireWalk {
  const int64_t* t;
  int64_t ntok;
  const uint8_t* d;
  int64_t ti = 0;
  bool ok = true;

  bool fail() {
    ok = false;
    return false;
  }
  bool have() const { return ti < ntok; }
  int64_t tag() const { return t[3 * ti]; }
  int64_t a() const { return t[3 * ti + 1]; }
  int64_t b() const { return t[3 * ti + 2]; }
  static bool eq(const uint8_t* p, int64_t l, const char* s) {
    size_t n = std::strlen(s);
    return (uint64_t)l == n && std::memcmp(p, s, n) == 0;
  }

  // type(v) is int and v >= 0 (bool tags are distinct — automatic).
  // Values past int64 saturate: acceptance parity matters, the engine
  // treats the result as "absurdly far future" exactly like Python's
  // window checks would.
  bool take_nonneg(int64_t& out) {
    if (!have() || (tag() & 0xff) != 0x03 || (tag() >> 8) != 0)
      return fail();
    int64_t off = a(), l = b();
    uint64_t v = 0;
    if (l > 8) {
      v = (uint64_t)INT64_MAX;
    } else {
      for (int64_t i = 0; i < l; ++i) v = (v << 8) | d[off + i];
      if (v > (uint64_t)INT64_MAX) v = (uint64_t)INT64_MAX;
    }
    out = (int64_t)v;
    ++ti;
    return true;
  }

  // wire._node_id: int (any sign/size), utf-8 str, or bytes.  Ids the
  // engine cannot route decode to the -2 sentinel — delivered, then
  // faulted as unknown proposer, the Python protocol layer's verdict
  // for an id outside the validator set.
  bool take_node_id(int32_t& out) {
    if (!have()) return fail();
    int64_t low = tag() & 0xff;
    if (low == 0x03) {
      int64_t off = a(), l = b();
      if ((tag() >> 8) != 0 || l > 4) {
        out = -2;
      } else {
        int64_t v = 0;
        for (int64_t i = 0; i < l; ++i) v = (v << 8) | d[off + i];
        out = v <= INT32_MAX ? (int32_t)v : -2;
      }
      ++ti;
      return true;
    }
    if (low == 0x05) {
      if (!wire_utf8_ok(d + a(), (size_t)b())) return fail();
      out = -2;
      ++ti;
      return true;
    }
    if (low == 0x04) {
      out = -2;
      ++ti;
      return true;
    }
    return fail();
  }

  bool take_bool(uint8_t& out) {
    if (!have() || (tag() != 0x01 && tag() != 0x02)) return fail();
    out = tag() == 0x02 ? 1 : 0;
    ++ti;
    return true;
  }

  bool take_none() {
    if (!have() || tag() != 0x00) return fail();
    ++ti;
    return true;
  }

  bool take_str(const uint8_t*& p, int64_t& l) {
    if (!have() || tag() != 0x05) return fail();
    p = d + a();
    l = b();
    ++ti;
    return true;
  }

  bool take_bytes(const uint8_t*& p, int64_t& l) {
    if (!have() || tag() != 0x04) return fail();
    p = d + a();
    l = b();
    ++ti;
    return true;
  }

  bool take_root(Root& out) {
    const uint8_t* p;
    int64_t l;
    if (!take_bytes(p, l) || l != 32) return fail();
    std::memcpy(out.data(), p, 32);
    return true;
  }

  bool enter_tuple(uint32_t count) {
    if (!have() || tag() != 0x06 || a() != (int64_t)count) return fail();
    ++ti;
    return true;
  }

  bool enter_tuple_any(int64_t& count) {
    if (!have() || tag() != 0x06) return fail();
    count = a();
    ++ti;
    return true;
  }

  bool enter_struct(const uint8_t*& name, int64_t& nl) {
    if (!have() || tag() != 0x10) return fail();
    name = d + a();
    nl = b();
    ++ti;
    return true;
  }

  // Pinned scalar group element: suite name must be the pin's (loads
  // rejects any other suite AT the group token), group id 1 or 2 (both
  // decode through the identical scalar from_bytes), 32 BE bytes < r.
  bool take_group_scalar(U256& out) {
    if (!have() || tag() != 0x11) return fail();
    if (!eq(d + a(), b(), kScalarSuiteName)) return fail();
    ++ti;
    if (!have()) return fail();
    int64_t grp = tag();
    if ((grp != 1 && grp != 2) || b() != 32) return fail();
    out = u256_from_be(d + a(), 32);
    if (!(u256_cmp(out, R_MOD) < 0)) return fail();
    ++ti;
    return true;
  }

  // sigshare/decshare: ("<suite>", elem).  Any other REGISTERED suite
  // name fails is_g1/is_g2 against the pinned scalar element in Python;
  // unregistered names fail the suite lookup — reject either way.
  bool take_share_struct(const char* sname, U256& out) {
    const uint8_t* nm;
    int64_t nl;
    if (!enter_struct(nm, nl) || !eq(nm, nl, sname)) return fail();
    if (!enter_tuple(2)) return false;
    const uint8_t* sp;
    int64_t sl;
    if (!take_str(sp, sl)) return false;
    if (!eq(sp, sl, kScalarSuiteName)) return fail();
    return take_group_scalar(out);
  }

  bool take_proof(std::shared_ptr<const ProofData>& out) {
    const uint8_t* nm;
    int64_t nl;
    if (!enter_struct(nm, nl) || !eq(nm, nl, "proof")) return fail();
    if (!enter_tuple(4)) return false;
    auto p = std::make_shared<ProofData>();
    const uint8_t* vp;
    int64_t vl;
    if (!take_bytes(vp, vl)) return false;
    p->value.assign((const char*)vp, (size_t)vl);
    int64_t idx;
    if (!take_nonneg(idx)) return false;
    p->index = wire_sat32(idx);  // >= n_leaves either way: invalid-proof
    int64_t cnt;
    if (!enter_tuple_any(cnt)) return false;  // empty path is codec-valid
    p->path.reserve((size_t)cnt);  // scan bounds count by input bytes
    for (int64_t i = 0; i < cnt; ++i) {
      Root h;
      if (!take_root(h)) return false;
      p->path.push_back(h);
    }
    if (!take_root(p->root)) return false;
    out = std::move(p);
    return true;
  }
};

// HbMessage fields (epoch, kind, proposer, inner) -> EMsg (era left to
// the caller).  Mirrors wire._unpack_hb_msg + the whole inner tree.
bool wire_walk_hbmsg_fields(WireWalk& w, EMsg& m) {
  if (!w.enter_tuple(4)) return false;
  int64_t epoch;
  if (!w.take_nonneg(epoch)) return false;
  m.epoch = wire_sat32(epoch);
  const uint8_t* kp;
  int64_t kl;
  if (!w.take_str(kp, kl)) return false;
  const uint8_t* nm;
  int64_t nl;
  if (WireWalk::eq(kp, kl, "decrypt")) {
    if (!w.take_node_id(m.proposer)) return false;
    if (!w.enter_struct(nm, nl) || !WireWalk::eq(nm, nl, "decmsg"))
      return w.fail();
    if (!w.enter_tuple(1)) return false;
    if (!w.take_share_struct("decshare", m.share)) return false;
    m.type = HB_DECRYPT;
    return true;
  }
  if (!WireWalk::eq(kp, kl, "subset")) return w.fail();
  if (!w.take_none()) return false;  // subset with a proposer rejects
  if (!w.enter_struct(nm, nl) || !WireWalk::eq(nm, nl, "subsetmsg"))
    return w.fail();
  if (!w.enter_tuple(3)) return false;
  if (!w.take_node_id(m.proposer)) return false;
  const uint8_t* sk;
  int64_t skl;
  if (!w.take_str(sk, skl)) return false;
  const uint8_t* in;
  int64_t il;
  if (WireWalk::eq(sk, skl, "bc")) {
    if (!w.enter_struct(in, il)) return false;
    if (WireWalk::eq(in, il, "bc_value") || WireWalk::eq(in, il, "bc_echo")) {
      m.type = WireWalk::eq(in, il, "bc_value") ? BC_VALUE : BC_ECHO;
      if (!w.enter_tuple(1)) return false;
      std::shared_ptr<const ProofData> pr;
      if (!w.take_proof(pr)) return false;
      m.proof = std::move(pr);
      return true;
    }
    if (WireWalk::eq(in, il, "bc_ready") ||
        WireWalk::eq(in, il, "bc_echohash") ||
        WireWalk::eq(in, il, "bc_candecode")) {
      m.type = WireWalk::eq(in, il, "bc_ready")      ? BC_READY
               : WireWalk::eq(in, il, "bc_echohash") ? BC_ECHO_HASH
                                                     : BC_CAN_DECODE;
      if (!w.enter_tuple(1)) return false;
      return w.take_root(m.root);
    }
    return w.fail();
  }
  if (!WireWalk::eq(sk, skl, "ba")) return w.fail();
  if (!w.enter_struct(in, il) || !WireWalk::eq(in, il, "ba")) return w.fail();
  if (!w.enter_tuple(2)) return false;
  int64_t rnd;
  if (!w.take_nonneg(rnd)) return false;
  m.round = wire_sat32(rnd);
  const uint8_t* cn;
  int64_t cl;
  if (!w.enter_struct(cn, cl)) return false;
  if (WireWalk::eq(cn, cl, "ba_bval") || WireWalk::eq(cn, cl, "ba_aux") ||
      WireWalk::eq(cn, cl, "ba_term")) {
    m.type = WireWalk::eq(cn, cl, "ba_bval")  ? BA_BVAL
             : WireWalk::eq(cn, cl, "ba_aux") ? BA_AUX
                                              : BA_TERM;
    if (!w.enter_tuple(1)) return false;
    return w.take_bool(m.bval);
  }
  if (WireWalk::eq(cn, cl, "ba_conf")) {
    m.type = BA_CONF;
    if (!w.enter_tuple(1)) return false;
    const uint8_t* bn;
    int64_t bl;
    if (!w.enter_struct(bn, bl) || !WireWalk::eq(bn, bl, "bools"))
      return w.fail();
    if (!w.enter_tuple(1)) return false;
    int64_t mask;
    if (!w.take_nonneg(mask) || mask > 3) return w.fail();  // BoolSet 0..3
    m.bval = (uint8_t)mask;
    return true;
  }
  if (WireWalk::eq(cn, cl, "ba_coin")) {
    m.type = BA_COIN;
    if (!w.enter_tuple(1)) return false;
    const uint8_t* sn;
    int64_t sl;
    if (!w.enter_struct(sn, sl) || !WireWalk::eq(sn, sl, "signmsg"))
      return w.fail();
    if (!w.enter_tuple(1)) return false;
    return w.take_share_struct("sigshare", m.share);
  }
  return w.fail();
}

// JoinPlan validation (wire._unpack_join_plan): accepted then IGNORED —
// SenderQueue's "already joined: nothing to do" — but acceptance parity
// still matters for the bad_payload counter and the fuzz contract.
bool wire_walk_joinplan_fields(WireWalk& w) {
  if (!w.enter_tuple(5)) return false;
  int64_t era;
  if (!w.take_nonneg(era)) return false;
  const uint8_t* sn;
  int64_t sl;
  if (!w.take_str(sn, sl)) return false;
  // Under the pin all commitment elements are scalar; a bls-named plan
  // fails is_g1 on them, an unregistered name fails the suite lookup.
  if (!WireWalk::eq(sn, sl, kScalarSuiteName)) return w.fail();
  const uint8_t* cn;
  int64_t cl;
  if (!w.enter_struct(cn, cl) || !WireWalk::eq(cn, cl, "comm"))
    return w.fail();
  if (!w.enter_tuple(1)) return false;
  int64_t elems;
  if (!w.enter_tuple_any(elems) || elems < 1) return w.fail();
  for (int64_t i = 0; i < elems; ++i) {
    U256 v;
    if (!w.take_group_scalar(v)) return false;
  }
  int64_t nval;
  if (!w.enter_tuple_any(nval) || nval < 1) return w.fail();
  for (int64_t i = 0; i < nval; ++i) {
    if (!w.enter_tuple(2)) return false;
    int32_t id;
    if (!w.take_node_id(id)) return false;
    const uint8_t* pn;
    int64_t pl;
    if (!w.enter_struct(pn, pl) || !WireWalk::eq(pn, pl, "pk"))
      return w.fail();
    if (!w.enter_tuple(2)) return false;
    const uint8_t* psn;
    int64_t psl;
    if (!w.take_str(psn, psl) || !WireWalk::eq(psn, psl, kScalarSuiteName))
      return w.fail();
    U256 v;
    if (!w.take_group_scalar(v)) return false;
  }
  const uint8_t* en;
  int64_t el;
  if (!w.enter_struct(en, el) || !WireWalk::eq(en, el, "encsched"))
    return w.fail();
  if (!w.enter_tuple(2)) return false;
  const uint8_t* kn;
  int64_t kl;
  if (!w.take_str(kn, kl)) return false;
  if (!(WireWalk::eq(kn, kl, "always") || WireWalk::eq(kn, kl, "never") ||
        WireWalk::eq(kn, kl, "every_nth") ||
        WireWalk::eq(kn, kl, "tick_tock")))
    return w.fail();
  int64_t schedn;
  if (!w.take_nonneg(schedn) || schedn < 1) return w.fail();
  return true;
}

bool wire_decode_tokens(const int64_t* t, int64_t ntok, const uint8_t* d,
                        WireDecoded& out) {
  WireWalk w{t, ntok, d};
  const uint8_t* nm;
  int64_t nl;
  if (!w.enter_struct(nm, nl) || !WireWalk::eq(nm, nl, "sqmsg")) return false;
  if (!w.enter_tuple(2)) return false;
  const uint8_t* kp;
  int64_t kl;
  if (!w.take_str(kp, kl)) return false;
  if (WireWalk::eq(kp, kl, "epoch_started")) {
    if (!w.enter_tuple(2)) return false;
    if (!w.take_nonneg(out.era) || !w.take_nonneg(out.epoch)) return false;
    out.kind = 1;
  } else if (WireWalk::eq(kp, kl, "algo")) {
    const uint8_t* an;
    int64_t al;
    if (!w.enter_struct(an, al)) return false;
    if (WireWalk::eq(an, al, "dhbmsg")) {
      if (!w.enter_tuple(2)) return false;
      int64_t era;
      if (!w.take_nonneg(era)) return false;
      const uint8_t* hn;
      int64_t hl;
      if (!w.enter_struct(hn, hl) || !WireWalk::eq(hn, hl, "hbmsg"))
        return false;
      if (!wire_walk_hbmsg_fields(w, out.msg)) return false;
      out.msg.era = wire_sat32(era);
      out.kind = 2;
    } else if (WireWalk::eq(an, al, "hbmsg")) {
      // Static-stack HbMessage: codec-valid (SqMessage admits both),
      // but the dynamic stack faults it as malformed without effect.
      EMsg scratch;
      if (!wire_walk_hbmsg_fields(w, scratch)) return false;
      out.kind = 3;
    } else {
      return false;
    }
  } else if (WireWalk::eq(kp, kl, "join_plan")) {
    const uint8_t* jn;
    int64_t jl;
    if (!w.enter_struct(jn, jl) || !WireWalk::eq(jn, jl, "joinplan"))
      return false;
    if (!wire_walk_joinplan_fields(w)) return false;
    out.kind = 3;
  } else {
    return false;
  }
  // The scan already rejects trailing bytes; a fully-consumed token
  // stream is the tree-level equivalent.
  return w.ok && w.ti == ntok;
}

extern "C" int64_t hbe_serde_scan(const uint8_t* data, uint64_t len,
                                  int64_t* out, uint64_t max_triples,
                                  int64_t max_depth, uint64_t max_len);

// Full wire decode: structural scan (serde limits) + typed walk.
bool wire_decode(const uint8_t* data, uint64_t len, WireDecoded& out) {
  if (len == 0) return false;
  // Optimistic token buffer with the exact-worst-case retry, like
  // serde._native_scan (one triple per input byte, +2 for root/group).
  // Typical frames reuse a thread_local scratch: a fresh zero-
  // initialized vector per frame was measurable on the burst ingest
  // path, and the scan only reads triples it wrote.  Oversized frames
  // (rare multi-MB bc_values) take a one-shot buffer instead so the
  // retained scratch stays bounded (~2 MB/thread).
  static thread_local std::vector<int64_t> scratch;
  std::vector<int64_t> oneshot;
  for (int attempt = 0; attempt < 2; ++attempt) {
    uint64_t triples = attempt == 0 ? len / 2 + 64 : len + 2;
    uint64_t need = 3 * triples;
    int64_t* bp;
    if (need <= (1ull << 18)) {
      if (scratch.size() < need) scratch.resize(need);
      bp = scratch.data();
    } else {
      oneshot.resize(need);
      bp = oneshot.data();
    }
    // mirror: serde-scan-limits (twin: serde.MAX_DEPTH / serde._MAX_LEN
    //     — HBX001 pins these literals to the Python constants)
    int64_t rc = hbe_serde_scan(data, len, bp, triples, 64, 1ull << 28);
    if (rc == -2) continue;  // buffer too small: retry exact
    if (rc < 0) return false;
    return wire_decode_tokens(bp, rc, data, out);
  }
  return false;
}

// ---------------------------------------------------------------------------
// Cluster egress gating + announcements (SenderQueue semantics)
// ---------------------------------------------------------------------------

// SenderQueue._admits: 0 send, 1 hold (ahead of window), 2 drop (stale).
// mirror: sq-admission (twin: sender_queue.SenderQueue._admits —
//     window-rule changes land on BOTH sides)
inline int cluster_admit(const std::array<int64_t, 2>& pe, int64_t era,
                         int64_t epoch, int32_t window) {
  if (era < pe[0]) return 2;
  if (era > pe[0]) return 1;
  if (epoch < pe[1]) return 2;
  if (epoch > pe[1] + window) return 1;
  return 0;
}

void cluster_emit(Engine& e, int dest, const std::shared_ptr<const EMsg>& msg) {
  ClusterState& c = e.cluster;
  if (dest < 0 || dest >= e.n || dest == c.local) return;
  const EMsg& m = *msg;
  int adm = cluster_admit(c.peer_epoch[dest], m.era, m.epoch, c.window);
  if (adm == 2) {
    c.stats[CL_DROPPED_STALE]++;
    return;
  }
  if (c.enc_src.get() != msg.get()) {  // one encode per broadcast
    c.enc_payload = std::make_shared<const Bytes>(wire_encode_algo(m));
    c.enc_src = msg;
  }
  if (adm == 0) {
    c.egress.push_back({(int32_t)dest, c.enc_payload});
    c.egress_bytes += c.enc_payload->size();
    c.stats[CL_SENT]++;
  } else {
    c.outbox[dest].push_back({m.era, m.epoch, c.enc_payload});
    c.stats[CL_HELD]++;
  }
}

void cluster_on_epoch_started(Engine& e, int sender, int64_t era,
                              int64_t epoch) {
  ClusterState& c = e.cluster;
  auto& pe = c.peer_epoch[sender];
  if (era < pe[0] || (era == pe[0] && epoch <= pe[1])) return;  // stale
  pe = {era, epoch};
  std::deque<ClusterHeld> held;
  held.swap(c.outbox[sender]);
  for (ClusterHeld& h : held) {
    int adm = cluster_admit(pe, h.era, h.epoch, c.window);
    if (adm == 0) {
      c.egress_bytes += h.payload->size();
      c.egress.push_back({(int32_t)sender, std::move(h.payload)});
      c.stats[CL_RELEASED]++;
    } else if (adm == 1) {
      c.outbox[sender].push_back(std::move(h));
    } else {
      c.stats[CL_DROPPED_STALE]++;
    }
  }
}

void cluster_announce(Engine& e) {
  ClusterState& c = e.cluster;
  if (c.local < 0) return;
  Node& nd = e.nodes[c.local];
  if (!nd.hb_init) return;
  int64_t era = nd.era, ep = nd.hb.epoch;
  if (era == c.ann_era && ep == c.ann_epoch) return;
  c.ann_era = era;
  c.ann_epoch = ep;
  BytesP p = std::make_shared<const Bytes>(wire_encode_epoch_started(era, ep));
  for (int d = 0; d < e.n; ++d) {
    if (d == c.local) continue;
    c.egress.push_back({(int32_t)d, p});
    c.egress_bytes += p->size();
  }
  c.stats[CL_ANNOUNCES]++;
}

// ---------------------------------------------------------------------------
// Scalar-suite DKG fast path: registered BivarCommitments + per-ack checks
// ---------------------------------------------------------------------------
//
// The era-change tail is the N^3 per-ack Python work (BASELINE.md round-4
// profile: decrypt + commitment row eval + compare per committed Ack, at
// every node).  A commitment matrix registers once per decoded Part
// (network-wide, on the shared object) and each ack check is ONE C call.
//
// The registry is process-global, mutex-guarded (ctypes.CDLL drops the
// GIL during foreign calls, so concurrent Python threads CAN race here),
// and byte-capped: when stored matrices exceed DKG_REG_MAX_BYTES the
// whole registry is cleared and the GENERATION bumps — cids encode
// (generation << 32 | index), so stale cids (including ones memoized on
// still-live Python commitment objects) never resolve to a different
// entry; they miss and the caller falls back to the pure-Python path,
// which is always correct.  This bounds memory across unbounded era
// churn in a long-lived process.

struct DkgCommit {
  int n1 = 0;
  U256 g;                                  // suite g1 generator value
  std::vector<U256> elems;                 // n1*n1 row-major [i][j]
  std::map<int, std::vector<U256>> rows;   // x -> committed row coeffs
};

const size_t DKG_REG_MAX_BYTES = 128u << 20;  // matrices only; rows ~2x

struct DkgRegistry {
  std::mutex mu;
  std::vector<DkgCommit> entries;
  uint64_t generation = 0;
  size_t bytes = 0;
};

DkgRegistry& dkg_registry() {
  static DkgRegistry reg;
  return reg;
}

// Committed row poly for x: row_j(x) = sum_i elems[i][j] * x^i
// (BivarCommitment.row's Horner, cached per (commitment, x) exactly like
// the Python object memo).  Caller holds the registry mutex.
const std::vector<U256>& dkg_row(DkgCommit& c, int x) {
  auto it = c.rows.find(x);
  if (it != c.rows.end()) return it->second;
  // One-side-Montgomery Horner (round 15): x lifts once, each step is
  // one REDC producing the exact canonical acc*x the classic mulmod
  // produced — identical rows, half the reduction work.
  U256 xs = {{(uint64_t)x, 0, 0, 0}};
  U256 xm = to_mont(xs);
  std::vector<U256> out(c.n1);
  for (int j = 0; j < c.n1; ++j) {
    U256 acc = U256_ZERO;
    for (int i = c.n1 - 1; i >= 0; --i)
      acc = addmod(mont_mul(acc, xm), c.elems[i * c.n1 + j]);
    out[j] = acc;
  }
  return c.rows.emplace(x, std::move(out)).first->second;
}

// Caller holds the registry mutex.
inline DkgCommit* dkg_get(DkgRegistry& reg, int64_t cid) {
  if (cid < 0 || (uint64_t)(cid >> 32) != reg.generation) return nullptr;
  size_t idx = (size_t)(cid & 0xFFFFFFFF);
  if (idx >= reg.entries.size()) return nullptr;
  return &reg.entries[idx];
}

// By-value snapshot of one registered commitment's data for a given
// evaluation point: everything the ack/row checks need OUTSIDE the
// registry mutex (snapshot-outside-the-lock — the KEM
// decrypt + Horner evaluations must not serialize all concurrent DKG
// checks process-wide; ctypes drops the GIL, so multi-threaded Python
// callers otherwise contend on the one global lock).
struct DkgRowCopy {
  bool ok = false;
  U256 g = U256_ZERO;
  int n1 = 0;
  std::vector<U256> row;  // committed row coeffs at the requested x
};

// Caller holds the registry mutex.
inline DkgRowCopy dkg_copy_row(DkgRegistry& reg, int64_t cid, int x) {
  DkgRowCopy out;
  DkgCommit* c = dkg_get(reg, cid);
  if (!c) return out;
  out.ok = true;
  out.g = c->g;
  out.n1 = c->n1;
  out.row = dkg_row(*c, x);  // copy out by value
  return out;
}

// row(x) evaluated at y by Horner (the commitment consistency check's
// expected value); runs lock-free over a DkgRowCopy.
inline U256 dkg_row_eval(const DkgRowCopy& rc, int y) {
  U256 ys = {{(uint64_t)y, 0, 0, 0}};
  U256 ym = to_mont(ys);  // one-side-Montgomery Horner, see dkg_row
  U256 acc = U256_ZERO;
  for (int j = rc.n1 - 1; j >= 0; --j)
    acc = addmod(mont_mul(acc, ym), rc.row[j]);
  return acc;
}

}  // namespace

// ===========================================================================
// C ABI
// ===========================================================================

extern "C" {

// --- scalar-suite KEM fast path (stateless; no engine handle) --------------
//
// Mirrors keys.py PublicKey.encrypt / SecretKey.decrypt for the scalar
// suite byte-for-byte (canonical_bytes framing, kdf_stream, h2g2) so the
// Python layer can route the N^3 DKG ack/row KEM operations here without
// changing any protocol output.  Randomness for encrypt is drawn by the
// CALLER (Python rng) to keep the rng consumption stream identical to the
// pure-Python stack — the equivalence tests depend on it.

// Decrypt: validate the ciphertext (w == u * h2g2(ct-hash-input), the
// scalar-suite pairing check), then unmask v with kdf(u * x).  u/w/x are
// 32-byte big-endian scalars < r; out must hold v_len bytes.  Returns 1
// and fills out on a valid ciphertext, 0 otherwise (out untouched).
int32_t hbe_kem_decrypt(const uint8_t* u_be, const uint8_t* v, uint64_t v_len,
                        const uint8_t* w_be, const uint8_t* x_be,
                        uint8_t* out) {
  ScalarCiphertext ct;
  ct.u = u256_from_be(u_be, 32);
  ct.w = u256_from_be(w_be, 32);
  ct.v.assign((const char*)v, v_len);
  U256 h = ct_hash_scalar(ct);
  if (!(mulmod(ct.u, h) == ct.w)) return 0;
  U256 shared = mulmod(ct.u, u256_from_be(x_be, 32));
  uint8_t sh_be[32];
  u256_to_be32(shared, sh_be);
  Bytes seed;
  canon_append(seed, "kem");
  canon_append(seed, Bytes((const char*)sh_be, 32));
  Bytes mask = kdf_stream(seed, v_len);
  for (uint64_t i = 0; i < v_len; ++i)
    out[i] = v[i] ^ (uint8_t)mask[i];
  return 1;
}

// Encrypt msg to pk with caller-provided randomness r (32B BE, in [1, r)).
// out_u/out_w: 32 bytes each; out_v: msg_len bytes.
void hbe_kem_encrypt(const uint8_t* pk_be, const uint8_t* msg,
                     uint64_t msg_len, const uint8_t* r_be, uint8_t* out_u,
                     uint8_t* out_v, uint8_t* out_w) {
  U256 r = u256_from_be(r_be, 32);
  U256 pk = u256_from_be(pk_be, 32);
  u256_to_be32(r, out_u);  // u = g1_generator * r = r in the scalar group
  U256 shared = mulmod(pk, r);
  uint8_t sh_be[32];
  u256_to_be32(shared, sh_be);
  Bytes seed;
  canon_append(seed, "kem");
  canon_append(seed, Bytes((const char*)sh_be, 32));
  Bytes mask = kdf_stream(seed, msg_len);
  for (uint64_t i = 0; i < msg_len; ++i)
    out_v[i] = msg[i] ^ (uint8_t)mask[i];
  ScalarCiphertext ct;
  ct.u = r;
  ct.v.assign((const char*)out_v, msg_len);
  U256 h = ct_hash_scalar(ct);
  u256_to_be32(mulmod(h, r), out_w);
}

// Batched hbe_kem_encrypt: n fixed-32-byte messages to n public keys
// with n caller-drawn randomness values (the DKG ack row: one encrypted
// evaluation per node).  Layout: flat n*32-byte arrays throughout.
void hbe_kem_encrypt_batch(const uint8_t* pks_be, const uint8_t* msgs,
                           int32_t n, const uint8_t* rs_be, uint8_t* out_u,
                           uint8_t* out_v, uint8_t* out_w) {
  for (int32_t i = 0; i < n; ++i)
    hbe_kem_encrypt(pks_be + 32 * i, msgs + 32 * i, 32, rs_be + 32 * i,
                    out_u + 32 * i, out_v + 32 * i, out_w + 32 * i);
}

// --- scalar-suite DKG fast path (registry notes above the C ABI) -----------

// Register a BivarCommitment matrix: elems_be = n1*n1 32-byte BE scalars
// (row-major), g_be = the suite's g1 generator value, r_be = the scalar
// modulus.  Returns a cid >= 0, or -1 when the modulus is not this
// build's R_MOD or an element is out of range (caller falls back to the
// Python path).
int64_t hbe_dkg_register(const uint8_t* elems_be, int32_t n1,
                         const uint8_t* g_be, const uint8_t* r_be) {
  if (n1 < 1 || n1 > 4096) return -1;
  if (!(u256_from_be(r_be, 32) == R_MOD)) return -1;
  DkgCommit c;
  c.n1 = n1;
  c.g = u256_from_be(g_be, 32);
  if (!(u256_cmp(c.g, R_MOD) < 0)) return -1;
  c.elems.resize((size_t)n1 * n1);
  for (size_t k = 0; k < c.elems.size(); ++k) {
    c.elems[k] = u256_from_be(elems_be + 32 * k, 32);
    if (!(u256_cmp(c.elems[k], R_MOD) < 0)) return -1;
  }
  DkgRegistry& reg = dkg_registry();
  std::lock_guard<std::mutex> lk(reg.mu);
  size_t add = c.elems.size() * sizeof(U256);
  if (reg.bytes + add > DKG_REG_MAX_BYTES) {
    reg.entries.clear();
    reg.bytes = 0;
    reg.generation++;  // stale cids from before the clear never resolve
  }
  reg.bytes += add;
  reg.entries.push_back(std::move(c));
  return (int64_t)((reg.generation << 32) | (reg.entries.size() - 1));
}

uint64_t hbe_dkg_registry_size() {
  DkgRegistry& reg = dkg_registry();
  std::lock_guard<std::mutex> lk(reg.mu);
  return reg.entries.size();
}

// Clear everything and bump the generation (tests / explicit release;
// stale cids fall back to the pure-Python path, never misresolve).
void hbe_dkg_clear() {
  DkgRegistry& reg = dkg_registry();
  std::lock_guard<std::mutex> lk(reg.mu);
  reg.entries.clear();
  reg.bytes = 0;
  reg.generation++;
}

// Full private ack check (sync_key_gen.handle_ack's value path): KEM
// decrypt of the 32-byte ack slot, scalar decode, and the commitment
// consistency check  g*val == row(sender_pos).eval(our_pos).
// Returns: 1 = valid (out_val32 = the 32-byte BE value), 2 = ciphertext
// valid but value bad (decode/consistency failure -> fault), 0 = the
// ciphertext itself failed the KEM validity check (-> fault; the caller
// records the ct-validity memo distinctly from the value verdict),
// -1 = unknown cid (caller must FALL BACK to the Python path, never
// fault).
int32_t hbe_dkg_ack_check(int64_t cid, int32_t sender_pos, int32_t our_pos,
                          const uint8_t* u_be, const uint8_t* v32,
                          const uint8_t* w_be, const uint8_t* sk_be,
                          uint8_t* out_val32) {
  // Row snapshot under the lock; decrypt + Horner OUTSIDE it (the
  // snapshot-outside-the-lock pattern — see DkgRowCopy).
  DkgRowCopy rc;
  {
    DkgRegistry& reg = dkg_registry();
    std::lock_guard<std::mutex> lk(reg.mu);
    rc = dkg_copy_row(reg, cid, sender_pos);
  }
  if (!rc.ok) return -1;
  uint8_t plain[32];
  if (!hbe_kem_decrypt(u_be, v32, 32, w_be, sk_be, plain)) return 0;
  U256 val = u256_from_be(plain, 32);
  if (!(u256_cmp(val, R_MOD) < 0)) return 2;
  U256 expected = dkg_row_eval(rc, our_pos);
  if (!(mulmod(rc.g, val) == expected)) return 2;
  std::memcpy(out_val32, plain, 32);
  return 1;
}

// Batched hbe_dkg_ack_check: ONE call for a whole committed batch's ack
// slots (the era-change continuation tail is per-batch Python work —
// this is the native half of the batch-digest fast path).  cids and
// sender positions vary per item (a batch's acks reference different
// dealers' commitments); our_pos and the secret key are fixed (one
// receiving node).  Registry lookups are amortized: ONE lock
// acquisition snapshots every referenced row (deduped by
// (cid, sender_pos)), then all KEM decrypts + Horner evaluations run
// outside the lock.  Per-item rc semantics are IDENTICAL to
// hbe_dkg_ack_check (1 ok / 2 bad value / 0 bad ciphertext / -1 fall
// back per item); u/v/w are flat count x 32-byte arrays, vals_out
// likewise.  Returns 1, or 0 on gross misuse (caller falls back
// entirely).
int32_t hbe_dkg_ack_check_batch(const int64_t* cids,
                                const int32_t* sender_pos, int32_t count,
                                int32_t our_pos, const uint8_t* u_flat,
                                const uint8_t* v_flat, const uint8_t* w_flat,
                                const uint8_t* sk_be, int32_t* rc_out,
                                uint8_t* vals_out) {
  if (count < 1 || count > (1 << 22)) return 0;
  std::vector<DkgRowCopy> uniq;
  std::vector<int> ref((size_t)count, -1);
  {
    DkgRegistry& reg = dkg_registry();
    std::lock_guard<std::mutex> lk(reg.mu);
    std::map<std::pair<int64_t, int32_t>, int> seen;
    for (int32_t i = 0; i < count; ++i) {
      auto key = std::make_pair(cids[i], sender_pos[i]);
      auto it = seen.find(key);
      if (it != seen.end()) {
        ref[i] = it->second;
        continue;
      }
      int idx = (int)uniq.size();
      uniq.push_back(dkg_copy_row(reg, cids[i], sender_pos[i]));
      seen.emplace(key, idx);
      ref[i] = idx;
    }
  }
  // our_pos is fixed across the batch, so each distinct row's expected
  // value is one Horner — not one per referencing ack (and each row's
  // generator lifts to the Montgomery domain once for the per-ack
  // g*val products below).
  std::vector<U256> expected(uniq.size(), U256_ZERO);
  std::vector<U256> gms(uniq.size(), U256_ZERO);
  for (size_t k = 0; k < uniq.size(); ++k)
    if (uniq[k].ok) {
      expected[k] = dkg_row_eval(uniq[k], our_pos);
      gms[k] = to_mont(uniq[k].g);
    }
  for (int32_t i = 0; i < count; ++i) {
    const DkgRowCopy& rc = uniq[ref[i]];
    if (!rc.ok) {
      rc_out[i] = -1;
      continue;
    }
    uint8_t plain[32];
    if (!hbe_kem_decrypt(u_flat + 32 * (size_t)i, v_flat + 32 * (size_t)i, 32,
                         w_flat + 32 * (size_t)i, sk_be, plain)) {
      rc_out[i] = 0;
      continue;
    }
    U256 val = u256_from_be(plain, 32);
    if (!(u256_cmp(val, R_MOD) < 0)) {
      rc_out[i] = 2;
      continue;
    }
    if (!(mont_mul(val, gms[ref[i]]) == expected[ref[i]])) {
      rc_out[i] = 2;
      continue;
    }
    std::memcpy(vals_out + 32 * (size_t)i, plain, 32);
    rc_out[i] = 1;
  }
  return 1;
}

// Part row consistency (sync_key_gen._decrypt_row's commitment check):
// plain = the decrypted row plaintext (n1 32-byte BE coefficients);
// checks g*coeff_j == committed row(our_pos)[j] for every j.  Returns 1
// ok, 0 mismatch/out-of-range (caller faults, exactly like the Python
// to_bytes comparison), -1 unknown cid (caller falls back).
int32_t hbe_dkg_row_check(int64_t cid, int32_t our_pos, const uint8_t* plain,
                          int32_t n_coeffs) {
  DkgRowCopy rc;
  {
    DkgRegistry& reg = dkg_registry();
    std::lock_guard<std::mutex> lk(reg.mu);
    rc = dkg_copy_row(reg, cid, our_pos);
  }
  if (!rc.ok) return -1;
  if (n_coeffs != rc.n1) return 0;
  U256 gm = to_mont(rc.g);  // g is loop-invariant: lift once
  for (int j = 0; j < rc.n1; ++j) {
    U256 v = u256_from_be(plain + 32 * j, 32);
    if (!(u256_cmp(v, R_MOD) < 0)) return 0;
    if (!(mont_mul(v, gm) == rc.row[j])) return 0;
  }
  return 1;
}

// Batched Part private check (sync_key_gen._decrypt_row in one call per
// batch): for each part, KEM-decrypt our encrypted row (v_flat holds
// count ciphertext bodies of n1*32 bytes each), range-check the n1
// decoded coefficients, and compare g*coeff_j against the registered
// commitment's row(our_pos) — the exact decrypt -> _decode_scalars ->
// row-consistency pipeline.  Registry lookups amortize through ONE
// lock acquisition (deduped by cid; our_pos is fixed), checks run
// outside it.  Per-item rc: 1 ok (rows_out[i] = the decrypted n1*32
// plaintext), 2 ciphertext valid but decode/consistency failed
// (-> fault), 0 the ciphertext itself failed the KEM check (-> fault;
// distinct so the caller's ct-validity memo stays faithful), -1 unknown
// cid (caller falls back per item).  Returns 1, or 0 on gross misuse.
int32_t hbe_dkg_part_check_batch(const int64_t* cids, int32_t count,
                                 int32_t our_pos, const uint8_t* u_flat,
                                 const uint8_t* v_flat,
                                 const uint8_t* w_flat, int32_t n1,
                                 const uint8_t* sk_be, int32_t* rc_out,
                                 uint8_t* rows_out) {
  if (count < 1 || count > (1 << 22) || n1 < 1 || n1 > 4096) return 0;
  size_t vlen = (size_t)n1 * 32;
  std::vector<DkgRowCopy> uniq;
  std::vector<int> ref((size_t)count, -1);
  {
    DkgRegistry& reg = dkg_registry();
    std::lock_guard<std::mutex> lk(reg.mu);
    std::map<int64_t, int> seen;
    for (int32_t i = 0; i < count; ++i) {
      auto it = seen.find(cids[i]);
      if (it != seen.end()) {
        ref[i] = it->second;
        continue;
      }
      int idx = (int)uniq.size();
      uniq.push_back(dkg_copy_row(reg, cids[i], our_pos));
      seen.emplace(cids[i], idx);
      ref[i] = idx;
    }
  }
  for (int32_t i = 0; i < count; ++i) {
    const DkgRowCopy& rc = uniq[ref[i]];
    if (!rc.ok) {
      rc_out[i] = -1;
      continue;
    }
    uint8_t* plain = rows_out + vlen * (size_t)i;
    if (!hbe_kem_decrypt(u_flat + 32 * (size_t)i, v_flat + vlen * (size_t)i,
                         vlen, w_flat + 32 * (size_t)i, sk_be, plain)) {
      rc_out[i] = 0;
      continue;
    }
    if (rc.n1 != n1) {  // registered degree mismatch: same fault as the
      rc_out[i] = 2;    // per-item row_check's n_coeffs != n1 verdict
      continue;
    }
    U256 gm = to_mont(rc.g);  // lift once per part, see hbe_dkg_row_check
    int ok = 1;
    for (int j = 0; j < n1 && ok; ++j) {
      U256 v = u256_from_be(plain + 32 * (size_t)j, 32);
      if (!(u256_cmp(v, R_MOD) < 0) || !(mont_mul(v, gm) == rc.row[j]))
        ok = 0;
    }
    rc_out[i] = ok ? 1 : 2;
  }
  return 1;
}

// --- serde token scan (native half of utils/serde.loads) -------------------
//
// One pass over a serde payload producing a flat int64 TRIPLE stream the
// Python builder walks without any per-byte work (utils/serde.py
// _decode's take/u8/u32 calls were the measured bulk of DKG-payload
// decoding).  Structural validation here mirrors the Python decoder's
// checks EXACTLY where they are structural (bounds, canonical ints,
// counts, depth, known tags); semantic checks (utf-8, struct/suite
// registries, dict key rules, unpack validation) stay in Python.  Both
// paths raise the same DecodeError class, so a malformed payload is
// rejected either way — only the failure MESSAGE can differ.
//
// Triple layout per value node:
//   NONE/TRUE/FALSE: [tag, 0, 0]
//   INT:   [0x03 | sign<<8, mag_offset, mag_len]
//   BYTES: [0x04, offset, len]    STR: [0x05, offset, len]
//   TUPLE/LIST: [tag, count, 0] then `count` child nodes
//   DICT:  [0x08, count, 0] then 2*count child nodes (k, v, k, v, ...)
//   STRUCT:[0x10, name_offset, name_len] then ONE node (the fields)
//   GROUP: [0x11, name_offset, name_len] then EXTRA triple
//          [group_id, payload_offset, payload_len]
// Returns the number of triples, -1 on malformed input, -2 when the
// output buffer is too small (caller retries with a bigger one).

namespace {
struct SerdeScan {
  const uint8_t* d;
  uint64_t len, pos = 0;
  int64_t* out;
  uint64_t max_triples, n = 0;
  // Limits supplied by the CALLER (serde.MAX_DEPTH / serde._MAX_LEN) so
  // the two decoders can never silently disagree after a constant edit.
  int64_t max_depth = 64;
  uint64_t max_len = 1ull << 28;
  int err = 0;  // 0 ok, 1 malformed, 2 overflow

  bool need(uint64_t k) {
    if (pos + k > len) {
      err = 1;
      return false;
    }
    return true;
  }
  bool emit(int64_t a, int64_t b, int64_t c) {
    if (n >= max_triples) {
      err = 2;
      return false;
    }
    out[3 * n] = a;
    out[3 * n + 1] = b;
    out[3 * n + 2] = c;
    n++;
    return true;
  }
  uint32_t u32() {
    uint32_t v = ((uint32_t)d[pos] << 24) | ((uint32_t)d[pos + 1] << 16) |
                 ((uint32_t)d[pos + 2] << 8) | d[pos + 3];
    pos += 4;
    return v;
  }

  void value(int depth) {
    if (err) return;
    if (depth > max_depth) {  // serde.MAX_DEPTH (caller-supplied)
      err = 1;
      return;
    }
    if (!need(1)) return;
    uint8_t tag = d[pos++];
    switch (tag) {
      case 0x00:
      case 0x01:
      case 0x02:
        emit(tag, 0, 0);
        return;
      case 0x03: {  // int: sign u8, len u32, magnitude
        if (!need(5)) return;
        uint8_t sign = d[pos++];
        if (sign > 1) {
          err = 1;
          return;
        }
        uint64_t l = u32();
        if (l > max_len) {
          err = 1;
          return;
        }
        if (!need(l)) return;
        if (l > 0 && d[pos] == 0) {  // non-minimal int
          err = 1;
          return;
        }
        if (sign == 1 && l == 0) {  // negative zero
          err = 1;
          return;
        }
        emit(0x03 | ((int64_t)sign << 8), (int64_t)pos, (int64_t)l);
        pos += l;
        return;
      }
      case 0x04:
      case 0x05: {  // bytes / str
        if (!need(4)) return;
        uint64_t l = u32();
        if (l > max_len) {
          err = 1;
          return;
        }
        if (!need(l)) return;
        emit(tag, (int64_t)pos, (int64_t)l);
        pos += l;
        return;
      }
      case 0x06:
      case 0x07: {  // tuple / list
        if (!need(4)) return;
        uint64_t count = u32();
        if (count > len - pos) {  // each element costs >= 1 byte
          err = 1;
          return;
        }
        if (!emit(tag, (int64_t)count, 0)) return;
        for (uint64_t i = 0; i < count && !err; ++i) value(depth + 1);
        return;
      }
      case 0x08: {  // dict
        if (!need(4)) return;
        uint64_t count = u32();
        if (2 * count > len - pos) {
          err = 1;
          return;
        }
        if (!emit(tag, (int64_t)count, 0)) return;
        for (uint64_t i = 0; i < 2 * count && !err; ++i) value(depth + 1);
        return;
      }
      case 0x10: {  // struct: name u8-len, then fields value
        if (!need(1)) return;
        uint64_t nl = d[pos++];
        if (!need(nl)) return;
        if (!emit(0x10, (int64_t)pos, (int64_t)nl)) return;
        pos += nl;
        value(depth + 1);
        return;
      }
      case 0x11: {  // group: name u8-len, group u8, payload u32-len
        if (!need(1)) return;
        uint64_t nl = d[pos++];
        if (!need(nl)) return;
        if (!emit(0x11, (int64_t)pos, (int64_t)nl)) return;
        pos += nl;
        if (!need(5)) return;
        uint8_t grp = d[pos++];
        uint64_t l = u32();
        if (l > max_len) {
          err = 1;
          return;
        }
        if (!need(l)) return;
        if (!emit(grp, (int64_t)pos, (int64_t)l)) return;
        pos += l;
        return;
      }
      default:
        err = 1;
        return;
    }
  }
};
}  // namespace

int64_t hbe_serde_scan(const uint8_t* data, uint64_t len, int64_t* out,
                       uint64_t max_triples, int64_t max_depth,
                       uint64_t max_len) {
  SerdeScan s{data, len, 0, out, max_triples, 0, max_depth, max_len};
  s.value(0);
  if (!s.err && s.pos != s.len) s.err = 1;  // trailing bytes
  if (s.err == 2) return -2;
  if (s.err) return -1;
  return (int64_t)s.n;
}

// --- vectorized Lagrange interpolation / combine ---------------------------
//
// The era-change batch tail's last Python-bigint stage: SyncKeyGen
// generate() interpolates f(0) once per complete proposal, and the
// scalar-suite PublicKeySet combines run the same Lagrange sum per
// signature/decryption.  These mirror crypto/poly.py interpolate()
// EXACTLY (same num/den products mod r, same f(0) value), batched so
// one C call covers a whole generate() / combine.

// sum over `n_groups` groups of interpolate_at_0(group) mod r — ONE
// call for SyncKeyGen.generate()'s per-proposal interpolations (the
// secret share is the sum) or, with n_groups = 1, a plain Lagrange
// combine.  xs: flat positive evaluation points; ys_be: flat 32-byte BE
// values < r; counts[g]: points in group g.  All denominators across
// every group share ONE Fermat inversion (the Montgomery batch trick of
// poly.lagrange_coefficients — a per-point invmod at 255 squarings each
// measured SLOWER than CPython's extended-gcd pow(-1)).  The sum equals
// poly.interpolate's per-group value exactly (same products mod r).
// Returns 1 and fills out32, or 0 when the modulus is not this build's
// R_MOD / a point is invalid / a denominator is zero (caller falls back
// to the Python path — never a silent wrong value).
int32_t hbe_scalar_interp_sum(const int32_t* xs, const uint8_t* ys_be,
                              const int32_t* counts, int32_t n_groups,
                              const uint8_t* r_be, uint8_t* out32) {
  if (n_groups < 1 || n_groups > (1 << 20)) return 0;
  if (!(u256_from_be(r_be, 32) == R_MOD)) return 0;
  size_t total = 0;
  for (int32_t g = 0; g < n_groups; ++g) {
    if (counts[g] < 1 || counts[g] > 65536) return 0;
    total += (size_t)counts[g];
  }
  // Pass 1 (round 15): per-group numerators via prefix/suffix products
  // in the Montgomery domain (O(cnt) one-REDC muls) and denominators
  // through the dispatched batch kernel (field_plane.h) — exactly the
  // same products mod r the old O(cnt^2) mulmod loops computed, so the
  // sum stays byte-identical to poly.interpolate in both SIMD arms.
  std::vector<U256> nums_m(total), dens(total), ys(total);
  {
    const int32_t* gx = xs;
    const uint8_t* gy = ys_be;
    size_t base = 0;
    std::vector<int64_t> xs64;
    std::vector<U256> xs_m, pre, suf;
    for (int32_t g = 0; g < n_groups; ++g) {
      int32_t cnt = counts[g];
      xs64.resize(cnt);
      for (int32_t k = 0; k < cnt; ++k) {
        if (gx[k] <= 0) return 0;
        ys[base + k] = u256_from_be(gy + 32 * (size_t)k, 32);
        if (!(u256_cmp(ys[base + k], R_MOD) < 0)) return 0;
        xs64[k] = gx[k];
      }
      hbf::lagrange_dens(xs64.data(), cnt, dens[base].w);
      for (int32_t k = 0; k < cnt; ++k)
        if (u256_is_zero(dens[base + k])) return 0;  // duplicate x
      xs_m.resize(cnt);
      for (int32_t k = 0; k < cnt; ++k) {
        U256 x = {{(uint64_t)xs64[k], 0, 0, 0}};
        xs_m[k] = to_mont(x);
      }
      pre.assign(cnt + 1, ONE_MONT);
      suf.assign(cnt + 1, ONE_MONT);
      for (int32_t k = 0; k < cnt; ++k)
        pre[k + 1] = mont_mul(pre[k], xs_m[k]);
      for (int32_t k = cnt; k-- > 0;) suf[k] = mont_mul(suf[k + 1], xs_m[k]);
      for (int32_t k = 0; k < cnt; ++k)
        nums_m[base + k] = mont_mul(pre[k], suf[k + 1]);
      gx += cnt;
      gy += (size_t)cnt * 32;
      base += (size_t)cnt;
    }
  }
  // Pass 2: one shared inversion, then accumulate y*num*den^-1 — the
  // chain runs in the Montgomery domain; each term converts back
  // through its final one-REDC products (exact canonical values).
  std::vector<U256> prefix(total + 1);
  prefix[0] = ONE_MONT;
  std::vector<U256> dens_m(total);
  for (size_t i = 0; i < total; ++i) dens_m[i] = to_mont(dens[i]);
  for (size_t i = 0; i < total; ++i)
    prefix[i + 1] = mont_mul(prefix[i], dens_m[i]);
  U256 inv_acc;
  hbf::mont_inv4(prefix[total].w, inv_acc.w);
  U256 acc = U256_ZERO;
  for (size_t i = total; i-- > 0;) {
    U256 dinv_m = mont_mul(inv_acc, prefix[i]);
    inv_acc = mont_mul(inv_acc, dens_m[i]);
    // mont_mul(ys, nums_m) = ys*num (canonical); then *dinv likewise.
    U256 t = mont_mul(ys[i], nums_m[i]);
    acc = addmod(acc, mont_mul(t, dinv_m));
  }
  u256_to_be32(acc, out32);
  return 1;
}

// Scalar-suite combine_decryption_shares in one call: Lagrange-combine
// the shares at 0, then unmask v with kdf(canonical(b"kem", acc)) —
// byte-identical to keys.PublicKeySet.combine_decryption_shares over
// ScalarSuite (the kdf/canonical framing is the shared scalar-KEM
// code the equivalence suites already pin).  Returns 1 and fills
// out[v_len], or 0 (caller falls back).
int32_t hbe_scalar_combine_unmask(const int32_t* xs, int32_t count,
                                  const uint8_t* ys_be, const uint8_t* r_be,
                                  const uint8_t* v, uint64_t v_len,
                                  uint8_t* out) {
  uint8_t acc_be[32];
  if (!hbe_scalar_interp_sum(xs, ys_be, &count, 1, r_be, acc_be)) return 0;
  Bytes seed;
  canon_append(seed, "kem");
  canon_append(seed, Bytes((const char*)acc_be, 32));
  Bytes mask = kdf_stream(seed, v_len);
  for (uint64_t i = 0; i < v_len; ++i) out[i] = v[i] ^ (uint8_t)mask[i];
  return 1;
}

// Row evaluations for ack building (Poly.eval at x = 1..n_points):
// coeffs_be = n_coeffs 32-byte BE scalars (ascending degree), out =
// n_points * 32 bytes.
void hbe_dkg_row_evals(const uint8_t* coeffs_be, int32_t n_coeffs,
                       int32_t n_points, uint8_t* out) {
  std::vector<U256> cs(n_coeffs);
  for (int32_t k = 0; k < n_coeffs; ++k)
    cs[k] = u256_from_be(coeffs_be + 32 * k, 32);
  for (int32_t p = 0; p < n_points; ++p) {
    U256 x = {{(uint64_t)(p + 1), 0, 0, 0}};
    U256 xm = to_mont(x);  // one-side-Montgomery Horner, see dkg_row
    U256 acc = U256_ZERO;
    for (int32_t k = n_coeffs - 1; k >= 0; --k)
      acc = addmod(mont_mul(acc, xm), cs[k]);
    u256_to_be32(acc, out + 32 * p);
  }
}

// This build's NodeSet width (for HBBFT_TPU_ENGINE_LIB overrides: the
// loader verifies a pre-built library is wide enough for the requested
// network instead of letting hbe_create fail opaquely).
int32_t hbe_words() { return HBE_WORDS; }

// --- SIMD field-plane dispatch + kernel test surface (round 15) ------------
//
// The vectorized field-arithmetic plane (native/field_plane.h /
// native/field_ifma.cpp) dispatches per call: AVX-512 IFMA when the
// build compiled it AND the host advertises it AND HBBFT_TPU_SIMD is
// not "0".  hbe_simd_force flips arms in-process (-1 = back to auto) so
// the equivalence/fuzz tests can pin both arms in one interpreter; the
// setting is process-global and read with relaxed atomics (flip only
// between runs).

int32_t hbe_simd_compiled() { return hbf_ifma_compiled(); }
int32_t hbe_simd_mode() { return hbf::simd_mode(); }
int32_t hbe_simd_force(int32_t mode) { return hbf::simd_force(mode); }

// Elementwise batched a*b mod r over 32-byte BE scalars (fuzz surface
// for the dispatched kernel; at least one side of each pair < r).
void hbe_field_mul_batch(const uint8_t* a_be, const uint8_t* b_be, int32_t n,
                         uint8_t* out_be) {
  if (n <= 0) return;
  std::vector<U256> a(n), b(n), out(n);
  for (int32_t i = 0; i < n; ++i) {
    a[i] = u256_from_be(a_be + 32 * i, 32);
    b[i] = u256_from_be(b_be + 32 * i, 32);
  }
  hbf::mul_batch(a[0].w, b[0].w, out[0].w, (size_t)n);
  for (int32_t i = 0; i < n; ++i) u256_to_be32(out[i], out_be + 32 * i);
}

// --- Batched sha3 plane test/stats surface (round 17) ----------------------

// SHA3-256 of `count` contiguous messages of `msg_len` bytes; 32-byte
// digests contiguous at out.  The sha3-plane fuzz surface: dispatches
// exactly as the engine's kdf/Merkle consumers do (8-lane arm for full
// groups when enabled, scalar tail), so both arms are pinnable from the
// tests via hbe_simd_force.
void hbe_sha3_batch(const uint8_t* msgs, uint64_t msg_len, uint64_t count,
                    uint8_t* out) {
  hbs::sha3_256_batch(msgs, (size_t)msg_len, (size_t)count, out);
}

// Plane counters since process start: {batch_calls, batch_msgs,
// ifma_msgs, single_msgs}.  Library-global (the plane is one dispatch
// point, not per-engine); benchmark lines report deltas or totals.
void hbe_sha3_stats(uint64_t out[4]) {
  hbs::Sha3Stats& s = hbs::stats();
  out[0] = s.batch_calls.load(std::memory_order_relaxed);
  out[1] = s.batch_msgs.load(std::memory_order_relaxed);
  out[2] = s.ifma_msgs.load(std::memory_order_relaxed);
  out[3] = s.single_msgs.load(std::memory_order_relaxed);
}

// Epoch-arena telemetry across this engine's nodes: {max per-node
// high-water mark (bytes/epoch), sum of per-node high-water marks,
// total watermark resets, recycle knob (HBBFT_TPU_ARENA)}.  Benchmark
// lines report these so arena A/Bs are self-documenting.
void hbe_arena_stats(void* h, uint64_t out[4]) {
  Engine& e = *(Engine*)h;
  uint64_t mx = 0, sum = 0, rs = 0;
  for (Node& nd : e.nodes) {
    if ((uint64_t)nd.arena.hwm > mx) mx = nd.arena.hwm;
    sum += nd.arena.hwm;
    rs += nd.arena.resets;
  }
  out[0] = mx;
  out[1] = sum;
  out[2] = rs;
  out[3] = e.arena_recycle ? 1 : 0;
}

// sum_i a_i*b_i mod r (the combine-sum kernel's fuzz surface).
void hbe_field_dot(const uint8_t* a_be, const uint8_t* b_be, int32_t n,
                   uint8_t* out32) {
  if (n <= 0) {
    std::memset(out32, 0, 32);
    return;
  }
  std::vector<U256> a(n), b(n);
  for (int32_t i = 0; i < n; ++i) {
    a[i] = u256_from_be(a_be + 32 * i, 32);
    b[i] = u256_from_be(b_be + 32 * i, 32);
  }
  U256 acc;
  hbf::dot_batch(a[0].w, b[0].w, (size_t)n, acc.w);
  u256_to_be32(acc, out32);
}

// Lagrange coefficients at 0 for x_i = idxs[i]+1 (exactly the engine's
// combine-path lagrange(); oracle-checked against crypto/poly.py).
void hbe_field_lagrange(const int32_t* idxs, int32_t k, uint8_t* out_be) {
  if (k <= 0) return;
  std::vector<int> v(idxs, idxs + k);
  std::vector<U256> coeffs = lagrange(v);
  for (int32_t i = 0; i < k; ++i) u256_to_be32(coeffs[i], out_be + 32 * i);
}

// acc64 (64-byte BE) = sum_i coeffs[i]*x[i] as an exact integer (the
// RLC accumulate kernel's fuzz surface; coeffs are 8-byte BE).
void hbe_field_rlc_accum(const uint8_t* x_be, const uint8_t* coeffs_be,
                         int32_t n, uint8_t* acc64_be) {
  if (n <= 0) {
    std::memset(acc64_be, 0, 64);
    return;
  }
  std::vector<U256> x(n);
  std::vector<uint64_t> cs(n);
  for (int32_t i = 0; i < n; ++i) {
    x[i] = u256_from_be(x_be + 32 * i, 32);
    uint64_t c = 0;
    for (int j = 0; j < 8; ++j) c = (c << 8) | coeffs_be[8 * i + j];
    cs[i] = c;
  }
  uint64_t acc[8] = {0, 0, 0, 0, 0, 0, 0, 0};
  hbf::rlc_accum(x[0].w, cs.data(), (size_t)n, acc);
  for (int i = 0; i < 8; ++i) {
    uint64_t w = acc[7 - i];
    for (int j = 0; j < 8; ++j)
      acc64_be[8 * i + j] = (uint8_t)(w >> (56 - 8 * j));
  }
}

void* hbe_create(int32_t n, int32_t f) {
  // MAX_NODES = this build's NodeSet width (the loader picks a wide
  // enough build); 65535 = the GF(2^16) codec's point budget.
  if (n < 1 || n > MAX_NODES || n > 65535 || f < 0 || 3 * f >= n)
    return nullptr;
  Engine* e = new Engine();
  e->n = n;
  e->f = f;
  e->nodes.resize(n);
  for (int i = 0; i < n; ++i) e->nodes[i].id = i;
  const char* g = getenv("HBBFT_TPU_CT_HASH_CACHE");
  e->ct_hash_cache = !(g && g[0] == '0' && !g[1]);
  const char* r = getenv("HBBFT_TPU_COIN_RLC");
  e->rlc = !(r && r[0] == '0' && !r[1]);
  const char* a = getenv("HBBFT_TPU_ARENA");
  e->arena_recycle = !(a && a[0] == '0' && !a[1]);
  return e;
}

void hbe_destroy(void* h) { delete (Engine*)h; }

void hbe_set_callbacks(void* h, BatchEventCb batch_cb, ContribCb contrib_cb) {
  Engine* e = (Engine*)h;
  e->batch_cb = batch_cb;
  e->contrib_cb = contrib_cb;
}

void hbe_set_silent(void* h, int32_t node, int32_t silent) {
  ((Engine*)h)->nodes[node].silent = silent != 0;
}

// (Re)initialize a node's HoneyBadger for an era.  sk_share: 32B BE or
// NULL (observer); pk_shares: n x 32B BE commitment evaluations (by
// validator index == node id); session: the HB session id bytes
// (canonical(dhb_session, era) — computed by the Python layer);
// sched_kind/n: EncryptionSchedule.
void hbe_init_node(void* h, int32_t node, int32_t era, const uint8_t* session,
                   uint64_t session_len, const int32_t* val_ids, int32_t n_val,
                   int32_t era_f, const uint8_t* sk_share,
                   const uint8_t* pk_shares, int32_t max_future_epochs,
                   int32_t sched_kind, int32_t sched_n,
                   int32_t subset_handling) {
  Engine* e = (Engine*)h;
  Node& nd = e->nodes[node];
  nd.era = era;
  nd.has_share = sk_share != nullptr;
  if (sk_share) nd.sk_share = u256_from_be(sk_share, 32);
  nd.val_ids.assign(val_ids, val_ids + n_val);
  std::sort(nd.val_ids.begin(), nd.val_ids.end());
  nd.val_index.assign(e->n, -1);
  for (int i = 0; i < n_val; ++i) nd.val_index[nd.val_ids[i]] = i;
  nd.era_n = n_val;
  nd.era_f = era_f;
  nd.pk_shares.resize(e->n);
  for (int i = 0; i < e->n; ++i)
    nd.pk_shares[i] = u256_from_be(pk_shares + 32 * i, 32);
  nd.hb = Hb();
  nd.hb_init = true;
  nd.hb.session_id.assign((const char*)session, session_len);
  nd.hb.max_future_epochs = max_future_epochs;
  nd.hb.sched_kind = sched_kind;
  nd.hb.sched_n = sched_n;
  nd.hb.subset_handling = subset_handling;
  Ctx ctx(*e, nd);
  ctx.hb_reset_state(nd.hb.state, 0);
}

// Era restart: re-init + replay the buffered next-era messages
// (dynamic_honey_badger._restart_era + _replay_next_era).  Runs as a
// nested unit so it can be called from inside a batch callback.
void hbe_restart_node(void* h, int32_t node, int32_t era,
                      const uint8_t* session, uint64_t session_len,
                      const int32_t* val_ids, int32_t n_val, int32_t era_f,
                      const uint8_t* sk_share, const uint8_t* pk_shares,
                      int32_t max_future_epochs, int32_t sched_kind,
                      int32_t sched_n, int32_t subset_handling) {
  hbe_init_node(h, node, era, session, session_len, val_ids, n_val, era_f,
                sk_share, pk_shares, max_future_epochs, sched_kind, sched_n,
                subset_handling);
}

// Replay the buffered next-era messages (DynamicHoneyBadger's
// _replay_next_era — the Python layer calls this at the exact point its
// reference implementation does, after the batch output).
void hbe_replay_era(void* h, int32_t node) {
  Engine* e = (Engine*)h;
  Node& nd = e->nodes[node];
  std::vector<std::pair<int, EMsg>> buffered;
  buffered.swap(nd.next_era_buffer);
  if (buffered.empty()) return;
  if (e->depth > 0) {
    Ctx ctx(*e, nd);
    for (auto& sm : buffered) ctx.deliver(sm.first, sm.second);
    ctx.commit_events();
  } else {
    engine_unit(*e, nd, [&](Ctx& ctx) {
      for (auto& sm : buffered) ctx.deliver(sm.first, sm.second);
    });
  }
}

// Propose a payload (already serialized + threshold-encrypted by the
// Python layer) for the node's CURRENT epoch.  Returns 1 if accepted,
// 0 if the node already proposed this epoch (caller holds and retries).
int32_t hbe_propose(void* h, int32_t node, int32_t era, const uint8_t* payload,
                    uint64_t len) {
  Engine* e = (Engine*)h;
  Node& nd = e->nodes[node];
  if (nd.silent || nd.era != era || !nd.hb_init) return 0;
  if (nd.hb.state.proposed) return 0;
  Bytes data((const char*)payload, len);
  if (e->depth > 0) {
    Ctx ctx(*e, nd);
    ctx.hb_propose(data);
    ctx.commit_events();
  } else {
    engine_unit(*e, nd, [&](Ctx& ctx) { ctx.hb_propose(data); });
    // VirtualNet.send_input's _maybe_flush; adversary-driven inputs to
    // faulty nodes (broadcast_input's on_input_to_faulty path) don't
    // tick the flush counter.
    if (!nd.tampered) engine_count_unit(*e);
  }
  return 1;
}

uint64_t hbe_run(void* h, uint64_t max_deliveries) {
  return engine_run(*(Engine*)h, max_deliveries);
}

// Multicore run (engine_run_mt notes above).  Falls back to the
// sequential loop whenever a sequential-only feature is active
// (external crypto's flush cadence, adversary hooks) — the Python
// layer also rejects those combinations loudly.
uint64_t hbe_run_mt(void* h, uint64_t max_deliveries, int32_t n_threads) {
  Engine& e = *(Engine*)h;
  bool tampered = false;
  for (auto& nd : e.nodes) tampered = tampered || nd.tampered;
  // scalar_deferred: the deferred flush cadence is a sequential
  // ordering, exactly like ext mode's (the Python layer also rejects
  // threads > 1 with a scalar flush_every != 1).  Cluster mode is
  // sequential too (egress buffer + encode memo are single-writer).
  if (n_threads <= 1 || e.ext || e.pre_crank_cb || tampered ||
      scalar_deferred(e) || e.cluster.local >= 0)
    return engine_run(e, max_deliveries);
  return engine_run_mt(e, max_deliveries, n_threads);
}

uint64_t hbe_queue_len(void* h) { return ((Engine*)h)->queue.size(); }
uint64_t hbe_delivered(void* h) { return ((Engine*)h)->delivered; }
int32_t hbe_epoch(void* h, int32_t node) {
  Node& nd = ((Engine*)h)->nodes[node];
  return nd.hb_init ? nd.hb.epoch : -1;
}
int32_t hbe_era(void* h, int32_t node) { return ((Engine*)h)->nodes[node].era; }
int32_t hbe_has_proposed(void* h, int32_t node) {
  Node& nd = ((Engine*)h)->nodes[node];
  return (nd.hb_init && nd.hb.state.proposed) ? 1 : 0;
}

// Current batch accessors (valid during a batch callback: the engine
// thread holds the recursive cb_mu across batch_cb, and these are only
// legal to call from inside that callback — same thread, lock held).
// lint: holds-cb_mu (batch-callback context, see comment above)
int32_t hbe_batch_size(void* h) { return (int32_t)((Engine*)h)->cur_batch.size(); }
int32_t hbe_batch_proposer(void* h, int32_t i) {
  return ((Engine*)h)->cur_batch[i].first;  // lint: holds-cb_mu (batch cb)
}
uint64_t hbe_batch_payload_len(void* h, int32_t i) {
  return ((Engine*)h)->cur_batch[i].second->size();  // lint: holds-cb_mu (batch cb)
}
void hbe_batch_payload(void* h, int32_t i, uint8_t* out) {
  const Bytes& b = *((Engine*)h)->cur_batch[i].second;  // lint: holds-cb_mu (batch cb)
  std::memcpy(out, b.data(), b.size());
}

// -- external-crypto mode --------------------------------------------------

// Enable external (opaque-bytes) crypto: all share signing, combining,
// ciphertext parsing, and verification happen Python-side through the
// callbacks; flush_every mirrors VirtualNet's knob (0 = flush only when
// the delivery queue runs dry — maximal batch amortization; identical
// protocol outputs by the deferred-verification invariant).
void hbe_set_ext_crypto(void* h, int32_t flush_every, VerifyBatchCb verify_cb,
                        SignCb sign_cb, CombineCb combine_cb,
                        CtParseCb ct_parse_cb) {
  Engine* e = (Engine*)h;
  e->ext = true;
  e->flush_every = flush_every;
  e->verify_cb = verify_cb;
  e->sign_cb = sign_cb;
  e->combine_cb = combine_cb;
  e->ct_parse_cb = ct_parse_cb;
}

void hbe_set_flush_every(void* h, int32_t flush_every) {
  ((Engine*)h)->flush_every = flush_every;
}

// Scalar RLC deferred verification on/off (round 7) — overrides the
// HBBFT_TPU_COIN_RLC default read at hbe_create.  0 restores the
// pre-round-7 path (submit-time verdicts, per-unit eager flush); with
// 1, hbe_set_flush_every governs the scalar flush cadence (1 = the old
// flush points exactly, 0 = queue-dry).
void hbe_set_rlc(void* h, int32_t enabled) {
  ((Engine*)h)->rlc = enabled != 0;
}

// -- adversarial scheduling -------------------------------------------------

void hbe_set_pre_crank(void* h, PreCrankCb cb) {
  ((Engine*)h)->pre_crank_cb = cb;
}

// Swap two pending queue entries (valid during a PreCrankCb call).
void hbe_queue_swap(void* h, uint64_t i, uint64_t j) {
  Engine* e = (Engine*)h;
  if (i < e->queue.size() && j < e->queue.size() && i != j)
    std::swap(e->queue[i], e->queue[j]);
}

int32_t hbe_queue_dest(void* h, uint64_t i) {
  Engine* e = (Engine*)h;
  return i < e->queue.size() ? e->queue[i].dest : -1;
}

// -- tampering adversary ----------------------------------------------------
//
// hbe_set_tamper installs the callback; hbe_set_tampered marks a node
// adversary-owned (it keeps running the real algorithm — contrast
// hbe_set_silent).  The hbe_tamper_* accessors/mutators are valid ONLY
// during a TamperCb call and act on the private clone of the outgoing
// message (net/adversary.py TamperingAdversary's rewrite set: flipped
// bvals/aux/term/conf, doubled shares, corrupted roots and proofs).

void hbe_set_tamper(void* h, TamperCb cb) { ((Engine*)h)->tamper_cb = cb; }

void hbe_set_tampered(void* h, int32_t node, int32_t flag) {
  ((Engine*)h)->nodes[node].tampered = flag != 0;
}

int32_t hbe_tamper_bval(void* h) {
  Engine* e = (Engine*)h;
  return e->cur_tamper ? e->cur_tamper->bval : -1;
}

void hbe_tamper_set_bval(void* h, int32_t v) {
  Engine* e = (Engine*)h;
  if (e->cur_tamper) e->cur_tamper->bval = (uint8_t)v;
}

// Flip the low bit of the first root byte (adversary.py flip_root).
void hbe_tamper_flip_root(void* h) {
  Engine* e = (Engine*)h;
  if (e->cur_tamper) e->cur_tamper->root[0] ^= 1;
}

// Corrupt the Merkle proof's leaf value (adversary.py ValueMsg/EchoMsg
// branch: flip the first byte, or b"\x01" for an empty value).  Clones
// the shared ProofData — other queue references keep the honest proof —
// and resets the validity memo (it is keyed to the object).
void hbe_tamper_corrupt_proof(void* h) {
  Engine* e = (Engine*)h;
  if (!e->cur_tamper || !e->cur_tamper->proof) return;
  auto bad = std::make_shared<ProofData>(*e->cur_tamper->proof);
  if (bad->value.empty())
    bad->value = Bytes(1, '\x01');
  else
    bad->value[0] ^= 1;
  bad->valid_memo = -1;
  bad->valid_n = 0;
  e->cur_tamper->proof = std::move(bad);
}

// Share accessors: scalar mode exposes the 32-byte BE scalar; external
// mode the opaque share bytes.  The setter replaces whichever is live.
uint64_t hbe_tamper_share_len(void* h) {
  Engine* e = (Engine*)h;
  if (!e->cur_tamper) return 0;
  if (e->cur_tamper->share_b) return e->cur_tamper->share_b->size();
  return 32;
}

void hbe_tamper_share(void* h, uint8_t* out) {
  Engine* e = (Engine*)h;
  if (!e->cur_tamper) return;
  if (e->cur_tamper->share_b) {
    std::memcpy(out, e->cur_tamper->share_b->data(),
                e->cur_tamper->share_b->size());
    return;
  }
  u256_to_be32(e->cur_tamper->share, out);
}

void hbe_tamper_set_share(void* h, const uint8_t* data, uint64_t len) {
  Engine* e = (Engine*)h;
  if (!e->cur_tamper) return;
  if (e->cur_tamper->share_b) {
    e->cur_tamper->share_b =
        std::make_shared<const Bytes>((const char*)data, len);
    return;
  }
  e->cur_tamper->share = u256_from_be(data, len);
}

uint64_t hbe_pending_verifies(void* h) { return ((Engine*)h)->pool_items; }

// Delivery profiling: accumulated rdtsc cycles / delivery counts by
// message type (MsgType values 0..10).
uint64_t hbe_prof_cycles(void* h, int32_t type) {
  return ((Engine*)h)->prof_cycles[type & 15];
}
uint64_t hbe_prof_count(void* h, int32_t type) {
  return ((Engine*)h)->prof_count[type & 15];
}

// Force a flush of all pending pools (top-level only).
void hbe_flush(void* h) {
  Engine* e = (Engine*)h;
  if (e->pool_items > 0) {
    if (e->ext)
      engine_flush_ext(*e);
    else if (scalar_deferred(*e))
      engine_flush_scalar(*e);
  }
  cluster_announce(*e);  // no-op outside cluster mode
}

// Bytes-return helper for Sign/Combine callbacks: Python calls this with
// the opaque `ret` handle it was given.
void hbe_ret_bytes(void* ret, const uint8_t* data, uint64_t len) {
  ((Bytes*)ret)->assign((const char*)data, len);
}

// Verify-request accessors (valid during a VerifyBatchCb call).
int32_t hbe_vreq_kind(void* h, int32_t i) {
  return ((Engine*)h)->cur_vreqs[i]->kind;
}
int32_t hbe_vreq_era(void* h, int32_t i) {
  return ((Engine*)h)->cur_vreqs[i]->era;
}
int32_t hbe_vreq_sender(void* h, int32_t i) {
  return ((Engine*)h)->cur_vreqs[i]->sender;
}
uint64_t hbe_vreq_doc_len(void* h, int32_t i) {
  const Bytes* d = ((Engine*)h)->cur_vreqs[i]->doc;
  return d ? d->size() : 0;
}
void hbe_vreq_doc(void* h, int32_t i, uint8_t* out) {
  const Bytes* d = ((Engine*)h)->cur_vreqs[i]->doc;
  if (d) std::memcpy(out, d->data(), d->size());
}
uint64_t hbe_vreq_ct_len(void* h, int32_t i) {
  const Bytes* d = ((Engine*)h)->cur_vreqs[i]->ct;
  return d ? d->size() : 0;
}
void hbe_vreq_ct(void* h, int32_t i, uint8_t* out) {
  const Bytes* d = ((Engine*)h)->cur_vreqs[i]->ct;
  if (d) std::memcpy(out, d->data(), d->size());
}
uint64_t hbe_vreq_share_len(void* h, int32_t i) {
  const auto& s = ((Engine*)h)->cur_vreqs[i]->share;
  return s ? s->size() : 0;
}
void hbe_vreq_share(void* h, int32_t i, uint8_t* out) {
  const auto& s = ((Engine*)h)->cur_vreqs[i]->share;
  if (s) std::memcpy(out, s->data(), s->size());
}

// Combine-share accessors (valid during a CombineCb call).
int32_t hbe_comb_index(void* h, int32_t i) {
  return ((Engine*)h)->cur_comb[i].first;
}
uint64_t hbe_comb_share_len(void* h, int32_t i) {
  return ((Engine*)h)->cur_comb[i].second->size();
}
void hbe_comb_share(void* h, int32_t i, uint8_t* out) {
  const Bytes* b = ((Engine*)h)->cur_comb[i].second;
  std::memcpy(out, b->data(), b->size());
}

// -- cluster (one-engine-per-node) mode ------------------------------------
//
// hbe_set_local() switches an engine into cluster mode: only `local` is
// driven; every emission toward another id is serde-encoded and
// epoch-gated into an egress buffer (the native SenderQueue mirror —
// ClusterState notes).  The runtime moves bytes in BATCHES: one
// hbe_node_ingest_frames call per transport read burst, one
// hbe_node_egress_drain per run — the message-boundary API that lets a
// real-socket node keep the whole decode+handle loop native.

void hbe_set_local(void* h, int32_t local, int32_t window) {
  Engine* e = (Engine*)h;
  e->cluster.local = local;
  e->cluster.window = window;
  e->cluster.peer_epoch.assign(e->n, {0, 0});
  e->cluster.outbox.assign(e->n, {});
}

// Consume one protocol-message payload from peer `s` (sender bounds
// already checked by the caller).  Decoded algo messages queue for the
// local node; epoch_started announces update the peer window and
// release held egress; codec-rejects count CL_BAD_PAYLOAD, exactly the
// Python node's serde.try_loads + isinstance(SqMessage) gate.  Returns
// true when the payload decoded to a consumable message.
static bool cluster_consume_payload(Engine& e, ClusterState& c, int32_t s,
                                    const uint8_t* p, uint64_t len) {
  WireDecoded wm;
  if (!wire_decode(p, len, wm)) {
    c.stats[CL_BAD_PAYLOAD]++;
    return false;
  }
  c.stats[CL_HANDLED]++;
  if (wm.kind == 1)
    cluster_on_epoch_started(e, s, wm.era, wm.epoch);
  else if (wm.kind == 2) {
    if (e.ext && (wm.msg.type == BA_COIN || wm.msg.type == HB_DECRYPT)) {
      // External-crypto mode consumes opaque share bytes (share_b);
      // the wire codec decoded the scalar grammar's 32-byte element
      // into the U256 slot — rematerialize the exact BE bytes so the
      // handlers route them to the verify-batch callback instead of
      // the (keyless, in ext mode) internal scalar checks.
      uint8_t be[32];
      u256_to_be32(wm.msg.share, be);
      wm.msg.share_b = std::make_shared<const Bytes>((const char*)be, 32);
    }
    e.queue.push_back(
        {s, c.local, std::make_shared<const EMsg>(std::move(wm.msg))});
  } else
    c.stats[CL_IGNORED]++;
  return true;
}

// Ingest one batch of MSG-frame payloads: senders[i] is the (transport-
// authenticated) peer id of frame i, whose bytes are
// buf[offsets[i]..offsets[i+1]).  Returns the number of consumable
// frames, or -1 if not in cluster mode.
int64_t hbe_node_ingest_frames(void* h, const int32_t* senders,
                               const uint64_t* offsets, int32_t count,
                               const uint8_t* buf) {
  Engine& e = *(Engine*)h;
  ClusterState& c = e.cluster;
  if (c.local < 0) return -1;
  int64_t handled = 0;
  for (int32_t i = 0; i < count; ++i) {
    int32_t s = senders[i];
    if (s < 0 || s >= e.n || s == c.local) {
      c.stats[CL_BAD_PAYLOAD]++;
      continue;
    }
    if (cluster_consume_payload(e, c, s, buf + offsets[i],
                                offsets[i + 1] - offsets[i]))
      ++handled;
  }
  return handled;
}

// mirror: msgb-grammar
// Ingest one transport read burst in WIRE form (round 20 coalescing):
// record i from peer senders[i] covers buf[offsets[i]..offsets[i+1]).
// nmsgs[i] == 0 means the record is one plain MSG payload; >= 1 means
// an MSGB body in the framing grammar —
//     body := count:u32be  ( len:u32be  bytes[len] ) * count
// carrying that many messages, walked here with no Python slicing (the
// whole point of the fast path).  The transport grammar-checked every
// MSGB before handing it over, but each bound is re-checked: a
// violation counts the record's remaining messages as bad_payload and
// moves to the next record — defense in depth, never an OOB read.
// Returns the number of consumable MESSAGES, or -1 if not cluster mode.
int64_t hbe_node_ingest_wire(void* h, const int32_t* senders,
                             const uint32_t* nmsgs, const uint64_t* offsets,
                             int32_t count, const uint8_t* buf) {
  Engine& e = *(Engine*)h;
  ClusterState& c = e.cluster;
  if (c.local < 0) return -1;
  int64_t handled = 0;
  for (int32_t i = 0; i < count; ++i) {
    int32_t s = senders[i];
    const uint8_t* p = buf + offsets[i];
    uint64_t len = offsets[i + 1] - offsets[i];
    uint32_t nm = nmsgs[i];
    if (s < 0 || s >= e.n || s == c.local) {
      c.stats[CL_BAD_PAYLOAD] += nm ? nm : 1;
      continue;
    }
    if (nm == 0) {
      if (cluster_consume_payload(e, c, s, p, len)) ++handled;
      continue;
    }
    uint32_t declared = 0;
    if (len >= 4)
      declared = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
                 ((uint32_t)p[2] << 8) | (uint32_t)p[3];
    uint64_t off = 4;
    uint32_t done = 0;
    bool ok = len >= 4 && declared == nm;
    while (ok && done < nm) {
      if (off + 4 > len) {
        ok = false;
        break;
      }
      uint64_t el = ((uint64_t)p[off] << 24) | ((uint64_t)p[off + 1] << 16) |
                    ((uint64_t)p[off + 2] << 8) | (uint64_t)p[off + 3];
      off += 4;
      if (el > len - off) {
        ok = false;
        break;
      }
      if (cluster_consume_payload(e, c, s, p + off, el)) ++handled;
      off += el;
      ++done;
    }
    if (!ok || off != len)  // structural violation or trailing bytes
      c.stats[CL_BAD_PAYLOAD] += (nm > done) ? (nm - done) : 1;
  }
  return handled;
}

// Bytes needed to drain the current egress batch (8-byte record header
// per frame + payload bytes).
uint64_t hbe_node_egress_bytes(void* h) {
  ClusterState& c = ((Engine*)h)->cluster;
  return c.egress_bytes + 8ull * c.egress.size();
}

// Drain ALL pending egress records into `out` as
// [dest u32 LE][len u32 LE][payload]*; returns the record count, or -1
// if `cap` is smaller than hbe_node_egress_bytes() (drains nothing).
int64_t hbe_node_egress_drain(void* h, uint8_t* out, uint64_t cap) {
  ClusterState& c = ((Engine*)h)->cluster;
  uint64_t need = c.egress_bytes + 8ull * c.egress.size();
  if (need > cap) return -1;
  uint64_t pos = 0;
  for (auto& rec : c.egress) {
    uint32_t dest = (uint32_t)rec.first;
    uint32_t len = (uint32_t)rec.second->size();
    out[pos] = (uint8_t)dest;
    out[pos + 1] = (uint8_t)(dest >> 8);
    out[pos + 2] = (uint8_t)(dest >> 16);
    out[pos + 3] = (uint8_t)(dest >> 24);
    out[pos + 4] = (uint8_t)len;
    out[pos + 5] = (uint8_t)(len >> 8);
    out[pos + 6] = (uint8_t)(len >> 16);
    out[pos + 7] = (uint8_t)(len >> 24);
    std::memcpy(out + pos + 8, rec.second->data(), len);
    pos += 8ull + len;
  }
  int64_t nrec = (int64_t)c.egress.size();
  c.egress.clear();
  c.egress_bytes = 0;
  c.enc_src = nullptr;  // release the broadcast-memo pin with the batch
  c.enc_payload = nullptr;
  return nrec;
}

// mirror: msgb-grammar
// Drain ALL pending egress as per-destination MSGB bodies (round 20
// coalescing): records are
//     [dest u32 LE][nmsg u32 LE][body_len u32 LE][body]*
// where body is the framing MSGB grammar —
//     body := count:u32be  ( len:u32be  bytes[len] ) * count
// (big-endian like the frame headers; count == nmsg).  Grouping is per
// DEST across the whole batch: broadcast emission pushes one entry per
// dest consecutively, so grouping consecutive same-dest runs would
// coalesce nothing.  Per-dest FIFO — the only order the transport
// guarantees — is preserved.  Bodies split when the next element would
// push past `max_body` payload bytes (a single oversized element still
// gets its own nmsg==1 record; the Python caller strips those to plain
// MSG frames, exactly the uncoalesced arm's bytes).  Returns bytes
// written, or -1 if `cap` can't hold the worst case (drains nothing).
int64_t hbe_node_egress_drain_msgb(void* h, uint64_t max_body, uint8_t* out,
                                   uint64_t cap) {
  Engine& e = *(Engine*)h;
  ClusterState& c = e.cluster;
  // Worst case: every entry its own record — 12B record header + 4B
  // count + 4B element header + payload.
  uint64_t worst = c.egress_bytes + 20ull * c.egress.size();
  if (worst > cap) return -1;
  if (max_body < 16) max_body = 16;
  std::vector<std::vector<uint32_t>> by_dest(e.n);
  for (uint32_t i = 0; i < (uint32_t)c.egress.size(); ++i) {
    int32_t d = c.egress[i].first;
    if (d >= 0 && d < e.n) by_dest[(size_t)d].push_back(i);
  }
  auto wr32le = [&](uint64_t at, uint32_t v) {
    out[at] = (uint8_t)v;
    out[at + 1] = (uint8_t)(v >> 8);
    out[at + 2] = (uint8_t)(v >> 16);
    out[at + 3] = (uint8_t)(v >> 24);
  };
  auto wr32be = [&](uint64_t at, uint32_t v) {
    out[at] = (uint8_t)(v >> 24);
    out[at + 1] = (uint8_t)(v >> 16);
    out[at + 2] = (uint8_t)(v >> 8);
    out[at + 3] = (uint8_t)v;
  };
  uint64_t pos = 0;
  for (int32_t d = 0; d < e.n; ++d) {
    auto& idxs = by_dest[(size_t)d];
    uint32_t i = 0;
    while (i < (uint32_t)idxs.size()) {
      uint64_t hdr = pos;    // record header, written once nmsg is known
      uint64_t body0 = hdr + 12;  // body starts with the count field
      pos = body0 + 4;
      uint32_t nmsg = 0;
      uint64_t body_len = 4;
      while (i < (uint32_t)idxs.size()) {
        const BytesP& pl = c.egress[idxs[i]].second;
        uint64_t need = 4ull + pl->size();
        if (nmsg > 0 && body_len + need > max_body) break;
        wr32be(pos, (uint32_t)pl->size());
        std::memcpy(out + pos + 4, pl->data(), pl->size());
        pos += need;
        body_len += need;
        ++nmsg;
        ++i;
      }
      wr32le(hdr, (uint32_t)d);
      wr32le(hdr + 4, nmsg);
      wr32le(hdr + 8, (uint32_t)(pos - body0));
      wr32be(body0, nmsg);
    }
  }
  c.egress.clear();
  c.egress_bytes = 0;
  c.enc_src = nullptr;  // release the broadcast-memo pin with the batch
  c.enc_payload = nullptr;
  return (int64_t)pos;
}

// ClStat counters (see the enum): 0 handled, 1 bad_payload, 2 ignored,
// 3 dropped_stale, 4 held, 5 released, 6 sent, 7 announces.
uint64_t hbe_node_stat(void* h, int32_t idx) {
  if (idx < 0 || idx >= 8) return 0;
  return ((Engine*)h)->cluster.stats[idx];
}

// -- flight recorder (ISSUE 9) ----------------------------------------------

// Enable the milestone event ring with `cap` records (0 disables and
// frees it).  One preallocation here; emitting never allocates.
void hbe_trace_enable(void* h, uint32_t cap) {
  TraceState& t = ((Engine*)h)->trace;
  t.ring.assign(cap, TraceRec{});
  t.ring.shrink_to_fit();
  t.cap = cap;
  t.head = t.tail = 0;
  t.dropped = 0;
}

// Drain every retained record (oldest first) into `out` as packed
// 32-byte little-endian structs {i64 ts_ns; i32 node, kind, a, b, c, d}.
// Returns the record count, or -1 if `cap_bytes` is too small for the
// current backlog (drains nothing — call again with a bigger buffer).
int64_t hbe_trace_drain(void* h, uint8_t* out, uint64_t cap_bytes) {
  TraceState& t = ((Engine*)h)->trace;
  uint64_t count = t.head - t.tail;
  if (count * sizeof(TraceRec) > cap_bytes) return -1;
  for (uint64_t i = 0; i < count; ++i) {
    std::memcpy(out + i * sizeof(TraceRec), &t.ring[(t.tail + i) % t.cap],
                sizeof(TraceRec));
  }
  t.tail = t.head;
  return (int64_t)count;
}

// Records pending in the ring (sizes the drain buffer).
uint64_t hbe_trace_pending(void* h) {
  TraceState& t = ((Engine*)h)->trace;
  return t.head - t.tail;
}

// Total records lost to ring overflow since enable.
uint64_t hbe_trace_dropped(void* h) {
  return ((Engine*)h)->trace.dropped;
}

// -- wire-codec test surface ------------------------------------------------

// Decode verdict for one MSG payload under the scalar pin: -1 reject,
// 1 epoch_started, 2 algo engine message, 3 codec-valid-but-non-engine
// (join_plan / bare-HbMessage algo).  Accept (> 0) must track Python's
// `isinstance(serde.try_loads(data, ScalarSuite()), SqMessage)` exactly
// — the fuzz-parity tests sweep corruptions against this.
int32_t hbe_wire_classify(const uint8_t* data, uint64_t len) {
  WireDecoded wm;
  return wire_decode(data, len, wm) ? wm.kind : -1;
}

// Decode + re-encode one payload: pins the C encoder byte-for-byte
// against serde.dumps for every engine-representable message.  Returns
// the encoded length, -1 on decode reject, -2 if `cap` is too small,
// -3 for messages encode cannot represent (kind 3, or node ids outside
// the engine's int range).
int64_t hbe_wire_roundtrip(const uint8_t* data, uint64_t len, uint8_t* out,
                           uint64_t cap) {
  WireDecoded wm;
  if (!wire_decode(data, len, wm)) return -1;
  Bytes enc;
  if (wm.kind == 1) {
    enc = wire_encode_epoch_started(wm.era, wm.epoch);
  } else if (wm.kind == 2 && wm.msg.proposer >= 0) {
    enc = wire_encode_algo(wm.msg);
  } else {
    return -3;
  }
  if (enc.size() > cap) return -2;
  std::memcpy(out, enc.data(), enc.size());
  return (int64_t)enc.size();
}

// Fault log accessors (per observing node).
int32_t hbe_fault_count(void* h, int32_t node) {
  return (int32_t)((Engine*)h)->nodes[node].faults.size();
}
int32_t hbe_fault_subject(void* h, int32_t node, int32_t i) {
  return ((Engine*)h)->nodes[node].faults[i].subject;
}
const char* hbe_fault_kind(void* h, int32_t node, int32_t i) {
  return ((Engine*)h)->nodes[node].faults[i].kind;
}

}  // extern "C"
