// Native host data plane: SHA3-256 / Merkle hashing and GF(256)
// Reed-Solomon erasure coding.
//
// Reference behavior: the reference's native (Rust) hot loops outside the
// pairing path — `tiny-keccak` SHA3-256 Merkle hashing and the
// `reed-solomon-erasure` GF(2^8) codec used by its broadcast module
// (SURVEY.md §2 #4).  This library is the host-side fast path of the new
// framework's data plane; the TPU (JAX) path batches the same ops on
// device, and the pure-Python implementations remain as oracles.
//
// Bit-exact contracts (checked by tests/test_native.py):
//  * SHA3-256 == hashlib.sha3_256 (FIPS 202 padding 0x06).
//  * Merkle levels == hbbft_tpu.ops.merkle.MerkleTree (leaf prefix 0x00,
//    branch prefix 0x01, pad with H(0x00) to the next power of two).
//  * RS matrix == hbbft_tpu.ops.gf256.encoding_matrix (poly 0x11d,
//    Vandermonde a_i = exp(i), systematic normalization).
//
// C ABI only (loaded via ctypes); no exceptions across the boundary.

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <utility>
#include <vector>

#include "sha3_gf.h"

// --------------------------------------------------------------------------
// Keccak-f[1600] / SHA3-256 (implementation shared via sha3_gf.h)
// --------------------------------------------------------------------------

namespace {

inline void sha3_256_one(const uint8_t* in, size_t len, uint8_t* out32) {
  hbn::sha3_256(in, len, out32);
}

}  // namespace

extern "C" {

void hb_sha3_256(const uint8_t* in, uint64_t len, uint8_t* out32) {
  sha3_256_one(in, static_cast<size_t>(len), out32);
}

// n messages of msg_len bytes each, contiguous -> n 32-byte digests.
void hb_sha3_256_batch(const uint8_t* in, uint64_t n, uint64_t msg_len,
                       uint8_t* out) {
  for (uint64_t i = 0; i < n; ++i)
    sha3_256_one(in + i * msg_len, static_cast<size_t>(msg_len), out + 32 * i);
}

// Merkle tree over n_leaves leaves of leaf_len bytes each.
// Writes every level bottom-up into out_levels: the padded leaf level has
// size = next power of two >= n_leaves, then size/2, ... down to 1 — a
// total of 2*size-1 32-byte nodes.  Leaf hash = H(0x00 || leaf); padding
// leaves use H(0x00); branch hash = H(0x01 || left || right).  Matches
// hbbft_tpu.ops.merkle.MerkleTree exactly.
void hb_merkle_levels(const uint8_t* leaves, uint64_t n_leaves,
                      uint64_t leaf_len, uint8_t* out_levels) {
  uint64_t size = 1;
  while (size < n_leaves) size <<= 1;

  std::vector<uint8_t> buf(1 + leaf_len);
  buf[0] = 0x00;
  uint8_t* level = out_levels;
  for (uint64_t i = 0; i < n_leaves; ++i) {
    std::memcpy(buf.data() + 1, leaves + i * leaf_len, leaf_len);
    sha3_256_one(buf.data(), 1 + leaf_len, level + 32 * i);
  }
  if (n_leaves < size) {
    uint8_t empty[32];
    uint8_t prefix = 0x00;
    sha3_256_one(&prefix, 1, empty);
    for (uint64_t i = n_leaves; i < size; ++i)
      std::memcpy(level + 32 * i, empty, 32);
  }
  uint8_t branch[65];
  branch[0] = 0x01;
  while (size > 1) {
    uint8_t* next = level + 32 * size;
    for (uint64_t i = 0; i < size / 2; ++i) {
      std::memcpy(branch + 1, level + 64 * i, 64);
      sha3_256_one(branch, 65, next + 32 * i);
    }
    level = next;
    size >>= 1;
  }
}

}  // extern "C"

// --------------------------------------------------------------------------
// GF(256) Reed-Solomon (poly 0x11d, generator 2)
// --------------------------------------------------------------------------

namespace {

inline void gf_matmul(const uint8_t* a, const uint8_t* b, uint8_t* out,
                      size_t m, size_t k, size_t n) {
  hbn::gf_matmul(a, b, out, m, k, n);
}

inline bool gf_mat_inv(const uint8_t* m_in, uint8_t* inv_out, size_t n) {
  return hbn::gf_mat_inv_t<std::vector<uint8_t>>(m_in, inv_out, n);
}

inline bool encoding_matrix_uncached(size_t k, size_t n,
                                     std::vector<uint8_t>& out) {
  return hbn::encoding_matrix_t<std::vector<uint8_t>>(k, n, out);
}

// Per-(k, n) cache: Broadcast creates one codec per RBC instance but the
// dimensions repeat (fixed validator set), and the O(k^3) normalization
// would otherwise rerun on every encode/reconstruct call.
bool encoding_matrix(size_t k, size_t n, const std::vector<uint8_t>*& out) {
  static std::mutex mu;
  static std::map<std::pair<size_t, size_t>, std::vector<uint8_t>> cache;
  std::lock_guard<std::mutex> lock(mu);
  auto key = std::make_pair(k, n);
  auto it = cache.find(key);
  if (it == cache.end()) {
    std::vector<uint8_t> mat;
    if (!encoding_matrix_uncached(k, n, mat)) return false;
    it = cache.emplace(key, std::move(mat)).first;
  }
  out = &it->second;
  return true;
}

}  // namespace

extern "C" {

// data: k x size bytes -> parity: (n-k) x size bytes.  Returns 0 on ok.
int hb_rs_encode(const uint8_t* data, uint64_t k, uint64_t n, uint64_t size,
                 uint8_t* parity) {
  if (!k || k > n || n > 255) return 1;
  const std::vector<uint8_t>* mat;
  if (!encoding_matrix(k, n, mat)) return 2;
  gf_matmul(mat->data() + k * k, data, parity, n - k, k, size);
  return 0;
}

// shards: k x size bytes whose global indices are idxs[0..k) (sorted,
// unique, < n) -> out: the k x size data shards.  Returns 0 on ok.
int hb_rs_reconstruct(const uint8_t* shards, const uint64_t* idxs, uint64_t k,
                      uint64_t n, uint64_t size, uint8_t* out) {
  if (!k || k > n || n > 255) return 1;
  const std::vector<uint8_t>* mat;
  if (!encoding_matrix(k, n, mat)) return 2;
  std::vector<uint8_t> sub(k * k);
  for (uint64_t r = 0; r < k; ++r) {
    if (idxs[r] >= n) return 3;
    std::memcpy(sub.data() + r * k, mat->data() + idxs[r] * k, k);
  }
  std::vector<uint8_t> dec(k * k);
  if (!gf_mat_inv(sub.data(), dec.data(), k)) return 4;
  gf_matmul(dec.data(), shards, out, k, k, size);
  return 0;
}

// -- GF(2^16) variants (validator sets > 255; symbols 2B big-endian) -------

namespace {

// Shared per-(k, n) GF(2^16) matrix cache (same rationale as the
// GF(256) encoding_matrix helper above).
bool encoding_matrix16(uint64_t k, uint64_t n,
                       const std::vector<uint16_t>*& out) {
  static std::mutex mu;
  static std::map<std::pair<uint64_t, uint64_t>, std::vector<uint16_t>> cache;
  std::lock_guard<std::mutex> lock(mu);
  auto key = std::make_pair(k, n);
  auto it = cache.find(key);
  if (it == cache.end()) {
    std::vector<uint16_t> m;
    if (!hbn::encoding_matrix16_t<std::vector<uint16_t>>(k, n, m))
      return false;
    it = cache.emplace(key, std::move(m)).first;
  }
  out = &it->second;
  return true;
}

}  // namespace

// data: k x size bytes (size even) -> parity: (n-k) x size bytes.
int hb_rs16_encode(const uint8_t* data, uint64_t k, uint64_t n, uint64_t size,
                   uint8_t* parity) {
  if (!k || k > n || n > 65535 || size % 2) return 1;
  const std::vector<uint16_t>* mat;
  if (!encoding_matrix16(k, n, mat)) return 2;
  uint64_t nsym = size / 2;
  std::vector<uint16_t> dsym(k * nsym), psym((n - k) * nsym);
  hbn::bytes_to_sym16(data, k * nsym, dsym.data());
  hbn::gf16_matmul(mat->data() + k * k, dsym.data(), psym.data(), n - k, k,
                   nsym);
  hbn::sym16_to_bytes(psym.data(), (n - k) * nsym, parity);
  return 0;
}

int hb_rs16_reconstruct(const uint8_t* shards, const uint64_t* idxs,
                        uint64_t k, uint64_t n, uint64_t size, uint8_t* out) {
  if (!k || k > n || n > 65535 || size % 2) return 1;
  const std::vector<uint16_t>* mat;
  if (!encoding_matrix16(k, n, mat)) return 2;
  std::vector<uint16_t> sub(k * k);
  for (uint64_t r = 0; r < k; ++r) {
    if (idxs[r] >= n) return 3;
    std::memcpy(sub.data() + r * k, mat->data() + idxs[r] * k, 2 * k);
  }
  std::vector<uint16_t> dec(k * k);
  if (!hbn::gf16_mat_inv_t<std::vector<uint16_t>>(sub.data(), dec.data(), k))
    return 4;
  uint64_t nsym = size / 2;
  std::vector<uint16_t> hsym(k * nsym), dsym(k * nsym);
  hbn::bytes_to_sym16(shards, k * nsym, hsym.data());
  hbn::gf16_matmul(dec.data(), hsym.data(), dsym.data(), k, k, nsym);
  hbn::sym16_to_bytes(dsym.data(), k * nsym, out);
  return 0;
}

}  // extern "C"
