// Native host data plane: SHA3-256 / Merkle hashing and GF(256)
// Reed-Solomon erasure coding.
//
// Reference behavior: the reference's native (Rust) hot loops outside the
// pairing path — `tiny-keccak` SHA3-256 Merkle hashing and the
// `reed-solomon-erasure` GF(2^8) codec used by its broadcast module
// (SURVEY.md §2 #4).  This library is the host-side fast path of the new
// framework's data plane; the TPU (JAX) path batches the same ops on
// device, and the pure-Python implementations remain as oracles.
//
// Bit-exact contracts (checked by tests/test_native.py):
//  * SHA3-256 == hashlib.sha3_256 (FIPS 202 padding 0x06).
//  * Merkle levels == hbbft_tpu.ops.merkle.MerkleTree (leaf prefix 0x00,
//    branch prefix 0x01, pad with H(0x00) to the next power of two).
//  * RS matrix == hbbft_tpu.ops.gf256.encoding_matrix (poly 0x11d,
//    Vandermonde a_i = exp(i), systematic normalization).
//
// C ABI only (loaded via ctypes); no exceptions across the boundary.

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <utility>
#include <vector>

// --------------------------------------------------------------------------
// Keccak-f[1600] / SHA3-256
// --------------------------------------------------------------------------

namespace {

const uint64_t RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808AULL,
    0x8000000080008000ULL, 0x000000000000808BULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008AULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000AULL,
    0x000000008000808BULL, 0x800000000000008BULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800AULL, 0x800000008000000AULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

const int RHO[25] = {0,  1,  62, 28, 27, 36, 44, 6,  55, 20, 3,  10, 43,
                     25, 39, 41, 45, 15, 21, 8,  18, 2,  61, 56, 14};

inline uint64_t rotl64(uint64_t x, int r) {
  return r ? (x << r) | (x >> (64 - r)) : x;
}

void keccak_f(uint64_t st[25]) {
  for (int round = 0; round < 24; ++round) {
    // theta
    uint64_t c[5], d[5];
    for (int x = 0; x < 5; ++x)
      c[x] = st[x] ^ st[x + 5] ^ st[x + 10] ^ st[x + 15] ^ st[x + 20];
    for (int x = 0; x < 5; ++x) {
      d[x] = c[(x + 4) % 5] ^ rotl64(c[(x + 1) % 5], 1);
      for (int y = 0; y < 5; ++y) st[x + 5 * y] ^= d[x];
    }
    // rho + pi
    uint64_t b[25];
    for (int x = 0; x < 5; ++x)
      for (int y = 0; y < 5; ++y)
        b[y + 5 * ((2 * x + 3 * y) % 5)] = rotl64(st[x + 5 * y], RHO[x + 5 * y]);
    // chi
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x)
        st[x + 5 * y] = b[x + 5 * y] ^ (~b[(x + 1) % 5 + 5 * y] & b[(x + 2) % 5 + 5 * y]);
    // iota
    st[0] ^= RC[round];
  }
}

const size_t RATE = 136;  // SHA3-256

void sha3_256_one(const uint8_t* in, size_t len, uint8_t* out32) {
  uint64_t st[25];
  std::memset(st, 0, sizeof(st));
  uint8_t block[RATE];
  while (len >= RATE) {
    for (size_t i = 0; i < RATE / 8; ++i) {
      uint64_t w;
      std::memcpy(&w, in + 8 * i, 8);
      st[i] ^= w;  // little-endian host assumed (x86-64 / aarch64)
    }
    keccak_f(st);
    in += RATE;
    len -= RATE;
  }
  std::memset(block, 0, RATE);
  std::memcpy(block, in, len);
  block[len] = 0x06;
  block[RATE - 1] ^= 0x80;
  for (size_t i = 0; i < RATE / 8; ++i) {
    uint64_t w;
    std::memcpy(&w, block + 8 * i, 8);
    st[i] ^= w;
  }
  keccak_f(st);
  std::memcpy(out32, st, 32);
}

}  // namespace

extern "C" {

void hb_sha3_256(const uint8_t* in, uint64_t len, uint8_t* out32) {
  sha3_256_one(in, static_cast<size_t>(len), out32);
}

// n messages of msg_len bytes each, contiguous -> n 32-byte digests.
void hb_sha3_256_batch(const uint8_t* in, uint64_t n, uint64_t msg_len,
                       uint8_t* out) {
  for (uint64_t i = 0; i < n; ++i)
    sha3_256_one(in + i * msg_len, static_cast<size_t>(msg_len), out + 32 * i);
}

// Merkle tree over n_leaves leaves of leaf_len bytes each.
// Writes every level bottom-up into out_levels: the padded leaf level has
// size = next power of two >= n_leaves, then size/2, ... down to 1 — a
// total of 2*size-1 32-byte nodes.  Leaf hash = H(0x00 || leaf); padding
// leaves use H(0x00); branch hash = H(0x01 || left || right).  Matches
// hbbft_tpu.ops.merkle.MerkleTree exactly.
void hb_merkle_levels(const uint8_t* leaves, uint64_t n_leaves,
                      uint64_t leaf_len, uint8_t* out_levels) {
  uint64_t size = 1;
  while (size < n_leaves) size <<= 1;

  std::vector<uint8_t> buf(1 + leaf_len);
  buf[0] = 0x00;
  uint8_t* level = out_levels;
  for (uint64_t i = 0; i < n_leaves; ++i) {
    std::memcpy(buf.data() + 1, leaves + i * leaf_len, leaf_len);
    sha3_256_one(buf.data(), 1 + leaf_len, level + 32 * i);
  }
  if (n_leaves < size) {
    uint8_t empty[32];
    uint8_t prefix = 0x00;
    sha3_256_one(&prefix, 1, empty);
    for (uint64_t i = n_leaves; i < size; ++i)
      std::memcpy(level + 32 * i, empty, 32);
  }
  uint8_t branch[65];
  branch[0] = 0x01;
  while (size > 1) {
    uint8_t* next = level + 32 * size;
    for (uint64_t i = 0; i < size / 2; ++i) {
      std::memcpy(branch + 1, level + 64 * i, 64);
      sha3_256_one(branch, 65, next + 32 * i);
    }
    level = next;
    size >>= 1;
  }
}

}  // extern "C"

// --------------------------------------------------------------------------
// GF(256) Reed-Solomon (poly 0x11d, generator 2)
// --------------------------------------------------------------------------

namespace {

struct GfTables {
  uint8_t exp[512];
  int log[256];
  // mul[a][b] flat table: one 64KB lookup beats exp/log chains in the
  // row-accumulation inner loop.
  uint8_t mul[256 * 256];
  GfTables() {
    int x = 1;
    for (int i = 0; i < 255; ++i) {
      exp[i] = static_cast<uint8_t>(x);
      log[x] = i;
      x <<= 1;
      if (x & 0x100) x ^= 0x11d;
    }
    for (int i = 255; i < 510; ++i) exp[i] = exp[i - 255];
    exp[510] = exp[511] = 0;
    log[0] = 0;
    for (int a = 0; a < 256; ++a)
      for (int b = 0; b < 256; ++b)
        mul[a * 256 + b] =
            (a && b) ? exp[log[a] + log[b]] : 0;
  }
};

const GfTables GF;

inline uint8_t gf_mul(uint8_t a, uint8_t b) { return GF.mul[a * 256 + b]; }

inline uint8_t gf_inv(uint8_t a) { return GF.exp[255 - GF.log[a]]; }

// out[r][c] ^= sum over i of a[r][i]*b[i][c]  (dims m x k @ k x n)
void gf_matmul(const uint8_t* a, const uint8_t* b, uint8_t* out, size_t m,
               size_t k, size_t n) {
  std::memset(out, 0, m * n);
  for (size_t r = 0; r < m; ++r) {
    for (size_t i = 0; i < k; ++i) {
      uint8_t coef = a[r * k + i];
      if (!coef) continue;
      const uint8_t* row = b + i * n;
      const uint8_t* tab = GF.mul + static_cast<size_t>(coef) * 256;
      uint8_t* dst = out + r * n;
      for (size_t c = 0; c < n; ++c) dst[c] ^= tab[row[c]];
    }
  }
}

// Gauss-Jordan inverse over GF(256); returns false if singular.
bool gf_mat_inv(const uint8_t* m_in, uint8_t* inv_out, size_t n) {
  std::vector<uint8_t> a(m_in, m_in + n * n);
  std::vector<uint8_t> inv(n * n, 0);
  for (size_t i = 0; i < n; ++i) inv[i * n + i] = 1;
  for (size_t col = 0; col < n; ++col) {
    size_t pivot = col;
    while (pivot < n && !a[pivot * n + col]) ++pivot;
    if (pivot == n) return false;
    if (pivot != col) {
      for (size_t j = 0; j < n; ++j) {
        std::swap(a[col * n + j], a[pivot * n + j]);
        std::swap(inv[col * n + j], inv[pivot * n + j]);
      }
    }
    uint8_t pinv = gf_inv(a[col * n + col]);
    for (size_t j = 0; j < n; ++j) {
      a[col * n + j] = gf_mul(a[col * n + j], pinv);
      inv[col * n + j] = gf_mul(inv[col * n + j], pinv);
    }
    for (size_t r = 0; r < n; ++r) {
      uint8_t f = a[r * n + col];
      if (r == col || !f) continue;
      for (size_t j = 0; j < n; ++j) {
        a[r * n + j] ^= gf_mul(a[col * n + j], f);
        inv[r * n + j] ^= gf_mul(inv[col * n + j], f);
      }
    }
  }
  std::memcpy(inv_out, inv.data(), n * n);
  return true;
}

// Systematic n x k encoding matrix, identical to gf256.encoding_matrix.
bool encoding_matrix_uncached(size_t k, size_t n, std::vector<uint8_t>& out) {
  std::vector<uint8_t> vand(n * k);
  for (size_t i = 0; i < n; ++i)
    for (size_t j = 0; j < k; ++j) vand[i * k + j] = GF.exp[(i * j) % 255];
  std::vector<uint8_t> top_inv(k * k);
  if (!gf_mat_inv(vand.data(), top_inv.data(), k)) return false;
  out.resize(n * k);
  gf_matmul(vand.data(), top_inv.data(), out.data(), n, k, k);
  return true;
}

// Per-(k, n) cache: Broadcast creates one codec per RBC instance but the
// dimensions repeat (fixed validator set), and the O(k^3) normalization
// would otherwise rerun on every encode/reconstruct call.
bool encoding_matrix(size_t k, size_t n, const std::vector<uint8_t>*& out) {
  static std::mutex mu;
  static std::map<std::pair<size_t, size_t>, std::vector<uint8_t>> cache;
  std::lock_guard<std::mutex> lock(mu);
  auto key = std::make_pair(k, n);
  auto it = cache.find(key);
  if (it == cache.end()) {
    std::vector<uint8_t> mat;
    if (!encoding_matrix_uncached(k, n, mat)) return false;
    it = cache.emplace(key, std::move(mat)).first;
  }
  out = &it->second;
  return true;
}

}  // namespace

extern "C" {

// data: k x size bytes -> parity: (n-k) x size bytes.  Returns 0 on ok.
int hb_rs_encode(const uint8_t* data, uint64_t k, uint64_t n, uint64_t size,
                 uint8_t* parity) {
  if (!k || k > n || n > 255) return 1;
  const std::vector<uint8_t>* mat;
  if (!encoding_matrix(k, n, mat)) return 2;
  gf_matmul(mat->data() + k * k, data, parity, n - k, k, size);
  return 0;
}

// shards: k x size bytes whose global indices are idxs[0..k) (sorted,
// unique, < n) -> out: the k x size data shards.  Returns 0 on ok.
int hb_rs_reconstruct(const uint8_t* shards, const uint64_t* idxs, uint64_t k,
                      uint64_t n, uint64_t size, uint8_t* out) {
  if (!k || k > n || n > 255) return 1;
  const std::vector<uint8_t>* mat;
  if (!encoding_matrix(k, n, mat)) return 2;
  std::vector<uint8_t> sub(k * k);
  for (uint64_t r = 0; r < k; ++r) {
    if (idxs[r] >= n) return 3;
    std::memcpy(sub.data() + r * k, mat->data() + idxs[r] * k, k);
  }
  std::vector<uint8_t> dec(k * k);
  if (!gf_mat_inv(sub.data(), dec.data(), k)) return 4;
  gf_matmul(dec.data(), shards, out, k, k, size);
  return 0;
}

}  // extern "C"
