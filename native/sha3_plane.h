// Batched sha3 plane for the native engine (ISSUE 17): multi-message
// Keccak-f[1600] over contiguous equal-length inputs with an AVX-512
// 8-lane state-parallel arm and the hbn:: scalar arm behind the SAME
// runtime dispatch point as the field plane (hbf::simd_mode — one cell,
// one env knob, one in-process force for both planes).
//
// Layering:
//   * hbn::sha3_256 (sha3_gf.h) — the scalar FIPS-202 arm, always
//     available, also the per-message tail of every batched call.
//   * hbf_ifma_sha3_256_x8 (native/field_ifma.cpp) — eight independent
//     SHA3-256 states side by side, one Keccak lane word per __m512i
//     (state-parallel, NOT a tree/interleaved construction).  Compiled
//     only in the -mavx512ifma unit per the COMDAT rule; stubbed when
//     the toolchain lacks the flag, in which case hbf_ifma_compiled()
//     is 0 and the dispatch never reaches it.
//
// THE DISPATCH-IDENTITY CONTRACT (docs/INVARIANTS.md "SIMD dispatch
// identity") applies verbatim: both arms compute the exact FIPS-202
// SHA3-256 digest of each message — the boundary values are digests,
// never internal state — so protocol outputs are byte-identical across
// HBBFT_TPU_SIMD=0/1 by construction and the equivalence suites pin it.
//
// Consumers (engine.cpp): kdf_stream block generation, Merkle
// leaf/branch level hashing in RBC encode/decode.  Long single messages
// (the DKG ciphertext digest) go through sha3_256_one — lane
// parallelism cannot help one message, and the stats keep that honest.
//
// This header references the hbf_ifma_* arm and therefore must be
// included ONLY by translation units linked against field_ifma.o (the
// engine); hbbft_native.cpp must keep including sha3_gf.h alone.

#ifndef HBBFT_SHA3_PLANE_H
#define HBBFT_SHA3_PLANE_H

#include <atomic>
#include <cstdint>
#include <cstring>

#include "field_plane.h"
#include "sha3_gf.h"

extern "C" {
// 8 messages of msg_len bytes at in, in+msg_len, ...; 8 digests of 32
// bytes at out, out+32, ...
void hbf_ifma_sha3_256_x8(const uint8_t* in, size_t msg_len, uint8_t* out);
}

namespace hbs {

// Batch-plane counters (relaxed atomics: multicore workers hash too).
// Exported via hbe_sha3_stats for the self-documenting benchmark lines.
struct Sha3Stats {
  std::atomic<uint64_t> batch_calls{0};  // sha3_256_batch invocations
  std::atomic<uint64_t> batch_msgs{0};   // messages through the batch entry
  std::atomic<uint64_t> ifma_msgs{0};    // of those, hashed by the 8-lane arm
  std::atomic<uint64_t> single_msgs{0};  // messages through sha3_256_one
};

inline Sha3Stats& stats() {
  static Sha3Stats s;
  return s;
}

// One message (the honest path for long inputs: ct digests and the
// like).  Same digest as hbn::sha3_256 — it IS hbn::sha3_256.
inline void sha3_256_one(const uint8_t* in, size_t len, uint8_t out32[32]) {
  stats().single_msgs.fetch_add(1, std::memory_order_relaxed);
  hbn::sha3_256(in, len, out32);
}

// count messages, each msg_len bytes, contiguous at stride msg_len;
// digests written contiguously (32 bytes each) to out.  Dispatches
// full groups of 8 to the state-parallel arm, the remainder to the
// scalar arm — per-message digests are identical either way.
inline void sha3_256_batch(const uint8_t* in, size_t msg_len, size_t count,
                           uint8_t* out) {
  if (!count) return;
  Sha3Stats& st = stats();
  st.batch_calls.fetch_add(1, std::memory_order_relaxed);
  st.batch_msgs.fetch_add(count, std::memory_order_relaxed);
  size_t i = 0;
  if (hbf::simd_mode() && count >= 8) {
    size_t main = count & ~(size_t)7;
    for (; i < main; i += 8)
      hbf_ifma_sha3_256_x8(in + i * msg_len, msg_len, out + i * 32);
    st.ifma_msgs.fetch_add(main, std::memory_order_relaxed);
  }
  for (; i < count; ++i) hbn::sha3_256(in + i * msg_len, msg_len, out + i * 32);
}

}  // namespace hbs

#endif  // HBBFT_SHA3_PLANE_H
