// Shared native primitives: Keccak-f[1600]/SHA3-256 and GF(256) tables.
//
// Used by both the data-plane library (hbbft_native.cpp) and the
// protocol engine (engine.cpp).  Bit-exact contracts (pinned by
// tests/test_native.py):
//  * SHA3-256 == hashlib.sha3_256 (FIPS 202 padding 0x06).
//  * GF(256) tables: poly 0x11d, generator 2 (gf256.py).
//
// Header-only (inline / function-local statics) so each .so carries its
// own copy without ODR issues across the C ABI boundary.

#pragma once

#include <cstdint>
#include <cstring>

namespace hbn {

inline uint64_t rotl64(uint64_t x, int r) {
  return r ? (x << r) | (x >> (64 - r)) : x;
}

inline void keccak_f(uint64_t st[25]) {
  static const uint64_t RC[24] = {
      0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808AULL,
      0x8000000080008000ULL, 0x000000000000808BULL, 0x0000000080000001ULL,
      0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008AULL,
      0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000AULL,
      0x000000008000808BULL, 0x800000000000008BULL, 0x8000000000008089ULL,
      0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
      0x000000000000800AULL, 0x800000008000000AULL, 0x8000000080008081ULL,
      0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};
  static const int RHO[25] = {0,  1,  62, 28, 27, 36, 44, 6,  55, 20, 3,  10,
                              43, 25, 39, 41, 45, 15, 21, 8,  18, 2,  61, 56,
                              14};
  for (int round = 0; round < 24; ++round) {
    uint64_t c[5], d[5];
    for (int x = 0; x < 5; ++x)
      c[x] = st[x] ^ st[x + 5] ^ st[x + 10] ^ st[x + 15] ^ st[x + 20];
    for (int x = 0; x < 5; ++x) {
      d[x] = c[(x + 4) % 5] ^ rotl64(c[(x + 1) % 5], 1);
      for (int y = 0; y < 5; ++y) st[x + 5 * y] ^= d[x];
    }
    uint64_t b[25];
    for (int x = 0; x < 5; ++x)
      for (int y = 0; y < 5; ++y)
        b[y + 5 * ((2 * x + 3 * y) % 5)] = rotl64(st[x + 5 * y], RHO[x + 5 * y]);
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x)
        st[x + 5 * y] =
            b[x + 5 * y] ^ (~b[(x + 1) % 5 + 5 * y] & b[(x + 2) % 5 + 5 * y]);
    st[0] ^= RC[round];
  }
}

const size_t SHA3_RATE = 136;  // SHA3-256

inline void sha3_256(const uint8_t* in, size_t len, uint8_t* out32) {
  uint64_t st[25];
  std::memset(st, 0, sizeof(st));
  uint8_t block[SHA3_RATE];
  while (len >= SHA3_RATE) {
    for (size_t i = 0; i < SHA3_RATE / 8; ++i) {
      uint64_t w;
      std::memcpy(&w, in + 8 * i, 8);
      st[i] ^= w;  // little-endian host assumed (x86-64 / aarch64)
    }
    keccak_f(st);
    in += SHA3_RATE;
    len -= SHA3_RATE;
  }
  std::memset(block, 0, SHA3_RATE);
  std::memcpy(block, in, len);
  block[len] = 0x06;
  block[SHA3_RATE - 1] ^= 0x80;
  for (size_t i = 0; i < SHA3_RATE / 8; ++i) {
    uint64_t w;
    std::memcpy(&w, block + 8 * i, 8);
    st[i] ^= w;
  }
  keccak_f(st);
  std::memcpy(out32, st, 32);
}

// Incremental SHA3 for multi-part inputs (avoids concatenation copies).
struct Sha3 {
  uint64_t st[25];
  uint8_t buf[SHA3_RATE];
  size_t fill = 0;
  Sha3() { std::memset(st, 0, sizeof(st)); }
  void update(const uint8_t* in, size_t len) {
    while (len) {
      size_t take = SHA3_RATE - fill;
      if (take > len) take = len;
      std::memcpy(buf + fill, in, take);
      fill += take;
      in += take;
      len -= take;
      if (fill == SHA3_RATE) {
        for (size_t i = 0; i < SHA3_RATE / 8; ++i) {
          uint64_t w;
          std::memcpy(&w, buf + 8 * i, 8);
          st[i] ^= w;
        }
        keccak_f(st);
        fill = 0;
      }
    }
  }
  void final(uint8_t* out32) {
    std::memset(buf + fill, 0, SHA3_RATE - fill);
    buf[fill] = 0x06;
    buf[SHA3_RATE - 1] ^= 0x80;
    for (size_t i = 0; i < SHA3_RATE / 8; ++i) {
      uint64_t w;
      std::memcpy(&w, buf + 8 * i, 8);
      st[i] ^= w;
    }
    keccak_f(st);
    std::memcpy(out32, st, 32);
  }
};

// -- GF(256), poly 0x11d, generator 2 ---------------------------------------

struct GfTables {
  uint8_t exp[512];
  int log[256];
  uint8_t mul[256 * 256];
  GfTables() {
    int x = 1;
    for (int i = 0; i < 255; ++i) {
      exp[i] = static_cast<uint8_t>(x);
      log[x] = i;
      x <<= 1;
      if (x & 0x100) x ^= 0x11d;
    }
    for (int i = 255; i < 510; ++i) exp[i] = exp[i - 255];
    exp[510] = exp[511] = 0;
    log[0] = 0;
    for (int a = 0; a < 256; ++a)
      for (int b = 0; b < 256; ++b)
        mul[a * 256 + b] = (a && b) ? exp[log[a] + log[b]] : 0;
  }
};

inline const GfTables& gf() {
  static const GfTables tables;
  return tables;
}

inline uint8_t gf_mul(uint8_t a, uint8_t b) { return gf().mul[a * 256 + b]; }
inline uint8_t gf_inv(uint8_t a) { return gf().exp[255 - gf().log[a]]; }

// out[r][c] ^= sum over i of a[r][i]*b[i][c]  (dims m x k @ k x n)
inline void gf_matmul(const uint8_t* a, const uint8_t* b, uint8_t* out,
                      size_t m, size_t k, size_t n) {
  if (!m || !n) return;  // empty shards: memset/memcpy on a null
                         // vector data() is UB even at size 0
  std::memset(out, 0, m * n);
  for (size_t r = 0; r < m; ++r) {
    for (size_t i = 0; i < k; ++i) {
      uint8_t coef = a[r * k + i];
      if (!coef) continue;
      const uint8_t* row = b + i * n;
      const uint8_t* tab = gf().mul + static_cast<size_t>(coef) * 256;
      uint8_t* dst = out + r * n;
      for (size_t c = 0; c < n; ++c) dst[c] ^= tab[row[c]];
    }
  }
}

// Gauss-Jordan inverse over GF(256); false if singular.  Needs <vector>.
template <typename Vec>
inline bool gf_mat_inv_t(const uint8_t* m_in, uint8_t* inv_out, size_t n) {
  Vec a(m_in, m_in + n * n);
  Vec inv(n * n, 0);
  for (size_t i = 0; i < n; ++i) inv[i * n + i] = 1;
  for (size_t col = 0; col < n; ++col) {
    size_t pivot = col;
    while (pivot < n && !a[pivot * n + col]) ++pivot;
    if (pivot == n) return false;
    if (pivot != col) {
      for (size_t j = 0; j < n; ++j) {
        uint8_t t = a[col * n + j];
        a[col * n + j] = a[pivot * n + j];
        a[pivot * n + j] = t;
        t = inv[col * n + j];
        inv[col * n + j] = inv[pivot * n + j];
        inv[pivot * n + j] = t;
      }
    }
    uint8_t pinv = gf_inv(a[col * n + col]);
    for (size_t j = 0; j < n; ++j) {
      a[col * n + j] = gf_mul(a[col * n + j], pinv);
      inv[col * n + j] = gf_mul(inv[col * n + j], pinv);
    }
    for (size_t r = 0; r < n; ++r) {
      uint8_t f = a[r * n + col];
      if (r == col || !f) continue;
      for (size_t j = 0; j < n; ++j) {
        a[r * n + j] ^= gf_mul(a[col * n + j], f);
        inv[r * n + j] ^= gf_mul(inv[col * n + j], f);
      }
    }
  }
  std::memcpy(inv_out, inv.data(), n * n);
  return true;
}

// Systematic n x k encoding matrix (gf256.encoding_matrix semantics).
// GF(256) Vandermonde points exp[i] are distinct only for n <= 255 —
// callers MUST use the GF(2^16) codec below past that.
template <typename Vec>
inline bool encoding_matrix_t(size_t k, size_t n, Vec& out) {
  if (n > 255) return false;
  Vec vand(n * k);
  for (size_t i = 0; i < n; ++i)
    for (size_t j = 0; j < k; ++j) vand[i * k + j] = gf().exp[(i * j) % 255];
  Vec top_inv(k * k);
  if (!gf_mat_inv_t<Vec>(vand.data(), top_inv.data(), k)) return false;
  out.assign(n * k, 0);
  gf_matmul(vand.data(), top_inv.data(), out.data(), n, k, k);
  return true;
}

// -- GF(2^16), poly 0x1100B, generator 2 ------------------------------------
//
// The large-validator-set RBC codec: GF(256) runs out of distinct
// Vandermonde evaluation points at 255 shards, so networks with more
// than 255 validators erasure-code over GF(2^16) (65535 points).
// Symbols are TWO bytes, big-endian on the wire (matches the numpy
// '>u2' view in ops/gf256.py); shard lengths must be even.

struct Gf16Tables {
  std::vector<uint16_t> exp;  // 2*65535 (wraparound, no mod in mul)
  std::vector<int32_t> log;   // 65536
  Gf16Tables() : exp(131070, 0), log(65536, 0) {
    uint32_t x = 1;
    for (int i = 0; i < 65535; ++i) {
      exp[i] = static_cast<uint16_t>(x);
      log[x] = i;
      x <<= 1;
      if (x & 0x10000) x ^= 0x1100B;
    }
    for (int i = 0; i < 65535; ++i) exp[65535 + i] = exp[i];
  }
};

inline const Gf16Tables& gf16() {
  static const Gf16Tables tables;
  return tables;
}

inline uint16_t gf16_mul(uint16_t a, uint16_t b) {
  if (!a || !b) return 0;
  const Gf16Tables& t = gf16();
  return t.exp[t.log[a] + t.log[b]];
}

inline uint16_t gf16_inv(uint16_t a) {
  const Gf16Tables& t = gf16();
  return t.exp[65535 - t.log[a]];
}

// out = a @ b over GF(2^16); dims m x k @ k x n, u16 symbol arrays.
inline void gf16_matmul(const uint16_t* a, const uint16_t* b, uint16_t* out,
                        size_t m, size_t k, size_t n) {
  const Gf16Tables& t = gf16();
  std::memset(out, 0, m * n * 2);
  for (size_t r = 0; r < m; ++r) {
    for (size_t i = 0; i < k; ++i) {
      uint16_t coef = a[r * k + i];
      if (!coef) continue;
      int32_t lc = t.log[coef];
      const uint16_t* row = b + i * n;
      uint16_t* dst = out + r * n;
      for (size_t c = 0; c < n; ++c)
        if (row[c]) dst[c] ^= t.exp[lc + t.log[row[c]]];
    }
  }
}

template <typename Vec16>
inline bool gf16_mat_inv_t(const uint16_t* m_in, uint16_t* inv_out, size_t n) {
  Vec16 a(m_in, m_in + n * n);
  Vec16 inv(n * n, 0);
  for (size_t i = 0; i < n; ++i) inv[i * n + i] = 1;
  for (size_t col = 0; col < n; ++col) {
    size_t pivot = col;
    while (pivot < n && !a[pivot * n + col]) ++pivot;
    if (pivot == n) return false;
    if (pivot != col) {
      for (size_t j = 0; j < n; ++j) {
        std::swap(a[col * n + j], a[pivot * n + j]);
        std::swap(inv[col * n + j], inv[pivot * n + j]);
      }
    }
    uint16_t pinv = gf16_inv(a[col * n + col]);
    for (size_t j = 0; j < n; ++j) {
      a[col * n + j] = gf16_mul(a[col * n + j], pinv);
      inv[col * n + j] = gf16_mul(inv[col * n + j], pinv);
    }
    for (size_t r = 0; r < n; ++r) {
      uint16_t f = a[r * n + col];
      if (r == col || !f) continue;
      for (size_t j = 0; j < n; ++j) {
        a[r * n + j] ^= gf16_mul(a[col * n + j], f);
        inv[r * n + j] ^= gf16_mul(inv[col * n + j], f);
      }
    }
  }
  std::memcpy(inv_out, inv.data(), n * n * 2);
  return true;
}

// Systematic n x k encoding matrix over GF(2^16) (points exp16[i],
// distinct for n <= 65535).
template <typename Vec16>
inline bool encoding_matrix16_t(size_t k, size_t n, Vec16& out) {
  if (n > 65535) return false;
  const Gf16Tables& t = gf16();
  Vec16 vand(n * k);
  for (size_t i = 0; i < n; ++i)
    for (size_t j = 0; j < k; ++j)
      vand[i * k + j] = t.exp[(i * j) % 65535];
  Vec16 top_inv(k * k);
  if (!gf16_mat_inv_t<Vec16>(vand.data(), top_inv.data(), k)) return false;
  out.assign(n * k, 0);
  gf16_matmul(vand.data(), top_inv.data(), out.data(), n, k, k);
  return true;
}

// Big-endian byte <-> u16 symbol conversion (wire format).
inline void bytes_to_sym16(const uint8_t* in, size_t n_sym, uint16_t* out) {
  for (size_t i = 0; i < n_sym; ++i)
    out[i] = (uint16_t)((in[2 * i] << 8) | in[2 * i + 1]);
}

inline void sym16_to_bytes(const uint16_t* in, size_t n_sym, uint8_t* out) {
  for (size_t i = 0; i < n_sym; ++i) {
    out[2 * i] = (uint8_t)(in[i] >> 8);
    out[2 * i + 1] = (uint8_t)(in[i] & 0xFF);
  }
}

}  // namespace hbn
