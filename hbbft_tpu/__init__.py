"""hbbft_tpu — a TPU-native asynchronous BFT consensus framework.

A brand-new implementation (not a port) with the capability surface of the
``zhaohanjin/hbbft`` reference (Honey Badger BFT, Rust): the full protocol
stack — reliable broadcast with Reed-Solomon erasure coding and Merkle
proofs, binary agreement with a threshold-signature common coin,
asynchronous common subset, HoneyBadger atomic broadcast with per-epoch
threshold decryption, dynamic membership with distributed key generation —
rebuilt idiomatically in Python/JAX with a pluggable ``CryptoBackend``
whose pairing-heavy inner loop (BLS12-381 share verification) is batched
onto TPU.

Reference layout (upstream ``poanetwork/hbbft`` paths; the fork checkout at
/root/reference was empty at survey time — see SURVEY.md "evidentiary
status"): ``src/lib.rs``, ``src/traits.rs`` for the substrate;
``src/{broadcast,binary_agreement,subset,honey_badger,...}`` for protocols;
the external ``threshold_crypto`` crate for L0.
"""

__version__ = "0.1.0"

from hbbft_tpu.protocols.traits import (  # noqa: F401
    ConsensusProtocol,
    SourcedMessage,
    Step,
    Target,
    TargetedMessage,
)
from hbbft_tpu.protocols.network_info import NetworkInfo  # noqa: F401
from hbbft_tpu.protocols.fault_log import Fault, FaultLog  # noqa: F401
