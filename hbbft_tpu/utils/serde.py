"""Contribution serialization.

The reference serializes contributions with ``bincode`` before threshold-
encrypting them (upstream ``src/honey_badger/honey_badger.rs``).  Here we
use pickle: each node only ever deserializes data it (or consensus)
committed to, in a closed in-process system; no cross-version wire
stability is required.  Centralized here so a stricter codec can be
swapped in without touching protocol code.
"""

from __future__ import annotations

import pickle
from typing import Any


def dumps(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=4)


def loads(data: bytes) -> Any:
    return pickle.loads(data)


def try_loads(data: bytes) -> Any:
    """Returns None on any malformed input (Byzantine-supplied bytes)."""
    try:
        return pickle.loads(data)
    except Exception:
        return None
