"""Safe self-describing codec for committed/wire bytes.

The reference serializes contributions with ``bincode`` — a schema-driven
codec that can only ever produce instances of the expected types
(upstream ``src/honey_badger/honey_badger.rs``).  This module is the
equivalent trust boundary here: Subset-committed payloads include bytes
*authored by a Byzantine proposer* and faithfully RBC'd, so arbitrary-
object deserialization (pickle) is out of the question.

Format: one tag byte per value, length-prefixed payloads, strict bounds
checking, and a bounded recursion depth.  Composite application types
(Ciphertext, SignedVote, DKG Parts, ...) are encoded through an explicit
registry (:mod:`hbbft_tpu.wire`): each registered type packs to a tuple
of primitive values and unpacks through a validating constructor — an
attacker can choose *which* registered type to decode and its field
values, but never what code runs.

Wire grammar (all integers big-endian):

    value   := NONE | TRUE | FALSE | int | bytes | str
             | tuple | list | dict | struct | group
    int     := 0x03 sign:u8 len:u32 magnitude[len]
    bytes   := 0x04 len:u32 raw[len]
    str     := 0x05 len:u32 utf8[len]
    tuple   := 0x06 count:u32 value*count
    list    := 0x07 count:u32 value*count
    dict    := 0x08 count:u32 (value value)*count
    struct  := 0x10 nlen:u8 name[nlen] fields:tuple
    group   := 0x11 nlen:u8 suite[nlen] g:u8 len:u32 raw[len]
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple, Type

# mirror: serde-scan-limits — these two constants are passed verbatim
#     to the native token scan (`_native_scan` below) and duplicated as
#     literals at engine.cpp's own hbe_serde_scan call site; HBX001
#     checks the values match, HBX003 keeps the anchors paired.
MAX_DEPTH = 64
_MAX_LEN = 1 << 28  # 256 MiB hard cap on any single length field


class EncodeError(TypeError):
    """Object (or one of its fields) is not encodable."""


class DecodeError(ValueError):
    """Malformed, truncated, oversized, or type-invalid input bytes."""


_T_NONE = 0x00
_T_FALSE = 0x01
_T_TRUE = 0x02
_T_INT = 0x03
_T_BYTES = 0x04
_T_STR = 0x05
_T_TUPLE = 0x06
_T_LIST = 0x07
_T_DICT = 0x08
_T_STRUCT = 0x10
_T_GROUP = 0x11


# ---------------------------------------------------------------------------
# Registries
# ---------------------------------------------------------------------------

# name -> (cls, pack(obj) -> tuple, unpack(fields_tuple) -> obj)
_STRUCTS: Dict[str, Tuple[Type, Callable[[Any], tuple], Callable[[tuple], Any]]] = {}
_STRUCT_BY_CLS: Dict[Type, str] = {}

# name -> token-level fast builder for the native-scan path (see
# register_token_struct).  Purely an accelerator: absence or a None
# return changes nothing.
_TOKEN_STRUCTS: Dict[str, Callable] = {}

# suite name -> suite instance (for group-element decoding)
_SUITES: Dict[str, Any] = {}

_bootstrapped = False


def register_struct(
    name: str,
    cls: Type,
    pack: Callable[[Any], tuple],
    unpack: Callable[[tuple], Any],
) -> None:
    """Register an application type.  ``unpack`` MUST validate its input
    (field count, field types, value ranges) and raise :class:`DecodeError`
    on anything off — it is the trust boundary for that type."""
    _STRUCTS[name] = (cls, pack, unpack)
    _STRUCT_BY_CLS[cls] = name


def register_suite(suite: Any) -> None:
    _SUITES[suite.name] = suite


def register_token_struct(name: str, fast: Callable) -> None:
    """Register a token-level fast builder for struct ``name`` on the
    native-scan decode path (hot committed types; wire.py registers one
    for the scalar ``"ct"`` — DKG-epoch payloads carry ~N^2 of them).

    ``fast(tokens, ti, data, suite_name)`` is called at the struct's
    FIELDS node and must either return ``(obj, next_ti)`` — with ``obj``
    EXACTLY what the generic ``_build`` + registered unpack would
    construct and ``next_ti`` just past the fields subtree — or return
    None for anything even slightly unusual (other suite, pin mismatch,
    malformed shape), deferring to the generic path so the canonical
    validation and DecodeError behavior apply.  The scan/pure
    fuzz-equivalence tests (tests/test_serde.py) pin both properties.
    """
    _TOKEN_STRUCTS[name] = fast


def get_suite(name: str) -> Any:
    """Suite registered under ``name`` (raises :class:`DecodeError`)."""
    suite = _SUITES.get(name)
    if suite is None:
        raise DecodeError(f"unknown suite {name!r}")
    return suite


def _bootstrap() -> None:
    """Load the module that registers all boundary types (lazy to avoid
    an import cycle: protocols import serde).  The flag is only set after
    a successful import so a transient failure stays loud and retryable
    instead of silently leaving the registry empty."""
    global _bootstrapped
    if not _bootstrapped:
        import hbbft_tpu.wire  # noqa: F401  (registers on import)

        _bootstrapped = True


# ---------------------------------------------------------------------------
# Encoding
# ---------------------------------------------------------------------------


def _u32(n: int) -> bytes:
    return n.to_bytes(4, "big")


def _encode(obj: Any, out: bytearray, depth: int) -> None:
    if depth > MAX_DEPTH:
        raise EncodeError("nesting too deep")
    if obj is None:
        out.append(_T_NONE)
    elif obj is True:
        out.append(_T_TRUE)
    elif obj is False:
        out.append(_T_FALSE)
    elif type(obj) is int:
        mag = abs(obj)
        raw = mag.to_bytes((mag.bit_length() + 7) // 8, "big") if mag else b""
        out.append(_T_INT)
        out.append(1 if obj < 0 else 0)
        out += _u32(len(raw))
        out += raw
    elif type(obj) in (bytes, bytearray, memoryview):
        raw = bytes(obj)
        out.append(_T_BYTES)
        out += _u32(len(raw))
        out += raw
    elif type(obj) is str:
        raw = obj.encode("utf-8")
        out.append(_T_STR)
        out += _u32(len(raw))
        out += raw
    elif type(obj) is tuple:
        out.append(_T_TUPLE)
        out += _u32(len(obj))
        for item in obj:
            _encode(item, out, depth + 1)
    elif type(obj) is list:
        out.append(_T_LIST)
        out += _u32(len(obj))
        for item in obj:
            _encode(item, out, depth + 1)
    elif type(obj) is dict:
        out.append(_T_DICT)
        out += _u32(len(obj))
        for k, v in obj.items():
            _encode(k, out, depth + 1)
            _encode(v, out, depth + 1)
    else:
        name = _STRUCT_BY_CLS.get(type(obj))
        if name is not None:
            # Pre-rendered encoding memo: producers that construct hot
            # struct objects natively (the scalar KEM's DKG ciphertexts)
            # attach the exact bytes this branch would emit — the memo
            # is a pure function of the frozen fields, and producers pin
            # byte-equality with this recursive path by test.
            try:
                cached = obj.__dict__.get("_serde_cache")
            except AttributeError:
                cached = None
            # depth + 2: the memo'd struct subtree reaches two levels
            # below this node (fields tuple -> leaf values); splicing it
            # deeper would let dumps emit bytes loads rejects.
            if cached is not None and depth + 2 <= MAX_DEPTH:
                out += cached
                return
            _, pack, _ = _STRUCTS[name]
            nraw = name.encode("utf-8")
            out.append(_T_STRUCT)
            out.append(len(nraw))
            out += nraw
            _encode(pack(obj), out, depth + 1)
            return
        # Group element of a registered suite?
        suite_name = getattr(obj, "serde_suite_name", None)
        group = getattr(obj, "serde_group", None)
        if suite_name is not None and group in (1, 2):
            raw = obj.to_bytes()
            nraw = suite_name.encode("utf-8")
            out.append(_T_GROUP)
            out.append(len(nraw))
            out += nraw
            out.append(group)
            out += _u32(len(raw))
            out += raw
            return
        raise EncodeError(f"unencodable type: {type(obj).__name__}")


def dumps(obj: Any) -> bytes:
    _bootstrap()
    out = bytearray()
    _encode(obj, out, 0)
    return bytes(out)


# ---------------------------------------------------------------------------
# Decoding
# ---------------------------------------------------------------------------


class _Reader:
    __slots__ = ("data", "pos", "suite_name")

    def __init__(self, data: bytes, suite_name: Any = None) -> None:
        self.data = data
        self.pos = 0
        self.suite_name = suite_name

    def take(self, n: int) -> bytes:
        if n > _MAX_LEN or self.pos + n > len(self.data):
            raise DecodeError("truncated")
        chunk = self.data[self.pos : self.pos + n]
        self.pos += n
        return chunk

    def u8(self) -> int:
        return self.take(1)[0]

    def u32(self) -> int:
        return int.from_bytes(self.take(4), "big")


def _decode(r: _Reader, depth: int) -> Any:
    if depth > MAX_DEPTH:
        raise DecodeError("nesting too deep")
    tag = r.u8()
    if tag == _T_NONE:
        return None
    if tag == _T_TRUE:
        return True
    if tag == _T_FALSE:
        return False
    if tag == _T_INT:
        sign = r.u8()
        if sign not in (0, 1):
            raise DecodeError("bad int sign")
        raw = r.take(r.u32())
        if raw[:1] == b"\x00":
            raise DecodeError("non-minimal int")  # canonical form only
        mag = int.from_bytes(raw, "big")
        if sign and mag == 0:
            raise DecodeError("negative zero")
        return -mag if sign else mag
    if tag == _T_BYTES:
        return r.take(r.u32())
    if tag == _T_STR:
        try:
            return r.take(r.u32()).decode("utf-8")
        except UnicodeDecodeError as e:
            raise DecodeError("bad utf-8") from e
    if tag in (_T_TUPLE, _T_LIST):
        count = r.u32()
        if count > len(r.data) - r.pos:  # each element costs >= 1 byte
            raise DecodeError("count exceeds input")
        items = [_decode(r, depth + 1) for _ in range(count)]
        return tuple(items) if tag == _T_TUPLE else items
    if tag == _T_DICT:
        count = r.u32()
        if 2 * count > len(r.data) - r.pos:
            raise DecodeError("count exceeds input")
        d: Dict[Any, Any] = {}
        for _ in range(count):
            k = _decode(r, depth + 1)
            v = _decode(r, depth + 1)
            try:
                if k in d:
                    raise DecodeError("duplicate dict key")
                d[k] = v
            except TypeError as e:
                raise DecodeError("unhashable dict key") from e
        return d
    if tag == _T_STRUCT:
        name_raw = r.take(r.u8())
        try:
            name = name_raw.decode("utf-8")
        except UnicodeDecodeError as e:
            raise DecodeError("bad struct name") from e
        entry = _STRUCTS.get(name)
        if entry is None:
            raise DecodeError(f"unknown struct {name!r}")
        fields = _decode(r, depth + 1)
        if not isinstance(fields, tuple):
            raise DecodeError("struct fields must be a tuple")
        try:
            return entry[2](fields)  # validating unpack
        except DecodeError:
            raise
        except Exception as e:  # unpack bug or missed validation: still safe
            raise DecodeError(f"invalid {name}: {e}") from e
    if tag == _T_GROUP:
        name_raw = r.take(r.u8())
        try:
            suite_name = name_raw.decode("utf-8")
        except UnicodeDecodeError as e:
            raise DecodeError("bad suite name") from e
        if r.suite_name is not None and suite_name != r.suite_name:
            raise DecodeError(
                f"suite {suite_name!r} not allowed (expected {r.suite_name!r})"
            )
        suite = get_suite(suite_name)
        group = r.u8()
        raw = r.take(r.u32())
        try:
            if group == 1:
                return suite.g1_from_bytes(raw)
            if group == 2:
                return suite.g2_from_bytes(raw)
        except ValueError as e:
            raise DecodeError(str(e)) from e
        raise DecodeError("bad group id")
    raise DecodeError(f"unknown tag 0x{tag:02x}")


def loads(data: bytes, suite: Any = None) -> Any:
    """Decode; raises :class:`DecodeError` on any malformed input.

    ``suite`` pins the deployment's crypto suite: group elements naming
    any other registered suite are rejected at the frame level.  Without
    the pin, attacker-authored bytes could select the INSECURE
    ``ScalarSuite`` for objects that later reach signature checks — every
    caller decoding wire/committed bytes in a real deployment MUST pass
    its network's suite.
    """
    _bootstrap()
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise DecodeError("not bytes")
    data = bytes(data)
    suite_name = None if suite is None else suite.name
    # Native token scan (C does all byte-level structural validation in
    # one pass; the Python builder below only constructs objects and
    # applies the semantic checks).  Any unavailability falls back to
    # the recursive pure-Python decoder — identical accept/reject
    # behavior either way, pinned by tests/test_serde.py.
    tokens = _native_scan(data)
    if tokens is not None:
        if tokens is _SCAN_MALFORMED:
            raise DecodeError("malformed (native scan)")
        obj, ti = _build(tokens, 0, data, suite_name, 0)
        return obj
    r = _Reader(data, suite_name)
    obj = _decode(r, 0)
    if r.pos != len(r.data):
        raise DecodeError("trailing bytes")
    return obj


def try_loads(data: bytes, suite: Any = None) -> Any:
    """Returns None on any malformed input (Byzantine-supplied bytes)."""
    try:
        return loads(data, suite=suite)
    except DecodeError:
        return None


# ---------------------------------------------------------------------------
# Native-scan decode path (C tokenizer in native/engine.cpp + this builder)
# ---------------------------------------------------------------------------

_SCAN_MALFORMED = object()
_NATIVE_SCAN_LIB: Any = False  # False = not probed yet; None = unavailable


def _native_scan(data: bytes):
    """Token triples from the C scanner, _SCAN_MALFORMED on structural
    rejection, or None when the native path is unavailable (fall back).
    """
    global _NATIVE_SCAN_LIB
    lib = _NATIVE_SCAN_LIB
    if lib is False or lib is None:
        # Only use an engine that is ALREADY loaded — decoding must
        # never trigger the engine's g++ build (a lightweight consumer's
        # first loads() would block on a minutes-class compile).  The
        # probe re-runs until an engine appears (e.g. the first
        # NativeQhbNet / scalar-KEM user loads it), then caches.
        try:
            from hbbft_tpu import native_engine  # lazy: import cycle

            # Any loaded width works — hbe_serde_scan is NodeSet-width
            # independent (a >256-node net loads only the w8 build).
            lib = next(
                (v for v in native_engine._LIBS.values() if v is not None),
                None,
            )
        except Exception:
            lib = None
        _NATIVE_SCAN_LIB = lib if lib is not None else None
    if lib is None:
        return None
    import ctypes

    n = len(data)
    # Optimistic buffer: typical values cost >= 4 input bytes per token
    # triple; pathological inputs (runs of 1-byte values) retry with the
    # exact worst case (one triple per input byte, +1 for the root).
    for triples in (n // 2 + 64, n + 2):
        buf = (ctypes.c_int64 * (3 * triples))()
        rc = int(
            lib.hbe_serde_scan(data, n, buf, triples, MAX_DEPTH, _MAX_LEN)
        )
        if rc == -2:
            continue
        if rc < 0:
            return _SCAN_MALFORMED
        return buf
    return _SCAN_MALFORMED  # unreachable: second buffer is worst-case


def _build(t: Any, ti: int, data: bytes, suite_name: Any, depth: int):
    """Construct the value at token index ``ti``; returns (value, next).

    Semantic twin of ``_decode`` over the pre-validated token stream:
    registries, utf-8, dict-key and unpack validation live here, byte
    structure was validated by the scanner.
    """
    if depth > MAX_DEPTH:  # scanner enforces this too; belt-and-braces
        raise DecodeError("nesting too deep")
    base = 3 * ti
    tag = t[base]
    off = t[base + 1]
    ln = t[base + 2]
    ti += 1
    if tag == _T_NONE:
        return None, ti
    if tag == _T_TRUE:
        return True, ti
    if tag == _T_FALSE:
        return False, ti
    low = tag & 0xFF
    if low == _T_INT:
        mag = int.from_bytes(data[off : off + ln], "big")
        return (-mag if tag >> 8 else mag), ti
    if tag == _T_BYTES:
        return data[off : off + ln], ti
    if tag == _T_STR:
        try:
            return data[off : off + ln].decode("utf-8"), ti
        except UnicodeDecodeError as e:
            raise DecodeError("bad utf-8") from e
    if tag in (_T_TUPLE, _T_LIST):
        items = []
        for _ in range(off):  # off = count
            v, ti = _build(t, ti, data, suite_name, depth + 1)
            items.append(v)
        return (tuple(items) if tag == _T_TUPLE else items), ti
    if tag == _T_DICT:
        d: Dict[Any, Any] = {}
        for _ in range(off):
            k, ti = _build(t, ti, data, suite_name, depth + 1)
            v, ti = _build(t, ti, data, suite_name, depth + 1)
            try:
                if k in d:
                    raise DecodeError("duplicate dict key")
                d[k] = v
            except TypeError as e:
                raise DecodeError("unhashable dict key") from e
        return d, ti
    if tag == _T_STRUCT:
        try:
            name = data[off : off + ln].decode("utf-8")
        except UnicodeDecodeError as e:
            raise DecodeError("bad struct name") from e
        entry = _STRUCTS.get(name)
        if entry is None:
            raise DecodeError(f"unknown struct {name!r}")
        fast = _TOKEN_STRUCTS.get(name)
        if fast is not None:
            res = fast(t, ti, data, suite_name)
            if res is not None:
                return res
        fields, ti = _build(t, ti, data, suite_name, depth + 1)
        if not isinstance(fields, tuple):
            raise DecodeError("struct fields must be a tuple")
        try:
            return entry[2](fields), ti
        except DecodeError:
            raise
        except Exception as e:
            raise DecodeError(f"invalid {name}: {e}") from e
    if tag == _T_GROUP:
        try:
            sname = data[off : off + ln].decode("utf-8")
        except UnicodeDecodeError as e:
            raise DecodeError("bad suite name") from e
        if suite_name is not None and sname != suite_name:
            raise DecodeError(
                f"suite {sname!r} not allowed (expected {suite_name!r})"
            )
        suite = get_suite(sname)
        base = 3 * ti
        group, poff, plen = t[base], t[base + 1], t[base + 2]
        ti += 1
        raw = data[poff : poff + plen]
        try:
            if group == 1:
                return suite.g1_from_bytes(raw), ti
            if group == 2:
                return suite.g2_from_bytes(raw), ti
        except ValueError as e:
            raise DecodeError(str(e)) from e
        raise DecodeError("bad group id")
    raise DecodeError(f"unknown tag 0x{tag:02x}")
