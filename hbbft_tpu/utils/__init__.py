"""Small shared utilities (canonical hashing, deterministic RNG)."""

from __future__ import annotations

import hashlib
from typing import Union


def sha3_256(data: bytes) -> bytes:
    return hashlib.sha3_256(data).digest()


def canonical_bytes(*parts: Union[bytes, str, int]) -> bytes:
    """Length-prefixed concatenation — collision-free framing for hashing."""
    out = bytearray()
    for p in parts:
        if isinstance(p, str):
            p = p.encode("utf-8")
        elif isinstance(p, int):
            p = p.to_bytes((max(p.bit_length(), 1) + 7) // 8, "big", signed=False)
        out += len(p).to_bytes(8, "big")
        out += p
    return bytes(out)


def xor_bytes(a: bytes, b: bytes) -> bytes:
    """XOR of the common prefix (wide-int XOR: ~10x the per-byte loop,
    which showed up in epoch profiles via the KEM mask path)."""
    n = min(len(a), len(b))
    return (
        int.from_bytes(a[:n], "little") ^ int.from_bytes(b[:n], "little")
    ).to_bytes(n, "little")


def kdf_stream(seed: bytes, n: int) -> bytes:
    """Expand ``seed`` into ``n`` bytes via SHA3-256 in counter mode."""
    out = bytearray()
    ctr = 0
    while len(out) < n:
        out += hashlib.sha3_256(seed + ctr.to_bytes(8, "big")).digest()
        ctr += 1
    return bytes(out[:n])
