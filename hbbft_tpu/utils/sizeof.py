"""Approximate wire-size estimator for protocol messages.

The virtual-time simulation (``examples/simulation.py``, the analog of
upstream ``examples/simulation.rs``'s bandwidth model) needs a byte size
for every in-flight message to drive its bandwidth/latency model.  The
strict committed-bytes codec (:mod:`hbbft_tpu.utils.serde`) deliberately
refuses protocol envelopes — they never cross a byte boundary in-process
— so sizing uses this structural walk instead: dataclass-ish objects
contribute their fields, group elements their encoding length, plain
containers their contents, everything gets a small per-object framing
overhead comparable to a real codec's tags.
"""

from __future__ import annotations

from typing import Any

_FRAME = 4  # per-object tag/length overhead, bincode-ish


def estimate(obj: Any, _depth: int = 0) -> int:
    if _depth > 32:
        return _FRAME
    if obj is None or isinstance(obj, bool):
        return 1
    if isinstance(obj, int):
        return _FRAME + max(1, (obj.bit_length() + 7) // 8)
    if isinstance(obj, float):
        return 8
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return _FRAME + len(obj)
    if isinstance(obj, str):
        return _FRAME + len(obj.encode("utf-8"))
    if isinstance(obj, (tuple, list, set, frozenset)):
        return _FRAME + sum(estimate(i, _depth + 1) for i in obj)
    if isinstance(obj, dict):
        return _FRAME + sum(
            estimate(k, _depth + 1) + estimate(v, _depth + 1)
            for k, v in obj.items()
        )
    to_bytes = getattr(obj, "to_bytes", None)
    if callable(to_bytes):
        try:
            return _FRAME + len(to_bytes())
        except Exception:
            pass
    # dataclasses / slotted protocol envelopes: walk their fields
    fields = getattr(obj, "__dict__", None)
    if fields:
        return _FRAME + sum(estimate(v, _depth + 1) for v in fields.values())
    slots = getattr(type(obj), "__slots__", None)
    if slots:
        return _FRAME + sum(
            estimate(getattr(obj, s, None), _depth + 1) for s in slots
        )
    return _FRAME
