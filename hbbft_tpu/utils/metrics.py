"""Structured metrics: counters, timers, and JAX profiler traces.

Reference behavior: the reference has no metrics beyond ``log`` lines and
the per-message CPU-time accounting of its simulation example (SURVEY.md
§5.1/§5.5).  This framework's observability surface is richer because the
crypto plane batches onto an accelerator — per-flush timing and batch
sizes are the operational signal — while staying optional: a ``Metrics``
instance is plain data, and nothing in the protocol plane requires one.

Usage::

    from hbbft_tpu.utils.metrics import Metrics

    m = Metrics()
    with m.timer("flush"):
        pool.flush(backend)
    m.count("verify_requests", 12)
    print(m.report())

``Metrics.trace(path)`` wraps ``jax.profiler.trace`` so a verify flush
can be captured for TensorBoard without importing jax at module scope.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple


@dataclass
class TimerStats:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0

    def add(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        self.max_s = max(self.max_s, dt)

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


@dataclass
class SummaryStats:
    """A quantile snapshot of some distribution (latency percentiles).

    Unlike :class:`TimerStats` (which accumulates raw observations),
    this is a point-in-time EXPORT: the producer owns the streaming
    estimator (e.g. :class:`hbbft_tpu.traffic.latency.LatencyHistogram`)
    and re-publishes count/sum/quantiles whenever it likes — last write
    wins, like gauges.  Keeping the estimator out of Metrics keeps
    Metrics plain data and lets producers pick their own accuracy/
    memory trade-off.
    """

    count: int = 0
    total: float = 0.0
    quantiles: Dict[float, float] = field(default_factory=dict)


@dataclass
class Metrics:
    """Counters + gauges + timers; cheap enough to leave on.

    Mutations are lock-protected: one instance is routinely shared
    between a transport's selector thread and its node's protocol
    thread (hbbft_tpu/transport/), and ``+=`` on a dict entry is not
    atomic across bytecodes — concurrent same-key counts would lose
    increments without the lock.
    """

    counters: Dict[str, int] = field(default_factory=lambda: defaultdict(int))
    timers: Dict[str, TimerStats] = field(default_factory=dict)
    gauges: Dict[str, float] = field(default_factory=dict)
    summaries: Dict[str, SummaryStats] = field(default_factory=dict)
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n

    def gauge(self, name: str, value: float) -> None:
        """Set a point-in-time observable (queue depth, bytes buffered);
        last write wins, unlike the monotonic counters."""
        with self._lock:
            self.gauges[name] = value

    def summary(
        self,
        name: str,
        quantiles: Dict[float, float],
        count: int,
        total: float,
    ) -> None:
        """Publish a quantile snapshot (gauge semantics: last write
        wins).  ``quantiles`` maps q in [0, 1] to the estimated value at
        that quantile; ``count``/``total`` are the observation count and
        sum backing the estimate (the Prometheus summary ``_count`` /
        ``_sum`` pair)."""
        with self._lock:
            self.summaries[name] = SummaryStats(
                count=count, total=total, quantiles=dict(quantiles)
            )

    @contextmanager
    def timer(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.timers.setdefault(name, TimerStats()).add(dt)

    @contextmanager
    def trace(self, logdir: str) -> Iterator[None]:
        """JAX profiler capture (TensorBoard format); no-op without jax."""
        try:
            import jax
        except ImportError:  # pragma: no cover
            yield
            return
        with jax.profiler.trace(logdir):
            yield

    def merge(self, other: "Metrics") -> None:
        # list() snapshots: ``other`` may belong to a live transport or
        # protocol thread that inserts new keys mid-merge (GIL makes the
        # item reads safe; iterating the live dict would not be)
        with self._lock:
            for k, v in list(other.counters.items()):
                self.counters[k] += v
            for k, st in list(other.timers.items()):
                mine = self.timers.setdefault(k, TimerStats())
                mine.count += st.count
                mine.total_s += st.total_s
                mine.max_s = max(mine.max_s, st.max_s)
            # gauges are point-in-time: the merged-in value wins (merge
            # order is "newer last" everywhere this is used)
            self.gauges.update(list(other.gauges.items()))
            # summaries share gauge semantics (snapshots, newest wins)
            self.summaries.update(list(other.summaries.items()))

    def _snapshot(
        self,
    ) -> Tuple[
        Dict[str, int],
        Dict[str, float],
        Dict[str, TimerStats],
        Dict[str, SummaryStats],
    ]:
        """Consistent copies for the export methods — they may run on a
        scrape thread while the owning threads keep inserting keys."""
        with self._lock:
            return (
                dict(self.counters),
                dict(self.gauges),
                dict(self.timers),
                dict(self.summaries),
            )

    def report(self) -> str:
        counters, gauges, timers, summaries = self._snapshot()
        lines = []
        if counters:
            lines.append("counters:")
            for k in sorted(counters):
                lines.append(f"  {k:<40} {counters[k]}")
        if gauges:
            lines.append("gauges:")
            for k in sorted(gauges):
                lines.append(f"  {k:<40} {gauges[k]:.12g}")
        if timers:
            lines.append("timers:  (count / mean ms / max ms / total s)")
            for k in sorted(timers):
                st = timers[k]
                lines.append(
                    f"  {k:<40} {st.count:>6} {st.mean_s * 1e3:>9.2f} "
                    f"{st.max_s * 1e3:>9.2f} {st.total_s:>8.2f}"
                )
        if summaries:
            lines.append("summaries:  (count / quantiles)")
            for k in sorted(summaries):
                sm = summaries[k]
                qs = " ".join(
                    f"p{q * 100:g}={v:.6g}" for q, v in sorted(sm.quantiles.items())
                )
                lines.append(f"  {k:<40} {sm.count:>6} {qs}")
        return "\n".join(lines) or "(no metrics)"

    # -- exports --------------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        """Plain-data snapshot (counters, gauges, timer stats) for JSON
        benchmark lines (benchmarks/scale_native.py,
        benchmarks/config6_tcp_cluster.py dump this)."""
        counters, gauges, timers, summaries = self._snapshot()
        out: Dict[str, Any] = {
            "counters": counters,
            "gauges": gauges,
            "timers": {
                k: {
                    "count": st.count,
                    "total_s": st.total_s,
                    "mean_s": st.mean_s,
                    "max_s": st.max_s,
                }
                for k, st in timers.items()
            },
        }
        if summaries:
            out["summaries"] = {
                k: {
                    "count": sm.count,
                    "total": sm.total,
                    # JSON object keys must be strings; %g keeps 0.5
                    # and 0.99 readable and round-trippable
                    "quantiles": {
                        f"{q:g}": v for q, v in sorted(sm.quantiles.items())
                    },
                }
                for k, sm in summaries.items()
            }
        return out

    def prometheus_text(self, prefix: str = "hbbft") -> str:
        """Prometheus exposition format (text/plain version 0.0.4).

        Dotted/arrow metric names ride in a ``name`` label (labels admit
        any UTF-8) under a few fixed metric families, so per-peer series
        (``transport.0->1.queue_frames``) stay distinguishable without
        name mangling.  Label values are escaped per the exposition
        format (backslash, quote, newline) — metric names can embed
        peer-announced node ids, which are untrusted.  Every family
        carries its ``# HELP``/``# TYPE`` header pair, and each timer
        additionally exports its max single observation as the
        ``_max`` gauge family (tracked by :class:`TimerStats` — a
        summary has no max series of its own).  The line grammar is
        golden-pinned by tests/test_obs.py.
        """

        def esc(name: str) -> str:
            return (
                name.replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
            )

        counters, gauges, timers, summaries = self._snapshot()
        lines: List[str] = []
        if counters:
            lines.append(
                f"# HELP {prefix}_count Monotonic event counters"
                " (dotted source name in the 'name' label)."
            )
            lines.append(f"# TYPE {prefix}_count counter")
            for k in sorted(counters):
                lines.append(f'{prefix}_count{{name="{esc(k)}"}} {counters[k]}')
        if gauges:
            lines.append(
                f"# HELP {prefix}_gauge Point-in-time observables"
                " (last write wins)."
            )
            lines.append(f"# TYPE {prefix}_gauge gauge")
            for k in sorted(gauges):
                # .12g, not :g — byte totals exported as gauges exceed
                # :g's 6 significant digits and would scrape corrupted
                lines.append(
                    f'{prefix}_gauge{{name="{esc(k)}"}} {gauges[k]:.12g}'
                )
        if timers:
            lines.append(
                f"# HELP {prefix}_timer_seconds Wall-clock timer"
                " observations (count/sum per name)."
            )
            lines.append(f"# TYPE {prefix}_timer_seconds summary")
            for k in sorted(timers):
                st = timers[k]
                lines.append(
                    f'{prefix}_timer_seconds_count{{name="{esc(k)}"}} {st.count}'
                )
                lines.append(
                    f'{prefix}_timer_seconds_sum{{name="{esc(k)}"}} '
                    f"{st.total_s:.12g}"
                )
            lines.append(
                f"# HELP {prefix}_timer_seconds_max Largest single"
                " observation per timer."
            )
            lines.append(f"# TYPE {prefix}_timer_seconds_max gauge")
            for k in sorted(timers):
                lines.append(
                    f'{prefix}_timer_seconds_max{{name="{esc(k)}"}} '
                    f"{timers[k].max_s:.12g}"
                )
        if summaries:
            lines.append(
                f"# HELP {prefix}_summary Quantile snapshots published"
                " by streaming estimators (latency percentiles)."
            )
            lines.append(f"# TYPE {prefix}_summary summary")
            for k in sorted(summaries):
                sm = summaries[k]
                for q, v in sorted(sm.quantiles.items()):
                    lines.append(
                        f'{prefix}_summary{{name="{esc(k)}",quantile="{q:g}"}} '
                        f"{v:.12g}"
                    )
                lines.append(
                    f'{prefix}_summary_sum{{name="{esc(k)}"}} {sm.total:.12g}'
                )
                lines.append(
                    f'{prefix}_summary_count{{name="{esc(k)}"}} {sm.count}'
                )
        return "\n".join(lines) + ("\n" if lines else "")


@dataclass
class EpochStats:
    """Per-epoch protocol observables (what the simulation table prints)."""

    epoch: Tuple[int, int]
    started_at: float
    finished_at: Optional[float] = None
    contributions: int = 0
    txns: int = 0

    @property
    def latency_s(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.started_at


class EpochTracker:
    """Collects EpochStats keyed by (era, epoch).

    Lock-protected (round 12): a cluster node's protocol thread records
    commits while a scrape/driver thread reads latencies for the
    ``epoch.latency`` summary export."""

    def __init__(self) -> None:
        self._stats: Dict[Tuple[int, int], EpochStats] = {}
        self._lock = threading.Lock()

    def start(self, epoch: Tuple[int, int], now: float) -> None:
        with self._lock:
            self._stats.setdefault(
                epoch, EpochStats(epoch=epoch, started_at=now)
            )

    def finish(
        self, epoch: Tuple[int, int], now: float, contributions: int, txns: int
    ) -> None:
        with self._lock:
            st = self._stats.setdefault(
                epoch, EpochStats(epoch=epoch, started_at=now)
            )
            if st.finished_at is None:
                st.finished_at = now
                st.contributions = contributions
                st.txns = txns

    def all(self) -> List[EpochStats]:
        with self._lock:
            return [self._stats[k] for k in sorted(self._stats)]

    def latencies(self) -> List[float]:
        """Commit latencies of every finished epoch (export feed for
        the ``epoch.latency`` summary)."""
        with self._lock:
            return [
                st.finished_at - st.started_at
                for st in self._stats.values()
                if st.finished_at is not None
            ]
