"""Structured metrics: counters, timers, and JAX profiler traces.

Reference behavior: the reference has no metrics beyond ``log`` lines and
the per-message CPU-time accounting of its simulation example (SURVEY.md
§5.1/§5.5).  This framework's observability surface is richer because the
crypto plane batches onto an accelerator — per-flush timing and batch
sizes are the operational signal — while staying optional: a ``Metrics``
instance is plain data, and nothing in the protocol plane requires one.

Usage::

    from hbbft_tpu.utils.metrics import Metrics

    m = Metrics()
    with m.timer("flush"):
        pool.flush(backend)
    m.count("verify_requests", 12)
    print(m.report())

``Metrics.trace(path)`` wraps ``jax.profiler.trace`` so a verify flush
can be captured for TensorBoard without importing jax at module scope.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple


@dataclass
class TimerStats:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0

    def add(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        self.max_s = max(self.max_s, dt)

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


@dataclass
class Metrics:
    """Counters + timers; cheap enough to leave on."""

    counters: Dict[str, int] = field(default_factory=lambda: defaultdict(int))
    timers: Dict[str, TimerStats] = field(default_factory=dict)

    def count(self, name: str, n: int = 1) -> None:
        self.counters[name] += n

    @contextmanager
    def timer(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.timers.setdefault(name, TimerStats()).add(dt)

    @contextmanager
    def trace(self, logdir: str) -> Iterator[None]:
        """JAX profiler capture (TensorBoard format); no-op without jax."""
        try:
            import jax
        except ImportError:  # pragma: no cover
            yield
            return
        with jax.profiler.trace(logdir):
            yield

    def merge(self, other: "Metrics") -> None:
        for k, v in other.counters.items():
            self.counters[k] += v
        for k, st in other.timers.items():
            mine = self.timers.setdefault(k, TimerStats())
            mine.count += st.count
            mine.total_s += st.total_s
            mine.max_s = max(mine.max_s, st.max_s)

    def report(self) -> str:
        lines = []
        if self.counters:
            lines.append("counters:")
            for k in sorted(self.counters):
                lines.append(f"  {k:<40} {self.counters[k]}")
        if self.timers:
            lines.append("timers:  (count / mean ms / max ms / total s)")
            for k in sorted(self.timers):
                st = self.timers[k]
                lines.append(
                    f"  {k:<40} {st.count:>6} {st.mean_s * 1e3:>9.2f} "
                    f"{st.max_s * 1e3:>9.2f} {st.total_s:>8.2f}"
                )
        return "\n".join(lines) or "(no metrics)"


@dataclass
class EpochStats:
    """Per-epoch protocol observables (what the simulation table prints)."""

    epoch: Tuple[int, int]
    started_at: float
    finished_at: Optional[float] = None
    contributions: int = 0
    txns: int = 0

    @property
    def latency_s(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.started_at


class EpochTracker:
    """Collects EpochStats keyed by (era, epoch)."""

    def __init__(self) -> None:
        self._stats: Dict[Tuple[int, int], EpochStats] = {}

    def start(self, epoch: Tuple[int, int], now: float) -> None:
        self._stats.setdefault(epoch, EpochStats(epoch=epoch, started_at=now))

    def finish(
        self, epoch: Tuple[int, int], now: float, contributions: int, txns: int
    ) -> None:
        st = self._stats.setdefault(epoch, EpochStats(epoch=epoch, started_at=now))
        if st.finished_at is None:
            st.finished_at = now
            st.contributions = contributions
            st.txns = txns

    def all(self) -> List[EpochStats]:
        return [self._stats[k] for k in sorted(self._stats)]
