"""Persistent XLA compilation cache setup.

The crypto kernels are large graphs (batched 381-bit limb arithmetic,
Miller-loop scans); first compilation is expensive.  Pointing JAX at an
on-disk cache makes every later process start (tests, bench, driver
entry checks) reuse the compiled executables.
"""

from __future__ import annotations

import os


def enable_cache(path: str | None = None) -> None:
    import jax

    cache_dir = path or os.environ.get(
        "HBBFT_TPU_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))), ".jax_cache"),
    )
    os.makedirs(cache_dir, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jaxlib without the knobs — caching is best-effort
