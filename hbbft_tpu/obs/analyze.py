"""Consensus critical-path analyzer + stall diagnostician (round 16).

The flight recorder (round 12) made "which phase ate the time" a
queryable artifact; this module is the query.  It consumes the merged
recorder rings — the same ``Dict[track, List[TraceEvent]]`` shape
:meth:`LocalCluster.trace_events` snapshots and
:func:`tracks_from_chrome` recovers from a dumped ``trace.json`` — and
answers the two questions the raw Chrome trace only answers to a human:

* **post-mortem** — per committed epoch, the *critical path* to commit:
  the cluster-wide chain of last milestones (``epoch.open`` →
  ``rbc.value`` → ``rbc.ready`` → ``rbc.deliver`` → ``ba.input`` →
  ``ba.round``/``ba.coin`` → ``ba.decide`` → ``decrypt.start`` →
  ``decrypt.done`` → ``epoch.commit``), with straggler attribution
  (which node's which phase was last), cross-node skew, BA
  rounds-to-decide histograms, and crypto-plane flush latency folded in
  from the ``cryptoplane`` track (the decrypt-after-order latency price
  of PAPERS.md arxiv 2407.12172, measured per epoch);
* **live** — :func:`diagnose`: when commit rate goes quiescent, *why* —
  which proposer's RBC is incomplete, which BA instance is stuck at
  which round, which peers are disconnected or banned.  The ``/diag``
  scrape endpoint and the ``tools/analyze.py`` CLI run THIS code over
  live rings and dumped traces respectively, so live and post-mortem
  diagnosis can never disagree.

Epoch attribution follows the exporter's bracketing rule
(obs/export.py): events carrying explicit ``era``/``epoch`` args (the
native arm) are keyed directly; Python-arm leaf milestones are assigned
to the track's currently-open epoch, which is sound because HoneyBadger
only processes current-epoch messages.

Determinism: every max/argmax here breaks timestamp ties by
``(ts, track, proposer)``, so two analyses of the same event streams —
and two same-seed sim-net runs, whose event ORDER is deterministic —
produce structurally identical paths (pinned by tests/test_analyze.py
against golden fixtures from both sim-net impls).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

from hbbft_tpu.obs.export import phase_summaries, summarize
from hbbft_tpu.obs.trace import TraceEvent

#: Milestone chain to commit, in protocol order.  ``ba.round`` /
#: ``ba.coin`` sit between input and decide (a BA instance may decide
#: in round 0 without either).
STAGES = (
    "epoch.open",
    "rbc.value",
    "rbc.ready",
    "rbc.deliver",
    "ba.input",
    "ba.round",
    "ba.coin",
    "ba.decide",
    "decrypt.start",
    "decrypt.done",
    "epoch.commit",
)

#: Stage -> coarse phase for share-of-wall aggregation and diagnosis.
STAGE_PHASE = {
    "epoch.open": "open",
    "rbc.value": "rbc",
    "rbc.ready": "rbc",
    "rbc.deliver": "rbc",
    "ba.input": "ba",
    "ba.round": "ba",
    "ba.coin": "coin",
    "ba.decide": "ba",
    "decrypt.start": "decrypt",
    "decrypt.done": "decrypt",
    "epoch.commit": "commit",
}

_STAGE_SET = frozenset(STAGES)
_NODE_TRACK_RE = re.compile(r"^node(\d+)$")


def node_tracks(tracks: Dict[str, List[TraceEvent]]) -> Dict[str, List[TraceEvent]]:
    """The per-node tracks (``node<i>``), dropping the cluster /
    cryptoplane side-tracks whose events are not epoch milestones."""
    return {t: evs for t, evs in tracks.items() if _NODE_TRACK_RE.match(t)}


def _sort_key(track: str) -> Tuple[int, str]:
    m = _NODE_TRACK_RE.match(track)
    return (int(m.group(1)), track) if m else (1 << 30, track)


def epoch_events(
    tracks: Dict[str, List[TraceEvent]]
) -> Dict[Tuple[int, int], Dict[str, List[TraceEvent]]]:
    """Group each node track's milestone events by ``(era, epoch)``
    using the exporter's bracketing rule.  Non-milestone events
    (transport/chaos/crypto) are not epoch-scoped and are skipped."""
    out: Dict[Tuple[int, int], Dict[str, List[TraceEvent]]] = {}
    for track in sorted(node_tracks(tracks), key=_sort_key):
        cur: Optional[Tuple[int, int]] = None
        for ev in tracks[track]:
            if ev.name not in _STAGE_SET:
                continue
            if "epoch" in ev.args:
                key: Optional[Tuple[int, int]] = (
                    int(ev.args.get("era", 0)),
                    int(ev.args["epoch"]),
                )
            else:
                key = cur
            if ev.name == "epoch.open":
                cur = key
            if key is None:
                continue  # unbracketed leaf (ring overflow ate the open)
            out.setdefault(key, {}).setdefault(track, []).append(ev)
    return out


def _last(
    events: Iterable[Tuple[str, TraceEvent]], limit: Optional[float] = None
) -> Optional[Tuple[str, TraceEvent]]:
    """The (track, event) with the largest timestamp, ties broken by
    (track, proposer) so the choice is stable across analyses."""
    best: Optional[Tuple[str, TraceEvent]] = None
    best_key: Optional[Tuple[float, Tuple[int, str], int]] = None
    for track, ev in events:
        if limit is not None and ev.ts > limit:
            continue
        key = (ev.ts, _sort_key(track), int(ev.args.get("proposer", -1)))
        if best_key is None or key > best_key:
            best, best_key = (track, ev), key
    return best


def critical_path(
    tracks: Dict[str, List[TraceEvent]]
) -> List[Dict[str, Any]]:
    """Per committed epoch, the cluster-wide critical path to commit.

    An epoch qualifies when at least one track observed BOTH its
    ``epoch.open`` and its ``epoch.commit``.  Per stage the path takes
    the LAST matching milestone across all node tracks at or before the
    epoch's commit wall; clamping to a running maximum guarantees the
    reported chain is monotone even if cross-track clock jitter
    reorders raw stamps.  Returns one dict per epoch, sorted::

        {era, epoch, t_open, t_commit, wall_s, open_skew_s,
         commit_skew_s, path: [{stage, phase, t, dt_s, node, proposer,
         round?}...], straggler: {stage, phase, node, proposer, dt_s},
         ba_rounds: {rounds_to_decide: count}, coins: int,
         flush: {flushes, total_s, max_s} | None}
    """
    by_epoch = epoch_events(tracks)
    flushes = _flush_spans(tracks)
    out: List[Dict[str, Any]] = []
    for key in sorted(by_epoch):
        per_track = by_epoch[key]
        opens = {
            t: [e for e in evs if e.name == "epoch.open"]
            for t, evs in per_track.items()
        }
        commits = {
            t: [e for e in evs if e.name == "epoch.commit"]
            for t, evs in per_track.items()
        }
        open_ts = [e.ts for es in opens.values() for e in es]
        commit_ts = [e.ts for es in commits.values() for e in es]
        if not commit_ts or not any(
            opens[t] and commits[t] for t in per_track
        ):
            continue  # in-flight or truncated epoch: no commit wall
        t_open = min(open_ts)
        t_commit = max(commit_ts)

        path: List[Dict[str, Any]] = []
        prev_t = t_open
        for stage in STAGES:
            cand = (
                (t, e)
                for t, evs in per_track.items()
                for e in evs
                if e.name == stage
            )
            hit = _last(cand, limit=t_commit)
            if hit is None:
                continue  # stage absent (e.g. unencrypted epoch)
            track, ev = hit
            t = max(ev.ts, prev_t)  # monotone by construction
            entry: Dict[str, Any] = {
                "stage": stage,
                "phase": STAGE_PHASE[stage],
                "t": t,
                "dt_s": t - prev_t,
                "node": track,
            }
            if "proposer" in ev.args:
                entry["proposer"] = ev.args["proposer"]
            if "round" in ev.args:
                entry["round"] = ev.args["round"]
            path.append(entry)
            prev_t = t

        stragglers = [p for p in path if p["stage"] != "epoch.open"]
        straggler = (
            max(stragglers, key=lambda p: p["dt_s"]) if stragglers else None
        )
        rounds_hist: Dict[int, int] = {}
        coins = 0
        for t, evs in per_track.items():
            for e in evs:
                if e.name == "ba.decide":
                    r = int(e.args.get("round", 0)) + 1
                    rounds_hist[r] = rounds_hist.get(r, 0) + 1
                elif e.name == "ba.coin":
                    coins += 1
        epoch_flush = [
            (t0, t1) for t0, t1 in flushes if t_open <= t1 <= t_commit
        ]
        rec: Dict[str, Any] = {
            "era": key[0],
            "epoch": key[1],
            "t_open": t_open,
            "t_commit": t_commit,
            "wall_s": t_commit - t_open,
            "open_skew_s": (max(open_ts) - min(open_ts)) if open_ts else 0.0,
            "commit_skew_s": max(commit_ts) - min(commit_ts),
            "path": path,
            "straggler": (
                {
                    "stage": straggler["stage"],
                    "phase": straggler["phase"],
                    "node": straggler["node"],
                    "proposer": straggler.get("proposer"),
                    "dt_s": straggler["dt_s"],
                }
                if straggler is not None
                else None
            ),
            "ba_rounds": rounds_hist,
            "coins": coins,
            "flush": (
                {
                    "flushes": len(epoch_flush),
                    "total_s": sum(t1 - t0 for t0, t1 in epoch_flush),
                    "max_s": max((t1 - t0 for t0, t1 in epoch_flush)),
                }
                if epoch_flush
                else None
            ),
        }
        out.append(rec)
    return out


def _flush_spans(
    tracks: Dict[str, List[TraceEvent]]
) -> List[Tuple[float, float]]:
    """(t_open, t_done) per crypto-plane flush.

    Events carrying a ``span`` id pair by id: RPC-mode clients
    (proc_service.py) share one ``cryptoplane`` buffer and flush
    CONCURRENTLY, so their open/done events interleave.  Spanless
    events (the in-thread service flushes sequentially on its own
    worker) keep the emit-order pairing.  Spans are returned sorted by
    open time so the per-epoch window filter sees one timeline.
    """
    evs = tracks.get("cryptoplane") or []
    spans: List[Tuple[float, float]] = []
    open_t: Optional[float] = None
    open_by_span: Dict[Any, float] = {}
    for ev in evs:
        span = ev.args.get("span")
        if ev.name == "crypto.flush.open":
            if span is not None:
                open_by_span[span] = ev.ts
            else:
                open_t = ev.ts
        elif ev.name == "crypto.flush.done":
            if span is not None:
                t0 = open_by_span.pop(span, None)
                if t0 is not None:
                    spans.append((t0, ev.ts))
            elif open_t is not None:
                spans.append((open_t, ev.ts))
                open_t = None
    spans.sort()
    return spans


def path_structure(rec: Dict[str, Any]) -> List[Tuple[str, str, Any]]:
    """The timestamp-free shape of one epoch's critical path —
    ``(stage, node, proposer)`` triples — for rerun-identity checks."""
    return [
        (p["stage"], p["node"], p.get("proposer")) for p in rec["path"]
    ]


def summarize_critical_paths(
    records: List[Dict[str, Any]]
) -> Dict[str, Any]:
    """Aggregate per-epoch critical paths into the compact summary the
    benchmark JSON lines carry (``critical_path``): straggler
    histograms, phase share of wall, commit skew quantiles, BA
    rounds-to-decide histogram, crypto-plane flush totals."""
    out: Dict[str, Any] = {"epochs": len(records)}
    if not records:
        return out
    strag_nodes: Dict[str, int] = {}
    strag_phases: Dict[str, int] = {}
    share: Dict[str, float] = {}
    ba_rounds: Dict[int, int] = {}
    coins = 0
    fl_n = 0
    fl_total = 0.0
    for rec in records:
        s = rec.get("straggler")
        if s is not None:
            strag_nodes[s["node"]] = strag_nodes.get(s["node"], 0) + 1
            strag_phases[s["phase"]] = strag_phases.get(s["phase"], 0) + 1
        wall = rec["wall_s"] or 0.0
        if wall > 0:
            for p in rec["path"]:
                share[p["phase"]] = (
                    share.get(p["phase"], 0.0) + p["dt_s"] / wall
                )
        for r, c in rec["ba_rounds"].items():
            ba_rounds[int(r)] = ba_rounds.get(int(r), 0) + c
        coins += rec["coins"]
        fl = rec.get("flush")
        if fl:
            fl_n += fl["flushes"]
            fl_total += fl["total_s"]
    n = len(records)
    sm = summarize([r["commit_skew_s"] for r in records])
    out.update(
        {
            "wall_p50_s": round(
                summarize([r["wall_s"] for r in records])[0][0.5], 6
            ),
            "straggler_nodes": dict(sorted(strag_nodes.items())),
            "straggler_phases": dict(sorted(strag_phases.items())),
            "phase_share": {
                k: round(v / n, 4) for k, v in sorted(share.items())
            },
            "commit_skew_p50_s": round(sm[0][0.5], 6),
            "commit_skew_max_s": round(
                max(r["commit_skew_s"] for r in records), 6
            ),
            "ba_rounds": {
                str(k): v for k, v in sorted(ba_rounds.items())
            },
            "coins": coins,
        }
    )
    if fl_n:
        out["flush"] = {"flushes": fl_n, "total_s": round(fl_total, 6)}
    return out


# ---------------------------------------------------------------------------
# Derived metric summaries (merged_metrics): phase.* + ba.rounds
# ---------------------------------------------------------------------------


def ba_rounds_to_decide(tracks: Dict[str, List[TraceEvent]]) -> List[int]:
    """Rounds-to-decide (decide round + 1) of every BA decision across
    all node tracks — the population behind the ``ba.rounds`` summary
    metric (one observation per (node, epoch, proposer) instance)."""
    return [
        int(ev.args.get("round", 0)) + 1
        for t, evs in node_tracks(tracks).items()
        for ev in evs
        if ev.name == "ba.decide"
    ]


def derived_summaries(
    tracks: Dict[str, List[TraceEvent]]
) -> Dict[str, Tuple[Dict[float, float], int, float]]:
    """Every ring-derived summary family merged_metrics publishes:
    ``phase.<name>`` (the round-12 per-epoch phase-latency breakdown)
    plus ``ba.rounds`` (rounds-to-decide, round-16 satellite)."""
    out = {
        f"phase.{name}": sm
        for name, sm in phase_summaries(tracks).items()
    }
    sm = summarize([float(r) for r in ba_rounds_to_decide(tracks)])
    if sm is not None:
        out["ba.rounds"] = sm
    return out


# ---------------------------------------------------------------------------
# Live stall diagnosis
# ---------------------------------------------------------------------------

#: Diagnosis phase order: earlier = further from commit (a proposer
#: stuck in rbc blocks more than one stuck in decrypt).
_DIAG_PHASES = ("rbc", "ba", "decrypt")


def _instance_status(
    evs: List[TraceEvent], proposer: int
) -> Optional[Dict[str, Any]]:
    """Status of one (epoch, proposer) consensus instance on one node's
    timeline; None when the instance completed (decided + any started
    decrypt finished)."""
    value = ready = delivered = decided = False
    dec_start = dec_done = False
    last_ts: Optional[float] = None
    round_ = 0
    decide_value: Optional[int] = None
    for ev in evs:
        if ev.args.get("proposer") != proposer:
            continue
        last_ts = ev.ts
        name = ev.name
        if name == "rbc.value":
            value = True
        elif name == "rbc.ready":
            ready = True
        elif name == "rbc.deliver":
            delivered = True
        elif name in ("ba.input", "ba.round", "ba.coin"):
            round_ = max(round_, int(ev.args.get("round", 0)))
        elif name == "ba.decide":
            decided = True
            round_ = int(ev.args.get("round", round_))
            decide_value = ev.args.get("value")
        elif name == "decrypt.start":
            dec_start = True
        elif name == "decrypt.done":
            dec_done = True
    if decided and (not dec_start or dec_done):
        return None  # complete (or decided-out: no decrypt follows)
    if not delivered:
        phase, detail = "rbc", (
            "no value received" if not value else "echo/ready incomplete"
        )
    elif not decided:
        phase, detail = "ba", f"undecided at round {round_}"
    else:
        phase, detail = "decrypt", "combine pending"
    return {
        "proposer": proposer,
        "phase": phase,
        "round": round_ if phase == "ba" else None,
        "detail": detail,
        "value_seen": value,
        "ready_seen": ready,
        "delivered": delivered,
        "decided": decided,
        "decide_value": decide_value,
        "last_ts": last_ts,
    }


def _link_status(
    evs: List[TraceEvent], now: float
) -> Tuple[List[Any], List[Dict[str, Any]]]:
    """(disconnected_peers, active_bans) from a node track's transport
    milestones: a peer is disconnected when its last connect/disconnect
    event is a disconnect; a ban is active while now < ts + duration."""
    last: Dict[Any, str] = {}
    bans: List[Dict[str, Any]] = []
    for ev in evs:
        if ev.name == "transport.connect":
            last[ev.args.get("peer")] = "up"
        elif ev.name == "transport.disconnect":
            last[ev.args.get("peer")] = "down"
        elif ev.name == "transport.ban":
            if now < ev.ts + float(ev.args.get("duration_s", 0.0)):
                bans.append(
                    {
                        "peer": ev.args.get("peer"),
                        "offense": ev.args.get("offense"),
                    }
                )
    down = sorted(
        (p for p, st in last.items() if st == "down"),
        key=lambda p: str(p),
    )
    return down, bans


def _verdict(
    stuck: List[Dict[str, Any]],
    links: Optional[Dict[str, Dict[str, Any]]] = None,
) -> Optional[Dict[str, Any]]:
    """The most-implicated cause, in evidence order.

    1. An ABSENT proposer — ``no value received`` on two or more nodes
       — outranks everything: a dead or partitioned proposer starves
       every downstream instance.  (One node reporting no-value only
       indicts the REPORTER's own link, so the threshold is 2.)
    2. A WIDELY-DOWN link — the same peer reported disconnected by two
       or more tracks — is named when no proposer is absent: a
       post-RBC quorum loss stalls every BA instance EQUALLY, and
       counting alone would blame an arbitrary well-behaved proposer
       while the link data holds the real cause.
    3. Otherwise: the instance stuck on the most nodes, ties toward
       the earlier phase then the lower proposer."""
    counts: Dict[Tuple[Any, str], int] = {}
    rounds: Dict[Tuple[Any, str], int] = {}
    absent: Dict[Any, int] = {}
    for s in stuck:
        k = (s["proposer"], s["phase"])
        counts[k] = counts.get(k, 0) + 1
        if s.get("round") is not None:
            rounds[k] = max(rounds.get(k, 0), s["round"])
        if s.get("detail") == "no value received":
            absent[s["proposer"]] = absent.get(s["proposer"], 0) + 1
    if not counts:
        return None
    wide_absent = {p: n for p, n in absent.items() if n >= 2}
    if wide_absent:
        proposer, n = min(
            wide_absent.items(), key=lambda kv: (-kv[1], str(kv[0]))
        )
        return {
            "proposer": proposer,
            "phase": "rbc",
            "nodes": n,
            "absent": True,
        }
    down: Dict[Any, int] = {}
    for st in (links or {}).values():
        for peer in st.get("disconnected", ()):
            down[peer] = down.get(peer, 0) + 1
    wide_down = {p: n for p, n in down.items() if n >= 2}
    if wide_down:
        return {
            "phase": "link",
            "peers": sorted(wide_down, key=str),
            "nodes": max(wide_down.values()),
        }
    (proposer, phase), n = min(
        counts.items(),
        key=lambda kv: (
            -kv[1],
            _DIAG_PHASES.index(kv[0][1]),
            str(kv[0][0]),
        ),
    )
    v: Dict[str, Any] = {"proposer": proposer, "phase": phase, "nodes": n}
    if (proposer, phase) in rounds:
        v["round"] = rounds[(proposer, phase)]
    return v


def diagnose(
    tracks: Dict[str, List[TraceEvent]],
    n: Optional[int] = None,
    now: Optional[float] = None,
    stall_after_s: float = 5.0,
) -> Dict[str, Any]:
    """Answer "why did the cluster stop committing" from the rings.

    ``n`` is the consensus size (proposer universe); inferred from the
    node-track indices when omitted (a single-node worker view should
    pass its cluster's real n).  ``now`` defaults to wall clock; pass
    the capture time (e.g. the newest event stamp) for post-mortem use.
    """
    if now is None:
        import time

        now = time.time()
    ntracks = node_tracks(tracks)
    if n is None:
        n = max(
            (int(_NODE_TRACK_RE.match(t).group(1)) + 1 for t in ntracks),
            default=0,
        )
    by_epoch = epoch_events(tracks)
    commit_ts = [
        e.ts
        for per_track in by_epoch.values()
        for evs in per_track.values()
        for e in evs
        if e.name == "epoch.commit"
    ]
    last_commit = max(commit_ts) if commit_ts else None
    first_ts = min(
        (evs[0].ts for evs in ntracks.values() if evs), default=None
    )
    anchor = last_commit if last_commit is not None else first_ts
    since_s = (now - anchor) if anchor is not None else None
    stalled = since_s is not None and since_s > stall_after_s

    last_committed: Dict[Tuple[int, int], float] = {}
    for key, per_track in by_epoch.items():
        for evs in per_track.values():
            for e in evs:
                if e.name == "epoch.commit":
                    last_committed[key] = max(
                        last_committed.get(key, 0.0), e.ts
                    )

    open_epochs: Dict[str, List[int]] = {}
    stuck: List[Dict[str, Any]] = []
    for track in sorted(ntracks, key=_sort_key):
        opened = {
            key
            for key, per_track in by_epoch.items()
            if any(
                e.name == "epoch.open" for e in per_track.get(track, ())
            )
        }
        committed = {
            key
            for key, per_track in by_epoch.items()
            if any(
                e.name == "epoch.commit" for e in per_track.get(track, ())
            )
        }
        pending = opened - committed
        if not pending:
            continue
        key = max(pending)
        open_epochs[track] = [key[0], key[1]]
        evs = by_epoch[key].get(track, [])
        open_ts = min(
            (e.ts for e in evs if e.name == "epoch.open"), default=now
        )
        for proposer in range(n):
            st = _instance_status(evs, proposer)
            if st is None:
                continue
            st.update(
                {
                    "node": track,
                    "era": key[0],
                    "epoch": key[1],
                    "age_s": now - (st.pop("last_ts") or open_ts),
                }
            )
            stuck.append(st)

    links: Dict[str, Dict[str, Any]] = {}
    for track in sorted(ntracks, key=_sort_key):
        down, bans = _link_status(ntracks[track], now)
        if down or bans:
            links[track] = {"disconnected": down, "banned": bans}

    last_key = max(last_committed) if last_committed else None
    return {
        "stalled": stalled,
        "since_s": round(since_s, 3) if since_s is not None else None,
        "stall_after_s": stall_after_s,
        "last_commit": list(last_key) if last_key is not None else None,
        "open_epochs": open_epochs,
        "stuck": stuck,
        "links": links,
        "verdict": _verdict(stuck, links) if stalled else None,
    }


def merge_diags(
    diags: List[Dict[str, Any]], stall_after_s: Optional[float] = None
) -> Dict[str, Any]:
    """Fold per-worker ``/diag`` payloads (one node track each — the
    process-per-node runtime) into one cluster-level diagnosis, using
    the SAME verdict rule as :func:`diagnose`.  The cluster is stalled
    when every reporting worker is (commits land on all survivors or
    none — HB has no partial commit)."""
    diags = [d for d in diags if d]
    if not diags:
        return {"stalled": False, "since_s": None, "workers": 0}
    stalled = all(d.get("stalled") for d in diags)
    stuck = [s for d in diags for s in d.get("stuck", ())]
    links: Dict[str, Any] = {}
    for d in diags:
        links.update(d.get("links", {}))
    open_epochs: Dict[str, Any] = {}
    for d in diags:
        open_epochs.update(d.get("open_epochs", {}))
    since = [d["since_s"] for d in diags if d.get("since_s") is not None]
    commits = [
        tuple(d["last_commit"])
        for d in diags
        if d.get("last_commit") is not None
    ]
    return {
        "stalled": stalled,
        "since_s": min(since) if since else None,
        "stall_after_s": (
            stall_after_s
            if stall_after_s is not None
            else max((d.get("stall_after_s", 0.0) for d in diags))
        ),
        "last_commit": list(max(commits)) if commits else None,
        "open_epochs": open_epochs,
        "stuck": stuck,
        "links": links,
        "workers": len(diags),
        "verdict": _verdict(stuck, links) if stalled else None,
    }


# ---------------------------------------------------------------------------
# Chrome-trace round trip (post-mortem CLI)
# ---------------------------------------------------------------------------


def tracks_from_chrome(doc: Dict[str, Any]) -> Dict[str, List[TraceEvent]]:
    """Recover recorder tracks from a dumped ``trace.json`` (the exact
    inverse of :func:`~hbbft_tpu.obs.export.chrome_trace` for instant
    events; derived span events are re-derivable, so they are ignored).
    Timestamps return to absolute wall seconds via the
    ``otherData.t0_unix_s`` anchor, so a post-mortem analysis of a dump
    and a live analysis of the same rings see identical numbers."""
    t0 = float((doc.get("otherData") or {}).get("t0_unix_s", 0.0))
    names: Dict[int, str] = {}
    for ev in doc.get("traceEvents", ()):  # metadata pass first: a part
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            names[int(ev.get("pid", 0))] = ev["args"]["name"]
    tracks: Dict[str, List[TraceEvent]] = {}
    for ev in doc.get("traceEvents", ()):
        if ev.get("ph") != "i":
            continue
        pid = int(ev.get("pid", 0))
        track = names.get(pid, f"pid{pid}")
        tracks.setdefault(track, []).append(
            TraceEvent(
                t0 + float(ev.get("ts", 0.0)) / 1e6,
                ev["name"],
                dict(ev.get("args") or {}),
            )
        )
    for evs in tracks.values():
        evs.sort(key=lambda e: e.ts)
    return tracks
