"""Trace merging + export: Chrome trace-event JSON and phase latency.

The merger aligns per-node rings on the shared wall clock (every event
is stamped with ``time.time()`` / ``CLOCK_REALTIME`` at emit — nothing
here re-times anything) and derives **per-epoch phase spans** from the
milestone taxonomy:

* ``epoch``   — ``epoch.open`` → ``epoch.commit``
* ``rbc``     — ``epoch.open`` → last ``rbc.deliver`` (value dispersal
  and Bracha agreement for every accepted proposer)
* ``ba``      — first ``ba.*`` milestone → last ``ba.decide``
* ``coin``    — first ``ba.coin`` → last ``ba.coin`` (the threshold-
  crypto rounds inside BA, separated out because the decrypt-after-
  order latency price — PAPERS.md arxiv 2407.12172 — is exactly the
  coin+decrypt share of the epoch)
* ``decrypt`` — first ``decrypt.start`` → last ``decrypt.done``

Events from the native arm carry explicit ``era``/``epoch`` args (the
engine knows them); Python-arm leaf milestones without them are
BRACKETED — assigned to the track's currently-open epoch, which is
sound because :class:`~hbbft_tpu.protocols.honey_badger.HoneyBadger`
only ever processes messages for its current epoch (future epochs are
buffered, stale ones dropped).

The Chrome output loads in Perfetto / ``chrome://tracing``: one
process (pid) per track, spans on per-phase thread lanes, milestones
as instant events on lane 0.  Timestamps are microseconds relative to
the earliest event (the absolute epoch is in the ``otherData`` block).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Tuple

from hbbft_tpu.obs.trace import TraceEvent

#: Span lanes (Chrome "tid") per track; lane 0 carries instant events.
_LANES = {"epoch": 1, "rbc": 2, "ba": 3, "coin": 4, "decrypt": 5}

#: Default quantiles for phase/epoch latency summaries.
QUANTILES = (0.5, 0.9, 0.99)


def summarize(
    values: Iterable[float], qs: Tuple[float, ...] = QUANTILES
) -> Optional[Tuple[Dict[float, float], int, float]]:
    """(quantiles, count, total) of ``values`` by sorting — the
    producer-side estimator for :meth:`Metrics.summary` when the
    population is bounded (epochs, phase spans), where exact beats
    streaming.  None for an empty population."""
    vs = sorted(values)
    if not vs:
        return None
    n = len(vs)
    quant = {q: vs[min(n - 1, int(q * n))] for q in qs}
    return quant, n, sum(vs)


class _EpochAcc:
    __slots__ = ("open", "commit", "rbc_last", "ba_first", "ba_last",
                 "coin_first", "coin_last", "dec_first", "dec_last")

    def __init__(self) -> None:
        self.open = self.commit = None
        self.rbc_last = None
        self.ba_first = self.ba_last = None
        self.coin_first = self.coin_last = None
        self.dec_first = self.dec_last = None


def phase_spans(
    tracks: Dict[str, List[TraceEvent]]
) -> List[Dict[str, Any]]:
    """Derive per-epoch phase spans from each track's event stream.

    Returns dicts ``{track, era, epoch, phase, t0, t1}`` (wall seconds);
    a span appears only when both endpoints were observed.
    """
    spans: List[Dict[str, Any]] = []
    for track, events in tracks.items():
        acc: Dict[Tuple[int, int], _EpochAcc] = {}
        cur: Optional[Tuple[int, int]] = None

        def key_for(ev: TraceEvent) -> Optional[Tuple[int, int]]:
            if "epoch" in ev.args:
                return (int(ev.args.get("era", 0)), int(ev.args["epoch"]))
            return cur

        for ev in events:
            name = ev.name
            if name == "epoch.open":
                k = key_for(ev)
                if k is None:
                    continue
                cur = k
                acc.setdefault(k, _EpochAcc()).open = ev.ts
                continue
            k = key_for(ev)
            if k is None:
                continue  # unbracketed leaf milestone (ring overflow)
            a = acc.setdefault(k, _EpochAcc())
            if name == "epoch.commit":
                a.commit = ev.ts
            elif name == "rbc.deliver":
                a.rbc_last = ev.ts
            elif name.startswith("ba."):
                if a.ba_first is None:
                    a.ba_first = ev.ts
                if name == "ba.decide":
                    a.ba_last = ev.ts
                if name == "ba.coin":
                    if a.coin_first is None:
                        a.coin_first = ev.ts
                    a.coin_last = ev.ts
            elif name == "decrypt.start":
                if a.dec_first is None:
                    a.dec_first = ev.ts
            elif name == "decrypt.done":
                # only a real combine closes the span — fabricating the
                # end from decrypt.start would emit 0 s decrypt spans
                # for killed/overflowed epochs and drag phase.decrypt
                # quantiles down
                a.dec_last = ev.ts

        for (era, epoch), a in sorted(acc.items()):
            def put(phase: str, t0, t1) -> None:
                if t0 is not None and t1 is not None and t1 >= t0:
                    spans.append(
                        {
                            "track": track,
                            "era": era,
                            "epoch": epoch,
                            "phase": phase,
                            "t0": t0,
                            "t1": t1,
                        }
                    )

            put("epoch", a.open, a.commit)
            put("rbc", a.open, a.rbc_last)
            put("ba", a.ba_first, a.ba_last)
            put("coin", a.coin_first, a.coin_last)
            put("decrypt", a.dec_first, a.dec_last)
    return spans


def phase_summaries(
    tracks: Dict[str, List[TraceEvent]]
) -> Dict[str, Tuple[Dict[float, float], int, float]]:
    """Per-phase latency summaries across all tracks/epochs — the
    derived breakdown :meth:`LocalCluster.merged_metrics` publishes as
    ``phase.<name>`` (Prometheus summary triplets)."""
    durs: Dict[str, List[float]] = {}
    for sp in phase_spans(tracks):
        durs.setdefault(sp["phase"], []).append(sp["t1"] - sp["t0"])
    out = {}
    for phase, vals in durs.items():
        sm = summarize(vals)
        if sm is not None:
            out[phase] = sm
    return out


def chrome_trace(
    tracks: Dict[str, List[TraceEvent]],
    pids: Optional[Dict[str, int]] = None,
) -> Dict[str, Any]:
    """Merge ``tracks`` into a Chrome trace-event JSON object.

    ``pids`` optionally pins track → pid (the cluster passes node ids);
    unpinned tracks get pids after the largest pinned one, in sorted
    track order.  Every emitted event carries the ``ts/pid/tid/ph/name``
    quintet (schema-pinned by tests/test_obs.py).
    """
    pids = dict(pids or {})
    next_pid = max(pids.values(), default=-1) + 1
    for track in sorted(tracks):
        if track not in pids:
            pids[track] = next_pid
            next_pid += 1

    all_ts = [ev.ts for evs in tracks.values() for ev in evs]
    t0 = min(all_ts) if all_ts else 0.0

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 1)

    events: List[Dict[str, Any]] = []
    for track in sorted(tracks):
        pid = pids[track]
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "ts": 0,
                "pid": pid,
                "tid": 0,
                "args": {"name": track},
            }
        )
        for lane_name, tid in [("milestones", 0)] + sorted(
            _LANES.items(), key=lambda kv: kv[1]
        ):
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "ts": 0,
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": lane_name},
                }
            )
        for ev in tracks[track]:
            events.append(
                {
                    "name": ev.name,
                    "ph": "i",
                    "s": "t",  # thread-scoped instant
                    "ts": us(ev.ts),
                    "pid": pid,
                    "tid": 0,
                    "cat": ev.name.split(".", 1)[0],
                    "args": ev.args,
                }
            )
    for sp in phase_spans(tracks):
        events.append(
            {
                "name": f"{sp['phase']} e{sp['era']}/{sp['epoch']}",
                "ph": "X",
                "ts": us(sp["t0"]),
                "dur": max(round((sp["t1"] - sp["t0"]) * 1e6, 1), 1),
                "pid": pids[sp["track"]],
                "tid": _LANES[sp["phase"]],
                "cat": "phase",
                "args": {"era": sp["era"], "epoch": sp["epoch"]},
            }
        )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"t0_unix_s": t0, "source": "hbbft-tpu flight recorder"},
    }


def write_chrome_trace(
    path: str,
    tracks: Dict[str, List[TraceEvent]],
    pids: Optional[Dict[str, int]] = None,
) -> str:
    with open(path, "w") as fh:
        json.dump(chrome_trace(tracks, pids), fh)
    return path


def merge_chrome_traces(parts: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge already-rendered Chrome traces from SEPARATE processes
    into one (the process-per-node cluster: each worker dumps its own
    node's trace; the parent merges).

    Every per-process trace's timestamps are relative to its OWN
    earliest event, but :func:`chrome_trace` records that absolute
    anchor in ``otherData.t0_unix_s`` — since every event was stamped
    with ``time.time()`` at emit, realigning each part by
    ``(t0_part - t0_min)`` puts all processes on the shared wall clock
    without re-timing anything.  Metadata records (``ph == "M"``,
    always ts 0) are not shifted.  Pid collisions across parts are
    remapped (workers pin pid = node id, so collisions only appear if
    two parts carry the same node — e.g. a restart's second trace).
    """
    anchored = []
    for p in parts:
        if not isinstance(p, dict):
            continue
        evs = p.get("traceEvents") or []
        t0 = float((p.get("otherData") or {}).get("t0_unix_s", 0.0))
        anchored.append((evs, t0, any(ev.get("ph") != "M" for ev in evs)))
    real_t0s = [t0 for _, t0, has_data in anchored if has_data]
    t0_min = min(real_t0s) if real_t0s else 0.0
    merged: List[Dict[str, Any]] = []
    used: set = set()
    for evs, t0, has_data in anchored:
        pids = sorted({int(ev.get("pid", 0)) for ev in evs})
        remap: Dict[int, int] = {}
        for pid in pids:
            new = pid
            while new in used:
                new = max(used) + 1
            remap[pid] = new
            used.add(new)
        shift_us = (t0 - t0_min) * 1e6 if has_data else 0.0
        for ev in evs:
            ev = dict(ev)
            ev["pid"] = remap.get(int(ev.get("pid", 0)), ev.get("pid", 0))
            if ev.get("ph") != "M":
                ev["ts"] = round(float(ev.get("ts", 0.0)) + shift_us, 1)
            merged.append(ev)
    return {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "t0_unix_s": t0_min,
            "source": "hbbft-tpu flight recorder (process merge)",
        },
    }
