"""Flight recorder (round 12): cross-node epoch tracing + live scrape.

Round 16 adds :mod:`hbbft_tpu.obs.analyze` — the consensus
critical-path analyzer and live stall diagnostician over the same
rings (``/diag`` on the scrape server, ``tools/analyze.py`` for dumped
traces).

Four pieces, usable separately:

* :mod:`hbbft_tpu.obs.trace` — a bounded per-node ring of structured
  protocol events (:class:`TraceBuffer`) plus the thread-local tracer
  the Python protocol modules emit through (a no-op when no tracer is
  installed, so VirtualNet simulations and unit tests pay one attribute
  lookup per milestone).
* :mod:`hbbft_tpu.obs.export` — merges per-node rings on the shared
  wall clock into Chrome trace-event JSON (one track per node, derived
  spans per epoch phase) and per-epoch phase-latency summaries.
* :mod:`hbbft_tpu.obs.server` — a stdlib-HTTP scrape server serving
  ``/metrics`` (Prometheus exposition), ``/trace.json`` and
  ``/healthz`` for a live :class:`~hbbft_tpu.transport.cluster.
  LocalCluster` (usable mid-run — every read path snapshots).

The native arm's events come from a bounded event log inside
``native/engine.cpp`` drained one ctypes call per sweep
(``hbe_trace_drain``); both arms share the event taxonomy documented in
docs/OBSERVABILITY.md.

Re-exports resolve LAZILY (PEP 562): every protocol module does
``from hbbft_tpu.obs import trace`` on import, and that must not drag
``http.server`` (via server.py) into simulations that never scrape.
"""

from typing import Any

_EXPORTS = {
    "TraceBuffer": "hbbft_tpu.obs.trace",
    "TraceEvent": "hbbft_tpu.obs.trace",
    "chrome_trace": "hbbft_tpu.obs.export",
    "phase_spans": "hbbft_tpu.obs.export",
    "phase_summaries": "hbbft_tpu.obs.export",
    "write_chrome_trace": "hbbft_tpu.obs.export",
    "ObsServer": "hbbft_tpu.obs.server",
    # round 16: critical-path analyzer + stall diagnostician
    "critical_path": "hbbft_tpu.obs.analyze",
    "summarize_critical_paths": "hbbft_tpu.obs.analyze",
    "diagnose": "hbbft_tpu.obs.analyze",
    "merge_diags": "hbbft_tpu.obs.analyze",
    "derived_summaries": "hbbft_tpu.obs.analyze",
    "tracks_from_chrome": "hbbft_tpu.obs.analyze",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str) -> Any:
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)
