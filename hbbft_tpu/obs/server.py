"""Live scrape endpoints for a running cluster (stdlib HTTP only).

One :class:`ObsServer` per :class:`~hbbft_tpu.transport.cluster.
LocalCluster` (``cluster.serve_obs()``), answering while the run is
live — every read path snapshots (Metrics takes its lock per family,
trace buffers copy under theirs, batch counts are O(1)), so a scrape
never blocks or perturbs the protocol/transport threads beyond those
snapshots.

Endpoints:

* ``GET /metrics``  — merged Prometheus exposition
  (:meth:`Metrics.prometheus_text` over
  :meth:`LocalCluster.merged_metrics`, which also carries the
  ``epoch.latency`` / ``phase.*`` summaries and the native arms'
  ``engine.cyc.*`` counters).
* ``GET /trace.json`` — the merged Chrome trace (one track per node;
  loads in Perfetto / ``chrome://tracing``).
* ``GET /healthz`` — JSON liveness: per node ``alive`` (protocol
  thread running), ``batches`` (committed count) and
  ``last_committed`` ``[era, epoch]`` (null before the first commit);
  top-level ``ok`` is true iff every non-Byzantine node is alive.
  Status 200 when ok, 503 otherwise (load-balancer semantics).
* ``GET /diag`` — the live stall diagnosis
  (:func:`~hbbft_tpu.obs.analyze.diagnose` over the SAME rings the
  trace export reads, so live and post-mortem analysis can never
  disagree): ``stalled`` / ``since_s``, the open epoch per node,
  per-instance stuck phases (which proposer's RBC is incomplete, which
  BA is stuck at which round), link state, and a ``verdict`` naming
  the most-implicated (proposer, phase) when stalled.
  ``?stall_s=<seconds>`` overrides the quiescence threshold (default
  5 s).  Always HTTP 200 — a diagnosis of "stalled" is a successful
  scrape.

Tests drive these with ``urllib`` against a driven N=4 cluster
(tests/test_obs.py); benchmarks expose them via ``BENCH_OBS_PORT``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional, Tuple
from urllib.parse import parse_qs


class ObsServer:
    """Serve /metrics, /trace.json and /healthz for ``cluster``."""

    def __init__(
        self, cluster: Any, host: str = "127.0.0.1", port: int = 0
    ) -> None:
        self.cluster = cluster
        obs = self

        class Handler(BaseHTTPRequestHandler):
            # quiet: a polling scraper must not spam the test log
            def log_message(self, fmt: str, *args: Any) -> None:
                pass

            def _reply(
                self, code: int, body: bytes, ctype: str
            ) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/metrics":
                        text = obs.cluster.merged_metrics().prometheus_text()
                        self._reply(
                            200,
                            text.encode(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/trace.json":
                        body = json.dumps(obs.cluster.chrome_trace()).encode()
                        self._reply(200, body, "application/json")
                    elif path == "/healthz":
                        ok, health = obs.health()
                        self._reply(
                            200 if ok else 503,
                            json.dumps(health).encode(),
                            "application/json",
                        )
                    elif path == "/diag":
                        qs = parse_qs(
                            self.path.partition("?")[2], keep_blank_values=False
                        )
                        try:
                            stall_s = float(qs["stall_s"][0])
                        except (KeyError, ValueError, IndexError):
                            stall_s = 5.0
                        self._reply(
                            200,
                            json.dumps(obs.diag(stall_s)).encode(),
                            "application/json",
                        )
                    else:
                        self._reply(404, b"not found\n", "text/plain")
                except Exception as exc:  # a scrape bug must not kill the run
                    try:
                        self._reply(
                            500, f"scrape error: {exc}\n".encode(), "text/plain"
                        )
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def addr(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def diag(self, stall_after_s: float = 5.0) -> dict:
        """The live stall diagnosis for ``/diag``: the cluster's own
        :meth:`diag` when it has one (LocalCluster), else
        :func:`~hbbft_tpu.obs.analyze.diagnose` over its rings (the
        single-node worker view, which carries the cluster's real
        consensus size as ``consensus_n``).  Dead HONEST protocol
        threads from the health probe ride along — a diagnosis that
        names a stuck proposer but hides a crashed node would mislead."""
        c = self.cluster
        own = getattr(c, "diag", None)
        if callable(own):
            d = own(stall_after_s)
        else:
            from hbbft_tpu.obs.analyze import diagnose

            d = diagnose(
                c.trace_events(),
                n=getattr(c, "consensus_n", None) or getattr(c, "n", None),
                stall_after_s=stall_after_s,
            )
        _ok, health = self.health()
        dead = sorted(
            int(i)
            for i, st in health["nodes"].items()
            if not st["alive"] and not st.get("byzantine")
        )
        if dead:
            d["dead_nodes"] = dead
        return d

    def health(self) -> Tuple[bool, dict]:
        c = self.cluster
        nodes = {}
        ok = True
        for i, node in sorted(c.nodes.items()):
            # is_alive(), not a None check: a protocol thread that died
            # from an uncaught exception still leaves its Thread object
            # behind — reporting it alive would hide an outage.
            t = getattr(node, "_thread", None)
            alive = t is not None and t.is_alive()
            last = c.last_committed(i)
            nodes[str(i)] = {
                "alive": alive,
                "batches": c.batch_count(i),
                "last_committed": list(last) if last is not None else None,
                "byzantine": i in getattr(c, "byzantine", {}),
            }
            if not alive and i not in getattr(c, "byzantine", {}):
                ok = False  # a dead HONEST node is an outage; a dead
                #             adversary (crash-stop) is the schedule
        return ok, {"ok": ok, "n": c.n, "nodes": nodes}

    def start(self) -> "ObsServer":
        assert self._thread is None
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="obs-server",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        self._thread = None
