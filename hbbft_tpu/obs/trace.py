"""Bounded protocol-event rings + the thread-local tracer.

Event model: a :class:`TraceEvent` is ``(ts, name, args)`` — wall-clock
seconds (``time.time()``, comparable across every node/thread on one
box, and with the engine's ``CLOCK_REALTIME`` stamps), a dotted
milestone name from the taxonomy in docs/OBSERVABILITY.md
(``epoch.open``, ``rbc.deliver``, ``ba.coin``, ...), and a small args
dict (era/epoch/proposer/round/...).

Cost model: events fire at MILESTONE rate (once per epoch phase
transition per proposer — tens per epoch), never per message, so the
ring can afford a lock and a timestamp.  The protocol modules emit via
the module-level :func:`emit`, which is a no-op costing one
thread-local attribute read when no tracer is installed — VirtualNet
simulations, unit tests, and the simulated-net benchmarks
(``NativeQhbNet``) never install one and stay unperturbed.

Memory model: the ring is a preallocated fixed-size list; overflow
drops the OLDEST event and counts it (``dropped``).  A reader that
polls often enough (the native node drains its engine ring every
sweep; the exporter snapshots on demand) sees everything; a reader
that does not still gets the newest ``capacity`` events and an honest
drop count — bounded memory under any flood (pinned by
tests/test_obs.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional


class TraceEvent(NamedTuple):
    ts: float        # wall-clock seconds (time.time() / CLOCK_REALTIME)
    name: str        # milestone name ("epoch.open", "ba.coin", ...)
    args: Dict[str, Any]


class TraceBuffer:
    """One node's (or the cluster's) bounded event ring.

    Thread-safe: a node's protocol thread and its transport's selector
    thread share one buffer (different milestones, same timeline).
    """

    __slots__ = ("track", "capacity", "_ring", "_head", "_tail",
                 "dropped", "_lock")

    def __init__(self, track: str = "", capacity: int = 8192) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.track = track
        self.capacity = capacity
        self._ring: List[Optional[TraceEvent]] = [None] * capacity
        self._head = 0  # total emitted (next write index, unwrapped)
        self._tail = 0  # oldest retained (unwrapped)
        self.dropped = 0
        self._lock = threading.Lock()

    def emit(self, name: str, **args: Any) -> None:
        ev = TraceEvent(time.time(), name, args)
        with self._lock:
            if self._head - self._tail == self.capacity:
                self._tail += 1
                self.dropped += 1
            self._ring[self._head % self.capacity] = ev
            self._head += 1

    def __len__(self) -> int:
        return self._head - self._tail

    def snapshot(self) -> List[TraceEvent]:
        """Copy of the retained events, oldest first (emit order — the
        exporter's bracketing relies on per-buffer order; cross-buffer
        alignment is by timestamp)."""
        with self._lock:
            return [
                self._ring[i % self.capacity]  # type: ignore[misc]
                for i in range(self._tail, self._head)
            ]

    def extend(self, events: List[TraceEvent]) -> None:
        """Append pre-stamped events (the native node's engine-ring
        drain path: stamps were taken in C at emit time)."""
        with self._lock:
            for ev in events:
                if self._head - self._tail == self.capacity:
                    self._tail += 1
                    self.dropped += 1
                self._ring[self._head % self.capacity] = ev
                self._head += 1


class _Tracer(threading.local):
    """Per-thread tracer state: the installed buffer plus a small
    context dict (era/epoch/proposer) the owning protocol layers keep
    current so leaf protocols (Broadcast, BinaryAgreement) can emit
    attributable milestones without API changes."""

    buf: Optional[TraceBuffer] = None

    def __init__(self) -> None:  # fresh ctx per thread
        self.ctx: Dict[str, Any] = {}
        # Saved contexts for swap(): single-thread simulators interleave
        # many nodes on one thread, and each node's accumulated ctx
        # (era, set at construction / era change) must survive the
        # interleaving.  Keyed by the buffer object (alive for the sim's
        # lifetime); install() clears it.
        self.saved: Dict[Any, Dict[str, Any]] = {}


_TLS = _Tracer()


def install(buf: Optional[TraceBuffer]) -> None:
    """Install ``buf`` as this thread's tracer (None uninstalls).  The
    context starts fresh; any swap() save-space is dropped."""
    _TLS.buf = buf
    _TLS.ctx = {}
    _TLS.saved = {}


def swap(buf: Optional[TraceBuffer]) -> None:
    """Switch this thread's tracer to ``buf``, PRESERVING each buffer's
    accumulated context across switches (unlike :func:`install`, which
    resets it).  This is the simulator hand-off: VirtualNet runs every
    node on one thread and swaps the matching buffer in around each
    handler call, so a node's era ctx (set once at construction or era
    change) keeps attributing its later emits."""
    t = _TLS
    if t.buf is buf:
        return
    if t.buf is not None:
        t.saved[t.buf] = t.ctx
    t.buf = buf
    t.ctx = t.saved.pop(buf, {}) if buf is not None else {}


def emit(name: str, **args: Any) -> None:
    """Emit a milestone through the thread's tracer; merges the current
    context under the explicit args.  No-op (one attribute read) when
    no tracer is installed."""
    buf = _TLS.buf
    if buf is None:
        return
    if _TLS.ctx:
        merged = dict(_TLS.ctx)
        merged.update(args)
        args = merged
    buf.emit(name, **args)


def set_ctx(**kw: Any) -> None:
    """Update the thread's tracer context (no-op without a tracer)."""
    if _TLS.buf is None:
        return
    _TLS.ctx.update(kw)


def clear_ctx(*keys: str) -> None:
    """Drop context keys (no-op without a tracer).  Epoch-level emits
    use this so a leaf-level key (proposer) set by an earlier message
    does not leak onto events that have no such attribution."""
    if _TLS.buf is None:
        return
    for k in keys:
        _TLS.ctx.pop(k, None)
