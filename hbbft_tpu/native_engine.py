"""Native (C++) protocol-plane engine: bindings + the engine-backed net.

Reference behavior: the reference's consensus stack is native code end to
end; ``native/engine.cpp`` is this framework's equivalent for the
message-intensive layers (Broadcast, SBV/BA + coin, ThresholdDecrypt,
Subset, the HoneyBadger epoch loop) over the scalar test suite, running
a whole simulated network (the VirtualNet crank loop) inside one C++
queue.  The per-BATCH layers stay in Python and are REUSED, not
reimplemented: :class:`NativeDhb` subclasses the real
``DynamicHoneyBadger`` (votes, DKG, era logic) and plugs an engine
facade in place of its inner HoneyBadger; ``QueueingHoneyBadger`` runs
unmodified on top.

Fidelity: the engine commits byte-identical batches and fault logs to
the pure-Python VirtualNet at the same seed (tests/test_native_engine.py
pins this at several N).  Randomness stays in Python — the engine calls
back / is called at exactly the points the Python stack would consume
the node rng, so the streams match by construction.

Scope: int node ids 0..N-1, no adversary (FIFO delivery, silent
crash-faulty nodes).  Two crypto configurations:

* **ScalarSuite (native)** — the engine computes the scalar-suite
  checks itself; protocol-plane benchmark configuration (BASELINE
  configs 3/4).  Round 7: COIN/DECRYPT share checks are verified per
  Ts/Td instance GROUP with one random-linear-combination check at the
  pool flush (``HBBFT_TPU_COIN_RLC=0`` / ``rlc=False`` restores the
  per-share submit-time path), and ``flush_every`` now also governs the
  scalar cadence when RLC is on — 1 keeps the pre-round-7 per-unit
  flush points byte-for-byte, 0 defers to queue-dry for maximal
  grouping with identical protocol outputs and fault sets (the
  deferred-verification invariant; tests/test_native_rlc.py).
* **External crypto (round 3)** — any real :class:`Suite` (BLS12-381):
  group elements travel through the engine as opaque bytes; signing,
  combining and ciphertext parsing call back into Python per instance,
  and verifications accumulate in the engine's per-node pools until a
  flush routes them through a pluggable
  :class:`~hbbft_tpu.crypto.backend.CryptoBackend` (Eager / Batched RLC
  / TpuBackend) — the reference runs real ``threshold_crypto`` under
  its native stack throughout (SURVEY.md §2 #14); this is the
  TPU-native equivalent with the deferred-verify flush.
  ``flush_every`` mirrors the VirtualNet knob; 0 = flush only when the
  delivery queue runs dry (maximal amortization — identical outputs by
  the deferred-verification invariant).
"""

from __future__ import annotations

import ctypes
import os
import random
from typing import Any, Callable, Dict, List, Optional, Tuple

from hbbft_tpu.crypto.backend import (
    BatchedBackend,
    CryptoBackend,
    VerifyRequest,
)
from hbbft_tpu.crypto.keys import (
    Ciphertext,
    DecryptionShare,
    SecretKey,
    SecretKeySet,
    SignatureShare,
)
from hbbft_tpu.crypto.pool import VerifySink
from hbbft_tpu.crypto.suite import ScalarSuite, Suite
from hbbft_tpu.protocols.dynamic_honey_badger import (
    DhbBatch,
    DynamicHoneyBadger,
    InternalContrib,
    SignedKeyGenMsg,
)
from hbbft_tpu.protocols.honey_badger import Batch, EncryptionSchedule
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger
from hbbft_tpu.protocols.traits import Step
from hbbft_tpu.utils import canonical_bytes, serde

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_ROOT, "native", "engine.cpp")
# One shared library per NodeSet width (-DHBE_WORDS): the 4-word build
# serves the common <= 256-node range at full speed; wider builds are
# compiled on demand for larger networks (see engine.cpp's NodeSet).
_SO_TMPL = os.path.join(_ROOT, "native", "build", "libhbbft_engine_w{w}.so")


def _words_for(n: int) -> int:
    w = 4
    while 64 * w < n:
        w *= 2
    return w

_BATCH_CB = ctypes.CFUNCTYPE(None, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32)
_CONTRIB_CB = ctypes.CFUNCTYPE(
    ctypes.c_int32,
    ctypes.c_int32,
    ctypes.c_int32,
    ctypes.c_int32,
    ctypes.c_int32,
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_uint64,
)
_VERIFY_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8)
)
_SIGN_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_int32,
    ctypes.c_int32,
    ctypes.c_int32,
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_uint64,
    ctypes.c_void_p,
)
_COMBINE_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_int32,
    ctypes.c_int32,
    ctypes.c_int32,
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_uint64,
    ctypes.c_int32,
    ctypes.c_void_p,
)
_CT_PARSE_CB = ctypes.CFUNCTYPE(
    ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64
)
_PRE_CRANK_CB = ctypes.CFUNCTYPE(None, ctypes.c_uint64)
_TAMPER_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
    ctypes.c_int32, ctypes.c_int32,
)


def _load(words: int) -> Optional[ctypes.CDLL]:
    # HBBFT_TPU_ENGINE_LIB: load a pre-built engine library instead of
    # compiling engine.cpp — the sanitizer tier's hook (make asan/ubsan/
    # tsan in native/, then point this at the produced .so; ASan/TSan
    # also need their runtime LD_PRELOADed into the Python process).
    # The override is width-blind: it is handed out for EVERY NodeSet
    # width request, so only drive networks the build's -DHBE_WORDS
    # supports (the Makefile default is 4 words = 256 nodes).
    override = os.environ.get("HBBFT_TPU_ENGINE_LIB")
    if override:
        try:
            lib = ctypes.CDLL(override)
        except OSError as exc:
            # An explicitly requested engine failing to load must be
            # LOUD: silently degrading to "unavailable" makes every
            # native test skip and hides e.g. a missing LD_PRELOAD of
            # the sanitizer runtime (the result would also be cached).
            raise RuntimeError(
                f"HBBFT_TPU_ENGINE_LIB={override!r} failed to load"
                " (sanitizer builds additionally need their runtime"
                " LD_PRELOADed — see tests/test_sanitizers.py)"
            ) from exc
        # Fail fast if the pre-built library's NodeSet width cannot
        # serve the requested network — otherwise hbe_create returns
        # nullptr and the caller dies on a messageless assert.
        try:
            lib.hbe_words.restype = ctypes.c_int32
            lib.hbe_words.argtypes = []
        except AttributeError as exc:
            raise RuntimeError(
                f"HBBFT_TPU_ENGINE_LIB={override!r} exports no hbe_words"
                " symbol: it was built from a pre-sanitizer-tier"
                " engine.cpp — rebuild it from the current source"
            ) from exc
        have = int(lib.hbe_words())
        if have < words:
            raise RuntimeError(
                f"HBBFT_TPU_ENGINE_LIB={override!r} was built with"
                f" -DHBE_WORDS={have} (max {64 * have} nodes) but this"
                f" network needs {words} words; rebuild with"
                f" ENGINE_WORDS={words} (native/Makefile)"
            )
    else:
        from hbbft_tpu.ops.native import build_and_load

        # The vectorized field plane (ISSUE 14): field_ifma.cpp is the
        # only unit compiled with -mavx512ifma (dropped automatically on
        # toolchains without it — the stub arm compiles instead, and the
        # runtime dispatch keeps scalar); field_plane.h / sha3_plane.h
        # (ISSUE 17) are header deps of engine.cpp, so edits rebuild
        # every width.
        native_dir = os.path.dirname(_SRC)
        lib = build_and_load(
            _SRC, _SO_TMPL.format(w=words),
            extra_flags=(f"-DHBE_WORDS={words}",),
            aux_sources=(os.path.join(native_dir, "field_ifma.cpp"),),
            aux_flags=("-mavx512ifma",),
            extra_deps=(
                os.path.join(native_dir, "field_plane.h"),
                os.path.join(native_dir, "sha3_plane.h"),
            ),
        )
    if lib is None:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.hbe_create.restype = ctypes.c_void_p
    lib.hbe_create.argtypes = [ctypes.c_int32, ctypes.c_int32]
    lib.hbe_destroy.argtypes = [ctypes.c_void_p]
    lib.hbe_set_callbacks.argtypes = [ctypes.c_void_p, _BATCH_CB, _CONTRIB_CB]
    lib.hbe_set_silent.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
    init_args = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, u8p, ctypes.c_uint64,
        i32p, ctypes.c_int32, ctypes.c_int32, u8p, u8p,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.hbe_init_node.argtypes = init_args
    lib.hbe_restart_node.argtypes = init_args
    lib.hbe_replay_era.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.hbe_propose.restype = ctypes.c_int32
    lib.hbe_propose.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, u8p, ctypes.c_uint64,
    ]
    lib.hbe_run.restype = ctypes.c_uint64
    lib.hbe_run.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.hbe_run_mt.restype = ctypes.c_uint64
    lib.hbe_run_mt.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int32,
    ]
    lib.hbe_queue_len.restype = ctypes.c_uint64
    lib.hbe_queue_len.argtypes = [ctypes.c_void_p]
    lib.hbe_delivered.restype = ctypes.c_uint64
    lib.hbe_delivered.argtypes = [ctypes.c_void_p]
    for name in ("hbe_epoch", "hbe_era", "hbe_has_proposed"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int32
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.hbe_batch_size.restype = ctypes.c_int32
    lib.hbe_batch_size.argtypes = [ctypes.c_void_p]
    lib.hbe_batch_proposer.restype = ctypes.c_int32
    lib.hbe_batch_proposer.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.hbe_batch_payload_len.restype = ctypes.c_uint64
    lib.hbe_batch_payload_len.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.hbe_batch_payload.argtypes = [ctypes.c_void_p, ctypes.c_int32, u8p]
    lib.hbe_fault_count.restype = ctypes.c_int32
    lib.hbe_fault_count.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.hbe_fault_subject.restype = ctypes.c_int32
    lib.hbe_fault_subject.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
    lib.hbe_fault_kind.restype = ctypes.c_char_p
    lib.hbe_fault_kind.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
    # external-crypto mode
    lib.hbe_set_ext_crypto.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, _VERIFY_CB, _SIGN_CB, _COMBINE_CB,
        _CT_PARSE_CB,
    ]
    lib.hbe_set_flush_every.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.hbe_set_rlc.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.hbe_set_pre_crank.argtypes = [ctypes.c_void_p, _PRE_CRANK_CB]
    lib.hbe_queue_swap.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
    ]
    lib.hbe_queue_dest.restype = ctypes.c_int32
    lib.hbe_queue_dest.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    # tampering adversary (TamperingAdversary mirror)
    lib.hbe_set_tamper.restype = None
    lib.hbe_set_tamper.argtypes = [ctypes.c_void_p, _TAMPER_CB]
    lib.hbe_set_tampered.restype = None
    lib.hbe_set_tampered.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.hbe_tamper_bval.restype = ctypes.c_int32
    lib.hbe_tamper_bval.argtypes = [ctypes.c_void_p]
    lib.hbe_tamper_set_bval.restype = None
    lib.hbe_tamper_set_bval.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    for name in ("hbe_tamper_flip_root", "hbe_tamper_corrupt_proof"):
        fn = getattr(lib, name)
        fn.restype = None
        fn.argtypes = [ctypes.c_void_p]
    lib.hbe_tamper_share_len.restype = ctypes.c_uint64
    lib.hbe_tamper_share_len.argtypes = [ctypes.c_void_p]
    lib.hbe_tamper_share.restype = None
    lib.hbe_tamper_share.argtypes = [ctypes.c_void_p, u8p]
    lib.hbe_tamper_set_share.restype = None
    lib.hbe_tamper_set_share.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64]
    # delivery profiling counters (BASELINE.md round-3 workflow)
    for name in ("hbe_prof_cycles", "hbe_prof_count"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_uint64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.hbe_pending_verifies.restype = ctypes.c_uint64
    lib.hbe_pending_verifies.argtypes = [ctypes.c_void_p]
    # scalar-suite KEM fast path (stateless; used by crypto/keys.py)
    lib.hbe_kem_decrypt.restype = ctypes.c_int32
    lib.hbe_kem_decrypt.argtypes = [u8p, u8p, ctypes.c_uint64, u8p, u8p, u8p]
    lib.hbe_kem_encrypt.restype = None
    lib.hbe_kem_encrypt.argtypes = [
        u8p, u8p, ctypes.c_uint64, u8p, u8p, u8p, u8p,
    ]
    # scalar DKG fast path (stateless registry; bytes args pass as
    # c_char_p so Python bytes cross zero-copy)
    cp = ctypes.c_char_p
    lib.hbe_kem_encrypt_batch.restype = None
    lib.hbe_kem_encrypt_batch.argtypes = [
        cp, cp, ctypes.c_int32, cp, u8p, u8p, u8p,
    ]
    lib.hbe_dkg_register.restype = ctypes.c_int64
    lib.hbe_dkg_register.argtypes = [cp, ctypes.c_int32, cp, cp]
    lib.hbe_dkg_registry_size.restype = ctypes.c_uint64
    lib.hbe_dkg_registry_size.argtypes = []
    lib.hbe_dkg_clear.restype = None
    lib.hbe_dkg_clear.argtypes = []
    lib.hbe_serde_scan.restype = ctypes.c_int64
    lib.hbe_serde_scan.argtypes = [
        cp, ctypes.c_uint64, ctypes.POINTER(ctypes.c_int64), ctypes.c_uint64,
        ctypes.c_int64, ctypes.c_uint64,
    ]
    # cluster (one-engine-per-node) mode + wire codec (round 9): the
    # message-boundary API — batch frame ingress, epoch-gated egress
    # drain, and the decode/roundtrip test surface.
    lib.hbe_set_local.restype = None
    lib.hbe_set_local.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
    lib.hbe_node_ingest_frames.restype = ctypes.c_int64
    lib.hbe_node_ingest_frames.argtypes = [
        ctypes.c_void_p, i32p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int32, cp,
    ]
    lib.hbe_node_egress_bytes.restype = ctypes.c_uint64
    lib.hbe_node_egress_bytes.argtypes = [ctypes.c_void_p]
    lib.hbe_node_egress_drain.restype = ctypes.c_int64
    lib.hbe_node_egress_drain.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64]
    # MSGB wire fast path (round 20 coalescing).  Guarded: pre-20 engine
    # snapshots loaded via HBBFT_TPU_ENGINE_LIB lack these symbols —
    # callers check NativeNodeEngine.supports_wire_batch and fall back
    # to the per-frame entry points above.
    if hasattr(lib, "hbe_node_ingest_wire"):
        lib.hbe_node_ingest_wire.restype = ctypes.c_int64
        lib.hbe_node_ingest_wire.argtypes = [
            ctypes.c_void_p, i32p, ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int32, cp,
        ]
        lib.hbe_node_egress_drain_msgb.restype = ctypes.c_int64
        lib.hbe_node_egress_drain_msgb.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, u8p, ctypes.c_uint64,
        ]
    lib.hbe_node_stat.restype = ctypes.c_uint64
    lib.hbe_node_stat.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    # flight recorder (round 12): bounded milestone event ring
    lib.hbe_trace_enable.restype = None
    lib.hbe_trace_enable.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.hbe_trace_drain.restype = ctypes.c_int64
    lib.hbe_trace_drain.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64]
    lib.hbe_trace_pending.restype = ctypes.c_uint64
    lib.hbe_trace_pending.argtypes = [ctypes.c_void_p]
    lib.hbe_trace_dropped.restype = ctypes.c_uint64
    lib.hbe_trace_dropped.argtypes = [ctypes.c_void_p]
    lib.hbe_wire_classify.restype = ctypes.c_int32
    lib.hbe_wire_classify.argtypes = [cp, ctypes.c_uint64]
    lib.hbe_wire_roundtrip.restype = ctypes.c_int64
    lib.hbe_wire_roundtrip.argtypes = [cp, ctypes.c_uint64, u8p, ctypes.c_uint64]
    lib.hbe_dkg_ack_check.restype = ctypes.c_int32
    lib.hbe_dkg_ack_check.argtypes = [
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, cp, cp, cp, cp, u8p,
    ]
    lib.hbe_dkg_row_check.restype = ctypes.c_int32
    lib.hbe_dkg_row_check.argtypes = [
        ctypes.c_int64, ctypes.c_int32, cp, ctypes.c_int32,
    ]
    # batch DKG digest (round 6): whole-batch ack/part checks + the
    # vectorized Lagrange/combine entry points
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.hbe_dkg_ack_check_batch.restype = ctypes.c_int32
    lib.hbe_dkg_ack_check_batch.argtypes = [
        i64p, i32p, ctypes.c_int32, ctypes.c_int32, cp, cp, cp, cp,
        i32p, u8p,
    ]
    lib.hbe_dkg_part_check_batch.restype = ctypes.c_int32
    lib.hbe_dkg_part_check_batch.argtypes = [
        i64p, ctypes.c_int32, ctypes.c_int32, cp, cp, cp, ctypes.c_int32,
        cp, i32p, u8p,
    ]
    lib.hbe_scalar_interp_sum.restype = ctypes.c_int32
    lib.hbe_scalar_interp_sum.argtypes = [
        i32p, cp, i32p, ctypes.c_int32, cp, u8p,
    ]
    lib.hbe_scalar_combine_unmask.restype = ctypes.c_int32
    lib.hbe_scalar_combine_unmask.argtypes = [
        i32p, ctypes.c_int32, cp, cp, cp, ctypes.c_uint64, u8p,
    ]
    lib.hbe_dkg_row_evals.restype = None
    lib.hbe_dkg_row_evals.argtypes = [
        cp, ctypes.c_int32, ctypes.c_int32, u8p,
    ]
    # SIMD field-plane dispatch + kernel fuzz surface (ISSUE 14):
    # hbe_simd_mode reports the resolved arm (1 = AVX-512 IFMA, 0 =
    # scalar), hbe_simd_force pins it in-process for both-arm
    # equivalence tests (-1 = back to HBBFT_TPU_SIMD/auto).
    for name in ("hbe_simd_mode", "hbe_simd_compiled"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int32
        fn.argtypes = []
    lib.hbe_simd_force.restype = ctypes.c_int32
    lib.hbe_simd_force.argtypes = [ctypes.c_int32]
    lib.hbe_field_mul_batch.restype = None
    lib.hbe_field_mul_batch.argtypes = [cp, cp, ctypes.c_int32, u8p]
    lib.hbe_field_dot.restype = None
    lib.hbe_field_dot.argtypes = [cp, cp, ctypes.c_int32, u8p]
    lib.hbe_field_lagrange.restype = None
    lib.hbe_field_lagrange.argtypes = [i32p, ctypes.c_int32, u8p]
    lib.hbe_field_rlc_accum.restype = None
    lib.hbe_field_rlc_accum.argtypes = [cp, cp, ctypes.c_int32, u8p]
    # Batched sha3 plane + epoch arena (ISSUE 17): the sha3 fuzz/stats
    # surface and the arena high-water-mark telemetry.  Guarded: pre-17
    # engine snapshots loaded via HBBFT_TPU_ENGINE_LIB for vs-seed A/Bs
    # lack these symbols — stats callers degrade to {} (arena_stats /
    # sha3_plane_stats), everything else is unaffected.
    if hasattr(lib, "hbe_sha3_batch"):
        lib.hbe_sha3_batch.restype = None
        lib.hbe_sha3_batch.argtypes = [
            cp, ctypes.c_uint64, ctypes.c_uint64, u8p,
        ]
        lib.hbe_sha3_stats.restype = None
        lib.hbe_sha3_stats.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
        lib.hbe_arena_stats.restype = None
        lib.hbe_arena_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ]
    lib.hbe_flush.restype = None
    lib.hbe_flush.argtypes = [ctypes.c_void_p]
    lib.hbe_ret_bytes.restype = None
    lib.hbe_ret_bytes.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64]
    for name in ("hbe_vreq_kind", "hbe_vreq_era", "hbe_vreq_sender",
                 "hbe_comb_index"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int32
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    for name in ("hbe_vreq_doc_len", "hbe_vreq_ct_len", "hbe_vreq_share_len",
                 "hbe_comb_share_len"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_uint64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    for name in ("hbe_vreq_doc", "hbe_vreq_ct", "hbe_vreq_share",
                 "hbe_comb_share"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int32, u8p]
    return lib


_LIBS: Dict[int, Optional[ctypes.CDLL]] = {}


def get_lib(words: int = 4) -> Optional[ctypes.CDLL]:
    if words not in _LIBS:
        _LIBS[words] = _load(words)
    return _LIBS[words]


def available() -> bool:
    return get_lib() is not None


def simd_mode(lib: Optional[ctypes.CDLL] = None) -> str:
    """Resolved field-plane dispatch arm of the (default) engine build:
    ``"ifma"`` or ``"scalar"``.  Benchmarks stamp this into their JSON
    lines so A/B rows are self-describing (CLAUDE.md clock-drift
    rules)."""
    lib = lib if lib is not None else get_lib()
    if lib is None:
        return "unavailable"
    return "ifma" if lib.hbe_simd_mode() else "scalar"


def sha3_plane_stats(lib: Optional[ctypes.CDLL] = None) -> Dict[str, int]:
    """Batched sha3-plane counters since process start (library-global,
    ISSUE 17): batch calls/messages, messages hashed by the 8-lane IFMA
    arm, and single-message (``sha3_256_one``) calls.  Module-level so
    benchmarks without a net handle (config6 clusters) can stamp them;
    per-run only when one engine build hashed in this process."""
    lib = lib if lib is not None else get_lib()
    if lib is None or not hasattr(lib, "hbe_sha3_stats"):
        return {}
    buf = (ctypes.c_uint64 * 4)()
    lib.hbe_sha3_stats(buf)
    return {
        "batch_calls": int(buf[0]),
        "batch_msgs": int(buf[1]),
        "ifma_msgs": int(buf[2]),
        "single_msgs": int(buf[3]),
    }


_SCHED_KINDS = {"always": 0, "never": 1, "every_nth": 2, "tick_tock": 3}
_DECODE_FAILED = object()
_DECODE_CACHE_MAX = 65536


def _cache_put(cache: Dict[Any, Any], key: Any, value: Any,
               cap: int = _DECODE_CACHE_MAX) -> None:
    """Insert with FIFO eviction (insertion-ordered dict): every engine
    cache holds pure-function results, so evicting a live entry is
    always correct — a later lookup recomputes it."""
    cache[key] = value
    if len(cache) > cap:
        cache.pop(next(iter(cache)))


def _share_decoders(suite: Suite):
    """(g1, g2) wire decoders for share bytes arriving via the engine.

    Structural decode only where the suite supports it (BLS): the
    membership policy is the backend's job (request_well_formed /
    on-device checks) — matching the in-process Python net, where shares
    arrive as objects and are policed exclusively at flush.  Suites
    without a structural decoder fall back to the strict codec entry
    points (cheap for ScalarSuite).
    """
    if getattr(suite, "name", "") == "bls12-381":
        from hbbft_tpu.crypto.bls import suite as _bls

        def dec_g1(data: bytes) -> Any:
            return _bls.G1Elem(_bls._jac_from_bytes(data, fq2=False))

        def dec_g2(data: bytes) -> Any:
            return _bls.G2Elem(_bls._jac_from_bytes(data, fq2=True))

        return dec_g1, dec_g2
    return suite.g1_from_bytes, suite.g2_from_bytes


def _be32(x: int) -> bytes:
    return int(x).to_bytes(32, "big")


class _NullSink(VerifySink):
    """DHB itself never submits verifications (vote signatures verify
    inline); the engine handles everything below HB internally."""

    def submit(self, req: Any, cb: Any) -> None:  # pragma: no cover
        raise AssertionError("native DHB layer should not submit verifies")


class EngineHb:
    """Facade standing in for DynamicHoneyBadger's inner HoneyBadger.

    Mirrors honey_badger.HoneyBadger._propose_now's data preparation
    byte-for-byte (serde.dumps + threshold-encrypt with the node rng),
    then hands the payload to the engine.
    """

    def __init__(self, net: "NativeQhbNet", node_id: int, era: int,
                 netinfo: NetworkInfo, schedule: EncryptionSchedule) -> None:
        self._net = net
        self._node_id = node_id
        self._era = era
        self._netinfo = netinfo
        self._schedule = schedule

    @property
    def epoch(self) -> int:
        return self._net.lib.hbe_epoch(self._net.handle, self._node_id)

    @property
    def has_input(self) -> bool:
        return bool(self._net.lib.hbe_has_proposed(self._net.handle, self._node_id))

    def handle_input(self, input: Any, rng: Any) -> Step:
        if not self._netinfo.is_validator():
            return Step.empty()
        if self.has_input:
            raise AssertionError(
                "engine HB cannot hold proposals; guard with has_input"
            )
        data = serde.dumps(input)
        if self._schedule.encrypt_on(self.epoch):
            pk = self._netinfo.public_key_set.public_key()
            data = serde.dumps(pk.encrypt(data, rng))
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        accepted = self._net.lib.hbe_propose(
            self._net.handle, self._node_id, self._era, buf, len(data)
        )
        assert accepted, "propose rejected (era/epoch mismatch)"
        return Step.empty()

    def handle_message(self, sender: Any, message: Any, rng: Any) -> Step:
        raise AssertionError("messages are engine-internal")


class NativeDhb(DynamicHoneyBadger):
    """DynamicHoneyBadger whose inner HoneyBadger runs in the engine.

    All vote / DKG / era logic is the REUSED parent implementation;
    only _make_hb (engine node init / era restart) and _replay_next_era
    (engine-buffered messages) differ.
    """

    def __init__(self, net: "NativeQhbNet", node_id: int,
                 netinfo: NetworkInfo, **kwargs: Any) -> None:
        self._net = net
        self._node_id = node_id
        self._engine_inited = False
        super().__init__(netinfo, _NullSink(), **kwargs)

    def _make_hb(self) -> EngineHb:
        net, nid = self._net, self._node_id
        netinfo = self._netinfo
        session = canonical_bytes(self._session_id, self._era)
        val_ids = list(netinfo.all_ids)
        arr = (ctypes.c_int32 * len(val_ids))(*val_ids)
        sk = netinfo.secret_key_share
        if net.ext:
            # External crypto: the engine never touches key material —
            # it only needs the has-share flag; sign/verify/combine go
            # through the Python callbacks, which look keys up here.
            net._node_era_info[(nid, self._era)] = netinfo
            net._era_netinfo.setdefault(self._era, netinfo)
            sk_buf = (
                (ctypes.c_uint8 * 32)() if sk is not None else None
            )
            pk_buf = (ctypes.c_uint8 * (32 * net.n))()
        else:
            sk_buf = (
                (ctypes.c_uint8 * 32).from_buffer_copy(_be32(sk.x))
                if sk is not None
                else None
            )
            pk_flat = bytearray(32 * net.n)
            for vid in val_ids:
                pk_flat[32 * vid : 32 * (vid + 1)] = _be32(
                    netinfo.public_key_share(vid).g1.value
                )
            pk_buf = (ctypes.c_uint8 * len(pk_flat)).from_buffer_copy(bytes(pk_flat))
        sess_buf = (ctypes.c_uint8 * len(session)).from_buffer_copy(session)
        fn = net.lib.hbe_init_node if not self._engine_inited else net.lib.hbe_restart_node
        fn(
            net.handle, nid, self._era, sess_buf, len(session),
            arr, len(val_ids), netinfo.num_faulty,
            sk_buf, pk_buf, self.max_future_epochs,
            _SCHED_KINDS[self.encryption_schedule.kind], self.encryption_schedule.n,
            1 if self.subset_handling == "all_at_end" else 0,
        )
        self._engine_inited = True
        return EngineHb(net, nid, self._era, netinfo, self.encryption_schedule)

    def _replay_next_era(self) -> Step:
        self._net.lib.hbe_replay_era(self._net.handle, self._node_id)
        return Step.empty()

    def handle_message(self, sender: Any, message: Any, rng: Any) -> Step:
        raise AssertionError("messages are engine-internal")


class _NativeNode:
    __slots__ = ("id", "qhb", "rng", "outputs", "contrib_cache")

    def __init__(self, nid: int, qhb: QueueingHoneyBadger, rng: random.Random):
        self.id = nid
        self.qhb = qhb
        self.rng = rng
        self.outputs: List[DhbBatch] = []
        self.contrib_cache: Dict[tuple, Any] = {}


class _EngineNetBase:
    """Shared engine-callback core: everything a Python runtime needs to
    host engine batch events, whether it drives a whole simulated
    network (:class:`NativeQhbNet`) or one cluster node over real
    sockets (:class:`NativeNodeEngine`).

    Subclass contract — attributes the callbacks read: ``lib``,
    ``handle``, ``nodes`` (engine id -> :class:`_NativeNode`),
    ``_suite``, ``_decode_cache`` / ``_slot_cache`` (shared
    committed-payload decode caches), ``_cb_error``.  The decode-cache
    purity rules documented on :class:`NativeQhbNet` apply to every
    subclass.
    """

    lib: Any
    handle: Any
    nodes: Dict[int, "_NativeNode"]

    # -- engine callbacks ----------------------------------------------
    def _on_contrib(self, node, era, epoch, proposer, data, length) -> int:
        # Committed payloads for a (era, epoch, proposer) slot are
        # byte-identical across every node (Subset agreement — the
        # engine's equivalence tests pin this), so after the first node
        # decodes a slot, later nodes skip both the payload copy and
        # the content-keyed lookup (DKG payloads are hundreds of KB).
        slot = (era, epoch, proposer, length)
        hit = self._slot_cache.get(slot)
        if hit is not None:
            if hit is _DECODE_FAILED:
                return 0
            self.nodes[node].contrib_cache[(era, epoch, proposer)] = hit
            return 1
        # ctypes.string_at = one memcpy; pointer slicing (data[:length])
        # is per-element and cost ~12 ms on DKG-sized (~100 KB) payloads.
        payload = ctypes.string_at(data, length) if length else b""
        if payload in self._decode_cache:
            obj = self._decode_cache[payload]
            if obj is _DECODE_FAILED:
                _cache_put(self._slot_cache, slot, _DECODE_FAILED)
                return 0
        else:
            try:
                obj = serde.loads(payload, suite=self._suite)
            except serde.DecodeError:
                _cache_put(self._decode_cache, payload, _DECODE_FAILED)
                _cache_put(self._slot_cache, slot, _DECODE_FAILED)
                return 0
            _cache_put(self._decode_cache, payload, obj)
        _cache_put(self._slot_cache, slot, obj)
        self.nodes[node].contrib_cache[(era, epoch, proposer)] = obj
        return 1

    def _on_batch(self, node, era, epoch) -> None:
        nd = self.nodes[node]
        lib = self.lib
        size = lib.hbe_batch_size(self.handle)
        contribs = []
        for i in range(size):
            proposer = lib.hbe_batch_proposer(self.handle, i)
            obj = nd.contrib_cache.pop((era, epoch, proposer), None)
            contribs.append((proposer, obj))
        batch = Batch(epoch, tuple(contribs))
        dhb: NativeDhb = nd.qhb.dhb  # type: ignore[assignment]
        dhb._rng = nd.rng
        # Batch-digest fast path: hand the whole batch's DKG private
        # checks to ONE native call before the per-message processing
        # walks it (the round-5 continuation-tail lever).  Per-item
        # misses fall back inside handle_part/handle_ack; a nested
        # batch event (a proposal fired from inside _process_batch)
        # clears the outer digests early, which only costs speed.
        skg = self._predigest_dkg(dhb, batch)
        try:
            step = dhb._process_batch(batch)
        finally:
            if skg is not None:
                skg.clear_predigest()
        step = nd.qhb._absorb(step, nd.rng)
        nd.outputs.extend(o for o in step.output if isinstance(o, DhbBatch))

    @staticmethod
    def _predigest_dkg(dhb: "NativeDhb", batch: Batch) -> Any:
        """Collect the batch's in-era key-gen messages and batch their
        private checks into the node's SyncKeyGen (no-op without a DKG
        in flight).  Returns the SyncKeyGen whose digests must be
        cleared after the batch, or None."""
        state = dhb._key_gen
        if state is None or state.key_gen is None:
            return None
        skg = state.key_gen
        msgs = []
        for _, contrib in batch.contributions:
            if not isinstance(contrib, InternalContrib):
                continue
            for kg in contrib.key_gen_messages:
                if isinstance(kg, SignedKeyGenMsg) and kg.era == dhb._era:
                    msgs.append((kg.sender, kg.payload))
        if msgs:
            try:
                skg.predigest_batch(msgs)
            except Exception:
                # Digesting is an optimization only: any failure leaves
                # the per-item paths to re-derive every verdict.
                skg.clear_predigest()
        return skg

    # Engine MsgType names for the typed delivery profiling slots 0..10
    # (native/engine.cpp enum MsgType order).
    MSG_TYPE_NAMES = (
        "VALUE", "ECHO", "READY", "ECHO_HASH", "CAN_DECODE",
        "BVAL", "AUX", "CONF", "COIN", "TERM", "DECRYPT",
    )

    def prof_stats(self) -> Dict[str, Dict[str, int]]:
        """Delivery profiling counters: per-message-type cycles/counts
        (slots 0..10) plus the claimed literal slots by registry name
        (tools/lint/slot_registry.py).  Under the deferred RLC cadence
        the engine folds flush-side continuation cycles back into the
        COIN/DECRYPT typed slots, so ``cycles/count`` stays an honest
        cyc/delivery across the HBBFT_TPU_COIN_RLC A/B."""
        lib, h = self.lib, self.handle
        out: Dict[str, Dict[str, int]] = {}
        for i, name in enumerate(self.MSG_TYPE_NAMES):
            out[name] = {
                "cycles": int(lib.hbe_prof_cycles(h, i)),
                "count": int(lib.hbe_prof_count(h, i)),
            }
        for slot, name in (
            (11, "rlc_groups"),
            (12, "batch_cb"),
            (13, "epoch_advance"),
            (14, "combine_kernel"),  # round 15: the SIMD combine wall
            # Round 17: slot 15 retired its round-6 contrib_cb stamp for
            # the epoch-arena stats (cycles = max per-node high-water
            # mark in BYTES, count = watermark resets).
            (15, "arena"),
        ):
            out[name] = {
                "cycles": int(lib.hbe_prof_cycles(h, slot)),
                "count": int(lib.hbe_prof_count(h, slot)),
            }
        return out

    def arena_stats(self) -> Dict[str, int]:
        """Epoch-arena telemetry (ISSUE 17): max/sum of the per-node
        high-water marks (bytes carved per epoch), total watermark
        resets, and the recycle knob (``HBBFT_TPU_ARENA``; 0 = the
        free-every-epoch A/B arm).  Empty on pre-17 engine snapshots
        (vs-seed A/B arms)."""
        if not hasattr(self.lib, "hbe_arena_stats"):
            return {}
        buf = (ctypes.c_uint64 * 4)()
        self.lib.hbe_arena_stats(self.handle, buf)
        return {
            "hwm_max": int(buf[0]),
            "hwm_sum": int(buf[1]),
            "resets": int(buf[2]),
            "recycle": int(buf[3]),
        }

    def sha3_stats(self) -> Dict[str, int]:
        """Batched sha3-plane counters since process start (library-
        global, ISSUE 17): batch calls/messages, messages hashed by the
        8-lane IFMA arm, and single-message (``sha3_256_one``) calls."""
        return sha3_plane_stats(self.lib)

    # Engine TraceKind values (native/engine.cpp enum TraceKind) -> the
    # shared milestone taxonomy (docs/OBSERVABILITY.md).  d packs
    # (round << 1) | value for input/coin/decide records.  Parity with
    # the enum is machine-checked (tools/lint HBC005).
    TRACE_KIND_NAMES = {
        1: "epoch.open",
        2: "epoch.commit",
        3: "rbc.value",
        4: "rbc.ready",
        5: "rbc.deliver",
        6: "ba.round",
        7: "ba.coin",
        8: "ba.decide",
        9: "decrypt.start",
        10: "decrypt.done",
        11: "ba.input",
    }

    def enable_trace(self, capacity: int = 8192) -> None:
        """Enable the engine's bounded milestone event ring (0 turns it
        off).  Emitting is allocation-free; drain with
        :meth:`drain_trace` (owner thread only, like every engine
        call)."""
        self.lib.hbe_trace_enable(self.handle, capacity)

    @property
    def trace_dropped(self) -> int:
        return int(self.lib.hbe_trace_dropped(self.handle))

    def drain_trace(self) -> List[Any]:
        """Drain engine trace records into :class:`~hbbft_tpu.obs.trace.
        TraceEvent`s (ns stamps -> float wall seconds; kind/abcd -> the
        taxonomy's named args)."""
        import struct

        from hbbft_tpu.obs.trace import TraceEvent

        lib = self.lib
        pending = int(lib.hbe_trace_pending(self.handle))
        if not pending:
            return []
        buf = (ctypes.c_uint8 * (32 * pending))()
        nrec = int(lib.hbe_trace_drain(self.handle, buf, len(buf)))
        out: List[Any] = []
        raw = bytes(buf)
        for i in range(max(nrec, 0)):
            ts_ns, node, kind, a, b, c, d = struct.unpack_from(
                "<q6i", raw, 32 * i
            )
            name = self.TRACE_KIND_NAMES.get(kind)
            if name is None:  # future-proof: unknown kinds still surface
                name, args = f"engine.k{kind}", {"a": a, "b": b, "c": c, "d": d}
            else:
                args = {"node": node, "era": a, "epoch": b}
                if name.startswith(("rbc.", "decrypt.")):
                    args["proposer"] = c
                elif name == "ba.round":
                    args["proposer"] = c
                    args["round"] = d
                elif name in ("ba.coin", "ba.decide", "ba.input"):
                    args["proposer"] = c
                    args["round"] = d >> 1
                    args["value"] = d & 1
                elif name == "epoch.commit":
                    args["contribs"] = c
            out.append(TraceEvent(ts_ns / 1e9, name, args))
        return out

    # -- external-crypto mode ------------------------------------------
    #
    # The opaque-bytes crypto plane (round 3), shared by BOTH engine
    # runtimes: the simulated net (NativeQhbNet external_crypto=True)
    # and the cluster-node engine (NativeNodeEngine with an attached
    # backend — the crypto-service arm, round 13).  The callbacks run
    # inside hbe_run / hbe_flush; exceptions must not cross the ctypes
    # boundary: they are trapped, recorded, and re-raised by the
    # caller's _raise_cb_error — with verdicts left False / results
    # left empty, which the protocol tolerates structurally.

    def _init_ext_crypto(
        self, suite: Suite, backend: CryptoBackend, flush_every: int
    ) -> None:
        """Arm the engine's external (opaque-bytes) crypto mode: share
        signing, verification, combining and ciphertext parsing route
        through the Python callbacks below, and verify requests
        accumulate in the engine's per-node pools until a flush hands
        the whole batch to ``backend.verify_batch`` (``flush_every``
        mirrors VirtualNet's knob; 0 = flush only on queue-dry)."""
        self.ext = True
        self._suite = suite
        self.backend = backend
        self._node_era_info: Dict[Tuple[int, int], NetworkInfo] = {}
        self._era_netinfo: Dict[int, NetworkInfo] = {}
        self._ct_cache: Dict[bytes, Any] = {}
        self._h2g2_cache: Dict[bytes, Any] = {}
        self._elem_cache: Dict[Tuple[bool, bytes], Any] = {}
        self._verdict_memo: Dict[tuple, bool] = {}
        self._dec_g1, self._dec_g2 = _share_decoders(suite)
        self.flush_stats: Dict[str, int] = {
            "flushes": 0,          # verify-batch callback invocations
            "requests": 0,         # raw requests (incl. memo hits)
            "backend_requests": 0, # requests actually sent to the backend
            "max_batch": 0,        # largest single backend batch
        }
        # keep callback objects alive for the engine's lifetime
        self._verify_cb = _VERIFY_CB(self._on_verify)
        self._sign_cb = _SIGN_CB(self._on_sign)
        self._combine_cb = _COMBINE_CB(self._on_combine)
        self._ct_parse_cb = _CT_PARSE_CB(self._on_ct_parse)
        self.lib.hbe_set_ext_crypto(
            self.handle, flush_every, self._verify_cb, self._sign_cb,
            self._combine_cb, self._ct_parse_cb,
        )

    def _read_vreq_bytes(self, len_fn: Any, get_fn: Any, i: int) -> bytes:
        ln = int(len_fn(self.handle, i))
        if not ln:
            return b""
        buf = (ctypes.c_uint8 * ln)()
        get_fn(self.handle, i, buf)
        return bytes(buf)

    def _on_verify(self, node: int, count: int, verdicts: Any) -> None:
        try:
            lib = self.lib
            pending = []  # (slot, memo key, VerifyRequest or None)
            for i in range(count):
                kind = lib.hbe_vreq_kind(self.handle, i)
                era = lib.hbe_vreq_era(self.handle, i)
                sender = lib.hbe_vreq_sender(self.handle, i)
                share = self._read_vreq_bytes(
                    lib.hbe_vreq_share_len, lib.hbe_vreq_share, i
                )
                if kind == 0:
                    ctx = self._read_vreq_bytes(
                        lib.hbe_vreq_doc_len, lib.hbe_vreq_doc, i
                    )
                else:
                    ctx = self._read_vreq_bytes(
                        lib.hbe_vreq_ct_len, lib.hbe_vreq_ct, i
                    )
                # Verdicts are pure functions of the request content, so
                # identical requests observed by different nodes verify
                # once (the backend still sees the whole UNIQUE batch).
                key = (kind, era, sender, ctx, share)
                memo = self._verdict_memo.get(key)
                if memo is not None:
                    verdicts[i] = 1 if memo else 0
                    continue
                pending.append(
                    (i, key, self._build_request(kind, era, sender, ctx, share))
                )
            reqs = [r for (_, _, r) in pending if r is not None]
            results = self.backend.verify_batch(reqs) if reqs else []
            st = self.flush_stats
            st["flushes"] += 1
            st["requests"] += count
            st["backend_requests"] += len(reqs)
            if len(reqs) > st["max_batch"]:
                st["max_batch"] = len(reqs)
            it = iter(results)
            for i, key, req in pending:
                ok = bool(next(it)) if req is not None else False
                _cache_put(self._verdict_memo, key, ok)
                verdicts[i] = 1 if ok else 0
        except BaseException as exc:  # pragma: no cover - defensive
            if self._cb_error is None:
                self._cb_error = exc

    def _build_request(
        self, kind: int, era: int, sender: int, ctx: bytes, share: bytes
    ) -> Optional[VerifyRequest]:
        """Reconstruct a VerifyRequest from engine wire bytes.

        Share points are decoded STRUCTURALLY only (no subgroup check):
        the backend applies the wire membership policy itself
        (request_well_formed / on-device torsion checks), exactly as for
        in-process Python-net requests.  Undecodable bytes verify False.
        """
        ni = self._era_netinfo.get(era)
        if ni is None:
            return None
        try:
            if kind == 0:
                return VerifyRequest.sig_share(
                    ni.public_key_share(sender),
                    ctx,
                    SignatureShare(self._elem(share, g2=True), self._suite),
                )
            ct = self._ct_lookup(ctx)
            if not isinstance(ct, Ciphertext):
                return None
            if kind == 1:
                return VerifyRequest.dec_share(
                    ni.public_key_share(sender),
                    ct,
                    DecryptionShare(self._elem(share, g2=False), self._suite),
                )
            return VerifyRequest.ciphertext(ct)
        except Exception:
            return None

    def _elem(self, data: bytes, g2: bool) -> Any:
        """Decode (and cache) a group element; cached points also keep
        their memoized subgroup/affine state across verify+combine."""
        key = (g2, data)
        el = self._elem_cache.get(key)
        if el is None:
            el = (self._dec_g2 if g2 else self._dec_g1)(data)
            _cache_put(self._elem_cache, key, el)
        return el

    def _ct_lookup(self, payload: bytes) -> Any:
        """Ciphertext for a serde payload — cache, or re-decode after an
        eviction (the payload IS the full encoding, so entries are
        always re-derivable)."""
        obj = self._ct_cache.get(payload)
        if obj is None:
            obj = serde.try_loads(payload, suite=self._suite)
            _cache_put(
                self._ct_cache, payload,
                obj if isinstance(obj, Ciphertext) else _DECODE_FAILED,
            )
        return obj

    def _on_sign(
        self, node: int, era: int, kind: int, ctx_ptr: Any, ctx_len: int, ret: Any
    ) -> None:
        try:
            ctx = ctypes.string_at(ctx_ptr, ctx_len) if ctx_len else b""
            ni = self._node_era_info[(node, era)]
            if kind == 0:
                h = self._h2g2_cache.get(ctx)
                if h is None:
                    h = self._suite.hash_to_g2(ctx)
                    _cache_put(self._h2g2_cache, ctx, h)
                share = ni.secret_key_share.sign_hash_point(h)
            else:
                share = ni.secret_key_share.decryption_share(self._ct_lookup(ctx))
            data = share.to_bytes()
            buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
            self.lib.hbe_ret_bytes(ret, buf, len(data))
        except BaseException as exc:  # pragma: no cover - defensive
            if self._cb_error is None:
                self._cb_error = exc

    def _on_combine(
        self, node: int, era: int, kind: int, ctx_ptr: Any, ctx_len: int,
        count: int, ret: Any,
    ) -> None:
        try:
            ctx = ctypes.string_at(ctx_ptr, ctx_len) if ctx_len else b""
            lib = self.lib
            ni = self._era_netinfo[era]
            pks = ni.public_key_set
            shares: Dict[int, Any] = {}
            for i in range(count):
                idx = lib.hbe_comb_index(self.handle, i)
                data = self._read_vreq_bytes(
                    lib.hbe_comb_share_len, lib.hbe_comb_share, i
                )
                if kind == 0:
                    shares[idx] = SignatureShare(
                        self._elem(data, g2=True), self._suite
                    )
                else:
                    shares[idx] = DecryptionShare(
                        self._elem(data, g2=False), self._suite
                    )
            if kind == 0:
                out = pks.combine_signatures(shares).to_bytes()
            else:
                out = pks.combine_decryption_shares(shares, self._ct_lookup(ctx))
            buf = (ctypes.c_uint8 * len(out)).from_buffer_copy(out)
            self.lib.hbe_ret_bytes(ret, buf, len(out))
        except BaseException as exc:  # pragma: no cover - defensive
            if self._cb_error is None:
                self._cb_error = exc

    def _on_ct_parse(self, node: int, ptr: Any, length: int) -> int:
        """serde decode gate for subset-accepted payloads — the exact
        ``serde.try_loads`` + isinstance verdict of
        honey_badger._start_decrypt, memoized per distinct payload."""
        try:
            payload = ctypes.string_at(ptr, length) if length else b""
            return 1 if isinstance(self._ct_lookup(payload), Ciphertext) else 0
        except BaseException as exc:  # pragma: no cover - defensive
            if self._cb_error is None:
                self._cb_error = exc
            return 0

    def _raise_cb_error(self) -> None:
        if self._cb_error is not None:
            exc, self._cb_error = self._cb_error, None
            raise RuntimeError("engine crypto callback failed") from exc

    def faults(self, nid: int) -> List[tuple]:
        out = []
        for i in range(self.lib.hbe_fault_count(self.handle, nid)):
            out.append(
                (
                    self.lib.hbe_fault_subject(self.handle, nid, i),
                    self.lib.hbe_fault_kind(self.handle, nid, i).decode(),
                )
            )
        return out

    def close(self) -> None:
        if self.handle:
            self.lib.hbe_destroy(self.handle)
            self.handle = None

    def __del__(self) -> None:  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class NativeQhbNet(_EngineNetBase):
    """Engine-backed QueueingHoneyBadger network (NetBuilder-compatible
    key generation and rng seeding, so runs are comparable to the
    Python VirtualNet at the same seed).

    ``threads=N`` (N > 1) runs the engine's generation-parallel
    multicore scheduler (``engine_run_mt``).  Its byte-identity with
    ``threads=1`` rests on an obligation this class's own callbacks
    honor and any SUBCLASS/EXTENSION must too: **Python batch/contrib
    callbacks may only touch per-node state** (per-node rngs, per-node
    protocol instances) or pure-function caches keyed by all of their
    inputs.  Cross-node mutable state in a callback — e.g. one shared
    rng, or a node-dependent memo on a shared decoded object — would
    make outputs depend on the worker interleaving and silently diverge
    from ``threads=1`` (the C++-side argument lives at engine_run_mt in
    native/engine.cpp; the Python-side contract is stated here because
    callbacks are where users extend the net).  Scalar internal-crypto
    mode only; external crypto and adversaries are rejected."""

    def __init__(
        self,
        n: int,
        seed: int = 0,
        batch_size: int = 8,
        num_faulty: Optional[int] = None,
        session_id: bytes = b"qhb-test",
        encryption_schedule: EncryptionSchedule = EncryptionSchedule.always(),
        subset_handling: str = "incremental",
        suite: Optional[Suite] = None,
        backend: Optional[CryptoBackend] = None,
        flush_every: int = 1,
        external_crypto: Optional[bool] = None,
        adversary: Any = None,
        threads: int = 1,
        rlc: Optional[bool] = None,
        engine_words: Optional[int] = None,
    ) -> None:
        # engine_words forces a wider NodeSet build than the network
        # needs (e.g. the -DHBE_WORDS=8 era-change smoke test runs a
        # small N on the wide build to pin width-independence).
        words = engine_words if engine_words is not None else _words_for(n)
        if words < _words_for(n):
            raise ValueError(
                f"engine_words={words} cannot serve n={n} "
                f"(needs {_words_for(n)})"
            )
        lib = get_lib(words)
        if lib is None:
            raise RuntimeError("native engine unavailable (no compiler?)")
        self.lib = lib
        self.n = n
        # Multicore generation-parallel delivery (engine_run_mt): scalar
        # mode only — the external-crypto flush cadence (one verify
        # callback per flush_every deliveries) and adversary replay are
        # inherently sequential orderings.  Byte-identity with threads=1
        # is pinned by tests/test_native_engine.py.
        self.threads = int(threads)
        if self.threads > 1:
            if external_crypto or (
                external_crypto is None
                and suite is not None
                and not isinstance(suite, ScalarSuite)
            ):
                raise ValueError(
                    "threads > 1 requires the scalar-suite internal "
                    "crypto mode (external-crypto flush cadence is "
                    "sequential)"
                )
            if adversary is not None:
                raise ValueError("threads > 1 does not support adversaries")
        f = num_faulty if num_faulty is not None else (n - 1) // 3
        assert 3 * f < n
        self.f = f
        suite = suite if suite is not None else ScalarSuite()
        # External (opaque-bytes) crypto is required for any non-scalar
        # suite; for ScalarSuite it is optional (used to pin the external
        # path's equivalence cheaply).
        self.ext = (
            external_crypto
            if external_crypto is not None
            else not isinstance(suite, ScalarSuite)
        )
        if not self.ext and not isinstance(suite, ScalarSuite):
            raise ValueError("native-scalar mode requires ScalarSuite")
        # Scalar RLC deferred verification (round 7): group COIN/DECRYPT
        # share checks at flush instead of per-share mulmods at submit.
        # Default from HBBFT_TPU_COIN_RLC (on unless "0"); the kwarg
        # overrides.  flush_every now also governs the SCALAR flush
        # cadence when RLC is on (1 = the pre-round-7 per-unit flush
        # points exactly; 0 = flush on queue-dry — maximal grouping,
        # identical protocol outputs by the deferred-verification
        # invariant, pinned by tests/test_native_rlc.py).
        self.rlc = (
            bool(rlc)
            if rlc is not None
            else os.environ.get("HBBFT_TPU_COIN_RLC", "1") != "0"
        )
        self.flush_every = flush_every
        if not self.ext and flush_every != 1:
            if not self.rlc:
                raise ValueError(
                    "scalar flush_every != 1 requires the RLC deferred "
                    "path (rlc=True / HBBFT_TPU_COIN_RLC=1); the legacy "
                    "per-share path only flushes per unit"
                )
            if self.threads > 1:
                raise ValueError(
                    "threads > 1 requires flush_every=1 in scalar mode "
                    "(the deferred scalar flush cadence is a sequential "
                    "ordering, like external crypto's)"
                )
        rng = random.Random(seed)
        sks = SecretKeySet.random(f, rng, suite)
        pks = sks.public_keys()
        node_sks = {i: SecretKey.random(rng, suite) for i in range(n)}
        node_pks = {i: node_sks[i].public_key() for i in range(n)}
        val_ids = list(range(n))
        faulty = val_ids[n - f :] if f else []
        self.faulty_ids = list(faulty)
        self.correct_ids = [i for i in range(n) if i not in faulty]
        # VirtualNet.node_order (Target.all expansion + NodeOrderAdversary)
        self.node_order = sorted(self.correct_ids) + sorted(self.faulty_ids)

        self.handle = lib.hbe_create(n, f)
        assert self.handle
        if rlc is not None:
            lib.hbe_set_rlc(self.handle, 1 if self.rlc else 0)
        if not self.ext and flush_every != 1:
            lib.hbe_set_flush_every(self.handle, flush_every)
        # keep callback objects alive for the engine's lifetime
        self._batch_cb = _BATCH_CB(self._on_batch)
        self._contrib_cb = _CONTRIB_CB(self._on_contrib)
        lib.hbe_set_callbacks(self.handle, self._batch_cb, self._contrib_cb)

        self.backend: Optional[CryptoBackend] = None
        self._cb_error: Optional[BaseException] = None
        # The net-level rng continues past key generation exactly like
        # NetBuilder's, so a seeded adversary replayed against the
        # engine queue consumes the SAME stream as the VirtualNet's.
        self._net_rng = rng
        self._adversary = adversary
        self._tampering = False
        if adversary is not None:
            from hbbft_tpu.net.adversary import (
                NodeOrderAdversary,
                NullAdversary,
                RandomAdversary,
                ReorderingAdversary,
                TamperingAdversary,
            )

            # EXACT stock types only: the replay reproduces these
            # implementations' rng consumption precisely; a subclass
            # with an overridden pre_crank would silently diverge.
            if type(adversary) is TamperingAdversary:
                # Byzantine mode: faulty nodes run the real algorithm and
                # the engine offers every outgoing message to _on_tamper,
                # which consumes the SAME net-rng stream as the Python
                # TamperingAdversary._drive at the same seed.
                self._tampering = True
                self._tamper_cb = _TAMPER_CB(self._on_tamper)
                lib.hbe_set_tamper(self.handle, self._tamper_cb)
            elif type(adversary) is not NullAdversary:
                if type(adversary) not in (
                    ReorderingAdversary, RandomAdversary, NodeOrderAdversary
                ):
                    raise ValueError(
                        "engine supports the stock scheduling adversaries "
                        "(Reordering/Random/NodeOrder) and "
                        "TamperingAdversary; subclasses run on the Python "
                        "VirtualNet"
                    )
                if (
                    type(adversary) is RandomAdversary
                    and adversary.replay_p > 0
                ):
                    raise ValueError(
                        "RandomAdversary replay (replay_p > 0) consumes rng "
                        "on faulty-destined deliveries and injects messages; "
                        "run it on the Python VirtualNet"
                    )
                self._pre_crank_cb = _PRE_CRANK_CB(self._on_pre_crank)
                lib.hbe_set_pre_crank(self.handle, self._pre_crank_cb)
        if self.ext:
            self._init_ext_crypto(
                suite,
                backend if backend is not None else BatchedBackend(suite),
                flush_every,
            )

        self.nodes: Dict[int, _NativeNode] = {}
        self._suite = suite
        # Committed payload bytes are identical across all N nodes; decode
        # once per distinct payload instead of once per node.  Consumers
        # may attach ONLY pure-function memos keyed by all of their
        # inputs to the shared objects (e.g. SignedVote/_KeyGenMsg
        # `_sp_bytes`/`_sig_ok`, Ciphertext `_verify_ok`); node-local or
        # impure state on a shared decoded object would silently couple
        # nodes and is forbidden.
        self._decode_cache: Dict[bytes, Any] = {}
        self._slot_cache: Dict[tuple, Any] = {}  # (era, epoch, proposer, len)
        for i in range(n):
            netinfo = NetworkInfo(
                our_id=i,
                val_ids=val_ids,
                public_key_set=pks,
                secret_key_share=sks.secret_key_share(i),
                public_keys={j: node_pks[j] for j in val_ids},
                secret_key=node_sks[i],
            )
            node_rng = random.Random((seed << 16) ^ (i + 1))
            dhb = NativeDhb(
                self, i, netinfo,
                session_id=session_id,
                encryption_schedule=encryption_schedule,
                subset_handling=subset_handling,
            )
            qhb = QueueingHoneyBadger(
                netinfo, _NullSink(), batch_size=batch_size,
                session_id=session_id, dhb=dhb,
            )
            self.nodes[i] = _NativeNode(i, qhb, node_rng)
            if i in faulty:
                if self._tampering:
                    lib.hbe_set_tampered(self.handle, i, 1)
                else:
                    lib.hbe_set_silent(self.handle, i, 1)

    # The external-crypto callbacks (_on_verify / _on_sign / _on_combine
    # / _on_ct_parse and their helpers) live on _EngineNetBase: the
    # cluster-node engine's crypto-service arm (round 13) shares them
    # verbatim — only the ingress/egress runtime differs.

    # Engine MsgType values (native/engine.cpp enum MsgType).
    _MT_VALUE, _MT_ECHO, _MT_READY, _MT_ECHO_HASH, _MT_CAN_DECODE = range(5)
    _MT_BVAL, _MT_AUX, _MT_CONF, _MT_COIN, _MT_TERM, _MT_DECRYPT = range(5, 11)

    def _on_tamper(
        self, sender: int, mtype: int, era: int, epoch: int,
        proposer: int, rnd: int,
    ) -> None:
        """Mirror of TamperingAdversary._tamper against the engine's
        outgoing-message clone — one net-rng draw per TargetedMessage,
        the same rewrites (flipped bvals/aux/term/conf, doubled shares,
        corrupted roots/proofs), so a tampered native run consumes the
        exact rng stream of the Python net at the same seed."""
        try:
            adv = self._adversary
            rng = self._net_rng
            if rng.random() >= adv.tamper_p:
                return
            lib, h = self.lib, self.handle
            if mtype in (self._MT_BVAL, self._MT_AUX, self._MT_TERM):
                lib.hbe_tamper_set_bval(h, 0 if lib.hbe_tamper_bval(h) else 1)
            elif mtype == self._MT_CONF:
                # BoolSet mask: 1 = {False}, 2 = {True}, 3 = both.
                if lib.hbe_tamper_bval(h) == 3:
                    lib.hbe_tamper_set_bval(h, 2 if rng.getrandbits(1) else 1)
                else:
                    lib.hbe_tamper_set_bval(h, 3)
            elif mtype in (self._MT_COIN, self._MT_DECRYPT):
                # SignatureShare(s.g2 * 2) / DecryptionShare(s.g1 * 2).
                ln = int(lib.hbe_tamper_share_len(h))
                buf = (ctypes.c_uint8 * ln)()
                lib.hbe_tamper_share(h, buf)
                data = bytes(buf)
                if self.ext:
                    el = (
                        self._dec_g2 if mtype == self._MT_COIN else self._dec_g1
                    )(data)
                    out = (el * 2).to_bytes()
                else:
                    s = int.from_bytes(data, "big")
                    out = (2 * s % self._suite.scalar_modulus).to_bytes(
                        32, "big"
                    )
                ob = (ctypes.c_uint8 * len(out)).from_buffer_copy(out)
                lib.hbe_tamper_set_share(h, ob, len(out))
            elif mtype in (
                self._MT_READY, self._MT_ECHO_HASH, self._MT_CAN_DECODE
            ):
                lib.hbe_tamper_flip_root(h)
            elif mtype in (self._MT_VALUE, self._MT_ECHO):
                lib.hbe_tamper_corrupt_proof(h)
            else:  # pragma: no cover - no other engine message types
                return
            adv.tampered_count += 1
        except BaseException as exc:  # pragma: no cover - defensive
            if self._cb_error is None:
                self._cb_error = exc

    def _on_pre_crank(self, qlen: int) -> None:
        """Replay the seeded scheduling adversary against the engine
        queue — the exact per-crank rng consumption of the Python
        Adversary.pre_crank hooks, so schedules match at the same seed."""
        try:
            adv = self._adversary
            rng = self._net_rng
            lib, h = self.lib, self.handle
            from hbbft_tpu.net.adversary import (
                NodeOrderAdversary,
                RandomAdversary,
                ReorderingAdversary,
            )

            if isinstance(adv, ReorderingAdversary):
                for _ in range(min(adv.swaps_per_crank, qlen)):
                    i = rng.randrange(qlen)
                    j = rng.randrange(qlen)
                    lib.hbe_queue_swap(h, i, j)
            elif isinstance(adv, RandomAdversary):
                if qlen > 1:
                    i = rng.randrange(qlen)
                    lib.hbe_queue_swap(h, 0, i)
            elif isinstance(adv, NodeOrderAdversary):
                if qlen:
                    order = {nid: k for k, nid in enumerate(self.node_order)}
                    dests = [lib.hbe_queue_dest(h, i) for i in range(qlen)]
                    perm = sorted(range(qlen), key=lambda i: order[dests[i]])
                    self._apply_queue_perm(perm)
        except BaseException as exc:  # pragma: no cover - defensive
            if self._cb_error is None:
                self._cb_error = exc

    def _apply_queue_perm(self, perm: List[int]) -> None:
        """Reorder the engine queue to `perm` (perm[new] = old) with
        swaps (mirrors a stable in-place sort result)."""
        lib, h = self.lib, self.handle
        pos = list(range(len(perm)))  # old index -> current position
        at = list(range(len(perm)))   # position -> old index
        for new, old in enumerate(perm):
            p = pos[old]
            if p == new:
                continue
            lib.hbe_queue_swap(h, new, p)
            displaced = at[new]
            at[new], at[p] = old, displaced
            pos[old], pos[displaced] = new, p

    # -- driving --------------------------------------------------------
    def send_input(self, nid: int, input: Any) -> None:
        nd = self.nodes[nid]
        if nid in self.faulty_ids and not self._tampering:
            return  # silent (crash-faulty) nodes never act
        step = nd.qhb.handle_input(input, nd.rng)
        nd.outputs.extend(o for o in step.output if isinstance(o, DhbBatch))
        # An input-triggered flush (flush_every=1) runs crypto callbacks;
        # surface their failures here, not at the next run() call.
        self._raise_cb_error()

    def run(self, max_deliveries: int = 1 << 62) -> int:
        if self.threads > 1:
            done = int(
                self.lib.hbe_run_mt(self.handle, max_deliveries, self.threads)
            )
        else:
            done = int(self.lib.hbe_run(self.handle, max_deliveries))
        self._raise_cb_error()
        return done

    def flush(self) -> None:
        """Force a verify flush of all pending pools (external mode)."""
        self.lib.hbe_flush(self.handle)
        self._raise_cb_error()

    @property
    def pending_verifies(self) -> int:
        return int(self.lib.hbe_pending_verifies(self.handle))

    def run_until(self, pred: Callable[["NativeQhbNet"], bool],
                  chunk: int = 50_000, max_total: int = 1 << 40) -> None:
        total = 0
        while not pred(self):
            done = self.run(chunk)
            total += done
            if done == 0 and not pred(self):
                raise RuntimeError("engine idle but condition not met")
            if total > max_total:
                raise RuntimeError("delivery limit exceeded")

    @property
    def delivered(self) -> int:
        return int(self.lib.hbe_delivered(self.handle))


class NativeNodeEngine(_EngineNetBase):
    """ONE cluster node's engine: the message-boundary runtime behind
    ``LocalCluster(node_impl="native")`` (round 9).

    Where :class:`NativeQhbNet` simulates all N nodes behind one
    internal queue, this engine runs in CLUSTER mode
    (``hbe_set_local``): only ``node_id`` is initialized and driven;
    every emission toward another id is serde-encoded in C (byte-
    identical to ``serde.dumps(SqMessage.algo(...))`` — pinned by the
    ``hbe_wire_roundtrip`` tests) and epoch-gated per peer with
    SenderQueue's admit rules, and ingress frames are decoded + handled
    natively in one ctypes call per read burst
    (``hbe_node_ingest_frames``).  The per-BATCH layers are the same
    reused Python stack as everywhere else: ``QueueingHoneyBadger``
    over :class:`NativeDhb`, fed through the shared batch callbacks.

    Scalar suite only (the cluster WIRE grammar pins the scalar-suite
    32-byte share encoding — ``wenc_share_struct`` in native/
    engine.cpp), in one of two crypto configurations:

    * **internal scalar** (default, ``backend=None``) — the engine
      computes the scalar-suite checks itself; ``flush_every`` is
      pinned to 1 (the byte-identical eager cadence) so committed
      batches match the Python-node oracle exactly.
    * **external backend** (round 13, ``backend=...``) — the ext-crypto
      mode under the cluster loop: shares travel as opaque bytes,
      verification accumulates in the engine pool and flushes through
      the attached :class:`~hbbft_tpu.crypto.backend.CryptoBackend`
      (the cluster crypto-service arm routes this to the shared
      :class:`~hbbft_tpu.cryptoplane.CryptoPlaneService`).  The
      deferred cadence is accepted here (``flush_every=0`` = flush on
      queue-dry, i.e. once per ingest sweep) — identical protocol
      outputs by the standing deferred-verification invariant
      (tests/test_cryptoplane.py pins ``batches_sha`` against the
      scalar arm).

    ``threads > 1`` composes only with the internal scalar mode at
    ``flush_every=1`` — the same sequential-cadence rules as
    :class:`NativeQhbNet` (and cluster mode runs sequentially in the
    engine regardless; the option exists for rule consistency).

    Threading: NOT thread-safe.  One owner thread makes every call
    (ingest / handle_input / run / drain_egress); the transport thread
    only ever touches the inbox queue in front of it
    (transport/native_node.py).
    """

    #: SenderQueue max_future_epochs mirror (the egress send gate).
    SQ_WINDOW = 3

    #: hbe_node_stat slot names (engine ClStat order).
    STAT_NAMES = (
        "handled", "bad_payload", "ignored", "dropped_stale",
        "held", "released", "sent", "announces",
    )

    def __init__(
        self,
        node_id: int,
        netinfo: NetworkInfo,
        seed: int = 0,
        batch_size: int = 8,
        session_id: bytes = b"tcp-cluster",
        encryption_schedule: EncryptionSchedule = EncryptionSchedule.always(),
        subset_handling: str = "incremental",
        suite: Optional[Suite] = None,
        rlc: Optional[bool] = None,
        trace_capacity: int = 8192,
        backend: Optional[CryptoBackend] = None,
        flush_every: int = 1,
        threads: int = 1,
    ) -> None:
        n = len(netinfo.all_ids)
        lib = get_lib(_words_for(n))
        if lib is None:
            raise RuntimeError("native engine unavailable (no compiler?)")
        suite = suite if suite is not None else ScalarSuite()
        if not isinstance(suite, ScalarSuite):
            raise ValueError(
                "NativeNodeEngine requires ScalarSuite (the cluster wire "
                "grammar pins the scalar-suite share encoding; attach a "
                "backend= for the external-crypto service arm)"
            )
        ext = backend is not None
        self.threads = int(threads)
        # The same cadence rules as NativeQhbNet: the external flush
        # cadence and the deferred scalar cadence are sequential
        # orderings, so they reject threads > 1; and WITHOUT an ext
        # backend the node pins flush_every=1 — the byte-identical eager
        # cadence the Python-node oracle equivalence rests on.
        if self.threads > 1:
            if ext:
                raise ValueError(
                    "threads > 1 requires the scalar-suite internal "
                    "crypto mode (external-crypto flush cadence is "
                    "sequential)"
                )
            if flush_every != 1:
                raise ValueError(
                    "threads > 1 requires flush_every=1 in scalar mode "
                    "(the deferred scalar flush cadence is a sequential "
                    "ordering, like external crypto's)"
                )
        if not ext and flush_every != 1:
            raise ValueError(
                "NativeNodeEngine pins flush_every=1 in scalar mode (the "
                "Python-oracle byte-identity cadence); attach an external "
                "CryptoBackend (backend=...) for the deferred cadence"
            )
        self.lib = lib
        self.n = n
        self.f = netinfo.num_faulty
        self.ext = False
        self.node_id = node_id
        self._suite = suite
        self.flush_every = flush_every
        self._cb_error: Optional[BaseException] = None
        self._decode_cache: Dict[bytes, Any] = {}
        self._slot_cache: Dict[tuple, Any] = {}
        self.handle = lib.hbe_create(n, self.f)
        assert self.handle
        if rlc is not None:
            lib.hbe_set_rlc(self.handle, 1 if rlc else 0)
        lib.hbe_set_local(self.handle, node_id, self.SQ_WINDOW)
        if ext:
            # Must precede NativeDhb construction below: _make_hb
            # branches on self.ext (era-info registration, keyless
            # engine init) during DynamicHoneyBadger.__init__.
            self._init_ext_crypto(suite, backend, flush_every)
        # Flight recorder (round 12): default-on for cluster nodes —
        # milestone-rate emits into a preallocated ring, drained by the
        # runtime once per sweep (trace_capacity=0 disables).
        if trace_capacity:
            self.enable_trace(trace_capacity)
        # keep callback objects alive for the engine's lifetime
        self._batch_cb = _BATCH_CB(self._on_batch)
        self._contrib_cb = _CONTRIB_CB(self._on_contrib)
        lib.hbe_set_callbacks(self.handle, self._batch_cb, self._contrib_cb)
        # Same rng ritual as ClusterNode / NativeQhbNet, so a native
        # cluster at seed s proposes the exact contribution stream of
        # the Python-node cluster at seed s (the cross-arm byte-identity
        # contract, tests/test_transport_native.py).
        rng = random.Random((seed << 16) ^ (node_id + 1))
        dhb = NativeDhb(
            self, node_id, netinfo,
            session_id=session_id,
            encryption_schedule=encryption_schedule,
            subset_handling=subset_handling,
        )
        qhb = QueueingHoneyBadger(
            netinfo, _NullSink(), batch_size=batch_size,
            session_id=session_id, dhb=dhb,
        )
        self.nodes = {node_id: _NativeNode(node_id, qhb, rng)}

    # -- driving (owner thread only) -----------------------------------
    def handle_input(self, input: Any) -> None:
        """Submit one local input (txn or vote) to the QHB stack; any
        resulting proposal lands in the egress buffer."""
        nd = self.nodes[self.node_id]
        step = nd.qhb.handle_input(input, nd.rng)
        nd.outputs.extend(o for o in step.output if isinstance(o, DhbBatch))
        self._raise_cb_error()

    def ingest(self, senders: List[int], payloads: List[bytes]) -> int:
        """Decode + enqueue one batch of MSG-frame payloads in a single
        ctypes call; returns the number of consumable frames (the
        cluster.msgs_handled mirror).  Follow with :meth:`run`."""
        k = len(payloads)
        if k == 0:
            return 0
        offs = (ctypes.c_uint64 * (k + 1))()
        pos = 0
        for i, p in enumerate(payloads):
            offs[i] = pos
            pos += len(p)
        offs[k] = pos
        handled = int(
            self.lib.hbe_node_ingest_frames(
                self.handle,
                (ctypes.c_int32 * k)(*senders),
                offs, k, b"".join(payloads),
            )
        )
        self._raise_cb_error()
        return handled

    @property
    def supports_wire_batch(self) -> bool:
        """True when the loaded engine exports the round-20 MSGB wire
        fast path (pre-20 HBBFT_TPU_ENGINE_LIB snapshots do not)."""
        return hasattr(self.lib, "hbe_node_ingest_wire")

    def ingest_wire(self, senders: List[int], records: List[Tuple[int, bytes]]) -> int:
        """Decode + enqueue one transport read burst in WIRE form: record
        i is ``(nmsg, data)`` — ``nmsg == 0`` a plain MSG payload,
        ``nmsg >= 1`` a validated raw MSGB body carrying that many
        messages, walked entirely in C (no Python slicing).  Returns the
        consumable-MESSAGE count.  Follow with :meth:`run`."""
        k = len(records)
        if k == 0:
            return 0
        offs = (ctypes.c_uint64 * (k + 1))()
        nmsgs = (ctypes.c_uint32 * k)()
        pos = 0
        for i, (nm, data) in enumerate(records):
            offs[i] = pos
            nmsgs[i] = nm
            pos += len(data)
        offs[k] = pos
        handled = int(
            self.lib.hbe_node_ingest_wire(
                self.handle,
                (ctypes.c_int32 * k)(*senders),
                nmsgs, offs, k, b"".join(d for _, d in records),
            )
        )
        self._raise_cb_error()
        return handled

    def run(self, max_deliveries: int = 1 << 62) -> int:
        """Drain the local delivery queue (returns when it is empty;
        in ext mode the queue-dry flush hands pending verifications to
        the backend before returning)."""
        if self.threads > 1:
            done = int(
                self.lib.hbe_run_mt(self.handle, max_deliveries, self.threads)
            )
        else:
            done = int(self.lib.hbe_run(self.handle, max_deliveries))
        self._raise_cb_error()
        return done

    def drain_egress(self, send: Callable[[int, bytes], None]) -> int:
        """Hand every pending egress frame to ``send(dest, payload)``;
        returns the frame count.  One C call moves the whole batch."""
        lib = self.lib
        size = int(lib.hbe_node_egress_bytes(self.handle))
        if not size:
            return 0
        buf = (ctypes.c_uint8 * size)()
        nrec = int(lib.hbe_node_egress_drain(self.handle, buf, size))
        if nrec <= 0:
            return 0
        data = memoryview(buf)  # zero-copy view; payload slices copy once
        pos = 0
        for _ in range(nrec):
            dest = int.from_bytes(data[pos:pos + 4], "little")
            ln = int.from_bytes(data[pos + 4:pos + 8], "little")
            send(dest, bytes(data[pos + 8:pos + 8 + ln]))
            pos += 8 + ln
        return nrec

    def drain_egress_msgb(
        self, emit: Callable[[int, int, bytes], None], max_body: int,
    ) -> int:
        """Drain every pending egress payload as per-destination MSGB
        bodies built in C (round 20 coalescing): one
        ``emit(dest, nmsg, body)`` per group, where ``body`` is the
        framing MSGB grammar and groups split at ``max_body`` payload
        bytes.  Returns the group count.  Callers strip ``nmsg == 1``
        groups to plain MSG frames (``body[8:]``) so singletons stay
        byte-identical to the uncoalesced arm."""
        lib = self.lib
        size = int(lib.hbe_node_egress_bytes(self.handle))
        if not size:
            return 0
        # Worst case per entry is 20B overhead + payload vs the 8B the
        # sizing entry reports, so 3x + slack provably covers it.
        cap = 3 * size + 64
        buf = (ctypes.c_uint8 * cap)()
        nbytes = int(
            lib.hbe_node_egress_drain_msgb(self.handle, max_body, buf, cap)
        )
        if nbytes <= 0:
            return 0
        data = memoryview(buf)  # zero-copy view; body slices copy once
        pos = 0
        groups = 0
        while pos < nbytes:
            dest = int.from_bytes(data[pos:pos + 4], "little")
            nmsg = int.from_bytes(data[pos + 4:pos + 8], "little")
            ln = int.from_bytes(data[pos + 8:pos + 12], "little")
            emit(dest, nmsg, bytes(data[pos + 12:pos + 12 + ln]))
            pos += 12 + ln
            groups += 1
        return groups

    def stats(self) -> Dict[str, int]:
        return {
            name: int(self.lib.hbe_node_stat(self.handle, i))
            for i, name in enumerate(self.STAT_NAMES)
        }

    @property
    def outputs(self) -> List[DhbBatch]:
        return self.nodes[self.node_id].outputs
