"""VirtualNet: every node's protocol instance in one process, single-stepped.

Reference: upstream ``tests/net/mod.rs`` (``NetBuilder``, ``VirtualNet``,
``crank()``, ``CrankError``) — SURVEY.md §3.5/§4.  Because protocols are
sans-I/O state machines, "a network" is just a message queue.

TPU-first addition: each node owns a :class:`~hbbft_tpu.crypto.pool.
VerifyPool`; the net flushes pools through the configured
``CryptoBackend`` according to ``flush_every`` (1 = eager, reference-
equivalent; larger = accumulate crypto checks into TPU-sized batches).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from hbbft_tpu.crypto.backend import BatchedBackend, CryptoBackend
from hbbft_tpu.crypto.keys import SecretKey, SecretKeySet
from hbbft_tpu.crypto.pool import VerifyPool
from hbbft_tpu.crypto.suite import ScalarSuite, Suite
from hbbft_tpu.net.adversary import Adversary, NullAdversary
from hbbft_tpu.obs import trace as _trace
from hbbft_tpu.protocols.fault_log import FaultLog
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.traits import ConsensusProtocol, Step
from hbbft_tpu.utils.metrics import Metrics


class CrankError(Exception):
    """Message/crank limit exceeded before the run condition was met."""


class MessageQueue:
    """FIFO with O(1) amortized popleft plus the list-ish surface
    adversaries use (indexing, in-place sort) — a plain list's ``pop(0)``
    would make long benchmark runs quadratic in delivered messages."""

    def __init__(self) -> None:
        self._items: List[Any] = []
        self._head = 0

    def append(self, item: Any) -> None:
        self._items.append(item)

    def popleft(self) -> Any:
        item = self._items[self._head]
        self._items[self._head] = None  # drop reference
        self._head += 1
        if self._head > 64 and self._head * 2 > len(self._items):
            self._compact()
        return item

    def _compact(self) -> None:
        self._items = self._items[self._head :]
        self._head = 0

    def sort(self, key=None) -> None:
        self._compact()
        self._items.sort(key=key)

    def __len__(self) -> int:
        return len(self._items) - self._head

    def __bool__(self) -> bool:
        return len(self) > 0

    def _index(self, i: int) -> int:
        n = len(self)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError("MessageQueue index out of range")
        return self._head + i

    def __getitem__(self, i: int) -> Any:
        return self._items[self._index(i)]

    def __setitem__(self, i: int, v: Any) -> None:
        self._items[self._index(i)] = v

    def __iter__(self):
        return iter(self._items[self._head :])


@dataclass
class NetMessage:
    sender: Any
    dest: Any
    payload: Any


@dataclass
class VirtualNode:
    id: Any
    netinfo: NetworkInfo
    protocol: ConsensusProtocol
    pool: VerifyPool
    rng: random.Random
    outputs: List[Any] = field(default_factory=list)
    faults: FaultLog = field(default_factory=FaultLog)
    sent_messages: int = 0

    @property
    def terminated(self) -> bool:
        return self.protocol.terminated


class VirtualNet:
    def __init__(
        self,
        nodes: Dict[Any, VirtualNode],
        faulty_ids: Sequence[Any],
        backend: CryptoBackend,
        adversary: Adversary,
        rng: random.Random,
        flush_every: int = 1,
        max_cranks: int = 100_000,
        faulty_nodes: Optional[Dict[Any, "VirtualNode"]] = None,
    ) -> None:
        self.nodes = nodes
        self.faulty_ids = list(faulty_ids)
        # Protocol instances for adversary-controlled nodes (used by
        # tampering adversaries that run the real algorithm and rewrite
        # its outgoing messages, upstream ``tamper``); silent/crash-style
        # adversaries simply never touch them.
        self.faulty_nodes: Dict[Any, VirtualNode] = dict(faulty_nodes or {})
        self.backend = backend
        self.adversary = adversary
        self.rng = rng
        self.flush_every = max(1, flush_every)
        self.max_cranks = max_cranks
        self.queue: MessageQueue = MessageQueue()
        self.node_order = sorted(nodes) + sorted(faulty_ids)
        self.cranks = 0
        self.delivered = 0
        self._since_flush = 0
        self._dirty_pools: set = set()
        self.metrics = Metrics()
        # Flight recorder (round 16): OFF by default — simulations pay
        # one attribute read per crank.  enable_trace() gives each
        # correct node a bounded ring; handlers run with that node's
        # buffer swapped in (tracer ctx preserved per node).
        self._traces: Optional[Dict[Any, Any]] = None

    # -- introspection -------------------------------------------------
    @property
    def correct_ids(self) -> List[Any]:
        return sorted(self.nodes)

    def node(self, node_id: Any) -> VirtualNode:
        return self.nodes[node_id]

    def all_terminated(self) -> bool:
        return all(n.terminated for n in self.nodes.values())

    def outputs(self) -> Dict[Any, List[Any]]:
        return {nid: list(n.outputs) for nid, n in self.nodes.items()}

    def correct_faults(self) -> List[Any]:
        """Faults *recorded by* correct nodes *against* correct nodes."""
        correct = set(self.nodes)
        return [
            f
            for n in self.nodes.values()
            for f in n.faults
            if f.node_id in correct
        ]

    # -- membership (upstream net_dynamic_hb analog) -------------------
    def add_node(self, node_id: Any, factory: Callable[[Any, random.Random], ConsensusProtocol]) -> VirtualNode:
        """Add a node mid-run (e.g. constructed from a ``JoinPlan``).

        ``factory(sink, rng) -> protocol``.  The node starts receiving
        broadcast traffic from the next send on.
        """
        assert node_id not in self.nodes and node_id not in self.faulty_ids
        node_rng = random.Random(self.rng.getrandbits(64))
        pool = VerifyPool()
        if self._traces is not None:
            # ring first, tracer swapped in DURING construction: the
            # new protocol's own epoch.open (with whatever era its
            # JoinPlan starts at) lands bracketed, unlike the original
            # nodes whose construction pre-dated enable_trace
            from hbbft_tpu.obs.trace import TraceBuffer

            self._traces[node_id] = TraceBuffer(
                f"node{node_id}", self._trace_capacity
            )
            self._swap_tracer(node_id)
        proto = factory(pool, node_rng)
        self._swap_tracer(None)
        node = VirtualNode(
            id=node_id,
            netinfo=getattr(proto, "netinfo", None),
            protocol=proto,
            pool=pool,
            rng=node_rng,
        )
        self.nodes[node_id] = node
        self.node_order = sorted(self.nodes) + sorted(self.faulty_ids)
        return node

    # -- flight recorder (round 16) ------------------------------------
    def enable_trace(self, capacity: int = 8192) -> None:
        """Give every correct node a bounded milestone ring (the same
        per-node tracks a LocalCluster records), for the sim-net golden
        traces the critical-path analyzer is pinned against.  Call
        BEFORE driving: protocol construction pre-dated the rings, so
        each gets the (era 0, epoch 0) open re-emitted here — exactly
        ClusterNode._run's first-epoch dance."""
        from hbbft_tpu.obs.trace import TraceBuffer

        self._trace_capacity = capacity
        self._traces = {
            nid: TraceBuffer(f"node{nid}", capacity)
            for nid in sorted(self.nodes)
        }
        for buf in self._traces.values():
            buf.emit("epoch.open", era=0, epoch=0)

    def trace_events(self) -> Dict[str, List[Any]]:
        """Snapshot of the per-node rings, keyed by track name (the
        shape the obs exporters/analyzer consume); empty when tracing
        was never enabled."""
        if self._traces is None:
            return {}
        return {buf.track: buf.snapshot() for buf in self._traces.values()}

    def _swap_tracer(self, node_id: Optional[Any]) -> None:
        if self._traces is not None:
            _trace.swap(
                self._traces.get(node_id) if node_id is not None else None
            )

    # -- driving -------------------------------------------------------
    def send_input(self, node_id: Any, input: Any) -> None:
        node = self.nodes[node_id]
        self._swap_tracer(node_id)
        step = node.protocol.handle_input(input, node.rng)
        self._process_step(node, step)
        self._maybe_flush()
        self._swap_tracer(None)

    def broadcast_input(self, input_fn: Callable[[Any], Any]) -> None:
        for nid in sorted(self.nodes):
            self.send_input(nid, input_fn(nid))
        for nid in sorted(self.faulty_ids):
            for m in self.adversary.on_input_to_faulty(
                self, nid, input_fn(nid), self.rng
            ):
                self.queue.append(m)

    def inject(self, msg: NetMessage) -> None:
        self.queue.append(msg)

    def crank(self) -> bool:
        """Deliver one message.  Returns False when idle (nothing pending)."""
        self.cranks += 1
        if self.cranks > self.max_cranks:
            raise CrankError(
                f"exceeded {self.max_cranks} cranks; delivered={self.delivered}"
            )
        self.adversary.pre_crank(self, self.rng)
        if not self.queue:
            # Drain any deferred verifications so progress can resume.
            self._flush_all_pools()
            return bool(self.queue)
        msg = self.queue.popleft()
        if msg.dest in self.faulty_ids:
            for injected in self.adversary.on_message_to_faulty(self, msg, self.rng):
                self.queue.append(injected)
            return True
        node = self.nodes.get(msg.dest)
        if node is None:
            return True  # unknown destination: drop
        self._swap_tracer(msg.dest)
        step = node.protocol.handle_message(msg.sender, msg.payload, node.rng)
        self.delivered += 1
        self._process_step(node, step)
        self._maybe_flush()
        self._swap_tracer(None)
        return True

    def crank_until(
        self, pred: Callable[["VirtualNet"], bool], max_cranks: Optional[int] = None
    ) -> None:
        limit = max_cranks if max_cranks is not None else self.max_cranks
        for _ in range(limit):
            if pred(self):
                return
            made_progress = self.crank()
            if not made_progress and not self.queue:
                self._flush_all_pools()
                if not self.queue and pred(self):
                    return
                if not self.queue:
                    raise CrankError("network idle but condition not met")
        if pred(self):
            return
        raise CrankError(f"condition not met after {limit} cranks")

    def run_to_termination(self, max_cranks: Optional[int] = None) -> None:
        self.crank_until(lambda net: net.all_terminated(), max_cranks)

    # -- internals -----------------------------------------------------
    def _process_step(self, node: VirtualNode, step: Step) -> None:
        node.outputs.extend(step.output)
        node.faults.extend(step.fault_log)
        all_ids = self.node_order
        for tm in step.messages:
            node.sent_messages += 1
            for dest in tm.target.recipients(all_ids, node.id):
                self.queue.append(NetMessage(node.id, dest, tm.message))
        if node.pool:
            self._dirty_pools.add(node.id)

    def _maybe_flush(self) -> None:
        self._since_flush += 1
        if self._since_flush >= self.flush_every:
            self._flush_all_pools()

    def _flush_all_pools(self) -> None:
        """Flush nodes with pending verify requests, in sorted-id order.

        Only *dirty* nodes are visited: a node's pool can only fill
        while its own handler (or its own flush) runs, so the set of
        non-empty pools is exactly the ids recorded by _process_step —
        scanning every node per crank was the single hottest line of
        the N=64 benchmark profile."""
        self._since_flush = 0
        while self._dirty_pools:
            for nid in sorted(self._dirty_pools):
                self._dirty_pools.discard(nid)
                node = self.nodes.get(nid)
                # flush continuations emit the node's own milestones
                # (decrypt.done, epoch.commit) — swap its ring in
                self._swap_tracer(nid if node is not None else None)
                while node is not None and node.pool:
                    self.metrics.count("verify_requests", len(node.pool))
                    with self.metrics.timer("verify_flush"):
                        step = node.pool.flush(self.backend)
                    self._process_step(node, step)
        self._swap_tracer(None)  # idle-path callers don't re-swap


class NetBuilder:
    """Configures and builds a :class:`VirtualNet`.

    Reference: upstream ``NetBuilder`` (node count, faulty set, adversary,
    RNG seed, limits).  Key generation is dealer-based
    (``SecretKeySet.random``) exactly as in upstream tests.
    """

    def __init__(self, num_nodes: int, seed: int = 0) -> None:
        self.num_nodes = num_nodes
        self.seed = seed
        self._num_faulty: Optional[int] = None
        self._num_observers = 0
        self._suite: Suite = ScalarSuite()
        self._backend_factory: Callable[[Suite], CryptoBackend] = BatchedBackend
        self._adversary: Adversary = NullAdversary()
        self._protocol_factory: Optional[Callable[..., ConsensusProtocol]] = None
        self._flush_every = 1
        self._max_cranks = 100_000

    def num_faulty(self, f: int) -> "NetBuilder":
        self._num_faulty = f
        return self

    def suite(self, suite: Suite) -> "NetBuilder":
        self._suite = suite
        return self

    def backend(self, factory: Callable[[Suite], CryptoBackend]) -> "NetBuilder":
        self._backend_factory = factory
        return self

    def adversary(self, adv: Adversary) -> "NetBuilder":
        self._adversary = adv
        return self

    def flush_every(self, k: int) -> "NetBuilder":
        self._flush_every = k
        return self

    def max_cranks(self, k: int) -> "NetBuilder":
        self._max_cranks = k
        return self

    def protocol(
        self, factory: Callable[[NetworkInfo, Any, random.Random], ConsensusProtocol]
    ) -> "NetBuilder":
        """``factory(netinfo, sink, rng) -> protocol instance``."""
        self._protocol_factory = factory
        return self

    def observers(self, k: int) -> "NetBuilder":
        """The last ``k`` node ids join as observers: they hold regular
        keypairs and receive all traffic but are not validators (no
        threshold key share).  Mirrors upstream NetBuilder observer
        support; the dynamic-HB churn tests promote them via votes."""
        self._num_observers = k
        return self

    def build(self) -> VirtualNet:
        assert self._protocol_factory is not None, "protocol factory required"
        rng = random.Random(self.seed)
        n = self.num_nodes
        n_obs = self._num_observers
        n_val = n - n_obs
        f = self._num_faulty if self._num_faulty is not None else (n_val - 1) // 3
        assert 3 * f < n_val, f"need 3f < N (got N={n_val}, f={f})"
        ids = list(range(n))
        val_ids = ids[:n_val]
        faulty_ids = val_ids[n_val - f :] if f else []
        correct_ids = [i for i in ids if i not in faulty_ids]

        suite = self._suite
        sks = SecretKeySet.random(f, rng, suite)
        pks = sks.public_keys()
        node_sks = {i: SecretKey.random(rng, suite) for i in ids}
        node_pks = {i: node_sks[i].public_key() for i in ids}

        def make_node(i: Any) -> VirtualNode:
            is_val = i in val_ids
            netinfo = NetworkInfo(
                our_id=i,
                val_ids=val_ids,
                public_key_set=pks,
                secret_key_share=sks.secret_key_share(val_ids.index(i)) if is_val else None,
                public_keys={j: node_pks[j] for j in val_ids},
                secret_key=node_sks[i],
            )
            pool = VerifyPool()
            node_rng = random.Random((self.seed << 16) ^ (i + 1))
            proto = self._protocol_factory(netinfo, pool, node_rng)
            return VirtualNode(
                id=i, netinfo=netinfo, protocol=proto, pool=pool, rng=node_rng
            )

        nodes = {i: make_node(i) for i in correct_ids}
        # Faulty nodes get real instances too (their key shares exist in
        # any case — the dealer handed them out).  Whether these run is
        # the adversary's choice: crash-style ones ignore them.
        faulty_nodes = {i: make_node(i) for i in faulty_ids}

        return VirtualNet(
            nodes=nodes,
            faulty_ids=faulty_ids,
            backend=self._backend_factory(suite),
            adversary=self._adversary,
            rng=rng,
            flush_every=self._flush_every,
            max_cranks=self._max_cranks,
            faulty_nodes=faulty_nodes,
        )
