"""Adversary framework: scheduling control + Byzantine node control.

Reference: upstream ``tests/net/adversary.rs`` (``Adversary`` trait with
``pre_crank`` and ``tamper``; stock ``NullAdversary``,
``NodeOrderAdversary``, ``ReorderingAdversary``, ``RandomAdversary``).
SURVEY.md §4.

The adversary owns the faulty nodes: messages addressed to a faulty node
are handed to :meth:`Adversary.on_message_to_faulty`, which may inject
arbitrary messages "from" any faulty node in response; ``pre_crank`` may
reorder the pending queue (asynchrony is adversarial scheduling).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, List

if TYPE_CHECKING:
    from hbbft_tpu.net.virtual_net import NetMessage, VirtualNet


class Adversary:
    """Base adversary: does nothing (crash-faulty faulty nodes)."""

    def pre_crank(self, net: "VirtualNet", rng: Any) -> None:
        """Inspect/reorder ``net.queue`` before the next delivery."""

    def on_message_to_faulty(
        self, net: "VirtualNet", msg: "NetMessage", rng: Any
    ) -> List["NetMessage"]:
        """React to a message delivered to an adversary-controlled node.

        Returns messages to inject into the network (sender must be a
        faulty node id).
        """
        return []

    def on_input_to_faulty(
        self, net: "VirtualNet", node_id: Any, input: Any, rng: Any
    ) -> List["NetMessage"]:
        """React to ``broadcast_input`` offering an input to a faulty
        node.  Crash-style adversaries ignore it (return []); algorithm-
        running adversaries feed it to ``net.faulty_nodes[node_id]``."""
        return []


class NullAdversary(Adversary):
    """FIFO delivery, silent faulty nodes."""


class NodeOrderAdversary(Adversary):
    """Delivers pending messages grouped by destination node order."""

    def pre_crank(self, net: "VirtualNet", rng: Any) -> None:
        if net.queue:
            net.queue.sort(key=lambda m: net.node_order.index(m.dest))


class ReorderingAdversary(Adversary):
    """Randomly swaps pending messages (bounded reordering)."""

    def __init__(self, swaps_per_crank: int = 8) -> None:
        self.swaps_per_crank = swaps_per_crank

    def pre_crank(self, net: "VirtualNet", rng: Any) -> None:
        q = net.queue
        for _ in range(min(self.swaps_per_crank, len(q))):
            i = rng.randrange(len(q))
            j = rng.randrange(len(q))
            q[i], q[j] = q[j], q[i]

    def on_message_to_faulty(self, net, msg, rng):
        return []


class TamperingAdversary(Adversary):
    """Runs the REAL algorithm on each faulty node and rewrites its
    outgoing messages: valid types, wrong contents (flipped BVals/Aux,
    corrupted Merkle proofs and roots, wrong-but-well-formed signature
    and decryption shares).  Upstream analog: ``tamper`` in
    ``tests/net/adversary.rs``.

    This exercises the hardest Byzantine class the stock adversaries
    missed: syntactically-valid-but-wrong protocol message streams.
    Correct nodes must still agree, and their fault logs must pin the
    faulty senders.  ``tamper_p`` < 1 interleaves honest and tampered
    traffic from the same faulty node (more adversarial than pure noise,
    which degenerates to crash-faulty behavior).
    """

    def __init__(self, tamper_p: float = 0.5) -> None:
        assert 0.0 <= tamper_p <= 1.0
        self.tamper_p = tamper_p
        self.tampered_count = 0

    # -- harness hooks --------------------------------------------------
    def on_input_to_faulty(self, net, node_id, input, rng):
        node = net.faulty_nodes.get(node_id)
        if node is None:
            return []
        step = node.protocol.handle_input(input, node.rng)
        return self._drive(net, node, step, rng)

    def on_message_to_faulty(self, net, msg, rng):
        node = net.faulty_nodes.get(msg.dest)
        if node is None:
            return []
        step = node.protocol.handle_message(msg.sender, msg.payload, node.rng)
        return self._drive(net, node, step, rng)

    # -- internals ------------------------------------------------------
    def _drive(self, net, node, step, rng) -> List["NetMessage"]:
        """Expand a faulty node's Step (and its deferred-verify flushes)
        into tampered network messages."""
        from hbbft_tpu.net.virtual_net import NetMessage

        out: List[NetMessage] = []
        steps = [step]
        while node.pool:
            steps.append(node.pool.flush(net.backend))
        for s in steps:
            for tm in s.messages:
                payload = tm.message
                if rng.random() < self.tamper_p:
                    tampered = self._tamper(payload, rng)
                    if tampered is not payload:
                        self.tampered_count += 1
                    payload = tampered
                for dest in tm.target.recipients(net.node_order, node.id):
                    out.append(NetMessage(sender=node.id, dest=dest, payload=payload))
        return out

    def _tamper(self, payload: Any, rng: Any) -> Any:
        """Rewrite one protocol message: dispatch on the innermost
        protocol content, rebuilding the (frozen dataclass) envelope
        chain around it.  Unknown leaves pass through untouched."""
        import dataclasses

        from hbbft_tpu.crypto.keys import DecryptionShare, SignatureShare
        from hbbft_tpu.protocols.binary_agreement import ConfMsg, TermMsg
        from hbbft_tpu.protocols.broadcast import (
            CanDecodeMsg,
            EchoHashMsg,
            EchoMsg,
            ReadyMsg,
            ValueMsg,
        )
        from hbbft_tpu.protocols.bool_set import BoolSet
        from hbbft_tpu.protocols.sbv_broadcast import AuxMsg, BValMsg
        from hbbft_tpu.protocols.threshold_decrypt import DecryptMessage
        from hbbft_tpu.protocols.threshold_sign import SignMessage

        def flip_root(root: bytes) -> bytes:
            return bytes([root[0] ^ 1]) + root[1:]

        t = type(payload)
        if t is BValMsg:
            return BValMsg(not payload.value)
        if t is AuxMsg:
            return AuxMsg(not payload.value)
        if t is TermMsg:
            return TermMsg(not payload.value)
        if t is ConfMsg:
            flipped = BoolSet.both() if len(payload.vals) < 2 else BoolSet.single(
                bool(rng.getrandbits(1))
            )
            return ConfMsg(flipped)
        if t is SignMessage:
            s = payload.share
            return SignMessage(SignatureShare(s.g2 * 2, s.suite))
        if t is DecryptMessage:
            s = payload.share
            return DecryptMessage(DecryptionShare(s.g1 * 2, s.suite))
        if t is ReadyMsg:
            return ReadyMsg(flip_root(payload.root))
        if t is EchoHashMsg:
            return EchoHashMsg(flip_root(payload.root))
        if t is CanDecodeMsg:
            return CanDecodeMsg(flip_root(payload.root))
        if t in (ValueMsg, EchoMsg):
            proof = payload.proof
            bad_value = (
                bytes([proof.value[0] ^ 1]) + proof.value[1:]
                if proof.value
                else b"\x01"
            )
            bad = dataclasses.replace(proof, value=bad_value)
            return t(bad)
        if dataclasses.is_dataclass(payload) and not isinstance(payload, type):
            # Envelope (SubsetMessage/HbMessage/DhbMessage/AbaMessage/
            # CoinMsg/SqMessage/...): recurse into its fields.
            changes = {}
            for f in dataclasses.fields(payload):
                v = getattr(payload, f.name)
                nv = self._tamper(v, rng)
                if nv is not v:
                    changes[f.name] = nv
            if changes:
                return dataclasses.replace(payload, **changes)
        return payload


class RandomAdversary(Adversary):
    """Picks a uniformly random pending message to deliver next, and
    echoes garbage-free random replays from faulty nodes with probability
    ``replay_p`` (replay = duplicate of a previously observed message)."""

    def __init__(self, replay_p: float = 0.0) -> None:
        self.replay_p = replay_p
        self._observed: List[Any] = []

    def pre_crank(self, net: "VirtualNet", rng: Any) -> None:
        if len(net.queue) > 1:
            i = rng.randrange(len(net.queue))
            net.queue[0], net.queue[i] = net.queue[i], net.queue[0]

    def on_message_to_faulty(self, net, msg, rng):
        from hbbft_tpu.net.virtual_net import NetMessage

        self._observed.append(msg)
        out: List[NetMessage] = []
        if self.replay_p > 0 and rng.random() < self.replay_p and self._observed:
            replay = self._observed[rng.randrange(len(self._observed))]
            for dest in net.correct_ids:
                out.append(NetMessage(sender=msg.dest, dest=dest, payload=replay.payload))
        return out
