"""Adversary framework: scheduling control + Byzantine node control.

Reference: upstream ``tests/net/adversary.rs`` (``Adversary`` trait with
``pre_crank`` and ``tamper``; stock ``NullAdversary``,
``NodeOrderAdversary``, ``ReorderingAdversary``, ``RandomAdversary``).
SURVEY.md §4.

The adversary owns the faulty nodes: messages addressed to a faulty node
are handed to :meth:`Adversary.on_message_to_faulty`, which may inject
arbitrary messages "from" any faulty node in response; ``pre_crank`` may
reorder the pending queue (asynchrony is adversarial scheduling).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, List

if TYPE_CHECKING:
    from hbbft_tpu.net.virtual_net import NetMessage, VirtualNet


class Adversary:
    """Base adversary: does nothing (crash-faulty faulty nodes)."""

    def pre_crank(self, net: "VirtualNet", rng: Any) -> None:
        """Inspect/reorder ``net.queue`` before the next delivery."""

    def on_message_to_faulty(
        self, net: "VirtualNet", msg: "NetMessage", rng: Any
    ) -> List["NetMessage"]:
        """React to a message delivered to an adversary-controlled node.

        Returns messages to inject into the network (sender must be a
        faulty node id).
        """
        return []


class NullAdversary(Adversary):
    """FIFO delivery, silent faulty nodes."""


class NodeOrderAdversary(Adversary):
    """Delivers pending messages grouped by destination node order."""

    def pre_crank(self, net: "VirtualNet", rng: Any) -> None:
        if net.queue:
            net.queue.sort(key=lambda m: net.node_order.index(m.dest))


class ReorderingAdversary(Adversary):
    """Randomly swaps pending messages (bounded reordering)."""

    def __init__(self, swaps_per_crank: int = 8) -> None:
        self.swaps_per_crank = swaps_per_crank

    def pre_crank(self, net: "VirtualNet", rng: Any) -> None:
        q = net.queue
        for _ in range(min(self.swaps_per_crank, len(q))):
            i = rng.randrange(len(q))
            j = rng.randrange(len(q))
            q[i], q[j] = q[j], q[i]

    def on_message_to_faulty(self, net, msg, rng):
        return []


class RandomAdversary(Adversary):
    """Picks a uniformly random pending message to deliver next, and
    echoes garbage-free random replays from faulty nodes with probability
    ``replay_p`` (replay = duplicate of a previously observed message)."""

    def __init__(self, replay_p: float = 0.0) -> None:
        self.replay_p = replay_p
        self._observed: List[Any] = []

    def pre_crank(self, net: "VirtualNet", rng: Any) -> None:
        if len(net.queue) > 1:
            i = rng.randrange(len(net.queue))
            net.queue[0], net.queue[i] = net.queue[i], net.queue[0]

    def on_message_to_faulty(self, net, msg, rng):
        from hbbft_tpu.net.virtual_net import NetMessage

        self._observed.append(msg)
        out: List[NetMessage] = []
        if self.replay_p > 0 and rng.random() < self.replay_p and self._observed:
            replay = self._observed[rng.randrange(len(self._observed))]
            for dest in net.correct_ids:
                out.append(NetMessage(sender=msg.dest, dest=dest, payload=replay.payload))
        return out
