"""Deterministic in-process network simulation harness.

Reference: upstream ``tests/net/`` (``NetBuilder``, ``VirtualNet``,
``Adversary``, ``CrankError``) — split into the ``hbbft_testing`` crate in
later upstream revisions.  SURVEY.md §4/§2 #16.  Shipped as part of the
framework (not just the test tree) because the simulator doubles as the
benchmark driver, as upstream's ``examples/simulation.rs`` does.
"""

from hbbft_tpu.net.adversary import (  # noqa: F401
    Adversary,
    NodeOrderAdversary,
    NullAdversary,
    RandomAdversary,
    ReorderingAdversary,
    TamperingAdversary,
)
from hbbft_tpu.net.virtual_net import CrankError, NetBuilder, VirtualNet  # noqa: F401
