"""Threshold BLS keys, signatures, and hybrid encryption — suite-generic.

Reference: upstream ``threshold_crypto/src/lib.rs`` (``SecretKeySet``,
``PublicKeySet``, ``SecretKeyShare``, ``SignatureShare``, ``Ciphertext``,
``DecryptionShare``; BLS signatures with pk in G1 / sig in G2; hybrid
ElGamal-style KEM with pairing-checkable ciphertext validity).  Fork
checkout empty at survey time; see SURVEY.md §2 #14.

Scheme (conventions as in the reference):

* master secret ``s`` = f(0) of a random degree-``t`` poly f; share i =
  f(i+1); ``PublicKeySet`` = coefficient commitment in G1.
* signature share on msg m: ``sigma_i = s_i * H2(m)`` in G2; verify share:
  ``e(G1, sigma_i) == e(pk_i, H2(m))``; combine t+1 valid shares by
  Lagrange in the exponent -> unique deterministic master signature.
* encryption to master pk ``P = s*G1``: pick r, ``U = r*G1``,
  ``V = m XOR KDF(r*P)``, ``W = r*H2(U||V)``; validity check
  ``e(G1, W) == e(U, H2(U||V))``; decryption share ``w_i = s_i * U`` with
  share validity ``e(w_i, H2(U||V)) == e(pk_i, W)``; combine t+1 shares by
  Lagrange -> ``s*U = r*P`` -> KDF unmasks.
"""

from __future__ import annotations

import os
import warnings
from dataclasses import dataclass
from typing import Any, Dict, Mapping, Optional

from hbbft_tpu.crypto.poly import Commitment, Poly, lagrange_coefficients
from hbbft_tpu.crypto.suite import Suite
from hbbft_tpu.utils import canonical_bytes, kdf_stream, xor_bytes


# ---------------------------------------------------------------------------
# Regular (non-threshold) keys — used for vote signing and DKG row encryption
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PublicKey:
    g1: Any
    suite: Suite

    def to_bytes(self) -> bytes:
        return self.g1.to_bytes()

    def verify(self, msg: bytes, sig: "Signature") -> bool:
        h = self.suite.hash_to_g2(msg)
        return self.suite.pairing_eq(self.suite.g1_generator(), sig.g2, self.g1, h)

    def encrypt(self, msg: bytes, rng: Any) -> "Ciphertext":
        suite = self.suite
        r = rng.randrange(1, suite.scalar_modulus)
        fast = _scalar_kem(suite)
        if fast is not None and isinstance(msg, bytes):
            return fast.encrypt(self, msg, r)
        u = suite.g1_generator() * r
        mask = kdf_stream(canonical_bytes(b"kem", (self.g1 * r).to_bytes()), len(msg))
        v = xor_bytes(msg, mask)
        w = suite.hash_to_g2(_ciphertext_hash_input(u, v)) * r
        return Ciphertext(u, v, w, suite)


@dataclass(frozen=True)
class SecretKey:
    x: int
    suite: Suite

    @staticmethod
    def random(rng: Any, suite: Suite) -> "SecretKey":
        return SecretKey(rng.randrange(1, suite.scalar_modulus), suite)

    def public_key(self) -> PublicKey:
        return PublicKey(self.suite.g1_generator() * self.x, self.suite)

    def sign(self, msg: bytes) -> "Signature":
        return Signature(self.suite.hash_to_g2(msg) * self.x, self.suite)

    def decrypt(self, ct: "Ciphertext") -> Optional[bytes]:
        fast = _scalar_kem(self.suite)
        if fast is not None and fast.ct_ok(ct):
            return fast.decrypt(self, ct)
        if not ct.verify():
            return None
        mask = kdf_stream(canonical_bytes(b"kem", (ct.u * self.x).to_bytes()), len(ct.v))
        return xor_bytes(ct.v, mask)


@dataclass(frozen=True)
class Signature:
    g2: Any
    suite: Suite

    def to_bytes(self) -> bytes:
        return self.g2.to_bytes()

    def parity(self) -> bool:
        """A deterministic bit derived from the signature (the common coin)."""
        from hbbft_tpu.utils import sha3_256

        return bool(sha3_256(self.to_bytes())[0] & 1)


# ---------------------------------------------------------------------------
# Threshold keys
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SignatureShare:
    g2: Any
    suite: Suite

    def to_bytes(self) -> bytes:
        return self.g2.to_bytes()


@dataclass(frozen=True)
class SecretKeyShare:
    x: int
    suite: Suite

    def sign(self, msg: bytes) -> SignatureShare:
        return SignatureShare(self.suite.hash_to_g2(msg) * self.x, self.suite)

    def sign_hash_point(self, h: Any) -> SignatureShare:
        return SignatureShare(h * self.x, self.suite)

    def decryption_share(self, ct: "Ciphertext") -> "DecryptionShare":
        return DecryptionShare(ct.u * self.x, self.suite)


@dataclass(frozen=True)
class PublicKeyShare:
    g1: Any
    suite: Suite

    def to_bytes(self) -> bytes:
        return self.g1.to_bytes()

    def verify_share(self, msg: bytes, share: SignatureShare) -> bool:
        h = self.suite.hash_to_g2(msg)
        return self.suite.pairing_eq(
            self.suite.g1_generator(), share.g2, self.g1, h
        )

    def verify_decryption_share(self, ct: "Ciphertext", share: "DecryptionShare") -> bool:
        h = self.suite.hash_to_g2(_ciphertext_hash_input(ct.u, ct.v))
        return self.suite.pairing_eq(share.g1, h, self.g1, ct.w)


@dataclass(frozen=True)
class DecryptionShare:
    g1: Any
    suite: Suite

    def to_bytes(self) -> bytes:
        return self.g1.to_bytes()


def _ciphertext_hash_input(u: Any, v: bytes) -> bytes:
    return canonical_bytes(b"ct", u.to_bytes(), v)


@dataclass(frozen=True)
class Ciphertext:
    """Hybrid threshold ciphertext ``(U, V, W)``; see module docstring."""

    u: Any  # G1
    v: bytes
    w: Any  # G2
    suite: Suite

    def hash_input(self) -> bytes:
        cached = self.__dict__.get("_hash_input")
        if cached is None:
            cached = _ciphertext_hash_input(self.u, self.v)
            object.__setattr__(self, "_hash_input", cached)
        return cached

    def verify(self) -> bool:
        """Ciphertext validity: ``e(G1, W) == e(U, H2(U||V))``.

        Memoized: validity is a pure function of the frozen fields, and
        ``SecretKey.decrypt`` re-verifies per decryptor — every node
        decrypting its slot of a shared DKG ciphertext otherwise pays
        the hash + pairing again."""
        cached = self.__dict__.get("_verify_ok")
        if cached is None:
            h = self.suite.hash_to_g2(self.hash_input())
            cached = self.suite.pairing_eq(
                self.suite.g1_generator(), self.w, self.u, h
            )
            object.__setattr__(self, "_verify_ok", cached)
        return cached

    def to_bytes(self) -> bytes:
        # Memoized: DKG signature payloads serialize the same ciphertext
        # once per receiving node per message otherwise (N^2-hot at
        # churn; pure function of frozen fields, so caching is safe).
        cached = self.__dict__.get("_bytes")
        if cached is None:
            cached = canonical_bytes(
                b"ciphertext", self.u.to_bytes(), self.v, self.w.to_bytes()
            )
            object.__setattr__(self, "_bytes", cached)
        return cached


class SecretKeySet:
    """Dealer-generated master secret: a random degree-``t`` polynomial.

    Any ``t + 1`` shares can sign/decrypt; ``t`` or fewer learn nothing.
    In HoneyBadger ``t = f = num_faulty``.
    """

    def __init__(self, poly: Poly, suite: Suite) -> None:
        self.poly = poly
        self.suite = suite

    @staticmethod
    def random(threshold: int, rng: Any, suite: Suite) -> "SecretKeySet":
        return SecretKeySet(Poly.random(threshold, rng, suite.scalar_modulus), suite)

    @property
    def threshold(self) -> int:
        return self.poly.degree

    def secret_key_share(self, i: int) -> SecretKeyShare:
        return SecretKeyShare(self.poly.eval(i + 1), self.suite)

    def public_keys(self) -> "PublicKeySet":
        return PublicKeySet(self.poly.commitment(self.suite), self.suite)


class PublicKeySet:
    """Public commitment to the master poly; derives master pk and shares."""

    def __init__(self, commitment: Commitment, suite: Suite) -> None:
        self.commitment = commitment
        self.suite = suite

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, PublicKeySet):
            return NotImplemented
        return self.commitment == other.commitment and self.suite == other.suite

    def __hash__(self) -> int:
        return hash((self.commitment, self.suite))

    @property
    def threshold(self) -> int:
        return self.commitment.degree

    def public_key(self) -> PublicKey:
        return PublicKey(self.commitment.elems[0], self.suite)

    def public_key_share(self, i: int) -> PublicKeyShare:
        return PublicKeyShare(self.commitment.eval(i + 1), self.suite)

    def to_bytes(self) -> bytes:
        return self.commitment.to_bytes()

    # -- combination ---------------------------------------------------
    def combine_signatures(self, shares: Mapping[int, SignatureShare]) -> Signature:
        """Lagrange-combine ``threshold + 1`` valid shares (by index)."""
        if len(shares) < self.threshold + 1:
            raise ValueError(
                f"need {self.threshold + 1} shares, got {len(shares)}"
            )
        idxs = sorted(shares)[: self.threshold + 1]
        # Scalar-suite vectorized combine: one C Lagrange call, same
        # mod-r sum as the loop below (fast path only for well-formed
        # scalar shares; anything else keeps the pure path).
        fast = _scalar_kem(self.suite)
        if fast is not None and _native_combine_enabled():
            vals = fast.share_values(idxs, shares, "g2")
            if vals is not None:
                acc = fast.combine_at_zero(idxs, vals)
                if acc is not None:
                    return Signature(
                        fast._g_type(acc, fast._mod), self.suite
                    )
        lam = lagrange_coefficients(idxs, self.suite.scalar_modulus)
        acc = None
        for i in idxs:
            term = shares[i].g2 * lam[i]
            acc = term if acc is None else acc + term
        return Signature(acc, self.suite)

    def combine_decryption_shares(
        self, shares: Mapping[int, DecryptionShare], ct: Ciphertext
    ) -> bytes:
        """Lagrange-combine decryption shares and unmask the plaintext."""
        if len(shares) < self.threshold + 1:
            raise ValueError(
                f"need {self.threshold + 1} shares, got {len(shares)}"
            )
        idxs = sorted(shares)[: self.threshold + 1]
        # Scalar-suite vectorized combine + kdf + xor in one C call —
        # byte-identical to the pure path below (the combine itself is
        # the same mod-r Lagrange sum; the kdf framing is the shared
        # scalar-KEM code the equivalence suites pin).
        fast = _scalar_kem(self.suite)
        if fast is not None and _native_combine_enabled() and isinstance(ct.v, bytes):
            vals = fast.share_values(idxs, shares, "g1")
            if vals is not None:
                out = fast.combine_unmask(idxs, vals, ct.v)
                if out is not None:
                    return out
        lam = lagrange_coefficients(idxs, self.suite.scalar_modulus)
        acc = None
        for i in idxs:
            term = shares[i].g1 * lam[i]
            acc = term if acc is None else acc + term
        mask = kdf_stream(canonical_bytes(b"kem", acc.to_bytes()), len(ct.v))
        return xor_bytes(ct.v, mask)

    def verify_signature(self, msg: bytes, sig: Signature) -> bool:
        return self.public_key().verify(msg, sig)


# ---------------------------------------------------------------------------
# Native KEM fast path (scalar suite only)
# ---------------------------------------------------------------------------
#
# The DKG threads N^3 KEM operations through consensus at an era change
# (every node encrypts one ack value to every node for every dealer, and
# decrypts its slot of every ack) — the dominant Python cost of config 4
# churn after the engine took over the message loop (BASELINE.md round
# 3).  native/engine.cpp exposes the same KEM byte-for-byte
# (hbe_kem_encrypt/decrypt mirror PublicKey.encrypt / SecretKey.decrypt:
# canonical_bytes framing, kdf_stream, h2g2); randomness stays drawn
# from the caller's rng HERE so the rng consumption stream — and hence
# every equivalence test — is unchanged.  Equivalence is pinned by
# tests/test_crypto_scheme.py::test_native_kem_matches_python.


# Pre-rendered serde encoding of a scalar-suite Ciphertext (the exact
# bytes serde.dumps emits: STRUCT "ct" + 4-field tuple of suite-name
# string, GROUP u, BYTES v, GROUP w — wire.py _pack_ciphertext over the
# serde grammar).  serde._encode consumes the `_serde_cache` memo, so
# the N^2 ack/row ciphertexts a DKG epoch encodes into outgoing
# contributions skip the recursive encoder.  Byte-equality with the
# recursive path is pinned by tests (a wrong rendering here would be a
# silent wire divergence).
_SCALAR_NAME = b"scalar-insecure"
_CT_HEAD = (
    bytes([0x10, 2]) + b"ct" + bytes([0x06]) + (4).to_bytes(4, "big")
    + bytes([0x05]) + len(_SCALAR_NAME).to_bytes(4, "big") + _SCALAR_NAME
)
_GRP_HEAD = (
    bytes([0x11, len(_SCALAR_NAME)]) + _SCALAR_NAME + bytes([1])
    + (32).to_bytes(4, "big")
)


def scalar_ct_serde(u_be32: bytes, v: bytes, w_be32: bytes) -> bytes:
    return (
        _CT_HEAD
        + _GRP_HEAD + u_be32
        + bytes([0x04]) + len(v).to_bytes(4, "big") + v
        + _GRP_HEAD + w_be32
    )


class _ScalarKem:
    def __init__(self, lib: Any, suite: Suite) -> None:
        self._lib = lib
        self._suite = suite
        self._g_type = type(suite.g1_generator())
        self._mod = suite.scalar_modulus
        self._r_be = suite.scalar_modulus.to_bytes(32, "big")

    def ct_ok(self, ct: Any) -> bool:
        """Fast path only for structurally sound scalar ciphertexts; the
        Python path keeps its existing behavior for everything else."""
        g = self._g_type
        return (
            isinstance(ct, Ciphertext)
            and type(ct.u) is g
            and type(ct.w) is g
            and isinstance(ct.v, bytes)
            and isinstance(ct.u.value, int)
            and isinstance(ct.w.value, int)
            and ct.u.modulus == self._mod
            and ct.w.modulus == self._mod
            and ct.suite == self._suite
            and 0 <= ct.u.value < self._mod
            and 0 <= ct.w.value < self._mod
        )

    def encrypt(self, pk: "PublicKey", msg: bytes, r: int) -> "Ciphertext":
        import ctypes

        n = len(msg)
        out_u = (ctypes.c_uint8 * 32)()
        out_v = (ctypes.c_uint8 * n)()
        out_w = (ctypes.c_uint8 * 32)()
        self._lib.hbe_kem_encrypt(
            (ctypes.c_uint8 * 32).from_buffer_copy(pk.g1.value.to_bytes(32, "big")),
            (ctypes.c_uint8 * n).from_buffer_copy(msg) if n else None,
            n,
            (ctypes.c_uint8 * 32).from_buffer_copy(r.to_bytes(32, "big")),
            out_u, out_v, out_w,
        )
        g, m = self._g_type, self._mod
        u_b, v_b, w_b = bytes(out_u), bytes(out_v), bytes(out_w)
        ct = Ciphertext(
            g(int.from_bytes(u_b, "big"), m),
            v_b,
            g(int.from_bytes(w_b, "big"), m),
            self._suite,
        )
        object.__setattr__(ct, "_verify_ok", True)
        object.__setattr__(ct, "_serde_cache", scalar_ct_serde(u_b, v_b, w_b))
        return ct

    def decrypt(self, sk: "SecretKey", ct: "Ciphertext") -> Optional[bytes]:
        import ctypes

        n = len(ct.v)
        out = (ctypes.c_uint8 * n)()
        ok = self._lib.hbe_kem_decrypt(
            (ctypes.c_uint8 * 32).from_buffer_copy(ct.u.value.to_bytes(32, "big")),
            (ctypes.c_uint8 * n).from_buffer_copy(ct.v) if n else None,
            n,
            (ctypes.c_uint8 * 32).from_buffer_copy(ct.w.value.to_bytes(32, "big")),
            (ctypes.c_uint8 * 32).from_buffer_copy(sk.x.to_bytes(32, "big")),
            out,
        )
        object.__setattr__(ct, "_verify_ok", bool(ok))
        return bytes(out) if ok else None

    # -- vectorized Lagrange combines (round 6) ------------------------
    #
    # One C call for the whole Lagrange sum (hbe_scalar_interp_sum /
    # hbe_scalar_combine_unmask mirror crypto/poly.py interpolate and
    # the kem kdf framing exactly) — the per-batch threshold combines
    # are part of the era-change Python tail.  Callers validate the
    # share shapes; a None return means "fall back to the pure path".

    def _xs_ys(self, idxs: Any, values: Any) -> Optional[tuple]:
        import ctypes

        # Explicit int32 bound: ctypes c_int32 arrays silently TRUNCATE
        # oversized Python ints (no OverflowError), which would hand the
        # C Lagrange a wrong-but-positive evaluation point and return a
        # silently wrong combine instead of falling back.
        if any(
            isinstance(i, bool) or not isinstance(i, int)
            or i < 0 or i + 1 >= (1 << 31)
            for i in idxs
        ):
            return None
        xs = (ctypes.c_int32 * len(idxs))(*[i + 1 for i in idxs])
        ys = b"".join(v.to_bytes(32, "big") for v in values)
        return xs, ys

    def combine_at_zero(self, idxs: Any, values: Any) -> Optional[int]:
        """sum_i lam_i * values[i] interpolated at 0 over x_i = i + 1
        (the scalar combine_signatures kernel)."""
        import ctypes

        prep = self._xs_ys(idxs, values)
        if prep is None:
            return None
        xs, ys = prep
        counts = (ctypes.c_int32 * 1)(len(idxs))
        out = (ctypes.c_uint8 * 32)()
        ok = int(
            self._lib.hbe_scalar_interp_sum(xs, ys, counts, 1, self._r_be, out)
        )
        return int.from_bytes(bytes(out), "big") if ok else None

    def combine_unmask(self, idxs: Any, values: Any, v: bytes) -> Optional[bytes]:
        """Lagrange-combine decryption shares and unmask ``v`` in one C
        call (combine + kdf + xor; the combine_decryption_shares
        kernel)."""
        import ctypes

        prep = self._xs_ys(idxs, values)
        if prep is None:
            return None
        xs, ys = prep
        out = (ctypes.c_uint8 * len(v))()
        ok = int(
            self._lib.hbe_scalar_combine_unmask(
                xs, len(idxs), ys, self._r_be, v, len(v), out
            )
        )
        return bytes(out) if ok else None

    def share_values(self, idxs: Any, shares: Any, attr: str) -> Optional[list]:
        """The int group-element values of ``shares[i].<attr>`` for the
        chosen indices — None unless every one is a well-formed scalar
        element of this suite (the fast-path admission check)."""
        vals = []
        for i in idxs:
            if isinstance(i, bool) or not isinstance(i, int) or i < 0:
                return None
            g = getattr(shares[i], attr, None)
            if (
                type(g) is not self._g_type
                or not isinstance(getattr(g, "value", None), int)
                or getattr(g, "modulus", None) != self._mod
                or not 0 <= g.value < self._mod
            ):
                return None
            vals.append(g.value)
        return vals


def dkg_batch_enabled() -> bool:
    """THE kill switch for every round-6 batch-plane fast path — the
    sync_key_gen predigest / vectorized generate AND the scalar
    combines here — so one env var (HBBFT_TPU_DKG_BATCH=0) A/Bs the
    whole plane against the per-item round-5 behavior.  Single
    definition; sync_key_gen imports it."""
    return os.environ.get("HBBFT_TPU_DKG_BATCH", "1") != "0"


_native_combine_enabled = dkg_batch_enabled


_KEM_CACHE: Dict[Any, Optional[_ScalarKem]] = {}


def _scalar_kem(suite: Suite) -> Optional[_ScalarKem]:
    if suite.name != "scalar-insecure":
        return None
    kem = _KEM_CACHE.get(suite.name, False)
    if kem is not False:
        return kem
    try:
        from hbbft_tpu import native_engine

        lib = native_engine.get_lib()
        kem = _ScalarKem(lib, suite) if lib is not None else None
    except Exception as exc:
        # Perf path only — the pure-Python KEM is always correct — but
        # the miss is permanent for the process, so say it once.
        warnings.warn(
            f"native KEM unavailable, using the pure-Python path: {exc!r}",
            RuntimeWarning,
            stacklevel=2,
        )
        kem = None
    _KEM_CACHE[suite.name] = kem
    return kem
