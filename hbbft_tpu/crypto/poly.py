"""Polynomials over the scalar field and group-element commitments.

Reference: upstream ``threshold_crypto/src/poly.rs`` (``Poly``,
``BivarPoly``, ``Commitment``, ``BivarCommitment``) — these power both key
sharing (SecretKeySet = random degree-f poly) and the SyncKeyGen DKG.
Fork checkout empty at survey time; see SURVEY.md §2 #12/#14.

Evaluation points: share ``i`` is the evaluation at ``x = i + 1`` (0 is
reserved for the master secret), matching the reference convention.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Sequence, Tuple


def _inv(a: int, m: int) -> int:
    return pow(a, -1, m)


def lagrange_coefficients(indices: Sequence[int], modulus: int) -> Dict[int, int]:
    """Lagrange coefficients at 0 for evaluation points ``x_i = i + 1``.

    Returns ``{i: lambda_i}`` with ``sum_i lambda_i * f(i+1) = f(0)`` for
    any poly of degree < len(indices).  One modular inversion total
    (Montgomery batch-inversion trick) — the per-index ``pow(-1)`` was a
    measurable slice of epoch time in the scalar-suite benchmarks.
    """
    idx = list(indices)
    xs = {i: (i + 1) % modulus for i in idx}
    nums: Dict[int, int] = {}
    dens: List[int] = []
    for i in idx:
        num, den = 1, 1
        for j in idx:
            if j == i:
                continue
            num = num * xs[j] % modulus
            den = den * (xs[j] - xs[i]) % modulus
        nums[i] = num
        dens.append(den)
    # Batch-invert dens: prefix[k] = den_0 ... den_{k-1}.
    prefix = [1]
    for d in dens:
        prefix.append(prefix[-1] * d % modulus)
    inv_acc = _inv(prefix[-1], modulus)
    coeffs: Dict[int, int] = {}
    for k in range(len(idx) - 1, -1, -1):
        d_inv = inv_acc * prefix[k] % modulus
        inv_acc = inv_acc * dens[k] % modulus
        coeffs[idx[k]] = nums[idx[k]] * d_inv % modulus
    return coeffs


def interpolate(points: Sequence[Tuple[int, int]], modulus: int) -> int:
    """Interpolate f(0) from arbitrary ``(x, y)`` points."""
    acc = 0
    for k, (xk, yk) in enumerate(points):
        num, den = 1, 1
        for j, (xj, _) in enumerate(points):
            if j == k:
                continue
            num = num * xj % modulus
            den = den * (xj - xk) % modulus
        acc = (acc + yk * num * _inv(den, modulus)) % modulus
    return acc


@dataclass(frozen=True)
class Poly:
    """Univariate polynomial over Z_r, coefficient order low-to-high."""

    coeffs: Tuple[int, ...]
    modulus: int

    @staticmethod
    def random(degree: int, rng: Any, modulus: int) -> "Poly":
        return Poly(
            tuple(rng.randrange(modulus) for _ in range(degree + 1)), modulus
        )

    @staticmethod
    def zero(modulus: int) -> "Poly":
        return Poly((0,), modulus)

    @staticmethod
    def constant(c: int, modulus: int) -> "Poly":
        return Poly((c % modulus,), modulus)

    @property
    def degree(self) -> int:
        return len(self.coeffs) - 1

    def eval(self, x: int) -> int:
        acc = 0
        for c in reversed(self.coeffs):
            acc = (acc * x + c) % self.modulus
        return acc

    def __add__(self, other: "Poly") -> "Poly":
        n = max(len(self.coeffs), len(other.coeffs))
        a = list(self.coeffs) + [0] * (n - len(self.coeffs))
        b = list(other.coeffs) + [0] * (n - len(other.coeffs))
        return Poly(tuple((x + y) % self.modulus for x, y in zip(a, b)), self.modulus)

    def commitment(self, suite: Any) -> "Commitment":
        g = suite.g1_generator()
        return Commitment(tuple(g * c for c in self.coeffs))


@dataclass(frozen=True)
class Commitment:
    """Commitment to a poly: per-coefficient group elements ``c_k * G``."""

    elems: Tuple[Any, ...]

    @property
    def degree(self) -> int:
        return len(self.elems) - 1

    def eval(self, x: int) -> Any:
        """The committed value of f(x) in the group (Horner), memoized
        per ``x`` (row commitments are shared across nodes in the DKG
        ack checks; see BivarCommitment.row)."""
        cache = self.__dict__.get("_eval_cache")
        if cache is None:
            cache = {}
            object.__setattr__(self, "_eval_cache", cache)
        hit = cache.get(x)
        if hit is not None:
            return hit
        from hbbft_tpu.crypto.suite import ScalarG

        first = self.elems[0] if self.elems else None
        if type(first) is ScalarG:
            # Scalar-suite fast path: Horner over raw ints, ONE group
            # object out.  The generic loop allocates two ScalarG per
            # coefficient, which dominated the N^3 DKG ack checks
            # (protocol-plane benchmarks run this suite).
            m = first.modulus
            acc_i = 0
            for e in reversed(self.elems):
                acc_i = (acc_i * x + e.value) % m
            acc = type(first)(acc_i, m)
        else:
            acc = None
            for e in reversed(self.elems):
                acc = e if acc is None else acc * x + e
        cache[x] = acc
        return acc

    def __add__(self, other: "Commitment") -> "Commitment":
        assert len(self.elems) == len(other.elems)
        return Commitment(tuple(a + b for a, b in zip(self.elems, other.elems)))

    def to_bytes(self) -> bytes:
        from hbbft_tpu.utils import canonical_bytes

        return canonical_bytes(*[e.to_bytes() for e in self.elems])


@dataclass(frozen=True)
class BivarPoly:
    """Symmetric bivariate polynomial p(x, y) of degree ``t`` in each var.

    ``coeffs[i][j]`` multiplies ``x^i y^j``; symmetry ``coeffs[i][j] ==
    coeffs[j][i]`` makes ``p(a, b) == p(b, a)``, the property the DKG
    relies on (node i can compute p(i+1, j+1) from its row and hand it to
    node j as evidence about p(·, j+1)).
    """

    coeffs: Tuple[Tuple[int, ...], ...]
    modulus: int

    @staticmethod
    def random(degree: int, rng: Any, modulus: int) -> "BivarPoly":
        n = degree + 1
        m = [[0] * n for _ in range(n)]
        for i in range(n):
            for j in range(i, n):
                v = rng.randrange(modulus)
                m[i][j] = v
                m[j][i] = v
        return BivarPoly(tuple(tuple(row) for row in m), modulus)

    @property
    def degree(self) -> int:
        return len(self.coeffs) - 1

    def eval(self, x: int, y: int) -> int:
        acc = 0
        for i in reversed(range(len(self.coeffs))):
            row_val = 0
            for c in reversed(self.coeffs[i]):
                row_val = (row_val * y + c) % self.modulus
            acc = (acc * x + row_val) % self.modulus
        return acc

    def row(self, x: int) -> Poly:
        """The univariate polynomial ``y -> p(x, y)``."""
        n = len(self.coeffs)
        out = []
        for j in range(n):
            c = 0
            xp = 1
            for i in range(n):
                c = (c + self.coeffs[i][j] * xp) % self.modulus
                xp = xp * x % self.modulus
            out.append(c)
        return Poly(tuple(out), self.modulus)

    def commitment(self, suite: Any) -> "BivarCommitment":
        g = suite.g1_generator()
        return BivarCommitment(
            tuple(tuple(g * c for c in row) for row in self.coeffs)
        )


@dataclass(frozen=True)
class BivarCommitment:
    """Commitment to a symmetric bivariate poly (matrix of group elems)."""

    elems: Tuple[Tuple[Any, ...], ...]

    @property
    def degree(self) -> int:
        return len(self.elems) - 1

    def eval(self, x: int, y: int) -> Any:
        acc = None
        for i in reversed(range(len(self.elems))):
            row_val = None
            for e in reversed(self.elems[i]):
                row_val = e if row_val is None else row_val * y + e
            acc = row_val if acc is None else acc * x + row_val
        return acc

    def row(self, x: int) -> Commitment:
        """Commitment to the univariate row poly ``y -> p(x, y)``.

        Memoized per ``x`` on the object: during DKG every acker's row
        is evaluated against the same (shared, immutable) commitment by
        every node — N^3-hot at churn without the cache."""
        cache = self.__dict__.get("_row_cache")
        if cache is None:
            cache = {}
            object.__setattr__(self, "_row_cache", cache)
        hit = cache.get(x)
        if hit is not None:
            return hit
        n = len(self.elems)
        out = []
        for j in range(n):
            acc = None
            for i in reversed(range(n)):
                e = self.elems[i][j]
                acc = e if acc is None else acc * x + e
            out.append(acc)
        result = Commitment(tuple(out))
        cache[x] = result
        return result

    def to_bytes(self) -> bytes:
        from hbbft_tpu.utils import canonical_bytes

        return canonical_bytes(
            *[e.to_bytes() for row in self.elems for e in row]
        )
