"""BLSSuite: plugs BLS12-381 into the suite-generic threshold scheme."""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

from hbbft_tpu.crypto.bls import curve as C
from hbbft_tpu.crypto.bls import fields as F
from hbbft_tpu.crypto.bls import pairing as PR
from hbbft_tpu.crypto.suite import Suite


class _PointElem:
    """Group-element wrapper satisfying the suite element protocol.

    Wraps a Jacobian point; affine form (for serialization/equality) is
    computed lazily and cached.
    """

    __slots__ = ("jac", "_affine", "_bytes", "_subgroup_ok")

    ops: C.FieldOps  # set on subclasses
    tag: bytes

    def __init__(self, jac: C.Jac) -> None:
        self.jac = jac
        self._affine: Any = _UNSET
        self._bytes: Optional[bytes] = None
        # Memo: r-torsion membership, once proven.  The check costs a
        # full scalar mult; serde decode, protocol validation, and the
        # eager backend may each ask — only the first pays.
        self._subgroup_ok = False

    def __getstate__(self):
        # Drop the lazy caches: the _UNSET sentinel does not survive
        # pickling by identity (a round-trip would resurrect it as an
        # arbitrary object that affine() then hands out as coordinates).
        # _subgroup_ok is also dropped: a pickle round-trip must not
        # carry a trust assertion.
        return self.jac

    def __setstate__(self, state):
        self.jac = state
        self._affine = _UNSET
        self._bytes = None
        self._subgroup_ok = False

    # -- group ops -----------------------------------------------------
    def __add__(self, other: "_PointElem"):
        return type(self)(C.jac_add(self.ops, self.jac, other.jac))

    def __neg__(self):
        return type(self)(C.jac_neg(self.ops, self.jac))

    def __sub__(self, other: "_PointElem"):
        return self + (-other)

    def __mul__(self, scalar: int):
        return type(self)(C.jac_mul(self.ops, self.jac, scalar % F.R))

    __rmul__ = __mul__

    def is_identity(self) -> bool:
        return C.jac_is_identity(self.ops, self.jac)

    # -- representation ------------------------------------------------
    def affine(self):
        if self._affine is _UNSET:
            self._affine = C.jac_to_affine(self.ops, self.jac)
        return self._affine

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, _PointElem) or self.tag != other.tag:
            return NotImplemented
        return C.jac_eq(self.ops, self.jac, other.jac)

    def __hash__(self) -> int:
        return hash(self.to_bytes())

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.to_bytes().hex()[:16]}…)"


_UNSET = object()


class G1Elem(_PointElem):
    ops = C.FQ_OPS
    tag = b"g1"
    serde_suite_name = "bls12-381"
    serde_group = 1

    def to_bytes(self) -> bytes:
        if self._bytes is None:
            aff = self.affine()
            if aff is None:
                self._bytes = b"\x00" * 97
            else:
                self._bytes = (
                    b"\x01" + aff[0].to_bytes(48, "big") + aff[1].to_bytes(48, "big")
                )
        return self._bytes


class G2Elem(_PointElem):
    ops = C.FQ2_OPS
    tag = b"g2"
    serde_suite_name = "bls12-381"
    serde_group = 2

    def to_bytes(self) -> bytes:
        if self._bytes is None:
            aff = self.affine()
            if aff is None:
                self._bytes = b"\x00" * 193
            else:
                (x0, x1), (y0, y1) = aff
                self._bytes = (
                    b"\x01"
                    + x0.to_bytes(48, "big")
                    + x1.to_bytes(48, "big")
                    + y0.to_bytes(48, "big")
                    + y1.to_bytes(48, "big")
                )
        return self._bytes


class BLSSuite(Suite):
    """Real BLS12-381 suite (pure-Python oracle backend)."""

    name = "bls12-381"
    scalar_modulus = F.R

    def g1_generator(self) -> G1Elem:
        return G1Elem(C.G1_GEN)

    def g2_generator(self) -> G2Elem:
        return G2Elem(C.G2_GEN)

    def g1_identity(self) -> G1Elem:
        return G1Elem(C.jac_identity(C.FQ_OPS))

    def g2_identity(self) -> G2Elem:
        return G2Elem(C.jac_identity(C.FQ2_OPS))

    def is_g1(self, obj: Any, check_subgroup: bool = True) -> bool:
        """Membership: structure, on-curve, and (optionally) r-torsion.

        Byzantine peers can hand us arbitrary point objects; the subgroup
        check defeats small-subgroup confinement of the RLC batch
        verification (a torsion component could otherwise cancel with
        noticeable probability).  Cost (one scalar mult) is acceptable in
        this oracle backend; the TPU backend batches the same check.
        The on-curve test runs in Jacobian form (no inversion — the
        affine conversion's ``pow`` dominated structural validation at
        flush batch sizes).
        """
        return (
            isinstance(obj, G1Elem)
            and _coords_valid(obj.jac, fq2=False)
            and _on_curve_and_torsion(
                C.FQ_OPS, obj, C.g1_on_curve_jac, check_subgroup
            )
        )

    def is_g2(self, obj: Any, check_subgroup: bool = True) -> bool:
        return (
            isinstance(obj, G2Elem)
            and _coords_valid(obj.jac, fq2=True)
            and _on_curve_and_torsion(
                C.FQ2_OPS, obj, C.g2_on_curve_jac, check_subgroup
            )
        )

    def g1_from_bytes(self, data: bytes) -> G1Elem:
        """Decode the 97-byte affine encoding; full membership validation
        (coordinate range, on-curve, r-torsion) — decoded elements come
        from committed-but-attacker-authored bytes and go straight into
        pairing checks, so the wire policy of :meth:`is_g1` applies."""
        elem = G1Elem(_jac_from_bytes(data, fq2=False))
        if not self.is_g1(elem):
            raise ValueError("not a valid G1 element")
        return elem

    def g2_from_bytes(self, data: bytes) -> G2Elem:
        elem = G2Elem(_jac_from_bytes(data, fq2=True))
        if not self.is_g2(elem):
            raise ValueError("not a valid G2 element")
        return elem

    def hash_to_g2(self, data: bytes) -> G2Elem:
        return G2Elem(C.hash_to_g2(bytes(data)))

    def pairing_product_is_one(
        self, pairs: Sequence[Tuple[G1Elem, G2Elem]]
    ) -> bool:
        aff_pairs = [(a.affine(), b.affine()) for a, b in pairs]
        return PR.multi_pairing_is_one(aff_pairs)

    def batch_affine(self, elems: Sequence[Any]) -> None:
        """Warm the affine caches of many points with ONE field inversion
        per group (Montgomery's batch-inversion trick).

        ``to_bytes``/``affine`` otherwise cost two ``pow(·, -1, p)`` per
        point, which dominates Fiat-Shamir coefficient derivation at
        flush batch sizes (BASELINE.md round-1 measurements).  Non-point
        objects and already-cached/identity points are skipped.
        """
        for cls, ops in ((G1Elem, C.FQ_OPS), (G2Elem, C.FQ2_OPS)):
            todo = []
            for e in elems:
                if (
                    type(e) is cls
                    and e._affine is _UNSET
                    and isinstance(e.jac, tuple)
                    and len(e.jac) == 3
                ):
                    todo.append(e)
            if not todo:
                continue
            finite = []
            for e in todo:
                if ops.is_zero(e.jac[2]):
                    e._affine = None
                else:
                    finite.append(e)
            if not finite:
                continue
            # prefix[i] = z_0 · … · z_{i-1}; one inversion of the total.
            prefix = [ops.one]
            for e in finite:
                prefix.append(ops.mul(prefix[-1], e.jac[2]))
            inv_acc = ops.inv(prefix[-1])
            for e in reversed(finite):
                z_inv = ops.mul(inv_acc, prefix[len(prefix) - 2])
                prefix.pop()
                inv_acc = ops.mul(inv_acc, e.jac[2])
                zi2 = ops.sqr(z_inv)
                x, y, _ = e.jac
                e._affine = (
                    ops.mul(x, zi2),
                    ops.mul(y, ops.mul(zi2, z_inv)),
                )


def _jac_from_bytes(data: Any, fq2: bool) -> C.Jac:
    """Parse the affine wire encoding produced by ``to_bytes`` into a
    Jacobian point (z = 1).  Structural checks only — curve/subgroup
    membership is the caller's job."""
    coords = 4 if fq2 else 2
    if not isinstance(data, bytes) or len(data) != 1 + 48 * coords:
        raise ValueError("bad point encoding length")
    flag, body = data[0], data[1:]
    if flag == 0:
        if any(body):
            raise ValueError("non-canonical identity encoding")
        return C.jac_identity(C.FQ2_OPS if fq2 else C.FQ_OPS)
    if flag != 1:
        raise ValueError("bad point flag")
    vals = [int.from_bytes(body[i * 48 : (i + 1) * 48], "big") for i in range(coords)]
    if any(v >= F.P for v in vals):
        raise ValueError("coordinate out of field range")
    if fq2:
        return ((vals[0], vals[1]), (vals[2], vals[3]), C.FQ2_OPS.one)
    return (vals[0], vals[1], C.FQ_OPS.one)


def _fq_valid(v: Any) -> bool:
    return isinstance(v, int) and not isinstance(v, bool) and 0 <= v < F.P


def _fq2_valid(v: Any) -> bool:
    return (
        isinstance(v, tuple) and len(v) == 2 and _fq_valid(v[0]) and _fq_valid(v[1])
    )


def _coords_valid(jac: Any, fq2: bool) -> bool:
    if not (isinstance(jac, tuple) and len(jac) == 3):
        return False
    check = _fq2_valid if fq2 else _fq_valid
    return all(check(c) for c in jac)


def _on_curve_and_torsion(
    ops: C.FieldOps, elem: _PointElem, on_curve_jac, check_subgroup: bool
) -> bool:
    jac = elem.jac
    if C.jac_is_identity(ops, jac):
        return True
    if not on_curve_jac(jac):
        return False
    if not check_subgroup or elem._subgroup_ok:
        return True
    # Endomorphism membership tests (curve.py): ~2x (G1) / ~4x (G2)
    # fewer group ops than the definitional [r]P == O, same verdict
    # (equivalence pinned by tests/test_bls.py against in_subgroup_slow).
    ok = C.g2_in_subgroup(jac) if ops is C.FQ2_OPS else C.g1_in_subgroup(jac)
    if ok:
        elem._subgroup_ok = True
    return ok
