"""BLS12-381 curve groups: Jacobian arithmetic, generators, hash-to-G2.

G1: E/Fq: y^2 = x^3 + 4.  G2: the M-twist E'/Fq2: y^2 = x^3 + 4*(1+u).

Constants policy: only p, r, the BLS parameter x, and the standard
generator coordinates are taken as given; curve orders and the G2
cofactor are *derived* (trace t = x + 1, twist-order candidates from the
Fq2 trace, selected by an actual order check on a sample point) and
verified by :func:`selfcheck`, so a mis-remembered constant cannot survive
the test suite.
"""

from __future__ import annotations

import hashlib
import math
from functools import lru_cache
from typing import Optional, Tuple

from hbbft_tpu.crypto.bls import fields as F
from hbbft_tpu.crypto.bls.fields import BLS_X, P, R, XI

# ---------------------------------------------------------------------------
# Generic Jacobian arithmetic, parameterized by field ops
# ---------------------------------------------------------------------------


class FieldOps:
    __slots__ = ("add", "sub", "neg", "mul", "sqr", "inv", "eq", "is_zero", "zero", "one", "muls")

    def __init__(self, add, sub, neg, mul, sqr, inv, eq, is_zero, zero, one, muls):
        self.add, self.sub, self.neg = add, sub, neg
        self.mul, self.sqr, self.inv = mul, sqr, inv
        self.eq, self.is_zero = eq, is_zero
        self.zero, self.one = zero, one
        self.muls = muls


FQ_OPS = FieldOps(
    add=lambda a, b: (a + b) % P,
    sub=lambda a, b: (a - b) % P,
    neg=lambda a: -a % P,
    mul=lambda a, b: a * b % P,
    sqr=lambda a: a * a % P,
    inv=lambda a: pow(a, P - 2, P),
    eq=lambda a, b: (a - b) % P == 0,
    is_zero=lambda a: a % P == 0,
    zero=0,
    one=1,
    muls=lambda a, s: a * s % P,
)

FQ2_OPS = FieldOps(
    add=F.fq2_add,
    sub=F.fq2_sub,
    neg=F.fq2_neg,
    mul=F.fq2_mul,
    sqr=F.fq2_sqr,
    inv=F.fq2_inv,
    eq=F.fq2_eq,
    is_zero=F.fq2_is_zero,
    zero=F.FQ2_ZERO,
    one=F.FQ2_ONE,
    muls=F.fq2_muls,
)

Jac = Tuple  # (X, Y, Z) in the underlying field


def jac_identity(ops: FieldOps) -> Jac:
    return (ops.one, ops.one, ops.zero)


def jac_is_identity(ops: FieldOps, p: Jac) -> bool:
    return ops.is_zero(p[2])


def jac_double(ops: FieldOps, p: Jac) -> Jac:
    X1, Y1, Z1 = p
    if ops.is_zero(Z1) or ops.is_zero(Y1):
        return jac_identity(ops)
    A = ops.sqr(X1)
    B = ops.sqr(Y1)
    C = ops.sqr(B)
    D = ops.sub(ops.sqr(ops.add(X1, B)), ops.add(A, C))
    D = ops.add(D, D)
    E = ops.add(ops.add(A, A), A)
    Ff = ops.sqr(E)
    X3 = ops.sub(Ff, ops.add(D, D))
    eightC = ops.add(C, C)
    eightC = ops.add(eightC, eightC)
    eightC = ops.add(eightC, eightC)
    Y3 = ops.sub(ops.mul(E, ops.sub(D, X3)), eightC)
    Z3 = ops.mul(ops.add(Y1, Y1), Z1)
    return (X3, Y3, Z3)


def jac_add(ops: FieldOps, p: Jac, q: Jac) -> Jac:
    if jac_is_identity(ops, p):
        return q
    if jac_is_identity(ops, q):
        return p
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = ops.sqr(Z1)
    Z2Z2 = ops.sqr(Z2)
    U1 = ops.mul(X1, Z2Z2)
    U2 = ops.mul(X2, Z1Z1)
    S1 = ops.mul(ops.mul(Y1, Z2), Z2Z2)
    S2 = ops.mul(ops.mul(Y2, Z1), Z1Z1)
    H = ops.sub(U2, U1)
    if ops.is_zero(H):
        if ops.eq(S1, S2):
            return jac_double(ops, p)
        return jac_identity(ops)
    I = ops.sqr(ops.add(H, H))
    J = ops.mul(H, I)
    rr = ops.sub(S2, S1)
    rr = ops.add(rr, rr)
    V = ops.mul(U1, I)
    X3 = ops.sub(ops.sub(ops.sqr(rr), J), ops.add(V, V))
    S1J = ops.mul(S1, J)
    Y3 = ops.sub(ops.mul(rr, ops.sub(V, X3)), ops.add(S1J, S1J))
    Z3 = ops.mul(
        ops.sub(ops.sub(ops.sqr(ops.add(Z1, Z2)), Z1Z1), Z2Z2), H
    )
    return (X3, Y3, Z3)


def jac_neg(ops: FieldOps, p: Jac) -> Jac:
    return (p[0], ops.neg(p[1]), p[2])


def jac_mul(ops: FieldOps, p: Jac, k: int) -> Jac:
    if k < 0:
        return jac_mul(ops, jac_neg(ops, p), -k)
    acc = jac_identity(ops)
    if k == 0 or jac_is_identity(ops, p):
        return acc
    for bit in bin(k)[2:]:
        acc = jac_double(ops, acc)
        if bit == "1":
            acc = jac_add(ops, acc, p)
    return acc


def jac_to_affine(ops: FieldOps, p: Jac) -> Optional[Tuple]:
    """Affine (x, y), or None for the identity."""
    if jac_is_identity(ops, p):
        return None
    zinv = ops.inv(p[2])
    zinv2 = ops.sqr(zinv)
    return (ops.mul(p[0], zinv2), ops.mul(ops.mul(p[1], zinv2), zinv))


def jac_eq(ops: FieldOps, p: Jac, q: Jac) -> bool:
    pi, qi = jac_is_identity(ops, p), jac_is_identity(ops, q)
    if pi or qi:
        return pi and qi
    Z1Z1 = ops.sqr(p[2])
    Z2Z2 = ops.sqr(q[2])
    if not ops.eq(ops.mul(p[0], Z2Z2), ops.mul(q[0], Z1Z1)):
        return False
    return ops.eq(
        ops.mul(ops.mul(p[1], q[2]), Z2Z2), ops.mul(ops.mul(q[1], p[2]), Z1Z1)
    )


# ---------------------------------------------------------------------------
# Curve parameters and derived orders
# ---------------------------------------------------------------------------

B1 = 4
B2 = F.fq2_muls(XI, 4)  # 4 * (1 + u)

# Standard generators.
G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
    1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
    F.FQ2_ONE,
)

TRACE = BLS_X + 1  # Frobenius trace of E/Fq
N1 = P + 1 - TRACE  # |E(Fq)|
H1 = N1 // R  # G1 cofactor


def g1_on_curve(x: int, y: int) -> bool:
    return (y * y - (x * x * x + B1)) % P == 0


def g2_on_curve(x: F.Fq2E, y: F.Fq2E) -> bool:
    rhs = F.fq2_add(F.fq2_mul(F.fq2_sqr(x), x), B2)
    return F.fq2_eq(F.fq2_sqr(y), rhs)


def g1_on_curve_jac(jac: Jac) -> bool:
    """On-curve in Jacobian form: Y^2 == X^3 + b*Z^6 — no inversion.

    (Affine x = X/Z^2, y = Y/Z^3; multiply the affine equation by Z^6.)
    Identity (Z == 0) counts as on-curve.
    """
    x, y, z = jac
    if z % P == 0:
        return True
    z2 = z * z % P
    return (y * y - (x * x % P * x + B1 * pow(z2, 3, P))) % P == 0


def g2_on_curve_jac(jac: Jac) -> bool:
    x, y, z = jac
    if F.fq2_is_zero(z):
        return True
    z2 = F.fq2_sqr(z)
    z6 = F.fq2_mul(F.fq2_sqr(z2), z2)
    rhs = F.fq2_add(F.fq2_mul(F.fq2_sqr(x), x), F.fq2_mul(B2, z6))
    return F.fq2_eq(F.fq2_sqr(y), rhs)


def _isqrt_exact(n: int) -> Optional[int]:
    if n < 0:
        return None
    s = math.isqrt(n)
    return s if s * s == n else None


@lru_cache(maxsize=1)
def twist_order() -> int:
    """|E'(Fq2)| for the M-twist, derived from the trace and verified.

    t2 = t^2 - 2p is the trace over Fq2; with t2^2 - 4p^2 = -3 f2^2, the
    sextic twists have orders p^2 + 1 - (±t2 ± 3 f2)/2.  The (unique)
    candidate that is divisible by r *and* annihilates a sample twist
    point is the order of our twist.
    """
    t2 = TRACE * TRACE - 2 * P
    f2 = _isqrt_exact((4 * P * P - t2 * t2) // 3)
    assert f2 is not None, "t2^2 - 4p^2 != -3 f2^2 — wrong trace"
    sample = _twist_sample_point()
    for num in (t2 + 3 * f2, t2 - 3 * f2, -t2 + 3 * f2, -t2 - 3 * f2):
        if num % 2:
            continue
        n = P * P + 1 - num // 2
        if n % R == 0 and jac_is_identity(FQ2_OPS, jac_mul(FQ2_OPS, sample, n)):
            return n
    raise AssertionError("no twist-order candidate verified")


def _twist_sample_point() -> Jac:
    """Deterministic non-generator point on E'(Fq2) via try-and-increment."""
    x0 = 7
    while True:
        for x1 in range(4):
            x = (x0, x1)
            rhs = F.fq2_add(F.fq2_mul(F.fq2_sqr(x), x), B2)
            y = F.fq2_sqrt(rhs)
            if y is not None:
                return (x, y, F.FQ2_ONE)
        x0 += 1


@lru_cache(maxsize=1)
def h2_cofactor() -> int:
    return twist_order() // R


# ---------------------------------------------------------------------------
# Fast subgroup membership via endomorphisms
# ---------------------------------------------------------------------------
#
# Replaces the definitional [r]P == O test (255 doubles + ~127 adds per
# point) with the standard endomorphism membership tests for BLS12-381
# (Bowe, "Faster subgroup checks for BLS12-381", eprint 2019/814; Scott,
# "A note on group membership tests for G1, G2 and GT", eprint 2021/1130
# — the simplified forms below are the ones deployed in blst):
#
#   G1:  phi(P) == -[x^2]P,  phi(X, Y, Z) = (beta*X, Y, Z) the GLV
#        endomorphism, beta a cube root of unity in Fq with eigenvalue
#        -x^2 on G1 (x = BLS_X, |x| 64 bits; x^2 is a fixed 128-bit
#        scalar -> two 64-bit chains host-side, one 128-bit chain that
#        exactly matches the RLC coefficient width on device).
#   G2:  psi(Q) == [x]Q,     psi the untwist-Frobenius-twist
#        endomorphism (|x| is 64 bits -> one 64-bit chain).
#
# Constants policy (matches the module docstring): beta and the psi
# coefficients are DERIVED at import — beta as the cube root of unity
# whose eigenvalue on the generator is -x^2, the psi coefficients by
# solving psi(G2) = [x]G2 coordinate-wise — then verified as genuine
# endomorphisms with the right eigenvalue on random multiples
# (selfcheck + tests/test_bls.py).  Soundness (no point OUTSIDE the
# r-torsion passes) is the cited results'; tests additionally construct
# cofactor-order points for every small prime factor of h1/h2 and check
# they fail (the passing set is a subgroup, so killing each prime
# ell-torsion kills every mixed-order component with ell | order).
#
# The ORACLE keeps the definitional check available as
# ``in_subgroup_slow`` — equivalence on random + adversarial points is
# pinned by tests; the TPU flush kernel mirrors the endomorphism form
# (crypto/tpu/backend.py) where it halves the batched scan width.

_X_ABS = -BLS_X  # |x|, positive 64-bit


@lru_cache(maxsize=1)
def g1_beta() -> int:
    """The cube root of unity in Fq whose GLV eigenvalue on G1 is
    -x^2 (i.e. beta*x_P pairs with jac_mul(P, -x^2 mod r))."""
    g = 2
    while True:
        b = pow(g, (P - 1) // 3, P)
        if b != 1:
            break
        g += 1
    lam = (-(_X_ABS * _X_ABS)) % R
    want = jac_mul(FQ_OPS, G1_GEN, lam)
    for beta in (b, b * b % P):
        x, y, z = G1_GEN
        if jac_eq(FQ_OPS, (beta * x % P, y, z), want):
            return beta
    raise AssertionError("no cube root of unity has eigenvalue -x^2")


@lru_cache(maxsize=1)
def psi_consts() -> Tuple[F.Fq2E, F.Fq2E]:
    """(cx, cy) with psi(X, Y, Z) = (cx*conj(X), cy*conj(Y), conj(Z)).

    Derived by solving psi(G2) = [x]G2 coordinate-wise (the generator's
    coordinates are nonzero, so the solution is unique and must equal
    the canonical untwist-Frobenius-twist coefficients); verified as an
    endomorphism with eigenvalue x on random multiples by selfcheck."""
    gx, gy, _ = G2_GEN  # affine (z = 1)
    target = jac_to_affine(FQ2_OPS, jac_mul(FQ2_OPS, G2_GEN, BLS_X % R))
    assert target is not None
    cx = F.fq2_mul(target[0], F.fq2_inv(F.fq2_conj(gx)))
    cy = F.fq2_mul(target[1], F.fq2_inv(F.fq2_conj(gy)))
    return cx, cy


def g2_psi(q: Jac) -> Jac:
    """The untwist-Frobenius-twist endomorphism on E'(Fq2), Jacobian
    form: Frobenius is coordinate conjugation (q-power), the twist
    constants fold into cx/cy (affine x = X/Z^2 conjugates to
    conj(X)/conj(Z)^2, so Z' = conj(Z) keeps the coordinates valid)."""
    cx, cy = psi_consts()
    x, y, z = q
    return (
        F.fq2_mul(cx, F.fq2_conj(x)),
        F.fq2_mul(cy, F.fq2_conj(y)),
        F.fq2_conj(z),
    )


def g1_in_subgroup(jac: Jac) -> bool:
    """P on E(Fq) is in the r-torsion iff phi(P) == -[x^2]P (identity
    included).  Callers must have checked on-curve already."""
    if jac_is_identity(FQ_OPS, jac):
        return True
    x, y, z = jac
    phi = (g1_beta() * x % P, y, z)
    xxp = jac_mul(FQ_OPS, jac_mul(FQ_OPS, jac, _X_ABS), _X_ABS)
    return jac_eq(FQ_OPS, phi, jac_neg(FQ_OPS, xxp))


def g2_in_subgroup(jac: Jac) -> bool:
    """Q on E'(Fq2) is in the r-torsion iff psi(Q) == [x]Q (identity
    included; x < 0 so the comparison is against -[|x|]Q)."""
    if jac_is_identity(FQ2_OPS, jac):
        return True
    return jac_eq(
        FQ2_OPS,
        g2_psi(jac),
        jac_neg(FQ2_OPS, jac_mul(FQ2_OPS, jac, _X_ABS)),
    )


def in_subgroup_slow(ops: FieldOps, jac: Jac) -> bool:
    """The definitional r-torsion test ([r]P == O) — oracle ground truth
    for the endomorphism checks above (tests pin their equivalence)."""
    return jac_is_identity(ops, jac_mul(ops, jac, R))


# ---------------------------------------------------------------------------
# Hash to G2 (try-and-increment + cofactor clearing)
# ---------------------------------------------------------------------------


def _hash_to_fq(tag: bytes) -> int:
    # 64 bytes of SHA3 -> uniform mod p (512 >> 381 bits: negligible bias).
    h = hashlib.sha3_256(tag + b"\x00").digest() + hashlib.sha3_256(tag + b"\x01").digest()
    return int.from_bytes(h, "big") % P


@lru_cache(maxsize=4096)
def hash_to_g2(data: bytes) -> Jac:
    """Map bytes to a point of order r on E'(Fq2), dlog unknown.

    Not the IETF SWU map (no wire-format interop requirement in a closed
    system — the reference's own ``hash_g2`` is a ChaCha-seeded random
    point, equally non-standard); try-and-increment is uniform over the
    curve and simple to audit.  Cofactor-cleared into the r-torsion.
    """
    ctr = 0
    while True:
        tag = b"h2g2" + len(data).to_bytes(8, "big") + data + ctr.to_bytes(4, "big")
        x = (_hash_to_fq(tag + b"c0"), _hash_to_fq(tag + b"c1"))
        rhs = F.fq2_add(F.fq2_mul(F.fq2_sqr(x), x), B2)
        y = F.fq2_sqrt(rhs)
        if y is None:
            ctr += 1
            continue
        # Deterministic sign choice from the hash, independent of which
        # root Tonelli-Shanks returned.
        want_odd = bool(_hash_to_fq(tag + b"sign") & 1)
        if bool(y[0] & 1) != want_odd:
            y = F.fq2_neg(y)
        point = jac_mul(FQ2_OPS, (x, y, F.FQ2_ONE), h2_cofactor())
        if jac_is_identity(FQ2_OPS, point):
            ctr += 1
            continue
        return point


# ---------------------------------------------------------------------------
# Self-check (exercised by the test suite)
# ---------------------------------------------------------------------------


def selfcheck() -> None:
    assert g1_on_curve(G1_GEN[0], G1_GEN[1]), "G1 generator not on curve"
    assert g2_on_curve(G2_GEN[0], G2_GEN[1]), "G2 generator not on twist"
    assert N1 % R == 0, "r does not divide |E(Fq)|"
    assert jac_is_identity(FQ_OPS, jac_mul(FQ_OPS, G1_GEN, R)), "G1 gen not r-torsion"
    assert jac_is_identity(FQ2_OPS, jac_mul(FQ2_OPS, G2_GEN, R)), "G2 gen not r-torsion"
    assert twist_order() % R == 0
    p = hash_to_g2(b"selfcheck")
    assert jac_is_identity(FQ2_OPS, jac_mul(FQ2_OPS, p, R)), "hashed point not r-torsion"
