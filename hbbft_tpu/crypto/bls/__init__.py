"""Pure-Python BLS12-381: the trusted CPU oracle suite.

Reference: the ``pairing``/``bls12_381`` crates under upstream
``threshold_crypto`` (SURVEY.md §2 #14).  This implementation is the
correctness oracle for the TPU path: slow, simple, and self-validating
(curve membership, subgroup orders, and twist cofactors are checked or
derived numerically at import — see :mod:`hbbft_tpu.crypto.bls.curve`).

Tower: Fq2 = Fq[u]/(u^2 + 1); Fq12 = Fq2[w]/(w^6 - xi), xi = 1 + u.
G1 on E: y^2 = x^3 + 4 over Fq; G2 on the M-twist E': y^2 = x^3 + 4*xi
over Fq2.  Pairing: optimal ate (Miller loop over |x|, x the BLS
parameter, with a final conjugation because x < 0), generic final
exponentiation (easy part via Frobenius, hard part by integer exponent
(p^4 - p^2 + 1) / r).
"""

from hbbft_tpu.crypto.bls.suite import BLSSuite  # noqa: F401
