"""Optimal ate pairing on BLS12-381 (oracle: affine Miller loop).

The Miller loop runs over the twist E'(Fq2); line functions are evaluated
at P in G1 and *untwisted* into sparse Fq12 elements.  With the untwist
(x, y) -> (x/w^2, y/w^3) the chord/tangent line through twist points,
scaled by the harmless factor w^3 (w^3 lies in Fq4, which the final
exponentiation kills), is

    l(P) = (lam * x_T - y_T)  +  (-lam * x_P) w^2  +  (y_P) w^3

with lam the Fq2 chord/tangent slope.  Affine steps cost one cheap Fq2
inversion each — fine for an oracle; the TPU path uses its own
projective formulation.

Final exponentiation: easy part by Frobenius/conjugate/inverse; hard part
by plain square-and-multiply with the integer (p^4 - p^2 + 1) / r.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from hbbft_tpu.crypto.bls import fields as F
from hbbft_tpu.crypto.bls.fields import BLS_X, P, R

X_ABS = -BLS_X  # the Miller-loop scalar (x is negative for BLS12-381)
_X_BITS = bin(X_ABS)[3:]  # bits below the MSB

HARD_EXP = (P**4 - P**2 + 1) // R
assert (P**4 - P**2 + 1) % R == 0, "BLS cyclotomic-polynomial identity broken"


def _line(
    lam: F.Fq2E, px: int, py: int, tx: F.Fq2E, ty: F.Fq2E
) -> F.Fq12E:
    """The (w^3-scaled, untwisted) line l(P) described in the module doc."""
    c0 = F.fq2_sub(F.fq2_mul(lam, tx), ty)
    c2 = F.fq2_neg(F.fq2_muls(lam, px))
    c3 = (py, 0)
    return (c0, F.FQ2_ZERO, c2, c3, F.FQ2_ZERO, F.FQ2_ZERO)


def miller_loop(p_aff: Tuple[int, int], q_aff: Tuple[F.Fq2E, F.Fq2E]) -> F.Fq12E:
    """Miller loop f_{|x|, Q}(P) with the x<0 conjugation applied."""
    px, py = p_aff
    qx, qy = q_aff
    tx, ty = qx, qy
    f = F.FQ12_ONE
    for bit in _X_BITS:
        # Tangent at T.
        lam = F.fq2_mul(
            F.fq2_muls(F.fq2_sqr(tx), 3), F.fq2_inv(F.fq2_add(ty, ty))
        )
        f = F.fq12_mul(F.fq12_sqr(f), _line(lam, px, py, tx, ty))
        x3 = F.fq2_sub(F.fq2_sqr(lam), F.fq2_add(tx, tx))
        ty = F.fq2_sub(F.fq2_mul(lam, F.fq2_sub(tx, x3)), ty)
        tx = x3
        if bit == "1":
            # Chord through T and Q (T != ±Q throughout the ate loop).
            lam = F.fq2_mul(F.fq2_sub(qy, ty), F.fq2_inv(F.fq2_sub(qx, tx)))
            f = F.fq12_mul(f, _line(lam, px, py, qx, qy))
            x3 = F.fq2_sub(F.fq2_sub(F.fq2_sqr(lam), tx), qx)
            ty = F.fq2_sub(F.fq2_mul(lam, F.fq2_sub(tx, x3)), ty)
            tx = x3
    # x < 0: f_{x,Q} = conjugate(f_{|x|,Q})
    return F.fq12_conjugate(f)


def final_exponentiation(f: F.Fq12E) -> F.Fq12E:
    """f^((p^12 - 1) / r)."""
    # Easy part: f^((p^6 - 1)(p^2 + 1)).
    f1 = F.fq12_mul(F.fq12_conjugate(f), F.fq12_inv(f))
    f2 = F.fq12_mul(F.fq12_frobenius(f1, 2), f1)
    # Hard part: ^(p^4 - p^2 + 1)/r.
    return F.fq12_pow(f2, HARD_EXP)


def pairing(p_aff: Tuple[int, int], q_aff: Tuple[F.Fq2E, F.Fq2E]) -> F.Fq12E:
    """e(P, Q) for affine P in G1(Fq), Q on the twist E'(Fq2)."""
    return final_exponentiation(miller_loop(p_aff, q_aff))


def multi_pairing_is_one(
    pairs: Sequence[Tuple[Optional[Tuple[int, int]], Optional[Tuple[F.Fq2E, F.Fq2E]]]]
) -> bool:
    """prod_i e(P_i, Q_i) == 1, sharing one final exponentiation.

    ``None`` for either component means the group identity (the pair
    contributes the factor 1 and is skipped).
    """
    acc = F.FQ12_ONE
    nontrivial = False
    for p_aff, q_aff in pairs:
        if p_aff is None or q_aff is None:
            continue
        acc = F.fq12_mul(acc, miller_loop(p_aff, q_aff))
        nontrivial = True
    if not nontrivial:
        return True
    return F.fq12_is_one(final_exponentiation(acc))
