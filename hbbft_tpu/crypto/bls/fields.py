"""BLS12-381 field tower: Fq, Fq2, Fq12 (direct degree-6 over Fq2).

Representation choices (oracle = simplicity over speed):

* Fq: plain Python ints mod P (functions, not a class — hot enough that
  object overhead matters even host-side).
* Fq2: ``(c0, c1)`` int tuples, ``c0 + c1*u``, ``u^2 = -1``.
* Fq12: 6-tuple of Fq2 coefficients in ``w``, ``w^6 = xi = 1 + u``.
  Frobenius maps are generic: coefficient-wise Fq2 Frobenius times the
  import-time constants ``gamma[k][i] = xi^(i*(p^k - 1)/6)``.
"""

from __future__ import annotations

from typing import List, Tuple

# The base-field modulus of BLS12-381 (381 bits).
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# The group order (scalar field, 255 bits).
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# The BLS parameter x (negative): p, r, t are polynomials in x.
BLS_X = -0xD201000000010000

Fq2E = Tuple[int, int]
Fq12E = Tuple[Fq2E, Fq2E, Fq2E, Fq2E, Fq2E, Fq2E]

# ---------------------------------------------------------------------------
# Fq2
# ---------------------------------------------------------------------------

FQ2_ZERO: Fq2E = (0, 0)
FQ2_ONE: Fq2E = (1, 0)
XI: Fq2E = (1, 1)  # the sextic-twist non-residue 1 + u


def fq2_add(a: Fq2E, b: Fq2E) -> Fq2E:
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fq2_sub(a: Fq2E, b: Fq2E) -> Fq2E:
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fq2_neg(a: Fq2E) -> Fq2E:
    return (-a[0] % P, -a[1] % P)


def fq2_mul(a: Fq2E, b: Fq2E) -> Fq2E:
    # (a0 + a1 u)(b0 + b1 u) = a0b0 - a1b1 + (a0b1 + a1b0) u
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def fq2_sqr(a: Fq2E) -> Fq2E:
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    t = a[0] * a[1]
    return ((a[0] + a[1]) * (a[0] - a[1]) % P, (t + t) % P)


def fq2_muls(a: Fq2E, s: int) -> Fq2E:
    return (a[0] * s % P, a[1] * s % P)


def fq2_conj(a: Fq2E) -> Fq2E:
    """The p-power Frobenius on Fq2 (conjugation)."""
    return (a[0], -a[1] % P)


def fq2_inv(a: Fq2E) -> Fq2E:
    # (a0 + a1 u)^-1 = (a0 - a1 u) / (a0^2 + a1^2)
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    inv = pow(norm, P - 2, P)
    return (a[0] * inv % P, -a[1] * inv % P)


def fq2_eq(a: Fq2E, b: Fq2E) -> bool:
    return a[0] % P == b[0] % P and a[1] % P == b[1] % P


def fq2_is_zero(a: Fq2E) -> bool:
    return a[0] % P == 0 and a[1] % P == 0


def fq2_pow(a: Fq2E, e: int) -> Fq2E:
    result = FQ2_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fq2_mul(result, base)
        base = fq2_sqr(base)
        e >>= 1
    return result


def fq2_legendre_is_square(a: Fq2E) -> bool:
    """Euler criterion in the field of q = p^2 elements."""
    if fq2_is_zero(a):
        return True
    return fq2_eq(fq2_pow(a, (P * P - 1) // 2), FQ2_ONE)


def _find_fq2_nonresidue() -> Fq2E:
    cand = (1, 1)
    while fq2_legendre_is_square(cand):
        cand = ((cand[0] + 1) % P, cand[1])
    return cand


_TS_Q = P * P - 1
_TS_S = (_TS_Q & -_TS_Q).bit_length() - 1  # 2-adic valuation of p^2 - 1
_TS_Q >>= _TS_S
_TS_Z: Fq2E | None = None  # lazily found non-residue


def fq2_sqrt(a: Fq2E) -> Fq2E | None:
    """Tonelli-Shanks in Fq2; returns None for non-squares."""
    global _TS_Z
    if fq2_is_zero(a):
        return FQ2_ZERO
    if not fq2_legendre_is_square(a):
        return None
    if _TS_Z is None:
        _TS_Z = _find_fq2_nonresidue()
    m = _TS_S
    c = fq2_pow(_TS_Z, _TS_Q)
    t = fq2_pow(a, _TS_Q)
    r = fq2_pow(a, (_TS_Q + 1) // 2)
    while not fq2_eq(t, FQ2_ONE):
        # find least i with t^(2^i) == 1
        i = 0
        t2 = t
        while not fq2_eq(t2, FQ2_ONE):
            t2 = fq2_sqr(t2)
            i += 1
        b = c
        for _ in range(m - i - 1):
            b = fq2_sqr(b)
        m = i
        c = fq2_sqr(b)
        t = fq2_mul(t, c)
        r = fq2_mul(r, b)
    assert fq2_eq(fq2_sqr(r), a)
    return r


# ---------------------------------------------------------------------------
# Fq12 = Fq2[w] / (w^6 - xi)
# ---------------------------------------------------------------------------

FQ12_ONE: Fq12E = (FQ2_ONE, FQ2_ZERO, FQ2_ZERO, FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ12_ZERO: Fq12E = (FQ2_ZERO,) * 6


def fq12_from_fq2(c: Fq2E, power: int = 0) -> Fq12E:
    out: List[Fq2E] = [FQ2_ZERO] * 6
    out[power] = c
    return tuple(out)  # type: ignore[return-value]


def fq12_add(a: Fq12E, b: Fq12E) -> Fq12E:
    return tuple(fq2_add(x, y) for x, y in zip(a, b))  # type: ignore[return-value]


def fq12_mul(a: Fq12E, b: Fq12E) -> Fq12E:
    acc: List[Fq2E] = [FQ2_ZERO] * 11
    for i in range(6):
        ai = a[i]
        if ai == FQ2_ZERO:
            continue
        for j in range(6):
            bj = b[j]
            if bj == FQ2_ZERO:
                continue
            acc[i + j] = fq2_add(acc[i + j], fq2_mul(ai, bj))
    # reduce w^(6+k) = xi * w^k
    for k in range(10, 5, -1):
        acc[k - 6] = fq2_add(acc[k - 6], fq2_mul(acc[k], XI))
    return tuple(acc[:6])  # type: ignore[return-value]


def fq12_sqr(a: Fq12E) -> Fq12E:
    return fq12_mul(a, a)


def fq12_eq(a: Fq12E, b: Fq12E) -> bool:
    return all(fq2_eq(x, y) for x, y in zip(a, b))


def fq12_is_one(a: Fq12E) -> bool:
    return fq12_eq(a, FQ12_ONE)


def fq12_pow(a: Fq12E, e: int) -> Fq12E:
    result = FQ12_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fq12_mul(result, base)
        base = fq12_mul(base, base)
        e >>= 1
    return result


# Frobenius constants gamma[k][i] = xi^(i * (p^k - 1) / 6) for w^i coeffs.
_GAMMA: dict[int, Tuple[Fq2E, ...]] = {}


def _gamma(k: int) -> Tuple[Fq2E, ...]:
    if k not in _GAMMA:
        e = (pow(P, k) - 1) // 6
        base = fq2_pow(XI, e)
        out = [FQ2_ONE]
        for _ in range(5):
            out.append(fq2_mul(out[-1], base))
        _GAMMA[k] = tuple(out)
    return _GAMMA[k]


def fq12_frobenius(a: Fq12E, k: int = 1) -> Fq12E:
    """a^(p^k).  Coefficient Frobenius (conjugate if k odd) times gamma."""
    g = _gamma(k)
    out = []
    for i in range(6):
        c = a[i]
        if k % 2 == 1:
            c = fq2_conj(c)
        out.append(fq2_mul(c, g[i]))
    return tuple(out)  # type: ignore[return-value]


def fq12_conjugate(a: Fq12E) -> Fq12E:
    """a^(p^6) — inverse for elements on the cyclotomic unit circle."""
    return fq12_frobenius(a, 6)


def fq12_inv(a: Fq12E) -> Fq12E:
    """Inverse via the norm to Fq2: prod of the 6 Galois conjugates."""
    # conj_k = a^(p^(2k)) for k = 1..5; a * prod(conj) = Norm in Fq2.
    prod_conj = FQ12_ONE
    for k in (2, 4, 6, 8, 10):
        prod_conj = fq12_mul(prod_conj, fq12_frobenius(a, k))
    norm12 = fq12_mul(a, prod_conj)
    # norm12 must lie in Fq2 (the w^0 coefficient).
    assert all(fq2_is_zero(norm12[i]) for i in range(1, 6)), "norm not in Fq2"
    ninv = fq2_inv(norm12[0])
    return tuple(fq2_mul(c, ninv) for c in prod_conj)  # type: ignore[return-value]
