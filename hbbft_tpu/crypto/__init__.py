"""L0 cryptography: threshold BLS signatures/encryption with pluggable backends.

Reference: the external ``threshold_crypto`` crate re-exported as
``hbbft::crypto`` (upstream ``poanetwork/threshold_crypto``:
``src/lib.rs``, ``src/poly.rs``).  Fork checkout empty at survey time; see
SURVEY.md §2 #14.

Structure (TPU-first redesign, not a port):

* :mod:`~hbbft_tpu.crypto.suite` — an abstract *group suite* (G1, G2,
  pairing, hash-to-curve).  Two host-side suites: the insecure
  ``ScalarSuite`` (fast, for protocol-logic tests) and ``BLSSuite``
  (pure-Python BLS12-381 oracle).
* :mod:`~hbbft_tpu.crypto.keys` — the threshold scheme, generic over a
  suite: ``SecretKeySet``/``PublicKeySet``/shares, signatures, hybrid
  threshold encryption, Lagrange combination.
* :mod:`~hbbft_tpu.crypto.backend` — the pluggable ``CryptoBackend``
  (BASELINE.json:5): batch verification of signature/decryption shares and
  ciphertexts, with random-linear-combination collapsing so a whole
  epoch's checks cost O(#distinct messages) pairings.
* :mod:`~hbbft_tpu.crypto.tpu` — the JAX/TPU device path: int32-limb
  Montgomery field arithmetic, batched G1/G2 Jacobian ops and scalar
  multiplication, the full optimal-ate pairing (Fq12 tower, scanned
  Miller loop, chained final exponentiation), and ``TpuBackend`` — the
  accelerator implementation of the RLC batch-verify contract.  Import
  lazily (pulls in jax).
"""

from hbbft_tpu.crypto.keys import (  # noqa: F401
    Ciphertext,
    DecryptionShare,
    PublicKey,
    PublicKeySet,
    PublicKeyShare,
    SecretKey,
    SecretKeySet,
    SecretKeyShare,
    Signature,
    SignatureShare,
)
from hbbft_tpu.crypto.suite import ScalarSuite  # noqa: F401
