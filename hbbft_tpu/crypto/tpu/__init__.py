"""Accelerator crypto plane: the RLC flush kernel and its backends.

Import surface for callers (benchmarks, embedders): ``TpuBackend`` —
the device flush; ``HybridBackend`` — size-routed host/device with
dead-relay failover.  Submodules (``curve``, ``fq``, ``fq2``,
``pairing``) are the kernel internals.
"""

from hbbft_tpu.crypto.tpu.backend import HybridBackend, TpuBackend

__all__ = ["HybridBackend", "TpuBackend"]
