"""Batched BLS12-381 base-field arithmetic for TPU (JAX, int32 limbs).

This is the device half of the crypto plane (SURVEY.md §7): the hot
pairing-check algebra — replacing upstream ``threshold_crypto``'s pure-Rust
``pairing`` backend (SURVEY.md §2 #14) — expressed as vectorized int32
limb arithmetic that XLA can tile over a TPU's VPU/MXU.

Representation
--------------
An Fq element is ``(..., NL)`` int32 limbs, little-endian, radix
``2^B = 2^11``, ``NL = 36`` limbs (396 bits), ALL LIMBS NONNEGATIVE in
``[0, 4096]``; values are in Montgomery form (``x·R mod P``, R = 2^396)
and only canonicalized on host at the boundary.

Design rules (each independently forced by TPU constraints):

* **11-bit limbs**: products (< 2^24) and 36-term convolution sums
  (< 2^29.2) fit int32 lanes — TPUs have no 64-bit integer path.
* **Nonnegative limbs**: with limbs >= 0, a bound on the VALUE bounds
  every limb's contribution, so dropping provably-zero high limbs after
  a carry is sound.  (Signed/borrow representations admit "ghost" ±1
  top limbs compensated by lower limbs of the other sign — those made
  bounded-round carry propagation unsound; this was learned the hard
  way.)  Subtraction therefore goes through a limb-wise complement:
  ``a - b ≡ a + (CVEC - b) + DELTA  (mod P)`` where CVEC has every limb
  4095 (so the limb subtraction never borrows) and DELTA ≡ -CVEC (mod P).
* **R = 2^396 ≫ P (15 spare bits)**: Montgomery SOS reduction lands far
  below 2^396, so redundant limbs never need an exact (sequential,
  rippling) normalization on device.
* **Value folding**: ops that grow values re-fold bits above 2^385
  through ``2^385 mod P`` (≈ 0.7P, shrinking ≈5 bits per stage); the
  number of stages is chosen statically per op from its worst-case bound.

Invariant (every public op requires and guarantees):
    limbs in [0, 4096],  value in [0, 2^385.9).
``mont_mul`` tolerates values < 2^386 and returns < 2^382.5.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from hbbft_tpu.crypto.bls.fields import P

B = 11
NL = 36
MASK = (1 << B) - 1
R_BITS = B * NL  # 396
R = 1 << R_BITS
R2 = (R * R) % P
NPRIME = (-pow(P, -1, R)) % R  # P * NPRIME ≡ -1 (mod R)
FOLD_AT = B * (NL - 1)  # 385: the value-fold boundary (limb 35's weight)

I32 = jnp.int32


def to_limbs_np(x: int, n: int = NL) -> np.ndarray:
    """Host: nonnegative int -> strict little-endian limbs."""
    assert 0 <= x < (1 << (B * n)), "value does not fit"
    out = np.empty(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= B
    return out


def from_limbs_int(a) -> int:
    """Host: limbs -> Python int value."""
    arr = np.asarray(a).astype(object).reshape(-1)
    acc = 0
    for i, v in enumerate(arr):
        acc += int(v) << (B * i)
    return acc


# Precomputed constants (strict limbs).
P_LIMBS = to_limbs_np(P)
NPRIME_LIMBS = to_limbs_np(NPRIME)
ONE_MONT = to_limbs_np(R % P)  # Montgomery form of 1
FOLD385 = to_limbs_np((1 << FOLD_AT) % P, n=NL - 1)
ZERO = np.zeros(NL, dtype=np.int32)
# Subtraction complement: CVEC has every limb 2^15-1 (>= any loose limb,
# and >= the raw 6-term coefficient sums the Fq12 layer feeds in);
# DELTA ≡ -value(CVEC) (mod P); both strict-limb constants.
CVEC = np.full(NL, 32767, dtype=np.int32)
_CVEC_VAL = from_limbs_int(CVEC)
DELTA = to_limbs_np((-_CVEC_VAL) % P)


def to_mont_np(x: int) -> np.ndarray:
    """Host: canonical int mod P -> Montgomery-form strict limbs."""
    return to_limbs_np((x % P) * R % P)


_LIMB_WEIGHTS = (1 << np.arange(B, dtype=np.int32))


def ints_to_limbs_batch(vals) -> np.ndarray:
    """Host: list of nonnegative ints (< 2^396) -> (N, NL) int32 limbs.

    Vectorized via bytes + unpackbits — the per-value Python limb loop
    (to_limbs_np) costs ~36 iterations each and dominates host->device
    conversion at firehose batch sizes.
    """
    if not vals:
        return np.zeros((0, NL), dtype=np.int32)
    limit = 1 << (B * NL)
    for v in vals:
        assert 0 <= v < limit, "value does not fit"
    data = np.frombuffer(
        b"".join(v.to_bytes(50, "little") for v in vals), dtype=np.uint8
    ).reshape(len(vals), 50)
    bits = np.unpackbits(data, axis=1, bitorder="little")[:, : B * NL]
    return (
        bits.reshape(len(vals), NL, B).astype(np.int32) * _LIMB_WEIGHTS
    ).sum(axis=2, dtype=np.int32)


def to_mont_batch(vals) -> np.ndarray:
    """Host: canonical ints mod P -> (N, NL) Montgomery limbs."""
    return ints_to_limbs_batch([(v % P) * R % P for v in vals])


def from_mont_int(a) -> int:
    """Host: Montgomery limbs -> canonical int mod P."""
    return (from_limbs_int(a) * pow(R, -1, P)) % P


def _carry(x: jnp.ndarray, rounds: int) -> jnp.ndarray:
    """Redistribute nonneg limbs down to [0, 4096]; value preserved.

    Pads rounds+1 limbs so the top limb never receives a carry (carries
    travel one limb per round) — value conservation is structural, and
    all quantities stay nonnegative (input limbs must be >= 0).
    """
    x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, rounds + 1)])
    for _ in range(rounds):
        lo = x & MASK
        c = x >> B
        x = lo + jnp.pad(c[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])
    return x


def _fold(x: jnp.ndarray, stages: int) -> jnp.ndarray:
    """Fold value bits above 2^385 back in via 2^385 mod P, ``stages``
    times, then truncate to NL limbs.  Requires nonneg limbs (post-carry)
    and value < 2^398; each stage shrinks the excess ~5 bits, and the
    final truncation is provably lossless for value < 2^396."""
    for _ in range(stages):
        e = x[..., NL - 1]
        for i in range(NL, min(x.shape[-1], NL + 2)):
            e = e + x[..., i] * (1 << (B * (i - (NL - 1))))
        folded = x[..., : NL - 1] + e[..., None] * jnp.asarray(FOLD385)
        x = _carry(folded, rounds=2)
    return x[..., :NL]


def _conv(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Limb convolution: (..., NL) x (..., NL) -> (..., 2*NL-1).

    Skew-reshape formulation (round 5): the anti-diagonal sums
    ``out[k] = sum_{i+j=k} a_i b_j`` are computed by padding each outer
    row to width 2*NL and reflattening with stride 2*NL-1, which shifts
    row i right by exactly i (flat index i*2NL + j re-read as
    i*(2NL-1) + (i+j)); one axis sum then yields the convolution.  This
    replaces the round-1 scatter matmul ``(.., NL^2) @ (NL^2, 2NL-1)``
    — ~92k MACs per field mul, the dominant FLOP term of every pairing
    kernel — with the same 1,296 products plus a 36-row sum (~24x fewer
    lane ops), still a handful of XLA ops (no unrolled slice-updates,
    no gathers), so the big pairing graphs stay compilable.  Bounds are
    unchanged: identical integer sums, products < 2^24, 36-term
    anti-diagonal sums < 2^29.2.
    """
    outer = a[..., :, None] * b[..., None, :]
    batch = outer.shape[:-2]
    padded = jnp.pad(
        outer, [(0, 0)] * (outer.ndim - 2) + [(0, 0), (0, NL)]
    )
    flat = padded.reshape(*batch, NL * 2 * NL)
    skewed = flat[..., : NL * (2 * NL - 1)].reshape(*batch, NL, 2 * NL - 1)
    # dtype pinned: under x64 jnp.sum promotes int32 accumulation to
    # int64, which TPU lanes don't have; the 36-term sums are < 2^29.2
    # so int32 accumulation is exact.
    return jnp.sum(skewed, axis=-2, dtype=I32)


def _conv_mat(b_limbs: np.ndarray) -> np.ndarray:
    """(NL, 2*NL-1) Toeplitz matrix M[i, i:i+NL] = b for a CONSTANT
    operand: the convolution becomes one small (.., NL) @ (NL, 2NL-1)
    matmul (~2.6k MACs) instead of outer + skew-sum (~3.9k lane ops)."""
    M = np.zeros((NL, 2 * NL - 1), dtype=np.int32)
    for i in range(NL):
        M[i, i : i + NL] = b_limbs
    return M


_NPRIME_MAT = _conv_mat(NPRIME_LIMBS)
_P_MAT = _conv_mat(P_LIMBS)


def _conv_const(a: jnp.ndarray, mat: np.ndarray) -> jnp.ndarray:
    """Limb convolution with a constant operand (Toeplitz matmul)."""
    return jnp.matmul(a, jnp.asarray(mat))


def mont_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product a·b·R^-1 (mod P), batched.

    SOS with redundant nonneg limbs: T = a·b; m ≡ T·N' (mod R);
    t = (T + m·P)/R.  The division is exact in value; the carried low
    part's value is exactly corr·R with corr in {0, 1, 2} (redundant m),
    read off limb 35.  Inputs: value < 2^386.  Output: value < 2^382.5.
    """
    t = _carry(_conv(a, b), rounds=3)
    m = _carry(_conv_const(t[..., :NL], _NPRIME_MAT), rounds=3)[..., :NL]
    mp = _conv_const(m, _P_MAT)
    full = jnp.pad(
        t, [(0, 0)] * (t.ndim - 1) + [(0, max(0, mp.shape[-1] - t.shape[-1]))]
    )
    full = full.at[..., : mp.shape[-1]].add(mp)
    full = _carry(full, rounds=3)
    lo, hi = full[..., :NL], full[..., NL : 2 * NL]
    # value(lo) = corr·R exactly; limb 35 sits in [2048·corr - 3, 2048·corr].
    corr = (lo[..., NL - 1] + 3) >> B
    return _carry(hi.at[..., 0].add(corr), rounds=1)[..., :NL]


def mont_sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mont_mul(a, a)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _fold(_carry(a + b, rounds=2), stages=1)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b (mod P) via the borrow-free complement (module docstring).
    Accepts limbs up to 2^15-1 (raw coefficient sums), value < 2^389."""
    d = a + (jnp.asarray(CVEC) - b) + jnp.asarray(DELTA)
    return _fold(_carry(d, rounds=2), stages=3)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    d = (jnp.asarray(CVEC) - a) + jnp.asarray(DELTA)
    return _fold(_carry(d, rounds=2), stages=3)


def small_mul(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small positive constant (k <= 16)."""
    assert 0 < k <= 16
    return _fold(_carry(a * k, rounds=2), stages=1)


def normalize(a: jnp.ndarray) -> jnp.ndarray:
    """Re-settle into the invariant range; value unchanged mod P."""
    return _fold(_carry(a, rounds=2), stages=1)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """Value ≡ 0 (mod P)?  Batched, device-side (sequential scans; keep
    out of hot loops — flag-carrying point code avoids needing this).

    One Montgomery shrink pass maps a (value < 2^386) to a value
    ≡ a (mod P) in [0, 2.1P); that is ≡ 0 mod P iff it equals 0, P, or
    2P — test each exactly.
    """
    v = mont_mul(a, jnp.asarray(ONE_MONT))
    acc = _is_exact_zero(v)
    for k in (1, 2):
        acc = acc | _is_exact_zero(v - jnp.asarray(to_limbs_np(k * P)))
    return acc


def _is_exact_zero(x: jnp.ndarray) -> jnp.ndarray:
    """Exact value==0 test via sequential carry scan (NL tiny steps).
    Input limbs may be signed here (difference of nonneg vectors)."""

    def step(c, limb):
        s = limb + c
        return s >> B, s & MASK

    carry0 = jnp.zeros(x.shape[:-1], dtype=I32)
    xs = jnp.moveaxis(x, -1, 0)
    final_c, lows = jax.lax.scan(step, carry0, xs)
    return (final_c == 0) & jnp.all(lows == 0, axis=0)


def pow_fixed(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e (Montgomery), e a fixed Python int.

    Fixed 4-bit windows MSB-first: 14 table muls + per window 4 squarings
    and one table mul — ~490 sequential muls for e = P-2 vs ~762 for the
    bit-at-a-time scan this replaced.  The window values are static
    (derived from e at trace time) but the table gather stays inside the
    scan so the graph is one small scan body, not 380 unrolled ops.
    """
    if e == 0:
        return jnp.broadcast_to(jnp.asarray(ONE_MONT), a.shape)
    W = 4
    nwin = (e.bit_length() + W - 1) // W
    wins = [(e >> (W * (nwin - 1 - i))) & 15 for i in range(nwin)]
    pows = [jnp.broadcast_to(jnp.asarray(ONE_MONT), a.shape), a]
    for _ in range(2, 16):
        pows.append(mont_mul(pows[-1], a))
    table = jnp.stack(pows)  # (16, ..., NL)
    acc = table[wins[0]]  # static index

    def step(acc, w):
        acc = mont_sqr(mont_sqr(mont_sqr(mont_sqr(acc))))
        t = jax.lax.dynamic_index_in_dim(table, w, 0, keepdims=False)
        return jnp.where(w > 0, mont_mul(acc, t), acc), None

    acc, _ = jax.lax.scan(step, acc, jnp.asarray(wins[1:], dtype=I32))
    return acc


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """Field inverse by Fermat: a^(P-2). ~570 muls — use sparingly."""
    return pow_fixed(a, P - 2)


def rand_elems(rng: np.random.Generator, shape=()) -> jnp.ndarray:
    """Host helper: random canonical Montgomery elements for tests."""
    flat = int(np.prod(shape)) if shape else 1
    outs = [
        to_mont_np(int.from_bytes(rng.bytes(48), "big") % P) for _ in range(flat)
    ]
    arr = np.stack(outs).reshape(*shape, NL) if shape else outs[0]
    return jnp.asarray(arr)
