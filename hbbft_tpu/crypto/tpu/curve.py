"""Batched Jacobian point arithmetic for G1 (Fq) and G2 (Fq2) on TPU.

Replaces the per-point CPU group ops of upstream ``threshold_crypto``
(SURVEY.md §2 #14) with branch-free, vmappable formulas.

Point representation: ``(x, y, z, inf)`` — Jacobian coordinates as limb
arrays plus an explicit int32 infinity flag (1 = identity).  Carrying the
flag avoids data-dependent field-equality tests (which need sequential
carry scans) in the hot paths.

``add_unsafe`` is branch-free and WRONG when both inputs are the same
non-identity point or exact negatives.  Its callers guarantee that can't
happen (or happens with cryptographically negligible probability):

* ``scalar_mul``: acc = m·B meets addend B only if m ≡ ±1 (mod r); after
  the first set bit m ∈ [2, 2^255) and the scalars here are either
  Fiat-Shamir RLC coefficients (< 2^128 ≪ r) or Lagrange coefficients we
  derive ourselves — hitting (r±1)/2 prefixes is a 2^-250-class event an
  adversary cannot steer.
* tree reduction over RLC-scaled points: points are c_i·P_i with c_i
  Fiat-Shamir coefficients fixed only after the P_i are committed, so
  engineered cancellations/collisions are negligible.

``add_safe`` (field-equality corrected, sequential scans inside) exists
for tests and cold paths.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hbbft_tpu.crypto.bls import fields as F
from hbbft_tpu.crypto.tpu import fq, fq2

Point = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]


@dataclass(frozen=True)
class Ops:
    """Field-op namespace a curve works over (G1: fq, G2: fq2)."""

    add: Callable
    sub: Callable
    mul: Callable
    sqr: Callable
    small_mul: Callable
    is_zero: Callable
    one: np.ndarray
    zero: np.ndarray
    elem_ndim: int  # trailing dims of one field element


G1_OPS = Ops(fq.add, fq.sub, fq.mont_mul, fq.mont_sqr, fq.small_mul,
             fq.is_zero, fq.ONE_MONT, fq.ZERO, 1)
G2_OPS = Ops(fq2.add, fq2.sub, fq2.mul, fq2.sqr, fq2.small_mul,
             fq2.is_zero, fq2.ONE, fq2.ZERO, 2)


def identity(ops: Ops, batch: Tuple[int, ...] = ()) -> Point:
    one = jnp.broadcast_to(jnp.asarray(ops.one), (*batch, *ops.one.shape))
    zero = jnp.broadcast_to(jnp.asarray(ops.zero), (*batch, *ops.zero.shape))
    return (one, one, zero, jnp.ones(batch, dtype=jnp.int32))


def double(ops: Ops, p: Point) -> Point:
    """Jacobian doubling (a = 0 curve).  Correct for all inputs: the
    subgroup has prime order, so y = 0 never occurs on valid points, and
    the identity flag rides through unchanged (z' = 2yz keeps z = 0)."""
    x, y, z, inf = p
    a = ops.sqr(x)
    b = ops.sqr(y)
    c = ops.sqr(b)
    d = ops.small_mul(ops.sub(ops.sub(ops.sqr(ops.add(x, b)), a), c), 2)
    e = ops.small_mul(a, 3)
    f = ops.sqr(e)
    x3 = ops.sub(f, ops.small_mul(d, 2))
    y3 = ops.sub(ops.mul(e, ops.sub(d, x3)), ops.small_mul(c, 8))
    z3 = ops.small_mul(ops.mul(y, z), 2)
    return (x3, y3, z3, inf)


def _sel(flag: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray, ndim: int) -> jnp.ndarray:
    """where(flag, a, b) with flag broadcast over trailing element dims."""
    f = flag.reshape(flag.shape + (1,) * ndim).astype(bool)
    return jnp.where(f, a, b)


def select(flag: jnp.ndarray, p: Point, q: Point, ops: Ops) -> Point:
    """Pointwise where(flag, p, q)."""
    return (
        _sel(flag, p[0], q[0], ops.elem_ndim),
        _sel(flag, p[1], q[1], ops.elem_ndim),
        _sel(flag, p[2], q[2], ops.elem_ndim),
        jnp.where(flag.astype(bool), p[3], q[3]),
    )


def add_unsafe(ops: Ops, p: Point, q: Point) -> Point:
    """General Jacobian addition; identity flags handled, p == ±q NOT
    (see module docstring for why callers may rely on that)."""
    x1, y1, z1, inf1 = p
    x2, y2, z2, inf2 = q
    z1z1 = ops.sqr(z1)
    z2z2 = ops.sqr(z2)
    u1 = ops.mul(x1, z2z2)
    u2 = ops.mul(x2, z1z1)
    s1 = ops.mul(y1, ops.mul(z2, z2z2))
    s2 = ops.mul(y2, ops.mul(z1, z1z1))
    h = ops.sub(u2, u1)
    i = ops.sqr(ops.small_mul(h, 2))
    j = ops.mul(h, i)
    rr = ops.small_mul(ops.sub(s2, s1), 2)
    v = ops.mul(u1, i)
    x3 = ops.sub(ops.sub(ops.sqr(rr), j), ops.small_mul(v, 2))
    y3 = ops.sub(ops.mul(rr, ops.sub(v, x3)), ops.small_mul(ops.mul(s1, j), 2))
    z3 = ops.mul(ops.small_mul(ops.mul(z1, z2), 2), h)
    out: Point = (x3, y3, z3, jnp.zeros_like(inf1))
    out = select(inf1, q, out, ops)
    out = select(inf2 & (1 - inf1), p, out, ops)
    return out


def add_safe(ops: Ops, p: Point, q: Point) -> Point:
    """Addition correct for ALL inputs (uses field-equality tests; slow —
    sequential scans — so keep out of scans/hot loops)."""
    x1, y1, z1, inf1 = p
    x2, y2, z2, inf2 = q
    z1z1 = ops.sqr(z1)
    z2z2 = ops.sqr(z2)
    u1 = ops.mul(x1, z2z2)
    u2 = ops.mul(x2, z1z1)
    s1 = ops.mul(y1, ops.mul(z2, z2z2))
    s2 = ops.mul(y2, ops.mul(z1, z1z1))
    h_zero = ops.is_zero(ops.sub(u2, u1)).astype(jnp.int32)
    r_zero = ops.is_zero(ops.sub(s2, s1)).astype(jnp.int32)
    both = (1 - inf1) * (1 - inf2)
    is_dbl = both * h_zero * r_zero
    is_cancel = both * h_zero * (1 - r_zero)
    # safety: the two select()s below replace exactly the lanes where
    # add_unsafe's P == ±Q precondition fails (is_dbl / is_cancel).
    out = add_unsafe(ops, p, q)
    out = select(is_dbl, double(ops, p), out, ops)
    out = select(is_cancel, identity(ops, tuple(inf1.shape)), out, ops)
    return out


def neg(ops: Ops, p: Point) -> Point:
    x, y, z, inf = p
    return (x, ops.sub(jnp.zeros_like(y), y), z, inf)


def scalar_mul(ops: Ops, base: Point, bits: jnp.ndarray) -> Point:
    """Batched double-and-add: bits ``(..., nbits)`` int32, MSB first.

    Scans over the bit axis; everything else is batch.  See module
    docstring for the add_unsafe safety argument.
    """
    nbits = bits.shape[-1]
    batch = bits.shape[:-1]
    acc = identity(ops, batch)
    started = jnp.zeros(batch, dtype=jnp.int32)
    xs = jnp.moveaxis(bits, -1, 0)  # (nbits, ...)

    def step(carry, bit):
        acc, started = carry
        acc = double(ops, acc)
        # "acc is identity" is exactly "no set bit yet": use the flag
        # instead of a field test.
        acc_id = (1 - started)
        summed = add_unsafe(ops, (acc[0], acc[1], acc[2], acc_id), base)
        acc = select(bit, summed, acc, ops)
        started = started | bit
        return (acc, started), None

    (acc, started), _ = jax.lax.scan(step, (acc, started), xs)
    x, y, z, _ = acc
    inf = (1 - started) | base[3]
    return (x, y, z, inf)


def jac_eq_dev(ops: Ops, p: Point, q: Point) -> jnp.ndarray:
    """Batched projective equality (cross-multiplied), device-side.

    Contains ``is_zero`` sequential scans — once-per-flush use only.
    Points whose z is zero but whose infinity flag is unset (the garbage
    add_unsafe produces on forbidden inputs) compare UNEQUAL to
    everything, so downstream checks fail closed.
    """
    x1, y1, z1, i1 = p
    x2, y2, z2, i2 = q
    z1z1 = ops.sqr(z1)
    z2z2 = ops.sqr(z2)
    ex = ops.is_zero(ops.sub(ops.mul(x1, z2z2), ops.mul(x2, z1z1)))
    ey = ops.is_zero(
        ops.sub(
            ops.mul(y1, ops.mul(z2, z2z2)), ops.mul(y2, ops.mul(z1, z1z1))
        )
    )
    z_ok = (~ops.is_zero(z1)) & (~ops.is_zero(z2))
    both_fin = (i1 == 0) & (i2 == 0)
    both_inf = (i1 == 1) & (i2 == 1)
    return both_inf | (both_fin & z_ok & ex & ey)


# ---------------------------------------------------------------------------
# Endomorphism subgroup checks — device mirror of bls.curve.g1_in_subgroup /
# g2_in_subgroup (see the derivation + soundness notes there and the
# equivalence/soundness tests in tests/test_bls.py, tests/test_tpu_crypto.py).
#
#   G1: phi(P) == -[x^2]P   (phi: X *= beta; x^2 is 127 bits)
#   G2: psi(Q) == -[|x|]Q   (psi: conjugate coords, X *= cx, Y *= cy)
#
# Both scalars fit the 128-bit RLC coefficient width, so the flush
# kernel's shared-doubling scan drops from the 255-step [r-1]P chain
# (the round-2 design) to 128 steps.
#
# Fail-closed safety with add_unsafe: an adversarial SMALL-ORDER point
# can steer the fixed-scalar chain into add_unsafe's forbidden P == ±Q
# cases, but those produce z = 0 outputs and z stays 0 through every
# subsequent double/add (z3 always carries a factor of the incoming z),
# and jac_eq_dev treats unflagged z == 0 as UNEQUAL — so a corrupted
# chain can only REJECT, which is the correct verdict for any point
# that could steer it (subgroup points can't: the prefix-coincidence
# argument in scalar_mul2's docstring).
# ---------------------------------------------------------------------------

ENDO_NBITS = 128


@lru_cache(maxsize=1)
def _endo_consts():
    """(beta_mont, psi_cx_mont, psi_cy_mont, x2_bits, xabs_bits) — device
    forms of the oracle-derived endomorphism constants."""
    from hbbft_tpu.crypto.bls import curve as OC

    x_abs = -F.BLS_X
    beta = fq.to_mont_np(OC.g1_beta())
    cx, cy = OC.psi_consts()
    x2_bits = _scalars_to_bits_np([x_abs * x_abs], ENDO_NBITS)[0]
    xabs_bits = _scalars_to_bits_np([x_abs], ENDO_NBITS)[0]
    return (
        beta,
        fq2.to_mont_np(cx),
        fq2.to_mont_np(cy),
        x2_bits,
        xabs_bits,
    )


def endo_bits(g2: bool, n: int) -> np.ndarray:
    """(n, ENDO_NBITS) LSB-first bits of the endomorphism-check scalar
    (x^2 for G1 rows, |x| for G2 rows) — the bits_b of the shared scan."""
    _, _, _, x2_bits, xabs_bits = _endo_consts()
    return np.broadcast_to(xabs_bits if g2 else x2_bits, (n, ENDO_NBITS))


def phi_g1(p: Point) -> Point:
    """GLV endomorphism on batched G1 Jacobian points: X *= beta."""
    beta, _, _, _, _ = _endo_consts()
    x, y, z, inf = p
    bx = fq.mont_mul(x, jnp.broadcast_to(jnp.asarray(beta), x.shape))
    return (bx, y, z, inf)


def psi_g2(p: Point) -> Point:
    """Untwist-Frobenius-twist on batched G2 Jacobian points:
    (cx*conj(X), cy*conj(Y), conj(Z))."""
    _, cx, cy, _, _ = _endo_consts()
    x, y, z, inf = p
    cxb = jnp.broadcast_to(jnp.asarray(cx), x.shape)
    cyb = jnp.broadcast_to(jnp.asarray(cy), y.shape)
    return (
        fq2.mul(cxb, fq2.conj(x)),
        fq2.mul(cyb, fq2.conj(y)),
        fq2.conj(z),
        inf,
    )


def endo_subgroup_eq(ops: Ops, pts: Point, chain_out: Point) -> jnp.ndarray:
    """Batched membership verdicts given ``chain_out`` = [x^2]P (G1) or
    [|x|]Q (G2) from the shared scan: endo(P) == -chain_out."""
    endo = psi_g2(pts) if ops is G2_OPS else phi_g1(pts)
    return jac_eq_dev(ops, endo, neg(ops, chain_out))


def scalar_mul2(
    ops: Ops, base: Point, bits_a: jnp.ndarray, bits_b: jnp.ndarray
) -> Tuple[Point, Point]:
    """Two scalar multiples of the SAME base per batch element, one scan.

    LSB-first double-and-add sharing the base-doubling chain: per step
    one double (of the base) + two conditional adds, so computing
    ``[a]P`` and ``[b]P`` together costs ~35% less than two MSB-first
    scans and halves the number of compiled scan bodies.  ``bits_a``/
    ``bits_b``: (..., nbits) int32, LSB FIRST, equal width (pad the
    shorter scalar with zero bits).

    add_unsafe safety (on top of the module-docstring argument): the
    accumulator after k steps holds ``(m mod 2^k)·P`` (fixed scalar) or a
    committed-coefficient partial sum (Fiat-Shamir), and the addend is
    ``2^k·P``; coincidence needs m mod 2^k ≡ ±2^k (mod r).  For any
    FIXED m < 2^128 over k ≤ 128 steps (the RLC coefficients and both
    endomorphism-chain scalars x^2 and |x| qualify) that is impossible:
    m mod 2^k < 2^k rules out +2^k as integers, and -2^k mod r =
    r - 2^k > 2^128 > m mod 2^k rules out the negative case; the same
    bounds covered the historic m = r-1 chain.  For small-ORDER inputs
    (adversarial non-subgroup points, where the arithmetic is mod
    ord(P), not r) a coincidence CAN occur, but then z becomes and
    stays 0, ``jac_eq_dev`` reports unequal, and the membership check
    fails closed — rejection being the right verdict for any point able
    to steer the chain (see the endo section notes above).
    """
    assert bits_a.shape == bits_b.shape
    batch = bits_a.shape[:-1]
    acc_a = identity(ops, batch)
    acc_b = identity(ops, batch)
    started_a = jnp.zeros(batch, dtype=jnp.int32)
    started_b = jnp.zeros(batch, dtype=jnp.int32)
    xs = (jnp.moveaxis(bits_a, -1, 0), jnp.moveaxis(bits_b, -1, 0))

    def acc_step(acc, started, cur, bit):
        summed = add_unsafe(ops, (acc[0], acc[1], acc[2], 1 - started), cur)
        return select(bit, summed, acc, ops), started | bit

    def step(carry, bits):
        acc_a, started_a, acc_b, started_b, cur = carry
        bit_a, bit_b = bits
        acc_a, started_a = acc_step(acc_a, started_a, cur, bit_a)
        acc_b, started_b = acc_step(acc_b, started_b, cur, bit_b)
        return (acc_a, started_a, acc_b, started_b, double(ops, cur)), None

    (acc_a, started_a, acc_b, started_b, _), _ = jax.lax.scan(
        step, (acc_a, started_a, acc_b, started_b, base), xs
    )
    inf_a = (1 - started_a) | base[3]
    inf_b = (1 - started_b) | base[3]
    return (
        (acc_a[0], acc_a[1], acc_a[2], inf_a),
        (acc_b[0], acc_b[1], acc_b[2], inf_b),
    )


def scalars_to_bits_lsb(scalars, nbits: int) -> jnp.ndarray:
    """Host: list of ints -> (N, nbits) int32 LSB-first bit matrix."""
    return jnp.asarray(_scalars_to_bits_np(scalars, nbits))


# ---------------------------------------------------------------------------
# Static-endo flush scans (round 4) — the per-row cost cut
#
# The flush kernel's per-row work is the scalar-mul scan.  Two structural
# facts the shared 128-step scan (scalar_mul2) never exploited:
#
#   * the endomorphism-check scalars are FIXED — x^2 (hamming weight 17)
#     on G1, |x| (hamming weight 6) on G2 — so the check chain needs an
#     ADD at only those static positions, not a computed-and-discarded
#     conditional add at every one of 128 steps;
#   * on G2 the verified psi(Q) = [x]Q endomorphism gives a second base:
#     a 128-bit RLC coefficient c splits as c = q·|x| + s (q ≤ 65 bits,
#     s < 64), and [c]Q = [s]Q + [q]([|x|]Q) = [s]Q + [q](-psi(Q)) — a
#     65-step two-scalar scan instead of 128 steps.  Using psi(Q) as a
#     base is sound exactly when psi(Q) == [x]Q, which is the subgroup
#     check VERIFIED IN THE SAME KERNEL: if it fails, the aggregate
#     verdict is already False and the RLC value is irrelevant; if it
#     holds, Q ∈ G2 (Bowe 2019/814 / Scott 2021/1130, bls.curve notes)
#     and the decomposition is exact group algebra.
#
# Structure note: the check chain is assembled as tree_sum over the
# COLLECTED doubling-chain points [2^k]P at the static set bits
# ([m]P = sum of distinct powers), not as adds interleaved between the
# scan segments — XLA 0.9.0's CPU pipeline dies with "Unknown MLIR
# failure" on scan→add→scan chains (reproduced + bisected round 4),
# while sequential scans plus one trailing tree reduction compile fine.
#
# add_unsafe safety for the NEW uses (CLAUDE.md invariant — on top of
# the scalar_mul2 docstring argument):
#
#   * check-chain tree_sum (both groups): every partial sum is
#     [m1]P for m1 a sub-mask of the fixed scalar's set bits, every
#     addend [m2]P for a DISJOINT nonzero sub-mask; coincidence needs
#     m1 ≡ ±m2 (mod r) with m1 ≠ m2, both < 2^128 ≪ r — impossible.
#   * B01 = Q + (-psi(Q)) precompute: forbidden iff psi(Q) == ±Q, i.e.
#     [x ∓ 1]Q = O — impossible for genuine G2 points (0 < |x ∓ 1| <
#     2^65 < r); adversarial non-subgroup points can poison it, but
#     they fail the psi check in the same kernel (fail-closed z = 0
#     argument in the endo section above), so a poisoned RLC never
#     reaches a True verdict.
#   * MSB accumulator adds (G2 RLC scan): deterministically impossible,
#     not merely improbable.  After the step's double the accumulator is
#     [2m]Q with m = s_k + |x|*q_k (k-bit MSB prefixes, k <= 64, so
#     2m < 2^131 << r: a mod-r wrap 2m = r - t is out of reach and any
#     coincidence must hold over the integers).  The addend scalars are
#     1 and |x|+1 (both odd — never equal to the even 2m) or |x| (even:
#     needs m = |x|/2, which forces q_k = 0 and s_k = |x|/2; but |x|/2
#     has 63 bits, so k >= 63 and s >= 2^(64-k) * |x|/2 >= |x|,
#     contradicting the decomposition's 0 <= s < |x|).
# ---------------------------------------------------------------------------

XSQ = (F.BLS_X * F.BLS_X)  # 128-bit G1 endo-check scalar (positive)


def _lsb_set_positions(value: int, nbits: int) -> Tuple[int, ...]:
    return tuple(i for i in range(nbits) if (value >> i) & 1)


def _stack_points(pts_list, ops: Ops) -> Point:
    """Stack unbatched-or-batched points along a new leading axis."""
    return tuple(
        jnp.stack([p[c] for p in pts_list]) for c in range(4)
    )


def _tree_sum_axis0(ops: Ops, pts: Point) -> Point:
    """Pairwise-halving sum over a SMALL static leading axis (any
    trailing batch dims; contrast tree_sum, whose identity padding
    assumes a single batch dim).  add_unsafe safety is the CALLER's
    obligation for the pair sums it induces."""
    m = pts[0].shape[0]
    while m > 1:
        half = m // 2
        lo = tuple(x[:half] for x in pts)
        hi = tuple(x[half : 2 * half] for x in pts)
        summed = add_unsafe(ops, lo, hi)
        if m % 2:
            tail = tuple(x[2 * half :] for x in pts)
            pts = tuple(
                jnp.concatenate([s, t]) for s, t in zip(summed, tail)
            )
            m = half + 1
        else:
            pts = summed
            m = half
    return tuple(x[0] for x in pts)


def scalar_mul_rlc_g1(base: Point, bits_lsb: jnp.ndarray) -> Tuple[Point, Point]:
    """([c]P, [x^2]P) per row — LSB-first shared-doubling scan.

    ``bits_lsb``: (..., 128) LSB-first RLC coefficient bits.  One base
    doubling chain serves both results; the RLC add is conditional per
    step, and the [x^2]P check chain is the tree_sum of the chain
    points [2^k]P collected at x^2's 17 static set bits (structure +
    safety: section notes above).
    """
    ops = G1_OPS
    nbits = bits_lsb.shape[-1]
    # The [x^2]P check chain reads chain points at x^2's set bits; a
    # narrower scan would silently truncate the check scalar and reject
    # every genuine point (fail-closed but undiagnosable) — mirror the
    # G2 scan's width guard.  Explicit raise, not assert: `python -O`
    # strips asserts, and this failure mode is exactly the one that
    # must stay loud (ADVICE round 5).
    if nbits < XSQ.bit_length():
        raise ValueError(
            f"RLC bit width {nbits} < x^2 width {XSQ.bit_length()}"
        )
    batch = bits_lsb.shape[:-1]
    acc = identity(ops, batch)
    started = jnp.zeros(batch, dtype=jnp.int32)
    xs_all = jnp.moveaxis(bits_lsb, -1, 0)  # (nbits, ...)

    def step(carry, bit):
        acc, started, cur = carry
        summed = add_unsafe(ops, (acc[0], acc[1], acc[2], 1 - started), cur)
        acc = select(bit, summed, acc, ops)
        started = started | bit
        return (acc, started, double(ops, cur)), None

    # Segment the scan at the static x^2 set bits: at bit k the carry
    # holds cur = [2^k]P once steps 0..k-1 have run, so each segment
    # ends just before a set bit (whose step opens the next segment).
    positions = _lsb_set_positions(XSQ, nbits)
    carry = (acc, started, base)
    curs = []
    prev = 0
    for k in positions:
        if k > prev:
            carry, _ = jax.lax.scan(step, carry, xs_all[prev:k])
            prev = k
        curs.append(carry[2])
    if prev < nbits:
        carry, _ = jax.lax.scan(step, carry, xs_all[prev:nbits])
    acc, started, _ = carry
    inf = (1 - started) | base[3]
    scaled = (acc[0], acc[1], acc[2], inf)
    chain = _tree_sum_axis0(ops, _stack_points(curs, ops))
    chain = (chain[0], chain[1], chain[2], chain[3] | base[3])
    return scaled, chain


G2_SCAN_NBITS = 65  # max(|x| bits, q = c div |x| bits) for c < 2^128


def decompose_g2_scalar(c: int) -> Tuple[int, int]:
    """Host: RLC coefficient c -> (s, q) with c = q·|x| + s, 0 ≤ s < |x|.

    Then [c]Q = [s]Q + [q][|x|]Q = [s]Q + [q](-psi(Q)) for subgroup Q
    (psi(Q) = [x]Q, x < 0).  For c < 2^128: q < 2^65, s < 2^64.
    """
    q, s = divmod(c, -F.BLS_X)
    return s, q


def scalar_mul_rlc_g2(
    base: Point, bits_s: jnp.ndarray, bits_q: jnp.ndarray
) -> Tuple[Point, Point]:
    """([c]Q, [|x|]Q) per row via the psi decomposition (section notes).

    ``bits_s``/``bits_q``: (..., 65) MSB-first bits of s and q from
    :func:`decompose_g2_scalar`.  The RLC sum is ONE MSB-first scan —
    per step one accumulator double and one add_unsafe of the addend
    selected from {O, Q, -psi(Q), Q-psi(Q)}; the [|x|]Q check chain is
    the tree_sum of [2^j]Q collected from a double-only chain at |x|'s
    6 static set bits.  ~129 doubles + ~72 adds replaces the shared
    128-step scan's 128 doubles + 256 computed adds — and the G2 rows
    are the most expensive in the flush (every Fq2 op is ~3 Fq muls).
    """
    ops = G2_OPS
    nbits = bits_s.shape[-1]
    assert bits_s.shape == bits_q.shape and nbits == G2_SCAN_NBITS
    batch = bits_s.shape[:-1]
    b0 = base
    b1 = neg(ops, psi_g2(base))
    b01 = add_unsafe(ops, b0, b1)  # safety: section notes (psi(Q) != ±Q)
    acc = identity(ops, batch)
    xs = (jnp.moveaxis(bits_s, -1, 0), jnp.moveaxis(bits_q, -1, 0))

    def step(acc, bits):
        sbit, qbit = bits
        acc = double(ops, acc)
        both = sbit & qbit
        addend = select(both, b01, select(sbit, b0, b1, ops), ops)
        # Identity addend when neither bit is set (and inherit the
        # base's own identity flag) — add_unsafe routes on the flag.
        addend = (
            addend[0],
            addend[1],
            addend[2],
            (1 - (sbit | qbit)) | addend[3],
        )
        # safety: MSB accumulator adds — deterministically impossible
        # coincidence (section notes above, third bullet).
        return add_unsafe(ops, acc, addend), None

    acc, _ = jax.lax.scan(step, acc, xs)

    # [|x|]Q check chain: double-only scan segments over the base,
    # collecting [2^j]Q at |x|'s set bits, then one tree reduction
    # (structure + add_unsafe safety: section notes above).
    def dbl_step(cur, _):
        return double(ops, cur), None

    curs = []
    cur = base
    prev = 0
    for j in _lsb_set_positions(-F.BLS_X, 64):
        if j > prev:
            cur, _ = jax.lax.scan(dbl_step, cur, None, length=j - prev)
            prev = j
        curs.append(cur)
    chain = _tree_sum_axis0(ops, _stack_points(curs, ops))

    # Identity flags: acc started as identity and add_unsafe tracked
    # flags through every add (a zero scalar leaves the flag set); the
    # chain inherits the base's flag through doubling.
    scaled = (acc[0], acc[1], acc[2], acc[3] | base[3])
    chain = (chain[0], chain[1], chain[2], chain[3] | base[3])
    return scaled, chain


def tree_sum(ops: Ops, pts: Point) -> Point:
    """Sum a batch of points over axis 0 (log2 rounds of add_unsafe)."""
    n = pts[0].shape[0]
    while n > 1:
        half = (n + 1) // 2
        top = _slice_or_identity(pts, half, n, ops)
        bottom = tuple(x[:half] for x in pts)
        # safety: tree reduction over RLC-scaled points (module
        # docstring, second bullet — committed-coefficient partial sums).
        pts = add_unsafe(ops, bottom, top)
        n = half
    return tuple(x[0] for x in pts)


def _slice_or_identity(pts: Point, half: int, n: int, ops: Ops) -> Point:
    """pts[half:n] padded with identities up to length half."""
    idx = jnp.arange(half)
    valid = idx + half < n
    gather = jnp.clip(idx + half, 0, n - 1)
    sliced = tuple(x[gather] for x in pts)
    return select(valid, sliced, identity(ops, (half,)), ops)


# ---------------------------------------------------------------------------
# Host conversions to/from the oracle's Jacobian-int representation
# ---------------------------------------------------------------------------


def g1_to_dev(jacs) -> Point:
    """Host: list of oracle G1 Jacobian points -> batched device point.

    Batch-vectorized (bytes + unpackbits): the per-limb Python loop
    dominated host->device conversion at firehose batch sizes."""
    n = len(jacs)
    xs, ys, zs = [], [], []
    infs = np.zeros(n, dtype=np.int32)
    for i, (x, y, z) in enumerate(jacs):
        if z % F.P == 0:
            infs[i] = 1
            x, y, z = 1, 1, 0
        xs.append(x)
        ys.append(y)
        zs.append(z)
    flat = fq.to_mont_batch(xs + ys + zs)
    return (jnp.asarray(flat[:n]), jnp.asarray(flat[n : 2 * n]),
            jnp.asarray(flat[2 * n :]), jnp.asarray(infs))


def g2_to_dev(jacs) -> Point:
    n = len(jacs)
    coords: list = []
    infs = np.zeros(n, dtype=np.int32)
    pts = []
    for i, (x, y, z) in enumerate(jacs):
        if z[0] % F.P == 0 and z[1] % F.P == 0:
            infs[i] = 1
            x, y, z = (1, 0), (1, 0), (0, 0)
        pts.append((x, y, z))
    for sel in range(3):
        for c in range(2):
            coords.extend(p[sel][c] for p in pts)
    flat = fq.to_mont_batch(coords)  # (6n, NL): x0 x1 y0 y1 z0 z1 blocks
    def elem(block):
        return jnp.asarray(
            np.stack([flat[block * 2 * n : block * 2 * n + n],
                      flat[block * 2 * n + n : (block + 1) * 2 * n]], axis=1)
        )
    return (elem(0), elem(1), elem(2), jnp.asarray(infs))


def g1_from_dev(p: Point, idx=None):
    """Host: one device G1 point -> oracle Jacobian int tuple."""
    x, y, z, inf = [np.asarray(v) for v in p]
    if idx is not None:
        x, y, z, inf = x[idx], y[idx], z[idx], inf[idx]
    if int(inf):
        return (1, 1, 0)
    return (fq.from_mont_int(x), fq.from_mont_int(y), fq.from_mont_int(z))


def g2_from_dev(p: Point, idx=None):
    x, y, z, inf = [np.asarray(v) for v in p]
    if idx is not None:
        x, y, z, inf = x[idx], y[idx], z[idx], inf[idx]
    if int(inf):
        return ((1, 0), (1, 0), (0, 0))
    return (fq2.from_mont_int(x), fq2.from_mont_int(y), fq2.from_mont_int(z))


def _scalars_to_bits_np(scalars, nbits: int) -> np.ndarray:
    """(N, nbits) int32 LSB-first bit matrix, vectorized."""
    nbytes = (nbits + 7) // 8
    for s in scalars:
        assert 0 <= s < (1 << nbits)
    if not scalars:
        return np.zeros((0, nbits), dtype=np.int32)
    data = np.frombuffer(
        b"".join(s.to_bytes(nbytes, "little") for s in scalars), dtype=np.uint8
    ).reshape(len(scalars), nbytes)
    return np.unpackbits(data, axis=1, bitorder="little")[:, :nbits].astype(
        np.int32
    )


def scalars_to_bits(scalars, nbits: int) -> jnp.ndarray:
    """Host: list of ints -> (N, nbits) int32 MSB-first bit matrix."""
    return jnp.asarray(_scalars_to_bits_np(scalars, nbits)[:, ::-1].copy())
