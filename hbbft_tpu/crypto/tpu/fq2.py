"""Batched Fq2 = Fq[u]/(u^2+1) arithmetic on TPU limbs.

Elements are ``(..., 2, NL)`` int32 limb arrays (c0 + c1·u), components in
Montgomery form.  Componentwise ops lift directly from :mod:`fq` (they act
on the last axis); mul/sqr use Karatsuba (3 base muls).

Mirrors the oracle tower in ``hbbft_tpu/crypto/bls/fields.py``.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from hbbft_tpu.crypto.tpu import fq

NL = fq.NL

ZERO = np.zeros((2, NL), dtype=np.int32)
ONE = np.stack([fq.ONE_MONT, fq.ZERO])


def to_mont_np(c: tuple) -> np.ndarray:
    """Host: oracle (c0, c1) int tuple -> (2, NL) Montgomery limbs."""
    return np.stack([fq.to_mont_np(c[0]), fq.to_mont_np(c[1])])


def from_mont_int(a) -> tuple:
    arr = np.asarray(a)
    return (fq.from_mont_int(arr[..., 0, :]), fq.from_mont_int(arr[..., 1, :]))


add = fq.add
sub = fq.sub
neg = fq.neg
small_mul = fq.small_mul
normalize = fq.normalize


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    a0, a1 = a[..., 0, :], a[..., 1, :]
    b0, b1 = b[..., 0, :], b[..., 1, :]
    t0 = fq.mont_mul(a0, b0)
    t1 = fq.mont_mul(a1, b1)
    t2 = fq.mont_mul(fq.add(a0, a1), fq.add(b0, b1))
    c0 = fq.sub(t0, t1)
    c1 = fq.sub(fq.sub(t2, t0), t1)
    return jnp.stack([c0, c1], axis=-2)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    a0, a1 = a[..., 0, :], a[..., 1, :]
    t = fq.mont_mul(a0, a1)
    c0 = fq.mont_mul(fq.add(a0, a1), fq.sub(a0, a1))
    c1 = fq.add(t, t)
    return jnp.stack([c0, c1], axis=-2)


def mul_fq(a: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
    """Multiply by a base-field scalar s: (..., NL)."""
    return jnp.stack(
        [fq.mont_mul(a[..., 0, :], s), fq.mont_mul(a[..., 1, :], s)], axis=-2
    )


def conj(a: jnp.ndarray) -> jnp.ndarray:
    """Frobenius on Fq2: c0 - c1·u."""
    return jnp.stack([a[..., 0, :], fq.neg(a[..., 1, :])], axis=-2)


def mul_by_xi(a: jnp.ndarray) -> jnp.ndarray:
    """Multiply by the sextic non-residue xi = 1 + u."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    return jnp.stack([fq.sub(a0, a1), fq.add(a0, a1)], axis=-2)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return fq.is_zero(a[..., 0, :]) & fq.is_zero(a[..., 1, :])


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """(a0 + a1·u)^-1 = (a0 - a1·u) / (a0^2 + a1^2).  One Fq inversion."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    norm = fq.add(fq.mont_sqr(a0), fq.mont_sqr(a1))
    ninv = fq.inv(norm)
    return jnp.stack(
        [fq.mont_mul(a0, ninv), fq.neg(fq.mont_mul(a1, ninv))], axis=-2
    )
