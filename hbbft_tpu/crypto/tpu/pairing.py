"""Batched optimal-ate pairing on TPU: Fq12 tower, Miller loop, final exp.

Device counterpart of the oracle in ``hbbft_tpu/crypto/bls/pairing.py``
(same math, re-architected for XLA):

* Fq12 elements are ``(..., 6, 2, NL)`` limb arrays (coefficients of w,
  ``w^6 = xi``), so a full Fq12 multiply is ONE batched Fq2 multiply over
  the 6x6 coefficient cross (3 ``mont_mul`` dispatches) plus cheap
  anti-diagonal reductions — the TPU sees wide vector ops, not 36 scalar
  multiplies.
* The Miller loop is a ``lax.scan`` over the 63 fixed bits of |x| with a
  branch-free conditional addition step; T is tracked in Jacobian
  coordinates and every line is scaled by a nonzero Fq2 factor (killed by
  the final exponentiation), so there are NO field inversions in the loop.
* The final exponentiation's hard part uses the verified identity
      3·(p^4 - p^2 + 1)/r = (x-1)^2·(x+p)·(x^2+p^2-1) + 3
  (checked against the integer value at import).  Raising to 3·hard
  instead of hard is sound for the ==1 check because 3 ∤ p^4-p^2+1, so
  cubing is a bijection on the cyclotomic subgroup.

Everything is batched over a leading "pairs" axis; the pairing-product
check shares one final exponentiation across all pairs (as the oracle's
``multi_pairing_is_one`` does).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hbbft_tpu.crypto.bls import fields as OF
from hbbft_tpu.crypto.bls.fields import BLS_X, P, R
from hbbft_tpu.crypto.tpu import curve as dcurve
from hbbft_tpu.crypto.tpu import fq, fq2

NL = fq.NL
X_ABS = -BLS_X

# The hard-part chain identity (module docstring); kept as an executable
# guard so a wrong refactor of the chain can't silently ship.
assert 3 * ((P**4 - P**2 + 1) // R) == (BLS_X - 1) ** 2 * (BLS_X + P) * (
    BLS_X**2 + P**2 - 1
) + 3
assert (P**4 - P**2 + 1) % 3 != 0

# Bits of |x| below the MSB, MSB-first — the Miller/x-exp schedule.
X_BITS = np.array([int(b) for b in bin(X_ABS)[3:]], dtype=np.int32)


def _bit_runs(bits) -> Tuple[Tuple[int, bool], ...]:
    """Static run-length form of an MSB-first bit schedule: maximal runs
    of steps where only the LAST bit is set -> (run_length, ends_set).

    |x| has hamming weight 6, so the 63-step double-and-add schedules
    (Miller loop, x-exponentiation) are really 63 doubling-class steps
    with only FIVE add-class steps.  The branch-free scan form this
    replaces computed the add arm + a select at every step — about half
    the fixed per-flush pairing cost, paid 58 times for nothing.
    """
    runs = []
    count = 0
    for b in bits:
        count += 1
        if b:
            runs.append((count, True))
            count = 0
    if count:
        runs.append((count, False))
    return tuple(runs)


X_RUNS = _bit_runs(X_BITS)

FQ12_ONE = np.zeros((6, 2, NL), dtype=np.int32)
FQ12_ONE[0, 0] = fq.ONE_MONT


@lru_cache(maxsize=None)
def _gamma_dev(k: int) -> np.ndarray:
    """Frobenius constants gamma[k][i] = xi^(i(p^k-1)/6) as device limbs."""
    g = OF._gamma(k)
    return np.stack([fq2.to_mont_np(c) for c in g])


# ---------------------------------------------------------------------------
# Fq12 arithmetic
# ---------------------------------------------------------------------------


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Full Fq12 multiply: one batched 6x6 Fq2 cross + xi-reduction."""
    prod = fq2.mul(a[..., :, None, :, :], b[..., None, :, :, :])
    return _reduce_cross(prod, np.arange(6), np.arange(6))


def _reduce_cross(prod: jnp.ndarray, ioffs: np.ndarray, joffs: np.ndarray) -> jnp.ndarray:
    """Sum prod[..., i, j, :, :] into w^(ioffs[i]+joffs[j]) buckets and
    fold w^(6+k) = xi·w^k.  Raw limb sums stay far inside int32."""
    out_lo = [None] * 6
    out_hi = [None] * 6
    for i, io in enumerate(ioffs):
        for j, jo in enumerate(joffs):
            k = int(io + jo)
            term = prod[..., i, j, :, :]
            if k < 6:
                out_lo[k] = term if out_lo[k] is None else out_lo[k] + term
            else:
                out_hi[k - 6] = term if out_hi[k - 6] is None else out_hi[k - 6] + term
    coeffs = []
    for k in range(6):
        lo = out_lo[k]
        hi = out_hi[k]
        if lo is None and hi is None:
            raise AssertionError("empty bucket")
        if hi is None:
            coeffs.append(fq2.normalize(lo))
        elif lo is None:
            coeffs.append(fq2.mul_by_xi(hi))
        else:
            coeffs.append(fq.add(lo, fq2.mul_by_xi(hi)))
    return jnp.stack(coeffs, axis=-3)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def sparse_mul(a: jnp.ndarray, l0: jnp.ndarray, l2: jnp.ndarray, l3: jnp.ndarray) -> jnp.ndarray:
    """a · (l0 + l2·w^2 + l3·w^3) — the Miller-line shape."""
    l = jnp.stack([l0, l2, l3], axis=-3)
    prod = fq2.mul(a[..., :, None, :, :], l[..., None, :, :, :])
    return _reduce_cross(prod, np.arange(6), np.array([0, 2, 3]))


def conj(a: jnp.ndarray) -> jnp.ndarray:
    """a^(p^6): inverse on the cyclotomic unit circle."""
    return frobenius(a, 6)


def frobenius(a: jnp.ndarray, k: int) -> jnp.ndarray:
    g = jnp.asarray(_gamma_dev(k))
    c = fq2.conj(a) if k % 2 == 1 else a
    return fq2.mul(c, g)


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """Inverse via the norm to Fq2 (mirrors the oracle's fq12_inv)."""
    prod_conj = None
    for k in (2, 4, 6, 8, 10):
        fr = frobenius(a, k)
        prod_conj = fr if prod_conj is None else mul(prod_conj, fr)
    norm12 = mul(a, prod_conj)
    ninv = fq2.inv(norm12[..., 0, :, :])
    return fq2.mul(prod_conj, ninv[..., None, :, :])


def pow_x_abs(f: jnp.ndarray) -> jnp.ndarray:
    """f^|x| — square-only scan runs + a mul at each of the 5 set bits
    (the static schedule X_RUNS; identical math to bit-at-a-time
    square-and-multiply, ~45% fewer Fq12 ops)."""

    def sq(acc, _):
        return sqr(acc), None

    acc = f
    for length, ends_set in X_RUNS:
        acc, _ = jax.lax.scan(sq, acc, None, length=length)
        if ends_set:
            acc = mul(acc, f)
    return acc


def pow_x(f: jnp.ndarray) -> jnp.ndarray:
    """f^x for the (negative) BLS parameter; f must be unitary."""
    return conj(pow_x_abs(f))


def is_one(a: jnp.ndarray) -> jnp.ndarray:
    """Batched check a == 1 (sequential scans; once per flush)."""
    ok = fq.is_zero(fq.sub(a[..., 0, 0, :], jnp.asarray(fq.ONE_MONT)))
    ok = ok & fq.is_zero(a[..., 0, 1, :])
    for i in range(1, 6):
        ok = ok & fq2.is_zero(a[..., i, :, :])
    return ok


# ---------------------------------------------------------------------------
# Miller loop (Jacobian T on the twist, scaled lines)
# ---------------------------------------------------------------------------


def miller_loop(px: jnp.ndarray, py: jnp.ndarray, qx: jnp.ndarray, qy: jnp.ndarray) -> jnp.ndarray:
    """f_{|x|,Q}(P) conjugated for x<0; batched over leading axes.

    px, py: (..., NL) affine G1; qx, qy: (..., 2, NL) affine twist point.
    Lines are scaled by 2YZ^3 (doubling) and HZ (addition) — nonzero Fq2
    factors the final exponentiation kills (oracle docstring, and e.g.
    upstream threshold_crypto's pairing backend relies on the same fact).
    """
    px_neg = fq.neg(px)
    one = jnp.broadcast_to(jnp.asarray(fq2.ONE), qx.shape)
    f0 = jnp.broadcast_to(jnp.asarray(FQ12_ONE), (*qx.shape[:-2], 6, 2, NL))

    def dbl_step(X, Y, Z, f):
        A = fq2.sqr(X)
        B = fq2.sqr(Y)
        Z1Z1 = fq2.sqr(Z)
        l0 = fq.sub(fq2.small_mul(fq2.mul(X, A), 3), fq2.small_mul(B, 2))
        l2 = fq.neg(fq2.mul_fq(fq2.small_mul(fq2.mul(A, Z1Z1), 3), px))
        Znew = fq2.small_mul(fq2.mul(Y, Z), 2)
        l3 = fq2.mul_fq(fq2.mul(Znew, Z1Z1), py)
        C = fq2.sqr(B)
        D = fq2.small_mul(fq.sub(fq.sub(fq2.sqr(fq.add(X, B)), A), C), 2)
        E = fq2.small_mul(A, 3)
        F = fq2.sqr(E)
        X3 = fq.sub(F, fq2.small_mul(D, 2))
        Y3 = fq.sub(fq2.mul(E, fq.sub(D, X3)), fq2.small_mul(C, 8))
        f = sqr(f)
        f = sparse_mul(f, l0, l2, l3)
        return X3, Y3, Znew, f

    def add_step(X, Y, Z, f):
        Z1Z1 = fq2.sqr(Z)
        U2 = fq2.mul(qx, Z1Z1)
        S2 = fq2.mul(qy, fq2.mul(Z, Z1Z1))
        H = fq.sub(U2, X)
        theta = fq.sub(S2, Y)
        HZ = fq2.mul(H, Z)
        l0 = fq.sub(fq2.mul(theta, qx), fq2.mul(qy, HZ))
        l2 = fq2.mul_fq(theta, px_neg)
        l3 = fq2.mul_fq(HZ, py)
        HH = fq2.sqr(H)
        I = fq2.small_mul(HH, 4)
        J = fq2.mul(H, I)
        rr = fq2.small_mul(theta, 2)
        V = fq2.mul(X, I)
        X3 = fq.sub(fq.sub(fq2.sqr(rr), J), fq2.small_mul(V, 2))
        Y3 = fq.sub(fq2.mul(rr, fq.sub(V, X3)), fq2.small_mul(fq2.mul(Y, J), 2))
        Z3 = fq2.small_mul(fq2.mul(Z, H), 2)
        f = sparse_mul(f, l0, l2, l3)
        return X3, Y3, Z3, f

    def dbl_only(carry, _):
        return dbl_step(*carry), None

    # Static X_RUNS schedule: double-only scan runs with the add step
    # unrolled at the 5 set bits of |x| — same result as the per-bit
    # branch-free form, without computing + discarding 58 add arms.
    carry = (qx, qy, one, f0)
    for length, ends_set in X_RUNS:
        carry, _ = jax.lax.scan(dbl_only, carry, None, length=length)
        if ends_set:
            carry = add_step(*carry)
    f = carry[3]
    # x < 0: f_{x,Q} = conjugate(f_{|x|,Q})
    return conj(f)


# ---------------------------------------------------------------------------
# Final exponentiation
# ---------------------------------------------------------------------------


def final_exp_is_one(f: jnp.ndarray) -> jnp.ndarray:
    """Is f^((p^12-1)/r) == 1?  Uses the 3·hard chain (module docstring)."""
    # Easy part: f^((p^6-1)(p^2+1)); result is unitary.
    f1 = mul(conj(f), inv(f))
    m = mul(frobenius(f1, 2), f1)
    # Hard part to the power 3·(p^4-p^2+1)/r = (x-1)^2(x+p)(x^2+p^2-1)+3.
    a = mul(pow_x(m), conj(m))                # m^(x-1)
    b = mul(pow_x(a), conj(a))                # a^(x-1)
    c = mul(pow_x(b), frobenius(b, 1))        # b^(x+p)
    d = pow_x(pow_x(c))                       # c^(x^2)
    g = mul(mul(d, frobenius(c, 2)), conj(c))  # c^(x^2+p^2-1)
    res = mul(g, mul(sqr(m), m))              # · m^3
    return is_one(res)


# ---------------------------------------------------------------------------
# Affine conversion + pairing-product check
# ---------------------------------------------------------------------------


def g1_affine(p: dcurve.Point) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Jacobian G1 -> affine; identity becomes garbage (caller gates on
    the inf flag).  One Fq inversion (Fermat scan)."""
    x, y, z, _inf = p
    zi = fq.inv(fq.normalize(z))
    zi2 = fq.mont_sqr(zi)
    return fq.mont_mul(x, zi2), fq.mont_mul(y, fq.mont_mul(zi2, zi))


def g2_affine(p: dcurve.Point) -> Tuple[jnp.ndarray, jnp.ndarray]:
    x, y, z, _inf = p
    zi = fq2.inv(fq2.normalize(z))
    zi2 = fq2.sqr(zi)
    return fq2.mul(x, zi2), fq2.mul(y, fq2.mul(zi2, zi))


def miller_product(g1s: dcurve.Point, g2s: dcurve.Point) -> jnp.ndarray:
    """prod_i f_{x,Q_i}(P_i) over the batch axis — the pairing product
    BEFORE the final exponentiation (one Fq12 element).

    Pairs where either side is the identity contribute the factor 1
    (mirrors the oracle's multi_pairing_is_one None-skip).  Splitting
    this from :func:`final_exp_is_one` lets a caller combine several
    independently-computed Miller products and pay ONE final
    exponentiation for all of them (the TpuBackend cross-chunk flush).
    """
    px, py = g1_affine(g1s)
    qx, qy = g2_affine(g2s)
    fs = miller_loop(px, py, qx, qy)
    skip = (g1s[3] | g2s[3]).astype(bool)
    one = jnp.broadcast_to(jnp.asarray(FQ12_ONE), fs.shape)
    fs = jnp.where(skip.reshape(skip.shape + (1, 1, 1)), one, fs)
    acc = fs[0]
    for i in range(1, fs.shape[0]):
        acc = mul(acc, fs[i])
    return acc


def pairing_product_is_one(g1s: dcurve.Point, g2s: dcurve.Point) -> jnp.ndarray:
    """prod_i e(P_i, Q_i) == 1 over a batch axis; one final exponentiation."""
    return final_exp_is_one(miller_product(g1s, g2s))
