"""TpuBackend: the north-star CryptoBackend (BASELINE.json:5).

Same random-linear-combination batch verification as
:class:`hbbft_tpu.crypto.backend.BatchedBackend` — identical Fiat-Shamir
coefficients, identical leg algebra, bisection fallback on failure — but
the heavy group algebra runs on the accelerator in ONE jitted kernel:

* every share/key/ciphertext point is scaled by its 128-bit RLC
  coefficient with a batched LSB-first double-and-add scan that
  SIMULTANEOUSLY computes the endomorphism-check chain (``[x^2]P`` on
  G1, ``[|x|]Q`` on G2 — both fit the same 128-bit width) off the same
  doubling chain — the subgroup (r-torsion) check for wire-sourced
  points runs on device as the standard phi/psi endomorphism tests
  (``bls.curve.g1_in_subgroup`` notes), batched, instead of as
  per-request Python scalar-mults on the host (which cost more than
  the entire device flush: BASELINE.md round-1 measurements); the
  endomorphism form halves the scan vs the round-2 ``[r-1]P`` chain,
* per-leg sums are masked tree reductions,
* the 1 + L pairing-product legs run through the batched Miller loop and
  one shared final exponentiation.

Kernel shapes are bucketed to powers of two so recompilation is bounded;
compiled kernels are cached per (n_g1, n_g2, n_legs) bucket.

Multi-chip: with ``shard=True`` (or ``HBBFT_TPU_SHARD=1``) and more than
one visible device, the batch axis is laid out over a data-parallel
``jax.sharding.Mesh`` — the scalar-mul scans run fully parallel per
shard and XLA inserts the collectives for the tree reductions (SURVEY.md
§2 parallelism note: batching over the share dimension IS this
framework's parallelism axis).

Replaces the per-share CPU pairing checks of upstream
``threshold_crypto`` (``src/lib.rs`` verify paths; SURVEY.md §2 #14).
"""

from __future__ import annotations

import os
import warnings
from functools import lru_cache
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hbbft_tpu.crypto.backend import (
    DEC_SHARE,
    SIG_SHARE,
    BatchedBackend,
    CryptoBackend,
    EagerBackend,
    VerifyRequest,
    _batch_coefficients,
    request_well_formed,
)
from hbbft_tpu.crypto.bls import curve as ocurve
from hbbft_tpu.crypto.bls.suite import BLSSuite
from hbbft_tpu.crypto.tpu import curve as dcurve
from hbbft_tpu.crypto.tpu import pairing as dpairing
from hbbft_tpu.utils import canonical_bytes

NBITS = 128  # RLC coefficient width


def _bucket(n: int, floor: int = 16) -> int:
    """Round up to a power of two (with a floor) to bound recompiles.

    The floor matters for bisection: all small sub-batches pad to the
    same shape and reuse one compiled kernel instead of compiling a
    fresh kernel per subset size."""
    b = floor
    while b < n:
        b *= 2
    return b


@lru_cache(maxsize=32)
def _scan_kernel(n_g1: int, n_g2: int, n_legs: int):
    """Compiled SCAN stage for one shape bucket (per-row work).

    Inputs (all device arrays):
      g1 pts (n_g1 batched G1 Jacobian+flag), g1 bits (n_g1, ENDO_NBITS
      = 128; the RLC coefficient), g1 subgroup-check mask (n_g1,), g1
      leg one-hot (n_legs, n_g1); g2 pts / bits / mask (n_g2 …) — the
      generator leg; rhs G2 points (n_legs) each G1 leg sum pairs with;
      the G1 generator.
    Returns (sub_ok, lhs, rhs): the aggregate subgroup verdict for every
    masked wire-sourced point (batched r-torsion on device — a Python
    subgroup check per request costs more than the whole device flush),
    and the (1 + n_legs) pairing pairs this chunk contributes.  The
    pairing itself is the separate :func:`_pair_kernel` stage so several
    chunks' pairs can share ONE batched Miller loop + final
    exponentiation (round-5 fixed-cost amortization; the stage split is
    also what the per-stage timing in BASELINE.md measures).
    """

    def run(
        g1_pts, g1_bits, g1_chk, seg,
        g2_pts, g2_bits_s, g2_bits_q, g2_chk, rhs_g2, gen_pt,
    ):
        # Round-4 scans (dcurve "static-endo flush scans" notes): G1 is
        # one LSB-first shared-doubling scan with the [x^2]P check-chain
        # adds unrolled at x^2's 17 static set bits; G2 splits each RLC
        # coefficient as c = q·|x| + s against the psi endomorphism —
        # a 65-step two-scalar scan (~60% fewer Fq2 ops than the shared
        # 128-step scan of rounds 2-3).  Soundness: the psi(Q) = [x]Q
        # identity the decomposition relies on IS the subgroup check
        # verified in this same kernel (fail-closed; see dcurve notes).
        # Equivalence + soundness pinned in tests/test_bls.py and
        # tests/test_tpu_crypto.py.
        scaled1, chain1 = dcurve.scalar_mul_rlc_g1(g1_pts, g1_bits)
        scaled2, chain2 = dcurve.scalar_mul_rlc_g2(g2_pts, g2_bits_s, g2_bits_q)
        sub1 = dcurve.endo_subgroup_eq(dcurve.G1_OPS, g1_pts, chain1)
        sub2 = dcurve.endo_subgroup_eq(dcurve.G2_OPS, g2_pts, chain2)
        sub_ok = jnp.all(sub1 | (g1_chk == 0)) & jnp.all(sub2 | (g2_chk == 0))
        gen_leg = dcurve.tree_sum(dcurve.G2_OPS, scaled2)
        leg_sums = []
        for l in range(n_legs):
            masked = dcurve.select(
                seg[l], scaled1, dcurve.identity(dcurve.G1_OPS, (n_g1,)), dcurve.G1_OPS
            )
            leg_sums.append(dcurve.tree_sum(dcurve.G1_OPS, masked))
        # Pair list: (gen, gen_leg) + (leg_sum_l, rhs_l).
        lhs = tuple(
            jnp.stack([gen_pt[c]] + [p[c] for p in leg_sums]) for c in range(4)
        )
        rhs = tuple(
            jnp.concatenate([jnp.stack([gen_leg[c]]), rhs_g2[c]]) for c in range(4)
        )
        return sub_ok, lhs, rhs

    return jax.jit(run)


@lru_cache(maxsize=32)
def _pair_kernel(n_pairs: int):
    """Compiled PAIR stage: batched Miller loop over ``n_pairs`` pairing
    pairs + ONE shared final exponentiation -> product == 1."""

    def run(lhs, rhs):
        return dpairing.pairing_product_is_one(lhs, rhs)

    return jax.jit(run)


def _pairs_bucket(n: int) -> int:
    """Pair-count bucket: exact for small counts, multiples of 8 above.

    Small flushes (one chunk: 1 + n_legs = 3/5/9 pairs) keep their exact
    size — on the 1-core virtual-CPU test platform every padded pair is
    a real 63-step Miller loop per execution (CLAUDE.md: the floor-8
    experiment made the suite strictly worse).  Multi-chunk combines pad
    to a multiple of 8 so the compile count stays bounded; padded pairs
    are identity pairs (factor 1 via the skip mask) and on TPU their
    cost rides the already-batched lanes.
    """
    return n if n <= 9 else (n + 7) // 8 * 8


def _shard_mesh(max_devices: int = 16):
    """Data-parallel mesh over the largest power-of-two device prefix.

    Capped at the kernel's minimum batch bucket (floor 16 in ``_bucket``)
    so the batch axis is always divisible by the mesh — a 32-way mesh
    over a 16-row bucket would make ``device_put`` raise on every small
    flush.
    """
    from jax.sharding import Mesh

    devs = jax.devices()
    n = 1
    while n * 2 <= min(len(devs), max_devices):
        n *= 2
    if n == 1:
        return None
    return Mesh(np.array(devs[:n]).reshape(n), axis_names=("dp",))


class TpuBackend(CryptoBackend):
    """RLC batch verification with the group algebra on the accelerator.

    ``shard=True`` (or env ``HBBFT_TPU_SHARD=1``) lays the batch axis
    over all visible devices data-parallel; default is single-device.
    """

    def __init__(
        self, suite: BLSSuite | None = None, shard: bool | None = None
    ) -> None:
        import os

        self.suite = suite or BLSSuite()
        self._eager = EagerBackend(self.suite)
        if shard is None:
            shard = os.environ.get("HBBFT_TPU_SHARD") == "1"
        self._mesh = _shard_mesh() if shard else None

    # -- leg construction (host, cheap): mirrors backend._rlc_pairs ----

    def _build_legs(self, reqs: Sequence[VerifyRequest], coeffs: Sequence[int]):
        """Returns (g2_entries, g1_entries, rhs_points).

        g2_entries: list of (scalar, oracle G2 jac, check) summed against
        the G1 generator.  g1_entries: (scalar, oracle G1 jac, leg_id,
        check).  rhs_points[leg_id]: oracle G2 jac each G1 leg pairs with.
        ``check`` = 1 marks wire-sourced points that need the device-side
        r-torsion check (shares, ciphertext points); locally-derived
        points (public-key shares, hash-to-curve outputs) are exempt.
        """
        g2_entries: List[Tuple[int, Any, int]] = []
        g1_entries: List[Tuple[int, Any, int, int]] = []
        rhs: List[Any] = []
        leg_of: Dict[bytes, int] = {}

        def leg(key: bytes, point_jac: Any) -> int:
            if key not in leg_of:
                leg_of[key] = len(rhs)
                rhs.append(point_jac)
            return leg_of[key]

        for r, c in zip(reqs, coeffs):
            if r.kind == SIG_SHARE:
                pk, msg, share = r.payload
                g2_entries.append((c, share.g2.jac, 1))
                l = leg(canonical_bytes(b"m", msg), self.suite.hash_to_g2(msg).jac)
                g1_entries.append((c, (-pk.g1).jac, l, 0))
            elif r.kind == DEC_SHARE:
                pk, ct, share = r.payload
                l = leg(
                    canonical_bytes(b"c", ct.hash_input()),
                    self.suite.hash_to_g2(ct.hash_input()).jac,
                )
                g1_entries.append((c, share.g1.jac, l, 1))
                lw = leg(canonical_bytes(b"w", ct.w.to_bytes()), ct.w.jac)
                g1_entries.append((c, (-pk.g1).jac, lw, 0))
            else:
                (ct,) = r.payload
                g2_entries.append((c, ct.w.jac, 1))
                l = leg(
                    canonical_bytes(b"c", ct.hash_input()),
                    self.suite.hash_to_g2(ct.hash_input()).jac,
                )
                # -U is in the subgroup iff U is.
                g1_entries.append((c, (-ct.u).jac, l, 1))
        return g2_entries, g1_entries, rhs

    def _aggregate_ok(self, reqs: Sequence[VerifyRequest]) -> bool:
        return bool(self._check_parts([self._scan_dev(reqs)]))

    def _scan_dev(self, reqs: Sequence[VerifyRequest]):
        """Dispatch one chunk's SCAN kernel; returns (sub_ok, lhs, rhs)
        device values WITHOUT forcing a host sync, so independent chunks
        pipeline on device."""
        (n1, n2, nl), args = self._scan_prep(reqs)
        return _scan_kernel(n1, n2, nl)(*args)

    def _scan_prep(self, reqs: Sequence[VerifyRequest]):
        """Host prep for one chunk: returns ((n1, n2, nl), kernel args).
        Split from :meth:`_scan_dev` so measurement tooling
        (benchmarks/flush_roofline.py) can lower the cached kernel on
        the exact production inputs."""
        coeffs = _batch_coefficients(self.suite, reqs)
        g2e, g1e, rhs = self._build_legs(reqs, coeffs)
        n1 = _bucket(max(len(g1e), 1))
        n2 = _bucket(max(len(g2e), 1))
        # Legs become pairing-product pairs (a Miller loop each, even
        # when identity-padded), so keep their floor LOW: on the 1-core
        # virtual-CPU test platform every padded leg costs real execution
        # minutes across the suite (a floor-8 experiment tripled warm
        # suite time).  The cost side — one ~7-min cold compile per
        # distinct legs bucket (2/4/8 under bisection) — is paid once and
        # covered by benchmarks/warm_crypto_cache.py + the persistent
        # .jax_cache.
        nl = _bucket(max(len(rhs), 1), floor=2)
        ident1 = (1, 1, 0)
        ident2 = ((1, 0), (1, 0), (0, 0))
        g1_pts = dcurve.g1_to_dev(
            [p for _, p, _, _ in g1e] + [ident1] * (n1 - len(g1e))
        )
        g1_bits = dcurve.scalars_to_bits_lsb(
            [s for s, _, _, _ in g1e] + [0] * (n1 - len(g1e)), dcurve.ENDO_NBITS
        )
        g1_chk = np.zeros(n1, dtype=np.int32)
        seg = np.zeros((nl, n1), dtype=np.int32)
        for i, (_, _, l, chk) in enumerate(g1e):
            seg[l, i] = 1
            g1_chk[i] = chk
        g2_pts = dcurve.g2_to_dev(
            [p for _, p, _ in g2e] + [ident2] * (n2 - len(g2e))
        )
        sq = [dcurve.decompose_g2_scalar(s) for s, _, _ in g2e]
        sq += [(0, 0)] * (n2 - len(g2e))
        g2_bits_s = dcurve.scalars_to_bits(
            [s for s, _ in sq], dcurve.G2_SCAN_NBITS
        )
        g2_bits_q = dcurve.scalars_to_bits(
            [q for _, q in sq], dcurve.G2_SCAN_NBITS
        )
        g2_chk = np.zeros(n2, dtype=np.int32)
        for i, (_, _, chk) in enumerate(g2e):
            g2_chk[i] = chk
        rhs_pts = dcurve.g2_to_dev(rhs + [ident2] * (nl - len(rhs)))
        gen_pt = dcurve.g1_to_dev([ocurve.G1_GEN])
        gen_pt = tuple(x[0] for x in gen_pt)
        g1_chk = jnp.asarray(g1_chk)
        seg = jnp.asarray(seg)
        g2_chk = jnp.asarray(g2_chk)
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as PS

            batch = NamedSharding(self._mesh, PS("dp"))
            seg_sh = NamedSharding(self._mesh, PS(None, "dp"))
            repl = NamedSharding(self._mesh, PS())

            def put(x, sh):
                return jax.device_put(x, sh)

            g1_pts = tuple(put(c, batch) for c in g1_pts)
            g2_pts = tuple(put(c, batch) for c in g2_pts)
            g1_bits = put(g1_bits, batch)
            g2_bits_s = put(g2_bits_s, batch)
            g2_bits_q = put(g2_bits_q, batch)
            g1_chk = put(g1_chk, batch)
            g2_chk = put(g2_chk, batch)
            seg = put(seg, seg_sh)
            rhs_pts = tuple(put(c, repl) for c in rhs_pts)
            gen_pt = tuple(put(c, repl) for c in gen_pt)
        return (n1, n2, nl), (
            g1_pts, g1_bits, g1_chk, seg,
            g2_pts, g2_bits_s, g2_bits_q, g2_chk, rhs_pts, gen_pt,
        )

    def _check_parts(self, parts) -> Any:
        """Combine one or more chunks' (sub_ok, lhs, rhs) scan outputs
        into a single device verdict: batched Miller loop over ALL pairs
        + ONE final exponentiation, AND of every chunk's subgroup bit.

        Soundness of the cross-chunk product check: each chunk is an RLC
        with Fiat-Shamir coefficients committed to that chunk's request
        contents, so the combined product == 1 test is one RLC over the
        union with blockwise-committed coefficients — an adversary must
        still grind the hash for an exact mod-r cancellation across the
        union (the same 2^-128-class bound as a single chunk; defects
        from duplicated content ADD with equal coefficients, they cannot
        cancel).  On any False the caller re-checks per chunk, so
        verdicts are identical to the per-chunk path.
        """
        sub_oks = [p[0] for p in parts]
        if len(parts) == 1:
            lhs, rhs = parts[0][1], parts[0][2]
        else:
            lhs = tuple(
                jnp.concatenate([p[1][c] for p in parts]) for c in range(4)
            )
            rhs = tuple(
                jnp.concatenate([p[2][c] for p in parts]) for c in range(4)
            )
        n = int(lhs[3].shape[0])
        b = _pairs_bucket(n)
        if b > n:
            pad1 = dcurve.identity(dcurve.G1_OPS, (b - n,))
            pad2 = dcurve.identity(dcurve.G2_OPS, (b - n,))
            lhs = tuple(
                jnp.concatenate([lhs[c], pad1[c]]) for c in range(4)
            )
            rhs = tuple(
                jnp.concatenate([rhs[c], pad2[c]]) for c in range(4)
            )
        ok = _pair_kernel(b)(lhs, rhs)
        for s in sub_oks:
            ok = ok & s
        return ok

    # -- public API ----------------------------------------------------

    # Per-flush device sweet spot (measured on the chip, BASELINE.md
    # round-4 battery): giant flushes split into chunks, each with its
    # own Fiat-Shamir coefficients, because per-row scan cost grows
    # with the bucket's working set (HBM pressure).  The round-4 kernel
    # moved the optimum from 4096 to 2048 (10240 shares: 1516/s at
    # 2048-chunks vs 1085/s at 4096 — the smaller bucket's per-row win
    # now outweighs the extra fixed pairing stages).  HBBFT_TPU_CHUNK
    # overrides for re-tuning.
    try:
        CHUNK = max(1, int(os.environ.get("HBBFT_TPU_CHUNK", "2048")))
    except ValueError:
        warnings.warn(
            "HBBFT_TPU_CHUNK is not an integer; falling back to 2048",
            stacklevel=1,
        )
        CHUNK = 2048

    def verify_batch(self, reqs: Sequence[VerifyRequest]) -> List[bool]:
        reqs = list(reqs)
        if not reqs:
            return []
        out = [False] * len(reqs)
        # Host: structure + on-curve only; the r-torsion checks run
        # batched inside the flush kernel (subgroup=False here).
        idxs = [
            i
            for i, r in enumerate(reqs)
            if request_well_formed(self.suite, r, subgroup=False)
        ]
        chunks = [idxs[s : s + self.CHUNK] for s in range(0, len(idxs), self.CHUNK)]
        # Dispatch every chunk's SCAN kernel before syncing on anything:
        # jax dispatch is async, so the device pipelines the chunks and
        # the host pays one round-trip total instead of one per chunk.
        scans = [self._scan_dev([reqs[i] for i in c]) for c in chunks]
        if len(chunks) > 1:
            # Fast path: ALL chunks' pairs through one batched Miller
            # loop + one final exponentiation (fixed pairing cost paid
            # once per flush, not once per chunk — _check_parts notes).
            if bool(self._check_parts(scans)):
                for c in chunks:
                    for i in c:
                        out[i] = True
                return out
        for c, part in zip(chunks, scans):
            if bool(self._check_parts([part])):
                for i in c:
                    out[i] = True
            else:
                self._bisect(reqs, c, out)
        return out

    def _bisect(
        self, all_reqs: List[VerifyRequest], idxs: List[int], out: List[bool]
    ) -> None:
        """Bisection fallback — the caller knows idxs' aggregate FAILED,
        so split immediately and aggregate only the halves."""
        if len(idxs) == 1:
            out[idxs[0]] = self._eager.verify_batch([all_reqs[idxs[0]]])[0]
            return
        mid = len(idxs) // 2
        for half in (idxs[:mid], idxs[mid:]):
            if self._aggregate_ok([all_reqs[i] for i in half]):
                for i in half:
                    out[i] = True
            else:
                self._bisect(all_reqs, half, out)


class HybridBackend(CryptoBackend):
    """Route each flush to the cheaper plane, fail over off-device.

    * Flushes with at least ``min_device_batch`` requests go to
      :class:`TpuBackend`; smaller ones go to the host
      :class:`~hbbft_tpu.crypto.backend.BatchedBackend` — small flushes
      are latency-dominated either way, and keeping them host-side
      avoids paying a fresh ~10-min XLA compile for every rare small
      shape bucket (measured, BASELINE.md round-3 battery).
    * If no accelerator platform is reachable at construction (the axon
      relay was down for rounds 1-2 straight), every flush rides the
      host path — protocols keep running, just without the device plane.

    Verdict-identical to both constituents by construction: every
    backend implements the same RLC/bisection semantics (pinned by
    tests/test_tpu_crypto.py + the backend-equivalence drive).
    """

    # Pass as ``device=`` to force host-only mode regardless of platform
    # (None means auto-detect, so it cannot express "no device").
    NO_DEVICE: Any = object()

    def __init__(
        self,
        suite: BLSSuite | None = None,
        min_device_batch: int = 64,
        device: CryptoBackend | None = None,
        host: CryptoBackend | None = None,
    ) -> None:
        self.suite = suite or BLSSuite()
        self.min_device_batch = min_device_batch
        self.host = host or BatchedBackend(self.suite)
        if device is HybridBackend.NO_DEVICE:
            self.device: CryptoBackend | None = None
        elif device is not None:
            self.device = device
        else:
            try:
                ok = jax.default_backend() not in ("", "cpu")
            except Exception:
                ok = False
            self.device = TpuBackend(self.suite) if ok else None

    def verify_batch(self, reqs: Sequence[VerifyRequest]) -> List[bool]:
        reqs = list(reqs)
        if self.device is not None and len(reqs) >= self.min_device_batch:
            try:
                return self.device.verify_batch(reqs)
            except Exception as exc:
                # Device died mid-run (the relay drops, historically) —
                # serve this and every later flush from the host plane.
                # Verdict-identical by construction, so the failover is
                # invisible to the protocol; warn so a genuine device
                # bug or OOM isn't silently masked by the degradation.
                warnings.warn(
                    "HybridBackend: device flush failed, failing over to "
                    f"host for the rest of the run: {exc!r}",
                    RuntimeWarning,
                    stacklevel=2,
                )
                self.device = None
        return self.host.verify_batch(reqs)
