"""Deferred-verification pool: the accumulate/flush contract.

Protocols submit :class:`~hbbft_tpu.crypto.backend.VerifyRequest`s together
with a callback ``cb(ok: bool) -> Step``; a flush runs the whole pending
batch through the backend in one go and merges the callback steps.  With an
eager flush policy (flush after every delivered message) the observable
behavior matches the reference's inline verification; with an epoch-flush
policy the TPU sees one big pairing batch (BASELINE.json:5).

Nested protocols (HoneyBadger -> Subset -> BinaryAgreement ->
ThresholdSign) receive *scoped* sinks: each wrapping layer lifts the child
step produced by a verification callback into the parent's message type via
the same step-processing logic used for ordinary child steps — so async
verification results flow up the stack exactly like messages do.
"""

from __future__ import annotations

from typing import Callable, List, Tuple

from hbbft_tpu.crypto.backend import CryptoBackend, VerifyRequest
from hbbft_tpu.protocols.traits import Step

Callback = Callable[[bool], Step]
Wrap = Callable[[Step], Step]


class VerifySink:
    """Interface protocols write verification requests to."""

    def submit(self, req: VerifyRequest, cb: Callback) -> None:
        raise NotImplementedError

    def scoped(self, wrap: Wrap) -> "VerifySink":
        return ScopedSink(self, wrap)


class ScopedSink(VerifySink):
    """Lifts callback steps through one protocol-nesting layer."""

    def __init__(self, inner: VerifySink, wrap: Wrap) -> None:
        self._inner = inner
        self._wrap = wrap

    def submit(self, req: VerifyRequest, cb: Callback) -> None:
        self._inner.submit(req, lambda ok: self._wrap(cb(ok)))


class VerifyPool(VerifySink):
    """Node-level pending-verification queue."""

    def __init__(self) -> None:
        self._items: List[Tuple[VerifyRequest, Callback]] = []

    def submit(self, req: VerifyRequest, cb: Callback) -> None:
        self._items.append((req, cb))

    def __len__(self) -> int:
        return len(self._items)

    def __bool__(self) -> bool:
        return bool(self._items)

    def flush(self, backend: CryptoBackend) -> Step:
        """Verify everything currently pending; returns the merged step.

        Callbacks may submit *new* requests (e.g. a decrypt started by a
        subset output); those stay queued for the next flush.
        """
        items, self._items = self._items, []
        step = Step.empty()
        if not items:
            return step
        results = backend.verify_batch([req for req, _ in items])
        for (req, cb), ok in zip(items, results):
            step.extend(cb(ok))
        return step
