"""Pluggable ``CryptoBackend``: batch verification of the hot pairing checks.

This is the north-star interface (BASELINE.json:5): protocols accumulate
signature-share / decryption-share / ciphertext verifications and a flush
verifies them as one batch.  Backends:

* :class:`EagerBackend` — per-item pairing checks via the suite (oracle).
* :class:`BatchedBackend` — random-linear-combination collapsing: all
  shares over the same message/ciphertext cost **two** pairings total; on
  aggregate failure it bisects to isolate the bad items (standard batch
  verification with fallback).
* ``TpuBackend`` (:mod:`hbbft_tpu.crypto.tpu`, later milestone) — same RLC
  algebra with scalar mults and Miller loops as vmapped JAX on TPU.

RLC coefficients are derived deterministically by Fiat-Shamir hashing of
the whole batch, so runs are reproducible and an adversary cannot predict
coefficients before committing to its shares.

Reference behavior being replaced: eager inline ``verify`` calls in
upstream ``src/threshold_sign.rs`` / ``src/threshold_decrypt.rs``.
"""

from __future__ import annotations

import abc
import hashlib
from dataclasses import dataclass
from typing import Any, Dict, List, Sequence, Tuple

from hbbft_tpu.crypto.keys import Ciphertext, DecryptionShare, PublicKeyShare, SignatureShare
from hbbft_tpu.crypto.suite import Suite
from hbbft_tpu.utils import canonical_bytes

SIG_SHARE = "sig_share"
DEC_SHARE = "dec_share"
CIPHERTEXT = "ciphertext"


@dataclass(frozen=True)
class VerifyRequest:
    """One deferred verification.

    kind == SIG_SHARE:  payload = (pk_share, msg_bytes, SignatureShare)
    kind == DEC_SHARE:  payload = (pk_share, Ciphertext, DecryptionShare)
    kind == CIPHERTEXT: payload = (Ciphertext,)
    """

    kind: str
    payload: Tuple[Any, ...]

    @staticmethod
    def sig_share(pk_share: PublicKeyShare, msg: bytes, share: SignatureShare) -> "VerifyRequest":
        return VerifyRequest(SIG_SHARE, (pk_share, msg, share))

    @staticmethod
    def dec_share(pk_share: PublicKeyShare, ct: Ciphertext, share: DecryptionShare) -> "VerifyRequest":
        return VerifyRequest(DEC_SHARE, (pk_share, ct, share))

    @staticmethod
    def ciphertext(ct: Ciphertext) -> "VerifyRequest":
        return VerifyRequest(CIPHERTEXT, (ct,))


class CryptoBackend(abc.ABC):
    """Verifies a batch of requests, returning one bool per request."""

    @abc.abstractmethod
    def verify_batch(self, reqs: Sequence[VerifyRequest]) -> List[bool]: ...


def request_well_formed(
    suite: Suite, req: VerifyRequest, subgroup: bool = True
) -> bool:
    """Structural validation of a request built from wire data.

    Byzantine peers can put arbitrary objects where group elements belong;
    anything that fails this check verifies as False instead of crashing
    the batch.  Full (subgroup) membership checks run only on the
    *wire-sourced* element of each request — the share itself, or the
    ciphertext points of a CIPHERTEXT check.  The public-key share is
    always derived locally from ``NetworkInfo`` and the ciphertext of a
    DEC_SHARE request was already vetted by a prior CIPHERTEXT request
    (``ThresholdDecrypt`` gates share submission on ciphertext validity),
    so those get the cheap structural check.

    ``subgroup=False`` skips the torsion checks entirely (on-curve and
    structure only) — for backends that run the subgroup checks
    themselves, batched on device (``TpuBackend``).  A host subgroup
    check costs one 255-bit scalar multiplication in Python PER REQUEST
    and dominates the whole flush otherwise.
    """
    if req.kind not in (SIG_SHARE, DEC_SHARE, CIPHERTEXT):
        raise ValueError(f"unknown request kind {req.kind!r}")  # local bug
    try:
        if req.kind == SIG_SHARE:
            pk, msg, share = req.payload
            return (
                isinstance(pk, PublicKeyShare)
                and suite.is_g1(pk.g1, check_subgroup=False)
                and isinstance(msg, bytes)
                and isinstance(share, SignatureShare)
                and suite.is_g2(share.g2, check_subgroup=subgroup)
            )
        if req.kind == DEC_SHARE:
            pk, ct, share = req.payload
            return (
                isinstance(pk, PublicKeyShare)
                and suite.is_g1(pk.g1, check_subgroup=False)
                and _ciphertext_well_formed(suite, ct, check_subgroup=False)
                and isinstance(share, DecryptionShare)
                and suite.is_g1(share.g1, check_subgroup=subgroup)
            )
        (ct,) = req.payload
        return _ciphertext_well_formed(suite, ct, check_subgroup=subgroup)
    except Exception:
        return False


def _ciphertext_well_formed(
    suite: Suite, ct: Any, check_subgroup: bool = True
) -> bool:
    return (
        isinstance(ct, Ciphertext)
        and suite.is_g1(ct.u, check_subgroup=check_subgroup)
        and isinstance(ct.v, bytes)
        and suite.is_g2(ct.w, check_subgroup=check_subgroup)
    )


class EagerBackend(CryptoBackend):
    """Per-item verification through the suite — the trusted slow path."""

    def __init__(self, suite: Suite) -> None:
        self.suite = suite

    def verify_batch(self, reqs: Sequence[VerifyRequest]) -> List[bool]:
        out = []
        for r in reqs:
            if not request_well_formed(self.suite, r):
                out.append(False)
            elif r.kind == SIG_SHARE:
                pk, msg, share = r.payload
                out.append(pk.verify_share(msg, share))
            elif r.kind == DEC_SHARE:
                pk, ct, share = r.payload
                out.append(pk.verify_decryption_share(ct, share))
            else:
                (ct,) = r.payload
                out.append(ct.verify())
        return out


def _warm_affine_caches(suite: Suite, reqs: Sequence[VerifyRequest]) -> None:
    """Batch-invert all points about to be serialized (one inversion per
    group instead of two ``pow(·, -1, p)`` per request)."""
    batch_affine = getattr(suite, "batch_affine", None)
    if batch_affine is None:
        return
    pts = []
    for r in reqs:
        for obj in r.payload:
            for attr in ("g1", "g2", "u", "w"):
                v = getattr(obj, attr, None)
                if v is not None:
                    pts.append(v)
    try:
        batch_affine(pts)
    except Exception:
        pass  # fall back to lazy per-element conversion


def _batch_coefficients(suite: Suite, reqs: Sequence[VerifyRequest]) -> List[int]:
    """Deterministic Fiat-Shamir RLC coefficients in [1, 2^128)."""
    _warm_affine_caches(suite, reqs)
    parts = []
    for r in reqs:
        if r.kind == SIG_SHARE:
            pk, msg, share = r.payload
            parts.append(canonical_bytes(r.kind, pk.to_bytes(), msg, share.to_bytes()))
        elif r.kind == DEC_SHARE:
            pk, ct, share = r.payload
            parts.append(canonical_bytes(r.kind, pk.to_bytes(), ct.to_bytes(), share.to_bytes()))
        else:
            (ct,) = r.payload
            parts.append(canonical_bytes(r.kind, ct.to_bytes()))
    seed = hashlib.sha3_256(canonical_bytes(b"rlc", *parts)).digest()
    coeffs = []
    for i in range(len(reqs)):
        h = hashlib.sha3_256(seed + i.to_bytes(8, "big")).digest()
        coeffs.append((int.from_bytes(h[:16], "big") | 1))  # odd => nonzero
    return coeffs


def _rlc_pairs(
    suite: Suite, reqs: Sequence[VerifyRequest], coeffs: Sequence[int]
) -> List[Tuple[Any, Any]]:
    """Build the pairing-product-==-1 pair list for an RLC'd batch.

    Per item (with random r):
      sig_share:  e(G1, r*sigma) * e(-r*pk, H2(msg))          == 1
      dec_share:  e(r*w,  H2(ct)) * e(-r*pk, W)               == 1
      ciphertext: e(G1, r*W) * e(-r*U, H2(ct))                == 1

    G1-generator legs, same-message/-ciphertext H2 legs, and same-W legs
    are collapsed, so k same-message sig shares (or k shares on one
    ciphertext) cost 2 pairings, not 2k.  Hash-to-curve runs once per
    distinct message/ciphertext.
    """
    g1 = suite.g1_generator()
    gen_leg = None  # sum over G2 of everything paired with the G1 generator
    by_hash_g2: Dict[bytes, Tuple[Any, Any]] = {}  # key -> (accum G1, H2 point)
    by_w_leg: Dict[bytes, Tuple[Any, Any]] = {}  # ct key -> (accum G1, W point)

    def add_gen_leg(g2elem: Any) -> None:
        nonlocal gen_leg
        gen_leg = g2elem if gen_leg is None else gen_leg + g2elem

    def add_hashed_leg(key: bytes, g1elem: Any, hash_input: bytes) -> None:
        if key in by_hash_g2:
            acc, h = by_hash_g2[key]
            by_hash_g2[key] = (acc + g1elem, h)
        else:
            by_hash_g2[key] = (g1elem, suite.hash_to_g2(hash_input))

    def add_w_leg(key: bytes, g1elem: Any, w: Any) -> None:
        if key in by_w_leg:
            acc, _ = by_w_leg[key]
            by_w_leg[key] = (acc + g1elem, w)
        else:
            by_w_leg[key] = (g1elem, w)

    for r, c in zip(reqs, coeffs):
        if r.kind == SIG_SHARE:
            pk, msg, share = r.payload
            add_gen_leg(share.g2 * c)
            add_hashed_leg(canonical_bytes(b"m", msg), -(pk.g1 * c), msg)
        elif r.kind == DEC_SHARE:
            pk, ct, share = r.payload
            key = canonical_bytes(b"c", ct.hash_input())
            add_hashed_leg(key, share.g1 * c, ct.hash_input())
            # W is determined by (U, V) for *valid* ciphertexts, but key on W
            # itself so shares of two conflicting ciphertexts never mix.
            add_w_leg(canonical_bytes(b"w", ct.w.to_bytes()), -(pk.g1 * c), ct.w)
        else:
            (ct,) = r.payload
            key = canonical_bytes(b"c", ct.hash_input())
            add_gen_leg(ct.w * c)
            add_hashed_leg(key, -(ct.u * c), ct.hash_input())

    pairs: List[Tuple[Any, Any]] = []
    if gen_leg is not None:
        pairs.append((g1, gen_leg))
    pairs.extend((acc, h) for acc, h in by_hash_g2.values())
    pairs.extend((acc, w) for acc, w in by_w_leg.values())
    return pairs


class BatchedBackend(CryptoBackend):
    """RLC batch verification with bisection fallback on failure."""

    def __init__(self, suite: Suite) -> None:
        self.suite = suite
        self._eager = EagerBackend(suite)

    def verify_batch(self, reqs: Sequence[VerifyRequest]) -> List[bool]:
        reqs = list(reqs)
        if not reqs:
            return []
        out = [False] * len(reqs)
        # Malformed requests fail immediately and never enter the RLC
        # algebra (where arbitrary objects could raise mid-aggregation).
        idxs = [
            i for i, r in enumerate(reqs) if request_well_formed(self.suite, r)
        ]
        self._verify_range(reqs, idxs, out)
        return out

    def _aggregate_ok(self, reqs: Sequence[VerifyRequest]) -> bool:
        coeffs = _batch_coefficients(self.suite, reqs)
        pairs = _rlc_pairs(self.suite, reqs, coeffs)
        return self.suite.pairing_product_is_one(pairs)

    def _verify_range(
        self, all_reqs: List[VerifyRequest], idxs: List[int], out: List[bool]
    ) -> None:
        if not idxs:
            return
        sub = [all_reqs[i] for i in idxs]
        if self._aggregate_ok(sub):
            for i in idxs:
                out[i] = True
            return
        if len(idxs) == 1:
            out[idxs[0]] = self._eager.verify_batch(sub)[0]
            return
        mid = len(idxs) // 2
        self._verify_range(all_reqs, idxs[:mid], out)
        self._verify_range(all_reqs, idxs[mid:], out)
