"""Abstract *group suite*: the algebra the threshold scheme is generic over.

The reference hardwires BLS12-381 via the ``pairing`` crate (upstream
``threshold_crypto/src/lib.rs``).  Here the scheme is written once against
this suite interface and instantiated with:

* :class:`ScalarSuite` — **insecure** arithmetic in Z_r where the "groups"
  are the additive group of integers mod r and the "pairing" is plain
  multiplication.  Structurally identical to BLS (linear scheme, Lagrange
  in the exponent, pairing product equations) but with trivial discrete
  logs — used only to make protocol-logic tests fast and deterministic.
* ``BLSSuite`` (:mod:`hbbft_tpu.crypto.bls`) — real BLS12-381,
  pure-Python oracle implementation.

Conventions (matching ``threshold_crypto``): public keys live in G1,
signatures and hashed messages in G2, decryption shares in G1.
"""

from __future__ import annotations

import abc
import hashlib
from dataclasses import dataclass
from typing import Any, Sequence, Tuple

from hbbft_tpu.utils import canonical_bytes


class Suite(abc.ABC):
    """A pairing-friendly group suite.

    Suites are stateless: two instances of the same class are the same
    suite (value equality), so objects that carry a suite reference —
    keys, ciphertexts, Changes — stay value-comparable across
    serialization round-trips.
    """

    name: str
    scalar_modulus: int  # order r of G1/G2

    def __eq__(self, other: object) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self))

    # -- group elements ----------------------------------------------
    @abc.abstractmethod
    def g1_generator(self) -> Any: ...

    @abc.abstractmethod
    def g2_generator(self) -> Any: ...

    @abc.abstractmethod
    def g1_identity(self) -> Any: ...

    @abc.abstractmethod
    def g2_identity(self) -> Any: ...

    # -- membership ---------------------------------------------------
    @abc.abstractmethod
    def is_g1(self, obj: Any, check_subgroup: bool = True) -> bool:
        """Whether ``obj`` is a G1 element of this suite (wire validation).

        ``check_subgroup=False`` skips the expensive r-torsion check for
        elements that are locally derived (trusted) rather than
        wire-sourced.
        """

    @abc.abstractmethod
    def is_g2(self, obj: Any, check_subgroup: bool = True) -> bool:
        """Whether ``obj`` is a G2 element of this suite (wire validation)."""

    # -- hashing ------------------------------------------------------
    @abc.abstractmethod
    def hash_to_g2(self, data: bytes) -> Any:
        """Hash arbitrary bytes to a G2 element of unknown discrete log."""

    def hash_to_scalar(self, data: bytes) -> int:
        """Hash to a scalar in [0, r)."""
        h = hashlib.sha3_256(b"h2s" + data).digest()
        return int.from_bytes(h, "big") % self.scalar_modulus

    # -- wire decoding ------------------------------------------------
    @abc.abstractmethod
    def g1_from_bytes(self, data: bytes) -> Any:
        """Decode (and fully validate) a wire-sourced G1 element.

        Raises ``ValueError`` on anything that is not the canonical
        encoding of a subgroup element — this is the codec-side twin of
        :meth:`is_g1` and MUST enforce the same membership policy,
        because decoded elements reach pairing checks directly.
        """

    @abc.abstractmethod
    def g2_from_bytes(self, data: bytes) -> Any:
        """Decode (and fully validate) a wire-sourced G2 element."""

    # -- pairing ------------------------------------------------------
    @abc.abstractmethod
    def pairing_product_is_one(self, pairs: Sequence[Tuple[Any, Any]]) -> bool:
        """Check ``prod_i e(a_i, b_i) == 1`` for ``(a_i, b_i)`` in G1 x G2."""

    def pairing_eq(self, a1: Any, b1: Any, a2: Any, b2: Any) -> bool:
        """Check ``e(a1, b1) == e(a2, b2)``."""
        return self.pairing_product_is_one([(a1, b1), (-a2, b2)])


@dataclass(frozen=True)
class ScalarG:
    """Element of the insecure scalar "group" (additive Z_r)."""

    value: int
    modulus: int

    # serde hooks (no annotation: class attrs, not dataclass fields).
    # G1 and G2 are the same structure in this suite, so one group id.
    serde_suite_name = "scalar-insecure"
    serde_group = 1

    def __add__(self, other: "ScalarG") -> "ScalarG":
        return ScalarG((self.value + other.value) % self.modulus, self.modulus)

    def __neg__(self) -> "ScalarG":
        return ScalarG(-self.value % self.modulus, self.modulus)

    def __sub__(self, other: "ScalarG") -> "ScalarG":
        return self + (-other)

    def __mul__(self, scalar: int) -> "ScalarG":
        return ScalarG(self.value * (scalar % self.modulus) % self.modulus, self.modulus)

    __rmul__ = __mul__

    def is_identity(self) -> bool:
        return self.value == 0

    def to_bytes(self) -> bytes:
        return self.value.to_bytes(32, "big")


# A 255-bit prime: the BLS12-381 scalar-field order, so scalars are
# interchangeable between the mock and the real suite.
BLS12_381_R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001


class ScalarSuite(Suite):
    """INSECURE mock suite over Z_r — protocol tests only (see module doc)."""

    name = "scalar-insecure"
    scalar_modulus = BLS12_381_R

    def g1_generator(self) -> ScalarG:
        return ScalarG(1, self.scalar_modulus)

    def g2_generator(self) -> ScalarG:
        return ScalarG(1, self.scalar_modulus)

    def g1_identity(self) -> ScalarG:
        return ScalarG(0, self.scalar_modulus)

    def g2_identity(self) -> ScalarG:
        return ScalarG(0, self.scalar_modulus)

    def is_g1(self, obj: Any, check_subgroup: bool = True) -> bool:
        return (
            isinstance(obj, ScalarG)
            and isinstance(obj.value, int)
            and not isinstance(obj.value, bool)
            and obj.modulus == self.scalar_modulus
            and 0 <= obj.value < obj.modulus
        )

    def is_g2(self, obj: Any, check_subgroup: bool = True) -> bool:
        return self.is_g1(obj)

    def g1_from_bytes(self, data: bytes) -> ScalarG:
        # lint: no-subgroup (prime-order scalar group: every residue in
        # range is a member; the range check IS the membership check)
        if not isinstance(data, bytes) or len(data) != 32:
            raise ValueError("scalar group element: want 32 bytes")
        v = int.from_bytes(data, "big")
        if v >= self.scalar_modulus:
            raise ValueError("scalar group element out of range")
        return ScalarG(v, self.scalar_modulus)

    g2_from_bytes = g1_from_bytes

    def hash_to_g2(self, data: bytes) -> ScalarG:
        h = hashlib.sha3_256(canonical_bytes(b"h2g2", data)).digest()
        # Avoid 0 (identity) so "unknown dlog" shape is preserved.
        v = int.from_bytes(h, "big") % (self.scalar_modulus - 1) + 1
        return ScalarG(v, self.scalar_modulus)

    def pairing_product_is_one(self, pairs: Sequence[Tuple[Any, Any]]) -> bool:
        acc = 0
        for a, b in pairs:
            acc = (acc + a.value * b.value) % self.scalar_modulus
        return acc == 0
