"""GF(256) arithmetic and systematic Reed-Solomon erasure coding.

Reference: the ``reed-solomon-erasure`` crate used by upstream
``src/broadcast/broadcast.rs`` (SURVEY.md §2 #4): N shards = K data +
(N-K) parity over GF(2^8), any K shards reconstruct.

Field: GF(2^8) with the primitive polynomial x^8+x^4+x^3+x^2+1 (0x11d),
generator 2.  The encoding matrix is a Vandermonde matrix normalized so
its top K x K block is the identity (systematic: data shards pass
through unchanged) — the same construction the reference crate uses.

Implementation: numpy log/exp-table arithmetic.  The TPU path expresses
the same encode/decode as int8 table-gather matmuls (ops/jax/).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Dict, List, Sequence

import numpy as np

try:
    from hbbft_tpu.ops import native as _native
except Exception:  # pragma: no cover - native plane is optional
    _native = None

_POLY = 0x11D

EXP = np.zeros(512, dtype=np.uint8)
LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    EXP[_i] = _x
    LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= _POLY
EXP[255:510] = EXP[:255]  # wraparound so exp[log a + log b] needs no mod


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(EXP[LOG[a] + LOG[b]])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of 0")
    return int(EXP[255 - LOG[a]])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(256); uint8 arrays (m,k) @ (k,n) -> (m,n)."""
    assert a.shape[1] == b.shape[0]
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.uint8)
    for i in range(a.shape[1]):  # rank-1 accumulation, vectorized over cells
        col = a[:, i]
        row = b[i, :]
        nz = (col[:, None].astype(np.int32) != 0) & (row[None, :].astype(np.int32) != 0)
        prod = EXP[(LOG[col][:, None] + LOG[row][None, :])]
        out ^= np.where(nz, prod, 0).astype(np.uint8)
    return out


def gf_mat_inv(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inverse over GF(256)."""
    n = m.shape[0]
    assert m.shape == (n, n)
    a = m.astype(np.uint8).copy()
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        pivot = None
        for r in range(col, n):
            if a[r, col] != 0:
                pivot = r
                break
        if pivot is None:
            raise ValueError("singular matrix over GF(256)")
        if pivot != col:
            a[[col, pivot]] = a[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        pinv = gf_inv(int(a[col, col]))
        a[col] = _row_scale(a[col], pinv)
        inv[col] = _row_scale(inv[col], pinv)
        for r in range(n):
            if r != col and a[r, col] != 0:
                factor = int(a[r, col])
                a[r] ^= _row_scale(a[col], factor)
                inv[r] ^= _row_scale(inv[col], factor)
    return inv


def _row_scale(row: np.ndarray, s: int) -> np.ndarray:
    if s == 0:
        return np.zeros_like(row)
    nz = row != 0
    out = np.zeros_like(row)
    out[nz] = EXP[LOG[row[nz]] + LOG[s]]
    return out


@lru_cache(maxsize=256)
def encoding_matrix(k: int, n: int) -> "np.ndarray":
    """Systematic n x k encoding matrix (top k rows = identity).

    Vandermonde rows [a_i^0 .. a_i^(k-1)] with distinct points a_i =
    exp(i) (distinct for n <= 255), right-multiplied by the inverse of
    the top k x k block; any k rows stay independent under that
    normalization.
    """
    assert 0 < k <= n <= 255
    vand = np.zeros((n, k), dtype=np.uint8)
    for i in range(n):
        for j in range(k):
            vand[i, j] = EXP[(i * j) % 255]
    top_inv = gf_mat_inv(vand[:k])
    return gf_matmul(vand, top_inv)


class ReedSolomon:
    """Systematic RS(k-of-n) erasure codec over byte shards."""

    shard_align = 1  # GF(256) symbols are single bytes

    def __init__(self, k: int, n: int) -> None:
        assert 0 < k <= n <= 255, "GF(256) Vandermonde supports at most 255 shards"
        self.k = k
        self.n = n
        self.matrix = encoding_matrix(k, n)

    def encode(self, data_shards: Sequence[bytes]) -> List[bytes]:
        """k equal-length data shards -> n shards (data + parity)."""
        assert len(data_shards) == self.k
        size = len(data_shards[0])
        assert all(len(s) == size for s in data_shards)
        if _native is not None and _native.available():
            out = _native.rs_encode(data_shards, self.n)
            if out is not None:
                return out
        data = np.frombuffer(b"".join(data_shards), dtype=np.uint8).reshape(
            self.k, size
        )
        parity = gf_matmul(self.matrix[self.k :], data)
        return [bytes(s) for s in data] + [bytes(p) for p in parity]

    def reconstruct(self, shards: Dict[int, bytes]) -> List[bytes]:
        """Any k shards (by index) -> the k data shards."""
        if len(shards) < self.k:
            raise ValueError(f"need {self.k} shards, got {len(shards)}")
        if _native is not None and _native.available():
            out = _native.rs_reconstruct(shards, self.k, self.n)
            if out is not None:
                return out
        idxs = sorted(shards)[: self.k]
        size = len(shards[idxs[0]])
        sub = self.matrix[idxs]
        dec = gf_mat_inv(sub)
        have = np.frombuffer(
            b"".join(shards[i] for i in idxs), dtype=np.uint8
        ).reshape(self.k, size)
        data = gf_matmul(dec, have)
        return [bytes(r) for r in data]


# ---------------------------------------------------------------------------
# GF(2^16): the large-validator-set codec
# ---------------------------------------------------------------------------
#
# GF(256) runs out of distinct Vandermonde evaluation points at 255
# shards; validator sets beyond that erasure-code over GF(2^16)
# (poly 0x1100B, generator 2 — verified primitive; 65535 points).
# Symbols are 2 bytes, big-endian on the wire ('>u2'), so shard lengths
# must be even (`ReedSolomon16.shard_align`).  The native engine carries
# the same tables/construction (native/sha3_gf.h) — pinned bit-for-bit
# by tests/test_gf16.py.

_POLY16 = 0x1100B


@lru_cache(maxsize=1)
def _tables16():
    exp = np.zeros(131070, dtype=np.uint16)
    log = np.zeros(65536, dtype=np.int64)
    x = 1
    for i in range(65535):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x10000:
            x ^= _POLY16
    exp[65535:131070] = exp[:65535]
    return exp, log


def gf16_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    exp, log = _tables16()
    return int(exp[log[a] + log[b]])


def gf16_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^16) inverse of 0")
    exp, log = _tables16()
    return int(exp[65535 - log[a]])


def gf16_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^16); uint16 arrays (m,k) @ (k,n) -> (m,n)."""
    assert a.shape[1] == b.shape[0]
    exp, log = _tables16()
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.uint16)
    for i in range(a.shape[1]):
        col = a[:, i]
        row = b[i, :]
        nz = (col[:, None].astype(np.int64) != 0) & (row[None, :].astype(np.int64) != 0)
        prod = exp[(log[col][:, None] + log[row][None, :])]
        out ^= np.where(nz, prod, 0).astype(np.uint16)
    return out


def _row_scale16(row: np.ndarray, s: int) -> np.ndarray:
    if s == 0:
        return np.zeros_like(row)
    exp, log = _tables16()
    nz = row != 0
    out = np.zeros_like(row)
    out[nz] = exp[log[row[nz]] + log[s]]
    return out


def gf16_mat_inv(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inverse over GF(2^16)."""
    n = m.shape[0]
    assert m.shape == (n, n)
    a = m.astype(np.uint16).copy()
    inv = np.eye(n, dtype=np.uint16)
    for col in range(n):
        pivot = None
        for r in range(col, n):
            if a[r, col] != 0:
                pivot = r
                break
        if pivot is None:
            raise ValueError("singular matrix over GF(2^16)")
        if pivot != col:
            a[[col, pivot]] = a[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        pinv = gf16_inv(int(a[col, col]))
        a[col] = _row_scale16(a[col], pinv)
        inv[col] = _row_scale16(inv[col], pinv)
        for r in range(n):
            if r != col and a[r, col] != 0:
                factor = int(a[r, col])
                a[r] ^= _row_scale16(a[col], factor)
                inv[r] ^= _row_scale16(inv[col], factor)
    return inv


@lru_cache(maxsize=64)
def encoding_matrix16(k: int, n: int) -> "np.ndarray":
    """Systematic n x k encoding matrix over GF(2^16) (n <= 65535)."""
    assert 0 < k <= n <= 65535
    exp, _ = _tables16()
    i = np.arange(n, dtype=np.int64)[:, None]
    j = np.arange(k, dtype=np.int64)[None, :]
    vand = exp[(i * j) % 65535].astype(np.uint16)
    top_inv = gf16_mat_inv(vand[:k])
    return gf16_matmul(vand, top_inv)


class ReedSolomon16:
    """Systematic RS(k-of-n) over GF(2^16); shard bytes must be even."""

    shard_align = 2

    def __init__(self, k: int, n: int) -> None:
        assert 0 < k <= n <= 65535
        self.k = k
        self.n = n
        self.matrix = encoding_matrix16(k, n)

    @staticmethod
    def _sym(shard_bytes: bytes) -> np.ndarray:
        assert len(shard_bytes) % 2 == 0, "GF(2^16) shards must be even-length"
        return np.frombuffer(shard_bytes, dtype=">u2").astype(np.uint16)

    @staticmethod
    def _bytes(sym_row: np.ndarray) -> bytes:
        return sym_row.astype(">u2").tobytes()

    def encode(self, data_shards: Sequence[bytes]) -> List[bytes]:
        assert len(data_shards) == self.k
        size = len(data_shards[0])
        assert all(len(s) == size for s in data_shards)
        if _native is not None and _native.available():
            out = _native.rs16_encode(data_shards, self.n)
            if out is not None:
                return out
        data = np.stack([self._sym(s) for s in data_shards])
        parity = gf16_matmul(self.matrix[self.k:], data)
        return [bytes(s) for s in data_shards] + [self._bytes(p) for p in parity]

    def reconstruct(self, shards: Dict[int, bytes]) -> List[bytes]:
        if len(shards) < self.k:
            raise ValueError(f"need {self.k} shards, got {len(shards)}")
        if _native is not None and _native.available():
            out = _native.rs16_reconstruct(shards, self.k, self.n)
            if out is not None:
                return out
        idxs = sorted(shards)[: self.k]
        sub = self.matrix[idxs]
        dec = gf16_mat_inv(sub)
        have = np.stack([self._sym(shards[i]) for i in idxs])
        data = gf16_matmul(dec, have)
        return [self._bytes(r) for r in data]


def rs_codec(k: int, n: int):
    """The RBC erasure codec for an n-validator network: GF(256) keeps
    the reference-matching byte layout up to 255 shards; larger sets
    use GF(2^16)."""
    return ReedSolomon(k, n) if n <= 255 else ReedSolomon16(k, n)
