"""Device-side (JAX) kernels for the data-plane hot ops.

Counterparts of the host implementations in :mod:`hbbft_tpu.ops`:
Reed-Solomon erasure coding as GF(2) bit-matmuls (the MXU sees a plain
integer matmul) and batched Keccak-f[1600]/SHA3-256 for Merkle hashing.
"""
