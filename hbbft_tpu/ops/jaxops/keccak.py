"""Batched Keccak-f[1600] / SHA3-256 in JAX (uint32 lane pairs).

Reference behavior: ``tiny-keccak`` SHA3-256 as used by upstream
``src/broadcast/merkle.rs`` (SURVEY.md §2 #4).  TPUs have no 64-bit
integer path, so each 64-bit lane is an (lo, hi) uint32 pair; rotations
split across the pair.  Everything is elementwise over a leading batch
axis — hashing a Merkle level of 10k shards is one vectorized call.

Multi-block sponge absorption (round 3): messages of any equal length
hash via block-wise XOR-absorb + permutation, so big RBC shards (e.g.
config 2's 10-node/1 KB shape: 129-byte shards) ride the device data
plane instead of falling back to the host — upstream ``tiny-keccak``
has no length limit (VERDICT round-2 item #5).  Merkle branch inputs
(65 bytes) keep the single-block fast path.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

RATE = 136  # SHA3-256 rate in bytes

_ROUND_CONSTANTS = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# rho rotation offsets, indexed [x][y] with lane index x + 5y.
_RHO = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

U32 = jnp.uint32


def _rotl(lo: jnp.ndarray, hi: jnp.ndarray, r: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Rotate the 64-bit (lo, hi) pair left by r."""
    r %= 64
    if r == 0:
        return lo, hi
    if r == 32:
        return hi, lo
    if r < 32:
        nlo = (lo << r) | (hi >> (32 - r))
        nhi = (hi << r) | (lo >> (32 - r))
        return nlo, nhi
    r -= 32
    nlo = (hi << r) | (lo >> (32 - r))
    nhi = (lo << r) | (hi >> (32 - r))
    return nlo, nhi


def keccak_f(state: jnp.ndarray) -> jnp.ndarray:
    """One permutation over ``(..., 25, 2)`` uint32 states (lo, hi)."""
    lanes_lo = [state[..., i, 0] for i in range(25)]
    lanes_hi = [state[..., i, 1] for i in range(25)]

    for rc in _ROUND_CONSTANTS:
        # theta
        c_lo = [lanes_lo[x] ^ lanes_lo[x + 5] ^ lanes_lo[x + 10] ^ lanes_lo[x + 15] ^ lanes_lo[x + 20] for x in range(5)]
        c_hi = [lanes_hi[x] ^ lanes_hi[x + 5] ^ lanes_hi[x + 10] ^ lanes_hi[x + 15] ^ lanes_hi[x + 20] for x in range(5)]
        for x in range(5):
            r_lo, r_hi = _rotl(c_lo[(x + 1) % 5], c_hi[(x + 1) % 5], 1)
            d_lo = c_lo[(x + 4) % 5] ^ r_lo
            d_hi = c_hi[(x + 4) % 5] ^ r_hi
            for y in range(5):
                lanes_lo[x + 5 * y] = lanes_lo[x + 5 * y] ^ d_lo
                lanes_hi[x + 5 * y] = lanes_hi[x + 5 * y] ^ d_hi
        # rho + pi
        b_lo = [None] * 25
        b_hi = [None] * 25
        for x in range(5):
            for y in range(5):
                nx, ny = y, (2 * x + 3 * y) % 5
                r_lo, r_hi = _rotl(lanes_lo[x + 5 * y], lanes_hi[x + 5 * y], _RHO[x][y])
                b_lo[nx + 5 * ny] = r_lo
                b_hi[nx + 5 * ny] = r_hi
        # chi
        for y in range(5):
            row_lo = [b_lo[x + 5 * y] for x in range(5)]
            row_hi = [b_hi[x + 5 * y] for x in range(5)]
            for x in range(5):
                lanes_lo[x + 5 * y] = row_lo[x] ^ (~row_lo[(x + 1) % 5] & row_lo[(x + 2) % 5])
                lanes_hi[x + 5 * y] = row_hi[x] ^ (~row_hi[(x + 1) % 5] & row_hi[(x + 2) % 5])
        # iota
        lanes_lo[0] = lanes_lo[0] ^ jnp.uint32(rc & 0xFFFFFFFF)
        lanes_hi[0] = lanes_hi[0] ^ jnp.uint32(rc >> 32)

    return jnp.stack(
        [jnp.stack([lanes_lo[i], lanes_hi[i]], axis=-1) for i in range(25)], axis=-2
    )


def pad_block(msgs: np.ndarray) -> np.ndarray:
    """(batch, m) uint8 messages (m <= RATE-1) -> (batch, RATE) padded."""
    batch, m = msgs.shape
    assert m <= RATE - 1, "single-block SHA3 only"
    out = np.zeros((batch, RATE), dtype=np.uint8)
    out[:, :m] = msgs
    out[:, m] = 0x06
    out[:, RATE - 1] ^= 0x80
    return out


def n_blocks_for(m: int) -> int:
    """SHA3 blocks absorbed for an m-byte message (padding adds >= 1)."""
    return m // RATE + 1


def pad_multi(msgs: np.ndarray) -> np.ndarray:
    """(batch, m) uint8 -> (batch, n_blocks*RATE) SHA3-padded."""
    batch, m = msgs.shape
    total = n_blocks_for(m) * RATE
    out = np.zeros((batch, total), dtype=np.uint8)
    out[:, :m] = msgs
    out[:, m] = 0x06
    out[:, total - 1] ^= 0x80
    return out


def block_words(block: np.ndarray) -> np.ndarray:
    """(batch, RATE) uint8 -> (batch, RATE//8, 2) uint32 (lo, hi) lanes."""
    batch = block.shape[0]
    as_u32 = block.reshape(batch, RATE // 4, 4).astype(np.uint32)
    vals = (
        as_u32[..., 0]
        | (as_u32[..., 1] << 8)
        | (as_u32[..., 2] << 16)
        | (as_u32[..., 3] << 24)
    )
    return np.stack([vals[:, 0::2], vals[:, 1::2]], axis=-1)


def digest_from_state(state: np.ndarray) -> np.ndarray:
    """(batch, 25, 2) uint32 permuted states -> (batch, 32) digests."""
    batch = state.shape[0]
    dig = state[:, :4, :]  # first 4 lanes = 32 bytes
    flat = np.zeros((batch, 32), dtype=np.uint8)
    for i in range(4):
        for half in range(2):
            v = dig[:, i, half]
            for b in range(4):
                flat[:, 8 * i + 4 * half + b] = (v >> (8 * b)) & 0xFF
    return flat


def sha3_256_multi(padded: np.ndarray) -> np.ndarray:
    """(batch, n_blocks*RATE) padded messages -> (batch, 32) digests.

    Block-wise sponge absorption; each block is one XOR into the state
    followed by the (batched) permutation — Pallas-fused on TPU.
    """
    import jax

    if jax.default_backend() == "tpu":
        from hbbft_tpu.ops.jaxops import keccak_pallas as _kp

        return _kp.sha3_256_multi(padded)
    batch, total = padded.shape
    nb = total // RATE
    state = jnp.zeros((batch, 25, 2), jnp.uint32)
    for b in range(nb):
        words = np.zeros((batch, 25, 2), dtype=np.uint32)
        words[:, : RATE // 8] = block_words(padded[:, b * RATE : (b + 1) * RATE])
        state = keccak_f(state ^ jnp.asarray(words))
    return digest_from_state(np.asarray(state))


def sha3_256_block(padded: np.ndarray) -> jnp.ndarray:
    """(batch, RATE) padded blocks -> (batch, 32) uint8 digests.

    On TPU the permutation dispatches to the fused Pallas kernel
    (ops/jaxops/keccak_pallas.py); elsewhere the jnp path below runs.
    """
    import jax

    if jax.default_backend() == "tpu":
        from hbbft_tpu.ops.jaxops import keccak_pallas as _kp

        return _kp.sha3_256_block(padded)
    batch = padded.shape[0]
    words = np.zeros((batch, 25, 2), dtype=np.uint32)
    words[:, : RATE // 8] = block_words(padded)
    out = keccak_f(jnp.asarray(words))
    return digest_from_state(np.asarray(out))


def sha3_256_batch(msgs: np.ndarray) -> np.ndarray:
    """Batched SHA3-256 over equal-length messages: (batch, m) -> (batch, 32).

    Single-block messages (m <= 135) take the one-permutation fast path;
    longer ones absorb block by block.
    """
    if msgs.shape[1] <= RATE - 1:
        return np.asarray(sha3_256_block(pad_block(msgs)))
    return np.asarray(sha3_256_multi(pad_multi(msgs)))


def merkle_level(prefix: int, pairs: np.ndarray) -> np.ndarray:
    """Hash one Merkle level: (batch, 64) sibling pairs -> (batch, 32).

    ``prefix`` is the domain-separation byte (0x01 for branches, matching
    hbbft_tpu.ops.merkle._h_branch).
    """
    batch = pairs.shape[0]
    msgs = np.concatenate(
        [np.full((batch, 1), prefix, dtype=np.uint8), pairs.astype(np.uint8)], axis=1
    )
    return sha3_256_batch(msgs)
