"""Batched Keccak-f[1600] / SHA3-256 in JAX (uint32 lane pairs).

Reference behavior: ``tiny-keccak`` SHA3-256 as used by upstream
``src/broadcast/merkle.rs`` (SURVEY.md §2 #4).  TPUs have no 64-bit
integer path, so each 64-bit lane is an (lo, hi) uint32 pair; rotations
split across the pair.  Everything is elementwise over a leading batch
axis — hashing a Merkle level of 10k shards is one vectorized call.

Single-block only (message <= 135 bytes after padding): Merkle leaf and
branch inputs are 1 + 32·2 = 65 bytes, well inside one SHA3-256 block.
The host path (hashlib) remains the general-length implementation.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

RATE = 136  # SHA3-256 rate in bytes

_ROUND_CONSTANTS = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# rho rotation offsets, indexed [x][y] with lane index x + 5y.
_RHO = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

U32 = jnp.uint32


def _rotl(lo: jnp.ndarray, hi: jnp.ndarray, r: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Rotate the 64-bit (lo, hi) pair left by r."""
    r %= 64
    if r == 0:
        return lo, hi
    if r == 32:
        return hi, lo
    if r < 32:
        nlo = (lo << r) | (hi >> (32 - r))
        nhi = (hi << r) | (lo >> (32 - r))
        return nlo, nhi
    r -= 32
    nlo = (hi << r) | (lo >> (32 - r))
    nhi = (lo << r) | (hi >> (32 - r))
    return nlo, nhi


def keccak_f(state: jnp.ndarray) -> jnp.ndarray:
    """One permutation over ``(..., 25, 2)`` uint32 states (lo, hi)."""
    lanes_lo = [state[..., i, 0] for i in range(25)]
    lanes_hi = [state[..., i, 1] for i in range(25)]

    for rc in _ROUND_CONSTANTS:
        # theta
        c_lo = [lanes_lo[x] ^ lanes_lo[x + 5] ^ lanes_lo[x + 10] ^ lanes_lo[x + 15] ^ lanes_lo[x + 20] for x in range(5)]
        c_hi = [lanes_hi[x] ^ lanes_hi[x + 5] ^ lanes_hi[x + 10] ^ lanes_hi[x + 15] ^ lanes_hi[x + 20] for x in range(5)]
        for x in range(5):
            r_lo, r_hi = _rotl(c_lo[(x + 1) % 5], c_hi[(x + 1) % 5], 1)
            d_lo = c_lo[(x + 4) % 5] ^ r_lo
            d_hi = c_hi[(x + 4) % 5] ^ r_hi
            for y in range(5):
                lanes_lo[x + 5 * y] = lanes_lo[x + 5 * y] ^ d_lo
                lanes_hi[x + 5 * y] = lanes_hi[x + 5 * y] ^ d_hi
        # rho + pi
        b_lo = [None] * 25
        b_hi = [None] * 25
        for x in range(5):
            for y in range(5):
                nx, ny = y, (2 * x + 3 * y) % 5
                r_lo, r_hi = _rotl(lanes_lo[x + 5 * y], lanes_hi[x + 5 * y], _RHO[x][y])
                b_lo[nx + 5 * ny] = r_lo
                b_hi[nx + 5 * ny] = r_hi
        # chi
        for y in range(5):
            row_lo = [b_lo[x + 5 * y] for x in range(5)]
            row_hi = [b_hi[x + 5 * y] for x in range(5)]
            for x in range(5):
                lanes_lo[x + 5 * y] = row_lo[x] ^ (~row_lo[(x + 1) % 5] & row_lo[(x + 2) % 5])
                lanes_hi[x + 5 * y] = row_hi[x] ^ (~row_hi[(x + 1) % 5] & row_hi[(x + 2) % 5])
        # iota
        lanes_lo[0] = lanes_lo[0] ^ jnp.uint32(rc & 0xFFFFFFFF)
        lanes_hi[0] = lanes_hi[0] ^ jnp.uint32(rc >> 32)

    return jnp.stack(
        [jnp.stack([lanes_lo[i], lanes_hi[i]], axis=-1) for i in range(25)], axis=-2
    )


def pad_block(msgs: np.ndarray) -> np.ndarray:
    """(batch, m) uint8 messages (m <= RATE-1) -> (batch, RATE) padded."""
    batch, m = msgs.shape
    assert m <= RATE - 1, "single-block SHA3 only"
    out = np.zeros((batch, RATE), dtype=np.uint8)
    out[:, :m] = msgs
    out[:, m] = 0x06
    out[:, RATE - 1] ^= 0x80
    return out


def sha3_256_block(padded: np.ndarray) -> jnp.ndarray:
    """(batch, RATE) padded blocks -> (batch, 32) uint8 digests.

    On TPU the permutation dispatches to the fused Pallas kernel
    (ops/jaxops/keccak_pallas.py); elsewhere the jnp path below runs.
    """
    import jax

    if jax.default_backend() == "tpu":
        from hbbft_tpu.ops.jaxops import keccak_pallas as _kp

        return _kp.sha3_256_block(padded)
    batch = padded.shape[0]
    words = np.zeros((batch, 25, 2), dtype=np.uint32)
    as_u32 = padded.reshape(batch, RATE // 4, 4)
    vals = (
        as_u32[..., 0].astype(np.uint32)
        | (as_u32[..., 1].astype(np.uint32) << 8)
        | (as_u32[..., 2].astype(np.uint32) << 16)
        | (as_u32[..., 3].astype(np.uint32) << 24)
    )
    for i in range(RATE // 8):
        words[:, i, 0] = vals[:, 2 * i]
        words[:, i, 1] = vals[:, 2 * i + 1]
    out = keccak_f(jnp.asarray(words))
    dig = np.asarray(out)[:, :4, :]  # first 4 lanes = 32 bytes
    flat = np.zeros((batch, 32), dtype=np.uint8)
    for i in range(4):
        for half in range(2):
            v = dig[:, i, half]
            for b in range(4):
                flat[:, 8 * i + 4 * half + b] = (v >> (8 * b)) & 0xFF
    return flat


def sha3_256_batch(msgs: np.ndarray) -> np.ndarray:
    """Batched single-block SHA3-256: (batch, m<=135) uint8 -> (batch, 32)."""
    return np.asarray(sha3_256_block(pad_block(msgs)))


def merkle_level(prefix: int, pairs: np.ndarray) -> np.ndarray:
    """Hash one Merkle level: (batch, 64) sibling pairs -> (batch, 32).

    ``prefix`` is the domain-separation byte (0x01 for branches, matching
    hbbft_tpu.ops.merkle._h_branch).
    """
    batch = pairs.shape[0]
    msgs = np.concatenate(
        [np.full((batch, 1), prefix, dtype=np.uint8), pairs.astype(np.uint8)], axis=1
    )
    return sha3_256_batch(msgs)
