"""Reed-Solomon over GF(256) as GF(2) bit-matrix multiplication on TPU.

Reference behavior: the ``reed-solomon-erasure`` crate used by upstream
``src/broadcast/broadcast.rs`` (SURVEY.md §2 #4), re-expressed for the
MXU: multiplication by a FIXED GF(256) element is GF(2)-linear on the 8
bits of a byte, so the whole systematic encode (parity = M ⊗ data over
GF(256)) becomes

    parity_bits = (ENC_BITS @ data_bits) mod 2

— one integer matmul over {0,1} matrices (batched over shard columns),
which is exactly the shape a TPU wants.  Reconstruction inverts the
surviving rows' submatrix on the host (tiny, O(k^3) bytes) and applies
the same bit-matmul for the bulk data.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Dict, List, Sequence

import jax.numpy as jnp
import numpy as np

from hbbft_tpu.ops import gf256 as host


def _mul_matrix_gf2(c: int) -> np.ndarray:
    """The 8x8 GF(2) matrix of y -> c·y in GF(256).

    Column j is the bit pattern of c·x^j (x = 0x02 basis powers).
    """
    m = np.zeros((8, 8), dtype=np.int32)
    for j in range(8):
        prod = host.gf_mul(c, 1 << j)
        for i in range(8):
            m[i, j] = (prod >> i) & 1
    return m


def _expand_bits(mat: np.ndarray) -> np.ndarray:
    """GF(256) matrix (r, c) -> GF(2) bit matrix (8r, 8c)."""
    r, c = mat.shape
    out = np.zeros((8 * r, 8 * c), dtype=np.int32)
    for i in range(r):
        for j in range(c):
            if mat[i, j]:
                out[8 * i : 8 * i + 8, 8 * j : 8 * j + 8] = _mul_matrix_gf2(
                    int(mat[i, j])
                )
    return out


@lru_cache(maxsize=64)
def _enc_bits(k: int, n: int) -> np.ndarray:
    """Bit-expanded parity rows of the systematic encoding matrix."""
    return _expand_bits(host.encoding_matrix(k, n)[k:])


def bytes_to_bits(data: np.ndarray) -> jnp.ndarray:
    """(r, s) uint8 -> (8r, s) int32 bits (LSB-first per byte)."""
    bits = np.unpackbits(data[:, None, :], axis=1, bitorder="little")
    return jnp.asarray(bits.reshape(data.shape[0] * 8, data.shape[1]).astype(np.int32))


def bits_to_bytes(bits: np.ndarray) -> np.ndarray:
    arr = np.asarray(bits, dtype=np.uint8).reshape(-1, 8, bits.shape[-1])
    return np.packbits(arr, axis=1, bitorder="little").reshape(
        arr.shape[0], bits.shape[-1]
    )


class ReedSolomonJax:
    """Systematic RS(k-of-n) with device-side encode/reconstruct."""

    def __init__(self, k: int, n: int) -> None:
        assert 0 < k <= n <= 255
        self.k = k
        self.n = n
        self._host = host.ReedSolomon(k, n)

    def encode(self, data_shards: Sequence[bytes]) -> List[bytes]:
        assert len(data_shards) == self.k
        size = len(data_shards[0])
        data = np.frombuffer(b"".join(data_shards), dtype=np.uint8).reshape(
            self.k, size
        )
        enc = jnp.asarray(_enc_bits(self.k, self.n))
        parity_bits = (enc @ bytes_to_bits(data)) & 1
        parity = bits_to_bytes(np.asarray(parity_bits))
        return [bytes(r) for r in data] + [bytes(r) for r in parity]

    def reconstruct(self, shards: Dict[int, bytes]) -> List[bytes]:
        if len(shards) < self.k:
            raise ValueError(f"need {self.k} shards, got {len(shards)}")
        idxs = sorted(shards)[: self.k]
        sub = self._host.matrix[idxs]
        dec = host.gf_mat_inv(sub)  # host: tiny k x k inverse
        dec_bits = jnp.asarray(_expand_bits(dec))
        size = len(shards[idxs[0]])
        have = np.frombuffer(
            b"".join(shards[i] for i in idxs), dtype=np.uint8
        ).reshape(self.k, size)
        data_bits = (dec_bits @ bytes_to_bits(have)) & 1
        return [bytes(r) for r in bits_to_bytes(np.asarray(data_bits))]
