"""Batched broadcast data plane on device: RS-encode + Merkle prove.

Reference behavior: the proposer side of ``Broadcast.handle_input`` —
``reed-solomon-erasure`` encode + ``tiny-keccak`` Merkle tree + per-node
proofs (SURVEY.md §2 #4) — for MANY values at once.  One RBC instance
per validator runs per epoch (Subset spawns N of them), so at firehose
scale the proposer's data plane is a batch problem: V values × N shards.
This module runs the whole thing as three device ops:

1. RS parity for all values in ONE GF(2) bit-matmul (the per-value
   encode matrices are identical, so values concatenate along the
   column axis of a single ``ENC_BITS @ data_bits``),
2. leaf hashes for all V×N shards in one batched Keccak call,
3. each tree level for all values in one batched Keccak call.

Bit-exact with the host path (``ops.merkle.MerkleTree`` /
``ops.gf256.ReedSolomon``) — proofs produced here validate against the
same roots.  Device Keccak absorbs multi-block since round 3, so big
shards (config 2's 10-node/1 KB shape packs to 129-byte shards) ride
the device path too; ``MAX_DEV_SHARD`` only bounds the unrolled block
count of one call.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from hbbft_tpu.ops.jaxops import gf256 as jgf
from hbbft_tpu.ops.jaxops import keccak as jk
from hbbft_tpu.ops.merkle import Proof, _depth


# Device-path shard bound: multi-block absorption handles any length;
# this only caps the per-call permutation count (16 blocks ~= 2 KB).
MAX_DEV_SHARD = 16 * jk.RATE - 2


def _pack(value: bytes, k: int) -> Tuple[np.ndarray, int]:
    """Length-prefix and pad into (k, shard_len) uint8."""
    payload = len(value).to_bytes(8, "big") + value
    shard_len = max(1, -(-len(payload) // k))
    payload = payload.ljust(k * shard_len, b"\x00")
    return (
        np.frombuffer(payload, dtype=np.uint8).reshape(k, shard_len),
        shard_len,
    )


def encode_and_prove(
    values: Sequence[bytes], k: int, n: int
) -> List[List[Proof]]:
    """RS-encode + Merkle-prove a batch of equal-shard-size values.

    Returns ``proofs[v][i]`` — the proof of value v's shard i, exactly
    what ``Broadcast`` sends node i as its ``Value`` message.  All
    values must pack to one common shard length (callers batch by size
    bucket); the device Keccak absorbs multi-block, so any length up to
    ``MAX_DEV_SHARD`` (the per-call block-count bound) is eligible.
    """
    assert values, "empty batch"
    packs = [_pack(v, k) for v in values]
    shard_len = packs[0][1]
    assert all(s == shard_len for _, s in packs), "mixed shard lengths"
    V = len(values)

    # 1. One bit-matmul for every value's parity.
    data = np.stack([p for p, _ in packs])  # (V, k, s)
    enc = jgf._enc_bits(k, n)  # (8*(n-k), 8k)
    flat = np.ascontiguousarray(np.swapaxes(data, 0, 1)).reshape(k, V * shard_len)
    parity_bits = np.asarray(
        (jnp.asarray(enc) @ jgf.bytes_to_bits(flat)) & 1
    )
    parity = jgf.bits_to_bytes(parity_bits).reshape(n - k, V, shard_len)
    shards = np.concatenate(
        [np.swapaxes(data, 0, 1), parity], axis=0
    )  # (n, V, s)
    shards_vn = np.swapaxes(shards, 0, 1)  # (V, n, s)

    # 2. Leaf hashes: H(0x00 || shard) for all V*n shards at once.
    size = 1 << _depth(n)
    leaves_in = np.zeros((V * n, 1 + shard_len), dtype=np.uint8)
    leaves_in[:, 1:] = shards_vn.reshape(V * n, shard_len)
    leaf_hashes = jk.sha3_256_batch(leaves_in).reshape(V, n, 32)
    if size > n:
        import hashlib

        pad = np.frombuffer(
            hashlib.sha3_256(b"\x00").digest(), dtype=np.uint8
        )
        pad_block = np.broadcast_to(pad, (V, size - n, 32))
        leaf_hashes = np.concatenate([leaf_hashes, pad_block], axis=1)

    # 3. Tree levels, one batched call per level.
    levels = [leaf_hashes]  # (V, width, 32)
    width = size
    while width > 1:
        cur = levels[-1].reshape(V * (width // 2), 64)
        nxt = jk.merkle_level(0x01, cur).reshape(V, width // 2, 32)
        levels.append(nxt)
        width //= 2

    roots = levels[-1][:, 0, :]
    out: List[List[Proof]] = []
    for v in range(V):
        root = roots[v].tobytes()
        proofs_v = []
        for i in range(n):
            path = []
            idx = i
            for level in levels[:-1]:
                path.append(level[v, idx ^ 1].tobytes())
                idx >>= 1
            proofs_v.append(
                Proof(
                    value=shards_vn[v, i].tobytes(),
                    index=i,
                    path=tuple(path),
                    root=root,
                )
            )
        out.append(proofs_v)
    return out
