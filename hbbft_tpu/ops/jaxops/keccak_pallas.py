"""Pallas TPU kernel for batched Keccak-f[1600] (SHA3-256 data plane).

Reference behavior: ``tiny-keccak`` SHA3-256 as used by the reference's
Merkle module (SURVEY.md §2 #4).  The jnp implementation
(:mod:`hbbft_tpu.ops.jaxops.keccak`) emits ~3k separate XLA ops per
permutation; this kernel runs the whole permutation fused in VMEM, one
grid step per batch tile, so a Merkle level over 10k shards is a single
`pallas_call` with no HBM round-trips between rounds.

Layout: the 25 x 64-bit state lives as 50 uint32 *rows* of shape
(50, batch) — row 2i is lane i's low half, row 2i+1 the high half — so
every elementwise op rides full 8x128 VPU tiles along the batch axis.

On CPU (tests) the kernel runs in interpret mode; on TPU it compiles
through Mosaic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from hbbft_tpu.ops.jaxops.keccak import RATE, _RHO, _ROUND_CONSTANTS

_BLK = 512  # batch tile (lanes axis); multiple of 128


def _rotl_pair(lo, hi, r: int):
    r %= 64
    if r == 0:
        return lo, hi
    if r == 32:
        return hi, lo
    if r < 32:
        return (
            (lo << r) | (hi >> (32 - r)),
            (hi << r) | (lo >> (32 - r)),
        )
    r -= 32
    return (
        (hi << r) | (lo >> (32 - r)),
        (lo << r) | (hi >> (32 - r)),
    )


def _keccak_kernel(state_ref, out_ref):
    """state_ref/out_ref: (50, BLK) uint32 in VMEM."""
    lo = [state_ref[2 * i, :] for i in range(25)]
    hi = [state_ref[2 * i + 1, :] for i in range(25)]
    for rc in _ROUND_CONSTANTS:
        c_lo = [lo[x] ^ lo[x + 5] ^ lo[x + 10] ^ lo[x + 15] ^ lo[x + 20] for x in range(5)]
        c_hi = [hi[x] ^ hi[x + 5] ^ hi[x + 10] ^ hi[x + 15] ^ hi[x + 20] for x in range(5)]
        for x in range(5):
            r_lo, r_hi = _rotl_pair(c_lo[(x + 1) % 5], c_hi[(x + 1) % 5], 1)
            d_lo = c_lo[(x + 4) % 5] ^ r_lo
            d_hi = c_hi[(x + 4) % 5] ^ r_hi
            for y in range(5):
                lo[x + 5 * y] = lo[x + 5 * y] ^ d_lo
                hi[x + 5 * y] = hi[x + 5 * y] ^ d_hi
        b_lo = [None] * 25
        b_hi = [None] * 25
        for x in range(5):
            for y in range(5):
                nx, ny = y, (2 * x + 3 * y) % 5
                r_lo, r_hi = _rotl_pair(lo[x + 5 * y], hi[x + 5 * y], _RHO[x][y])
                b_lo[nx + 5 * ny] = r_lo
                b_hi[nx + 5 * ny] = r_hi
        for y in range(5):
            row_lo = [b_lo[x + 5 * y] for x in range(5)]
            row_hi = [b_hi[x + 5 * y] for x in range(5)]
            for x in range(5):
                lo[x + 5 * y] = row_lo[x] ^ (~row_lo[(x + 1) % 5] & row_lo[(x + 2) % 5])
                hi[x + 5 * y] = row_hi[x] ^ (~row_hi[(x + 1) % 5] & row_hi[(x + 2) % 5])
        lo[0] = lo[0] ^ jnp.uint32(rc & 0xFFFFFFFF)
        hi[0] = hi[0] ^ jnp.uint32(rc >> 32)
    for i in range(25):
        out_ref[2 * i, :] = lo[i]
        out_ref[2 * i + 1, :] = hi[i]


def _keccak_f_cols(state: jnp.ndarray, interpret: bool, blk: int) -> jnp.ndarray:
    n = state.shape[1]
    pad = (-n) % blk
    if pad:
        state = jnp.pad(state, ((0, 0), (0, pad)))
    padded = state.shape[1]
    out = pl.pallas_call(
        _keccak_kernel,
        out_shape=jax.ShapeDtypeStruct((50, padded), jnp.uint32),
        grid=(padded // blk,),
        in_specs=[pl.BlockSpec((50, blk), lambda i: (0, i))],
        out_specs=pl.BlockSpec((50, blk), lambda i: (0, i)),
        interpret=interpret,
    )(state)
    return out[:, :n]


_keccak_f_cols_jit = jax.jit(
    functools.partial(_keccak_f_cols, interpret=False, blk=_BLK)
)


def keccak_f_cols(state: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """(50, batch) uint32 column-major states -> permuted states.

    ``batch`` is padded to a multiple of the tile internally.  Interpret
    mode (CPU tests) runs the interpreter eagerly — jitting the
    interpreter's expansion produces an XLA graph whose LLVM compile
    time is unbounded in practice.
    """
    if interpret:
        # One grid step over the whole (small, test-sized) batch.
        return _keccak_f_cols(state, interpret=True, blk=max(state.shape[1], 1))
    return _keccak_f_cols_jit(state)


def keccak_f(state: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Drop-in for jaxops.keccak.keccak_f: (..., 25, 2) uint32 states."""
    lead = state.shape[:-2]
    flat = state.reshape((-1, 50)).T  # (50, batch)
    out = keccak_f_cols(flat, interpret=interpret)
    return out.T.reshape(lead + (25, 2))


def sha3_256_block(padded: np.ndarray, interpret: bool = False) -> np.ndarray:
    """(batch, RATE) padded blocks -> (batch, 32) digests (Pallas path)."""
    batch = padded.shape[0]
    as_u32 = padded.reshape(batch, RATE // 4, 4).astype(np.uint32)
    vals = as_u32[..., 0] | (as_u32[..., 1] << 8) | (as_u32[..., 2] << 16) | (
        as_u32[..., 3] << 24
    )
    state = np.zeros((50, batch), dtype=np.uint32)
    for i in range(RATE // 8):
        state[2 * i] = vals[:, 2 * i]
        state[2 * i + 1] = vals[:, 2 * i + 1]
    out = np.asarray(keccak_f_cols(jnp.asarray(state), interpret=interpret))
    dig = np.zeros((batch, 32), dtype=np.uint8)
    for i in range(4):
        for half in range(2):
            v = out[2 * i + half]
            for b in range(4):
                dig[:, 8 * i + 4 * half + b] = (v >> (8 * b)) & 0xFF
    return dig


def sha3_256_multi(padded: np.ndarray, interpret: bool = False) -> np.ndarray:
    """(batch, n_blocks*RATE) padded messages -> (batch, 32) digests.

    Multi-block sponge: XOR-absorb each block into the (50, batch)
    column state and run the fused Pallas permutation per block.
    """
    from hbbft_tpu.ops.jaxops.keccak import block_words, digest_from_state

    padded = np.asarray(padded, dtype=np.uint8)
    batch, total = padded.shape
    nb = total // RATE
    state = jnp.zeros((50, batch), dtype=jnp.uint32)
    for b in range(nb):
        words = block_words(padded[:, b * RATE : (b + 1) * RATE])  # (batch, 17, 2)
        cols = np.zeros((50, batch), dtype=np.uint32)
        cols[0 : 2 * (RATE // 8) : 2] = words[:, :, 0].T
        cols[1 : 2 * (RATE // 8) : 2] = words[:, :, 1].T
        state = keccak_f_cols(state ^ jnp.asarray(cols), interpret=interpret)
    out = np.asarray(state)  # (50, batch)
    lanes = np.stack([out[0::2].T, out[1::2].T], axis=-1)  # (batch, 25, 2)
    return digest_from_state(lanes)


def sha3_256_batch(msgs: np.ndarray, interpret: bool = False) -> np.ndarray:
    """Batched SHA3-256 via the Pallas permutation.

    (batch, m) uint8 -> (batch, 32) uint8; bit-identical to
    jaxops.keccak.sha3_256_batch and hashlib.  Single-block messages
    take the one-permutation path; longer ones absorb block by block.
    """
    from hbbft_tpu.ops.jaxops.keccak import pad_block, pad_multi

    msgs = np.asarray(msgs, dtype=np.uint8)
    if msgs.shape[1] <= RATE - 1:
        return sha3_256_block(pad_block(msgs), interpret=interpret)
    return sha3_256_multi(pad_multi(msgs), interpret=interpret)
