"""Data-plane ops: GF(256) erasure coding, Keccak/SHA3 Merkle hashing.

Host (numpy) implementations here; batched JAX/Pallas equivalents for the
TPU hot path live in :mod:`hbbft_tpu.ops.jax` (SURVEY.md §2 native-
components table: ``reed-solomon-erasure`` -> GF(256) table matmuls,
``tiny-keccak`` -> vmapped Keccak-f[1600]).
"""
