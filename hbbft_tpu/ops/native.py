"""ctypes bindings for the native (C++) host data plane.

Reference behavior: the reference's data-plane hot loops are native
(Rust ``tiny-keccak``/``reed-solomon-erasure``; SURVEY.md §2 #4 + the
native-components note).  Here the equivalents live in
``native/hbbft_native.cpp``; this module loads (and, if needed, builds)
the shared library and exposes thin typed wrappers.

Loading is lazy and never raises: if no compiler/library is available,
``available()`` is False and callers use the pure-Python/numpy paths.
Correctness is pinned by tests comparing both paths bit-for-bit
(tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import functools
import os
import re
import subprocess
from typing import Dict, List, Optional, Sequence

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_ROOT, "native", "hbbft_native.cpp")
_SO = os.path.join(_ROOT, "native", "build", "libhbbft_native.so")


@functools.lru_cache(maxsize=None)
def _flags_supported(flags: tuple) -> bool:
    """Probe whether g++ accepts ``flags`` (against an empty input, the
    same probe as the Makefile's IFMA_FLAG) — the ISA feature gate.
    Probing, rather than retrying a failed real compile without the
    flags, keeps a genuine source error in the gated arm LOUD instead
    of silently building the stub arm."""
    if not flags:
        return True
    try:
        subprocess.run(
            ["g++", *flags, "-x", "c++", "-c", os.devnull, "-o", os.devnull],
            check=True, capture_output=True, timeout=60,
        )
        return True
    except Exception:
        return False


def _build_aux_object(src: str, obj_stem: str, deps: Sequence[str],
                      preferred_flags: Sequence[str],
                      timeout: int) -> Optional[str]:
    """Compile ``src`` to an object file if stale and return its path
    (None on failure).  ``preferred_flags`` are used iff the toolchain's
    probe accepts them (e.g. ``-mavx512ifma``; without it the source
    compiles its stub arm) — the flag OUTCOME is encoded in the object
    filename, so a toolchain upgrade or flag change triggers a rebuild
    instead of linking a stale stub object forever."""
    use_flags = (
        tuple(preferred_flags) if _flags_supported(tuple(preferred_flags))
        else ()
    )
    tag = (
        re.sub(r"[^A-Za-z0-9]+", "_", " ".join(use_flags)).strip("_")
        if use_flags else "plain"
    )
    obj = f"{obj_stem}.{tag}.o"

    def _mtime(path: str) -> float:
        return os.path.getmtime(path) if os.path.exists(path) else 0.0

    stale = not os.path.exists(obj) or max(
        _mtime(src), *(_mtime(d) for d in deps)
    ) > os.path.getmtime(obj)
    if not stale:
        return obj
    try:
        os.makedirs(os.path.dirname(obj), exist_ok=True)
        tmp = f"{obj}.{os.getpid()}.tmp.o"
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-std=c++17", "-c", *use_flags,
             "-o", tmp, src],
            check=True, capture_output=True, timeout=timeout,
        )
        os.replace(tmp, obj)
        return obj
    except Exception:
        return None


def build_and_load(
    src: str, so: str, timeout: int = 300,
    extra_flags: Sequence[str] = (),
    aux_sources: Sequence[str] = (),
    aux_flags: Sequence[str] = (),
    extra_deps: Sequence[str] = (),
) -> Optional[ctypes.CDLL]:
    """Compile ``src`` into ``so`` if stale and dlopen it; None on any
    failure (callers fall back to pure-Python paths).

    Staleness tracks the source AND the shared sha3_gf.h header (both
    native libraries include it; a header edit must rebuild both), plus
    any ``extra_deps`` and aux objects.  The build lands in a
    process-unique temp path then atomically renames: other processes
    may have the current .so mapped, and a concurrent importer must
    never CDLL a half-written file.

    ``extra_flags``: additional g++ flags (e.g. the engine's
    ``-DHBE_WORDS=N`` NodeSet-width parameter); callers must encode
    flag-relevant state in the ``so`` filename.

    ``aux_sources``: extra translation units compiled to objects with
    ``aux_flags`` when the toolchain's probe accepts them (dropped
    otherwise — the ISA feature gate for the engine's AVX-512 IFMA
    field-plane arm; the flag outcome is baked into the object name).
    Objects are shared across flag variants of the same ``src`` (they
    must not depend on ``extra_flags``).
    """
    if os.environ.get("HBBFT_TPU_NO_NATIVE"):
        return None

    def _mtime(path: str) -> float:
        return os.path.getmtime(path) if os.path.exists(path) else 0.0

    header = os.path.join(os.path.dirname(src), "sha3_gf.h")
    deps = [header, *extra_deps]
    objs = []
    for aux in aux_sources:
        stem = os.path.join(
            os.path.dirname(so),
            os.path.splitext(os.path.basename(aux))[0],
        )
        obj = _build_aux_object(aux, stem, deps, aux_flags, timeout)
        if obj is None:
            return None
        objs.append(obj)
    newest = max(_mtime(src), *(_mtime(d) for d in deps),
                 *(_mtime(o) for o in objs)) if (deps or objs) else _mtime(src)
    if not os.path.exists(so) or newest > os.path.getmtime(so):
        try:
            os.makedirs(os.path.dirname(so), exist_ok=True)
            tmp = f"{so}.{os.getpid()}.tmp"
            subprocess.run(
                ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread",
                 *extra_flags, "-o", tmp, src, *objs],
                check=True,
                capture_output=True,
                timeout=timeout,
            )
            os.replace(tmp, so)
        except Exception:
            return None
    try:
        return ctypes.CDLL(so)
    except OSError:
        return None


def _load() -> Optional[ctypes.CDLL]:
    lib = build_and_load(_SRC, _SO, timeout=120)
    if lib is None:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.hb_sha3_256.argtypes = [u8p, ctypes.c_uint64, u8p]
    lib.hb_sha3_256_batch.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64, u8p]
    lib.hb_merkle_levels.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64, u8p]
    lib.hb_rs_encode.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64,
                                 ctypes.c_uint64, u8p]
    lib.hb_rs_encode.restype = ctypes.c_int
    lib.hb_rs_reconstruct.argtypes = [u8p, u64p, ctypes.c_uint64,
                                      ctypes.c_uint64, ctypes.c_uint64, u8p]
    lib.hb_rs_reconstruct.restype = ctypes.c_int
    lib.hb_rs16_encode.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64,
                                   ctypes.c_uint64, u8p]
    lib.hb_rs16_encode.restype = ctypes.c_int
    lib.hb_rs16_reconstruct.argtypes = [u8p, u64p, ctypes.c_uint64,
                                        ctypes.c_uint64, ctypes.c_uint64, u8p]
    lib.hb_rs16_reconstruct.restype = ctypes.c_int
    return lib


_LIB: Optional[ctypes.CDLL] = None
_LOADED = False


def _get() -> Optional[ctypes.CDLL]:
    """Lazy memoized loader: the g++ build (first run only) must not be
    an import-time side effect of merely importing gf256/merkle."""
    global _LIB, _LOADED
    if not _LOADED:
        _LIB = _load()
        _LOADED = True
    return _LIB


def available() -> bool:
    return _get() is not None


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def sha3_256(data: bytes) -> bytes:
    buf = np.frombuffer(data, dtype=np.uint8) if data else np.zeros(0, np.uint8)
    out = np.zeros(32, dtype=np.uint8)
    _get().hb_sha3_256(_u8(np.ascontiguousarray(buf)), len(data), _u8(out))
    return out.tobytes()


def sha3_256_batch(msgs: np.ndarray) -> np.ndarray:
    """(batch, m) uint8 -> (batch, 32) uint8."""
    msgs = np.ascontiguousarray(msgs, dtype=np.uint8)
    n, m = msgs.shape
    out = np.zeros((n, 32), dtype=np.uint8)
    _get().hb_sha3_256_batch(_u8(msgs), n, m, _u8(out))
    return out


def merkle_levels(leaves: Sequence[bytes]) -> List[List[bytes]]:
    """Equal-length leaves -> all tree levels, bottom-up (padded)."""
    n = len(leaves)
    leaf_len = len(leaves[0])
    assert all(len(v) == leaf_len for v in leaves)
    size = 1
    while size < n:
        size <<= 1
    flat = np.frombuffer(b"".join(leaves), dtype=np.uint8) if leaf_len else \
        np.zeros(0, np.uint8)
    out = np.zeros((2 * size - 1, 32), dtype=np.uint8)
    _get().hb_merkle_levels(_u8(np.ascontiguousarray(flat)), n, leaf_len, _u8(out))
    levels: List[List[bytes]] = []
    off = 0
    width = size
    while width >= 1:
        levels.append([out[off + i].tobytes() for i in range(width)])
        off += width
        if width == 1:
            break
        width >>= 1
    return levels


def rs_encode(data_shards: Sequence[bytes], n: int) -> Optional[List[bytes]]:
    """k data shards -> n total shards (data + parity); None on error."""
    k = len(data_shards)
    size = len(data_shards[0])
    data = np.frombuffer(b"".join(data_shards), dtype=np.uint8).reshape(k, size)
    data = np.ascontiguousarray(data)
    parity = np.zeros((n - k, size), dtype=np.uint8)
    rc = _get().hb_rs_encode(_u8(data), k, n, size, _u8(parity))
    if rc != 0:
        return None
    return [bytes(s) for s in data] + [bytes(p) for p in parity]


def rs_reconstruct(shards: Dict[int, bytes], k: int, n: int) -> Optional[List[bytes]]:
    """Any k of n shards (by index) -> the k data shards; None on error."""
    idxs = sorted(shards)[:k]
    size = len(shards[idxs[0]])
    have = np.frombuffer(
        b"".join(shards[i] for i in idxs), dtype=np.uint8
    ).reshape(k, size)
    have = np.ascontiguousarray(have)
    idx_arr = np.asarray(idxs, dtype=np.uint64)
    out = np.zeros((k, size), dtype=np.uint8)
    rc = _get().hb_rs_reconstruct(
        _u8(have),
        idx_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        k, n, size, _u8(out),
    )
    if rc != 0:
        return None
    return [bytes(r) for r in out]


def rs16_encode(data_shards: Sequence[bytes], n: int) -> Optional[List[bytes]]:
    """GF(2^16) variant of :func:`rs_encode` (even shard lengths)."""
    k = len(data_shards)
    size = len(data_shards[0])
    data = np.frombuffer(b"".join(data_shards), dtype=np.uint8).reshape(k, size)
    data = np.ascontiguousarray(data)
    parity = np.zeros((n - k, size), dtype=np.uint8)
    rc = _get().hb_rs16_encode(_u8(data), k, n, size, _u8(parity))
    if rc != 0:
        return None
    return [bytes(s) for s in data] + [bytes(p) for p in parity]


def rs16_reconstruct(
    shards: Dict[int, bytes], k: int, n: int
) -> Optional[List[bytes]]:
    """GF(2^16) variant of :func:`rs_reconstruct`."""
    idxs = sorted(shards)[:k]
    size = len(shards[idxs[0]])
    have = np.frombuffer(
        b"".join(shards[i] for i in idxs), dtype=np.uint8
    ).reshape(k, size)
    have = np.ascontiguousarray(have)
    idx_arr = np.asarray(idxs, dtype=np.uint64)
    out = np.zeros((k, size), dtype=np.uint8)
    rc = _get().hb_rs16_reconstruct(
        _u8(have),
        idx_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        k, n, size, _u8(out),
    )
    if rc != 0:
        return None
    return [bytes(r) for r in out]
