"""SHA3-256 Merkle trees and inclusion proofs.

Reference: upstream ``src/broadcast/merkle.rs`` (``MerkleTree``, ``Proof``
over ``tiny-keccak`` SHA3-256) — SURVEY.md §2 #4.  Domain-separated leaf
vs branch hashing prevents proof-length forgeries.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Sequence, Tuple

try:
    from hbbft_tpu.ops import native as _native
except Exception:  # pragma: no cover - native plane is optional
    _native = None


def _h_leaf(data: bytes) -> bytes:
    return hashlib.sha3_256(b"\x00" + data).digest()


def _h_branch(left: bytes, right: bytes) -> bytes:
    return hashlib.sha3_256(b"\x01" + left + right).digest()


@dataclass(frozen=True)
class Proof:
    """Inclusion proof: the leaf value, its index, and the sibling path."""

    value: bytes
    index: int
    path: Tuple[bytes, ...]
    root: bytes

    def well_formed(self) -> bool:
        """Structural check — fields may be arbitrary Byzantine objects."""
        return (
            isinstance(self.value, bytes)
            and isinstance(self.index, int)
            and not isinstance(self.index, bool)
            and isinstance(self.path, tuple)
            and all(isinstance(p, bytes) and len(p) == 32 for p in self.path)
            and isinstance(self.root, bytes)
            and len(self.root) == 32
        )

    def validate(self, n_leaves: int) -> bool:
        """Check the path hashes from ``value`` up to ``root``.

        ``n_leaves`` bounds the expected path length so a forged deeper/
        shallower proof is rejected.
        """
        if not self.well_formed():
            return False
        if not 0 <= self.index < n_leaves:
            return False
        if len(self.path) != _depth(n_leaves):
            return False
        h = _h_leaf(self.value)
        idx = self.index
        for sib in self.path:
            if idx & 1:
                h = _h_branch(sib, h)
            else:
                h = _h_branch(h, sib)
            idx >>= 1
        return h == self.root


def _depth(n_leaves: int) -> int:
    d = 0
    size = 1
    while size < n_leaves:
        size <<= 1
        d += 1
    return d


class MerkleTree:
    """Complete binary tree over the leaves (padded with empty hashes)."""

    def __init__(self, leaves: Sequence[bytes]) -> None:
        assert leaves, "empty tree"
        self.leaves = list(leaves)
        n = len(self.leaves)
        leaf_len = len(self.leaves[0])
        if (
            _native is not None
            and _native.available()
            and all(len(v) == leaf_len for v in self.leaves)
        ):
            # Native C++ fast path (equal-length leaves, the Broadcast
            # shard case); bit-identical to the fallback below.
            self.levels = _native.merkle_levels(self.leaves)
            return
        size = 1 << _depth(n)
        level = [_h_leaf(v) for v in self.leaves]
        level += [_h_leaf(b"")] * (size - n)
        self.levels: List[List[bytes]] = [level]
        while len(level) > 1:
            level = [
                _h_branch(level[i], level[i + 1]) for i in range(0, len(level), 2)
            ]
            self.levels.append(level)

    @property
    def root(self) -> bytes:
        return self.levels[-1][0]

    def proof(self, index: int) -> Proof:
        assert 0 <= index < len(self.leaves)
        path = []
        idx = index
        for level in self.levels[:-1]:
            path.append(level[idx ^ 1])
            idx >>= 1
        return Proof(self.leaves[index], index, tuple(path), self.root)
