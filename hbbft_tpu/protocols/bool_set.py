"""BoolSet: the four subsets of {True, False} as a tiny value type.

Reference: upstream ``src/binary_agreement/bool_set.rs`` (SURVEY.md §2 #5).
"""

from __future__ import annotations

from typing import Iterator

_NONE = 0
_FALSE = 1
_TRUE = 2
_BOTH = 3


class BoolSet:
    """Immutable subset of {False, True} backed by a 2-bit mask."""

    __slots__ = ("mask",)

    def __init__(self, mask: int = 0) -> None:
        assert 0 <= mask <= 3
        object.__setattr__(self, "mask", mask)

    def __setattr__(self, *a) -> None:  # immutability
        raise AttributeError("BoolSet is immutable")

    @staticmethod
    def none() -> "BoolSet":
        return BoolSet(_NONE)

    @staticmethod
    def both() -> "BoolSet":
        return BoolSet(_BOTH)

    @staticmethod
    def single(b: bool) -> "BoolSet":
        return BoolSet(_TRUE if b else _FALSE)

    def insert(self, b: bool) -> "BoolSet":
        return BoolSet(self.mask | (_TRUE if b else _FALSE))

    def __contains__(self, b: bool) -> bool:
        return bool(self.mask & (_TRUE if b else _FALSE))

    def is_subset(self, other: "BoolSet") -> bool:
        return (self.mask & ~other.mask) == 0

    def union(self, other: "BoolSet") -> "BoolSet":
        return BoolSet(self.mask | other.mask)

    def definite(self) -> bool | None:
        """The single element, if this is a singleton."""
        if self.mask == _TRUE:
            return True
        if self.mask == _FALSE:
            return False
        return None

    def __iter__(self) -> Iterator[bool]:
        if self.mask & _FALSE:
            yield False
        if self.mask & _TRUE:
            yield True

    def __len__(self) -> int:
        return bin(self.mask).count("1")

    def __bool__(self) -> bool:
        return self.mask != 0

    def __eq__(self, other: object) -> bool:
        return isinstance(other, BoolSet) and self.mask == other.mask

    def __hash__(self) -> int:
        return self.mask

    def __repr__(self) -> str:
        return f"BoolSet({set(self) or '{}'})"
