"""HoneyBadger: epoch-structured atomic broadcast (Miller et al. 2016).

Reference: upstream ``src/honey_badger/{honey_badger,epoch_state,batch,
builder}.rs`` + ``encryption_schedule.rs`` (SURVEY.md §2 #9,
BASELINE.json:9).  Per epoch: serialize own contribution, threshold-
encrypt it under the master public key (censorship resistance: agree on
ciphertexts *before* anyone can see the contents), run Subset over the
ciphertexts, then one ThresholdDecrypt per accepted ciphertext; the
decrypted contributions form the epoch's ``Batch``.

``EncryptionSchedule`` can skip the encryption layer on configured epochs
(upstream ``EncryptionSchedule::{Always,Never,EveryNthEpoch,TickTock}``).
``max_future_epochs`` bounds buffering for peers who are ahead.

HoneyBadger never terminates on its own — it produces a batch per epoch
for as long as it is driven (as in the reference).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Set, Tuple

from hbbft_tpu.crypto.keys import Ciphertext
from hbbft_tpu.crypto.pool import VerifySink
from hbbft_tpu.obs import trace as _trace
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.subset import Subset, SubsetOutput
from hbbft_tpu.protocols.threshold_decrypt import ThresholdDecrypt
from hbbft_tpu.protocols.errors import ContributionNotEncodable
from hbbft_tpu.protocols.traits import ConsensusProtocol, Step
from hbbft_tpu.utils import canonical_bytes, serde

FAULT_FUTURE_EPOCH = "honey_badger:message-beyond-max-future-epochs"
FAULT_MALFORMED = "honey_badger:malformed-message"
FAULT_FLOOD = "honey_badger:future-epoch-flood"

# Per-sender cap on buffered future-epoch messages.  An honest node sends
# O(N) Subset messages plus a bounded number of ABA/decrypt messages per
# epoch; the multiplier is generous so slow-but-honest peers never hit it,
# while a Byzantine flooder cannot grow memory without bound.
_FUTURE_BUFFER_PER_SENDER_FACTOR = 64
FAULT_BAD_CIPHERTEXT = "honey_badger:invalid-ciphertext"
FAULT_BAD_CONTRIBUTION = "honey_badger:undecodable-contribution"

SUBSET = "subset"
DECRYPT = "decrypt"

# SubsetHandlingStrategy (upstream ``src/honey_badger/`` builder option):
# "incremental" starts decrypting each accepted contribution as Subset
# emits it; "all_at_end" defers until Subset completes, then processes
# the whole set at once.  Final batches are identical either way — the
# strategy only trades decryption-latency overlap against doing one
# batched pass (which also gives the verify pool a bigger flush batch).
INCREMENTAL = "incremental"
ALL_AT_END = "all_at_end"


# ---------------------------------------------------------------------------
# Encryption schedule
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class EncryptionSchedule:
    """Which epochs use threshold encryption.

    kind: "always" | "never" | "every_nth" | "tick_tock"
    ``every_nth``: encrypt on epochs divisible by n.
    ``tick_tock``: alternate n encrypted / n plaintext epochs.
    """

    kind: str = "always"
    n: int = 1

    @staticmethod
    def always() -> "EncryptionSchedule":
        return EncryptionSchedule("always")

    @staticmethod
    def never() -> "EncryptionSchedule":
        return EncryptionSchedule("never")

    @staticmethod
    def every_nth(n: int) -> "EncryptionSchedule":
        return EncryptionSchedule("every_nth", n)

    @staticmethod
    def tick_tock(n: int) -> "EncryptionSchedule":
        return EncryptionSchedule("tick_tock", n)

    def encrypt_on(self, epoch: int) -> bool:
        if self.kind == "always":
            return True
        if self.kind == "never":
            return False
        if self.kind == "every_nth":
            return epoch % self.n == 0
        if self.kind == "tick_tock":
            return (epoch // self.n) % 2 == 0
        raise ValueError(self.kind)


@dataclass(frozen=True)
class Batch:
    """One committed epoch: every accepted node's contribution."""

    epoch: int
    contributions: Tuple[Tuple[Any, Any], ...]  # sorted (proposer, contribution)

    def contribution_map(self) -> Dict[Any, Any]:
        return dict(self.contributions)

    def __repr__(self) -> str:
        return f"Batch(epoch={self.epoch}, from={[p for p, _ in self.contributions]})"


@dataclass(frozen=True)
class HbMessage:
    epoch: int
    kind: str  # SUBSET | DECRYPT
    proposer: Any  # None for SUBSET
    inner: Any


# ---------------------------------------------------------------------------
# Per-epoch state
# ---------------------------------------------------------------------------


class _EpochState:
    """Reference: upstream ``src/honey_badger/epoch_state.rs``."""

    def __init__(self, hb: "HoneyBadger", epoch: int) -> None:
        # Flight-recorder milestone (no-op without an installed tracer;
        # leaf milestones below epoch level are BRACKETED by these
        # open/commit events — obs/export.py).  Epoch-level events carry
        # no proposer: drop any leaf ctx left by the previous message.
        _trace.clear_ctx("proposer")
        _trace.emit("epoch.open", epoch=epoch)
        self.hb = hb
        self.epoch = epoch
        self.encrypted = hb.encryption_schedule.encrypt_on(epoch)
        sink = hb._sink.scoped(lambda s, e=epoch: hb._guard_epoch(e, self._on_subset_step, s))
        self.subset = Subset(
            hb._netinfo, canonical_bytes(hb._session_id, epoch), sink
        )
        self.decrypts: Dict[Any, ThresholdDecrypt] = {}
        self.accepted: Dict[Any, bytes] = {}  # proposer -> subset payload
        self.pending_payloads: List[Tuple[Any, bytes]] = []  # all_at_end buffer
        self.subset_done = False
        self.decrypted: Dict[Any, Any] = {}
        self.faulty_proposers: Set[Any] = set()
        self.proposed = False
        self.batch_emitted = False

    # -- child-step lifting -------------------------------------------
    def _on_subset_step(self, sub_step: Step) -> Step:
        step = sub_step.map_messages(
            lambda m: HbMessage(self.epoch, SUBSET, None, m)
        )
        outputs, step.output = step.output, []
        for out in outputs:
            step.extend(self._on_subset_output(out))
        return step

    def _on_subset_output(self, out: SubsetOutput) -> Step:
        step = Step.empty()
        if out.kind == "contribution":
            self.accepted[out.proposer] = out.value
            if self.hb.subset_handling == ALL_AT_END:
                self.pending_payloads.append((out.proposer, out.value))
            else:
                step.extend(self._start_decrypt(out.proposer, out.value))
        elif out.kind == "done":
            self.subset_done = True
            pending, self.pending_payloads = self.pending_payloads, []
            for proposer, value in pending:
                step.extend(self._start_decrypt(proposer, value))
            step.extend(self._try_batch())
        return step

    def _start_decrypt(self, proposer: Any, payload: bytes) -> Step:
        step = Step.empty()
        if not self.encrypted:
            return step.extend(self._accept_plaintext(proposer, payload))
        _trace.emit("decrypt.start", proposer=proposer)
        ct = serde.try_loads(payload, suite=self.hb._suite())
        if not isinstance(ct, Ciphertext):
            self.faulty_proposers.add(proposer)
            step.fault(proposer, FAULT_BAD_CIPHERTEXT)
            return step.extend(self._try_batch())
        td = self._get_decrypt(proposer)
        step.extend(
            self.hb._guard_epoch(
                self.epoch,
                lambda s, p=proposer: self._on_decrypt_step(p, s),
                td.handle_input(ct, None),
            )
        )
        return step

    def _get_decrypt(self, proposer: Any) -> ThresholdDecrypt:
        if proposer not in self.decrypts:
            sink = self.hb._sink.scoped(
                lambda s, e=self.epoch, p=proposer: self.hb._guard_epoch(
                    e, lambda cs: self._on_decrypt_step(p, cs), s
                )
            )
            self.decrypts[proposer] = ThresholdDecrypt(self.hb._netinfo, sink)
        return self.decrypts[proposer]

    def _on_decrypt_step(self, proposer: Any, td_step: Step) -> Step:
        step = td_step.map_messages(
            lambda m: HbMessage(self.epoch, DECRYPT, proposer, m)
        )
        outputs, step.output = step.output, []
        td = self.decrypts.get(proposer)
        if td is not None and td.ciphertext_invalid and proposer not in self.faulty_proposers:
            self.faulty_proposers.add(proposer)
            step.fault(proposer, FAULT_BAD_CIPHERTEXT)
            step.extend(self._try_batch())
        if outputs:
            _trace.emit("decrypt.done", proposer=proposer)
        for plaintext in outputs:
            step.extend(self._accept_plaintext(proposer, plaintext))
        return step

    def _accept_plaintext(self, proposer: Any, data: bytes) -> Step:
        step = Step.empty()
        if proposer in self.decrypted or proposer in self.faulty_proposers:
            return step
        # loads (not try_loads): a legitimate None contribution must be
        # distinguishable from malformed bytes.
        try:
            self.decrypted[proposer] = serde.loads(data, suite=self.hb._suite())
        except serde.DecodeError:
            self.faulty_proposers.add(proposer)
            step.fault(proposer, FAULT_BAD_CONTRIBUTION)
        return step.extend(self._try_batch())

    # -- message routing ----------------------------------------------
    def handle_message(self, sender: Any, msg: HbMessage, rng: Any) -> Step:
        if msg.kind == SUBSET:
            return self._on_subset_step(
                self.subset.handle_message(sender, msg.inner, rng)
            )
        if msg.kind == DECRYPT:
            if not self.encrypted:
                return Step.empty().fault(sender, FAULT_BAD_CIPHERTEXT)
            try:
                known = self.hb._netinfo.is_node_validator(msg.proposer)
            except TypeError:  # unhashable garbage from a faulty peer
                known = False
            if not known:
                return Step.empty().fault(sender, FAULT_BAD_CIPHERTEXT)
            td = self._get_decrypt(msg.proposer)
            return self._on_decrypt_step(
                msg.proposer, td.handle_message(sender, msg.inner, rng)
            )
        return Step.empty()

    # -- completion ----------------------------------------------------
    def _try_batch(self) -> Step:
        step = Step.empty()
        if self.batch_emitted or not self.subset_done:
            return step
        pending = [
            p
            for p in self.accepted
            if p not in self.decrypted and p not in self.faulty_proposers
        ]
        if pending:
            return step
        self.batch_emitted = True
        batch = Batch(
            self.epoch,
            tuple(sorted(self.decrypted.items(), key=lambda kv: str(kv[0]))),
        )
        _trace.clear_ctx("proposer")  # epoch events carry no proposer
        _trace.emit(
            "epoch.commit", epoch=self.epoch, contribs=len(batch.contributions)
        )
        step.with_output(batch)
        return step


# ---------------------------------------------------------------------------
# HoneyBadger proper
# ---------------------------------------------------------------------------


class HoneyBadger(ConsensusProtocol):
    def __init__(
        self,
        netinfo: NetworkInfo,
        sink: VerifySink,
        session_id: bytes = b"hb",
        max_future_epochs: int = 3,
        encryption_schedule: EncryptionSchedule = EncryptionSchedule.always(),
        subset_handling: str = INCREMENTAL,
    ) -> None:
        if subset_handling not in (INCREMENTAL, ALL_AT_END):
            raise ValueError(f"bad subset_handling: {subset_handling!r}")
        self._netinfo = netinfo
        self._sink = sink
        self._session_id = bytes(session_id)
        self.max_future_epochs = max_future_epochs
        self.encryption_schedule = encryption_schedule
        self.subset_handling = subset_handling
        self._epoch = 0
        self._state = _EpochState(self, 0)
        self._future: Dict[int, List[Tuple[Any, HbMessage]]] = {}
        self._future_per_sender: Dict[Any, int] = {}
        self._pending_proposal: Optional[Tuple[Any, Any, bytes]] = None

    def _suite(self) -> Any:
        """The network's crypto suite — pins serde decoding so committed
        bytes cannot select a different (e.g. the insecure test) suite."""
        return self._netinfo.public_key_set.suite

    # -- ConsensusProtocol --------------------------------------------
    @property
    def our_id(self) -> Any:
        return self._netinfo.our_id

    @property
    def terminated(self) -> bool:
        return False  # HB is a service: one batch per epoch, forever

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def has_input(self) -> bool:
        """Whether we have proposed in the current epoch."""
        return self._state.proposed

    def handle_input(self, input: Any, rng: Any) -> Step:
        """Propose ``input`` (any codec-encodable contribution) this
        epoch: primitives, containers, and the registered wire types
        (see :mod:`hbbft_tpu.wire`).  Raises
        :class:`~hbbft_tpu.protocols.errors.ContributionNotEncodable`
        for anything else — at the boundary, before any state changes.

        A proposal made while the current epoch already has one is held
        and submitted at the next epoch start.
        """
        if not self._netinfo.is_validator():
            return Step.empty()
        try:
            data = serde.dumps(input)
        except serde.EncodeError as e:
            raise ContributionNotEncodable(str(e)) from e
        if self._state.proposed:
            # Hold (with its rng — the epoch may roll over inside a
            # verify-pool flush, where no caller rng is in scope).
            self._pending_proposal = (input, rng, data)
            return Step.empty()
        return self._propose_now(input, rng, data)

    def _propose_now(self, input: Any, rng: Any, data: Optional[bytes] = None) -> Step:
        self._state.proposed = True
        if data is None:
            data = serde.dumps(input)
        if self._state.encrypted:
            pk = self._netinfo.public_key_set.public_key()
            data = serde.dumps(pk.encrypt(data, rng))
        return self._guard_epoch(
            self._epoch, self._state._on_subset_step, self._state.subset.handle_input(data, rng)
        )

    def handle_message(self, sender: Any, message: HbMessage, rng: Any) -> Step:
        step = Step.empty()
        if (
            not isinstance(message, HbMessage)
            or not isinstance(message.epoch, int)
            or isinstance(message.epoch, bool)
            or message.kind not in (SUBSET, DECRYPT)
        ):
            return step.fault(sender, FAULT_MALFORMED)
        if message.epoch < self._epoch:
            return step  # stale epoch: drop
        if message.epoch > self._epoch + self.max_future_epochs:
            return step.fault(sender, FAULT_FUTURE_EPOCH)
        if message.epoch > self._epoch:
            cap = (
                _FUTURE_BUFFER_PER_SENDER_FACTOR
                * (self.max_future_epochs + 1)
                * max(1, self._netinfo.num_nodes)
            )
            buffered = self._future_per_sender.get(sender, 0)
            if buffered >= cap:
                return step.fault(sender, FAULT_FLOOD)
            self._future_per_sender[sender] = buffered + 1
            self._future.setdefault(message.epoch, []).append((sender, message))
            return step
        step.extend(self._state.handle_message(sender, message, rng))
        return step.extend(self._advance(rng))

    # -- epoch transitions --------------------------------------------
    def _guard_epoch(self, epoch: int, fn, child_step: Step) -> Step:
        """Run a child-step lift only if ``epoch`` is still current; late
        verification results of completed epochs keep only their faults."""
        if epoch != self._epoch:
            return Step(output=[], messages=[], fault_log=child_step.fault_log)
        step = fn(child_step)
        return step.extend(self._advance(None))

    def _advance(self, rng: Any) -> Step:
        step = Step.empty()
        while self._state.batch_emitted:
            self._epoch += 1
            self._state = _EpochState(self, self._epoch)
            if self._pending_proposal is not None:
                (proposal, prop_rng, data), self._pending_proposal = (
                    self._pending_proposal,
                    None,
                )
                step.extend(self._propose_now(proposal, prop_rng, data))
            replay = self._future.pop(self._epoch, [])
            for sender, msg in replay:
                remaining = self._future_per_sender.get(sender, 1) - 1
                if remaining > 0:
                    self._future_per_sender[sender] = remaining
                else:
                    self._future_per_sender.pop(sender, None)
                step.extend(self._state.handle_message(sender, msg, rng))
        return step
