"""The universal state-machine contract.

Reference: upstream ``src/traits.rs`` + ``src/lib.rs`` (``ConsensusProtocol``
trait with associated types ``NodeId/Input/Output/Message/FaultKind``,
``Step`` as the sole side-effect channel, ``Target``/``TargetedMessage``
routing).  Fork checkout was empty at survey time; see SURVEY.md §2 #1.

Design deviations (TPU-first, per SURVEY.md §7):

* ``Step`` is a plain dataclass with an explicit ``merge``; protocols build
  steps functionally.
* Cryptographic verification is *deferred*: protocols submit
  ``VerifyRequest``s to a :class:`hbbft_tpu.crypto.pool.VerifyPool` and
  receive results through ``on_verified`` callbacks, so an epoch's worth of
  pairing checks can be flushed to the TPU as one batch (the north star in
  BASELINE.json:5).  With an eager flush policy the observable behavior is
  identical to the reference's inline verification.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Callable, FrozenSet, Generic, Iterable, List, TypeVar

from hbbft_tpu.protocols.fault_log import FaultLog

N = TypeVar("N")  # NodeId type


@dataclass(frozen=True)
class Target:
    """Message routing directive without a transport.

    Reference: upstream ``Target::{All, AllExcept(set), Nodes(set)}``.
    (No ``slots=True`` here: the ``nodes`` field's slot descriptor would
    shadow the ``nodes()`` constructor.)
    """

    kind: str  # "all" | "all_except" | "nodes"
    nodes: FrozenSet[Any] = frozenset()

    ALL = "all"
    ALL_EXCEPT = "all_except"
    NODES = "nodes"

    @staticmethod
    def all() -> "Target":
        return _TARGET_ALL

    @staticmethod
    def all_except(nodes: Iterable[Any]) -> "Target":
        return Target(Target.ALL_EXCEPT, frozenset(nodes))

    @staticmethod
    def nodes(nodes: Iterable[Any]) -> "Target":
        return Target(Target.NODES, frozenset(nodes))

    @staticmethod
    def node(node: Any) -> "Target":
        return Target(Target.NODES, frozenset([node]))

    def recipients(self, all_ids: Iterable[Any], our_id: Any) -> List[Any]:
        """Expand to a concrete recipient list (excluding ourselves)."""
        if self.kind == Target.ALL:
            return [n for n in all_ids if n != our_id]
        if self.kind == Target.ALL_EXCEPT:
            return [n for n in all_ids if n != our_id and n not in self.nodes]
        return [n for n in self.nodes if n != our_id]


_TARGET_ALL = Target(Target.ALL)


@dataclass(frozen=True, slots=True)
class TargetedMessage:
    """An outgoing message with its routing directive."""

    target: Target
    message: Any


@dataclass(frozen=True, slots=True)
class SourcedMessage:
    """An incoming message tagged with its sender."""

    sender: Any
    message: Any


@dataclass(slots=True)
class Step:
    """The sole side-effect channel of every protocol handler.

    Reference: upstream ``Step{output, fault_log, messages}``.
    """

    output: List[Any] = field(default_factory=list)
    messages: List[TargetedMessage] = field(default_factory=list)
    fault_log: FaultLog = field(default_factory=FaultLog)

    @staticmethod
    def empty() -> "Step":
        return Step()

    def extend(self, other: "Step") -> "Step":
        """Merge ``other`` into self (in place), returning self."""
        if other.output:
            self.output.extend(other.output)
        if other.messages:
            self.messages.extend(other.messages)
        if other.fault_log.faults:
            self.fault_log.extend(other.fault_log)
        return self

    def with_output(self, out: Any) -> "Step":
        self.output.append(out)
        return self

    def map_messages(self, wrap: Callable[[Any], Any]) -> "Step":
        """Wrap every message payload IN PLACE and return self.

        This is how parent protocols lift child messages into their own
        message type (reference: ``Step::map`` in upstream ``src/traits.rs``).
        Output and fault log are carried through unchanged.  The caller
        must not reuse the un-wrapped step afterwards — every handler
        merges the result into a fresh parent step, and the copying
        version's per-message allocations dominated the control-plane
        profile at N=64.
        """
        msgs = self.messages
        for i, m in enumerate(msgs):
            msgs[i] = TargetedMessage(m.target, wrap(m.message))
        return self

    def broadcast(self, message: Any) -> "Step":
        self.messages.append(TargetedMessage(Target.all(), message))
        return self

    def send(self, node: Any, message: Any) -> "Step":
        self.messages.append(TargetedMessage(Target.node(node), message))
        return self

    def send_targeted(self, target: Target, message: Any) -> "Step":
        self.messages.append(TargetedMessage(target, message))
        return self

    def fault(self, node_id: Any, kind: str) -> "Step":
        self.fault_log.append_fault(node_id, kind)
        return self


class ConsensusProtocol(abc.ABC, Generic[N]):
    """Base contract for every protocol instance.

    Reference: upstream ``ConsensusProtocol`` trait (``handle_input``,
    ``handle_message``, ``terminated``, ``our_id``); name varies by
    revision (older: ``DistAlgorithm``).
    """

    @abc.abstractmethod
    def handle_input(self, input: Any, rng: Any) -> Step:
        """Process a local input (propose a value, cast a vote, ...)."""

    @abc.abstractmethod
    def handle_message(self, sender: N, message: Any, rng: Any) -> Step:
        """Process a message received from ``sender``."""

    @property
    @abc.abstractmethod
    def terminated(self) -> bool:
        """True once this instance will produce no further output."""

    @property
    @abc.abstractmethod
    def our_id(self) -> N:
        """Our own node id."""
