"""Immutable per-instance view of the validator set.

Reference: upstream ``src/network_info.rs`` (``NetworkInfo``: ordered node
map, threshold ``PublicKeySet``, our ``SecretKeyShare``, ``num_faulty =
(N-1)/3``).  Fork checkout empty at survey time; see SURVEY.md §2 #2.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple


class NetworkInfo:
    """Validator-set view held (shared) by every protocol instance.

    Parameters
    ----------
    our_id:
        This node's id (may be an observer not in ``val_ids``).
    val_ids:
        The validator ids; stored sorted, and a validator's *index* (used
        for threshold-crypto share evaluation points) is its position in
        the sorted order.
    public_key_set:
        The threshold master public key (commitment to the secret poly).
    secret_key_share:
        Our share of the master secret; ``None`` for observers.
    public_keys:
        Per-node *regular* public keys (vote signing, DKG row encryption).
    secret_key:
        Our regular secret key.
    """

    def __init__(
        self,
        our_id: Any,
        val_ids: Sequence[Any],
        public_key_set: Any,
        secret_key_share: Optional[Any] = None,
        public_keys: Optional[Dict[Any, Any]] = None,
        secret_key: Optional[Any] = None,
    ) -> None:
        self._our_id = our_id
        self._val_ids: Tuple[Any, ...] = tuple(sorted(val_ids))
        self._index = {n: i for i, n in enumerate(self._val_ids)}
        self._public_key_set = public_key_set
        self._secret_key_share = secret_key_share
        self._public_keys = dict(public_keys or {})
        self._secret_key = secret_key

    # -- identities ---------------------------------------------------
    @property
    def our_id(self) -> Any:
        return self._our_id

    @property
    def all_ids(self) -> Tuple[Any, ...]:
        return self._val_ids

    def index(self, node_id: Any) -> int:
        return self._index[node_id]

    def contains(self, node_id: Any) -> bool:
        return node_id in self._index

    @property
    def our_index(self) -> Optional[int]:
        return self._index.get(self._our_id)

    def is_validator(self) -> bool:
        """Whether WE actively participate: listed in the validator set
        AND holding our threshold key share.  A node can be listed but
        share-less — e.g. it joined from a ``JoinPlan`` of an era whose
        DKG it did not observe; it then acts as an observer (commits
        batches, signs nothing) until a later era's DKG deals it a
        share.  Peers cannot distinguish this (``is_node_validator`` is
        membership-only), which is safe: the protocols never rely on a
        specific validator contributing, only on thresholds."""
        return self._our_id in self._index and self._secret_key_share is not None

    def is_node_validator(self, node_id: Any) -> bool:
        return node_id in self._index

    # -- sizes --------------------------------------------------------
    @property
    def num_nodes(self) -> int:
        return len(self._val_ids)

    @property
    def num_faulty(self) -> int:
        """f = (N-1)//3, the maximum tolerated Byzantine nodes."""
        return (len(self._val_ids) - 1) // 3

    @property
    def num_correct(self) -> int:
        return self.num_nodes - self.num_faulty

    # -- keys ---------------------------------------------------------
    @property
    def public_key_set(self) -> Any:
        return self._public_key_set

    @property
    def secret_key_share(self) -> Optional[Any]:
        return self._secret_key_share

    @property
    def secret_key(self) -> Optional[Any]:
        return self._secret_key

    def public_key(self, node_id: Any) -> Any:
        return self._public_keys[node_id]

    @property
    def public_key_map(self) -> Dict[Any, Any]:
        return dict(self._public_keys)

    def public_key_share(self, node_id: Any) -> Any:
        """The threshold public-key share of ``node_id`` (by index)."""
        return self._public_key_set.public_key_share(self.index(node_id))

    def __repr__(self) -> str:
        return (
            f"NetworkInfo(our_id={self._our_id!r}, n={self.num_nodes}, "
            f"f={self.num_faulty}, validator={self.is_validator()})"
        )
