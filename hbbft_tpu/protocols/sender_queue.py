"""SenderQueue: epoch-aware outbox for real (lossless, ordered) links.

Reference: upstream ``src/sender_queue/{mod,message,honey_badger,
dynamic_honey_badger,queueing_honey_badger}.rs`` (SURVEY.md §2 #13).

The wrapped protocol's messages are only valid within an (era, epoch)
window; sending one to a peer that is far behind would make the peer
flag us as a flooder, and sending to a peer that has moved on wastes
bandwidth.  ``SenderQueue``:

* broadcasts ``EpochStarted(era, epoch)`` whenever our own protocol
  advances;
* tracks every peer's last announced (era, epoch);
* expands ``Target::All``-style messages into per-peer sends and holds
  each until the peer's announced window admits it (ahead-of-window
  messages buffer; behind-of-window messages drop);
* implements ``ConsensusProtocol`` itself, so the caller's event loop
  sees one protocol.

Adapters: any wrapped protocol works given an ``epoch_of(message) ->
(era, epoch)`` and a ``current_epoch(protocol) -> (era, epoch)``; the
standard ones for HoneyBadger / DynamicHoneyBadger /
QueueingHoneyBadger are provided (upstream's
``SenderQueueableConsensusProtocol`` impls).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from hbbft_tpu.protocols.dynamic_honey_badger import DhbMessage, DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import HbMessage, HoneyBadger
from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger
from hbbft_tpu.protocols.traits import ConsensusProtocol, Step

FAULT_MALFORMED = "sender_queue:malformed-message"

EpochId = Tuple[int, int]  # (era, epoch), lexicographic


@dataclass(frozen=True)
class SqMessage:
    kind: str  # "epoch_started" | "algo" | "join_plan"
    value: Any

    @staticmethod
    def epoch_started(epoch: EpochId) -> "SqMessage":
        return SqMessage("epoch_started", epoch)

    @staticmethod
    def algo(inner: Any) -> "SqMessage":
        return SqMessage("algo", inner)

    @staticmethod
    def join_plan(plan: Any) -> "SqMessage":
        return SqMessage("join_plan", plan)


def _hb_epoch_of(message: HbMessage) -> EpochId:
    return (0, message.epoch)


def _hb_current(hb: HoneyBadger) -> EpochId:
    return (0, hb.epoch)


def _dhb_epoch_of(message: DhbMessage) -> EpochId:
    return (message.era, message.inner.epoch)


def _dhb_current(dhb: DynamicHoneyBadger) -> EpochId:
    return (dhb.era, dhb._hb.epoch)


def _qhb_current(qhb: QueueingHoneyBadger) -> EpochId:
    return _dhb_current(qhb.dhb)


class SenderQueue(ConsensusProtocol):
    def __init__(
        self,
        inner: ConsensusProtocol,
        peers: List[Any],
        epoch_of: Optional[Callable[[Any], EpochId]] = None,
        current_epoch: Optional[Callable[[Any], EpochId]] = None,
        max_future_epochs: int = 3,
    ) -> None:
        self.inner = inner
        self.max_future_epochs = max_future_epochs
        self._epoch_of = epoch_of or _default_epoch_of(inner)
        self._current = current_epoch or _default_current(inner)
        self._peers = [p for p in peers if p != inner.our_id]
        self._peer_epochs: Dict[Any, EpochId] = {p: (0, 0) for p in self._peers}
        self._outbox: Dict[Any, List[Tuple[EpochId, Any]]] = {p: [] for p in self._peers}
        self._last_announced: Optional[EpochId] = None
        # Membership-change duties (upstream ``src/sender_queue/
        # dynamic_honey_badger.rs``): current validator set (for diffing
        # era changes), peers already handed a JoinPlan, and departing
        # validators with the era whose announcement releases them.
        self._validator_ids = set(_validator_ids_of(inner))
        self._join_plan_sent: set = set()
        self._departing: Dict[Any, int] = {}
        self._removed: set = set()

    @classmethod
    def wrap(
        cls,
        inner_factory: Callable[[Any], ConsensusProtocol],
        sink: Any,
        peers: List[Any],
        **kwargs: Any,
    ) -> "SenderQueue":
        """Build the inner protocol with a sink scoped through this
        SenderQueue, so steps surfacing from deferred-verification
        flushes are epoch-gated and wrapped exactly like ordinary ones.

        ``inner_factory(scoped_sink) -> protocol``.
        """
        box: List["SenderQueue"] = []
        scoped = sink.scoped(lambda step: box[0]._post(step) if box else step)
        inner = inner_factory(scoped)
        sq = cls(inner, peers, **kwargs)
        box.append(sq)
        return sq

    # -- ConsensusProtocol --------------------------------------------
    @property
    def our_id(self) -> Any:
        return self.inner.our_id

    @property
    def terminated(self) -> bool:
        return self.inner.terminated

    def handle_input(self, input: Any, rng: Any) -> Step:
        return self._post(self.inner.handle_input(input, rng))

    def handle_message(self, sender: Any, message: Any, rng: Any) -> Step:
        if not isinstance(message, SqMessage):
            return Step.empty().fault(sender, FAULT_MALFORMED)
        if message.kind == "epoch_started":
            return self._on_epoch_started(sender, message.value)
        if message.kind == "algo":
            return self._post(self.inner.handle_message(sender, message.value, rng))
        if message.kind == "join_plan":
            return Step.empty()  # already joined: nothing to do
        return Step.empty().fault(sender, FAULT_MALFORMED)

    # -- internals -----------------------------------------------------
    def _on_epoch_started(self, peer: Any, epoch: Any) -> Step:
        step = Step.empty()
        if (
            not isinstance(epoch, tuple)
            or len(epoch) != 2
            or not all(isinstance(x, int) and not isinstance(x, bool) for x in epoch)
        ):
            return step.fault(peer, FAULT_MALFORMED)
        dep_era = self._departing.get(peer)
        if dep_era is not None and epoch[0] >= dep_era:
            # Deferred removal completes: the departing validator has
            # announced the era past its membership, i.e. it observed
            # the change-complete batch — its last epoch's messages have
            # drained and we stop serving it.
            self._departing.pop(peer, None)
            self._peer_epochs.pop(peer, None)
            self._outbox.pop(peer, None)
            if peer in self._peers:
                self._peers.remove(peer)
            self._removed.add(peer)
            return step
        if peer in self._removed:
            return step  # gone until a future change re-adds it
        if peer not in self._peer_epochs:
            self._peer_epochs[peer] = (0, 0)
            self._outbox[peer] = []
            self._peers.append(peer)
        if epoch <= self._peer_epochs[peer]:
            return step
        self._peer_epochs[peer] = epoch
        held, self._outbox[peer] = self._outbox[peer], []
        for msg_epoch, msg in held:
            self._route(step, peer, msg_epoch, msg)
        return step

    # mirror: sq-admission — this send/hold/drop window decision is
    #     mirrored by `cluster_admit` in native/engine.cpp; divergence
    #     makes the two impls deliver different message sets.
    def _admits(self, peer_epoch: EpochId, msg_epoch: EpochId) -> str:
        """'send' | 'hold' | 'drop' for a message vs a peer's window."""
        if msg_epoch[0] < peer_epoch[0]:
            return "drop"  # stale era
        if msg_epoch[0] > peer_epoch[0]:
            return "hold"  # future era: wait for the peer to get there
        if msg_epoch[1] < peer_epoch[1]:
            return "drop"  # stale epoch
        if msg_epoch[1] > peer_epoch[1] + self.max_future_epochs:
            return "hold"
        return "send"

    def _route(self, step: Step, peer: Any, msg_epoch: EpochId, msg: Any) -> None:
        verdict = self._admits(self._peer_epochs[peer], msg_epoch)
        if verdict == "send":
            step.send(peer, SqMessage.algo(msg))
        elif verdict == "hold":
            self._outbox[peer].append((msg_epoch, msg))

    def _post(self, inner_step: Step) -> Step:
        """Expand + gate the inner step's messages; announce our epoch."""
        step = Step(
            output=inner_step.output, messages=[], fault_log=inner_step.fault_log
        )
        for out in inner_step.output:
            self._on_batch(step, out)
        for tm in inner_step.messages:
            recipients = tm.target.recipients(self._peers, None)
            msg_epoch = self._epoch_of(tm.message)
            for peer in recipients:
                if peer == self.our_id:
                    continue
                self._route(step, peer, msg_epoch, tm.message)
        cur = self._current(self.inner)
        if cur != self._last_announced:
            self._last_announced = cur
            step.broadcast(SqMessage.epoch_started(cur))
        return step

    def _on_batch(self, step: Step, out: Any) -> None:
        """Membership-change duties on a change-complete batch (upstream
        ``src/sender_queue/dynamic_honey_badger.rs``): hand the
        ``JoinPlan`` to newly-added peers through the queue, and mark
        removed validators as *departing* — they keep receiving their
        final era's messages and are only dropped once they announce the
        new era (deferred removal)."""
        plan = getattr(out, "join_plan", None)
        change = getattr(out, "change", None)
        if plan is None or change is None or change.kind != "complete":
            return
        new_ids = set(plan.validator_map())
        added = new_ids - self._validator_ids
        removed = self._validator_ids - new_ids
        self._validator_ids = new_ids
        # Era expiry for departing peers that never announced (crashed
        # before observing their removal): once a LATER era completes,
        # they have missed a whole era — stop serving them, else their
        # outbox grows without bound for the lifetime of the network.
        for peer, dep_era in list(self._departing.items()):
            if dep_era < plan.era:
                self._departing.pop(peer, None)
                self._peer_epochs.pop(peer, None)
                self._outbox.pop(peer, None)
                if peer in self._peers:
                    self._peers.remove(peer)
                self._removed.add(peer)
        for peer in removed:
            if peer != self.our_id and peer in self._peer_epochs:
                self._departing[peer] = plan.era
            # A removed validator re-added by a LATER change must get
            # that change's JoinPlan again.
            self._join_plan_sent.discard(peer)
        for peer in sorted(added, key=str):
            if peer == self.our_id:
                continue
            self._removed.discard(peer)
            self._departing.pop(peer, None)
            if peer not in self._peer_epochs:
                self._peer_epochs[peer] = (plan.era, 0)
                self._outbox[peer] = []
                self._peers.append(peer)
            if peer not in self._join_plan_sent:
                self._join_plan_sent.add(peer)
                step.send(peer, SqMessage.join_plan(plan))


class JoiningSenderQueue(ConsensusProtocol):
    """A node that is not yet a participant: it waits for a
    :class:`~hbbft_tpu.protocols.dynamic_honey_badger.JoinPlan` handed
    through a peer's SenderQueue, then constructs its protocol from the
    plan and becomes a live :class:`SenderQueue` — no manual plumbing.

    ``make_inner(join_plan, sink) -> protocol`` builds the inner
    protocol (default: ``DynamicHoneyBadger.from_join_plan``; pass a
    QHB-building factory for the queueing stack).  Messages arriving
    before the plan are buffered (bounded) and replayed after joining.

    Trust: ``join_quorum`` distinct peers must deliver value-identical
    plans before joining (default 1 — first valid plan wins, the
    reference's application-trusted stance; set it to f+1 so no
    coalition of <= f Byzantine peers can feed a forged plan).
    """

    _MAX_BUFFER = 4096

    def __init__(
        self,
        our_id: Any,
        secret_key: Any,
        sink: Any,
        peers: List[Any],
        make_inner: Optional[Callable[[Any, Any], ConsensusProtocol]] = None,
        max_future_epochs: int = 3,
        session_id: bytes = b"dhb",
        join_quorum: int = 1,
    ) -> None:
        self._our_id = our_id
        self._secret_key = secret_key
        self._sink = sink
        self._peers = list(peers)
        self._max_future_epochs = max_future_epochs
        self._session_id = session_id
        self._make_inner = make_inner
        self._join_quorum = max(1, join_quorum)
        # One endorsed plan per configured peer: a peer re-sending a
        # different plan replaces its previous vote, so at most
        # len(peers) candidate plans are ever retained (Byzantine peers
        # cannot grow memory with novel forged plans), and votes from
        # senders outside the configured peer set never count toward
        # the quorum.
        self._plan_vote_by_peer: Dict[Any, bytes] = {}
        self._plan_votes: Dict[bytes, set] = {}
        self._plan_by_digest: Dict[bytes, Any] = {}
        self._sq: Optional[SenderQueue] = None
        self._buffer: List[Tuple[Any, Any]] = []

    @property
    def our_id(self) -> Any:
        return self._our_id

    @property
    def terminated(self) -> bool:
        return False

    @property
    def joined(self) -> bool:
        return self._sq is not None

    @property
    def inner(self) -> Optional[ConsensusProtocol]:
        return self._sq.inner if self._sq is not None else None

    def handle_input(self, input: Any, rng: Any) -> Step:
        if self._sq is None:
            return Step.empty()  # not a participant yet
        return self._sq.handle_input(input, rng)

    def handle_message(self, sender: Any, message: Any, rng: Any) -> Step:
        if self._sq is not None:
            return self._sq.handle_message(sender, message, rng)
        if not isinstance(message, SqMessage):
            return Step.empty().fault(sender, FAULT_MALFORMED)
        if message.kind == "join_plan":
            return self._join(message.value, sender, rng)
        if len(self._buffer) < self._MAX_BUFFER:
            self._buffer.append((sender, message))
        return Step.empty()

    def _join(self, plan: Any, sender: Any, rng: Any) -> Step:
        from hbbft_tpu.protocols.dynamic_honey_badger import JoinPlan
        from hbbft_tpu.utils import serde

        if not isinstance(plan, JoinPlan):
            return Step.empty().fault(sender, FAULT_MALFORMED)
        if self._join_quorum > 1:
            if sender not in self._peers:
                # Only configured peers vote: transport-level spoofing /
                # unexpected senders must not weaken the f+1 quorum.
                return Step.empty().fault(sender, FAULT_MALFORMED)
            try:
                digest = serde.dumps(plan)
            except serde.EncodeError:
                return Step.empty().fault(sender, FAULT_MALFORMED)
            prev = self._plan_vote_by_peer.get(sender)
            if prev is not None and prev != digest:
                votes = self._plan_votes.get(prev)
                if votes is not None:
                    votes.discard(sender)
                    if not votes:
                        del self._plan_votes[prev]
                        del self._plan_by_digest[prev]
            self._plan_vote_by_peer[sender] = digest
            self._plan_votes.setdefault(digest, set()).add(sender)
            self._plan_by_digest[digest] = plan
            if len(self._plan_votes[digest]) < self._join_quorum:
                return Step.empty()
            plan = self._plan_by_digest[digest]

        def default_make(p: Any, sink: Any) -> ConsensusProtocol:
            return DynamicHoneyBadger.from_join_plan(
                self._our_id,
                self._secret_key,
                p,
                sink,
                session_id=self._session_id,
                max_future_epochs=self._max_future_epochs,
            )

        make = self._make_inner or default_make
        self._sq = SenderQueue.wrap(
            lambda scoped: make(plan, scoped),
            self._sink,
            peers=self._peers,
            max_future_epochs=self._max_future_epochs,
        )
        # Announce where we are and replay anything that arrived early.
        step = self._sq._post(Step.empty())
        buffered, self._buffer = self._buffer, []
        for peer, msg in buffered:
            step.extend(self._sq.handle_message(peer, msg, rng))
        return step


def _validator_ids_of(inner: ConsensusProtocol) -> Tuple[Any, ...]:
    ni = getattr(inner, "netinfo", None)
    if ni is None:
        return ()
    return tuple(getattr(ni, "all_ids", ()))


def _default_epoch_of(inner: ConsensusProtocol) -> Callable[[Any], EpochId]:
    if isinstance(inner, (DynamicHoneyBadger, QueueingHoneyBadger)):
        return _dhb_epoch_of
    if isinstance(inner, HoneyBadger):
        return _hb_epoch_of
    raise TypeError(f"no SenderQueue adapter for {type(inner)!r}")


def _default_current(inner: ConsensusProtocol) -> Callable[[Any], EpochId]:
    if isinstance(inner, QueueingHoneyBadger):
        return _qhb_current
    if isinstance(inner, DynamicHoneyBadger):
        return _dhb_current
    if isinstance(inner, HoneyBadger):
        return _hb_current
    raise TypeError(f"no SenderQueue adapter for {type(inner)!r}")
