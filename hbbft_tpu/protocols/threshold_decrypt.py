"""ThresholdDecrypt: cooperative decryption of one threshold ciphertext.

Reference: upstream ``src/threshold_decrypt.rs`` (SURVEY.md §2 #7).  On
receiving the ciphertext each validator checks its validity (pairing
check), emits its decryption share, verifies every incoming share against
the ciphertext and the sender's public-key share (pairing check — hot
loop), and after ``f + 1`` valid shares combines them into the plaintext.

Shares arriving before the ciphertext are buffered raw and verified once
the ciphertext is known — asynchrony means peers may be ahead of us.

Combining is delegated to ``PublicKeySet.combine_decryption_shares``,
which on the scalar suite routes through the engine's vectorized
Lagrange+unmask entry point (``hbe_scalar_combine_unmask``, round 6):
the per-epoch combine of a DKG-sized ciphertext — Lagrange sum plus a
kdf stream over hundreds of KB — was part of the measured era-change
batch tail, and is byte-identical through either path.

Native-engine mirror (round 7): the engine batch-verifies each flush's
pending decryption shares of one instance with a single RLC check —
``Σ rᵢ·shareᵢ·H(ct) == (Σ rᵢ·pkᵢ)·ct.w`` — bisecting failed groups so
bad shares get the same :data:`FAULT_INVALID_SHARE` attribution as this
per-share path (``HBBFT_TPU_COIN_RLC=0`` restores per-share checks;
tests/test_native_rlc.py pins the matrix).  Changes to the acceptance
rules here (buffering, the terminated gate, fault timing) must be
mirrored in ``native/engine.cpp``'s ``td_verified_cb`` AND
``td_group_verified_cb``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Set

from hbbft_tpu.crypto.backend import VerifyRequest
from hbbft_tpu.crypto.keys import Ciphertext, DecryptionShare
from hbbft_tpu.crypto.pool import VerifySink
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.traits import ConsensusProtocol, Step

FAULT_INVALID_SHARE = "threshold_decrypt:invalid-share"
FAULT_NON_VALIDATOR = "threshold_decrypt:non-validator"
FAULT_DUPLICATE = "threshold_decrypt:duplicate-share"
FAULT_MALFORMED = "threshold_decrypt:malformed-message"


@dataclass(frozen=True)
class DecryptMessage:
    """Wire message: one decryption share."""

    share: DecryptionShare


class ThresholdDecrypt(ConsensusProtocol):
    """Outputs the plaintext ``bytes`` of the input ciphertext.

    If the input ciphertext itself is invalid, ``ciphertext_invalid``
    becomes True and the instance terminates without output — the parent
    (HoneyBadger) is responsible for faulting whoever proposed it.
    """

    def __init__(self, netinfo: NetworkInfo, sink: VerifySink) -> None:
        self._netinfo = netinfo
        self._sink = sink
        self._ciphertext: Optional[Ciphertext] = None
        self._ct_valid = False
        self.ciphertext_invalid = False
        self._buffered: Dict[Any, DecryptionShare] = {}
        self._verified: Dict[Any, DecryptionShare] = {}
        self._seen: Set[Any] = set()
        self._terminated = False
        self._plaintext: Optional[bytes] = None

    @property
    def our_id(self) -> Any:
        return self._netinfo.our_id

    @property
    def terminated(self) -> bool:
        return self._terminated

    @property
    def plaintext(self) -> Optional[bytes]:
        return self._plaintext

    def handle_input(self, input: Ciphertext, rng: Any) -> Step:
        """Provide the ciphertext to decrypt."""
        step = Step.empty()
        if self._ciphertext is not None or self._terminated:
            return step
        self._ciphertext = input
        self._sink.submit(
            VerifyRequest.ciphertext(input),
            lambda ok: self._on_ciphertext_checked(ok),
        )
        return step

    # mirror: td-acceptance-item — the acceptance rules below (who is
    #     counted, when faults fire, the terminated gate) are mirrored
    #     by the engine's per-item continuation (`td_verified_cb` in
    #     native/engine.cpp); HBX003 keeps the pair of anchors alive.
    def handle_message(self, sender: Any, message: DecryptMessage, rng: Any) -> Step:
        step = Step.empty()
        if self._terminated:
            return step
        if not self._netinfo.is_node_validator(sender):
            return step.fault(sender, FAULT_NON_VALIDATOR)
        if not isinstance(message, DecryptMessage) or not isinstance(
            message.share, DecryptionShare
        ):
            return step.fault(sender, FAULT_MALFORMED)
        if sender in self._seen:
            return step.fault(sender, FAULT_DUPLICATE)
        self._seen.add(sender)
        if self._ct_valid:
            self._submit_share(sender, message.share)
        else:
            self._buffered[sender] = message.share
        return step

    # -- internal ------------------------------------------------------
    def _on_ciphertext_checked(self, ok: bool) -> Step:
        step = Step.empty()
        if self._terminated:
            return step
        if not ok:
            self.ciphertext_invalid = True
            self._terminated = True
            return step
        self._ct_valid = True
        if self._netinfo.is_validator():
            share = self._netinfo.secret_key_share.decryption_share(self._ciphertext)
            self._seen.add(self.our_id)
            self._verified[self.our_id] = share
            step.broadcast(DecryptMessage(share))
        buffered, self._buffered = self._buffered, {}
        for sender, share in buffered.items():
            self._submit_share(sender, share)
        return step.extend(self._try_output())

    def _submit_share(self, sender: Any, share: DecryptionShare) -> None:
        self._sink.submit(
            VerifyRequest.dec_share(
                self._netinfo.public_key_share(sender), self._ciphertext, share
            ),
            lambda ok, s=sender, sh=share: self._on_verified(s, sh, ok),
        )

    # mirror: td-acceptance-group — the same rules applied to a deferred
    #     RLC group verdict are mirrored by `td_group_verified_cb` in
    #     native/engine.cpp (per-sender attribution through bisection).
    def _on_verified(self, sender: Any, share: DecryptionShare, ok: bool) -> Step:
        step = Step.empty()
        if self._terminated:
            return step
        if not ok:
            return step.fault(sender, FAULT_INVALID_SHARE)
        self._verified[sender] = share
        return step.extend(self._try_output())

    def _try_output(self) -> Step:
        step = Step.empty()
        pks = self._netinfo.public_key_set
        if self._terminated or len(self._verified) < pks.threshold + 1:
            return step
        by_index = {
            self._netinfo.index(nid): sh for nid, sh in self._verified.items()
        }
        # One call: Lagrange combine + unmask (native vectorized on the
        # scalar suite — module docstring).
        self._plaintext = pks.combine_decryption_shares(by_index, self._ciphertext)
        self._terminated = True
        return step.with_output(self._plaintext)
