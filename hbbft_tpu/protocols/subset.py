"""Subset (ACS): agree on a common subset of proposers' contributions.

Reference: upstream ``src/subset/{subset,proposal_state,message}.rs``
(SURVEY.md §2 #8).  One :class:`Broadcast` instance per proposer plus one
:class:`BinaryAgreement` per proposer, cross-wired:

* RBC delivery of proposer p's value => input True into BA_p.
* Once N - f BAs have decided True => input False into every undecided BA.
* Output = the contributions of every proposer whose BA decided True
  (emitted incrementally as ``SubsetOutput.contribution``; a final
  ``SubsetOutput.done`` marks termination).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

from hbbft_tpu.crypto.pool import VerifySink
from hbbft_tpu.obs import trace as _trace
from hbbft_tpu.protocols.binary_agreement import BinaryAgreement
from hbbft_tpu.protocols.broadcast import Broadcast
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.traits import ConsensusProtocol, Step
from hbbft_tpu.utils import canonical_bytes

FAULT_UNKNOWN_PROPOSER = "subset:unknown-proposer"
FAULT_BAD_MESSAGE = "subset:bad-message"

BC = "bc"
BA = "ba"


@dataclass(frozen=True)
class SubsetMessage:
    proposer: Any
    kind: str  # BC | BA
    inner: Any


@dataclass(frozen=True)
class SubsetOutput:
    """Incremental ACS output."""

    kind: str  # "contribution" | "done"
    proposer: Any = None
    value: Optional[bytes] = None

    @staticmethod
    def contribution(proposer: Any, value: bytes) -> "SubsetOutput":
        return SubsetOutput("contribution", proposer, value)

    @staticmethod
    def done() -> "SubsetOutput":
        return SubsetOutput("done")


class _Proposal:
    """Per-proposer state: the RBC + BA pair and its progress."""

    __slots__ = ("broadcast", "ba", "value", "decision", "emitted")

    def __init__(self, broadcast: Broadcast, ba: BinaryAgreement) -> None:
        self.broadcast = broadcast
        self.ba = ba
        self.value: Optional[bytes] = None
        self.decision: Optional[bool] = None
        self.emitted = False


class Subset(ConsensusProtocol):
    def __init__(
        self, netinfo: NetworkInfo, session_id: bytes, sink: VerifySink
    ) -> None:
        self._netinfo = netinfo
        self._session_id = bytes(session_id)
        self._sink = sink
        self._proposals: Dict[Any, _Proposal] = {}
        self._terminated = False
        self._done_emitted = False
        for pid in netinfo.all_ids:
            ba_sink = sink.scoped(lambda s, p=pid: self._on_ba_step(p, s))
            self._proposals[pid] = _Proposal(
                Broadcast(netinfo, pid),
                BinaryAgreement(
                    netinfo,
                    canonical_bytes(b"subset-ba", self._session_id, str(pid)),
                    ba_sink,
                ),
            )

    # -- ConsensusProtocol --------------------------------------------
    @property
    def our_id(self) -> Any:
        return self._netinfo.our_id

    @property
    def terminated(self) -> bool:
        return self._terminated

    def handle_input(self, input: bytes, rng: Any) -> Step:
        """Propose our contribution (any bytes)."""
        if not self._netinfo.is_validator() or self._terminated:
            return Step.empty()
        prop = self._proposals[self.our_id]
        return self._on_bc_step(self.our_id, prop.broadcast.handle_input(input, rng))

    def handle_message(self, sender: Any, message: SubsetMessage, rng: Any) -> Step:
        step = Step.empty()
        if self._terminated:
            return step
        if not isinstance(message, SubsetMessage):
            return step.fault(sender, FAULT_BAD_MESSAGE)
        try:
            known = message.proposer in self._proposals
        except TypeError:  # unhashable garbage proposer
            known = False
        if not known:
            return step.fault(sender, FAULT_UNKNOWN_PROPOSER)
        # Tracer context: leaf milestones (BA coin flips/rounds) emit
        # without knowing which proposer's instance they serve.
        _trace.set_ctx(proposer=message.proposer)
        prop = self._proposals[message.proposer]
        if message.kind == BC:
            return self._on_bc_step(
                message.proposer,
                prop.broadcast.handle_message(sender, message.inner, rng),
            )
        if message.kind == BA:
            return self._on_ba_step(
                message.proposer,
                prop.ba.handle_message(sender, message.inner, rng),
            )
        return step.fault(sender, FAULT_BAD_MESSAGE)

    # -- child-step processing ----------------------------------------
    def _on_bc_step(self, proposer: Any, bc_step: Step) -> Step:
        step = bc_step.map_messages(lambda m: SubsetMessage(proposer, BC, m))
        outputs, step.output = step.output, []
        prop = self._proposals[proposer]
        for value in outputs:
            if prop.value is None:
                prop.value = value
                _trace.emit("rbc.deliver", proposer=proposer)
                # Deliver => vote to include this proposer.
                step.extend(self._input_ba(proposer, True))
        step.extend(self._progress(proposer))
        return step

    def _on_ba_step(self, proposer: Any, ba_step: Step) -> Step:
        step = ba_step.map_messages(lambda m: SubsetMessage(proposer, BA, m))
        outputs, step.output = step.output, []
        prop = self._proposals[proposer]
        for decision in outputs:
            if prop.decision is None:
                prop.decision = bool(decision)
                step.extend(self._after_decision())
        step.extend(self._progress(proposer))
        return step

    def _input_ba(self, proposer: Any, value: bool) -> Step:
        prop = self._proposals[proposer]
        _trace.set_ctx(proposer=proposer)
        return self._on_ba_step(proposer, prop.ba.handle_input(value, None))

    def _after_decision(self) -> Step:
        """Apply the N - f rule and check completion."""
        step = Step.empty()
        accepted = sum(1 for p in self._proposals.values() if p.decision is True)
        if accepted >= self._netinfo.num_correct:
            for pid, prop in list(self._proposals.items()):
                if prop.decision is None and not prop.ba.terminated:
                    step.extend(self._input_ba(pid, False))
        return step

    def _progress(self, proposer: Any) -> Step:
        """Emit newly available contributions; emit done when complete."""
        step = Step.empty()
        if self._terminated:
            return step
        prop = self._proposals[proposer]
        if prop.decision is True and prop.value is not None and not prop.emitted:
            prop.emitted = True
            step.with_output(SubsetOutput.contribution(proposer, prop.value))
        if all(p.decision is not None for p in self._proposals.values()) and all(
            p.emitted or p.decision is False for p in self._proposals.values()
        ):
            if not self._done_emitted:
                self._done_emitted = True
                self._terminated = True
                step.with_output(SubsetOutput.done())
        return step
