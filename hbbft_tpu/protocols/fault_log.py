"""Structured Byzantine-evidence channel.

Reference: upstream ``src/fault_log.rs`` (``FaultLog``, ``Fault{node_id,
kind}``; per-module ``FaultKind`` enums).  Fork checkout empty at survey
time; see SURVEY.md §2 #3.

Every verification failure (bad Merkle proof, invalid signature share,
duplicate message, decoding failure) is recorded here instead of panicking
or silently dropping — the fault log is the framework's Byzantine-behavior
observable.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterator, List


@dataclass(frozen=True)
class Fault:
    node_id: Any
    kind: str

    def __repr__(self) -> str:  # compact in test output
        return f"Fault({self.node_id!r}, {self.kind})"


@dataclass
class FaultLog:
    faults: List[Fault] = field(default_factory=list)

    def append_fault(self, node_id: Any, kind: str) -> None:
        self.faults.append(Fault(node_id, kind))

    def append(self, fault: Fault) -> None:
        self.faults.append(fault)

    def extend(self, other: "FaultLog") -> None:
        self.faults.extend(other.faults)

    def __len__(self) -> int:
        return len(self.faults)

    def __iter__(self) -> Iterator[Fault]:
        return iter(self.faults)

    def __bool__(self) -> bool:
        return bool(self.faults)
