"""Synchronized binary-value broadcast: the BVal/Aux stage of one ABA round.

Reference: upstream ``src/binary_agreement/sbv_broadcast.rs`` (SURVEY.md
§2 #5).  Properties (N = 3f+1): every value in ``bin_values`` was input
by a correct node; all correct nodes eventually share ``bin_values``;
completion delivers a set ``vals`` backed by N - f Aux messages.

Message flow: on input b, broadcast ``BVal(b)``.  On f+1 ``BVal(b)``,
relay ``BVal(b)`` (if not sent).  On 2f+1 ``BVal(b)``, insert b into
``bin_values``; the first insertion broadcasts ``Aux(b)``.  When N - f
``Aux`` messages carry values inside ``bin_values``, output ``vals`` =
the set of those values.

This class emits raw :class:`BValMsg`/:class:`AuxMsg`; the parent
BinaryAgreement wraps them with its round number.  Output: a single
``BoolSet`` in ``Step.output`` on completion (may re-fire with a larger
set if ``bin_values`` grows before the round advances, as upstream does).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Set

from hbbft_tpu.protocols.bool_set import BoolSet
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.traits import Step

FAULT_DUPLICATE_BVAL = "sbv:duplicate-bval"
FAULT_DUPLICATE_AUX = "sbv:duplicate-aux"


@dataclass(frozen=True)
class BValMsg:
    value: bool


@dataclass(frozen=True)
class AuxMsg:
    value: bool


class SbvBroadcast:
    def __init__(self, netinfo: NetworkInfo) -> None:
        self._netinfo = netinfo
        self._bval_received: Dict[bool, Set[Any]] = {False: set(), True: set()}
        self._bval_sent: Set[bool] = set()
        self._aux_received: Dict[bool, Set[Any]] = {False: set(), True: set()}
        self._aux_sent = False
        self._termed_bval: Dict[bool, Set[Any]] = {False: set(), True: set()}
        self._termed_aux: Dict[bool, Set[Any]] = {False: set(), True: set()}
        self.bin_values = BoolSet.none()
        self._last_output: BoolSet | None = None

    def input(self, b: bool) -> Step:
        """Start the stage by broadcasting BVal(b)."""
        return self._send_bval(b)

    def handle_bval(self, sender: Any, b: bool) -> Step:
        step = Step.empty()
        if sender in self._bval_received[b]:
            if sender in self._termed_bval[b]:
                # The one real message racing its own Term evidence.
                self._termed_bval[b].discard(sender)
                return step
            return step.fault(sender, FAULT_DUPLICATE_BVAL)
        self._bval_received[b].add(sender)
        count = len(self._bval_received[b])
        f = self._netinfo.num_faulty
        if count >= f + 1 and b not in self._bval_sent:
            step.extend(self._send_bval(b))
        if count >= 2 * f + 1 and b not in self.bin_values:
            first = not self.bin_values
            self.bin_values = self.bin_values.insert(b)
            if first and not self._aux_sent:
                step.extend(self._send_aux(b))
            step.extend(self._try_output())
        return step

    def handle_aux(self, sender: Any, b: bool) -> Step:
        step = Step.empty()
        if sender in self._aux_received[b]:
            if sender in self._termed_aux[b]:
                # The one real message racing its own Term evidence.
                self._termed_aux[b].discard(sender)
                return step
            return step.fault(sender, FAULT_DUPLICATE_AUX)
        self._aux_received[b].add(sender)
        return step.extend(self._try_output())

    def add_term_evidence(self, sender: Any, b: bool) -> Step:
        """A Term(b) counts as this sender's BVal(b) and Aux(b) forever.

        The sender's genuine BVal/Aux may still be in flight (delivered
        after the Term under reordering); each gets ONE free pass — any
        further duplicate is flagged as Byzantine as usual.
        """
        step = Step.empty()
        if sender not in self._bval_received[b]:
            self._termed_bval[b].add(sender)
            step.extend(self.handle_bval(sender, b))
        if sender not in self._aux_received[b]:
            self._termed_aux[b].add(sender)
            step.extend(self.handle_aux(sender, b))
        return step

    # -- internals -----------------------------------------------------
    def _send_bval(self, b: bool) -> Step:
        step = Step.empty()
        if b in self._bval_sent:
            return step
        self._bval_sent.add(b)
        step.broadcast(BValMsg(b))
        step.extend(self.handle_bval(self._netinfo.our_id, b))
        return step

    def _send_aux(self, b: bool) -> Step:
        step = Step.empty()
        self._aux_sent = True
        step.broadcast(AuxMsg(b))
        step.extend(self.handle_aux(self._netinfo.our_id, b))
        return step

    def _try_output(self) -> Step:
        """Output vals once N - f Aux messages carry bin_values members."""
        step = Step.empty()
        if not self.bin_values:
            return step
        vals = BoolSet.none()
        count = 0
        for b in self.bin_values:
            senders = self._aux_received[b]
            if senders:
                vals = vals.insert(b)
                count += len(senders)
        # A sender may (faultily) Aux both values; count each sender once.
        all_senders = self._aux_received[False] | self._aux_received[True]
        count = min(count, len(all_senders))
        if count >= self._netinfo.num_correct and vals and vals != self._last_output:
            self._last_output = vals
            step.with_output(vals)
        return step
