"""Protocol plane: pure host-side state machines.

Every protocol is a deterministic state machine consuming ``(sender,
message)`` pairs and inputs and emitting a :class:`~hbbft_tpu.protocols.
traits.Step`.  No I/O, no threads, no clock — the caller owns the event
loop and the transport, exactly as in the reference (upstream
``src/lib.rs`` module docs).

Stack (upstream README's composition diagram):
``QueueingHoneyBadger -> DynamicHoneyBadger -> HoneyBadger -> Subset ->
{Broadcast, BinaryAgreement -> ThresholdSign}`` plus ``ThresholdDecrypt``
per epoch, ``SyncKeyGen`` for membership change, and ``SenderQueue`` as
the network-facing outbox wrapper.
"""

from hbbft_tpu.protocols.broadcast import Broadcast  # noqa: F401
from hbbft_tpu.protocols.binary_agreement import BinaryAgreement  # noqa: F401
from hbbft_tpu.protocols.dynamic_honey_badger import (  # noqa: F401
    Change,
    ChangeState,
    DhbBatch,
    DynamicHoneyBadger,
    JoinPlan,
)
from hbbft_tpu.protocols.honey_badger import (  # noqa: F401
    Batch,
    EncryptionSchedule,
    HoneyBadger,
)
from hbbft_tpu.protocols.queueing_honey_badger import (  # noqa: F401
    Input,
    QueueingHoneyBadger,
)
from hbbft_tpu.protocols.sender_queue import (  # noqa: F401
    JoiningSenderQueue,
    SenderQueue,
)
from hbbft_tpu.protocols.subset import Subset, SubsetOutput  # noqa: F401
from hbbft_tpu.protocols.sync_key_gen import SyncKeyGen  # noqa: F401
from hbbft_tpu.protocols.threshold_decrypt import ThresholdDecrypt  # noqa: F401
from hbbft_tpu.protocols.threshold_sign import ThresholdSign  # noqa: F401
from hbbft_tpu.protocols.transaction_queue import TransactionQueue  # noqa: F401
