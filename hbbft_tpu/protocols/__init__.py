"""Protocol plane: pure host-side state machines.

Every protocol is a deterministic state machine consuming ``(sender,
message)`` pairs and inputs and emitting a :class:`~hbbft_tpu.protocols.
traits.Step`.  No I/O, no threads, no clock — the caller owns the event
loop and the transport, exactly as in the reference (upstream
``src/lib.rs`` module docs).
"""
