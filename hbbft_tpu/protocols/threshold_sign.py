"""ThresholdSign: cooperative threshold signature over a fixed document.

Reference: upstream ``src/threshold_sign.rs`` (SURVEY.md §2 #6).  Each
validator broadcasts its signature share of H(doc); incoming shares are
verified against the sender's public-key share (the pairing check — THE
hot loop, BASELINE.json:2/5); ``f + 1`` valid shares Lagrange-combine into
the unique deterministic master signature, which is the output.  Used
standalone and as the common coin of BinaryAgreement (coin value = parity
of the combined signature).

TPU-first deviation: share verification is *deferred* — submitted to the
:class:`~hbbft_tpu.crypto.pool.VerifySink` and counted only once the batch
flush confirms it (SURVEY.md §7 "deferred-verify queue").

Native-engine mirror (round 7): over the scalar suite the engine
additionally batch-verifies each flush's pending shares of one
ThresholdSign instance with a single random-linear-combination check —
``Σ rᵢ·shareᵢ == (Σ rᵢ·pkᵢ)·H(doc)`` with small nonzero engine-PRNG
coefficients — bisecting a failed group down to per-share checks so a
bad share yields the same :data:`FAULT_INVALID_SHARE` against the same
sender as this per-share path.  That is an *optimization inside the
verify step*, never a semantics change: protocol outputs and fault
attribution must stay identical to verifying each share individually
(``HBBFT_TPU_COIN_RLC=0`` restores per-share verification; the matrix is
pinned by tests/test_native_rlc.py, invariant in docs/INVARIANTS.md).
Any change to the acceptance rules here (who is counted, when faults
fire, the terminated gate) must be mirrored in ``native/engine.cpp``'s
``ts_verified_cb`` AND ``ts_group_verified_cb``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Set

from hbbft_tpu.crypto.backend import VerifyRequest
from hbbft_tpu.crypto.keys import Signature, SignatureShare
from hbbft_tpu.crypto.pool import VerifySink
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.traits import ConsensusProtocol, Step

FAULT_INVALID_SHARE = "threshold_sign:invalid-share"
FAULT_NON_VALIDATOR = "threshold_sign:non-validator"
FAULT_DUPLICATE = "threshold_sign:duplicate-share"
FAULT_MALFORMED = "threshold_sign:malformed-message"


@dataclass(frozen=True)
class SignMessage:
    """Wire message: one signature share."""

    share: SignatureShare


class ThresholdSign(ConsensusProtocol):
    """Signs ``doc`` cooperatively; outputs the combined ``Signature``."""

    def __init__(self, netinfo: NetworkInfo, doc: bytes, sink: VerifySink) -> None:
        self._netinfo = netinfo
        self._doc = doc
        self._sink = sink
        self._verified: Dict[Any, SignatureShare] = {}
        self._seen: Set[Any] = set()
        self._had_input = False
        self._terminated = False
        self._signature: Optional[Signature] = None

    # -- ConsensusProtocol --------------------------------------------
    @property
    def our_id(self) -> Any:
        return self._netinfo.our_id

    @property
    def terminated(self) -> bool:
        return self._terminated

    @property
    def signature(self) -> Optional[Signature]:
        return self._signature

    def handle_input(self, input: Any, rng: Any) -> Step:
        """Start signing (input value is ignored, as in the reference).

        The share is broadcast even if we already terminated via peers'
        shares — otherwise slower peers could be starved of their
        (f+1)-th share forever (liveness).
        """
        if self._had_input:
            return Step.empty()
        self._had_input = True
        step = Step.empty()
        if not self._netinfo.is_validator():
            return step
        share = self._netinfo.secret_key_share.sign(self._doc)
        step.broadcast(SignMessage(share))
        if not self._terminated:
            self._seen.add(self.our_id)
            self._verified[self.our_id] = share  # own share needs no check
            step.extend(self._try_output())
        return step

    # mirror: ts-acceptance-item — the acceptance rules below (who is
    #     counted, when faults fire, the terminated gate) are mirrored
    #     by the engine's per-item continuation (`ts_verified_cb` in
    #     native/engine.cpp); HBX003 keeps the pair of anchors alive.
    def handle_message(self, sender: Any, message: SignMessage, rng: Any) -> Step:
        step = Step.empty()
        if self._terminated:
            return step
        if not self._netinfo.is_node_validator(sender):
            return step.fault(sender, FAULT_NON_VALIDATOR)
        if not isinstance(message, SignMessage) or not isinstance(
            message.share, SignatureShare
        ):
            return step.fault(sender, FAULT_MALFORMED)
        if sender in self._seen:
            return step.fault(sender, FAULT_DUPLICATE)
        self._seen.add(sender)
        share = message.share
        self._sink.submit(
            VerifyRequest.sig_share(
                self._netinfo.public_key_share(sender), self._doc, share
            ),
            lambda ok, s=sender, sh=share: self._on_verified(s, sh, ok),
        )
        return step

    # -- internal ------------------------------------------------------
    # mirror: ts-acceptance-group — the same rules applied to a deferred
    #     RLC group verdict are mirrored by `ts_group_verified_cb` in
    #     native/engine.cpp (per-sender attribution through bisection).
    def _on_verified(self, sender: Any, share: SignatureShare, ok: bool) -> Step:
        step = Step.empty()
        if self._terminated:
            return step
        if not ok:
            return step.fault(sender, FAULT_INVALID_SHARE)
        self._verified[sender] = share
        return step.extend(self._try_output())

    def _try_output(self) -> Step:
        step = Step.empty()
        pks = self._netinfo.public_key_set
        if self._terminated or len(self._verified) < pks.threshold + 1:
            return step
        by_index = {
            self._netinfo.index(nid): sh for nid, sh in self._verified.items()
        }
        self._signature = pks.combine_signatures(by_index)
        self._terminated = True
        return step.with_output(self._signature)
