"""Typed errors for API misuse (caller bugs, not Byzantine input).

Reference: upstream per-module ``error.rs`` enums behind ``Result<Step,
Error>`` (SURVEY.md §2 #15).  The split here mirrors the reference's
philosophy: *Byzantine* input never raises — it lands in the
:class:`~hbbft_tpu.protocols.fault_log.FaultLog` — while *caller* errors
(bad arguments, unencodable contributions, inputs to the wrong node)
raise typed exceptions the application can catch at the call site.
"""

from __future__ import annotations


class HbbftError(Exception):
    """Base for all typed API-misuse errors in this package."""


class ContributionNotEncodable(HbbftError, TypeError):
    """The proposed contribution (or transaction) contains a type the
    committed-bytes codec refuses.  Raised at the input boundary —
    before any protocol state changes — so a bad transaction cannot
    crash the node epochs later when it is finally sampled."""


class NotAValidator(HbbftError, ValueError):
    """The operation requires this node to be a current validator."""


class InvalidInput(HbbftError, ValueError):
    """Malformed argument to a protocol entry point."""
