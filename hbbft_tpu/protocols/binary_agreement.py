"""BinaryAgreement: Mostéfaoui-Moumen-Raynal asynchronous binary consensus.

Reference: upstream ``src/binary_agreement/binary_agreement.rs`` (SURVEY.md
§2 #5).  Rounds of: SBV-broadcast (BVal/Aux), a Conf stage, then the
common coin (a ThresholdSign over the round nonce, SURVEY.md §2 #6).
Decide when the singleton conf value equals the coin; ``Term(b)``
broadcast on decision lets others decide without further rounds (f + 1
matching Terms are decisive, and a Term counts as its sender's BVal/Aux
in every later round).

Safety does not rest on the coin (agreement holds for any coin values);
the unpredictable threshold-signature coin defeats the adaptive scheduler
that asynchronous liveness requires (tested by the MITM coin-delay
adversary, per the reference's ``binary_agreement_mitm.rs``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Set, Tuple

from hbbft_tpu.crypto.pool import VerifySink
from hbbft_tpu.obs import trace as _trace
from hbbft_tpu.protocols.bool_set import BoolSet
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.sbv_broadcast import AuxMsg, BValMsg, SbvBroadcast
from hbbft_tpu.protocols.threshold_sign import SignMessage, ThresholdSign
from hbbft_tpu.protocols.traits import ConsensusProtocol, Step
from hbbft_tpu.utils import canonical_bytes

FAULT_DUPLICATE_CONF = "binary_agreement:duplicate-conf"
FAULT_DUPLICATE_TERM = "binary_agreement:duplicate-term"
FAULT_MALFORMED = "binary_agreement:malformed-message"


def _content_well_formed(content: Any) -> bool:
    if isinstance(content, (BValMsg, AuxMsg, TermMsg)):
        return isinstance(content.value, bool)
    if isinstance(content, ConfMsg):
        return isinstance(content.vals, BoolSet)
    if isinstance(content, CoinMsg):
        return isinstance(content.inner, SignMessage)
    return False

MAX_FUTURE_ROUNDS = 100  # bound per-sender buffering of rounds ahead of us


@dataclass(frozen=True)
class ConfMsg:
    vals: BoolSet


@dataclass(frozen=True)
class CoinMsg:
    inner: SignMessage


@dataclass(frozen=True)
class TermMsg:
    value: bool


@dataclass(frozen=True)
class AbaMessage:
    """All ABA wire messages are (round, content)-tagged."""

    round: int
    content: Any  # BValMsg | AuxMsg | ConfMsg | CoinMsg | TermMsg


class BinaryAgreement(ConsensusProtocol):
    """Agrees on one bool; ``session_id`` disambiguates coin documents
    across concurrent instances (e.g. per-proposer in Subset)."""

    def __init__(
        self, netinfo: NetworkInfo, session_id: bytes, sink: VerifySink
    ) -> None:
        self._netinfo = netinfo
        self._session_id = bytes(session_id)
        self._sink = sink
        self._round = 0
        self._sbv = SbvBroadcast(netinfo)
        self._conf_sent = False
        self._confs: Dict[Any, BoolSet] = {}
        self._term_confs: Set[Any] = set()  # synthetic entries from Terms
        self._coin: Optional[ThresholdSign] = None
        self._coin_requested = False
        self._coin_value: Optional[bool] = None
        self._conf_vals: Optional[BoolSet] = None
        self._estimate: Optional[bool] = None
        self._terms: Dict[bool, Set[Any]] = {False: set(), True: set()}
        self._term_senders: Set[Any] = set()
        self._future: List[Tuple[Any, AbaMessage]] = []
        self._decision: Optional[bool] = None
        self._terminated = False
        self._make_coin_for_round()  # shares may arrive before our input

    # -- ConsensusProtocol --------------------------------------------
    @property
    def our_id(self) -> Any:
        return self._netinfo.our_id

    @property
    def terminated(self) -> bool:
        return self._terminated

    @property
    def decision(self) -> Optional[bool]:
        return self._decision

    @property
    def round(self) -> int:
        return self._round

    def handle_input(self, input: bool, rng: Any) -> Step:
        if self._estimate is not None or self._terminated:
            return Step.empty()
        self._estimate = bool(input)
        # Flight-recorder milestone (round 16): a BA instance stuck at
        # round 0 emits no ba.round (that fires on ADVANCE), so without
        # this the stall diagnostician cannot tell "BA started, stuck"
        # from "BA never received its input".  Mirrored by the native
        # engine's TR_BA_INPUT.
        _trace.emit("ba.input", round=self._round, value=int(input))
        return self._wrap(self._sbv.input(self._estimate))

    def handle_message(self, sender: Any, message: AbaMessage, rng: Any) -> Step:
        step = Step.empty()
        if (
            not isinstance(message, AbaMessage)
            or not isinstance(message.round, int)
            or isinstance(message.round, bool)
            or not _content_well_formed(message.content)
        ):
            return step.fault(sender, FAULT_MALFORMED)
        content = message.content
        if isinstance(content, TermMsg):
            return self._handle_term(sender, content.value)
        if self._terminated:
            return step
        if message.round < self._round:
            return step  # stale round: drop silently (reference behavior)
        if message.round > self._round:
            if (
                message.round - self._round <= MAX_FUTURE_ROUNDS
                and sum(1 for s, _ in self._future if s == sender) < 4 * MAX_FUTURE_ROUNDS
            ):
                self._future.append((sender, message))
            return step
        if isinstance(content, BValMsg):
            step.extend(self._wrap(self._sbv.handle_bval(sender, content.value)))
        elif isinstance(content, AuxMsg):
            step.extend(self._wrap(self._sbv.handle_aux(sender, content.value)))
        elif isinstance(content, ConfMsg):
            step.extend(self._handle_conf(sender, content.vals))
        elif isinstance(content, CoinMsg):
            step.extend(self._handle_coin_msg(sender, content.inner))
        return step

    # -- step wrapping -------------------------------------------------
    def _wrap(self, sbv_step: Step) -> Step:
        """Lift an SBV step: tag messages with the round; react to output."""
        rnd = self._round
        step = sbv_step.map_messages(lambda m: AbaMessage(rnd, m))
        outputs, step.output = step.output, []
        for vals in outputs:
            step.extend(self._on_sbv_vals(vals))
        return step

    def _on_sbv_vals(self, vals: BoolSet) -> Step:
        step = Step.empty()
        if not self._conf_sent:
            self._conf_sent = True
            step.broadcast(AbaMessage(self._round, ConfMsg(self._sbv.bin_values)))
            step.extend(self._handle_conf(self.our_id, self._sbv.bin_values))
        else:
            step.extend(self._try_start_coin())
        return step

    # -- conf stage ----------------------------------------------------
    def _handle_conf(self, sender: Any, vals: BoolSet) -> Step:
        step = Step.empty()
        if sender in self._confs:
            # A synthetic conf seeded from this sender's Term is not the
            # sender's fault — its real Conf may arrive afterwards.
            if sender not in self._term_confs:
                step.fault(sender, FAULT_DUPLICATE_CONF)
            return step
        self._confs[sender] = vals
        return step.extend(self._try_start_coin())

    def _try_start_coin(self) -> Step:
        step = Step.empty()
        if self._coin_requested or not self._conf_sent:
            return step
        accepted = [
            v for v in self._confs.values() if v.is_subset(self._sbv.bin_values)
        ]
        if len(accepted) < self._netinfo.num_correct:
            return step
        self._coin_requested = True
        vals = BoolSet.none()
        for v in accepted:
            vals = vals.union(v)
        self._conf_vals = vals
        assert self._coin is not None
        step.extend(self._wrap_coin(self._coin.handle_input(None, None)))
        # The coin may already have flipped from peers' shares alone.
        return step.extend(self._maybe_advance())

    # -- common coin ---------------------------------------------------
    def _coin_doc(self) -> bytes:
        return canonical_bytes(b"aba-coin", self._session_id, self._round)

    def _make_coin_for_round(self) -> Step:
        """Create the round's coin instance (receives shares before we
        request our own flip)."""
        rnd = self._round
        sink = self._sink.scoped(lambda s, r=rnd: self._coin_scope_wrap(r, s))
        self._coin = ThresholdSign(self._netinfo, self._coin_doc(), sink)
        return Step.empty()

    def _coin_scope_wrap(self, rnd: int, child_step: Step) -> Step:
        if rnd != self._round or self._terminated:
            # Result of a verification from an already-finished round.
            return Step(output=[], messages=[], fault_log=child_step.fault_log)
        return self._wrap_coin(child_step)

    def _wrap_coin(self, coin_step: Step) -> Step:
        rnd = self._round
        step = coin_step.map_messages(lambda m: AbaMessage(rnd, CoinMsg(m)))
        outputs, step.output = step.output, []
        for sig in outputs:
            step.extend(self._on_coin(sig.parity()))
        return step

    def _handle_coin_msg(self, sender: Any, inner: SignMessage) -> Step:
        assert self._coin is not None
        return self._wrap_coin(self._coin.handle_message(sender, inner, None))

    def _on_coin(self, s: bool) -> Step:
        """Record the coin flip; advance once the conf stage is also done.

        The coin can complete from peers' shares alone, before our own
        conf threshold is reached — stash the value in that case.
        """
        self._coin_value = s
        _trace.emit("ba.coin", round=self._round, value=int(s))
        return self._maybe_advance()

    def _maybe_advance(self) -> Step:
        step = Step.empty()
        if self._terminated or self._coin_value is None or self._conf_vals is None:
            return step
        s = self._coin_value
        definite = self._conf_vals.definite()
        if definite is not None:
            if definite == s:
                return self._decide(definite)
            self._estimate = definite
        else:
            self._estimate = s
        return step.extend(self._next_round())

    # -- rounds and termination ---------------------------------------
    def _next_round(self) -> Step:
        self._round += 1
        _trace.emit("ba.round", round=self._round)
        self._sbv = SbvBroadcast(self._netinfo)
        self._conf_sent = False
        self._confs = {}
        self._coin_requested = False
        self._coin_value = None
        self._conf_vals = None
        step = self._make_coin_for_round()
        # Terms seen so far seed the new round's BVal/Aux/Conf evidence —
        # decided nodes no longer participate, so without this the N - f
        # conf threshold could become unreachable (deadlock).
        for b in (False, True):
            for sender in self._terms[b]:
                step.extend(self._wrap(self._sbv.add_term_evidence(sender, b)))
                self._confs.setdefault(sender, BoolSet.single(b))
                self._term_confs.add(sender)
        step.extend(self._wrap(self._sbv.input(self._estimate)))
        # Replay buffered messages that now belong to the current round.
        future, self._future = self._future, []
        for sender, msg in future:
            step.extend(self.handle_message(sender, msg, None))
        return step

    def _handle_term(self, sender: Any, b: bool) -> Step:
        step = Step.empty()
        if sender in self._term_senders:
            if sender not in self._terms[b]:
                step.fault(sender, FAULT_DUPLICATE_TERM)
            return step
        self._term_senders.add(sender)
        self._terms[b].add(sender)
        if not self._terminated:
            if len(self._terms[b]) >= self._netinfo.num_faulty + 1:
                return step.extend(self._decide(b))
            step.extend(self._wrap(self._sbv.add_term_evidence(sender, b)))
            if sender not in self._confs:
                self._term_confs.add(sender)
                step.extend(self._handle_conf(sender, BoolSet.single(b)))
        return step

    def _decide(self, b: bool) -> Step:
        step = Step.empty()
        if self._terminated:
            return step
        self._decision = b
        self._terminated = True
        _trace.emit("ba.decide", round=self._round, value=int(b))
        step.broadcast(AbaMessage(self._round, TermMsg(b)))
        return step.with_output(b)
