"""TransactionQueue: pending-transaction buffer with random proposal sampling.

Reference: upstream ``src/transaction_queue.rs`` (SURVEY.md §2 #11).
Proposals are a RANDOM sample of the queue — the HoneyBadger paper's
defense against censorship and cross-node duplication: if every node
proposed its queue head, an adversary could predict and suppress
specific transactions, and all nodes would propose the same ones.
"""

from __future__ import annotations

from typing import Any, Iterable, List


class TransactionQueue:
    """Default deque-backed implementation (upstream impl on VecDeque)."""

    def __init__(self, txns: Iterable[Any] = ()) -> None:
        self._txns: List[Any] = list(txns)

    def __len__(self) -> int:
        return len(self._txns)

    def __bool__(self) -> bool:
        return bool(self._txns)

    def extend(self, txns: Iterable[Any]) -> None:
        self._txns.extend(txns)

    def push(self, txn: Any) -> None:
        self._txns.append(txn)

    def remove_multiple(self, txns: Iterable[Any]) -> None:
        """Drop committed transactions (compares by equality).

        One O(queue + committed) pass: multiset-subtract the committed
        transactions (each committed occurrence removes at most one
        queued occurrence, matching per-item ``list.remove`` semantics).
        The old per-item scan was O(committed x queue) — quadratic at
        firehose batch sizes.  Unhashable transactions fall back to the
        equality scan (rare; transactions are normally plain data).
        """
        committed = list(txns)
        if not committed or not self._txns:
            return
        try:
            pending: dict = {}
            for t in committed:
                pending[t] = pending.get(t, 0) + 1
            kept: List[Any] = []
            for t in self._txns:
                n = pending.get(t, 0)
                if n:
                    pending[t] = n - 1
                else:
                    kept.append(t)
            self._txns = kept
        except TypeError:
            for t in committed:
                try:
                    self._txns.remove(t)
                except ValueError:
                    pass

    def choose(self, rng: Any, amount: int) -> List[Any]:
        """A random sample of up to ``amount`` pending transactions."""
        if amount >= len(self._txns):
            return list(self._txns)
        return rng.sample(self._txns, amount)
