"""Broadcast: Bracha reliable broadcast with AVID erasure-coded dispersal.

Reference: upstream ``src/broadcast/broadcast.rs`` (SURVEY.md §2 #4,
BASELINE.json:8).  Protocol for proposer p, value v:

* p RS-encodes v into N shards (K = N - 2f data + 2f parity), Merkle-
  hashes them, and sends node i its proof ``Value(proof_i)``.
* On a valid ``Value``, a node gossips ``Echo(proof_i)`` to everyone.
* On N - f valid Echos for one root: send ``Ready(root)``.
* On f + 1 Readys without having sent one: send ``Ready`` (amplification).
* On 2f + 1 Readys and >= K stored shards: reconstruct, re-encode, and
  re-hash to verify the root (catches a proposer that encoded garbage),
  then output the value.

Per-node byte cost is O(|v| * N / K) instead of O(|v| * N).

Later upstream revisions add two bandwidth-optimization messages, both
implemented here (SURVEY.md §2 #4 "EchoHash/CanDecode"):

* ``CanDecode(root)`` — broadcast once a node holds K shards for a root:
  "stop sending me full proofs".
* ``EchoHash(root)`` — sent in place of a full ``Echo(proof)`` to peers
  that have declared ``CanDecode``; counts toward the N - f Echo
  threshold but carries no shard.

Safety is unchanged: decoding still requires K locally-validated shards
and a recomputed Merkle root match; the optimization only drops shard
payloads to peers that declared they no longer need them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from hbbft_tpu.obs import trace as _trace
from hbbft_tpu.ops.gf256 import rs_codec
from hbbft_tpu.ops.merkle import MerkleTree, Proof
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.traits import ConsensusProtocol, Step, Target

FAULT_INVALID_PROOF = "broadcast:invalid-proof"
FAULT_WRONG_INDEX = "broadcast:wrong-shard-index"
FAULT_NOT_PROPOSER = "broadcast:value-from-non-proposer"
FAULT_MULTIPLE_VALUES = "broadcast:multiple-values"
FAULT_DUPLICATE = "broadcast:duplicate-message"
FAULT_BAD_ENCODING = "broadcast:root-mismatch-after-decode"
FAULT_MALFORMED = "broadcast:malformed-message"


@dataclass(frozen=True)
class ValueMsg:
    proof: Proof


@dataclass(frozen=True)
class EchoMsg:
    proof: Proof


@dataclass(frozen=True)
class ReadyMsg:
    root: bytes


@dataclass(frozen=True)
class EchoHashMsg:
    """Echo without the shard, for peers that declared CanDecode."""

    root: bytes


@dataclass(frozen=True)
class CanDecodeMsg:
    """Sender holds K shards for ``root`` and needs no more full Echos."""

    root: bytes


def _pack(value: bytes, k: int, align: int = 1) -> Tuple[bytes, ...]:
    """Length-prefix and pad ``value`` into k equal shards.

    ``align=2`` for the GF(2^16) codec (validator sets > 255): its
    symbols are 2 bytes, so shard lengths must be even.
    """
    payload = len(value).to_bytes(8, "big") + value
    shard_len = max(1, -(-len(payload) // k))
    shard_len = -(-shard_len // align) * align
    payload = payload.ljust(k * shard_len, b"\x00")
    return tuple(payload[i * shard_len : (i + 1) * shard_len] for i in range(k))


def _unpack(data_shards: Tuple[bytes, ...]) -> Optional[bytes]:
    payload = b"".join(data_shards)
    if len(payload) < 8:
        return None
    n = int.from_bytes(payload[:8], "big")
    if 8 + n > len(payload):
        return None
    return payload[8 : 8 + n]


class Broadcast(ConsensusProtocol):
    """One reliable-broadcast instance for a designated proposer."""

    def __init__(self, netinfo: NetworkInfo, proposer_id: Any) -> None:
        self._netinfo = netinfo
        self._proposer = proposer_id
        n, f = netinfo.num_nodes, netinfo.num_faulty
        self._data_shards = n - 2 * f
        # GF(256) up to 255 validators (reference-matching byte layout);
        # GF(2^16) beyond — GF(256) has no 256th Vandermonde point.
        self._rs = rs_codec(self._data_shards, n)
        self._echos: Dict[Any, Proof] = {}
        self._echo_hashes: Dict[Any, bytes] = {}
        self._readys: Dict[Any, bytes] = {}
        self._can_decode: Dict[Any, bytes] = {}  # peer -> root it can decode
        self._can_decode_sent = False
        self._echo_sent = False
        self._ready_sent = False
        self._had_input = False
        self._terminated = False
        self._value: Optional[bytes] = None

    @property
    def our_id(self) -> Any:
        return self._netinfo.our_id

    @property
    def terminated(self) -> bool:
        return self._terminated

    @property
    def value(self) -> Optional[bytes]:
        return self._value

    # -- input (proposer only) ----------------------------------------
    def handle_input(self, input: bytes, rng: Any) -> Step:
        if self.our_id != self._proposer or self._had_input:
            return Step.empty()
        shards = self._rs.encode(
            list(_pack(bytes(input), self._data_shards, self._rs.shard_align))
        )
        tree = MerkleTree(shards)
        return self.propose_with_proofs([tree.proof(i) for i in range(self._netinfo.num_nodes)])

    def propose_with_proofs(self, proofs) -> Step:
        """Proposer fast path: disperse PRECOMPUTED shard proofs.

        ``proofs[i]`` is shard i's proof (index order).  Used by
        :func:`batch_propose` to feed device-computed (batched RS +
        Merkle) proofs into many instances without redoing the data
        plane per instance; ``handle_input`` routes through here too.
        """
        step = Step.empty()
        if self.our_id != self._proposer or self._had_input:
            return step
        self._had_input = True
        for nid in self._netinfo.all_ids:
            proof = proofs[self._netinfo.index(nid)]
            if nid == self.our_id:
                step.extend(self._handle_value(self.our_id, proof))
            else:
                step.send(nid, ValueMsg(proof))
        return step

    # -- messages ------------------------------------------------------
    def handle_message(self, sender: Any, message: Any, rng: Any) -> Step:
        step = Step.empty()
        if self._terminated:
            return step
        if not self._netinfo.is_node_validator(sender):
            return step.fault(sender, FAULT_NOT_PROPOSER)
        if isinstance(message, ValueMsg):
            if sender != self._proposer:
                return step.fault(sender, FAULT_NOT_PROPOSER)
            if not isinstance(message.proof, Proof) or not message.proof.well_formed():
                return step.fault(sender, FAULT_MALFORMED)
            return self._handle_value(sender, message.proof)
        if isinstance(message, EchoMsg):
            if not isinstance(message.proof, Proof) or not message.proof.well_formed():
                return step.fault(sender, FAULT_MALFORMED)
            return self._handle_echo(sender, message.proof)
        if isinstance(message, ReadyMsg):
            if not isinstance(message.root, bytes):
                return step.fault(sender, FAULT_MALFORMED)
            return self._handle_ready(sender, message.root)
        if isinstance(message, EchoHashMsg):
            if not isinstance(message.root, bytes):
                return step.fault(sender, FAULT_MALFORMED)
            return self._handle_echo_hash(sender, message.root)
        if isinstance(message, CanDecodeMsg):
            if not isinstance(message.root, bytes):
                return step.fault(sender, FAULT_MALFORMED)
            return self._handle_can_decode(sender, message.root)
        return step.fault(sender, FAULT_MALFORMED)

    # -- internals -----------------------------------------------------
    def _handle_value(self, sender: Any, proof: Proof) -> Step:
        step = Step.empty()
        if self._echo_sent:
            # A second Value with a different root is proposer equivocation.
            if self._echos.get(self.our_id) and proof.root != self._echos[self.our_id].root:
                step.fault(sender, FAULT_MULTIPLE_VALUES)
            return step
        if proof.index != self._netinfo.our_index or not proof.validate(
            self._netinfo.num_nodes
        ):
            return step.fault(sender, FAULT_INVALID_PROOF)
        self._echo_sent = True
        _trace.emit("rbc.value", proposer=self._proposer)
        # Full Echo (with the shard) to everyone still needing shards —
        # Target.all_except so observers (not in the validator set) keep
        # receiving shards — and hash-only Echo to peers that declared
        # CanDecode for this root.
        hash_only = frozenset(
            nid for nid, r in self._can_decode.items() if r == proof.root
        )
        step.send_targeted(Target.all_except(hash_only), EchoMsg(proof))
        if hash_only:
            step.send_targeted(Target.nodes(hash_only), EchoHashMsg(proof.root))
        step.extend(self._handle_echo(self.our_id, proof))
        return step

    def _handle_echo(self, sender: Any, proof: Proof) -> Step:
        step = Step.empty()
        if sender in self._echos:
            if self._echos[sender] != proof:
                step.fault(sender, FAULT_DUPLICATE)
            return step
        if proof.index != self._netinfo.index(sender):
            return step.fault(sender, FAULT_WRONG_INDEX)
        if not proof.validate(self._netinfo.num_nodes):
            return step.fault(sender, FAULT_INVALID_PROOF)
        if sender in self._echo_hashes and self._echo_hashes[sender] != proof.root:
            return step.fault(sender, FAULT_DUPLICATE)
        self._echos[sender] = proof
        n, f = self._netinfo.num_nodes, self._netinfo.num_faulty
        step.extend(self._maybe_can_decode(proof.root))
        if self._echo_count(proof.root) >= n - f and not self._ready_sent:
            step.extend(self._send_ready(proof.root))
        return step.extend(self._try_decode())

    def _echo_count(self, root: bytes) -> int:
        """Distinct senders vouching for ``root`` via Echo or EchoHash."""
        senders = {s for s, p in self._echos.items() if p.root == root}
        senders |= {s for s, r in self._echo_hashes.items() if r == root}
        return len(senders)

    def _handle_echo_hash(self, sender: Any, root: bytes) -> Step:
        step = Step.empty()
        if sender in self._echo_hashes or sender in self._echos:
            prev = self._echo_hashes.get(sender)
            prev_root = prev if prev is not None else self._echos[sender].root
            if prev_root != root:
                step.fault(sender, FAULT_DUPLICATE)
            return step
        self._echo_hashes[sender] = root
        n, f = self._netinfo.num_nodes, self._netinfo.num_faulty
        if self._echo_count(root) >= n - f and not self._ready_sent:
            step.extend(self._send_ready(root))
        return step.extend(self._try_decode())

    def _handle_can_decode(self, sender: Any, root: bytes) -> Step:
        step = Step.empty()
        if sender in self._can_decode:
            if self._can_decode[sender] != root:
                step.fault(sender, FAULT_DUPLICATE)
            return step
        self._can_decode[sender] = root
        return step

    def _maybe_can_decode(self, root: bytes) -> Step:
        """Announce CanDecode once K shards for ``root`` are stored.

        Observers follow the protocol silently (they are not in the
        validator set, so peers would fault their messages)."""
        step = Step.empty()
        if self._can_decode_sent or self._terminated:
            return step
        if not self._netinfo.is_validator():
            return step
        shards = sum(1 for p in self._echos.values() if p.root == root)
        if shards >= self._data_shards:
            self._can_decode_sent = True
            step.broadcast(CanDecodeMsg(root))
        return step

    def _handle_ready(self, sender: Any, root: bytes) -> Step:
        step = Step.empty()
        if sender in self._readys:
            if self._readys[sender] != root:
                step.fault(sender, FAULT_DUPLICATE)
            return step
        self._readys[sender] = root
        f = self._netinfo.num_faulty
        count = sum(1 for r in self._readys.values() if r == root)
        if count >= f + 1 and not self._ready_sent:
            step.extend(self._send_ready(root))
        return step.extend(self._try_decode())

    def _send_ready(self, root: bytes) -> Step:
        step = Step.empty()
        self._ready_sent = True
        _trace.emit("rbc.ready", proposer=self._proposer)
        step.broadcast(ReadyMsg(root))
        step.extend(self._handle_ready(self.our_id, root))
        return step

    def _try_decode(self) -> Step:
        step = Step.empty()
        if self._terminated:
            return step
        f = self._netinfo.num_faulty
        # A root with 2f+1 Readys is decodable once K shards are stored.
        from collections import Counter

        ready_roots = Counter(self._readys.values())
        for root, count in ready_roots.items():
            if count < 2 * f + 1:
                continue
            shards = {
                p.index: p.value for p in self._echos.values() if p.root == root
            }
            if len(shards) < self._data_shards:
                continue
            # A Byzantine proposer can commit a tree over unequal-length
            # (or otherwise undecodable) leaves; that is its fault, not a
            # crash.
            lengths = {len(s) for s in shards.values()}
            if len(lengths) != 1:
                self._terminated = True
                return step.fault(self._proposer, FAULT_BAD_ENCODING)
            try:
                data = self._rs.reconstruct(shards)
                full = self._rs.encode(data)
            except (ValueError, AssertionError):
                self._terminated = True
                return step.fault(self._proposer, FAULT_BAD_ENCODING)
            # Re-encode and re-hash: the root must commit to a consistent
            # codeword, else the proposer encoded garbage.
            if MerkleTree(full).root != root:
                self._terminated = True  # unrecoverable: proposer Byzantine
                return step.fault(self._proposer, FAULT_BAD_ENCODING)
            value = _unpack(tuple(data))
            if value is None:
                self._terminated = True
                return step.fault(self._proposer, FAULT_BAD_ENCODING)
            self._value = value
            self._terminated = True
            return step.with_output(value)
        return step


def batch_propose(broadcasts, values):
    """Propose many values across many Broadcast instances at once.

    Computes every instance's RS shards + Merkle proofs with the batched
    device data plane (:mod:`hbbft_tpu.ops.jaxops.dataplane`) when shard
    sizes allow — one bit-matmul and a handful of Keccak calls for the
    whole batch — and falls back to the per-instance host path
    otherwise.  Returns one Step per instance (same semantics as calling
    ``handle_input`` on each).

    At firehose scale a proposer participates in many concurrent
    sessions/epochs; this is the aggregation point that turns N
    independent O(|v|) data-plane jobs into one device batch.
    """
    from collections import defaultdict

    assert len(broadcasts) == len(values)
    steps: dict = {}
    groups = defaultdict(list)
    for idx, (bc, value) in enumerate(zip(broadcasts, values)):
        k, n = bc._data_shards, bc._netinfo.num_nodes
        _, shard_len = _dataplane()._pack(bytes(value), k)
        # The device dataplane is GF(256)-only; > 255 validators use the
        # GF(2^16) host codec via the ordinary propose path.
        if n <= 255 and shard_len <= _dataplane().MAX_DEV_SHARD:
            groups[(k, n, shard_len)].append(idx)
        else:
            steps[idx] = bc.handle_input(bytes(value), None)
    for (k, n, _), idxs in groups.items():
        if len(idxs) == 1:
            i = idxs[0]
            steps[i] = broadcasts[i].handle_input(bytes(values[i]), None)
            continue
        proofs = _dataplane().encode_and_prove(
            [bytes(values[i]) for i in idxs], k, n
        )
        for j, i in enumerate(idxs):
            steps[i] = broadcasts[i].propose_with_proofs(proofs[j])
    return [steps[i] for i in range(len(broadcasts))]


def _dataplane():
    from hbbft_tpu.ops.jaxops import dataplane

    return dataplane
