"""DynamicHoneyBadger: HoneyBadger with validator-set change (era system).

Reference: upstream ``src/dynamic_honey_badger/{dynamic_honey_badger,
votes,change,batch,builder}.rs`` (SURVEY.md §2 #10, BASELINE.json:10
"validator churn").  Capability surface preserved:

* validators cast **signed votes** for a :class:`Change` (a full new
  id -> public-key map, or a new encryption schedule);
* votes and DKG messages ride **inside HoneyBadger contributions**
  (``InternalContrib``), so every node processes them in the same agreed
  order — the one ordering guarantee everything else builds on.  (The
  reference additionally gossips them peer-to-peer as a latency
  optimization; the agreed-order path is the correctness-bearing one and
  is what this implementation uses.)
* on a strict majority of current validators' latest votes, an embedded
  :class:`~hbbft_tpu.protocols.sync_key_gen.SyncKeyGen` runs among the
  NEW validator set, its Part/Ack messages threaded through consensus as
  signed key-gen messages;
* when the DKG is ready, the node switches to the new
  :class:`NetworkInfo`, restarts its inner HoneyBadger, and bumps the
  **era**; the emitted :class:`DhbBatch` carries
  ``ChangeState.complete`` and a :class:`JoinPlan` for joining observers.

Messages are (era, epoch)-tagged; previous-era messages are dropped,
next-era messages are buffered (bounded).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from hbbft_tpu.crypto.pool import VerifySink
from hbbft_tpu.obs import trace as _trace
from hbbft_tpu.protocols.honey_badger import (
    Batch,
    EncryptionSchedule,
    HbMessage,
    HoneyBadger,
)
from hbbft_tpu.protocols.errors import ContributionNotEncodable
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.sync_key_gen import Ack, Part, SyncKeyGen
from hbbft_tpu.protocols.traits import ConsensusProtocol, Step
from hbbft_tpu.utils import canonical_bytes, serde

FAULT_MALFORMED = "dynamic_honey_badger:malformed-message"
FAULT_BAD_CONTRIB = "dynamic_honey_badger:malformed-contribution"
FAULT_BAD_VOTE_SIG = "dynamic_honey_badger:invalid-vote-signature"
FAULT_BAD_KG_SIG = "dynamic_honey_badger:invalid-keygen-signature"
FAULT_FUTURE_ERA = "dynamic_honey_badger:message-beyond-next-era"
FAULT_BAD_KG_MSG = "dynamic_honey_badger:invalid-keygen-message"

_FUTURE_ERA_BUFFER_PER_SENDER = 4096


# ---------------------------------------------------------------------------
# Change / votes / join plans
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Change:
    """A proposed reconfiguration.

    kind == "node_change": ``new_validators`` is the COMPLETE new
    id -> regular-public-key map (upstream ``Change::NodeChange``).
    kind == "encryption_schedule": ``schedule`` replaces the inner HB's
    schedule (upstream ``Change::EncryptionSchedule``).
    """

    kind: str
    new_validators: Tuple[Tuple[Any, Any], ...] = ()
    schedule: Optional[EncryptionSchedule] = None

    @staticmethod
    def node_change(pub_keys: Dict[Any, Any]) -> "Change":
        return Change(
            "node_change",
            tuple(sorted(pub_keys.items(), key=lambda kv: str(kv[0]))),
        )

    @staticmethod
    def encryption_schedule(schedule: EncryptionSchedule) -> "Change":
        return Change("encryption_schedule", (), schedule)

    def validator_map(self) -> Dict[Any, Any]:
        return dict(self.new_validators)

    def digest(self) -> bytes:
        parts: List[Any] = [b"change", self.kind]
        for node, pk in self.new_validators:
            parts.append(str(node))
            parts.append(pk.to_bytes())
        if self.schedule is not None:
            parts.append(self.schedule.kind)
            parts.append(self.schedule.n)
        return canonical_bytes(*parts)


@dataclass(frozen=True)
class ChangeState:
    """none | in_progress(change) | complete(change)."""

    kind: str = "none"
    change: Optional[Change] = None

    @staticmethod
    def none() -> "ChangeState":
        return ChangeState("none", None)

    @staticmethod
    def in_progress(change: Change) -> "ChangeState":
        return ChangeState("in_progress", change)

    @staticmethod
    def complete(change: Change) -> "ChangeState":
        return ChangeState("complete", change)


@dataclass(frozen=True)
class JoinPlan:
    """Everything a new observer needs to join at an era boundary."""

    era: int
    public_key_set: Any
    validators: Tuple[Tuple[Any, Any], ...]  # id -> regular public key
    encryption_schedule: EncryptionSchedule

    def validator_map(self) -> Dict[Any, Any]:
        return dict(self.validators)


def _memo_signed_payload(obj: Any, build) -> bytes:
    """Payload bytes are a pure function of the (frozen) object; committed
    messages are decode-cache-shared across all N nodes, so caching on the
    object turns N identical serializations into one."""
    cached = obj.__dict__.get("_sp_bytes")
    if cached is None:
        cached = build()
        object.__setattr__(obj, "_sp_bytes", cached)
    return cached


def _memo_sig_verdict(obj: Any, pk: Any, check) -> bool:
    """Signature verdicts are pure functions of (pk, payload, signature);
    key the per-object memo by the pk's canonical bytes so nodes with
    diverging validator maps can never share a wrong verdict."""
    try:
        key = pk.to_bytes()
    except Exception:
        return bool(check())
    memo = obj.__dict__.get("_sig_ok")
    if memo is None:
        memo = {}
        object.__setattr__(obj, "_sig_ok", memo)
    ok = memo.get(key)
    if ok is None:
        ok = bool(check())
        memo[key] = ok
    return ok


@dataclass(frozen=True)
class SignedVote:
    voter: Any
    era: int
    num: int  # per-voter sequence number; the highest committed one wins
    change: Change
    signature: Any

    def signed_payload(self) -> bytes:
        return _memo_signed_payload(
            self,
            lambda: canonical_bytes(
                b"dhb-vote", str(self.voter), self.era, self.num, self.change.digest()
            ),
        )


@dataclass(frozen=True)
class SignedKeyGenMsg:
    """A DKG Part/Ack, signed by its sender, threaded through consensus."""

    era: int
    sender: Any
    payload: Any  # Part | Ack
    signature: Any

    def signed_payload(self) -> bytes:
        return _memo_signed_payload(
            self,
            lambda: canonical_bytes(
                b"dhb-kg", str(self.sender), self.era, _kg_payload_bytes(self.payload)
            ),
        )


def _kg_payload_bytes(payload: Any) -> bytes:
    """Canonical (collision-free) bytes of a Part/Ack for signing.

    Memoized on the (frozen) payload object: every node recomputes this
    for every committed key-gen message otherwise — with shared decoded
    objects that is N^2 serializations of multi-kilobyte Parts per
    churn epoch."""
    cached = payload.__dict__.get("_kg_bytes") if hasattr(payload, "__dict__") else None
    if cached is not None:
        return cached
    if isinstance(payload, Part):
        out = canonical_bytes(
            b"part", payload.commitment.to_bytes(), *[c.to_bytes() for c in payload.rows]
        )
    elif isinstance(payload, Ack):
        out = canonical_bytes(
            b"ack", str(payload.proposer), *[c.to_bytes() for c in payload.values]
        )
    else:
        raise TypeError(f"not a key-gen payload: {type(payload)!r}")
    object.__setattr__(payload, "_kg_bytes", out)
    return out


@dataclass(frozen=True)
class InternalContrib:
    """What actually rides through the inner HoneyBadger each epoch."""

    contribution: Any
    key_gen_messages: Tuple[SignedKeyGenMsg, ...] = ()
    votes: Tuple[SignedVote, ...] = ()


@dataclass(frozen=True)
class DhbMessage:
    era: int
    inner: HbMessage


@dataclass(frozen=True)
class DhbBatch:
    """One committed epoch at the DHB layer."""

    era: int
    epoch: int
    contributions: Tuple[Tuple[Any, Any], ...]  # user contributions only
    change: ChangeState = ChangeState.none()
    join_plan: Optional[JoinPlan] = None

    def contribution_map(self) -> Dict[Any, Any]:
        return dict(self.contributions)


# ---------------------------------------------------------------------------
# Vote counting
# ---------------------------------------------------------------------------


class VoteCounter:
    """Latest committed vote per validator; winner = strict majority.

    Reference: upstream ``src/dynamic_honey_badger/votes.rs``.
    """

    def __init__(self) -> None:
        self.committed: Dict[Any, SignedVote] = {}

    def add(self, vote: SignedVote) -> None:
        cur = self.committed.get(vote.voter)
        if cur is None or vote.num > cur.num:
            self.committed[vote.voter] = vote

    def winner(self, validators: Tuple[Any, ...]) -> Optional[Change]:
        tally: Dict[bytes, Tuple[int, Change]] = {}
        for node in validators:
            vote = self.committed.get(node)
            if vote is None:
                continue
            d = vote.change.digest()
            cnt, _ = tally.get(d, (0, vote.change))
            tally[d] = (cnt + 1, vote.change)
        for cnt, change in tally.values():
            if 2 * cnt > len(validators):
                return change
        return None


# ---------------------------------------------------------------------------
# Key-generation state (one validator-set change in flight)
# ---------------------------------------------------------------------------


class _KeyGenState:
    def __init__(
        self, change: Change, key_gen: SyncKeyGen, threshold: int
    ) -> None:
        self.change = change
        self.key_gen = key_gen
        self.threshold = threshold
        self.parts_handled: Dict[Any, bool] = {}
        # change.validator_map() builds a fresh dict per call; the kg
        # signature path asks once per committed Part/Ack (N^2 per churn).
        self.val_map: Dict[Any, Any] = (
            change.validator_map() if change.kind == "node_change" else {}
        )

    @property
    def ready(self) -> bool:
        return self.key_gen.is_ready()


# ---------------------------------------------------------------------------
# DynamicHoneyBadger
# ---------------------------------------------------------------------------


class DynamicHoneyBadger(ConsensusProtocol):
    """Era-structured HoneyBadger with embedded DKG for membership change.

    ``sink`` is the node-level :class:`VerifySink`; child protocols get
    scoped views so verification callbacks re-enter through this layer
    exactly like ordinary messages.
    """

    def __init__(
        self,
        netinfo: NetworkInfo,
        sink: VerifySink,
        session_id: bytes = b"dhb",
        era: int = 0,
        max_future_epochs: int = 3,
        encryption_schedule: EncryptionSchedule = EncryptionSchedule.always(),
        suite: Any = None,
        subset_handling: str = "incremental",
    ) -> None:
        self._netinfo = netinfo
        self._sink = sink
        self._session_id = bytes(session_id)
        self._era = era
        self.max_future_epochs = max_future_epochs
        self.encryption_schedule = encryption_schedule
        self.subset_handling = subset_handling
        self._suite = suite if suite is not None else _suite_of(netinfo)
        self._hb: HoneyBadger = self._make_hb()
        self._vote_counter = VoteCounter()
        self._our_vote: Optional[SignedVote] = None
        self._vote_num = 0
        self._key_gen: Optional[_KeyGenState] = None
        self._outgoing_kg: List[SignedKeyGenMsg] = []
        self._next_era_buffer: List[Tuple[Any, DhbMessage]] = []
        self._rng: Any = None  # last rng seen; used for era restarts

    # -- construction helpers -----------------------------------------
    @staticmethod
    def from_join_plan(
        our_id: Any,
        secret_key: Any,
        join_plan: JoinPlan,
        sink: VerifySink,
        session_id: bytes = b"dhb",
        max_future_epochs: int = 3,
        suite: Any = None,
    ) -> "DynamicHoneyBadger":
        """Join as an observer at the era boundary described by the plan."""
        netinfo = NetworkInfo(
            our_id,
            tuple(join_plan.validator_map()),
            join_plan.public_key_set,
            None,
            join_plan.validator_map(),
            secret_key,
        )
        return DynamicHoneyBadger(
            netinfo,
            sink,
            session_id=session_id,
            era=join_plan.era,
            max_future_epochs=max_future_epochs,
            encryption_schedule=join_plan.encryption_schedule,
            suite=suite,
        )

    def _make_hb(self) -> HoneyBadger:
        # The scoped sink pins this HB's era: verification callbacks of a
        # finished era keep only their fault reports.
        era = self._era
        # Tracer era ctx must advance HERE, not only at handle_message
        # entry: an era change runs inside a batch's processing, and the
        # new HoneyBadger's _EpochState(0) emits epoch.open immediately —
        # with a stale ctx the new era's first epoch would be keyed to
        # the OLD era and corrupt both eras' phase spans.
        _trace.set_ctx(era=era)
        return HoneyBadger(
            self._netinfo,
            self._sink.scoped(lambda s, e=era: self._on_hb_step_era(e, s)),
            session_id=canonical_bytes(self._session_id, self._era),
            max_future_epochs=self.max_future_epochs,
            encryption_schedule=self.encryption_schedule,
            subset_handling=self.subset_handling,
        )

    # -- ConsensusProtocol --------------------------------------------
    @property
    def our_id(self) -> Any:
        return self._netinfo.our_id

    @property
    def terminated(self) -> bool:
        return False

    @property
    def era(self) -> int:
        return self._era

    @property
    def netinfo(self) -> NetworkInfo:
        return self._netinfo

    @property
    def has_input(self) -> bool:
        return self._hb.has_input

    def handle_input(self, input: Any, rng: Any) -> Step:
        """Propose a user contribution this epoch.

        Encodability is validated BEFORE ``_make_contrib`` drains the
        outgoing key-gen queue, so a bad input cannot destroy queued DKG
        messages on its way to raising."""
        self._rng = rng
        try:
            serde.dumps(input)
        except serde.EncodeError as e:
            raise ContributionNotEncodable(str(e)) from e
        return self._lift(self._hb.handle_input(self._make_contrib(input), rng))

    def vote_for(self, change: Change, rng: Any) -> Step:
        """Cast (or replace) our signed vote; rides in contributions."""
        if not self._netinfo.is_validator():
            return Step.empty()
        self._vote_num += 1
        vote = SignedVote(self.our_id, self._era, self._vote_num, change, None)
        sig = self._netinfo.secret_key.sign(vote.signed_payload())
        self._our_vote = SignedVote(self.our_id, self._era, self._vote_num, change, sig)
        return Step.empty()

    def vote_to_add(self, node_id: Any, pub_key: Any, rng: Any) -> Step:
        keys = self._netinfo.public_key_map
        keys[node_id] = pub_key
        return self.vote_for(Change.node_change(keys), rng)

    def vote_to_remove(self, node_id: Any, rng: Any) -> Step:
        keys = self._netinfo.public_key_map
        keys.pop(node_id, None)
        return self.vote_for(Change.node_change(keys), rng)

    def handle_message(self, sender: Any, message: Any, rng: Any) -> Step:
        self._rng = rng
        step = Step.empty()
        if not isinstance(message, DhbMessage) or not isinstance(
            message.era, int
        ) or isinstance(message.era, bool):
            return step.fault(sender, FAULT_MALFORMED)
        if message.era < self._era:
            return step  # previous era: stale, drop
        if message.era > self._era + 1:
            return step.fault(sender, FAULT_FUTURE_ERA)
        if message.era == self._era + 1:
            if len(self._next_era_buffer) < _FUTURE_ERA_BUFFER_PER_SENDER:
                self._next_era_buffer.append((sender, message))
            return step
        # Tracer context: epoch-level milestones below HB carry the era
        # they belong to (era changes restart HB's epoch counter at 0).
        _trace.set_ctx(era=self._era)
        return step.extend(self._lift(self._hb.handle_message(sender, message.inner, rng)))

    # -- internals -----------------------------------------------------
    def _make_contrib(self, input: Any) -> InternalContrib:
        kg, self._outgoing_kg = tuple(self._outgoing_kg), []
        votes = (self._our_vote,) if self._our_vote is not None else ()
        return InternalContrib(input, kg, votes)

    def _lift(self, hb_step: Step) -> Step:
        """Wrap inner-HB messages with the era tag; process batches."""
        step = hb_step.map_messages(lambda m: DhbMessage(self._era, m))
        outputs, step.output = step.output, []
        for batch in outputs:
            step.extend(self._process_batch(batch))
        return step

    def _on_hb_step_era(self, era: int, hb_step: Step) -> Step:
        if era != self._era:
            return Step(output=[], messages=[], fault_log=hb_step.fault_log)
        return self._lift(hb_step)

    def _process_batch(self, batch: Batch) -> Step:
        step = Step.empty()
        user_contribs: List[Tuple[Any, Any]] = []
        kg_msgs: List[Tuple[Any, SignedKeyGenMsg]] = []
        for proposer, contrib in batch.contributions:
            if not isinstance(contrib, InternalContrib):
                step.fault(proposer, FAULT_BAD_CONTRIB)
                continue
            user_contribs.append((proposer, contrib.contribution))
            for vote in contrib.votes:
                step.extend(self._commit_vote(proposer, vote))
            for kg in contrib.key_gen_messages:
                if not isinstance(kg, SignedKeyGenMsg):
                    step.fault(proposer, FAULT_BAD_CONTRIB)
                    continue
                kg_msgs.append((proposer, kg))
        # Process key-gen messages in the batch's deterministic order.
        for proposer, kg in kg_msgs:
            step.extend(self._handle_kg_message(proposer, kg))
        change_state = ChangeState.none()
        join_plan: Optional[JoinPlan] = None
        if self._key_gen is None:
            winner = self._vote_counter.winner(self._netinfo.all_ids)
            if winner is not None:
                step.extend(self._start_key_gen(winner))
                if self._key_gen is not None:
                    change_state = ChangeState.in_progress(winner)
        era_before = self._era
        if self._key_gen is not None:
            if self._key_gen.change.kind == "encryption_schedule":
                change_state, join_plan = self._complete_schedule_change()
            elif self._key_gen.ready:
                change_state, join_plan = self._complete_node_change()
            else:
                change_state = ChangeState.in_progress(self._key_gen.change)
        step.with_output(
            DhbBatch(
                era_before,
                batch.epoch,
                tuple(user_contribs),
                change_state,
                join_plan,
            )
        )
        if self._era != era_before:
            step.extend(self._replay_next_era())
        return step

    def _commit_vote(self, proposer: Any, vote: Any) -> Step:
        step = Step.empty()
        if (
            not isinstance(vote, SignedVote)
            or not isinstance(vote.change, Change)
            or vote.era != self._era
            or not self._netinfo.is_node_validator(vote.voter)
        ):
            return step.fault(proposer, FAULT_BAD_VOTE_SIG)
        try:
            pk = self._netinfo.public_key(vote.voter)
            ok = _memo_sig_verdict(
                vote, pk, lambda: pk.verify(vote.signed_payload(), vote.signature)
            )
        except Exception:
            ok = False
        if not ok:
            return step.fault(proposer, FAULT_BAD_VOTE_SIG)
        self._vote_counter.add(vote)
        return step

    def _start_key_gen(self, change: Change) -> Step:
        step = Step.empty()
        if change.kind == "encryption_schedule":
            self._key_gen = _KeyGenState(change, None, 0)  # type: ignore[arg-type]
            return step
        new_map = change.validator_map()
        threshold = (len(new_map) - 1) // 3
        key_gen, part = SyncKeyGen.new(
            self.our_id,
            self._netinfo.secret_key,
            new_map,
            threshold,
            self._rng,
            self._suite,
        )
        self._key_gen = _KeyGenState(change, key_gen, threshold)
        if part is not None and self._netinfo.is_validator():
            self._queue_kg(part)
        return step

    def _queue_kg(self, payload: Any) -> None:
        msg = SignedKeyGenMsg(self._era, self.our_id, payload, None)
        sig = self._netinfo.secret_key.sign(msg.signed_payload())
        self._outgoing_kg.append(
            SignedKeyGenMsg(self._era, self.our_id, payload, sig)
        )

    def _handle_kg_message(self, proposer: Any, kg: SignedKeyGenMsg) -> Step:
        step = Step.empty()
        state = self._key_gen
        if state is None or state.key_gen is None or kg.era != self._era:
            return step  # no change in flight (or stale): ignore
        sender = kg.sender
        # Signature check: the sender must be a CURRENT-era validator
        # (only they deal/ack) or a NEW-set member for acks.
        pk = self._netinfo.public_key_map.get(sender) or state.val_map.get(sender)
        try:
            ok = pk is not None and _memo_sig_verdict(
                kg, pk, lambda: pk.verify(kg.signed_payload(), kg.signature)
            )
        except Exception:
            ok = False
        if not ok:
            return step.fault(proposer, FAULT_BAD_KG_SIG)
        if isinstance(kg.payload, Part):
            outcome = state.key_gen.handle_part(sender, kg.payload, self._rng)
            if not outcome.is_valid:
                step.fault(sender, FAULT_BAD_KG_MSG)
            elif outcome.ack is not None:
                self._queue_kg(outcome.ack)
        elif isinstance(kg.payload, Ack):
            outcome = state.key_gen.handle_ack(sender, kg.payload)
            if not outcome.is_valid:
                step.fault(sender, FAULT_BAD_KG_MSG)
        else:
            step.fault(proposer, FAULT_BAD_CONTRIB)
        return step

    def _complete_schedule_change(self) -> Tuple[ChangeState, Optional[JoinPlan]]:
        change = self._key_gen.change
        self.encryption_schedule = change.schedule
        return self._restart_era(change, self._netinfo)

    def _complete_node_change(self) -> Tuple[ChangeState, Optional[JoinPlan]]:
        state = self._key_gen
        pub_key_set, sk_share = state.key_gen.generate()
        new_map = state.change.validator_map()
        netinfo = NetworkInfo(
            self.our_id,
            tuple(new_map),
            pub_key_set,
            sk_share if self.our_id in new_map else None,
            new_map,
            self._netinfo.secret_key,
        )
        return self._restart_era(state.change, netinfo)

    def _restart_era(
        self, change: Change, netinfo: NetworkInfo
    ) -> Tuple[ChangeState, Optional[JoinPlan]]:
        self._era += 1
        self._netinfo = netinfo
        self._key_gen = None
        self._vote_counter = VoteCounter()
        self._our_vote = None
        self._outgoing_kg = []
        self._hb = self._make_hb()
        join_plan = JoinPlan(
            self._era,
            netinfo.public_key_set,
            tuple(sorted(netinfo.public_key_map.items(), key=lambda kv: str(kv[0]))),
            self.encryption_schedule,
        )
        return ChangeState.complete(change), join_plan

    def _replay_next_era(self) -> Step:
        step = Step.empty()
        buffered, self._next_era_buffer = self._next_era_buffer, []
        for sender, msg in buffered:
            step.extend(self.handle_message(sender, msg, self._rng))
        return step


def _suite_of(netinfo: NetworkInfo) -> Any:
    pks = netinfo.public_key_set
    suite = getattr(pks, "suite", None)
    if suite is None:
        raise ValueError("cannot infer crypto suite from NetworkInfo")
    return suite
