"""QueueingHoneyBadger: DynamicHoneyBadger + automatic transaction queue.

Reference: upstream ``src/queueing_honey_badger/{mod,builder}.rs``
(SURVEY.md §2 #11).  Maintains a :class:`TransactionQueue`; each epoch
proposes a random sample of up to ``batch_size / N`` pending
transactions, removes committed ones, and re-proposes across era
changes.  Input is either a user transaction or a :class:`Change` vote.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional

from hbbft_tpu.crypto.pool import VerifySink
from hbbft_tpu.protocols.dynamic_honey_badger import (
    Change,
    DhbBatch,
    DynamicHoneyBadger,
    JoinPlan,
)
from hbbft_tpu.protocols.errors import ContributionNotEncodable
from hbbft_tpu.protocols.network_info import NetworkInfo
from hbbft_tpu.protocols.traits import ConsensusProtocol, Step
from hbbft_tpu.utils import serde
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.protocols.transaction_queue import TransactionQueue


@dataclass(frozen=True)
class Input:
    """User(txn) or Change(vote) — upstream ``Input::{User,Change}``."""

    kind: str  # "user" | "change"
    value: Any

    @staticmethod
    def user(txn: Any) -> "Input":
        return Input("user", txn)

    @staticmethod
    def change(change: Change) -> "Input":
        return Input("change", change)


class QueueingHoneyBadger(ConsensusProtocol):
    def __init__(
        self,
        netinfo: NetworkInfo,
        sink: VerifySink,
        batch_size: int = 100,
        session_id: bytes = b"qhb",
        max_future_epochs: int = 3,
        encryption_schedule: EncryptionSchedule = EncryptionSchedule.always(),
        dhb: Optional[DynamicHoneyBadger] = None,
        subset_handling: str = "incremental",
    ) -> None:
        self.batch_size = batch_size
        self.queue = TransactionQueue()
        self._rng: Any = None
        # Scope the sink: batches surfacing from deferred-verification
        # flushes must pass through _absorb (txn removal + re-propose)
        # exactly like batches from ordinary message handling.
        scoped = sink.scoped(lambda step: self._absorb(step, self._rng))
        self.dhb = dhb or DynamicHoneyBadger(
            netinfo,
            scoped,
            session_id=session_id,
            max_future_epochs=max_future_epochs,
            encryption_schedule=encryption_schedule,
            subset_handling=subset_handling,
        )

    @staticmethod
    def from_join_plan(
        our_id: Any,
        secret_key: Any,
        join_plan: JoinPlan,
        sink: VerifySink,
        batch_size: int = 100,
        session_id: bytes = b"qhb",
        max_future_epochs: int = 3,
    ) -> "QueueingHoneyBadger":
        dhb = DynamicHoneyBadger.from_join_plan(
            our_id, secret_key, join_plan, sink,
            session_id=session_id, max_future_epochs=max_future_epochs,
        )
        qhb = QueueingHoneyBadger(dhb.netinfo, sink, batch_size=batch_size, dhb=dhb)
        return qhb

    # -- ConsensusProtocol --------------------------------------------
    @property
    def our_id(self) -> Any:
        return self.dhb.our_id

    @property
    def terminated(self) -> bool:
        return False

    @property
    def netinfo(self) -> NetworkInfo:
        return self.dhb.netinfo

    def handle_input(self, input: Any, rng: Any) -> Step:
        self._rng = rng
        if not isinstance(input, Input):
            input = Input.user(input)  # convenience: bare txn
        if input.kind == "change":
            step = self.dhb.vote_for(input.value, rng)
        else:
            # Validate at push: a bad transaction must fail HERE, not
            # epochs later when the queue happens to sample it.
            try:
                serde.dumps(input.value)
            except serde.EncodeError as e:
                raise ContributionNotEncodable(str(e)) from e
            self.queue.push(input.value)
            step = Step.empty()
        return step.extend(self._propose(rng))

    def push_transaction(self, txn: Any, rng: Any) -> Step:
        return self.handle_input(Input.user(txn), rng)

    def vote_for(self, change: Change, rng: Any) -> Step:
        return self.handle_input(Input.change(change), rng)

    def handle_message(self, sender: Any, message: Any, rng: Any) -> Step:
        self._rng = rng
        return self._absorb(self.dhb.handle_message(sender, message, rng), rng)

    # -- internals -----------------------------------------------------
    def _amount(self) -> int:
        n = max(1, self.dhb.netinfo.num_nodes)
        return max(1, self.batch_size // n)

    def _propose(self, rng: Any) -> Step:
        """Propose a fresh random sample unless this epoch already has one."""
        if not self.dhb.netinfo.is_validator() or self.dhb.has_input:
            return Step.empty()
        sample = self.queue.choose(rng, self._amount())
        return self._absorb(self.dhb.handle_input(sample, rng), rng)

    def _absorb(self, dhb_step: Step, rng: Any) -> Step:
        """Lift DHB batches: drop committed txns, re-propose if needed."""
        step = dhb_step
        batches: List[DhbBatch] = [o for o in step.output if isinstance(o, DhbBatch)]
        for batch in batches:
            committed: List[Any] = []
            for _, contrib in batch.contributions:
                if isinstance(contrib, (list, tuple)):
                    committed.extend(contrib)
            self.queue.remove_multiple(committed)
        if batches:
            # Always re-propose (an empty sample if the queue is drained):
            # Subset needs N-f proposals per epoch, so a node going quiet
            # would stall everyone (upstream QHB proposes every epoch too).
            step = step.extend(self._propose(rng))
        return step
