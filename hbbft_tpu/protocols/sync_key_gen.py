"""SyncKeyGen: dealerless distributed key generation (DKG).

Reference: upstream ``src/sync_key_gen.rs`` (SURVEY.md §2 #12) — fork
checkout empty at survey time, reconstructed from the upstream public
crate's documented scheme.

Scheme (Pedersen-style DKG over symmetric bivariate polynomials):

* Each proposer ``d`` deals a random *symmetric* bivariate polynomial
  ``p_d(x, y)`` of degree ``t`` in each variable and publishes a ``Part``:
  the :class:`~hbbft_tpu.crypto.poly.BivarCommitment` plus, for each node
  ``m``, the row polynomial ``p_d(m+1, ·)`` encrypted to ``m``'s public
  key.
* A node ``m`` that receives a valid ``Part`` (its row matches the
  commitment) answers with an ``Ack`` carrying, for each node ``j``, the
  value ``p_d(m+1, j+1)`` encrypted to ``j``.  By symmetry this equals
  ``p_d(j+1, m+1)``, i.e. one evaluation point of ``j``'s row — so ``j``
  can reconstruct its secret even if the dealer equivocates or crashes
  after sending only some rows.
* A proposal is *complete* once ``2t+1`` nodes have acked it; key
  generation is *ready* once ``t+1`` proposals are complete.
* ``generate()``: the joint public-key commitment is the sum over
  complete proposals of the committed master row ``p_d(0, ·)``; node
  ``j``'s secret share is ``sum_d p_d(0, j+1)``, each term interpolated
  at ``x = 0`` from the ``t+1``-plus received evaluations
  ``p_d(m+1, j+1)``.

The synchronous-rounds assumption is satisfied by running the Part/Ack
exchange *through* consensus (DynamicHoneyBadger threads them through
committed batches, SURVEY.md §3.3), so every node processes the same
messages in the same order.  SyncKeyGen itself is a plain
message-in/outcome-out state machine with no Step/Target plumbing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Set, Tuple

from hbbft_tpu.crypto.keys import (
    Ciphertext,
    PublicKey,
    SecretKey,
    SecretKeyShare,
    dkg_batch_enabled,
)
from hbbft_tpu.crypto.poly import BivarCommitment, BivarPoly, Commitment, Poly, interpolate
from hbbft_tpu.crypto.suite import Suite

FAULT_MULTIPLE_PARTS = "sync_key_gen:multiple-parts"
FAULT_BAD_PART = "sync_key_gen:invalid-part"
FAULT_BAD_ACK = "sync_key_gen:invalid-ack"
FAULT_UNKNOWN_SENDER = "sync_key_gen:unknown-sender"
FAULT_ACK_BEFORE_PART = "sync_key_gen:ack-without-part"

_SCALAR_BYTES = 32  # BLS12-381 r fits in 255 bits


class _NativeDkg:
    """Scalar-suite fast path for the DKG's N^3 private checks.

    The committed-ack value check (KEM decrypt + commitment row eval +
    compare) and the ack-row construction (poly evals + N encrypts) are
    the measured Python tail of an era change (BASELINE.md round-4/5).
    native/engine.cpp exposes them as single C calls over a registered
    commitment matrix; semantics are byte-identical to the pure path
    (same KEM, same Horner, same fault outcomes — the native engine
    equivalence suites pin this end to end), and ANY mismatch in shape,
    suite, or registry routing falls back to the pure-Python path.
    """

    def __init__(self, lib: Any, suite: Suite) -> None:
        import ctypes

        self._ctypes = ctypes
        self._lib = lib
        self._suite = suite
        self._g = suite.g1_generator().to_bytes()
        self._r = suite.scalar_modulus.to_bytes(_SCALAR_BYTES, "big")
        from hbbft_tpu.crypto.keys import _scalar_kem

        self.kem = _scalar_kem(suite)

    def commit_id(self, commitment: Any) -> int:
        """Register (once, memoized on the shared decoded object)."""
        cached = commitment.__dict__.get("_native_cid")
        if cached is not None:
            return cached
        try:
            flat = b"".join(
                e.value.to_bytes(_SCALAR_BYTES, "big")
                for row in commitment.elems
                for e in row
            )
            cid = int(
                self._lib.hbe_dkg_register(
                    flat, len(commitment.elems), self._g, self._r
                )
            )
        except Exception:
            cid = -1
        object.__setattr__(commitment, "_native_cid", cid)
        return cid

    def refresh_commit_id(self, commitment: Any) -> int:
        """Drop a STALE memoized cid and re-register once (ADVICE round
        5): after a registry generation bump (byte-cap clear) every
        still-live commitment's memo returns -1 from the checks forever
        — correct but permanently stranded on the slow path.  Called on
        an rc == -1 from ack/row checks; the caller retries once with
        the fresh cid and falls back if that one misses too."""
        commitment.__dict__.pop("_native_cid", None)
        return self.commit_id(commitment)

    def ack_check(
        self, cid: int, sender_pos: int, our_pos: int, ct: Any, sk_x: int
    ) -> Tuple[int, int]:
        """(rc, value): rc 1 ok, 2 bad value, 0 bad ciphertext, -1 fall
        back."""
        out = (self._ctypes.c_uint8 * _SCALAR_BYTES)()
        rc = int(
            self._lib.hbe_dkg_ack_check(
                cid, sender_pos, our_pos,
                ct.u.value.to_bytes(_SCALAR_BYTES, "big"), ct.v,
                ct.w.value.to_bytes(_SCALAR_BYTES, "big"),
                sk_x.to_bytes(_SCALAR_BYTES, "big"), out,
            )
        )
        return rc, int.from_bytes(bytes(out), "big")

    def row_check(self, cid: int, our_pos: int, plain: bytes, n1: int) -> int:
        return int(self._lib.hbe_dkg_row_check(cid, our_pos, plain, n1))

    def ack_check_batch(
        self, items: list, our_pos: int, sk_x: int
    ) -> Optional[list]:
        """One C call for a whole batch's ack checks.

        ``items``: ``(cid, sender_pos, ct)`` triples; returns a matching
        ``[(rc, value)]`` list with per-item rc identical to
        :meth:`ack_check`, or None when the native call itself is
        unusable (caller falls back per item)."""
        ctypes = self._ctypes
        n = len(items)
        cids = (ctypes.c_int64 * n)(*[c for c, _, _ in items])
        spos = (ctypes.c_int32 * n)(*[s for _, s, _ in items])
        u = b"".join(
            ct.u.value.to_bytes(_SCALAR_BYTES, "big") for _, _, ct in items
        )
        v = b"".join(ct.v for _, _, ct in items)
        w = b"".join(
            ct.w.value.to_bytes(_SCALAR_BYTES, "big") for _, _, ct in items
        )
        rcs = (ctypes.c_int32 * n)()
        vals = (ctypes.c_uint8 * (_SCALAR_BYTES * n))()
        ok = int(
            self._lib.hbe_dkg_ack_check_batch(
                cids, spos, n, our_pos, u, v, w,
                sk_x.to_bytes(_SCALAR_BYTES, "big"), rcs, vals,
            )
        )
        if not ok:
            return None
        vb = bytes(vals)
        return [
            (
                int(rcs[i]),
                int.from_bytes(
                    vb[_SCALAR_BYTES * i : _SCALAR_BYTES * (i + 1)], "big"
                ),
            )
            for i in range(n)
        ]

    def part_check_batch(
        self, items: list, our_pos: int, n1: int, sk_x: int
    ) -> Optional[list]:
        """One C call for a batch of Part row checks (decrypt our row +
        decode + commitment consistency).  ``items``: ``(cid, ct)``
        pairs whose ``ct.v`` is exactly ``n1 * 32`` bytes; returns
        ``[(rc, row_plain_bytes)]`` (rc 1 ok / 2 fault / 0 bad ct /
        -1 fall back), or None."""
        ctypes = self._ctypes
        n = len(items)
        vlen = n1 * _SCALAR_BYTES
        cids = (ctypes.c_int64 * n)(*[c for c, _ in items])
        u = b"".join(
            ct.u.value.to_bytes(_SCALAR_BYTES, "big") for _, ct in items
        )
        v = b"".join(ct.v for _, ct in items)
        w = b"".join(
            ct.w.value.to_bytes(_SCALAR_BYTES, "big") for _, ct in items
        )
        rcs = (ctypes.c_int32 * n)()
        rows = (ctypes.c_uint8 * (vlen * n))()
        ok = int(
            self._lib.hbe_dkg_part_check_batch(
                cids, n, our_pos, u, v, w, n1,
                sk_x.to_bytes(_SCALAR_BYTES, "big"), rcs, rows,
            )
        )
        if not ok:
            return None
        rb = bytes(rows)
        return [
            (int(rcs[i]), rb[vlen * i : vlen * (i + 1)]) for i in range(n)
        ]

    def interp_sum(self, groups: list) -> Optional[int]:
        """sum over groups of interpolate_at_0(points) mod r in one C
        call (the vectorized Lagrange entry; mirrors poly.interpolate).
        ``groups``: lists of ``(x, y)`` int points.  None = fall back."""
        ctypes = self._ctypes
        xs: list = []
        ys: list = []
        counts: list = []
        for pts in groups:
            counts.append(len(pts))
            for x, y in pts:
                xs.append(x)
                ys.append(y)
        # c_int32 arrays TRUNCATE oversized ints silently (no
        # OverflowError) — bound explicitly so a huge x falls back to
        # the Python oracle instead of interpolating at a wrong point.
        if any(
            isinstance(x, bool) or not isinstance(x, int)
            or x <= 0 or x >= (1 << 31)
            for x in xs
        ):
            return None
        xs_arr = (ctypes.c_int32 * len(xs))(*xs)
        counts_arr = (ctypes.c_int32 * len(counts))(*counts)
        ys_b = b"".join(y.to_bytes(_SCALAR_BYTES, "big") for y in ys)
        out = (ctypes.c_uint8 * _SCALAR_BYTES)()
        ok = int(
            self._lib.hbe_scalar_interp_sum(
                xs_arr, ys_b, counts_arr, len(counts), self._r, out
            )
        )
        if not ok:
            return None
        return int.from_bytes(bytes(out), "big")

    def ack_values(
        self, row: "Poly", pub_keys_g1: list, rng: Any
    ) -> Tuple["Ciphertext", ...]:
        """The ack's encrypted row evaluations, batched: one C call for
        the N poly evals and one for the N KEM encrypts.  The rng draws
        happen HERE in the exact per-encrypt order of the pure path
        (PublicKey.encrypt draws randrange(1, r) once per call), so the
        consumption stream — and every equivalence test — is unchanged.
        """
        ctypes = self._ctypes
        n = len(pub_keys_g1)
        mod = self._suite.scalar_modulus
        coeffs = b"".join(
            c.to_bytes(_SCALAR_BYTES, "big") for c in row.coeffs
        )
        evals = (ctypes.c_uint8 * (_SCALAR_BYTES * n))()
        self._lib.hbe_dkg_row_evals(coeffs, len(row.coeffs), n, evals)
        rs = b"".join(
            rng.randrange(1, mod).to_bytes(_SCALAR_BYTES, "big")
            for _ in range(n)
        )
        pks = b"".join(
            g.value.to_bytes(_SCALAR_BYTES, "big") for g in pub_keys_g1
        )
        out_u = (ctypes.c_uint8 * (_SCALAR_BYTES * n))()
        out_v = (ctypes.c_uint8 * (_SCALAR_BYTES * n))()
        out_w = (ctypes.c_uint8 * (_SCALAR_BYTES * n))()
        self._lib.hbe_kem_encrypt_batch(
            pks, bytes(evals), n, rs, out_u, out_v, out_w
        )
        from hbbft_tpu.crypto.keys import scalar_ct_serde

        g_type = type(self._suite.g1_generator())
        u_b, v_b, w_b = bytes(out_u), bytes(out_v), bytes(out_w)
        cts = []
        for j in range(n):
            s = slice(_SCALAR_BYTES * j, _SCALAR_BYTES * (j + 1))
            ct = Ciphertext(
                g_type(int.from_bytes(u_b[s], "big"), mod),
                v_b[s],
                g_type(int.from_bytes(w_b[s], "big"), mod),
                self._suite,
            )
            object.__setattr__(ct, "_verify_ok", True)
            object.__setattr__(
                ct, "_serde_cache", scalar_ct_serde(u_b[s], v_b[s], w_b[s])
            )
            cts.append(ct)
        return tuple(cts)


_NATIVE_DKG: dict = {}

# Batch-digest observation counters (tests/benchmarks only; protocol
# logic NEVER reads these).  "items" = entries pre-digested, "hits" =
# entries consumed by handle_ack/_decrypt_row.
PREDIGEST_STATS = {"items": 0, "hits": 0}


# Kill switch for the round-6 batch-digest fast paths (predigest,
# vectorized generate/combine) — HBBFT_TPU_DKG_BATCH=0 restores the
# per-item round-5 behavior for back-to-back A/B measurement.  Single
# definition in crypto.keys so the combines and the digest can never
# disagree about the switch.
_batch_dkg_enabled = dkg_batch_enabled


def _native_dkg(suite: Suite) -> Optional[_NativeDkg]:
    if suite.name != "scalar-insecure":
        return None
    nd = _NATIVE_DKG.get(suite.name, False)
    if nd is not False:
        return nd
    try:
        from hbbft_tpu import native_engine

        lib = native_engine.get_lib()
        nd = _NativeDkg(lib, suite) if lib is not None else None
        if nd is not None and nd.kem is None:
            nd = None
    except Exception:
        nd = None
    _NATIVE_DKG[suite.name] = nd
    return nd


def _encode_scalars(vals: Tuple[int, ...]) -> bytes:
    """Fixed-width canonical encoding — the decrypted plaintext is
    attacker-chosen, so no pickle here (arbitrary-object deserialization
    of Byzantine bytes would be code execution)."""
    return b"".join(v.to_bytes(_SCALAR_BYTES, "big") for v in vals)


def _decode_scalars(data: Any, count: int, modulus: int) -> Optional[Tuple[int, ...]]:
    if not isinstance(data, bytes) or len(data) != count * _SCALAR_BYTES:
        return None
    vals = tuple(
        int.from_bytes(data[i * _SCALAR_BYTES : (i + 1) * _SCALAR_BYTES], "big")
        for i in range(count)
    )
    if any(v >= modulus for v in vals):
        return None
    return vals


@dataclass(frozen=True)
class Part:
    """A dealer's contribution: commitment + per-node encrypted rows."""

    commitment: BivarCommitment
    rows: Tuple[Ciphertext, ...]  # rows[m] encrypts serde(row poly of node m)

    def __repr__(self) -> str:
        return f"Part(degree={self.commitment.degree}, rows={len(self.rows)})"


@dataclass(frozen=True)
class Ack:
    """Node's confirmation of a dealer's Part: per-node encrypted values."""

    proposer: Any
    values: Tuple[Ciphertext, ...]  # values[j] encrypts int p_d(our+1, j+1)

    def __repr__(self) -> str:
        return f"Ack(proposer={self.proposer!r}, values={len(self.values)})"


@dataclass(frozen=True)
class PartOutcome:
    """Result of handling a Part: an Ack to broadcast, or a fault."""

    ack: Optional[Ack] = None
    fault: Optional[str] = None

    @property
    def is_valid(self) -> bool:
        return self.fault is None


@dataclass(frozen=True)
class AckOutcome:
    fault: Optional[str] = None

    @property
    def is_valid(self) -> bool:
        return self.fault is None


class _ProposalState:
    """Per-dealer accumulation (upstream ``ProposalState``)."""

    def __init__(self, commitment: BivarCommitment) -> None:
        self.commitment = commitment
        # Evaluation point (m+1) -> value p_d(m+1, our+1) == p_d(our+1, m+1).
        self.values: Dict[int, int] = {}
        self.acks: Set[int] = set()  # node indices that acked

    def is_complete(self, threshold: int) -> bool:
        return len(self.acks) > 2 * threshold


class SyncKeyGen:
    """One node's view of a DKG among ``pub_keys``' owners.

    Construct via :meth:`new`, which also returns our ``Part`` to be
    disseminated (``None`` for observers).
    """

    def __init__(
        self,
        our_id: Any,
        secret_key: SecretKey,
        pub_keys: Dict[Any, PublicKey],
        threshold: int,
        suite: Suite,
    ) -> None:
        self.our_id = our_id
        self.secret_key = secret_key
        self.pub_keys = dict(pub_keys)
        self.threshold = threshold
        self.suite = suite
        self._ids: List[Any] = sorted(pub_keys)
        self._index = {n: i for i, n in enumerate(self._ids)}
        self.proposals: Dict[Any, _ProposalState] = {}
        # Batch-digested native check results, keyed by message object
        # identity (see predigest_batch); populated by the engine's
        # batch callback for the duration of ONE committed batch and
        # consumed by handle_part/handle_ack — empty in every other
        # driving mode, so the per-item paths are untouched.
        self._predigest: Dict[tuple, tuple] = {}

    # -- construction --------------------------------------------------
    @staticmethod
    def new(
        our_id: Any,
        secret_key: SecretKey,
        pub_keys: Dict[Any, PublicKey],
        threshold: int,
        rng: Any,
        suite: Suite,
    ) -> Tuple["SyncKeyGen", Optional[Part]]:
        skg = SyncKeyGen(our_id, secret_key, pub_keys, threshold, suite)
        if our_id not in skg._index:
            return skg, None  # observer: no contribution
        poly = BivarPoly.random(threshold, rng, suite.scalar_modulus)
        commitment = poly.commitment(suite)
        rows = tuple(
            pub_keys[n].encrypt(_encode_scalars(poly.row(m + 1).coeffs), rng)
            for m, n in enumerate(skg._ids)
        )
        return skg, Part(commitment, rows)

    # -- introspection -------------------------------------------------
    @property
    def our_index(self) -> Optional[int]:
        return self._index.get(self.our_id)

    def is_node_ready(self, proposer: Any) -> bool:
        state = self.proposals.get(proposer)
        return state is not None and state.is_complete(self.threshold)

    def count_complete(self) -> int:
        return sum(
            1 for s in self.proposals.values() if s.is_complete(self.threshold)
        )

    def is_ready(self) -> bool:
        """Enough complete proposals to generate the joint key."""
        return self.count_complete() > self.threshold

    # -- batch digest (native fast path) -------------------------------
    #
    # The engine's batch callback hands a whole committed batch of
    # key-gen messages to Python at once; the per-message native checks
    # (one C call per ack/part) were the measured 16M-cycle continuation
    # tail at era changes (CLAUDE.md round-5 envelope profile).  These
    # two methods batch ALL of a committed batch's private checks into
    # one C call per kind; handle_part/handle_ack then consume the
    # stored verdicts instead of re-deriving them.  Everything here is a
    # pure function of message bytes + our secret key — pre-computing
    # results for messages that later fail the public checks changes
    # nothing (the results are simply never consumed), so outputs stay
    # byte-identical by construction.  Any per-item native miss (stale
    # cid, shape mismatch, oversized slot) leaves no entry and the
    # consumer falls back to the existing per-item path, pure-Python
    # oracle last.

    def predigest_batch(self, msgs: Any) -> None:
        """Batch the private DKG checks for ``(sender, payload)`` pairs
        of one committed batch (payloads: Part | Ack, in batch order).

        The admission loop runs ~N^2 times per DKG batch per node, so it
        is written hot: locals pinned, the scalar-ciphertext type checks
        inlined (same predicates as ``_ScalarKem.ct_ok`` + the slot
        length), and any unexpected shape aborts the WHOLE digest via
        the enclosing try — the per-item paths then re-derive every
        verdict, so a Byzantine oddball costs speed, never correctness.
        """
        nd = _native_dkg(self.suite)
        our_idx = self.our_index
        if nd is None or our_idx is None or not _batch_dkg_enabled():
            return
        kem = nd.kem
        g_type = kem._g_type
        mod = kem._mod
        suite = self.suite
        index_get = self._index.get
        proposals_get = self.proposals.get
        predigest = self._predigest
        commit_id = nd.commit_id
        n1 = self.threshold + 1
        part_vlen = n1 * _SCALAR_BYTES
        ack_keys: List[tuple] = []
        ack_items: List[tuple] = []
        part_keys: List[tuple] = []
        part_items: List[tuple] = []
        try:
            for sender, payload in msgs:
                cls = payload.__class__
                if cls is Ack:
                    sender_idx = index_get(sender)
                    if sender_idx is None:
                        continue
                    # A part for this proposer handled LATER in the same
                    # batch is a digest miss; the per-item path covers it.
                    state = proposals_get(payload.proposer)
                    if state is None or sender_idx in state.acks:
                        continue
                    values = payload.values
                    if type(values) is not tuple or len(values) <= our_idx:
                        continue
                    ct = values[our_idx]
                    if type(ct) is not Ciphertext:
                        continue
                    u = ct.u
                    w = ct.w
                    v = ct.v
                    if (
                        type(u) is not g_type
                        or type(w) is not g_type
                        or type(v) is not bytes
                        or len(v) != _SCALAR_BYTES
                        or not 0 <= u.value < mod
                        or not 0 <= w.value < mod
                        or u.modulus != mod
                        or w.modulus != mod
                        or ct.suite != suite
                    ):
                        continue
                    key = ("ack", id(payload), sender_idx)
                    if key in predigest:
                        continue
                    cid = state.commitment.__dict__.get("_native_cid")
                    if cid is None:
                        cid = commit_id(state.commitment)
                    if cid < 0:
                        continue
                    ack_keys.append((key, payload))
                    ack_items.append((cid, sender_idx + 1, ct))
                elif cls is Part:
                    if index_get(sender) is None or sender in self.proposals:
                        continue
                    key = ("part", id(payload))
                    if key in predigest:
                        continue
                    rows = payload.rows
                    if type(rows) is not tuple or len(rows) <= our_idx:
                        continue
                    ct = rows[our_idx]
                    if not (
                        kem.ct_ok(ct) and len(ct.v) == part_vlen
                    ):
                        continue
                    cid = commit_id(payload.commitment)
                    if cid < 0:
                        continue
                    part_keys.append((key, payload))
                    part_items.append((cid, ct))
            sk_x = self.secret_key.x
            stored = 0
            if ack_items:
                res = nd.ack_check_batch(ack_items, our_idx + 1, sk_x)
                if res is not None:
                    for (key, payload), rv in zip(ack_keys, res):
                        if rv[0] >= 0:  # -1 (stale cid) = per-item miss
                            predigest[key] = (payload, rv[0], rv[1])
                            stored += 1
            if part_items:
                res = nd.part_check_batch(part_items, our_idx + 1, n1, sk_x)
                if res is not None:
                    for (key, payload), rv in zip(part_keys, res):
                        if rv[0] >= 0:
                            predigest[key] = (payload, rv[0], rv[1])
                            stored += 1
            PREDIGEST_STATS["items"] += stored
        except Exception:
            # Correctness never depends on the digest: drop everything
            # and let the per-item paths run.
            predigest.clear()

    def clear_predigest(self) -> None:
        """Drop all batch-digested results (end of the committed batch).
        Consumers fall back to the per-item paths for anything still
        unprocessed, so clearing is always safe."""
        self._predigest.clear()

    # -- message handling ----------------------------------------------
    #
    # CRITICAL invariant: whether a Part is *accepted* and whether an Ack
    # is *counted* must depend only on PUBLICLY visible data (the message
    # bytes every node sees in the same consensus order) — never on data
    # only we can decrypt.  Otherwise a Byzantine dealer/acker could
    # corrupt one node's encrypted slot and make the proposal/ack sets —
    # and hence the generated keys — diverge across nodes.  Failures of
    # the *private* checks are reported as faults but do not affect
    # acceptance/counting.

    def handle_part(self, sender: Any, part: Part, rng: Any) -> PartOutcome:
        if sender not in self._index:
            return PartOutcome(fault=FAULT_UNKNOWN_SENDER)
        if not self._part_shape_ok(part):  # public check
            return PartOutcome(fault=FAULT_BAD_PART)
        existing = self.proposals.get(sender)
        if existing is not None:
            if existing.commitment == part.commitment:
                return PartOutcome()  # duplicate: ignore
            return PartOutcome(fault=FAULT_MULTIPLE_PARTS)
        self.proposals[sender] = _ProposalState(part.commitment)

        our_idx = self.our_index
        if our_idx is None:
            return PartOutcome()  # observer: track commitment only

        # Private check: our encrypted row.  On failure the proposal stays
        # tracked (others' acks can still complete it and recover our
        # share); we just cannot ack it ourselves.
        row = self._decrypt_row(part, our_idx)
        if row is None:
            return PartOutcome(fault=FAULT_BAD_PART)
        # Our ack: hand every node j one evaluation of its row.
        nd = _native_dkg(self.suite)
        if nd is not None:
            mod = self.suite.scalar_modulus
            pks_g1 = [getattr(self.pub_keys[n], "g1", None) for n in self._ids]
            if all(
                isinstance(getattr(g, "value", None), int)
                and 0 <= g.value < mod
                for g in pks_g1
            ):
                return PartOutcome(
                    ack=Ack(sender, nd.ack_values(row, pks_g1, rng))
                )
        values = tuple(
            self.pub_keys[n].encrypt(
                _encode_scalars((row.eval(j + 1),)), rng
            )
            for j, n in enumerate(self._ids)
        )
        return PartOutcome(ack=Ack(sender, values))

    def handle_ack(self, sender: Any, ack: Ack) -> AckOutcome:
        if sender not in self._index:
            return AckOutcome(fault=FAULT_UNKNOWN_SENDER)
        if not self._ack_shape_ok(ack):  # public check
            return AckOutcome(fault=FAULT_BAD_ACK)
        try:
            state = self.proposals.get(ack.proposer)
        except TypeError:  # unhashable garbage proposer
            state = None
        if state is None:
            # Part/Ack ordering is guaranteed by consensus; an ack for an
            # unknown proposal is Byzantine (or the proposer never dealt).
            return AckOutcome(fault=FAULT_ACK_BEFORE_PART)
        sender_idx = self._index[sender]
        if sender_idx in state.acks:
            return AckOutcome()  # duplicate: ignore
        # All public checks passed: the ack COUNTS at every node, even if
        # the value encrypted to us turns out bad (see invariant above).
        state.acks.add(sender_idx)

        our_idx = self.our_index
        if our_idx is None:
            return AckOutcome()
        # Native fast path: decrypt + decode + commitment consistency in
        # one C call (identical verdicts; _NativeDkg docstring) — batch
        # pre-digested where the engine's batch callback ran first.
        nd = _native_dkg(self.suite)
        ct = ack.values[our_idx]
        if self._predigest:
            pre = self._predigest.get(("ack", id(ack), sender_idx))
            if pre is not None and pre[0] is ack:
                PREDIGEST_STATS["hits"] += 1
                rc, nval = pre[1], pre[2]
                # Mirror SecretKey.decrypt's ciphertext-validity memo
                # (rc 0 = invalid ct; 1/2 = valid ct).
                object.__setattr__(ct, "_verify_ok", rc != 0)
                if rc != 1:
                    return AckOutcome(fault=FAULT_BAD_ACK)
                state.values[sender_idx + 1] = nval
                return AckOutcome()
        if (
            nd is not None
            and nd.kem.ct_ok(ct)
            and len(ct.v) == _SCALAR_BYTES
        ):
            cid = nd.commit_id(state.commitment)
            if cid >= 0:
                rc, nval = nd.ack_check(
                    cid, sender_idx + 1, our_idx + 1, ct, self.secret_key.x
                )
                if rc < 0:
                    # Stale cid (registry generation bump): clear the
                    # memo and re-register once before giving up on the
                    # fast path (ADVICE round 5).
                    cid = nd.refresh_commit_id(state.commitment)
                    if cid >= 0:
                        rc, nval = nd.ack_check(
                            cid, sender_idx + 1, our_idx + 1, ct,
                            self.secret_key.x,
                        )
                if rc >= 0:
                    # Mirror SecretKey.decrypt's ciphertext-validity memo
                    # (rc 0 = invalid ct; 1/2 = valid ct).
                    object.__setattr__(ct, "_verify_ok", rc != 0)
                    if rc != 1:
                        return AckOutcome(fault=FAULT_BAD_ACK)
                    state.values[sender_idx + 1] = nval
                    return AckOutcome()
        val = self._decrypt_value(ack, our_idx)
        if val is not None:
            # Private consistency: v must equal p_d(sender+1, our+1); check
            # in the group against the committed row of the sender.
            expected = state.commitment.row(sender_idx + 1).eval(our_idx + 1)
            actual = self.suite.g1_generator() * val
            if expected.to_bytes() != actual.to_bytes():
                val = None
        if val is None:
            return AckOutcome(fault=FAULT_BAD_ACK)
        state.values[sender_idx + 1] = val
        return AckOutcome()

    # -- key derivation ------------------------------------------------
    def generate(self) -> Tuple["PublicKeySet", Optional[SecretKeyShare]]:
        """Derive the joint keys from the complete proposals.

        Deterministic across nodes: the proposal set and ack sets are
        identical everywhere because Part/Ack ordering came through
        consensus.
        """
        from hbbft_tpu.crypto.keys import PublicKeySet

        complete = [
            (d, s)
            for d, s in sorted(self.proposals.items(), key=lambda kv: str(kv[0]))
            if s.is_complete(self.threshold)
        ]
        if len(complete) <= self.threshold:
            raise RuntimeError(
                f"not ready: {len(complete)} complete proposals, "
                f"need {self.threshold + 1}"
            )
        commitment: Optional[Commitment] = None
        for _, s in complete:
            row0 = s.commitment.row(0)
            commitment = row0 if commitment is None else commitment + row0
        pk_set = PublicKeySet(commitment, self.suite)

        our_idx = self.our_index
        if our_idx is None:
            return pk_set, None
        modulus = self.suite.scalar_modulus
        groups: List[List[Tuple[int, int]]] = []
        for d, s in complete:
            pts = sorted(s.values.items())[: self.threshold + 1]
            if len(pts) <= self.threshold:
                raise RuntimeError(
                    f"proposal {d!r} complete but only {len(pts)} values known"
                )
            groups.append(pts)
        # Vectorized Lagrange (one C call sums every proposal's
        # interpolation — same mod-r value as the loop below); any
        # native miss falls back to the pure-Python oracle.
        nd = _native_dkg(self.suite)
        secret: Optional[int] = None
        if nd is not None and _batch_dkg_enabled():
            secret = nd.interp_sum(groups)
        if secret is None:
            secret = 0
            for pts in groups:
                secret = (secret + interpolate(pts, modulus)) % modulus
        return pk_set, SecretKeyShare(secret, self.suite)

    # -- internals -----------------------------------------------------
    def _shape_memo_key(self) -> tuple:
        # The verdict depends only on public data + these parameters, so
        # it can be cached on the (shared, immutable) message object —
        # at churn every node re-validates the same decoded Part/Ack
        # otherwise (N^3 ciphertext checks network-wide).
        return (self.threshold, len(self._ids), self.suite.name)

    def _part_shape_ok(self, part: Any) -> bool:
        """Public structural validation (fields may be arbitrary objects)."""
        from hbbft_tpu.crypto.backend import _ciphertext_well_formed

        key = self._shape_memo_key()
        try:
            cached = part.__dict__.get("_shape_ok")
            if cached is not None and cached[0] == key:
                return cached[1]
        except Exception:
            cached = None
        ok = self._part_shape_ok_uncached(part, _ciphertext_well_formed)
        try:
            object.__setattr__(part, "_shape_ok", (key, ok))
        except Exception:
            pass
        return ok

    def _part_shape_ok_uncached(self, part: Any, _ciphertext_well_formed) -> bool:
        try:
            n1 = self.threshold + 1
            return (
                isinstance(part, Part)
                and isinstance(part.commitment, BivarCommitment)
                and isinstance(part.commitment.elems, tuple)
                and len(part.commitment.elems) == n1
                and all(
                    isinstance(row, tuple)
                    and len(row) == n1
                    and all(self.suite.is_g1(e) for e in row)
                    for row in part.commitment.elems
                )
                and isinstance(part.rows, tuple)
                and len(part.rows) == len(self._ids)
                and all(_ciphertext_well_formed(self.suite, c) for c in part.rows)
            )
        except Exception:
            return False

    def _ack_shape_ok(self, ack: Any) -> bool:
        from hbbft_tpu.crypto.backend import _ciphertext_well_formed

        key = self._shape_memo_key()
        try:
            cached = ack.__dict__.get("_shape_ok")
            if cached is not None and cached[0] == key:
                return cached[1]
        except Exception:
            cached = None
        ok = self._ack_shape_ok_uncached(ack, _ciphertext_well_formed)
        try:
            object.__setattr__(ack, "_shape_ok", (key, ok))
        except Exception:
            pass
        return ok

    def _ack_shape_ok_uncached(self, ack: Any, _ciphertext_well_formed) -> bool:
        try:
            return (
                isinstance(ack, Ack)
                and isinstance(ack.values, tuple)
                and len(ack.values) == len(self._ids)
                and all(_ciphertext_well_formed(self.suite, c) for c in ack.values)
            )
        except Exception:
            return False

    def _decrypt_row(self, part: Part, our_idx: int) -> Optional[Poly]:
        # Batch-digested verdict (decrypt + decode + row consistency in
        # the one-call batch check): same outcomes as the step-by-step
        # path below, including the ct-validity memo.
        pre = (
            self._predigest.get(("part", id(part)))
            if self._predigest
            else None
        )
        if pre is not None and pre[0] is part:
            PREDIGEST_STATS["hits"] += 1
            rc, data = pre[1], pre[2]
            object.__setattr__(part.rows[our_idx], "_verify_ok", rc != 0)
            if rc != 1:
                return None
            coeffs = _decode_scalars(
                data, self.threshold + 1, self.suite.scalar_modulus
            )
            if coeffs is None:  # defensive: the C check validated ranges
                return None
            return Poly(coeffs, self.suite.scalar_modulus)
        try:
            data = self.secret_key.decrypt(part.rows[our_idx])
        except Exception:
            data = None
        if data is None:
            return None
        coeffs = _decode_scalars(
            data, self.threshold + 1, self.suite.scalar_modulus
        )
        if coeffs is None:
            return None
        row = Poly(coeffs, self.suite.scalar_modulus)
        # Validate the row against the public commitment (native fast
        # path: per-coefficient g*c comparison against the registered
        # commitment's row — same verdict as the to_bytes comparison).
        nd = _native_dkg(self.suite)
        if nd is not None:
            cid = nd.commit_id(part.commitment)
            if cid >= 0:
                rc = nd.row_check(
                    cid, our_idx + 1, data, self.threshold + 1
                )
                if rc < 0:
                    # Stale cid: re-register once (ADVICE round 5).
                    cid = nd.refresh_commit_id(part.commitment)
                    if cid >= 0:
                        rc = nd.row_check(
                            cid, our_idx + 1, data, self.threshold + 1
                        )
                if rc >= 0:
                    return row if rc == 1 else None
        committed = part.commitment.row(our_idx + 1)
        ours = row.commitment(self.suite)
        if committed.to_bytes() != ours.to_bytes():
            return None
        return row

    def _decrypt_value(self, ack: Ack, our_idx: int) -> Optional[int]:
        try:
            data = self.secret_key.decrypt(ack.values[our_idx])
        except Exception:
            data = None
        if data is None:
            return None
        vals = _decode_scalars(data, 1, self.suite.scalar_modulus)
        return None if vals is None else vals[0]
